"""Continuous batching: iteration-level request scheduling.

The reference serves one request at a time end-to-end
(``consumer_server.py:73`` ``batch_size = 1``, with a TODO admitting batching
is future work). This scheduler implements Orca-style continuous batching on
top of the static-shape engine: a persistent ``[L, B, T]`` ring cache whose
**rows** are the scheduling unit. New requests are prefilled into a batch-1
scratch cache and inserted into a free row between decode steps; every decode
step advances all active rows with per-row sampling parameters; finished rows
free immediately for the next waiting request — no request waits for an
unrelated request to finish.

Invariant tested in ``tests/test_continuous.py``: interleaved admission must
produce exactly the tokens the request would get alone (row isolation — the
causal mask is driven by per-row cache positions, so rows never see each
other).
"""

from __future__ import annotations

import dataclasses
import threading
import time
from collections import deque
from functools import partial
from typing import Callable

import jax
import jax.numpy as jnp
import numpy as np

from llmss_tpu.engine.cache import KVCache
from llmss_tpu.engine.engine import DecodeEngine, GenerationParams, _bucket


@dataclasses.dataclass
class _Row:
    req_id: str
    gen: GenerationParams
    out: list[int]
    cur_pos: int
    # Called as done_cb(tokens) on completion, done_cb(tokens, True) when
    # the request was cancelled (tokens = what was produced before the
    # cancel) — so the serving layer can answer honestly instead of
    # disguising a cancelled request as a success.
    done_cb: Callable[..., None]
    # Optional per-increment hook: called with the NEW tokens after each
    # scheduler step that produced any (streaming delivery; granularity is
    # the decode chunk).
    stream_cb: Callable[[list[int]], None] | None = None
    emitted: int = 0


@dataclasses.dataclass
class _InFlightAdmission:
    """An admission batch whose prefill + insert are dispatched but whose
    first tokens have not been fetched: resolved (rows activated) at the
    top of the next step, overlapping admission with the decode chunk."""

    taken: list  # [(req_id, ids, gen, cb, stream_cb, t_submit)]
    rows: list[int]
    tok: jax.Array  # [P] first sampled token per admission row (device)


class ContinuousBatcher:
    def __init__(
        self, engine: DecodeEngine, *, rows: int = 8, chunk_steps: int = 1
    ):
        # chunk_steps > 1 advances all rows that many tokens per host
        # round-trip (one fused scan + one fetch instead of per-token
        # sync) — the serving throughput lever; admission/finish/cancel
        # granularity becomes the chunk instead of the single token.
        if chunk_steps < 1:
            raise ValueError(f"chunk_steps must be >= 1, got {chunk_steps}")
        self.engine = engine
        self.rows = rows
        self.chunk_steps = chunk_steps
        self.cache = engine.new_cache(rows)
        self._scratch_template = None
        self.pending: deque = deque()
        self.active: dict[int, _Row] = {}
        self._free = list(range(rows))
        self._tokens = np.zeros(rows, np.int32)
        self._step_count = 0
        self._cancelled: set[str] = set()
        self._inflight: _InFlightAdmission | None = None
        self._cancel_at_resolve: set[str] = set()
        self._lock = threading.Lock()

        cfg = engine.cfg
        self._insert = jax.jit(self._insert_impl, donate_argnums=(0,))
        self._prefill_row = jax.jit(
            partial(DecodeEngine._prefill_impl, cfg, engine.mesh),
            donate_argnums=(2,),
        )

    def _pad_row_idx(self, P: int, rows: list[int]) -> np.ndarray:
        """[P] scatter indices for an admission insert: real rows first,
        padding filled with a POSITIVE out-of-range sentinel (self.rows).
        mode="drop" only drops indices that are OOB *after* normalization,
        and JAX wraps negative indices first — a -1 sentinel would scatter
        the dummy row into live row rows-1, zeroing its KV."""
        idx = np.full(P, self.rows, np.int32)
        idx[: len(rows)] = rows
        return idx

    @staticmethod
    def _insert_impl(big: KVCache, small: KVCache, rows) -> KVCache:
        """Copy scratch-cache rows into the persistent cache at ``rows``
        ([P] int32; entries >= big rows are padding and dropped — the
        sentinel must be positive OOB, since negative indices wrap)."""
        return KVCache(
            k=big.k.at[:, rows].set(small.k, mode="drop"),
            v=big.v.at[:, rows].set(small.v, mode="drop"),
            positions=big.positions.at[rows].set(
                small.positions, mode="drop"
            ),
            k_scale=(
                big.k_scale.at[:, rows].set(small.k_scale, mode="drop")
                if big.k_scale is not None else None
            ),
            v_scale=(
                big.v_scale.at[:, rows].set(small.v_scale, mode="drop")
                if big.v_scale is not None else None
            ),
        )

    def prewarm(self, seq_buckets: list[int] | None = None) -> int:
        """Compile every executable the scheduler can hit: admission
        prefill for each (admission-batch P, seq bucket S) pair, the row
        insert per P, and the decode step/chunk at the full row count —
        so no request ever eats a multi-second XLA compile mid-serve.
        ``seq_buckets`` narrows the prompt-length envelope when known
        (default: every bucket up to the engine's max_seq_len). Returns
        the number of executables compiled."""
        eng = self.engine
        if seq_buckets is None:
            seq_buckets = eng.seq_buckets()
        Ps, p = [], 1
        while p < self.rows:
            Ps.append(p)
            p *= 2
        Ps.append(p)  # one above, for n == rows when rows isn't a pow2
        n_compiled = 0
        for P in sorted(set(Ps)):
            sa = eng._sample_args(GenerationParams(), P)
            scratch = None
            for S in seq_buckets:
                scratch = eng.new_cache(P)
                ids = jnp.zeros((P, S), np.int32)
                lens = jnp.ones(P, np.int32)
                _tok, _, scratch = self._prefill_row(
                    eng.params, ids, scratch, jnp.asarray(lens), sa,
                )
                n_compiled += 1
            # Insert with all-dropped indices: compiles the P-shaped
            # scatter without touching live rows. Twice, because the
            # cache's PartitionSpec representation alternates between two
            # normalized forms as it cycles through jit outputs — each
            # cache-consuming executable has two steady-state signatures.
            for _ in range(2):
                self.cache = self._insert(
                    self.cache, scratch,
                    jnp.asarray(self._pad_row_idx(P, [])),
                )
                n_compiled += 1
        # Decode step/chunk at the full row count (twice — see above).
        sa = eng._sample_args(GenerationParams(), self.rows)
        cur = jnp.zeros(self.rows, np.int32)
        toks = jnp.zeros(self.rows, np.int32)
        for _ in range(2):
            if self.chunk_steps > 1:
                _t, self.cache, _, _ = eng._decode_many(
                    eng.params, toks, self.cache, cur, sa,
                    jnp.ones(self.rows, bool),
                    jnp.full(self.rows, -1, np.int32),
                    n_steps=self.chunk_steps,
                )
            else:
                _t, _, self.cache = eng._decode(
                    eng.params, toks, self.cache, cur, sa
                )
            n_compiled += 1
        # The prewarm decode ran with every row marked done/free, but its
        # cache writes still landed — reset positions so no ghost slots
        # survive into real serving. device_put with the original sharding:
        # an eager op could re-commit the array and key fresh compiles for
        # every executable that takes the cache.
        self.cache = self.cache._replace(
            positions=jax.device_put(
                jnp.full_like(self.cache.positions, -1),
                self.cache.positions.sharding,
            ),
        )
        return n_compiled

    # -- submission ---------------------------------------------------------

    def submit(
        self,
        token_ids: list[int],
        gen: GenerationParams,
        done_cb: Callable[[list[int]], None],
        req_id: str = "",
        stream_cb: Callable[[list[int]], None] | None = None,
    ) -> None:
        gen.validate()
        with self._lock:
            self.pending.append(
                (req_id, list(token_ids), gen, done_cb, stream_cb,
                 time.perf_counter())
            )

    # -- scheduling ---------------------------------------------------------

    def _admit_dispatch(self) -> _InFlightAdmission | None:
        """Dispatch admission for every pending request that has a free
        row: ONE batched prefill + ONE row-scatter insert, **no blocking
        fetch** — the first tokens are read by ``_resolve_admission`` at
        the top of the next step, so admission compute and its device→host
        round-trip overlap the decode chunk instead of serializing behind
        it (per-request admission measured ~0.2 s over the bench host's
        tunnel; batched + overlapped it disappears from the critical path).

        Must be called *after* the step's decode is dispatched: device
        programs run in dispatch order, so the insert lands between this
        chunk and the next — the chunk can't scribble on freshly inserted
        rows (done rows still write their cache slot), and the next chunk
        sees them.

        The admission batch pads to a power of two (dummy rows) so the
        compile envelope stays (log₂ rows × log₂ seq buckets) executables.
        """
        with self._lock:
            n = min(len(self.pending), len(self._free))
            if n == 0:
                return None
            taken = [self.pending.popleft() for _ in range(n)]
            rows = [self._free.pop() for _ in range(n)]

        P = 1
        while P < n:
            P *= 2
        S = _bucket(
            max(len(ids) for _rid, ids, _g, _cb, _scb, _t in taken),
            self.engine.max_seq_len,
        )
        padded = np.zeros((P, S), np.int32)
        lens = np.ones(P, np.int32)  # dummy rows prefill one pad token
        gens = []
        for i, (_rid, ids, gen, _cb, _scb, _t) in enumerate(taken):
            padded[i, : len(ids)] = ids
            lens[i] = len(ids)
            gens.append(gen)
        gens += [GenerationParams()] * (P - n)
        row_idx = self._pad_row_idx(P, rows)

        scratch = self.engine.new_cache(P)
        sample_args = self.engine._sample_args(gens, P)
        tok, _, scratch = self._prefill_row(
            self.engine.params, jnp.asarray(padded), scratch,
            jnp.asarray(lens), sample_args,
        )
        self.cache = self._insert(
            self.cache, scratch, jnp.asarray(row_idx)
        )
        return _InFlightAdmission(taken=taken, rows=rows, tok=tok)

    def _resolve_admission(self) -> int:
        """Activate the previously dispatched admission batch (fetch its
        first tokens — by now overlapped with the last decode chunk)."""
        adm, self._inflight = self._inflight, None
        if adm is None:
            return 0
        firsts = np.asarray(adm.tok)
        now = time.perf_counter()
        cancelled = self._cancel_at_resolve
        self._cancel_at_resolve = set()
        for i, (req_id, ids, gen, cb, scb, t_submit) in enumerate(adm.taken):
            row = adm.rows[i]
            r = _Row(
                req_id=req_id, gen=gen, out=[], cur_pos=len(ids),
                done_cb=cb, stream_cb=scb,
            )
            if req_id in cancelled:
                # Not served, no TTFT sample — matches the static Worker's
                # accounting for pre-cancelled requests.
                self.engine.metrics.add_cancelled(1)
                self._finish(row, r, cancelled=True)
                continue
            # TTFT spans submit → resolve: queueing for a free row, the
            # admission prefill, AND the decode chunk the admission
            # deliberately overlapped — the time a client actually waited
            # for its first token. NOT recorded as prefill latency (that
            # stat stays a tight measure of prefill compute).
            self.engine.metrics.ttft.record(now - t_submit)
            self.engine.metrics.add_request(1)
            first = int(firsts[i])
            eos = gen.eos_token_id if gen.eos_token_id is not None else -1
            if first == eos or gen.max_new_tokens == 0:
                self._finish(row, r)
                continue
            r.out.append(first)
            self.engine.metrics.add_tokens(1)
            self._tokens[row] = first
            self.active[row] = r
            if len(r.out) >= r.gen.max_new_tokens:
                self._finish(row, r)
            else:
                # First token goes out now, not a full chunk later —
                # streaming's perceived TTFT is the point.
                self._flush_stream(r)
        return len(adm.taken)

    def _finish(self, row: int, r: _Row, cancelled: bool = False) -> None:
        self.active.pop(row, None)
        with self._lock:
            self._free.append(row)
        self._flush_stream(r)
        if cancelled:
            r.done_cb(r.out, True)
        else:
            r.done_cb(r.out)

    @staticmethod
    def _flush_stream(r: _Row) -> None:
        if r.stream_cb is not None and len(r.out) > r.emitted:
            r.stream_cb(r.out[r.emitted:])
            r.emitted = len(r.out)

    def cancel(self, req_id: str) -> None:
        """Mark a request cancelled (thread-safe). The worker thread frees
        its row / drops it from the queue at the top of the next ``step()``
        — i.e. a cancelled request stops consuming decode steps within one
        step. Its ``done_cb`` fires with the tokens produced so far."""
        with self._lock:
            self._cancelled.add(req_id)

    def _process_cancellations(self) -> int:
        """Worker-thread half of ``cancel``: drop marked pending requests
        (their callbacks fire with ``cancelled=True`` so every submitted
        request gets exactly one response), free marked active rows, and
        mark in-flight admissions for drop at resolve. Unmatched ids are
        discarded — the broker-side cancellation flag persists (TTL'd), so
        a cancel racing ahead of its request is re-delivered by the
        worker's ``check_cancelled`` once the request shows up."""
        with self._lock:
            if not self._cancelled:
                return 0
            ids, self._cancelled = self._cancelled, set()
            dropped = [p for p in self.pending if p[0] in ids]
            self.pending = deque(p for p in self.pending if p[0] not in ids)
        n = len(dropped)
        for _rid, _ids, _gen, cb, _scb, _t in dropped:
            cb([], True)
        if self._inflight is not None:
            for req_id, *_rest in self._inflight.taken:
                if req_id in ids:
                    # metrics counted at resolve, where the row frees
                    self._cancel_at_resolve.add(req_id)
        for row, r in list(self.active.items()):
            if r.req_id in ids:
                self._finish(row, r, cancelled=True)
                n += 1
        if n:
            self.engine.metrics.add_cancelled(n)
        return n

    def live_ids(self) -> list[str]:
        """Every request id this batcher currently owns (pending, in-flight
        admission, active) — what the worker polls cancellation flags for."""
        with self._lock:
            ids = [req_id for (req_id, *_r) in self.pending]
        if self._inflight is not None:
            ids += [req_id for (req_id, *_r) in self._inflight.taken]
        ids += [r.req_id for r in self.active.values()]
        return ids

    def drain_all(self) -> list[str]:
        """Remove every pending, in-flight, and active request and return
        their ids — supervisor teardown: a restarting worker must error
        these out so no client waits forever on a request the new batcher
        never saw.

        Runs on the worker thread (the supervisor tears down from inside the
        crashed worker's loop), so touching ``self.active`` here doesn't race
        ``step()``; the queue and free-list stay lock-guarded.
        """
        with self._lock:
            ids = [req_id for (req_id, *_rest) in self.pending]
            self.pending.clear()
        if self._inflight is not None:
            adm, self._inflight = self._inflight, None
            ids += [req_id for (req_id, *_rest) in adm.taken]
            with self._lock:
                self._free.extend(adm.rows)
        for row in list(self.active):
            r = self.active.pop(row)
            ids.append(r.req_id)
            with self._lock:
                self._free.append(row)
        return ids

    def _sample_args_all(self):
        gens = []
        for i in range(self.rows):
            r = self.active.get(i)
            gens.append(r.gen if r else GenerationParams())
        return self.engine._sample_args(gens, self.rows)

    def step(self) -> int:
        """One scheduler iteration: resolve last step's admissions, advance
        all active rows ``chunk_steps`` tokens in one fused scan, and
        dispatch new admissions to overlap with that scan.

        Rows keep their exact solo tokens (row isolation is positional, and
        a row that finishes mid-chunk is freed with only its real tokens) —
        the chunk only batches the host round-trips. Free/finished rows ride
        along as done rows emitting discarded fills, the same cost a
        single-step loop pays for inactive rows in the batch.
        """
        self._process_cancellations()
        self._resolve_admission()
        if not self.active:
            # Nothing to overlap with: dispatch + resolve immediately.
            self._inflight = self._admit_dispatch()
            if self._inflight is not None:
                self._resolve_admission()
            if not self.active:
                return 0

        k = self.chunk_steps
        cur_pos = np.zeros(self.rows, np.int32)
        done = np.ones(self.rows, bool)
        eos_arr = np.full(self.rows, -1, np.int32)
        for i, r in self.active.items():
            cur_pos[i] = r.cur_pos
            done[i] = False
            if r.gen.eos_token_id is not None:
                eos_arr[i] = r.gen.eos_token_id

        t0 = time.perf_counter()
        if k > 1:
            toks, self.cache, _, _ = self.engine._decode_many(
                self.engine.params, jnp.asarray(self._tokens), self.cache,
                jnp.asarray(cur_pos), self._sample_args_all(),
                jnp.asarray(done), jnp.asarray(eos_arr), n_steps=k,
            )
        else:
            tok, _, self.cache = self.engine._decode(
                self.engine.params, jnp.asarray(self._tokens), self.cache,
                jnp.asarray(cur_pos), self._sample_args_all(),
            )
            toks = tok[:, None]
        # Admission prefill+insert dispatched while the chunk runs; device
        # order guarantees the insert lands between this chunk and the
        # next. Resolved (rows activated) at the top of the next step.
        t_adm = time.perf_counter()
        self._inflight = self._admit_dispatch()
        t_adm = time.perf_counter() - t_adm
        toks_np = np.asarray(toks)  # [rows, k] — the one blocking sync
        # Admission prep (host-side padding + dispatches) overlaps the
        # chunk on device but not on the host clock — subtract it so the
        # decode_step stat stays a clean per-token latency.
        self.engine.metrics.decode_step.record(
            (time.perf_counter() - t0 - t_adm) / k
        )

        n = 0
        for i in list(self.active):
            r = self.active[i]
            eos = r.gen.eos_token_id if r.gen.eos_token_id is not None else -1
            finished = False
            for col in range(k):
                t = int(toks_np[i, col])
                r.cur_pos += 1
                if t == eos:
                    finished = True
                    break
                r.out.append(t)
                n += 1
                if len(r.out) >= r.gen.max_new_tokens:
                    finished = True
                    break
            if finished:
                self._finish(i, r)
            else:
                # Survived the whole chunk: device advanced it k steps.
                self._tokens[i] = int(toks_np[i, k - 1])
                self._flush_stream(r)
        self._step_count += 1
        self.engine.metrics.add_tokens(n)
        return n

    @property
    def idle(self) -> bool:
        with self._lock:
            return (
                not self.active and not self.pending
                and self._inflight is None
            )

    def run_until_idle(self) -> None:
        while not self.idle:
            self.step()

    def run_forever(self, stop: threading.Event, poll_s: float = 0.005):
        while not stop.is_set():
            if self.idle:
                time.sleep(poll_s)
                continue
            self.step()
