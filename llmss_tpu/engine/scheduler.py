"""Continuous batching: iteration-level request scheduling, pipelined.

The reference serves one request at a time end-to-end
(``consumer_server.py:73`` ``batch_size = 1``, with a TODO admitting batching
is future work). This scheduler implements Orca-style continuous batching on
top of the static-shape engine: a persistent ``[L, B, T]`` ring cache whose
**rows** are the scheduling unit. New requests are prefilled into a scratch
cache and inserted into free rows between decode chunks; every chunk advances
all active rows with per-row sampling parameters; finished rows free for the
next waiting request — no request waits for an unrelated request to finish.

**The decode state lives on device and the host observes it one GROUP late.**
Round 3 fetched every chunk's tokens before dispatching the next chunk, so
each chunk paid a full device→host round-trip on the critical path (~90 ms on
the axon bench host — the serving layer reached 0.21 of roofline while the
bare engine hit 0.65). Here:

- ``tokens``/``cur_pos`` are device arrays; the fused decode group feeds
  itself, so group N+1 is dispatched *before* group N's results are fetched
  and the fetch overlaps device compute instead of serializing behind it.
- While busy, ``group_chunks`` fused chunks run as ONE jitted program
  (``DecodeEngine._decode_group``): EOS/done and poison flags carry on
  device between the chunks, and the whole group's tokens + per-chunk
  poison flags cross the host link in a single packed int32 transfer —
  host syncs and dispatch overhead scale per group, not per chunk
  (docs/decode-loop.md).
- Admissions merge their first tokens into the device state with a jitted
  scatter (``DecodeEngine._admit_merge``) — the host never needs to see a
  token to keep the device advancing.
- The host processes group N's results (stream callbacks, EOS/max-token
  finishes, row frees) while group N+1 runs. Freeing and admission therefore
  lag one group — a freshly finished row keeps decoding discarded fills for
  one extra group, the same cost an idle row pays anyway.

Invariant tested in ``tests/test_continuous.py``: interleaved admission must
produce exactly the tokens the request would get alone (row isolation — the
causal mask is driven by per-row cache positions, so rows never see each
other; the one-group lag changes *when* the host learns tokens, never which
tokens the device computes), and grouped dispatch must emit bit-identical
token streams to the ungrouped path under EOS, poison, and admission churn.
"""

from __future__ import annotations

import dataclasses
import threading
import time
from collections import deque
from functools import partial
from typing import Callable

import jax
import jax.numpy as jnp
import numpy as np

from llmss_tpu.engine.cache import (
    BlockAllocator, KVCache, PagedKVCache, export_blocks,
    export_dense_row, import_blocks, table_sentinel,
)
from llmss_tpu.engine.engine import DecodeEngine, GenerationParams, _bucket
from llmss_tpu.utils import devtel, trace


@dataclasses.dataclass
class _Row:
    req_id: str
    gen: GenerationParams
    out: list[int]
    # Called as done_cb(tokens) on completion, done_cb(tokens, True) when
    # the request was cancelled (tokens = what was produced before the
    # cancel) — so the serving layer can answer honestly instead of
    # disguising a cancelled request as a success.
    done_cb: Callable[..., None]
    # Optional per-increment hook: called with the NEW tokens after each
    # scheduler step that produced any (streaming delivery; granularity is
    # the decode chunk).
    stream_cb: Callable[[list[int]], None] | None = None
    emitted: int = 0
    # Row is active on device (its admission merge is dispatched) but the
    # host hasn't yet fetched its prefill-sampled first token.
    awaiting_first: bool = True
    t_submit: float = 0.0
    # Preemption rank (SLO_CLASS_RANK: 0 = interactive, highest). A
    # pending request with a strictly LOWER rank may evict this row when
    # admission is blocked; equal ranks never preempt (livelock).
    priority: int = 1
    # Tokens this row replays from a previous (preempted) run: ``out`` is
    # preloaded with them and finish thresholds shift by this count, so
    # the resumed stream continues exactly where the evicted one stopped.
    replayed: int = 0


@dataclasses.dataclass
class _InFlightAdmission:
    """An admission whose prefill + insert + device-state merge are
    dispatched but whose first tokens have not been fetched. Rows are
    already active (the device decodes them from the next chunk on);
    ``resolve`` is host bookkeeping only."""

    entries: list  # [(row_idx, _Row)]
    tok: jax.Array  # [P] first sampled token per admission row (device)


@dataclasses.dataclass
class _InFlightGroup:
    """A dispatched decode GROUP (n_chunks fused chunks in one jitted
    program) whose packed results the host hasn't read yet."""

    # Flat int32 device array (copy_to_host_async issued):
    # ``n_chunks·rows·k`` tokens followed by ``n_chunks·rows`` per-chunk
    # poisoned flags — the group's ONE device→host transfer. Poisoned rows
    # were already forced done on device (EOS fills from the bad step on);
    # _process_group errors them out instead of reporting a success.
    packed: jax.Array
    n_chunks: int
    k: int  # steps per chunk
    # An admission's device work (prefill+insert+merge) ran between the
    # previous group and this one, so this group's fetch-to-fetch interval
    # is not a clean decode-only sample.
    has_admission: bool = False
    # Ragged mixed group (chunked prefill): for each row whose prompt
    # completed inside this group, the chunk index whose sampled token is
    # the request's FIRST token — admission bookkeeping happens at that
    # chunk in _process_group (chunked admissions never create an
    # _InFlightAdmission). Rows absent from the map either finished
    # streaming earlier or are still mid-prompt (skip their chunks).
    prefill_firsts: dict | None = None
    # Devtel roofline tagging, attached at dispatch (a cost-table dict
    # get) so _process_group can fold the measured fetch-to-fetch
    # interval into achieved MFU/MBU without recomputing the key.
    kind: str = "decode_group"
    cost: object = None  # devtel.KernelCost | None


def select_preemption_victim(candidates, head_priority: int):
    """Pick the row to evict for a blocked head request, or ``None``.

    ``candidates`` is an iterable of ``(key, priority, emitted_tokens)``
    for the rows that are *evictable at all* (the caller applies its own
    structural filters — settled, refundable, not mid-prefill). Policy:
    only rows strictly outranked by the head (``priority >
    head_priority``) qualify; among those, evict the lowest class first,
    ties broken by FEWEST emitted tokens — the cheapest replay prefill.
    Exact ties keep the first candidate, so iteration order is part of
    the contract (dict order for the batcher, row order for the sim).

    Factored to module level so the fleet simulator preempts with the
    scheduler's REAL policy rather than a re-implementation; both
    ``ContinuousBatcher._maybe_preempt`` and ``sim.replica`` call this.
    """
    victim = None
    for key, priority, emitted in candidates:
        if priority <= head_priority:
            continue
        if victim is None or (priority, -emitted) > (victim[1], -victim[2]):
            victim = (key, priority, emitted)
    return None if victim is None else victim[0]


class ContinuousBatcher:
    def __init__(
        self, engine: DecodeEngine, *, rows: int = 8, chunk_steps: int = 1,
        chunk_steps_low: int | None = None, group_chunks: int = 1,
        prefill_only: bool = False, chunked_prefill: int | None = None,
    ):
        # chunk_steps > 1 advances all rows that many tokens per scheduler
        # step (one fused scan instead of per-token dispatch); combined
        # with the one-chunk-lag pipeline the host round-trip disappears
        # from the critical path entirely.
        #
        # The chunk is also the scheduling granularity: admission and
        # row-freeing happen once per chunk, so TTFT carries ~1.5 chunks
        # of latency. ``chunk_steps_low`` (default: half of chunk_steps)
        # is used while under 3/4 of the rows are busy — at low load the
        # chip has headroom and the shorter chunk halves perceived TTFT;
        # at saturation the full chunk keeps the host off the critical
        # path. Both sizes are prewarmed.
        #
        # ``group_chunks`` (K) dispatches K chunks as ONE jitted program
        # while busy (DecodeEngine._decode_group): on-device EOS/poison
        # carry between the chunks and the host gets one packed fetch per
        # GROUP — K× fewer host syncs and dispatches at saturation, at the
        # cost of admission/free granularity stretching to K chunks. At
        # low load the group collapses to (1 × chunk_steps_low) so TTFT
        # keeps the short-chunk latency. Token streams are bit-identical
        # to group_chunks=1 (docs/decode-loop.md).
        if chunk_steps < 1:
            raise ValueError(f"chunk_steps must be >= 1, got {chunk_steps}")
        if group_chunks < 1:
            raise ValueError(
                f"group_chunks must be >= 1, got {group_chunks}"
            )
        self.engine = engine
        self.rows = rows
        self.chunk_steps = chunk_steps
        self.chunk_steps_low = (
            chunk_steps_low if chunk_steps_low is not None
            else max(1, chunk_steps // 2)
        )
        self.group_chunks = group_chunks
        # Paged KV: the scheduling capacity unit becomes the block pool,
        # not the row count — rows are admitted when free blocks cover
        # prompt + max_new (+ shared prefix blocks ride for free), and a
        # finished/cancelled row returns its blocks immediately. All the
        # paged bookkeeping below is worker-thread state (like ``active``);
        # only the BlockAllocator itself is cross-thread (metrics read it)
        # and carries its own lock.
        self._paged = engine.kv_layout == "paged"
        # Prefill-only mode (disaggregated serving, serve/handoff.py):
        # admission runs exactly as usual — seed + batched prefill into
        # pool blocks — but instead of decoding, _resolve_admission
        # EXPORTS each row's blocks through ``export_cb`` and frees the
        # row immediately. No decode group ever dispatches (active is
        # empty outside the admit->resolve window, so step() always takes
        # the direct admit path). Requests whose answer IS the first
        # token (max_new <= 1, or the prefill sampled EOS) are answered
        # locally through done_cb — bit-identical to a unified worker.
        # Paged-only: the block table is the transfer unit.
        if prefill_only and engine.kv_layout != "paged":
            raise ValueError("prefill_only requires kv_layout='paged'")
        self.prefill_only = prefill_only
        # Chunked prefill (docs/decode-loop.md): prompts admit WITHOUT a
        # dedicated prefill program — they stream through the ragged
        # mixed-batch dispatch (DecodeEngine._ragged_group) as extra query
        # rows, ``chunked_prefill`` tokens per step, alongside the decode
        # rows advancing one token each. The prefill bucket ladder and its
        # (P × S) prewarm grid die with the dedicated program, and a long
        # prompt admits across O(len/budget) *shared* steps instead of one
        # monolithic prefill that stalls every decode row for seconds.
        # Paged-only: admission is a table upload + positions merge (the
        # pool IS the scratch); the dense path would still need a row copy.
        if chunked_prefill is not None:
            if chunked_prefill < 1:
                raise ValueError(
                    f"chunked_prefill must be >= 1, got {chunked_prefill}"
                )
            if engine.kv_layout != "paged":
                raise ValueError(
                    "chunked_prefill requires kv_layout='paged'"
                )
        self.chunked_prefill = chunked_prefill
        self._chunked = chunked_prefill is not None
        # row -> remaining prompt tokens to feed / total prompt length
        # (worker-thread state, like ``active``).
        self._inflight_prefill: dict[int, list[int]] = {}
        self._prefill_plen: dict[int, int] = {}
        # Called as export_cb(req_id, first_token, n_tokens, blocks) with
        # ``blocks`` the export_blocks() host-array dict; set by the
        # serving layer before submitting.
        self.export_cb: Callable[..., None] | None = None
        # Preemption hook: called as preempt_cb(req_id, tokens) when a
        # running row is evicted for a higher-priority pending request
        # (the serving layer stamps resume_tokens and refunds the request
        # to the broker). None disables preemption entirely — the check
        # never runs, keeping FIFO deployments at zero overhead.
        self.preempt_cb: Callable[[str, list[int]], None] | None = None
        # Tiered-KV hooks (serve/kvstore.py). ``demote_cb(prefix)``
        # receives each idle Prefix evicted from the pool — its blocks
        # are already freed (the Prefix owns its own arrays), so the
        # store encodes off-thread while admission proceeds.
        # ``park_cb(req_id, tokens, blocks)`` receives a finished session
        # turn's exported KV (see ``_maybe_park``). Both None by default:
        # without a store every eviction is a plain drop and no finish
        # exports — bit-identical to the pre-tiering batcher.
        self.demote_cb: Callable[..., None] | None = None
        self.park_cb: Callable[..., None] | None = None
        # req_id -> (token_ids, replayed): park interest registered by
        # the serving layer, which is the only holder of prompt ids (the
        # batcher's rows carry outputs, and adopted rows no ids at all).
        self._park_ids: dict[str, tuple] = {}  # guarded_by: self._lock
        if self._paged:
            mb = engine.max_seq_len // engine.block_size
            n_blocks = engine.kv_blocks or rows * mb
            self.cache = engine.new_paged_cache(
                rows, num_blocks=n_blocks, identity=False
            )
            self.allocator = BlockAllocator(n_blocks)
            self._sentinel = table_sentinel(n_blocks)
            self._host_tables = np.full((rows, mb), self._sentinel, np.int32)
            self._row_owned: dict[int, list[int]] = {}
            self._row_shared: dict[int, list[int]] = {}
            # row -> monotonic reserve time: block-seconds cost attribution
            # (blocks held x hold duration, charged at release).
            self._row_reserve_t: dict[int, float] = {}
            # id(prefix) -> (prefix, full-block ids); the registry holds
            # one allocator ref per block so an idle prefix survives until
            # evicted to admit new work.
            self._paged_prefixes: dict[int, tuple] = {}
            engine.metrics.set_kv_blocks(total=n_blocks, in_use=0)
            self._merge_positions = jax.jit(
                lambda big, sub, rows_: big.at[rows_].set(sub, mode="drop"),
                donate_argnums=(0,),
            )
            self._seed_blocks = jax.jit(
                self._seed_blocks_impl, donate_argnums=(0,)
            )
            # Decode-side adopt scatter (cache.import_blocks): block count
            # pads to a power of two (sentinel ids drop), so the compile
            # envelope is log2(max_blocks) programs.
            self._import_blocks = jax.jit(
                import_blocks, donate_argnums=(0,)
            )
        else:
            self.cache = engine.new_cache(rows)
        self.pending: deque = deque()  # guarded_by: self._lock
        self.active: dict[int, _Row] = {}
        self._free = list(range(rows))  # guarded_by: self._lock
        # Host-side upper bound on each ACTIVE row's ring position — drives
        # the decode chunk's cache-read bucket (engine.decode_bucket): the
        # chunk reads only the live-context prefix of the ring, so decode
        # cost follows occupancy, not the provisioned max_seq_len. Freed
        # rows keep advancing on device past any bucket; their reads are
        # garbage nobody consumes and their writes stay within their own
        # row, so only active rows constrain the bucket.
        self._row_pos: dict[int, int] = {}
        # Device-resident decode state (see module docstring), carried in
        # the engine's canonical shardings so every executable keeps one
        # steady-state signature (DecodeEngine.canon_cache/canon_vec).
        self._tokens_dev = engine.canon_vec(jnp.zeros(rows, jnp.int32))
        self._cur_pos_dev = engine.canon_vec(jnp.zeros(rows, jnp.int32))
        self._step_count = 0
        self._cancelled: set[str] = set()  # guarded_by: self._lock
        self._inflight: _InFlightGroup | None = None
        self._pending_adm: _InFlightAdmission | None = None
        self._last_fetch_t: float | None = None
        self._devtel_last_t = float("-inf")
        self._lock = threading.Lock()

        cfg = engine.cfg
        self._insert = jax.jit(self._insert_impl, donate_argnums=(0,))
        self._prefill_row = jax.jit(
            partial(DecodeEngine._prefill_impl, cfg, engine.mesh),
            donate_argnums=(2,),
        )

    def _pad_row_idx(self, P: int, rows: list[int]) -> np.ndarray:
        """[P] scatter indices for an admission insert: real rows first,
        padding filled with a POSITIVE out-of-range sentinel (self.rows).
        mode="drop" only drops indices that are OOB *after* normalization,
        and JAX wraps negative indices first — a -1 sentinel would scatter
        the dummy row into live row rows-1, zeroing its KV."""
        idx = np.full(P, self.rows, np.int32)
        idx[: len(rows)] = rows
        return idx

    @staticmethod
    def _insert_impl(big: KVCache, small: KVCache, rows) -> KVCache:
        """Copy scratch-cache rows into the persistent cache at ``rows``
        ([P] int32; entries >= big rows are padding and dropped — the
        sentinel must be positive OOB, since negative indices wrap)."""
        return KVCache(
            k=big.k.at[:, rows].set(small.k, mode="drop"),
            v=big.v.at[:, rows].set(small.v, mode="drop"),
            positions=big.positions.at[rows].set(
                small.positions, mode="drop"
            ),
            k_scale=(
                big.k_scale.at[:, rows].set(small.k_scale, mode="drop")
                if big.k_scale is not None else None
            ),
            v_scale=(
                big.v_scale.at[:, rows].set(small.v_scale, mode="drop")
                if big.v_scale is not None else None
            ),
        )

    # -- paged-KV plumbing --------------------------------------------------

    @staticmethod
    def _seed_blocks_impl(cache: PagedKVCache, pk, pv, pks, pvs, block_ids):
        """Materialize a prefix's FULL blocks in the pool: the dense
        ``Prefix`` segment's first ``nf*bs`` tokens, reshaped block-wise
        and scattered at ``block_ids`` ([nf] int32). These blocks are
        immutable from here on — rows reference them via their tables and
        never write them (COW masks the seed's own writes elsewhere)."""
        bs = cache.block_size
        nf = block_ids.shape[0]

        def put(pool, seg):
            if pool is None:
                return None
            seg = seg[:, : nf * bs]
            r = seg.reshape((seg.shape[0], nf, bs) + seg.shape[2:])
            return pool.at[:, block_ids].set(r.astype(pool.dtype), mode="drop")

        return cache._replace(
            k=put(cache.k, pk), v=put(cache.v, pv),
            k_scale=put(cache.k_scale, pks), v_scale=put(cache.v_scale, pvs),
        )

    def _dev_tables(self, tables: np.ndarray) -> jax.Array:
        from jax.sharding import NamedSharding, PartitionSpec

        return jax.device_put(
            jnp.asarray(tables, jnp.int32),
            NamedSharding(self.engine.mesh, PartitionSpec()),
        )

    def _paged_scratch_view(
        self, P: int, tables: np.ndarray | None = None
    ) -> PagedKVCache:
        """A P-row admission 'scratch cache' that SHARES the big pool:
        fresh per-view positions and the admitted rows' tables, but the
        same pool buffers — prefill writes land in place, so absorbing an
        admission is a positions merge + table upload, never a KV copy."""
        eng = self.engine
        if tables is None:
            mb = eng.max_seq_len // eng.block_size
            tables = np.full((P, mb), self._sentinel, np.int32)
        return PagedKVCache(
            k=self.cache.k, v=self.cache.v,
            block_tables=self._dev_tables(tables),
            positions=eng.canon_vec(
                jnp.full((P, eng.max_seq_len), -1, jnp.int32)
            ),
            k_scale=self.cache.k_scale, v_scale=self.cache.v_scale,
        )

    def _paged_absorb(self, view: PagedKVCache, row_idx: np.ndarray) -> None:
        """Fold a prefilled scratch view back into the big cache. The view's
        pool buffers ARE the big cache's (threaded through the seed/prefill
        donations), so only row positions scatter in and the host tables
        upload — this is also where freed rows' device tables go sentinel,
        cutting off their stale reads."""
        eng = self.engine
        view = eng.canon_cache(view)
        self.cache = eng.canon_cache(PagedKVCache(
            k=view.k, v=view.v,
            block_tables=self._dev_tables(self._host_tables),
            positions=self._merge_positions(
                self.cache.positions, view.positions, jnp.asarray(row_idx)
            ),
            k_scale=view.k_scale, v_scale=view.v_scale,
        ))

    def _paged_evict_idle_prefixes(self, keep: int | None = None) -> int:
        """Reclaim prefix block sets no live row references (every block
        at the registry's own refcount of 1) — the paged admission's
        backstop when the pool runs dry. Returns sets evicted."""
        freed = 0
        demoted = 0
        for key, (_pfx, blocks) in list(self._paged_prefixes.items()):
            if key == keep or not blocks:
                continue
            if all(self.allocator.refcount(b) == 1 for b in blocks):
                self.allocator.free(blocks)
                del self._paged_prefixes[key]
                freed += 1
                if self.demote_cb is not None:
                    # Tiered KV: hand the Prefix down instead of dropping
                    # it. The blocks are already free — the store's encode
                    # reads the Prefix's OWN arrays, off this thread.
                    try:
                        self.demote_cb(_pfx)
                        demoted += 1
                    except Exception:  # noqa: BLE001 — a failed demote is a drop
                        pass
        if freed:
            self.allocator.record_evictions(freed)
            self.engine.metrics.add_kv_evictions(demoted, demoted=True)
            self.engine.metrics.add_kv_evictions(freed - demoted)
        return freed

    def _ensure_paged_prefix(self, prefix) -> list[int] | None:
        """Register a retained Prefix's FULL blocks in the pool (once per
        prefix object): allocate, scatter the dense segment in, and hold
        one ref per block so the set outlives its rows. Returns the block
        ids (possibly []), or None when the pool can't fit them even
        after evicting idle prefixes."""
        key = id(prefix)
        hit = self._paged_prefixes.get(key)
        if hit is not None:
            return hit[1]
        bs = self.engine.block_size
        nf = prefix.length // bs
        if nf == 0:
            self._paged_prefixes[key] = (prefix, [])
            return []
        blocks = self.allocator.alloc(nf)
        if blocks is None and self._paged_evict_idle_prefixes(keep=key):
            blocks = self.allocator.alloc(nf)
        if blocks is None:
            return None
        self.cache = self.engine.canon_cache(self._seed_blocks(
            self.cache, prefix.k, prefix.v, prefix.k_scale, prefix.v_scale,
            jnp.asarray(blocks, jnp.int32),
        ))
        self._paged_prefixes[key] = (prefix, blocks)
        return blocks

    def _paged_reserve(self, taken: list, rows: list[int], head_prefix):
        """Block-pool admission control: reserve each candidate row's
        blocks (``ceil((prompt + max_new)/bs)`` minus the prefix's shared
        full blocks, which are increfed instead of copied — the COW
        partial tail lands in the row's first owned block). Rows that
        don't fit requeue to the FRONT of the queue in order and their
        row slots go back — admission degrades to pool capacity, not row
        count. Returns the (items, rows) that did fit."""
        bs = self.engine.block_size
        shared: list[int] = []
        if head_prefix is not None:
            got = self._ensure_paged_prefix(head_prefix)
            if got is None:
                with self._lock:
                    for item, row in zip(reversed(taken), reversed(rows)):
                        self.pending.appendleft(item)
                        self._free.append(row)
                return [], []
            shared = got
        ns = len(shared)
        keep = id(head_prefix) if head_prefix is not None else None
        ok_items, ok_rows, failed = [], [], []
        for item, row in zip(taken, rows):
            ids, gen = item[1], item[2]
            need = -(-(len(ids) + gen.max_new_tokens) // bs) - ns
            if need + ns > self.allocator.num_blocks:
                # Bigger than the whole pool: requeueing would spin
                # forever. Answer it now (check_capacity bounds requests
                # by max_seq_len, not by a smaller kv_blocks setting).
                with self._lock:
                    self._free.append(row)
                self.engine.metrics.add_error(1)
                item[3]([], error=(
                    f"request needs {need + ns} KV blocks but the pool "
                    f"has {self.allocator.num_blocks}"
                ))
                continue
            owned = self.allocator.alloc(need)
            if owned is None and self._paged_evict_idle_prefixes(keep=keep):
                owned = self.allocator.alloc(need)
            if owned is None:
                failed.append((item, row))
                continue
            if shared:
                self.allocator.incref(shared)
            self._row_owned[row] = owned
            self._row_shared[row] = list(shared)
            self._row_reserve_t[row] = time.monotonic()
            self._host_tables[row, :] = self._sentinel
            self._host_tables[row, :ns] = shared
            self._host_tables[row, ns:ns + len(owned)] = owned
            ok_items.append(item)
            ok_rows.append(row)
        if failed:
            with self._lock:
                for item, row in reversed(failed):
                    self.pending.appendleft(item)
                    self._free.append(row)
        self.engine.metrics.set_kv_blocks(
            in_use=self.allocator.blocks_in_use
        )
        return ok_items, ok_rows

    def _paged_release_row(self, row: int) -> float:
        """Return a finished/cancelled row's blocks to the pool NOW (owned
        blocks free; shared prefix blocks decref). The device-side table
        stays stale until the next admission uploads tables — safe because
        done rows' KV writes are slot-suppressed on device
        (DecodeEngine._decode_many_impl) and nobody reads a freed row.

        Returns the row's block-seconds (blocks held x hold duration) for
        per-request cost attribution; the cumulative also lands on the
        engine's ``kv_block_seconds`` counter."""
        if not self._paged:
            return 0.0
        owned = self._row_owned.pop(row, [])
        shared = self._row_shared.pop(row, [])
        self.allocator.free(owned)
        self.allocator.free(shared)
        self._host_tables[row, :] = self._sentinel
        held = 0.0
        t0 = self._row_reserve_t.pop(row, None)
        n_blocks = len(owned) + len(shared)
        if t0 is not None and n_blocks:
            held = (time.monotonic() - t0) * n_blocks
            self.engine.metrics.add_kv_block_seconds(held)
        self.engine.metrics.set_kv_blocks(
            in_use=self.allocator.blocks_in_use
        )
        return held

    def _prewarm_scratch(self, P: int):
        """Admission scratch for prewarm. Paged: an all-sentinel VIEW over
        the live pool (every write drops) — the pool's shape is baked into
        the prefill executable, so prewarming against a separately sized
        throwaway pool would compile the wrong program."""
        if self._paged:
            return self._paged_scratch_view(P)
        return self.engine.new_cache(P)

    def _prewarm_absorb_pools(self, scratch) -> None:
        """Paged prewarm threads the ONE pool through every donating
        prefill — rebind the big cache's pool leaves from the view after
        each call so the next view (and live serving) holds live buffers."""
        if not self._paged:
            return
        eng = self.engine
        scratch = eng.canon_cache(scratch)
        self.cache = eng.canon_cache(self.cache._replace(
            k=scratch.k, v=scratch.v,
            k_scale=scratch.k_scale, v_scale=scratch.v_scale,
        ))

    def prewarm(
        self, seq_buckets: list[int] | None = None,
        prefix_prefill: bool = False,
    ) -> int:
        """Compile every executable the scheduler can hit: admission
        prefill for each (admission-batch P, seq bucket S) pair, the row
        insert + device-state merge per P, and the decode chunk at the
        full row count — so no request ever eats a multi-second XLA
        compile mid-serve. ``seq_buckets`` narrows the prompt-length
        envelope when known (default: every bucket up to the engine's
        max_seq_len); ``prefix_prefill`` additionally compiles each
        bucket's prefix-reuse admission variant (the ``start``-offset
        signature) — set it when requests will carry a ``prefix``.
        Returns the number of executables compiled."""
        eng = self.engine
        if seq_buckets is None:
            seq_buckets = eng.seq_buckets()
        dt = devtel.enabled()
        if dt:
            devtel.install_monitoring_hook()
            # Watch both jit namespaces: the engine's grouped/ragged
            # programs AND the scheduler's own insert/prefill-row jits.
            devtel.observer().watch_obj(eng)
            devtel.observer().watch_obj(self)
        Ps, p = [], 1
        while p < self.rows:
            Ps.append(p)
            p *= 2
        Ps.append(p)  # one above, for n == rows when rows isn't a pow2
        n_compiled = 0
        if self._chunked and prefix_prefill:
            # build_prefix still runs through the ENGINE's own _prefill jit
            # at batch=1 even under chunked prefill (prefix construction is
            # a one-off dense prefill, not an admission) — warm it per
            # bucket so the first prefix build doesn't compile mid-serve.
            sa1 = eng._sample_args(GenerationParams(), 1)
            for S in seq_buckets:
                c1 = eng.new_cache(1)
                _, _, c1 = eng._prefill(
                    eng.params, jnp.zeros((1, S), np.int32), c1,
                    jnp.ones(1, np.int32), sa1,
                )
                del c1
                n_compiled += 1
        for P in sorted(set(Ps)):
            sa = eng._sample_args(GenerationParams(), P)
            scratch = None
            tok = jnp.zeros(P, jnp.int32)
            # Chunked prefill KILLS the (P × S) admission-prefill grid:
            # prompts stream through the ragged dispatch, so no dedicated
            # prefill executable exists to warm — only the per-P positions
            # merge + device-state merge below, and the ragged combos
            # after the decode loop. The steady-state executable count
            # collapses to the two grouped-decode combos (× buckets) plus
            # the two ragged step counts (tests/test_ragged.py asserts).
            for S in seq_buckets if not self._chunked else []:
                scratch = self._prewarm_scratch(P)
                ids = jnp.zeros((P, S), np.int32)
                lens = jnp.ones(P, np.int32)
                tok, _, scratch = self._prefill_row(
                    eng.params, ids, scratch, jnp.asarray(lens), sa,
                )
                self._prewarm_absorb_pools(scratch)
                n_compiled += 1
                if prefix_prefill:
                    scratch = self._prewarm_scratch(P)
                    tok, _, scratch = self._prefill_row(
                        eng.params, ids, scratch, jnp.asarray(lens), sa,
                        jnp.zeros(P, np.int32),
                    )
                    self._prewarm_absorb_pools(scratch)
                    n_compiled += 1
                    # build_prefix itself runs through the ENGINE's own
                    # _prefill jit at batch=1 — a separate jit object from
                    # _prefill_row — so the first prefix build would
                    # otherwise compile mid-serve.
                    c1 = eng.new_cache(1)
                    sa1 = eng._sample_args(GenerationParams(), 1)
                    _, _, c1 = eng._prefill(
                        eng.params, jnp.zeros((1, S), np.int32), c1,
                        jnp.ones(1, np.int32), sa1,
                    )
                    del c1
                    n_compiled += 1
            # Insert/absorb with all-dropped indices: compiles the P-shaped
            # scatter without touching live rows. Once — the live path
            # feeds it exactly these canonical shardings.
            if self._paged:
                self.cache = eng.canon_cache(self.cache._replace(
                    positions=self._merge_positions(
                        self.cache.positions,
                        eng.canon_vec(
                            jnp.full((P, eng.max_seq_len), -1, jnp.int32)
                        ),
                        jnp.asarray(self._pad_row_idx(P, [])),
                    ),
                ))
            else:
                scratch = eng.canon_cache(scratch)
                self.cache = eng.canon_cache(self._insert(
                    self.cache, scratch,
                    jnp.asarray(self._pad_row_idx(P, [])),
                ))
            n_compiled += 1
            self._tokens_dev, self._cur_pos_dev = (
                eng.canon_vec(x) for x in eng._admit_merge(
                    self._tokens_dev, self._cur_pos_dev, eng.canon_vec(tok),
                    jnp.ones(P, jnp.int32),
                    jnp.asarray(self._pad_row_idx(P, [])),
                )
            )
            n_compiled += 1
        # Decode group at the full row count: both live (n_chunks, k)
        # combos — the busy full group and the low-load single short chunk
        # — × every cache-read bucket (the live path picks the bucket from
        # row positions, so all ladder entries are reachable).
        sa = eng._sample_args(GenerationParams(), self.rows)
        combos = sorted({
            (self.group_chunks, self.chunk_steps),
            (1, self.chunk_steps_low),
        })
        for nc, k in combos:
            for tb in eng.prewarm_bucket_set():
                if dt:
                    # Roofline cost from the unoptimized HLO, derived
                    # BEFORE the executing call (lower() only traces;
                    # execution deletes the donated carries).
                    eng.devtel_cost(
                        "decode_group", (self.rows, nc, k, tb),
                        batch=self.rows, steps=nc * k, kv_len=tb,
                        lower_thunk=lambda: eng._decode_group.lower(
                            eng.params, self._tokens_dev, self.cache,
                            self._cur_pos_dev, sa,
                            jnp.ones(self.rows, bool),
                            jnp.full(self.rows, -1, np.int32),
                            n_chunks=nc, n_steps=k, t_bucket=tb,
                        ),
                    )
                _, last_tok, cache, cur_pos, _ = eng._decode_group(
                    eng.params, self._tokens_dev, self.cache,
                    self._cur_pos_dev, sa,
                    jnp.ones(self.rows, bool),
                    jnp.full(self.rows, -1, np.int32),
                    n_chunks=nc, n_steps=k, t_bucket=tb,
                )
                self.cache = eng.canon_cache(cache)
                self._cur_pos_dev = eng.canon_vec(cur_pos)
                self._tokens_dev = eng.canon_vec(last_tok)
                n_compiled += 1
        if self._chunked:
            # The ragged mixed-batch programs — one per live step count
            # (busy and low-load). All-done dummy schedules: no KV writes
            # land (live = valid & ~done), but the executable for each
            # live xs shape [nc, rows, CB] compiles.
            CB = self.chunked_prefill
            for nc in sorted({
                self.group_chunks * self.chunk_steps, self.chunk_steps_low,
            }):
                if dt:
                    # The padded ragged executable computes every chunk
                    # slot regardless of masks, so its cost includes the
                    # full nc·rows·CB prefill budget.
                    eng.devtel_cost(
                        "ragged_group", (self.rows, nc, CB),
                        batch=self.rows, steps=nc, kv_len=None,
                        prefill_tokens=nc * self.rows * CB,
                        lower_thunk=lambda: eng._ragged_group.lower(
                            eng.params, self._tokens_dev, self.cache,
                            self._cur_pos_dev, sa,
                            jnp.ones(self.rows, bool),
                            jnp.full(self.rows, -1, np.int32),
                            jnp.zeros((nc, self.rows, CB), jnp.int32),
                            jnp.ones((nc, self.rows), jnp.int32),
                            jnp.zeros((nc, self.rows), bool),
                            jnp.ones((nc, self.rows), bool),
                        ),
                    )
                _, last_tok, cache, cur_pos, _ = eng._ragged_group(
                    eng.params, self._tokens_dev, self.cache,
                    self._cur_pos_dev, sa,
                    jnp.ones(self.rows, bool),
                    jnp.full(self.rows, -1, np.int32),
                    jnp.zeros((nc, self.rows, CB), jnp.int32),
                    jnp.ones((nc, self.rows), jnp.int32),
                    jnp.zeros((nc, self.rows), bool),
                    jnp.ones((nc, self.rows), bool),
                )
                self.cache = eng.canon_cache(cache)
                self._cur_pos_dev = eng.canon_vec(cur_pos)
                self._tokens_dev = eng.canon_vec(last_tok)
                n_compiled += 1
        # The prewarm decode ran with every row marked done/free, but its
        # cache writes still landed — reset positions so no ghost slots
        # survive into real serving. device_put with the original sharding:
        # an eager op could re-commit the array and key fresh compiles for
        # every executable that takes the cache.
        self.cache = self.cache._replace(
            positions=jax.device_put(
                jnp.full_like(self.cache.positions, -1),
                self.cache.positions.sharding,
            ),
        )
        self._cur_pos_dev = eng.canon_vec(jnp.zeros(self.rows, jnp.int32))
        self._tokens_dev = eng.canon_vec(jnp.zeros(self.rows, jnp.int32))
        # Drain the device queue before declaring warm: prewarm dispatched
        # one execution per compiled program, and remote-tunnel backends
        # pay a per-program first-run load — queued up, that backlog would
        # otherwise land on the first real admission (engine.prewarm has
        # the same guard).
        jax.block_until_ready(self.cache.positions)
        _ = int(jnp.zeros((), jnp.int32) + 1)
        if dt:
            # Every serving-path executable is compiled: from here on any
            # compile is a steady-state recompile — counted by the
            # observer and flagged on /slo.
            devtel.observer().mark_steady()
        return n_compiled

    # -- submission ---------------------------------------------------------

    def submit(
        self,
        token_ids: list[int],
        gen: GenerationParams,
        done_cb: Callable[[list[int]], None],
        req_id: str = "",
        stream_cb: Callable[[list[int]], None] | None = None,
        prefix=None,  # engine.Prefix: token_ids must extend it
        priority: int = 1,
        replayed: int = 0,
    ) -> None:
        """Queue a request. ``prefix`` (from ``engine.build_prefix``) marks
        ``token_ids`` as extending a retained KV segment: admission seeds
        the row from the segment and prefills only the suffix — turn-2 of
        a session (or the Nth request sharing a system prompt) skips the
        shared prefill entirely, with identical tokens.

        ``priority`` is the SLO-class rank (0 = interactive, highest);
        ``replayed`` resumes a preempted request: the LAST ``replayed``
        entries of ``token_ids`` are its already-emitted tokens (prompt +
        resume tokens prefill as one prompt — sampling is stateless per
        (seed, position), so the continuation is identical to the
        unpreempted run), preloaded into the row's output so the stream
        picks up where it stopped and ``max_new_tokens`` counts only the
        REMAINING tokens."""
        gen.validate()
        if replayed and not 0 < replayed < len(token_ids):
            raise ValueError(
                f"replayed={replayed} must be in [0, len(token_ids))"
            )
        if prefix is not None:
            # Same contract split_prefix enforces; checked at submit time
            # so the error surfaces on the caller, not the worker thread.
            P = prefix.length
            if len(token_ids) <= P or tuple(token_ids[:P]) != prefix.tokens:
                raise ValueError(
                    "token_ids does not extend the prefix (needs its "
                    f"{P} tokens plus at least one more)"
                )
            if P + _bucket(
                len(token_ids) - P, self.engine.max_seq_len
            ) > self.engine.max_seq_len:
                # Ring-wrap guard (ADVICE.md high): even this request's
                # own BUCKET-padded suffix would reach past the ring and
                # wrap over the seeded prefix slots — admit it without the
                # prefix (from-scratch prefill, identical tokens). Dropping
                # here also keeps it out of the prefix's admission group,
                # where a longer batchmate's bucket applies the same guard
                # batch-wide (_admit_dispatch).
                prefix = None
        # With chunked decode a near-capacity row would advance past
        # max_seq_len mid-chunk, wrap, and silently serve context-corrupted
        # tokens (the host can't see the wrap — the decode state is
        # device-resident).
        self.engine.check_capacity(len(token_ids), gen.max_new_tokens)
        with self._lock:
            self.pending.append(
                (req_id, list(token_ids), gen, done_cb, stream_cb,
                 time.perf_counter(), prefix, priority, replayed)
            )
            depth = len(self.pending)
        if req_id:
            trace.record(req_id, "sched_submit", queued=depth)

    # -- scheduling ---------------------------------------------------------

    def _admit_dispatch(self) -> _InFlightAdmission | None:
        """Dispatch admission for every pending request that has a free
        row: ONE batched prefill + ONE row-scatter cache insert + ONE
        device-state merge, **no blocking fetch**. The rows become active
        immediately (the next decode chunk reads the merged device state);
        the host fetches the first tokens later, overlapped with that
        chunk (``_resolve_admission``).

        Must be called *after* the step's decode chunk is dispatched:
        device programs run in dispatch order, so the insert + merge land
        between this chunk and the next — the running chunk can't scribble
        on freshly inserted rows, and the next chunk sees them.

        The admission batch pads to a power of two (dummy rows) so the
        compile envelope stays (log₂ rows × log₂ seq buckets) executables.

        Prefix-sharing requests are admitted in their own batches (every
        row of one admission shares one retained ``Prefix``, matched by
        identity): the scratch cache is seeded from the segment and only
        the suffixes prefill. One admission takes the OLDEST request's
        whole group from anywhere in the queue (same-prefix entries may
        jump ahead of other groups by one admission — the other groups go
        in the next step's admission, one chunk later), so an interleaved
        queue still admits in O(#groups) steps, not O(#requests).
        """
        with self._lock:
            if not self.pending or not self._free:
                return None
            head_prefix = self.pending[0][6]
            free_n = len(self._free)
            taken, rest = [], deque()
            while self.pending:
                item = self.pending.popleft()
                if len(taken) < free_n and item[6] is head_prefix:
                    taken.append(item)
                else:
                    rest.append(item)
            self.pending = rest
            rows = [self._free.pop() for _ in taken]
            n = len(taken)

        if head_prefix is not None:
            # Ring-wrap guard (ADVICE.md high): the suffix prefill pads to
            # the BATCH's bucket, and padded columns still compute slots
            # (slot = position % max_len) — a prefix start + bucket past
            # the ring would wrap those writes over the seeded prefix
            # slots. Decided BEFORE the paged reserve so the block
            # accounting matches the prefill actually dispatched. The batch
            # admits WITHOUT the prefix (from-scratch prefill of the full
            # prompts — always ring-safe since _bucket caps at
            # max_seq_len); identical tokens, only the prefix's FLOP
            # savings are lost.
            probe = _bucket(
                max(len(item[1]) - head_prefix.length for item in taken),
                self.engine.max_seq_len,
            )
            if head_prefix.length + probe > self.engine.max_seq_len:
                head_prefix = None

        if self._paged:
            # Second gate: row slots are necessary but not sufficient —
            # each row also needs blocks for prompt + max_new. Rows that
            # don't fit the pool went back to the queue inside.
            taken, rows = self._paged_reserve(taken, rows, head_prefix)
            if not taken:
                return None
            n = len(taken)

        P = 1
        while P < n:
            P *= 2
        if self._chunked:
            self._admit_chunked(taken, rows, P, head_prefix)
            return None
        plen = head_prefix.length if head_prefix is not None else 0
        # With a prefix, only each request's suffix is padded/prefilled.
        suffixes = [item[1][plen:] for item in taken]
        S = _bucket(
            max(len(s) for s in suffixes), self.engine.max_seq_len,
        )
        padded = np.zeros((P, S), np.int32)
        lens = np.ones(P, np.int32)  # dummy rows prefill one pad token
        gens = []
        for i, s in enumerate(suffixes):
            padded[i, : len(s)] = s
            lens[i] = len(s)
            gens.append(taken[i][2])
        gens += [GenerationParams()] * (P - n)
        row_idx = self._pad_row_idx(P, rows)

        sample_args = self.engine._sample_args(gens, P)
        if self._paged:
            mb = self.engine.max_seq_len // self.engine.block_size
            sub_tables = np.full((P, mb), self._sentinel, np.int32)
            sub_tables[:n] = self._host_tables[rows]
            scratch = self._paged_scratch_view(P, sub_tables)
            if head_prefix is not None:
                # Seed through COW-masked tables: the SHARED full blocks'
                # columns are sentineled out so the seed's writes to them
                # drop (they were materialized once by _seed_blocks); only
                # the partial tail lands, in each row's first OWNED block —
                # the copy-on-write copy (docs/paged-kv.md).
                ns = len(self._row_shared[rows[0]])
                seed_tables = sub_tables.copy()
                seed_tables[:, :ns] = self._sentinel
                seeded = self.engine.seed_cache(
                    scratch._replace(
                        block_tables=self._dev_tables(seed_tables)
                    ),
                    head_prefix,
                )
                scratch = self.engine.canon_cache(
                    seeded._replace(block_tables=scratch.block_tables)
                )
                tok, _, scratch = self._prefill_row(
                    self.engine.params, jnp.asarray(padded), scratch,
                    jnp.asarray(lens), sample_args,
                    jnp.full(P, plen, jnp.int32),
                )
            else:
                tok, _, scratch = self._prefill_row(
                    self.engine.params, jnp.asarray(padded), scratch,
                    jnp.asarray(lens), sample_args,
                )
            # The view's pool buffers ARE the big cache's (threaded through
            # the seed/prefill donations) — absorbing is a positions merge
            # + host-table upload, never a KV copy.
            self._paged_absorb(scratch, row_idx)
        elif head_prefix is not None:
            scratch = self.engine.canon_cache(
                self.engine.seed_cache(self.engine.new_cache(P), head_prefix)
            )
            tok, _, scratch = self._prefill_row(
                self.engine.params, jnp.asarray(padded), scratch,
                jnp.asarray(lens), sample_args,
                jnp.full(P, plen, jnp.int32),
            )
            scratch = self.engine.canon_cache(scratch)
            self.cache = self.engine.canon_cache(self._insert(
                self.cache, scratch, jnp.asarray(row_idx)
            ))
        else:
            tok, _, scratch = self._prefill_row(
                self.engine.params, jnp.asarray(padded),
                self.engine.new_cache(P), jnp.asarray(lens), sample_args,
            )
            scratch = self.engine.canon_cache(scratch)
            self.cache = self.engine.canon_cache(self._insert(
                self.cache, scratch, jnp.asarray(row_idx)
            ))
        self._tokens_dev, self._cur_pos_dev = (
            self.engine.canon_vec(x) for x in self.engine._admit_merge(
                self._tokens_dev, self._cur_pos_dev,
                self.engine.canon_vec(tok),
                jnp.asarray(lens + plen), jnp.asarray(row_idx),
            )
        )
        try:
            tok.copy_to_host_async()
        except AttributeError:  # older jax array types
            pass

        entries = []
        for i, (req_id, ids, gen, cb, scb, t_submit, _pfx, pri, rpl) in (
            enumerate(taken)
        ):
            r = _Row(
                req_id=req_id, gen=gen,
                # Resumed rows preload the replayed tokens (the prompt's
                # tail) so done_cb returns the full generation while
                # ``emitted`` keeps the stream from re-sending them.
                out=list(ids[len(ids) - rpl:]) if rpl else [],
                done_cb=cb, stream_cb=scb, awaiting_first=True,
                t_submit=t_submit, priority=pri, replayed=rpl,
                emitted=rpl,
            )
            self.active[rows[i]] = r
            self._row_pos[rows[i]] = len(ids)
            entries.append((rows[i], r))
        return _InFlightAdmission(entries=entries, tok=tok)

    def _admit_chunked(
        self, taken: list, rows: list[int], P: int, head_prefix,
    ) -> None:
        """Chunked-prefill admission: NO prefill program runs. The rows'
        blocks are already reserved (``_paged_reserve``) and their tables
        staged host-side; admission is one table upload, one positions
        merge (seeding prefix rows' shared-FULL-block positions, clearing
        everything else to -1), and one device-state merge pointing
        ``cur_pos`` at the feed start. The prompt itself streams through
        the next ragged groups, ``chunked_prefill`` tokens per step.

        Prefix rows resume after the shared full blocks (``start = ns·bs``)
        and re-feed the COW partial tail through the ragged steps — its KV
        lands in the row's first owned block, exactly where the dedicated
        prefill's copy-on-write would put it."""
        eng = self.engine
        n = len(taken)
        row_idx = self._pad_row_idx(P, rows)
        ns = (
            len(self._row_shared[rows[0]]) if head_prefix is not None else 0
        )
        start = ns * eng.block_size
        sub = np.full((P, eng.max_seq_len), -1, np.int32)
        sub[:n, :start] = np.arange(start, dtype=np.int32)[None, :]
        self.cache = eng.canon_cache(self.cache._replace(
            block_tables=self._dev_tables(self._host_tables),
            positions=self._merge_positions(
                self.cache.positions, eng.canon_vec(jnp.asarray(sub)),
                jnp.asarray(row_idx),
            ),
        ))
        starts = np.ones(P, np.int32)
        starts[:n] = start
        # Carry token 0 is never read: every planned chunk of these rows
        # feeds prompt slices until emit flips on.
        self._tokens_dev, self._cur_pos_dev = (
            eng.canon_vec(x) for x in eng._admit_merge(
                self._tokens_dev, self._cur_pos_dev,
                eng.canon_vec(jnp.zeros(P, jnp.int32)),
                jnp.asarray(starts), jnp.asarray(row_idx),
            )
        )
        for i, (req_id, ids, gen, cb, scb, t_submit, _pfx, pri, rpl) in (
            enumerate(taken)
        ):
            r = _Row(
                req_id=req_id, gen=gen,
                out=list(ids[len(ids) - rpl:]) if rpl else [],
                done_cb=cb, stream_cb=scb, awaiting_first=True,
                t_submit=t_submit, priority=pri, replayed=rpl,
                emitted=rpl,
            )
            self.active[rows[i]] = r
            self._row_pos[rows[i]] = start
            self._inflight_prefill[rows[i]] = list(ids[start:])
            self._prefill_plen[rows[i]] = len(ids)

    def _maybe_preempt(self) -> int:
        """Evict the lowest-priority running row when the head pending
        request strictly outranks it and admission is blocked on rows or
        pool blocks. At most ONE eviction per step — the freed capacity
        feeds this same step's ``_admit_dispatch``, and bounding the hook
        keeps its host cost within the per-request overhead budget
        (tools/bench_priority.py measures the no-op path).

        The eviction mirrors ``_finish`` minus the terminal callback:
        flush what already streamed, release the row's blocks (owned free,
        COW prefix shares decref — exactly balancing the reserve's
        increfs), and hand the emitted tokens to ``preempt_cb`` for the
        broker refund. Tokens for this row still inside the in-flight
        group are discarded unseen; sampling is stateless per (seed,
        position), so the resume regenerates them identically."""
        cb = self.preempt_cb
        if cb is None or self.prefill_only:
            return 0
        with self._lock:
            if not self.pending:
                return 0
            head = self.pending[0]
            free_rows = len(self._free)
        head_pri = head[7]
        blocked = free_rows == 0
        if not blocked and self._paged:
            ids, gen = head[1], head[2]
            need = -(
                -(len(ids) + gen.max_new_tokens) // self.engine.block_size
            )
            blocked = need > self.allocator.free_blocks
        if not blocked:
            return 0
        candidates = [
            (row, r.priority, len(r.out))
            for row, r in self.active.items()
            # Only settled rows are evictable: a row awaiting its first
            # token (admission in flight, or prompt still streaming
            # through ragged chunks) has no resume point yet, and an
            # anonymous row can't be refunded to a broker.
            if r.req_id and not r.awaiting_first
            and row not in self._inflight_prefill
        ]
        row = select_preemption_victim(candidates, head_pri)
        if row is None:
            return 0
        r = self.active[row]
        self._flush_stream(r)
        self.active.pop(row, None)
        self._row_pos.pop(row, None)
        self._prefill_plen.pop(row, None)
        self._paged_release_row(row)
        with self._lock:
            self._free.append(row)
        self.engine.metrics.add_preempted(1)
        trace.record(
            r.req_id, "evict", tokens=len(r.out), priority=r.priority,
            for_priority=head_pri,
        )
        cb(r.req_id, list(r.out))
        return 1

    def _resolve_admission(self, adm: _InFlightAdmission | None) -> int:
        """Host bookkeeping for a dispatched admission (fetch its first
        tokens — by now overlapped with at least one decode chunk)."""
        if adm is None:
            return 0
        firsts = np.asarray(adm.tok)
        n = 0
        for i, (row, r) in enumerate(adm.entries):
            if self.active.get(row) is not r:
                continue  # cancelled (and possibly re-admitted) meanwhile
            self._resolve_first(row, r, int(firsts[i]))
            n += 1
        return n

    def _resolve_first(self, row: int, r: _Row, first: int) -> None:
        """Host bookkeeping at a request's FIRST token — shared by the
        admission-prefill resolve and the ragged chunked path (there the
        first token arrives in the chunk that completed the prompt)."""
        now = time.perf_counter()
        # TTFT spans submit → resolve: queueing for a free row, the
        # admission prefill (or the chunked prompt streaming), AND the
        # decode work the admission deliberately overlapped — the time a
        # client actually waited for its first token. Resumed rows skip
        # both stats: their client saw its first token before the
        # preemption, and counting the re-admission would double-bill
        # requests_served.
        if not r.replayed:
            self.engine.metrics.ttft.record(now - r.t_submit)
            self.engine.metrics.add_request(1)
        if r.req_id:
            # "admit" (not "prefill"): its duration is submit→first
            # token — queue wait + prefill + overlapped chunk — while
            # the role worker's "prefill" span times only the export
            # call; distinct names keep phase sums from double-counting.
            trace.record(r.req_id, "admit", dur_s=now - r.t_submit)
        r.awaiting_first = False
        eos = (
            r.gen.eos_token_id if r.gen.eos_token_id is not None else -1
        )
        if first == eos or r.gen.max_new_tokens == 0:
            self._finish(row, r)
            return
        if self.prefill_only and r.gen.max_new_tokens > 1:
            # Disaggregated prefill: export the row's blocks and free
            # it — the decode replica owns the request from here.
            # (max_new == 1 falls through: the first token IS the
            # answer, shipping KV for it would be pure overhead.)
            self._export_row(
                row, r, first, n_tokens=self._prefill_plen.get(row)
            )
            return
        r.out.append(first)
        self.engine.metrics.add_tokens(1)
        if len(r.out) >= r.gen.max_new_tokens + r.replayed:
            self._finish(row, r)
        else:
            # First token goes out now, not a full chunk later —
            # streaming's perceived TTFT is the point.
            self._flush_stream(r)

    def _export_row(
        self, row: int, r: _Row, first: int, n_tokens: int | None = None,
    ) -> None:
        """Prefill-only epilogue for one admitted row: copy its blocks to
        host (a pure pool read — COW-shared prefix blocks stay shared and
        refcounted for the NEXT request; ``export_blocks`` zeroes slot
        garbage past ``n_tokens``), free the row, then hand the payload
        to ``export_cb``. Freeing first means an export_cb that throws
        can't leak the row; the host copy is complete before the blocks
        return to the pool, so reuse can't corrupt it. ``n_tokens`` is the
        prompt length — passed explicitly on the chunked path, where
        ``_row_pos`` has already advanced past it by plan time."""
        if n_tokens is None:
            n_tokens = self._row_pos[row]
        bs = self.engine.block_size
        nb = -(-n_tokens // bs)
        blk_ids = self._host_tables[row, :nb].copy()
        blocks = export_blocks(self.cache, blk_ids, n_tokens)
        cb = self.export_cb
        self.active.pop(row, None)
        self._row_pos.pop(row, None)
        self._inflight_prefill.pop(row, None)
        self._prefill_plen.pop(row, None)
        self._paged_release_row(row)
        with self._lock:
            self._free.append(row)
        self.engine.metrics.add_tokens(1)
        if cb is not None:
            cb(r.req_id, first, n_tokens, blocks)

    def adopt(
        self,
        req_id: str,
        first_token: int,
        n_tokens: int,
        blocks: dict,
        gen: GenerationParams,
        done_cb: Callable[..., None],
        stream_cb: Callable[[list[int]], None] | None = None,
    ) -> bool:
        """Decode-side half of the KV handoff: install an imported
        prompt's blocks into a free row and decode from token ``n_tokens``
        on, WITHOUT a prefill pass. Returns False (record untouched) when
        no row or not enough pool blocks are free — the caller keeps the
        record and retries while touching its handoff lease.

        Bit-identity with a local prefill holds because every piece of
        decode-visible state is reconstructed exactly: the pool bytes are
        the exported ones (bf16/int8 round-trip is exact), positions are
        the same arange-mask a local admission produces, and sampling is
        stateless per (seed, position) so resuming at ``cur_pos =
        n_tokens`` with ``tokens = first_token`` continues the identical
        stream (tests/test_handoff.py).
        """
        if not self._paged:
            raise ValueError("adopt requires kv_layout='paged'")
        if self.prefill_only:
            raise ValueError("prefill-only batcher cannot adopt")
        gen.validate()
        self.engine.check_capacity(n_tokens, gen.max_new_tokens)
        eng = self.engine
        bs = eng.block_size
        nb = -(-n_tokens // bs)
        k_seg = blocks["k"]
        if k_seg is None or k_seg.shape[1] != nb:
            raise ValueError(
                f"payload has {None if k_seg is None else k_seg.shape[1]} "
                f"blocks, prompt of {n_tokens} tokens needs {nb}"
            )
        if k_seg.shape[2] != bs:
            raise ValueError(
                f"payload block_size {k_seg.shape[2]} != engine {bs}"
            )
        if bool(blocks.get("k_scale") is not None) != self.cache.quantized:
            raise ValueError(
                "payload quantization does not match the engine's pool"
            )
        # All validation done — now take a row and the blocks.
        with self._lock:
            if not self._free:
                return False
            row = self._free.pop()
        need = -(-(n_tokens + gen.max_new_tokens) // bs)
        owned = self.allocator.alloc(need)
        if owned is None and self._paged_evict_idle_prefixes():
            owned = self.allocator.alloc(need)
        if owned is None:
            with self._lock:
                self._free.append(row)
            return False
        self._row_owned[row] = owned
        self._row_shared[row] = []
        self._row_reserve_t[row] = time.monotonic()
        self._host_tables[row, :] = self._sentinel
        self._host_tables[row, :need] = owned
        eng.metrics.set_kv_blocks(in_use=self.allocator.blocks_in_use)

        # Import scatter, block count padded to a power of two (sentinel
        # ids drop) so the compile envelope stays log2(max_blocks).
        P2 = 1
        while P2 < nb:
            P2 *= 2
        ids = np.full(P2, self._sentinel, np.int32)
        ids[:nb] = owned[:nb]

        def padded(seg):
            if seg is None:
                return None
            seg = np.asarray(seg)
            if P2 == nb:
                return seg
            pad = np.zeros(
                (seg.shape[0], P2 - nb) + seg.shape[2:], seg.dtype
            )
            return np.concatenate([seg, pad], axis=1)

        cache = self._import_blocks(
            self.cache, padded(blocks["k"]), padded(blocks["v"]),
            padded(blocks.get("k_scale")), padded(blocks.get("v_scale")),
            jnp.asarray(ids),
        )
        # Positions: the same arange-under-n_tokens mask a local
        # admission's prefill writes; table upload cuts any stale mapping.
        sub = np.full((1, eng.max_seq_len), -1, np.int32)
        sub[0, :n_tokens] = np.arange(n_tokens, dtype=np.int32)
        cache = cache._replace(
            block_tables=self._dev_tables(self._host_tables),
            positions=self._merge_positions(
                cache.positions, eng.canon_vec(jnp.asarray(sub)),
                jnp.asarray([row], jnp.int32),
            ),
        )
        self.cache = eng.canon_cache(cache)
        # Device decode state: resume at cur_pos = n_tokens with the
        # prefill-sampled first token (the P=1 merge is prewarmed).
        self._tokens_dev, self._cur_pos_dev = (
            eng.canon_vec(x) for x in eng._admit_merge(
                self._tokens_dev, self._cur_pos_dev,
                eng.canon_vec(jnp.asarray([first_token], jnp.int32)),
                jnp.asarray([n_tokens], jnp.int32),
                jnp.asarray([row], jnp.int32),
            )
        )
        r = _Row(
            req_id=req_id, gen=gen, out=[first_token], done_cb=done_cb,
            stream_cb=stream_cb, awaiting_first=False,
            t_submit=time.perf_counter(),
        )
        self.active[row] = r
        self._row_pos[row] = n_tokens
        eng.metrics.add_request(1)
        eng.metrics.add_tokens(1)
        if req_id:
            trace.record(req_id, "adopt", n_tokens=n_tokens, row=row)
        if len(r.out) >= gen.max_new_tokens:
            self._finish(row, r)
        else:
            self._flush_stream(r)
        return True

    def request_park(
        self, req_id: str, token_ids, replayed: int = 0,
    ) -> None:
        """Register session-park interest for a request (thread-safe):
        when its row finishes served, ``park_cb`` receives the full token
        sequence (``token_ids`` + the non-replayed outputs) and the row's
        exported KV blocks. Idempotent; a no-op without ``park_cb``."""
        with self._lock:
            self._park_ids[req_id] = (list(token_ids), int(replayed))

    def forget_park(self, req_id: str) -> None:
        """Withdraw park interest (submit/adopt failed after
        registration — the row will never reach ``_finish``)."""
        with self._lock:
            self._park_ids.pop(req_id, None)

    def _maybe_park(self, row: int, r: _Row, parked: tuple) -> None:
        """Export the finished row's KV for session parking
        (serve/kvstore.py). The device may still be running the in-flight
        group, which keeps advancing this row past its last sampled token
        — positions >= T-1 can be (re)written with garbage-continuation
        KV after this host-side finish. Only positions < T-1 are
        guaranteed stable, so the parked segment covers the first
        (T-1)//bs FULL blocks; and when the in-flight lag could ring-wrap
        into slot 0 (T-1 + group-lag past max_seq_len) parking is skipped
        outright — the low slots themselves would be hazardous. Parking
        is best-effort: any failure is a plain drop (the next turn
        re-prefills), never an error on the finished request."""
        ids, replayed = parked
        seq = list(ids) + [int(t) for t in r.out[replayed:]]
        T = len(seq)
        eng = self.engine
        bs = eng.block_size
        if T - 1 + self.group_chunks * self.chunk_steps > eng.max_seq_len:
            return
        nf = (T - 1) // bs
        if nf == 0:
            return
        try:
            if self._paged:
                blk = [int(b) for b in self._host_tables[row, :nf]]
                if any(b >= self._sentinel for b in blk):
                    return  # row shorter than its sequence claims
                blocks = export_blocks(self.cache, blk, nf * bs)
            else:
                blocks = export_dense_row(self.cache, row, nf * bs, bs)
            self.park_cb(r.req_id, seq[: nf * bs], blocks)
        except Exception:  # noqa: BLE001 — parking never fails a request
            pass

    def _finish(
        self, row: int, r: _Row, cancelled: bool = False,
        error: str | None = None,
    ) -> None:
        self.active.pop(row, None)
        self._row_pos.pop(row, None)
        self._inflight_prefill.pop(row, None)
        self._prefill_plen.pop(row, None)
        with self._lock:
            parked = self._park_ids.pop(r.req_id, None)
        if (
            parked is not None and self.park_cb is not None
            and error is None and not cancelled
        ):
            # Park BEFORE the release: the row's blocks must still be
            # this row's when the export reads them.
            self._maybe_park(row, r, parked)
        kv_block_s = self._paged_release_row(row)
        with self._lock:
            self._free.append(row)
        self._flush_stream(r)
        disposition = (
            "error" if error is not None
            else "cancelled" if cancelled else "served"
        )
        self.engine.metrics.add_finish(disposition)
        if r.req_id:
            trace.record(
                r.req_id, "finish", tokens=len(r.out),
                disposition=disposition,
                **(
                    {"kv_block_s": round(kv_block_s, 6)}
                    if kv_block_s else {}
                ),
            )
        if error is not None:
            # Keyword-only on the error path: existing 2-positional-arg
            # callbacks (tests, batch worker) never see it, and a callback
            # that doesn't accept it raising TypeError is the right
            # loud failure for a serving layer that can't report errors.
            r.done_cb(r.out, error=error)
        elif cancelled:
            r.done_cb(r.out, True)
        else:
            r.done_cb(r.out)

    @staticmethod
    def _flush_stream(r: _Row) -> None:
        if r.stream_cb is not None and len(r.out) > r.emitted:
            r.stream_cb(r.out[r.emitted:])
            r.emitted = len(r.out)

    def cancel(self, req_id: str) -> None:
        """Mark a request cancelled (thread-safe). The worker thread frees
        its row / drops it from the queue at the top of the next ``step()``
        — i.e. a cancelled request stops consuming decode steps within one
        step. Its ``done_cb`` fires with the tokens produced so far."""
        with self._lock:
            self._cancelled.add(req_id)

    def _process_cancellations(self) -> int:
        """Worker-thread half of ``cancel``: drop marked pending requests
        (their callbacks fire with ``cancelled=True`` so every submitted
        request gets exactly one response) and free marked active rows
        (admitted-but-unresolved rows are active too — their resolve
        notices the row changed hands and skips). Unmatched ids are
        discarded — the broker-side cancellation flag persists (TTL'd), so
        a cancel racing ahead of its request is re-delivered by the
        worker's ``check_cancelled`` once the request shows up."""
        with self._lock:
            if not self._cancelled:
                return 0
            ids, self._cancelled = self._cancelled, set()
            dropped = [p for p in self.pending if p[0] in ids]
            self.pending = deque(p for p in self.pending if p[0] not in ids)
        n = len(dropped)
        for item in dropped:
            item[3]([], True)
        for row, r in list(self.active.items()):
            if r.req_id in ids:
                self._finish(row, r, cancelled=True)
                n += 1
        if n:
            self.engine.metrics.add_cancelled(n)
        return n

    def live_ids(self) -> list[str]:
        """Every request id this batcher currently owns (pending or
        active, including admitted-but-unresolved rows) — what the worker
        polls cancellation flags for."""
        with self._lock:
            ids = [req_id for (req_id, *_r) in self.pending]
        ids += [r.req_id for r in self.active.values()]
        return ids

    def load_snapshot(self) -> dict:
        """Cheap load view for the fleet registry heartbeat: row/queue
        occupancy, KV-pool headroom, and the content hashes of the COW
        prefixes resident in the pool (the ``prefix_affinity`` routing
        signal). Host-side counters and host tables only — never touches
        a device array, so publishing it from a heartbeat thread can't
        force a device sync mid-decode."""
        from llmss_tpu.serve.protocol import prefix_hash

        with self._lock:
            pending = len(self.pending)
            free_slots = len(self._free)
        snap = {
            "rows": self.rows,
            "inflight_rows": self.rows - free_slots,
            "pending": pending,
            "free_slots": free_slots,
            "free_kv_blocks": None,
            "kv_blocks_total": None,
            "prefix_hashes": [],
        }
        if self._paged:
            snap["free_kv_blocks"] = self.allocator.free_blocks
            snap["kv_blocks_total"] = self.allocator.num_blocks
            snap["prefix_hashes"] = [
                prefix_hash(pfx.tokens)
                for pfx, _blocks in list(self._paged_prefixes.values())
            ]
        return snap

    def drain_all(self) -> list[str]:
        """Remove every pending and active request and return their ids —
        supervisor teardown: a restarting worker must error these out so no
        client waits forever on a request the new batcher never saw.

        Runs on the worker thread (the supervisor tears down from inside the
        crashed worker's loop), so touching ``self.active`` here doesn't race
        ``step()``; the queue and free-list stay lock-guarded.
        """
        with self._lock:
            ids = [req_id for (req_id, *_rest) in self.pending]
            self.pending.clear()
            self._park_ids.clear()
        self._inflight = None
        self._pending_adm = None
        self._last_fetch_t = None
        self._row_pos.clear()
        self._inflight_prefill.clear()
        self._prefill_plen.clear()
        for row in list(self.active):
            r = self.active.pop(row)
            ids.append(r.req_id)
            self._paged_release_row(row)
            with self._lock:
                self._free.append(row)
        return ids

    def drop_pending(self) -> list[str]:
        """Remove every PENDING (never-admitted) request and return its id
        WITHOUT firing callbacks — drain-deadline path: work the device
        never touched goes back to the broker queue for another worker
        (``release_requests``) instead of being answered with an error.
        Active rows are not touched; the caller aborts those separately."""
        with self._lock:
            ids = [req_id for (req_id, *_rest) in self.pending]
            self.pending.clear()
        return ids

    def _chunk_args(self):
        """Per-chunk host-side control arrays. ``done``/``eos``/sampling
        params come from the host's (one-chunk-lagged) view — a row that
        finished on device but not yet on host rides one extra chunk as a
        done row emitting discarded fills, the same cost an idle row pays.
        """
        done = np.ones(self.rows, bool)
        eos_arr = np.full(self.rows, -1, np.int32)
        gens = []
        for i in range(self.rows):
            r = self.active.get(i)
            gens.append(r.gen if r else GenerationParams())
            if r is not None:
                done[i] = False
                if r.gen.eos_token_id is not None:
                    eos_arr[i] = r.gen.eos_token_id
        sa = self.engine._sample_args(gens, self.rows)
        return done, eos_arr, sa

    def _process_group(self, group: _InFlightGroup) -> int:
        """Fetch a group's packed results (ONE device→host transfer,
        overlapped with the next group already running on device) and
        apply host bookkeeping chunk by chunk: per-row token accounting,
        stream flushes, EOS / max-token finishes — the same per-chunk
        granularity as the ungrouped path, so a row that finishes (or
        poisons) in chunk c never has chunk c+1's fill tokens read as
        output."""
        R, k, nc = self.rows, group.k, group.n_chunks
        with self.engine.metrics.host_fetch.time():
            flat = np.asarray(group.packed)  # the ONE blocking fetch
        self.engine.metrics.add_host_sync()
        for r in self.active.values():
            if r.req_id and not r.awaiting_first:
                # Throttled + sheddable (``group_`` prefix): per-group
                # cadence would otherwise dominate a long request's ring.
                trace.record(
                    r.req_id, "group_fetch", throttle_s=0.05,
                    chunks=group.n_chunks, k=group.k,
                )
        toks_np = flat[: nc * R * k].reshape(nc, R, k)
        poisoned_np = flat[nc * R * k:].reshape(nc, R).astype(bool)
        now = time.perf_counter()
        if self._last_fetch_t is not None and not group.has_admission:
            # Fetch-to-fetch interval — but only for groups with no
            # admission dispatched in between: the admission's prefill +
            # insert + merge execute on device between the two groups and
            # would inflate the per-token decode stat.
            self.engine.metrics.decode_step.record(
                (now - self._last_fetch_t) / (nc * k)
            )
        if self._last_fetch_t is not None and group.cost is not None:
            # Roofline fold: the same fetch-to-fetch interval against the
            # executable's derived cost. Unlike decode_step, admission
            # groups fold too (ragged groups ARE the admission path) —
            # the included prefill/insert work slightly under-reports
            # utilization for those samples, a documented caveat
            # (docs/observability.md).
            devtel.fold(group.kind, now - self._last_fetch_t, group.cost)
        self._last_fetch_t = now

        n = 0
        t_cb = time.perf_counter()
        firsts = group.prefill_firsts or {}
        for c in range(nc):
            for i in list(self.active):
                r = self.active[i]
                if r.awaiting_first:
                    first_c = firsts.get(i)
                    if first_c is None or c < first_c:
                        # Mid-prompt (or admitted after this group was
                        # dispatched): nothing to consume yet.
                        continue
                    # The chunk that completed this row's prompt — its
                    # sampled token is the request's FIRST token; admission
                    # bookkeeping happens here (chunked admissions never
                    # create an _InFlightAdmission). Poison first: a NaN
                    # anywhere in the prompt condemns the row before its
                    # garbage first token reads as a clean answer.
                    if poisoned_np[c, i]:
                        self.engine.metrics.add_poisoned(1)
                        self._finish(
                            i, r,
                            error="non-finite logits: row poisoned "
                                  "(NaN/inf in model output)",
                        )
                        continue
                    self._resolve_first(i, r, int(toks_np[c, i, 0]))
                    continue
                if poisoned_np[c, i]:
                    # Checked BEFORE token processing: the device
                    # EOS-filled the poisoned row from the bad step on
                    # (with -1 when the row has no eos), so its chunk
                    # tokens would otherwise read as a clean early finish.
                    # Error the row with the tokens produced before the
                    # poison; co-batched rows are untouched (row isolation
                    # is positional — a NaN never crosses rows). The flags
                    # are cumulative within the group, so the row errors at
                    # its FIRST poisoned chunk and leaves ``active``.
                    self.engine.metrics.add_poisoned(1)
                    self._finish(
                        i, r,
                        error="non-finite logits: row poisoned "
                              "(NaN/inf in model output)",
                    )
                    continue
                eos = (
                    r.gen.eos_token_id
                    if r.gen.eos_token_id is not None else -1
                )
                finished = False
                for col in range(k):
                    t = int(toks_np[c, i, col])
                    if t == eos:
                        finished = True
                        break
                    r.out.append(t)
                    n += 1
                    if len(r.out) >= r.gen.max_new_tokens + r.replayed:
                        finished = True
                        break
                if finished:
                    self._finish(i, r)
                else:
                    self._flush_stream(r)
        self.engine.metrics.add_tokens(n)
        self.engine.metrics.host_callback.record(time.perf_counter() - t_cb)
        return n

    def _plan_ragged(self, n_steps: int):
        """Host-side schedule for one ragged mixed group: every active row
        advances one token per step; rows with an in-flight prompt feed
        ``chunked_prefill``-token slices instead, sampling suppressed
        until the slice that completes the prompt (``emit`` flips on —
        that step's sample is the row's first token). A row whose prompt
        completes mid-group decodes normally for the remaining steps.
        Returns the xs arrays plus {row: step} first-token marks."""
        CB, R = self.chunked_prefill, self.rows
        ids = np.zeros((n_steps, R, CB), np.int32)
        qlens = np.ones((n_steps, R), np.int32)
        feed = np.zeros((n_steps, R), bool)
        emit = np.ones((n_steps, R), bool)
        firsts: dict[int, int] = {}
        fed = 0
        for s in range(n_steps):
            for row in list(self._inflight_prefill):
                rem = self._inflight_prefill[row]
                q = min(CB, len(rem))
                ids[s, row, :q] = rem[:q]
                del rem[:q]
                qlens[s, row] = q
                feed[s, row] = True
                emit[s, row] = not rem
                fed += q
                if not rem:
                    firsts[row] = s
                    del self._inflight_prefill[row]
        pre = int(feed.sum())
        self.engine.metrics.add_mixed_steps(
            steps=n_steps,
            decode_rows=n_steps * len(self.active) - pre,
            prefill_rows=pre, prefill_tokens=fed,
            budget_tokens=pre * CB,
        )
        return ids, qlens, feed, emit, firsts

    def step(self) -> int:
        """One scheduler iteration of the pipelined loop:

        1. dispatch decode group N+1 from the device-resident state — ONE
           jitted program covering ``group_chunks`` fused chunks while
           busy (a single chunk at low load) — the device never waits for
           the host;
        2. fetch + process group N's packed results, overlapped with group
           N+1 executing on device — this is where rows finish and free;
        3. resolve the admission dispatched last step (host bookkeeping —
           its merge already executed on device);
        4. dispatch admissions for the rows phase 2 just freed; their
           prefill + insert + merge land between group N+1 and N+2, so a
           finished row is back in service after exactly one idle group.

        Rows keep their exact solo tokens (row isolation is positional,
        and the device state never depends on host processing) — the
        pipeline only delays when the *host* learns them by one group.
        """
        self._process_cancellations()

        if not self.active:
            # Nothing running: drain the pipeline, then admit directly
            # (resolve immediately — nothing to overlap with; the merge
            # makes rows live for the next step's first group).
            if self._inflight is not None:
                group, self._inflight = self._inflight, None
                self._last_fetch_t = None
                n = self._process_group(group)
                n += self._resolve_admission(self._pending_adm)
                self._pending_adm = None
                return n
            if self._pending_adm is not None:
                adm, self._pending_adm = self._pending_adm, None
                return self._resolve_admission(adm)
            adm = self._admit_dispatch()
            if adm is None:
                return 0
            self._last_fetch_t = None
            return self._resolve_admission(adm)

        done, eos_arr, sa = self._chunk_args()
        busy = len(self.active) >= (3 * self.rows) // 4
        t0 = time.perf_counter()
        if self._chunked and self._inflight_prefill:
            # Mixed batch: in-flight prompts stream through the ragged
            # dispatch as chunk-budget query rows while decode rows
            # advance one token per step. No t_bucket — the ragged
            # executable's identity is keyed purely by the xs shapes, so
            # exactly TWO programs exist (the busy and low-load step
            # counts). The group never records decode_step (it is not a
            # clean decode-only sample — has_admission covers that).
            nc, k = (
                self.group_chunks * self.chunk_steps if busy
                else self.chunk_steps_low
            ), 1
            ids_seq, qlens_seq, feed_seq, emit_seq, firsts = (
                self._plan_ragged(nc)
            )
            packed, last_tok, cache, cur_pos, _ = self.engine._ragged_group(
                self.engine.params, self._tokens_dev, self.cache,
                self._cur_pos_dev, sa, jnp.asarray(done),
                jnp.asarray(eos_arr), jnp.asarray(ids_seq),
                jnp.asarray(qlens_seq), jnp.asarray(feed_seq),
                jnp.asarray(emit_seq),
            )
            adv = qlens_seq.sum(axis=0)
            for row in self._row_pos:
                self._row_pos[row] += int(adv[row])
            group = _InFlightGroup(
                packed=packed, n_chunks=nc, k=k, has_admission=True,
                prefill_firsts=firsts,
                kind="ragged_group",
                cost=self.engine.devtel_cost(
                    "ragged_group", (self.rows, nc, self.chunked_prefill),
                    batch=self.rows, steps=nc, kv_len=None,
                    prefill_tokens=nc * self.rows * self.chunked_prefill,
                ) if devtel.enabled() else None,
            )
        else:
            # Busy → the full group of full chunks (host off the critical
            # path); low load → one short chunk (admission/TTFT
            # granularity). Exactly these two (n_chunks, n_steps) combos
            # exist, so the executable envelope stays two programs per
            # cache-read bucket — same count as the ungrouped
            # two-chunk-size scheme.
            nc, k = (
                (self.group_chunks, self.chunk_steps) if busy
                else (1, self.chunk_steps_low)
            )
            t_bucket = self.engine.decode_bucket(
                max(self._row_pos.values(), default=0) + nc * k
            )
            packed, last_tok, cache, cur_pos, _ = self.engine._decode_group(
                self.engine.params, self._tokens_dev, self.cache,
                self._cur_pos_dev, sa, jnp.asarray(done),
                jnp.asarray(eos_arr),
                n_chunks=nc, n_steps=k, t_bucket=t_bucket,
            )
            for row in self._row_pos:
                self._row_pos[row] += nc * k
            # The admission dispatched LAST step sits between the previous
            # group and this one on the device queue, so this group's
            # fetch-to-fetch interval includes its prefill+insert+merge
            # time.
            group = _InFlightGroup(
                packed=packed, n_chunks=nc, k=k,
                has_admission=self._pending_adm is not None,
                cost=self.engine.devtel_cost(
                    "decode_group", (self.rows, nc, k, t_bucket),
                    batch=self.rows, steps=nc * k, kv_len=t_bucket,
                ) if devtel.enabled() else None,
            )
        self.cache = self.engine.canon_cache(cache)
        self._cur_pos_dev = self.engine.canon_vec(cur_pos)
        self._tokens_dev = self.engine.canon_vec(last_tok)
        try:
            packed.copy_to_host_async()
        except AttributeError:
            pass
        self.engine.metrics.host_dispatch.record(time.perf_counter() - t0)
        self.engine.metrics.add_group()
        for r in self.active.values():
            if r.req_id and not r.awaiting_first:
                trace.record(
                    r.req_id, "group_dispatch", throttle_s=0.05,
                    chunks=nc, k=k,
                )

        prev, self._inflight = self._inflight, group
        n = 0
        if prev is not None:
            n = self._process_group(prev)  # frees finished rows
        n += self._resolve_admission(self._pending_adm)
        # Preemption sits between resolve and admit: an evicted row's slot
        # and blocks feed THIS step's admission, so a blocked interactive
        # request is running one group after its eviction decision.
        self._maybe_preempt()
        # Admission takes the rows processing just freed; its device work
        # overlaps the in-flight group and lands before the next one.
        self._pending_adm = self._admit_dispatch()
        self._step_count += 1
        if devtel.enabled():
            self._devtel_sample()
        return n

    def _devtel_sample(self) -> None:
        """Devtel sampling at a group boundary: counter tracks (throttled
        to 0.05 s — the group_dispatch trace cadence) and the compile
        observer's ``_cache_size`` sweep (throttled to 0.5 s inside the
        observer). Host counters and host tables only — never a device
        sync (``memory_stats`` reads runtime-owned host counters)."""
        now = time.monotonic()
        rid = next(
            (r.req_id for r in self.active.values() if r.req_id), None,
        )
        devtel.observer().maybe_sample(rid)
        if now - self._devtel_last_t < 0.05:
            return
        self._devtel_last_t = now
        with self._lock:
            pending = len(self.pending)
            free_slots = len(self._free)
        prefill_rows = len(self._inflight_prefill)
        tracks = {
            "rows": {
                "decode": len(self.active) - prefill_rows,
                "prefill": prefill_rows,
                "free": free_slots,
            },
            "queue_depth": {"pending": pending},
        }
        if self._paged:
            alloc = self.allocator
            free = alloc.free_blocks
            tracks["kv_blocks"] = {
                "in_use": alloc.num_blocks - free, "free": free,
            }
            tracks["kv_fragmentation"] = {
                "largest_free_run": alloc.largest_free_run(), "free": free,
            }
        util = devtel.last_util()
        if util:
            # The roofline gauges ride the counter tracks too, so the
            # Perfetto timeline shows achieved MFU/MBU next to the spans.
            tracks["mfu"] = {k: g["mfu"] for k, g in util.items()}
            tracks["mbu"] = {k: g["mbu"] for k, g in util.items()}
        mem = devtel.device_memory_stats()
        if mem is not None:
            tracks["device_memory"] = mem
        devtel.record_counters(tracks, t=now)

    @property
    def idle(self) -> bool:
        with self._lock:
            return (
                not self.active and not self.pending
                and self._inflight is None and self._pending_adm is None
            )

    def run_until_idle(self) -> None:
        while not self.idle:
            self.step()

    def run_forever(self, stop: threading.Event, poll_s: float = 0.005):
        while not stop.is_set():
            if self.idle:
                time.sleep(poll_s)
                continue
            self.step()
