"""DecodeEngine: jitted prefill + decode steps and generation loops.

Replaces the reference's three decode loops (``generate.py:99-190`` cache and
no-cache paths, ``consumer_server.py:123-166``). Differences by design:

- **On-device sampling inside the jitted step**: the per-token chain
  logits→host→rank-0 sample→NCCL broadcast (``generate.py:109-144``) becomes
  a fused argmax/top-k/top-p/categorical on device; the host only reads the
  emitted token (streaming mode) or nothing at all (fused mode).
- **Two generation modes**: ``generate`` — a host-side loop around the jitted
  decode step (streaming, early-exit on EOS); ``generate_fused`` — the whole
  token loop as ``lax.scan`` inside one jit (zero host round-trips, the
  throughput path).
- **Static shapes with prompt bucketing**: prompts right-pad to a bucket
  length (compile-once-per-bucket), pads masked out of attention — fixing the
  reference's unmasked left-pad quirk (SURVEY.md §2.11.3).
- **Sliding-window overflow** (`generate.py:132-142`) is ring-buffer slot
  arithmetic (``slot = position % max_len``), not host-side trimming.
- **Donated cache buffers**: each step consumes and re-emits the cache with
  no reallocation (the reference re-allocates and calls
  ``torch.cuda.empty_cache()``, ``generate.py:187``).
"""

from __future__ import annotations

import dataclasses
import time
from functools import partial
from typing import TYPE_CHECKING, NamedTuple

import jax
import jax.numpy as jnp
import numpy as np

from llmss_tpu.engine.cache import (
    KVCache, PagedKVCache, init_cache, init_paged_cache,
    paged_write_stacked,
)
from llmss_tpu.models.common import DecoderConfig
from llmss_tpu.ops.sampling import sample
from llmss_tpu.utils import devtel

if TYPE_CHECKING:  # a runtime import would be circular when the models
    # package is imported first (models.decoder -> engine.cache runs
    # engine/__init__ -> engine.engine -> models.decoder).
    from llmss_tpu.models.decoder import Params  # noqa: F401


class Prefix(NamedTuple):
    """A retained, device-resident KV segment for a shared prompt prefix
    (system prompt / earlier turns of a session). Built once with
    ``DecodeEngine.build_prefix``; admissions that start with these tokens
    seed their cache rows from it and prefill only the suffix — the
    prefix's prefill FLOPs and TTFT are paid once per prefix, not per
    request. Token-exact vs from-scratch on bf16 caches (absolute
    positions/counters); on int8 caches the stored bits are stable but
    reads pass through quantization, so exactness is not guaranteed. The
    reference has no analogue (it re-prefills every request from scratch,
    ``generate.py:99``)."""

    tokens: tuple[int, ...]  # the prefix token ids (host, for matching)
    k: jax.Array  # [L, P, Hkv, D] (or int8 when the engine is int8)
    v: jax.Array
    k_scale: jax.Array | None  # [L, P, Hkv] f32 iff int8
    v_scale: jax.Array | None

    @property
    def length(self) -> int:
        return len(self.tokens)


@dataclasses.dataclass
class GenerationParams:
    """Per-call generation controls (≙ reference CLI flags,
    ``generate.py:21-32``; correctness fixes per SURVEY.md §2.11.1)."""

    max_new_tokens: int = 20
    is_greedy: bool = True
    temperature: float = 1.0
    top_k: int = 0
    top_p: float = 1.0
    eos_token_id: int | None = None
    seed: int = 0

    def validate(self) -> None:
        # Range checks, parity with generate.py:37-40 — but raising, not
        # asserting: the engine path must reject bad params under
        # ``python -O`` too, same as the protocol path.
        if not self.is_greedy:
            if not self.temperature > 0.0:
                raise ValueError("temperature must be > 0")
            if not self.top_k >= 0:
                raise ValueError("top_k must be >= 0")
            if not 0.0 < self.top_p <= 1.0:
                raise ValueError("top_p must be in (0, 1]")
        if not self.max_new_tokens > 0:
            raise ValueError("max_new_tokens must be > 0")


def _bucket(n: int, cap: int) -> int:
    b = 16
    while b < n:
        b *= 2
    return min(b, cap)


class DecodeEngine:
    """Drives one model on one mesh with a fixed (batch, max_seq) envelope."""

    def __init__(
        self,
        cfg: DecoderConfig,
        params: Params,
        mesh,
        *,
        batch_size: int = 1,
        max_seq_len: int | None = None,
        kv_dtype: str | None = None,
        kv_layout: str = "dense",
        block_size: int = 16,
        kv_blocks: int | None = None,
    ):
        from llmss_tpu.utils.metrics import EngineMetrics

        self.cfg = cfg
        self.params = params
        self.mesh = mesh
        self.batch_size = batch_size
        self.max_seq_len = max_seq_len or cfg.max_position_embeddings
        # kv_layout="paged": rows address KV through per-row block tables
        # into a global block pool instead of owning a dense [T] ring —
        # same logical-slot contract, so every generate/serve path works
        # unchanged (models/decoder.py:_forward_paged, docs/paged-kv.md).
        # ``kv_blocks`` sizes the scheduler's shared pool (None = the
        # dense-equivalent batch*max_len/block_size); the engine's own
        # generate paths always use identity tables over a full pool.
        if kv_layout not in ("dense", "paged"):
            raise ValueError(
                f"kv_layout must be 'dense' or 'paged', got {kv_layout!r}"
            )
        self.kv_layout = kv_layout
        self.block_size = block_size
        self.kv_blocks = kv_blocks
        if kv_layout == "paged":
            from llmss_tpu.parallel.mesh import AXIS_SP

            if self.max_seq_len % block_size:
                raise ValueError(
                    f"kv_layout='paged' needs max_seq_len "
                    f"({self.max_seq_len}) divisible by block_size "
                    f"({block_size})"
                )
            if mesh is not None and AXIS_SP in mesh.shape and (
                mesh.shape[AXIS_SP] > 1
            ):
                raise ValueError(
                    "kv_layout='paged' does not support sp > 1 meshes "
                    "(the sequence axis is block-indirected per row)"
                )
        if (
            cfg.rope_original_max_positions is not None
            and cfg.rope_freq_factors_short is not None
        ):
            # LongRoPE: the rotary basis follows the context this engine
            # actually serves (models/phi3.py documents the contract) —
            # a 4k-context engine on a 128k checkpoint runs the short
            # factors, exactly as HF does for forwards within 4k.
            import dataclasses as _dc

            chosen = (
                cfg.rope_freq_factors_long
                if self.max_seq_len > cfg.rope_original_max_positions
                else cfg.rope_freq_factors_short
            )
            cfg = self.cfg = _dc.replace(cfg, rope_freq_factors=chosen)
        # kv_dtype="int8" stores the cache quantized (per-token-per-head
        # scales): half the HBM footprint → double the rows/context per
        # chip. On sp=1 meshes the dequant scales fold into the attention
        # contractions (no dequantized copy materializes,
        # ops/attention.py); sp>1 meshes pre-dequantize each layer before
        # the shard_map'd sequence-parallel attention (models/decoder.py).
        if kv_dtype == "int8":
            self._cache_dtype = jnp.int8
        else:
            self._cache_dtype = cfg.compute_dtype
        self.metrics = EngineMetrics()
        self._ladder = self.bucket_ladder()
        self._canon_cache_memo: dict[tuple, KVCache | PagedKVCache] = {}
        self._devtel_model: devtel.EngineCostModel | None = None

        # mesh is partial-bound (a compile-time constant, not a traced arg):
        # it enables the shard_map'd Pallas attention path inside forward.
        self._prefill = jax.jit(
            partial(self._prefill_impl, cfg, mesh), donate_argnums=(2,),
        )
        self._decode = jax.jit(
            partial(self._decode_impl, cfg, mesh), donate_argnums=(2,),
            static_argnames=("t_bucket",),
        )
        self._decode_many = jax.jit(
            partial(self._decode_many_impl, cfg, mesh),
            donate_argnums=(2,),
            static_argnames=("n_steps", "t_bucket"),
        )
        # Grouped decode: n_chunks fused chunks in ONE program with ONE
        # packed device→host fetch for the whole group. Donates the token
        # and position carries as well as the cache — XLA reuses their
        # storage across every step of the group.
        self._decode_group = jax.jit(
            partial(self._decode_group_impl, cfg, mesh),
            donate_argnums=(1, 2, 3),
            static_argnames=("n_chunks", "n_steps", "t_bucket"),
        )
        # Ragged mixed prefill+decode group (chunked prefill): each scan
        # step advances decode rows by one token AND streams chunk-budget
        # slices of in-flight prompts through the same dispatch
        # (forward_ragged). Executable identity is keyed purely by the xs
        # shapes [n_chunks, B(, CB)] — no static args, no bucket ladder.
        self._ragged_group = jax.jit(
            partial(self._ragged_group_impl, cfg, mesh),
            donate_argnums=(1, 2, 3),
        )
        self._admit_merge = jax.jit(
            self._admit_merge_impl, donate_argnums=(0, 1)
        )
        self._seed = jax.jit(self._seed_impl, donate_argnums=(0,))

    # -- jitted bodies ------------------------------------------------------

    @staticmethod
    def _prefill_impl(
        cfg, mesh, params, ids, cache, prompt_lens, sample_args, start=None,
    ):
        """Prefill ``ids`` into the cache. ``start`` ([B] int32, optional)
        offsets every row's positions — the prefix-reuse path prefills only
        a request's *suffix* at positions ``[start, start + len)`` against
        a cache whose first ``start`` slots were seeded from a retained
        ``Prefix``; ``prompt_lens`` is then the suffix length. The default
        (start absent) is the ordinary from-zero prefill."""
        from llmss_tpu.models.decoder import forward

        B, S = ids.shape
        rel = jnp.broadcast_to(jnp.arange(S, dtype=jnp.int32), (B, S))
        off = jnp.zeros((B,), jnp.int32) if start is None else start
        positions = off[:, None] + rel
        valid = rel < prompt_lens[:, None]
        slots = positions % cache.max_len
        kv_pos = jnp.where(valid, positions, -1)
        logits, cache = forward(
            cfg, params, ids, positions, cache, slots,
            gather_idx=prompt_lens - 1, kv_write_positions=kv_pos, mesh=mesh,
        )
        # The sampled token sits at absolute position start + prompt_len —
        # that position is the per-row draw counter (ops/sampling.py:
        # stateless per-request randomness), so a prefix-reused request
        # draws exactly the tokens it would draw prefilled from scratch.
        tok = sample(logits[:, 0], counters=off + prompt_lens, **sample_args)
        return tok, logits[:, 0], cache

    @staticmethod
    def _seed_impl(cache, pk, pv, pks, pvs, plen):
        """Write a retained prefix segment into logical slots [0, Pb) of
        EVERY row of a (fresh) cache. The segment is BUCKET-padded
        (``build_prefix`` keeps the prefill bucket's shape): only slots
        below ``plen`` (traced, [] int32) record real positions — pad
        slots stay -1 so attention never sees them, and this one jit
        serves every prefix length in a bucket instead of compiling a
        bespoke scatter per length. Rows that go on to serve non-prefix
        work are simply overwritten by their own prefill; dummy admission
        rows ignore it entirely."""
        Pb = pk.shape[1]
        rel = jnp.arange(Pb, dtype=jnp.int32)
        pos_row = jnp.where(rel < plen, rel, -1)
        pos = cache.positions.at[:, :Pb].set(pos_row[None, :])
        if isinstance(cache, PagedKVCache):
            B = cache.block_tables.shape[0]
            slots = jnp.broadcast_to(rel, (B, Pb))

            def scatter(pool, seg):
                if pool is None:
                    return None
                new = jnp.broadcast_to(
                    seg[:, None], (seg.shape[0], B) + seg.shape[1:]
                )
                # Sentinel table entries drop the write — the scheduler
                # seeds through COW-masked tables whose SHARED prefix
                # blocks are sentineled out (docs/paged-kv.md).
                return paged_write_stacked(
                    pool, new, cache.block_tables, slots, cache.block_size
                )

            return PagedKVCache(
                k=scatter(cache.k, pk), v=scatter(cache.v, pv),
                block_tables=cache.block_tables, positions=pos,
                k_scale=scatter(cache.k_scale, pks),
                v_scale=scatter(cache.v_scale, pvs),
            )
        return KVCache(
            k=cache.k.at[:, :, :Pb].set(pk[:, None]),
            v=cache.v.at[:, :, :Pb].set(pv[:, None]),
            positions=pos,
            k_scale=(
                cache.k_scale.at[:, :, :Pb].set(pks[:, None])
                if pks is not None else None
            ),
            v_scale=(
                cache.v_scale.at[:, :, :Pb].set(pvs[:, None])
                if pvs is not None else None
            ),
        )

    def seed_cache(self, cache, prefix: Prefix):
        """Seed a fresh cache's rows with ``prefix`` (jitted, donating)."""
        return self._seed(
            cache, prefix.k, prefix.v, prefix.k_scale, prefix.v_scale,
            jnp.asarray(prefix.length, jnp.int32),
        )

    def build_prefix(self, token_ids: list[int]) -> Prefix:
        """Prefill ``token_ids`` once and retain the resulting KV segment
        for reuse by later requests that start with these tokens (shared
        system prompt, earlier turns of a session). int8 engines store the
        prefix quantized — the seeded bits are identical on every reuse
        (storage bit-stability, models/decoder.py).

        The retained segment keeps the prefill BUCKET's padded length
        (pad slots carry no positions): construction rides the exact
        executables ``prewarm(prefix_prefill=True)`` already compiled and
        the seed scatter compiles once per bucket, not once per prefix
        length — this removed a ~28 s one-time bespoke-shape compile per
        distinct prefix length (PREFIX_BENCH.json)."""
        P = len(token_ids)
        if not 0 < P < self.max_seq_len:
            raise ValueError(
                f"prefix length {P} must be in (0, {self.max_seq_len})"
            )
        cache = self.new_cache(1)
        ids, lens = self._pad_prompts([list(token_ids)])
        Pb = ids.shape[1]
        sa = self._sample_args(GenerationParams(), 1)
        _, _, cache = self._prefill(
            self.params, jnp.asarray(ids), cache, jnp.asarray(lens), sa,
        )
        if isinstance(cache, PagedKVCache):
            # Row 0 of a fresh engine cache has the identity table: logical
            # slot s lives at pool[block s // bs, s % bs] — unfold the
            # first ceil(Pb/bs) blocks back into a dense [L, Pb] segment
            # (the Prefix stays layout-neutral; seeding re-scatters it
            # through whatever tables the target cache carries).
            bs = cache.block_size
            nb = -(-Pb // bs)

            def seg(pool):
                if pool is None:
                    return None
                v = pool[:, :nb]
                return v.reshape(
                    (v.shape[0], nb * bs) + v.shape[3:]
                )[:, :Pb]

            return Prefix(
                tokens=tuple(int(t) for t in token_ids),
                k=seg(cache.k), v=seg(cache.v),
                k_scale=seg(cache.k_scale), v_scale=seg(cache.v_scale),
            )
        return Prefix(
            tokens=tuple(int(t) for t in token_ids),
            k=cache.k[:, 0, :Pb],
            v=cache.v[:, 0, :Pb],
            k_scale=(
                cache.k_scale[:, 0, :Pb] if cache.k_scale is not None
                else None
            ),
            v_scale=(
                cache.v_scale[:, 0, :Pb] if cache.v_scale is not None
                else None
            ),
        )

    @staticmethod
    def split_prefix(
        prompts: list[list[int]], prefix: Prefix
    ) -> tuple[np.ndarray, list[list[int]]]:
        """Validate every prompt extends ``prefix`` and return (full
        lengths, suffixes). A prompt must be strictly longer than the
        prefix — the suffix prefill needs at least one token to produce
        the first logits."""
        P = prefix.length
        suffixes = []
        for p in prompts:
            if len(p) <= P or tuple(p[:P]) != prefix.tokens:
                raise ValueError(
                    "prompt does not extend the prefix (needs the prefix's "
                    f"{P} tokens plus at least one more)"
                )
            suffixes.append(list(p[P:]))
        lens = np.asarray([len(p) for p in prompts], np.int32)
        return lens, suffixes

    @staticmethod
    def _decode_impl(
        cfg, mesh, params, tokens, cache, cur_pos, sample_args,
        *, t_bucket: int | None = None,
    ):
        from llmss_tpu.models.decoder import forward

        # tokens [B], cur_pos [B] — position at which each token sits.
        positions = cur_pos[:, None]
        slots = positions % cache.max_len
        logits, cache = forward(
            cfg, params, tokens[:, None], positions, cache, slots,
            last_only=True, mesh=mesh, t_bucket=t_bucket,
        )
        tok = sample(logits[:, 0], counters=cur_pos + 1, **sample_args)
        return tok, logits[:, 0], cache

    @staticmethod
    def _admit_merge_impl(tokens, cur_pos, adm_tok, adm_lens, rows):
        """Merge an admission batch into the device-resident decode state:
        ``tokens[rows] = adm_tok`` (each row's prefill-sampled first token)
        and ``cur_pos[rows] = adm_lens``. ``rows`` is [P] int32 padded with
        a positive out-of-range sentinel (mode="drop"; negative would wrap
        — the r3 admission-sentinel bug). This is what lets the scheduler
        pipeline decode chunks without fetching tokens to the host: the
        next chunk reads the merged state directly (scheduler.py)."""
        return (
            tokens.at[rows].set(adm_tok, mode="drop"),
            cur_pos.at[rows].set(adm_lens, mode="drop"),
        )

    @staticmethod
    def _decode_many_impl(
        cfg, mesh, params, tokens, cache, cur_pos, sample_args, done,
        eos, *, n_steps: int, t_bucket: int | None = None,
    ):
        """Fused multi-token decode: lax.scan over the single-token step.

        Returns a 5th array, ``poisoned`` [B] bool: rows whose logits went
        non-finite at any step of this chunk (ops/sampling.nonfinite_rows).
        A poisoned row is forced done on device — its later "tokens" are
        EOS fills — and the host errors out exactly that row; co-batched
        rows never see it (row isolation is positional)."""
        from llmss_tpu.parallel.sharding import ys_pin

        body = partial(
            DecodeEngine._decode_step_body, cfg, mesh, params, sample_args,
            eos, t_bucket,
        )
        poisoned0 = jnp.zeros_like(done)
        carry, toks = jax.lax.scan(
            body, (tokens, cache, cur_pos, done, poisoned0), None,
            length=n_steps,
        )
        tokens, cache, cur_pos, done, poisoned = carry
        # The host reads the stacked tokens: pin them replicated, same
        # GSPMD partial-sum hazard as _decode_group_impl (found by
        # shardcheck — this path predates the grouped fix and leaked the
        # same unpinned ys to np.asarray in generate_fused/speculative).
        pin = ys_pin(mesh)
        return pin(toks.T), cache, cur_pos, done, poisoned  # [B, n_steps]

    @staticmethod
    def _decode_step_body(cfg, mesh, params, sample_args, eos, t_bucket,
                          carry, _x=None):
        """One fused decode step — the scanned body shared by
        ``_decode_many`` and the grouped ``_decode_group`` (the two paths
        are bit-identical by construction because this IS the same
        traced program)."""
        from llmss_tpu.models.decoder import forward
        from llmss_tpu.ops.sampling import fold_step_outcome

        tokens, cache, cur_pos, done, poisoned = carry
        positions = cur_pos[:, None]
        # Done rows stop WRITING KV: their slot goes positive-OOB, and
        # every write site drops OOB indices. A dense done-row write
        # was merely wasted bandwidth (the row owns its ring); under
        # the paged layout a freed row's STALE device block table may
        # point at blocks the allocator already handed to another row
        # — or at shared prefix blocks, once its position wraps — so
        # the write must not land at all (docs/paged-kv.md).
        slots = jnp.where(
            done[:, None], cache.max_len, positions % cache.max_len
        )
        logits, cache = forward(
            cfg, params, tokens[:, None], positions, cache, slots,
            last_only=True, mesh=mesh, t_bucket=t_bucket,
        )
        tok = sample(logits[:, 0], counters=cur_pos + 1, **sample_args)
        tok, done, poisoned = fold_step_outcome(
            logits[:, 0], tok, done, poisoned, eos
        )
        cur_pos = cur_pos + 1
        return (tok, cache, cur_pos, done, poisoned), tok

    @staticmethod
    def _decode_group_impl(
        cfg, mesh, params, tokens, cache, cur_pos, sample_args, done,
        eos, *, n_chunks: int, n_steps: int, t_bucket: int | None = None,
    ):
        """A GROUP of ``n_chunks`` fused decode chunks as one program: an
        outer ``lax.scan`` over the ``_decode_many`` chunk scan, with EOS/
        done and poison folded into the on-device carry so no host decision
        is needed between chunks. The host gets everything in ONE packed
        int32 transfer — ``n_chunks·B·n_steps`` tokens followed by
        ``n_chunks·B`` per-chunk poisoned flags (cumulative within the
        group, snapshotted after each chunk so the host can error a
        poisoned row at the same chunk granularity as the ungrouped
        path) — instead of one tokens + one poisoned fetch per chunk.

        Returns ``(packed [n_chunks·B·(n_steps+1)] int32, last_tok [B],
        cache, cur_pos, done)``; the carried token/position/cache outputs
        feed the next group's dispatch directly (device-resident state,
        donated in)."""
        body = partial(
            DecodeEngine._decode_step_body, cfg, mesh, params, sample_args,
            eos, t_bucket,
        )
        # The stacked ys MUST be pinned to a replicated sharding here:
        # GSPMD otherwise propagates an unreduced partial-sum layout from
        # the tp-sharded logits into the outer scan's stacked output, and
        # the host reads token values summed over the tp axis (observed:
        # every packed token exactly tp× its true value). The carry never
        # hits this — its sharding is pinned by the next iteration's
        # consumers — only the ys leave the loop unconstrained
        # (parallel/sharding.ys_pin documents the hazard; shardcheck's
        # partial-sum-leak rule gates it).
        from llmss_tpu.parallel.sharding import ys_pin

        pin = ys_pin(mesh)

        def chunk(carry, _):
            carry, toks = jax.lax.scan(body, carry, None, length=n_steps)
            # Snapshot per-chunk: toks [n_steps, B] → [B, n_steps]; the
            # poison flags as of this chunk's end.
            return carry, (pin(toks.T), pin(carry[4]))

        poisoned0 = jnp.zeros_like(done)
        carry, (toks, pois) = jax.lax.scan(
            chunk, (tokens, cache, cur_pos, done, poisoned0), None,
            length=n_chunks,
        )
        tokens, cache, cur_pos, done, _ = carry
        packed = jnp.concatenate(
            [toks.reshape(-1), pois.astype(jnp.int32).reshape(-1)]
        )
        return packed, tokens, cache, cur_pos, done

    @staticmethod
    def _ragged_step_body(cfg, mesh, params, sample_args, eos, carry, xs):
        """One ragged mixed prefill+decode step (chunked prefill,
        ISSUE 10): every row carries a CB-token query chunk of which
        ``q_lens[b]`` are live. Decode rows run with ``q_len == 1``,
        ``feed == False`` (the carried token is the input) and ``emit ==
        True`` — for them the positions/slots/counters arithmetic below
        reduces exactly to ``_decode_step_body``'s, so their token streams
        match the split decode path. Mid-prefill rows feed prompt slices
        (``feed == True``) and suppress sampling until the chunk that
        completes the prompt (``emit`` flips on): the token sampled there
        — at counter ``cur_pos + q_len`` = prompt length, the prefill
        counter — is the row's first token, exactly what the dedicated
        prefill program would have produced."""
        from llmss_tpu.models.decoder import forward_ragged
        from llmss_tpu.ops.sampling import fold_step_outcome

        tokens, cache, cur_pos, done, poisoned = carry
        ids, q_lens, feed, emit = xs
        CB = ids.shape[1]
        # Decode rows consume the device-resident carry token; prefill
        # rows consume the host-fed prompt slice.
        ids = ids.at[:, 0].set(jnp.where(feed, ids[:, 0], tokens))
        rel = jnp.arange(CB, dtype=jnp.int32)
        positions = cur_pos[:, None] + rel[None, :]
        valid = rel[None, :] < q_lens[:, None]
        live = valid & ~done[:, None]
        # Dead columns (chunk padding / done rows) write nowhere: slot
        # goes positive-OOB and position -1 — same containment as the
        # decode step's done-row handling (docs/paged-kv.md).
        slots = jnp.where(live, positions % cache.max_len, cache.max_len)
        kv_pos = jnp.where(live, positions, -1)
        logits, cache = forward_ragged(
            cfg, params, ids, positions, cache, slots, q_lens,
            kv_write_positions=kv_pos, mesh=mesh,
        )
        tok = sample(logits[:, 0], counters=cur_pos + q_lens, **sample_args)
        tok, done2, poisoned = fold_step_outcome(
            logits[:, 0], tok, done, poisoned, eos
        )
        # Mid-prefill rows emit nothing this step: keep the carried token
        # and done state (a garbage mid-prompt sample must not EOS the
        # row). Poison is cumulative regardless — non-finite logits in
        # any chunk condemn the row.
        tok = jnp.where(emit, tok, tokens)
        done = jnp.where(emit, done2, done)
        cur_pos = cur_pos + q_lens
        return (tok, cache, cur_pos, done, poisoned), tok

    @staticmethod
    def _ragged_group_impl(
        cfg, mesh, params, tokens, cache, cur_pos, sample_args, done,
        eos, ids_seq, qlens_seq, feed_seq, emit_seq,
    ):
        """A GROUP of ragged mixed steps as one program — the chunked-
        prefill twin of ``_decode_group_impl``. ``ids_seq`` [nc, B, CB],
        ``qlens_seq``/``feed_seq``/``emit_seq`` [nc, B] are host-planned
        per-step chunk schedules (which rows feed prompt slices, which
        decode). One packed int32 transfer returns ``nc·B`` tokens then
        ``nc·B`` cumulative poison snapshots — same layout as the decode
        group at ``n_steps == 1``, so the scheduler's group processing is
        shared. Returns ``(packed, last_tok, cache, cur_pos, done)``."""
        body = partial(
            DecodeEngine._ragged_step_body, cfg, mesh, params, sample_args,
            eos,
        )
        # Pin the stacked ys replicated — same GSPMD partial-sum hazard
        # as _decode_group_impl (parallel/sharding.ys_pin).
        from llmss_tpu.parallel.sharding import ys_pin

        pin = ys_pin(mesh)

        def step(carry, xs):
            carry, tok = body(carry, xs)
            return carry, (pin(tok), pin(carry[4]))

        poisoned0 = jnp.zeros_like(done)
        carry, (toks, pois) = jax.lax.scan(
            step, (tokens, cache, cur_pos, done, poisoned0),
            (ids_seq, qlens_seq, feed_seq, emit_seq),
        )
        tokens, cache, cur_pos, done, _ = carry
        packed = jnp.concatenate(
            [toks.reshape(-1), pois.astype(jnp.int32).reshape(-1)]
        )
        return packed, tokens, cache, cur_pos, done

    # -- host API -----------------------------------------------------------

    def timed_prefill(self, prefill_fn, *args, batch: int):
        """Run a jitted prefill, recording prefill latency, TTFT, and the
        request count (one definition for all prefill sites: generate,
        generate_fused, and the continuous batcher's row admission)."""
        t0 = time.perf_counter()
        with self.metrics.prefill.time():
            out = prefill_fn(*args)
            out[0].block_until_ready()
        self.metrics.ttft.record(time.perf_counter() - t0)
        self.metrics.add_request(batch)
        return out

    def seq_buckets(self) -> list[int]:
        """Every prompt bucket _pad_prompts can produce for this engine."""
        out, b = [], 16
        while b < self.max_seq_len:
            out.append(b)
            b *= 2
        out.append(self.max_seq_len)
        return out

    def bucket_ladder(self) -> list[int]:
        """The static cache-read buckets decode executables compile for:
        multiples of ``max(32, max_seq_len/16)`` below max_seq_len — at
        most 15 entries, so the executable set stays bounded while the
        average over-read is ~one granule. ``LLMSS_BUCKETS=0`` disables
        bucketing (every decode reads the full ring)."""
        import os

        if os.environ.get("LLMSS_BUCKETS") == "0":
            return []
        g = max(32, -(-self.max_seq_len // (16 * 32)) * 32)  # round UP
        return list(range(g, self.max_seq_len, g))

    def _bucketable(self) -> bool:
        """Whether this engine's decode path can bucket cache reads at
        all: sp>1 meshes and the Pallas decode override read the full
        cache by construction. IMPL_OVERRIDE is re-read each call (tests
        monkeypatch it) — the mesh check is the cheap early-out."""
        import importlib

        from llmss_tpu.parallel.mesh import AXIS_SP

        if self.mesh is not None and AXIS_SP in self.mesh.shape and (
            self.mesh.shape[AXIS_SP] > 1
        ):
            return False
        _att = importlib.import_module("llmss_tpu.ops.attention")
        return _att.IMPL_OVERRIDE != "pallas"

    def decode_bucket(self, pos_bound: int) -> int | None:
        """Pick the cache-read bucket for a decode call whose rows' ring
        positions (current + steps in the call) are all < ``pos_bound``.
        Returns None — read the full ring — when no ladder entry covers it,
        when any row may have wrapped (pos_bound > max_seq_len), or on the
        sp>1 / Pallas-kernel decode paths (which read the full cache by
        construction)."""
        if not self._ladder or pos_bound > self.max_seq_len:
            return None  # wrapped rows: full-ring semantics
        if not self._bucketable():
            return None
        for b in self._ladder:
            if b >= pos_bound:
                return b
        return None

    def prewarm_bucket_set(self) -> "list[int | None]":
        """Every ``t_bucket`` value the live decode path can pick — what a
        prewarm must compile. Skips the ladder when this engine's decode
        path can't bucket at all (sp>1 mesh / pallas override): every
        ladder value would compile a byte-identical full-ring program."""
        out: list[int | None] = [None]
        if self.decode_bucket(1) is not None:
            out += self._ladder
        return out

    def check_capacity(self, n_prompt_tokens: int, max_new_tokens: int):
        """Reject a request that cannot fit the ring: it would advance past
        max_seq_len mid-generation, wrap, and silently slide its own early
        context out of the window. The ONE capacity rule shared by every
        serving path (batch Worker and continuous batcher)."""
        if n_prompt_tokens + max_new_tokens > self.max_seq_len:
            raise ValueError(
                f"prompt ({n_prompt_tokens} tokens) + max_new_tokens "
                f"({max_new_tokens}) exceeds the engine's max_seq_len "
                f"({self.max_seq_len})"
            )

    def devtel_cost_model(self) -> devtel.EngineCostModel:
        """Lazy analytical roofline model for this engine's config — the
        fallback cost source when the backend's cost_analysis is empty
        and the lazy source for signatures first seen mid-serve."""
        if self._devtel_model is None:
            count, nbytes = devtel.param_stats(self.params)
            self._devtel_model = devtel.EngineCostModel(
                self.cfg, count, nbytes,
                kv_itemsize=jnp.dtype(self._cache_dtype).itemsize,
                max_seq_len=self.max_seq_len,
            )
        return self._devtel_model

    def devtel_cost(
        self, kind: str, key: tuple, *, batch: int, steps: int,
        kv_len: int | None, prefill_tokens: int = 0, lower_thunk=None,
    ) -> devtel.KernelCost | None:
        """Cost for one executable signature via the process cost table:
        cache hit (the per-dispatch path — one dict get), else
        ``lower_thunk().cost_analysis()`` (prewarm passes the thunk), else
        the analytical model. ``key`` must be identical between the
        prewarm derivation and the fold-site lookup."""
        from llmss_tpu.utils.signatures import signature

        full_key = signature(kind, *key)
        hit = devtel.costs().get(full_key)
        if hit is not None:
            # The per-dispatch path: never price the analytical model on
            # a hit — step_cost alone busts the 2 us/group budget
            # (DEVTEL_BENCH.json).
            return hit
        m = self.devtel_cost_model()
        return devtel.costs().derive(
            full_key, lower_thunk,
            fallback=m.step_cost(batch, steps, kv_len, prefill_tokens),
        )

    def prewarm(
        self, batch: int, *, chunk_steps: tuple[int, ...] | int = (),
        buckets: bool = True, prefix_prefill: bool = False,
    ) -> int:
        """Compile every executable the serving path can hit at ``batch``:
        prefill for each seq bucket, the single-token decode step, and the
        fused chunk scans — each × every cache-read bucket when ``buckets``
        (the default; the live path picks buckets by row position, so all
        are reachable). Eats the multi-second XLA compiles at worker
        startup instead of on the first unlucky request. Returns the number
        of executables compiled.

        Each executable is compiled exactly ONCE: ``generate``/
        ``generate_fused`` (like the scheduler) re-wrap every carried state
        array with the engine's canonical shardings, so each executable has
        a single steady-state input signature.

        ``prefix_prefill`` additionally compiles each prefill bucket's
        prefix-reuse variant (the ``start``-offset signature): set it when
        this engine will serve ``generate(prefix=...)`` so the first
        prefix request doesn't eat the multi-second prefill compile
        mid-serve. (The per-prefix seed scatter still compiles on first
        use — its shape depends on the prefix length — but that's a
        sub-second scatter compile, not a model compile.)"""
        if isinstance(chunk_steps, int):
            chunk_steps = (chunk_steps,)
        sa = self._sample_args(GenerationParams(), batch)
        dt = devtel.enabled()
        if dt:
            devtel.install_monitoring_hook()
            devtel.observer().watch_obj(self)
        n = 0
        for S in self.seq_buckets():
            cache = self.new_cache(batch)
            ids = jnp.zeros((batch, S), jnp.int32)
            lens = jnp.ones(batch, jnp.int32)
            if dt:
                # Derive roofline cost BEFORE the executing call: lower()
                # only traces (nothing is donated), but after execution
                # the donated cache buffer is gone.
                self.devtel_cost(
                    "prefill", (batch, S), batch=batch, steps=1, kv_len=S,
                    prefill_tokens=batch * (S - 1),
                    lower_thunk=lambda: self._prefill.lower(
                        self.params, ids, cache, lens, sa
                    ),
                )
            tok, _, cache = self._prefill(self.params, ids, cache, lens, sa)
            del cache
            n += 1
            if prefix_prefill:
                cache = self.new_cache(batch)
                tok, _, cache = self._prefill(
                    self.params, ids, cache, lens, sa,
                    jnp.zeros(batch, jnp.int32),
                )
                del cache
                n += 1
        tok = self.canon_vec(tok)
        bucket_set = self.prewarm_bucket_set() if buckets else [None]
        cache = self.canon_cache(self.new_cache(batch))
        cur = self.canon_vec(jnp.ones(batch, jnp.int32))
        for tb in bucket_set:
            if dt:
                self.devtel_cost(
                    "decode", (batch, tb), batch=batch, steps=1, kv_len=tb,
                    lower_thunk=lambda: self._decode.lower(
                        self.params, tok, cache, cur, sa, t_bucket=tb
                    ),
                )
            _, _, c2 = self._decode(
                self.params, tok, cache, cur, sa, t_bucket=tb
            )
            cache = self.canon_cache(c2)
            n += 1
        for k in chunk_steps:
            if k <= 1:
                continue
            done = self.canon_vec(jnp.zeros(batch, bool))
            eos = self.canon_vec(jnp.full(batch, -1, jnp.int32))
            for tb in bucket_set:
                # generate()'s chunked branch runs the grouped program at
                # n_chunks=1 — token/position carries are donated, so
                # rebind them from the outputs before the next compile.
                if dt:
                    self.devtel_cost(
                        "decode_group", (batch, 1, k, tb),
                        batch=batch, steps=k, kv_len=tb,
                        lower_thunk=lambda: self._decode_group.lower(
                            self.params, tok, cache, cur, sa, done, eos,
                            n_chunks=1, n_steps=k, t_bucket=tb,
                        ),
                    )
                _, t2, c2, cur2, _ = self._decode_group(
                    self.params, tok, cache, cur, sa, done, eos,
                    n_chunks=1, n_steps=k, t_bucket=tb,
                )
                cache = self.canon_cache(c2)
                tok = self.canon_vec(t2)
                cur = self.canon_vec(cur2)
                n += 1
        # Drain the device before returning: each prewarm call above also
        # DISPATCHED one execution, and on remote-tunnel backends the
        # first execution of a program carries a program-load cost — left
        # queued, that backlog lands on the first real request (measured
        # 150 s of "TTFT" that was actually deferred prewarm work).
        jax.block_until_ready(cache.positions)
        _ = int(jnp.zeros((), jnp.int32) + 1)
        del cache
        return n

    def new_cache(self, batch: int | None = None):
        if self.kv_layout == "paged":
            # Engine-owned generate paths use the dense-equivalent identity
            # layout (full pool, no allocator); the scheduler builds its
            # shared-pool cache via new_paged_cache directly.
            return self.new_paged_cache(batch)
        return init_cache(
            self.mesh,
            n_layers=self.cfg.n_layers,
            batch=batch or self.batch_size,
            max_len=self.max_seq_len,
            n_kv_heads=self.cfg.n_kv_heads,
            head_dim=self.cfg.head_dim,
            dtype=self._cache_dtype,
        )

    def new_paged_cache(
        self, batch: int | None = None, *,
        num_blocks: int | None = None, identity: bool = True,
    ) -> PagedKVCache:
        """Fresh paged cache. ``identity=True`` (engine generate paths)
        pre-maps row b to blocks [b*MB, (b+1)*MB) over a full pool;
        ``identity=False`` (scheduler) starts every table at the unmapped
        sentinel and sizes the pool to ``num_blocks`` (default: the
        engine's ``kv_blocks`` flag, else dense-equivalent)."""
        b = batch or self.batch_size
        if num_blocks is None and not identity:
            num_blocks = self.kv_blocks
        return init_paged_cache(
            self.mesh,
            n_layers=self.cfg.n_layers,
            batch=b,
            max_len=self.max_seq_len,
            n_kv_heads=self.cfg.n_kv_heads,
            head_dim=self.cfg.head_dim,
            dtype=self._cache_dtype,
            block_size=self.block_size,
            num_blocks=num_blocks,
            identity_tables=identity,
        )

    # -- canonical state shardings ------------------------------------------
    #
    # jit-produced arrays carry GSPMD-inferred shardings whose PartitionSpec
    # representation is not a stable normal form: feeding one executable's
    # output to another can key a fresh compile even though the layout is
    # identical (round 3 worked around this by prewarming every executable
    # TWICE to cover the 2-cycle of representations). The scheduler instead
    # re-wraps every state array it carries across steps with the engine's
    # canonical shardings — ``jax.device_put`` to an equivalent sharding is
    # a metadata rewrap, not a copy — so each executable has exactly ONE
    # steady-state input signature and prewarm compiles it exactly once
    # (asserted by tests/test_serve.py::test_prewarm_covers_all_shapes).

    def _canon_cache_shardings(self, cache):
        # Memoized: canon_cache runs once per decoded token on the
        # single-step generate path. Dense shardings depend on the batch
        # (dp shards rows); paged ones only on the layout (the pool is
        # row-free) — the key carries both plus the cache type.
        paged = isinstance(cache, PagedKVCache)
        key = (paged, cache.block_tables.shape[0] if paged
               else cache.k.shape[1])
        hit = self._canon_cache_memo.get(key)
        if hit is not None:
            return hit
        from jax.sharding import NamedSharding

        from llmss_tpu.engine.cache import (
            cache_specs_for, paged_cache_specs_for,
        )

        if paged:
            specs = paged_cache_specs_for(
                self.mesh, n_kv_heads=self.cfg.n_kv_heads,
                dtype=self._cache_dtype,
            )
            out = PagedKVCache(*[
                NamedSharding(self.mesh, s) if s is not None else None
                for s in specs
            ])
        else:
            specs = cache_specs_for(
                self.mesh, batch=cache.k.shape[1],
                max_len=self.max_seq_len,
                n_kv_heads=self.cfg.n_kv_heads, dtype=self._cache_dtype,
            )
            out = KVCache(*[
                NamedSharding(self.mesh, s) if s is not None else None
                for s in specs
            ])
        self._canon_cache_memo[key] = out
        return out

    def canon_cache(self, cache):
        """Re-wrap a (possibly jit-produced) cache with the same canonical
        shardings ``new_cache`` uses — layout-identical, so no data moves."""
        sh = self._canon_cache_shardings(cache)
        return type(cache)(*[
            jax.device_put(x, s) if x is not None else None
            for x, s in zip(cache, sh)
        ])

    def canon_vec(self, x: jax.Array) -> jax.Array:
        """Canonical (replicated) sharding for small per-row state vectors
        (tokens, positions) carried across scheduler steps."""
        from jax.sharding import NamedSharding, PartitionSpec

        return jax.device_put(x, NamedSharding(self.mesh, PartitionSpec()))

    def _sample_args(self, gens: "GenerationParams | list[GenerationParams]",
                     batch: int):
        if isinstance(gens, GenerationParams):
            gens = [gens] * batch
        return dict(
            seeds=jnp.asarray([g.seed for g in gens], jnp.int32),
            temperature=jnp.asarray(
                [g.temperature for g in gens], jnp.float32
            ),
            top_k=jnp.asarray([g.top_k for g in gens], jnp.int32),
            top_p=jnp.asarray([g.top_p for g in gens], jnp.float32),
            greedy=jnp.asarray([g.is_greedy for g in gens], bool),
        )

    def _pad_prompts(
        self, prompts: list[list[int]], pad_id: int = 0
    ) -> tuple[np.ndarray, np.ndarray]:
        lens = np.array([len(p) for p in prompts], np.int32)
        if lens.max() > self.max_seq_len:
            raise ValueError(
                f"prompt length {lens.max()} exceeds max_seq_len "
                f"{self.max_seq_len}"
            )
        S = _bucket(int(lens.max()), self.max_seq_len)
        ids = np.full((len(prompts), S), pad_id, np.int32)
        for i, p in enumerate(prompts):
            ids[i, : len(p)] = p
        return ids, lens

    def generate(
        self,
        prompts: list[list[int]],
        gen: GenerationParams | list[GenerationParams],
        *,
        on_token=None,
        on_increment=None,
        on_poisoned=None,
        cancel_poll=None,
        chunk_steps: int = 1,
        live_rows: int | None = None,
        prefix: "Prefix | None" = None,
    ) -> list[list[int]]:
        """Streaming host-loop generation (≙ generate.py:99-145 cache path).

        ``prefix``: a retained KV segment (``build_prefix``) every prompt
        must extend — its tokens are NOT re-prefilled: the cache rows are
        seeded from the segment and only each prompt's suffix runs through
        the model. On bf16 caches emitted tokens are identical to the
        from-scratch run (positions, masks, and sampling counters are all
        absolute); int8 caches are storage-bit-stable but the suffix reads
        the prefix through quantized KV, so tokens can differ from a
        from-scratch run at logit ties (models/decoder.py).

        ``gen`` may be a list with one entry per prompt: a batch can mix
        greedy/sampled requests with different warpers, lengths, and EOS ids
        (the serving path; the reference hard-codes one config per batch).
        ``on_token(step, tokens: np.ndarray)`` is called per step with the
        raw batch tokens; ``on_increment(row, new_tokens: list[int])`` is
        called only for tokens actually ACCEPTED into a row's output (EOS
        and post-completion fills excluded) — the serving layer streams
        from here with engine-owned completion semantics. Stops early when every row is done.
        ``on_poisoned(row)`` (optional) fires when a row's logits go
        non-finite mid-decode (``chunk_steps > 1`` path — the serving
        path): that row stops decoding with the tokens produced before the
        poison, co-batched rows are unaffected, and the caller should
        answer the row with an error rather than a truncated success.
        ``cancel_poll() -> iterable[int]`` (optional) is polled for row
        indices whose clients went away: those rows stop accumulating
        tokens and count as done.

        ``chunk_steps > 1`` runs that many fused decode steps per host
        round-trip (one dispatch + one token fetch per chunk instead of per
        token): the serving throughput lever — host-link latency amortizes
        across the chunk. Token *results* are identical; the trade is
        granularity: ``on_token``/``cancel_poll`` fire once per chunk, and
        a row reaching EOS mid-chunk stops contributing but the chunk still
        runs to its end on device (its extra steps produce discarded EOS
        fills — same cost the single-step path pays keeping done rows in
        the batch).

        ``live_rows`` marks how many leading rows are real requests when
        the caller padded the batch to its envelope (serving): metrics
        count only those, and only their tokens.
        """
        if chunk_steps < 1:
            raise ValueError(f"chunk_steps must be >= 1, got {chunk_steps}")
        B = len(prompts)
        gens = gen if isinstance(gen, list) else [gen] * B
        assert len(gens) == B
        for g in gens:
            g.validate()
        cache = self.new_cache(B)
        sample_args = self._sample_args(gens, B)

        if prefix is not None:
            full_lens, suffixes = self.split_prefix(prompts, prefix)
            if int(full_lens.max()) > self.max_seq_len:
                # Same guard _pad_prompts applies on the non-prefix path:
                # a prefix+suffix total past the ring would wrap the
                # suffix over the just-seeded prefix slots.
                raise ValueError(
                    f"prompt length {int(full_lens.max())} exceeds "
                    f"max_seq_len {self.max_seq_len}"
                )
            ids, suf_lens = self._pad_prompts(suffixes)
            if prefix.length + ids.shape[1] > self.max_seq_len:
                # The suffix prefill pads to a BUCKET, and every padded
                # column computes a slot (slot = position % max_len) even
                # though its kv position is masked to -1 — so a start +
                # bucket reaching past the ring wraps those writes over
                # the just-seeded prefix slots, destroying the reused KV.
                # The request itself fits (checked above); only the
                # bucket-padded suffix doesn't. Fall back to a from-scratch
                # prefill of the full prompts — identical tokens, just
                # without the prefix's FLOP savings.
                prefix = None
        if prefix is not None:
            cache = self.canon_cache(self.seed_cache(cache, prefix))
            start = jnp.full(B, prefix.length, jnp.int32)
            tok, _, cache = self.timed_prefill(
                self._prefill, self.params, jnp.asarray(ids), cache,
                jnp.asarray(suf_lens), sample_args, start,
                batch=live_rows or B,
            )
            lens = full_lens
        else:
            ids, lens = self._pad_prompts(prompts)
            tok, _, cache = self.timed_prefill(
                self._prefill, self.params, jnp.asarray(ids), cache,
                jnp.asarray(lens), sample_args, batch=live_rows or B,
            )
        # Carry canon-resharded state (like the scheduler): every decode
        # executable then has exactly one steady-state input signature, so
        # prewarm compiles each once and no mid-request compile can occur.
        tok = self.canon_vec(tok)
        cache = self.canon_cache(cache)
        eos = np.asarray(
            [g.eos_token_id if g.eos_token_id is not None else -1
             for g in gens]
        )
        max_new = np.asarray([g.max_new_tokens for g in gens])
        out = [[] for _ in range(B)]
        done = np.zeros(B, bool)
        cur_pos = self.canon_vec(jnp.asarray(lens))
        # Host-side upper bound on any row's ring position — drives the
        # cache-read bucket (decode cost follows live context, not ring
        # size).
        pos_hi = int(lens.max())
        total_steps = int(max_new.max())
        eos_dev = self.canon_vec(jnp.asarray(eos, jnp.int32))

        step = 0

        inc_buf: list[list[int]] = [[] for _ in range(B)]

        def flush_increments() -> None:
            # One on_increment per row per host round-trip (chunk): SSE /
            # broker push costs scale with chunks, not tokens.
            if on_increment is None:
                return
            for i in range(B):
                if inc_buf[i]:
                    on_increment(i, inc_buf[i])
                    inc_buf[i] = []

        def process(tok_np) -> bool:
            """Account one step's tokens; returns True when all rows done."""
            nonlocal step
            newly_done = (tok_np == eos) | (step >= max_new)
            for i in range(B):
                if not done[i] and not newly_done[i]:
                    out[i].append(int(tok_np[i]))
                    if on_increment is not None:
                        inc_buf[i].append(int(tok_np[i]))
                    if len(out[i]) == max_new[i]:
                        done[i] = True
            done[:] = done | newly_done
            if on_token is not None:
                on_token(step, tok_np)
            step += 1
            return bool(done.all())

        process(np.asarray(tok))
        flush_increments()
        while not done.all() and step < total_steps:
            if cancel_poll is not None:
                for i in cancel_poll():
                    done[i] = True
                if done.all():
                    break
            # Always run full chunks (never a remainder-sized one): a
            # distinct n_steps would compile a fresh executable mid-request.
            # Overshoot columns are discarded by process() — once step
            # reaches every row's max_new, all rows are done and the loop
            # exits.
            k = chunk_steps
            if k == 1:
                with self.metrics.decode_step.time():
                    tok, _, cache = self._decode(
                        self.params, tok, cache, cur_pos, sample_args,
                        t_bucket=self.decode_bucket(pos_hi + 1),
                    )
                    # Sync inside the timer: dispatch is async, so without
                    # this the stat would record ~µs dispatch overhead, not
                    # step latency. The loop reads the token next iteration
                    # anyway, so this costs nothing.
                    tok.block_until_ready()  # lint: ignore[host-sync-in-loop]
                tok = self.canon_vec(tok)
                cache = self.canon_cache(cache)
                cur_pos = cur_pos + 1
                pos_hi += 1
                # Deliberate per-step fetch: chunk_steps=1 IS the
                # token-granularity streaming mode; the sync is the product.
                process(np.asarray(tok))  # lint: ignore[host-sync-in-loop]
                flush_increments()
            else:
                t0 = time.perf_counter()
                tb = self.decode_bucket(pos_hi + k)
                packed, last_tok, cache, cur_pos, _ = self._decode_group(
                    self.params, tok, cache, cur_pos, sample_args,
                    self.canon_vec(jnp.asarray(done)), eos_dev,
                    n_chunks=1, n_steps=k, t_bucket=tb,
                )
                cache = self.canon_cache(cache)
                cur_pos = self.canon_vec(cur_pos)
                tok = self.canon_vec(last_tok)
                pos_hi += k
                self.metrics.host_dispatch.record(time.perf_counter() - t0)
                self.metrics.add_group()
                # ONE packed fetch per chunk BY DESIGN: tokens and poison
                # flags cross the host link in a single transfer (the
                # pipelined scheduler overlaps it with the next dispatch).
                with self.metrics.host_fetch.time():
                    flat = np.asarray(packed)  # lint: ignore[host-sync-in-loop]
                self.metrics.add_host_sync()
                chunk_np = flat[: B * k].reshape(B, k)
                poisoned_np = flat[B * k:].astype(bool)
                t1 = time.perf_counter()
                self.metrics.decode_step.record((t1 - t0) / k)
                if devtel.enabled():
                    # Dispatch→fetch covers the whole fused group, so the
                    # fold prices the full k-step executable (cache hit
                    # after prewarm; analytical for cold signatures).
                    devtel.fold(
                        "decode_group", t1 - t0,
                        self.devtel_cost(
                            "decode_group", (B, 1, k, tb),
                            batch=B, steps=k, kv_len=tb,
                        ),
                    )
                t_cb = time.perf_counter()
                for col in range(k):
                    if process(chunk_np[:, col]):
                        break
                # Poisoned rows were forced done on device (EOS-filled from
                # the bad step on), so process() already stopped accepting
                # their tokens; surface the flag so the caller errors the
                # row instead of returning a silently truncated success.
                for i in range(B):
                    if poisoned_np[i] and not done[i]:
                        done[i] = True
                if on_poisoned is not None:
                    for i in np.flatnonzero(poisoned_np):
                        on_poisoned(int(i))
                flush_increments()
                self.metrics.host_callback.record(
                    time.perf_counter() - t_cb
                )
        self.metrics.add_tokens(
            sum(len(o) for o in out[: live_rows or B])
        )
        return out

    def generate_speculative(
        self, prompts: list[list[int]], gen: GenerationParams, *,
        gamma: int = 4, ngram: int = 3,
    ) -> list[list[int]]:
        """Greedy generation with prompt-lookup speculative decoding:
        exactly ``generate``'s tokens, 1..gamma+1 of them per forward /
        host round-trip (engine/speculative.py)."""
        from llmss_tpu.engine.speculative import generate_speculative

        return generate_speculative(
            self, prompts, gen, gamma=gamma, ngram=ngram
        )

    def generate_fused(
        self, prompts: list[list[int]], gen: GenerationParams
    ) -> list[list[int]]:
        """Whole-generation-on-device path: prefill + one fused scan jit.

        Zero per-token host round-trips — the TPU-native answer to the
        reference's per-token broadcast tax (``generate.py:144``).
        """
        gen.validate()
        B = len(prompts)
        ids, lens = self._pad_prompts(prompts)
        cache = self.new_cache(B)
        sample_args = self._sample_args(gen, B)

        tok, _, cache = self.timed_prefill(
            self._prefill, self.params, jnp.asarray(ids), cache,
            jnp.asarray(lens), sample_args, batch=B,
        )
        tok = self.canon_vec(tok)
        cache = self.canon_cache(cache)
        eos = jnp.int32(
            gen.eos_token_id if gen.eos_token_id is not None else -1
        )
        eos_dev = self.canon_vec(jnp.full(B, int(eos), jnp.int32))
        done = self.canon_vec(tok == eos_dev)
        # Read the prefill token BEFORE the grouped call: the token carry
        # is donated, so the buffer is dead once the program is enqueued.
        first = np.asarray(tok)[:, None]
        n_steps = gen.max_new_tokens - 1
        packed, _, cache, _, done = self._decode_group(
            self.params, tok, cache, self.canon_vec(jnp.asarray(lens)),
            sample_args, done, eos_dev, n_chunks=1, n_steps=n_steps,
            t_bucket=self.decode_bucket(int(lens.max()) + n_steps),
        )
        rest = np.asarray(packed)[: B * n_steps].reshape(B, n_steps)
        all_toks = np.concatenate([first, rest], axis=1)
        out = []
        for row in all_toks:
            stop = np.where(row == int(eos))[0]
            out.append(row[: stop[0]].tolist() if stop.size else row.tolist())
        self.metrics.add_tokens(sum(len(o) for o in out))
        return out
