"""Prompt-lookup speculative decoding: draft-free speculation for greedy
decode, fully on-device.

Each speculative step drafts ``gamma`` candidate tokens by n-gram lookup
in the row's OWN device-resident history (prompt + emitted tokens —
"prompt lookup decoding": repeated spans are common in summarization,
code, chat with shared context), then verifies the whole draft in ONE
forward of S = gamma+1 tokens against the KV cache. The model's greedy
choice at each draft position either confirms the next draft token
(accept, keep going) or replaces it (stop; the replacement is the step's
bonus token). Every step emits 1..gamma+1 tokens for ~2.5x the cost of a
single-token step (measured: 11.5 vs 4.5 ms at 1b2/batch16), so
workloads with lookup hits come out ahead — with no distribution drift:
every emitted token is the argmax of the model's logits given the true
prefix.

**Everything runs on device in fused groups**: the n-gram lookup, the
verify forward, acceptance, the history append, and EOS handling run
inside ONE jitted program of ``m`` scanned speculative steps per group
(``spec_group_impl``), with the group's choices/emits/state packed into
a single flat array inside the jit — one dispatch and one device→host
fetch per group, exactly the grouped-decode discipline of
``DecodeEngine._decode_group`` (a host-side draft loop was measured 10x
SLOWER through a ~100 ms-RTT host link: one round-trip per ~3.5
tokens; chained per-step dispatch still paid ~10 ms of host exec
overhead per verify — SPEC_BENCH.json's 0.82x wall-clock).

Exactness scope: verification is exact *under the verify forward's own
numerics*. When the S=gamma+1 forward and the S=1 decode step lower to
the same kernels (the CPU test mesh), output is token-identical to plain
``generate`` — asserted in tests/test_speculative.py. On TPU the two
paths use different attention kernels whose fp32 logits can resolve an
argmax tie differently, so the two valid greedy decodes may diverge at a
tie; ``tools/bench_spec.py`` reports the agreement span instead of
asserting identity.

TPU design notes:
- ``gamma`` and the chunk length are static; drafts are data. Rows with
  no n-gram match draft a repeat of their last token — usually rejected,
  which degrades to a normal 1-token step, never to a wrong token.
- Rows advance by different amounts; per-row ``hist_len`` drives ring
  positions (the engine's ring addressing supports desynced rows).
- The verify forward writes all gamma+1 draft tokens' KV; slots of
  REJECTED draft tokens are invalidated in the same step (``positions``
  reset to -1) so later steps never attend them. Accepted tokens' KV is
  valid by construction: an accepted draft token IS the token the model
  chose at that position.

The reference has no speculation of any kind (one token per
``generate.py:99`` loop iteration).
"""

from __future__ import annotations

import time
from functools import partial

import numpy as np

import jax
import jax.numpy as jnp


def lookup_draft(
    hist: list[int], gamma: int, ngram: int = 3,
) -> list[int]:
    """Host-side reference of the device draft rule: match the trailing
    n-gram (falling back to shorter n, then to repeating the last token)
    against the row's own past; propose the ``gamma`` tokens that
    followed the most recent match."""
    h = np.asarray(hist, np.int32)
    L = len(h)
    for n in range(min(ngram, L - 1), 0, -1):
        tail = h[L - n:]
        windows = np.lib.stride_tricks.sliding_window_view(h[:-1], n)
        hits = np.flatnonzero((windows == tail).all(axis=1))
        for s in hits[::-1]:
            cont = h[s + n: s + n + gamma]
            if len(cont) > 0:
                out = cont.tolist()
                while len(out) < gamma:
                    out.append(out[-1])
                return out
    return [int(h[-1])] * gamma


def _device_draft(hist: jax.Array, L: jax.Array, gamma: int, ngram: int):
    """Vectorized prompt-lookup draft for one row: ``hist`` [H], ``L``
    scalar live length. Mirrors ``lookup_draft``: longest n first, most
    recent match; the continuation may overlap the tail (self-extending
    periodic patterns). Falls back to repeating the last token."""
    H = hist.shape[0]
    iota = jnp.arange(H, dtype=jnp.int32)
    last = hist[jnp.clip(L - 1, 0, H - 1)]
    draft = jnp.full((gamma,), last, jnp.int32)
    found_any = jnp.zeros((), bool)
    for n in range(ngram, 0, -1):
        # window starting at s covers hist[s : s+n]; candidate iff it lies
        # strictly before the trailing occurrence (s + n <= L - 1) and the
        # history is long enough for an n-gram tail (L - n >= 1).
        tail = jax.lax.dynamic_slice(
            hist, (jnp.clip(L - n, 0, H - n),), (n,)
        )
        win = hist[jnp.clip(iota[:, None] + jnp.arange(n)[None, :], 0,
                            H - 1)]  # [H, n]
        valid = (iota + n <= L - 1) & (L - n >= 1)
        hit = valid & jnp.all(win == tail[None, :], axis=1)
        s_best = jnp.max(jnp.where(hit, iota, -1))
        found = s_best >= 0
        cont_idx = s_best + n + jnp.arange(gamma, dtype=jnp.int32)
        # Positions past the live history pad with the CONTINUATION's last
        # in-range element — the host reference's ``out.append(out[-1])``
        # rule, stated literally (a truncated continuation always ends at
        # ``hist[L-1]``, so this pad VALUE equals the row's last token; the
        # code now encodes the documented rule rather than relying on that
        # coincidence). A hit guarantees ``s_best + n < L``, so the pad
        # index is in range whenever ``found`` (and masked out otherwise).
        pad = hist[jnp.clip(jnp.minimum(cont_idx[-1] + 1, L) - 1, 0, H - 1)]
        cont = jnp.where(
            cont_idx < L, hist[jnp.clip(cont_idx, 0, H - 1)], pad
        )
        take = found & ~found_any
        draft = jnp.where(take, cont, draft)
        found_any = found_any | found
    return draft


def spec_step_impl(
    cfg, mesh, params, hist, hist_len, cache, done, eos,
    *, gamma: int, ngram: int = 3, t_bucket: int | None = None,
):
    """One speculative step as a single jit: device draft → verify
    forward → acceptance → EOS/ring handling → history append. Full-size
    groups of these run as ONE scanned program (``spec_group_impl`` —
    one dispatch + one packed fetch per group); the chained-dispatch
    form remains the ring-constrained partial-group path, where a
    bespoke grouped executable per residual group size would compile at
    every ring boundary.

    hist [B, H] int32 — prompt + emitted tokens (no EOS); hist_len [B].
    Returns (choice [B, gamma+1], n_emit [B], hist, hist_len, cache,
    done): the host emits ``choice[r, :n_emit[r]]`` in order. ``done``
    rows are frozen (n_emit 0, no live writes); the HOST must stop
    dispatching before any live row lacks ring headroom for a full
    window — a frozen-row write may wrap harmlessly over its own dead
    slots, but a live row's wrap would destroy its context.
    """
    from llmss_tpu.models.decoder import forward

    B, H = hist.shape
    S = gamma + 1
    b_idx = jnp.arange(B, dtype=jnp.int32)[:, None]
    drafter = jax.vmap(
        partial(_device_draft, gamma=gamma, ngram=ngram)
    )

    cur = hist_len - 1  # position/index of each row's current token
    frozen = done
    cur_tok = hist[b_idx[:, 0], jnp.clip(cur, 0, H - 1)]
    draft = jnp.concatenate(
        [cur_tok[:, None], drafter(hist, hist_len)], axis=1
    )  # [B, S]
    positions = cur[:, None] + jnp.arange(S, dtype=jnp.int32)[None, :]
    slots = positions % cache.max_len
    logits, cache = forward(
        cfg, params, draft, positions, cache, slots, mesh=mesh,
        t_bucket=t_bucket,
    )
    choice = jnp.argmax(logits, axis=-1).astype(jnp.int32)  # [B, S]
    match = draft[:, 1:] == choice[:, :-1]
    n_acc = jnp.sum(
        jnp.cumprod(match.astype(jnp.int32), axis=1), axis=1
    )
    n_emit = n_acc + 1  # accepted draft tokens + bonus/replacement

    col = jnp.arange(S, dtype=jnp.int32)[None, :]
    # EOS inside the emitted window truncates the emission (the EOS
    # itself is not emitted) and finishes the row.
    eos_hit = (choice == eos[:, None]) & (col < n_emit[:, None])
    any_eos = jnp.any(eos_hit, axis=1)
    first_eos = jnp.argmax(eos_hit, axis=1)
    n_emit = jnp.where(any_eos, first_eos, n_emit)
    n_emit = jnp.where(frozen, 0, n_emit)

    # Invalidate rejected draft KV; frozen (done) rows contribute nothing
    # live, so their whole window is invalidated too.
    keep = (col <= n_acc[:, None]) & ~frozen[:, None]
    fixed = jnp.where(keep, positions, -1)
    cache = cache._replace(
        positions=cache.positions.at[b_idx, slots].set(fixed)
    )

    # Append emitted tokens to the history (masked scatter).
    app_idx = hist_len[:, None] + col
    app_ok = col < n_emit[:, None]
    hist = hist.at[
        b_idx, jnp.clip(app_idx, 0, H - 1)
    ].set(jnp.where(app_ok, choice, hist[
        b_idx, jnp.clip(app_idx, 0, H - 1)
    ]))
    hist_len = hist_len + n_emit
    done = done | (any_eos & ~frozen)
    return choice, n_emit, hist, hist_len, cache, done


def spec_group_impl(
    cfg, mesh, params, hist, hist_len, cache, done, eos,
    *, m: int, gamma: int, ngram: int = 3, t_bucket: int | None = None,
):
    """A GROUP of ``m`` speculative steps as ONE jitted program: an outer
    ``lax.scan`` over ``spec_step_impl`` with the result packing moved
    inside the jit — the same grouped-dispatch discipline as the main
    decode path (``DecodeEngine._decode_group``). The host pays one
    dispatch and one packed fetch per group instead of one dispatch per
    verify forward, which is what deletes the per-verify host exec
    overhead the chained-dispatch loop still paid (~10 ms/verify measured
    through the serving tunnel — SPEC_BENCH.json's 0.82x wall-clock was
    entirely that tax).

    Returns ``(packed, hist, hist_len, cache, done)`` where ``packed`` is
    the flat int32 array ``[m·B·S choices | m·B emits | B hist_len |
    B done]`` — byte-identical to the layout the host previously
    concatenated from chained step outputs, so the unpack code is shared.
    """
    # Pin the stacked ys to a replicated sharding: GSPMD otherwise
    # propagates an unreduced partial-sum layout from the tp-sharded
    # logits into the scan's stacked outputs, and the host reads choices
    # summed over the tp axis (tp× their true value — the same hazard
    # fixed in DecodeEngine._decode_group_impl). The carry is immune;
    # only the ys leave the loop unconstrained (parallel/sharding.ys_pin
    # documents the hazard; shardcheck's partial-sum-leak rule gates it).
    from llmss_tpu.parallel.sharding import ys_pin

    pin = ys_pin(mesh)

    def body(carry, _):
        hist, hist_len, cache, done = carry
        choice, n_emit, hist, hist_len, cache, done = spec_step_impl(
            cfg, mesh, params, hist, hist_len, cache, done, eos,
            gamma=gamma, ngram=ngram, t_bucket=t_bucket,
        )
        return (hist, hist_len, cache, done), (pin(choice), pin(n_emit))

    (hist, hist_len, cache, done), (choices, emits) = jax.lax.scan(
        body, (hist, hist_len, cache, done), None, length=m,
    )
    packed = jnp.concatenate([
        choices.reshape(-1), emits.reshape(-1), hist_len,
        done.astype(jnp.int32),
    ])
    return packed, hist, hist_len, cache, done


def generate_speculative(
    engine,
    prompts: list[list[int]],
    gen,
    *,
    gamma: int = 4,
    ngram: int = 3,
    chunk_steps: int = 8,
) -> list[list[int]]:
    """Greedy generation with fused-chunk prompt-lookup speculation (see
    module docstring). Emits a valid greedy decode — token-identical to
    ``generate`` whenever both lower to the same kernels — in roughly
    ``1/mean_accepted`` of the forwards and ``1/(chunk·mean_accepted)``
    of the host round-trips. When ring headroom for a full speculative
    window runs out, the tail finishes on plain single-token steps.

    Records acceptance stats on ``engine.metrics.spec_stats``."""
    gen.validate()
    if not gen.is_greedy:
        raise ValueError(
            "speculative decoding verifies greedy argmax choices; "
            "sampled requests must use generate()"
        )
    B = len(prompts)
    S = gamma + 1
    lens_probe = max(len(p) for p in prompts)
    if lens_probe + S + 1 > engine.max_seq_len:
        # No ring headroom for even one speculative window (or the prompt
        # fills the ring outright): plain greedy serves the identical
        # contract. Stats reflect THIS call (zero speculation).
        engine.metrics.spec_stats = {
            "verify_forwards": 0, "tokens_via_speculation": 0,
            "mean_tokens_per_forward_per_row": 0.0,
            "gamma": gamma, "chunk_steps": chunk_steps,
        }
        return engine.generate(prompts, gen)

    def get_step(t_bucket):
        key = ("_spec_step", gamma, ngram, t_bucket)
        fn = engine.__dict__.get(key)
        if fn is None:
            fn = jax.jit(
                partial(
                    spec_step_impl, engine.cfg, engine.mesh,
                    gamma=gamma, ngram=ngram, t_bucket=t_bucket,
                ),
                donate_argnums=(3,),
            )
            engine.__dict__[key] = fn
        return fn

    def get_group(t_bucket):
        # One grouped program per (group size, draft params, bucket) —
        # cached on the engine like the step jits so CompileGuard sees it.
        # Only the FULL group size compiles (partial groups near the ring
        # chain the step jit instead), bounding the executable count.
        key = ("_spec_group", chunk_steps, gamma, ngram, t_bucket)
        fn = engine.__dict__.get(key)
        if fn is None:
            fn = jax.jit(
                partial(
                    spec_group_impl, engine.cfg, engine.mesh,
                    m=chunk_steps, gamma=gamma, ngram=ngram,
                    t_bucket=t_bucket,
                ),
                donate_argnums=(1, 3),  # hist, cache
            )
            engine.__dict__[key] = fn
        return fn

    ids, lens = engine._pad_prompts(prompts)
    cache = engine.new_cache(B)
    sa = engine._sample_args(gen, B)
    tok, _, cache = engine.timed_prefill(
        engine._prefill, engine.params, jnp.asarray(ids), cache,
        jnp.asarray(lens), sa, batch=B,
    )
    tok_np = np.asarray(tok)
    cache = engine.canon_cache(cache)

    eos_val = gen.eos_token_id if gen.eos_token_id is not None else -1
    out: list[list[int]] = [[] for _ in range(B)]
    done_np = np.zeros(B, bool)

    def emit(r: int, t: int) -> bool:
        """Append token t to row r; returns True iff it was appended
        (the row may complete in the same call). (Device-side EOS/done
        handling already excludes EOS tokens and frozen rows; max_new is
        enforced here on the host.)"""
        if done_np[r]:
            return False
        out[r].append(t)
        if len(out[r]) >= gen.max_new_tokens:
            done_np[r] = True
        return True

    H = engine.max_seq_len
    hist_np = np.zeros((B, H), np.int32)
    for r, p in enumerate(prompts):
        hist_np[r, : len(p)] = p
    first_live = ~(tok_np == eos_val)
    for r in range(B):
        if first_live[r]:
            emit(r, int(tok_np[r]))
        else:
            done_np[r] = True
        if not done_np[r]:
            hist_np[r, lens[r]] = tok_np[r]
    hist = engine.canon_vec(jnp.asarray(hist_np))
    hist_len = engine.canon_vec(
        jnp.asarray(lens + first_live.astype(np.int32), jnp.int32)
    )
    done = engine.canon_vec(jnp.asarray(done_np))
    eos = engine.canon_vec(jnp.full(B, eos_val, jnp.int32))

    n_forwards = 0
    n_emitted = 0
    # Speculative phase: groups of ``chunk_steps`` back-to-back step
    # dispatches (async — the host blocks only on the group's fetch).
    # Each LIVE row must have headroom for chunk_steps full windows
    # (worst case all-accept); done rows' windows wrap harmlessly over
    # their own dead slots. Host-side completions (max_new) are pushed
    # back into the device ``done`` each group so finished rows neither
    # advance the guard nor burn verify work.
    hl_host = np.asarray(hist_len)
    while not done_np.all():
        live_hi = int(hl_host[~done_np].max())
        # Shrink the group near the ring so speculation keeps running
        # while a worthwhile number of windows fits (worst-case-all-accept
        # bound per group). Below half a group, the per-group fetch
        # round-trip outweighs the speculative win — finish on the
        # chunked plain tail instead.
        m = min(chunk_steps, (engine.max_seq_len - live_hi) // S)
        if m < max(1, chunk_steps // 2):
            break
        # Bucketed cache reads for the whole group: every live row's
        # positions stay under live_hi + m·S by the guard above.
        # (Frozen rows' dead windows may read truncated garbage — unread.)
        tb = engine.decode_bucket(live_hi + m * S)
        t0 = time.perf_counter()
        if m == chunk_steps:
            # Full group: ONE jitted program covers all m verify steps
            # with the packing inside the jit (spec_group_impl) — one
            # dispatch + one fetch per group; per-verify host exec
            # overhead disappears.
            packed_dev, hist, hist_len, cache, done = get_group(tb)(
                engine.params, hist, hist_len, cache, done, eos,
            )
        else:
            # Ring-constrained partial group: chain the per-step jit (a
            # grouped program per residual m would compile a bespoke
            # executable near every ring boundary) and pack on the host.
            step = get_step(tb)
            group = []
            for _ in range(m):
                # Raw jit outputs feed straight back in — a canon rewrap
                # per carried array here costs a host round-trip EACH on
                # remote backends (4/step × 8 steps ≈ the whole group's
                # device time). The executable set stabilizes after at
                # most one extra compile per bucket (self-consistent
                # output→input cycle).
                choice, n_emit, hist, hist_len, cache, done = step(
                    engine.params, hist, hist_len, cache, done, eos,
                )
                group.append((choice, n_emit))
            packed_dev = jnp.concatenate(
                [jnp.stack([c for c, _ in group]).reshape(-1)]
                + [jnp.stack([e for _, e in group]).reshape(-1)]
                + [hist_len, done.astype(jnp.int32)]
            )
        n_forwards += m
        engine.metrics.host_dispatch.record(time.perf_counter() - t0)
        engine.metrics.add_group()
        # Deliberate single fetch per speculative group: the packed layout
        # exists precisely so the whole group's choices/emits/state cross
        # the host link in ONE transfer instead of per-step fetches.
        with engine.metrics.host_fetch.time():
            packed = np.asarray(packed_dev)  # lint: ignore[host-sync-in-loop]
        engine.metrics.add_host_sync()
        t_cb = time.perf_counter()
        ch_np = packed[: m * B * S].reshape(m, B, S)
        ne_np = packed[m * B * S: m * B * (S + 1)].reshape(m, B)
        hl_host = packed[m * B * (S + 1): m * B * (S + 1) + B]
        dev_done = packed[m * B * (S + 1) + B:].astype(bool)
        for s in range(m):
            for r in range(B):
                for c in range(int(ne_np[s, r])):
                    if emit(r, int(ch_np[s, r, c])):
                        n_emitted += 1
                    if done_np[r]:
                        break
        # Device-side EOS completions never show in the emitted tokens
        # (the EOS is truncated out) — adopt them, or the host would keep
        # dispatching for rows the device already finished.
        done_np |= dev_done
        # Push host-side (max_new) completions into the device done mask.
        if (done_np & ~dev_done).any():
            done = engine.canon_vec(jnp.asarray(dev_done | done_np))
        engine.metrics.host_callback.record(time.perf_counter() - t_cb)

    # Ring-constrained tail (a full speculative window no longer fits):
    # plain CHUNKED decode via _decode_many — including past the ring
    # boundary, where generate()'s sliding-window wrap semantics apply
    # identically (each row is bounded by max_new_tokens).
    if not done_np.all():
        hl_np = np.asarray(hist_len)
        h_np = np.asarray(hist)
        pos_hi = int(hl_np.max())
        tok_cur = engine.canon_vec(jnp.asarray(
            [int(h_np[r, min(int(hl_np[r]) - 1, H - 1)]) for r in range(B)],
            jnp.int32,
        ))
        cur = engine.canon_vec(jnp.asarray(hl_np - 1, jnp.int32))
        eos_dev = engine.canon_vec(jnp.full(B, eos_val, jnp.int32))
        k = 16
        while not done_np.all():
            toks, cache, cur, _, _ = engine._decode_many(
                engine.params, tok_cur, cache, cur, sa,
                engine.canon_vec(jnp.asarray(done_np)), eos_dev,
                n_steps=k, t_bucket=engine.decode_bucket(pos_hi + k),
            )
            cache = engine.canon_cache(cache)
            cur = engine.canon_vec(cur)
            tok_cur = engine.canon_vec(toks[:, -1])
            pos_hi += k
            # One fetch per k-step tail chunk (same amortization as
            # engine.generate's chunked decode loop).
            t_np = np.asarray(toks)  # lint: ignore[host-sync-in-loop]
            for col in range(k):
                for r in range(B):
                    if not done_np[r]:
                        t = int(t_np[r, col])
                        if t == eos_val:
                            done_np[r] = True
                        else:
                            emit(r, t)

    engine.metrics.add_tokens(sum(len(o) for o in out))
    # Always overwrite: stale stats from a previous call must not be
    # misattributed to this one.
    engine.metrics.spec_stats = {
        "verify_forwards": n_forwards,
        "tokens_via_speculation": n_emitted,
        "mean_tokens_per_forward_per_row": round(
            n_emitted / n_forwards / B, 3
        ) if n_forwards else 0.0,
        "gamma": gamma,
        "chunk_steps": chunk_steps,
    }
    return out
