"""shardcheck: seeded-regression detection, manifest drift, suppression/
baseline mechanics, and the shared executable-signature vocabulary.

The seeded fixtures re-introduce the exact bug classes the auditor exists
for — the PR 6 partial-sum leak (unpinned scan ys fetched by the host) and
a donation that aliases nothing — and assert each flips the exit code.
The full-registry audit against the committed golden manifest is the CI
step itself (and the `slow`-marked gate test at the bottom).
"""

import json
from functools import partial

import jax
import jax.numpy as jnp
import pytest
from jax.sharding import NamedSharding, PartitionSpec as P

from llmss_tpu.analysis import shardcheck as sc
from llmss_tpu.parallel.mesh import AXIS_TP


@pytest.fixture(scope="module")
def env(devices):
    e = sc.build_env()
    # Every run_shardcheck() in this module reuses the one audit env —
    # rebuilding params + engines per exit-code test is pure overhead.
    mp = pytest.MonkeyPatch()
    mp.setattr(sc, "build_env", lambda plan=None: e)
    yield e
    mp.undo()


def _prog(name, host_fetch, fn, args, kwargs=None, line=999):
    return sc.Program(name, line, host_fetch, lambda e: (fn, args, kwargs or {}))


# -- seeded regressions ------------------------------------------------------

def _buggy_pair(env):
    """The PR 6 bug, minimal: scan-stacked argmax over a tp-sharded matmul
    reaches a host-fetched output. GSPMD stacks the *unreduced* per-shard
    layout into the ys; every host fetch then sees partial sums."""
    mesh = env.mesh
    w = jax.device_put(
        jnp.zeros((8, 16)), NamedSharding(mesh, P(None, AXIS_TP))
    )
    x = jnp.zeros((2, 8))

    def buggy(w, x):
        def step(h, _):
            tok = jnp.argmax(h @ w, -1).astype(jnp.int32)
            return h, tok

        h, toks = jax.lax.scan(step, x, None, length=3)
        return toks.T, h

    def fixed(w, x):
        from llmss_tpu.parallel.sharding import ys_pin

        pin = ys_pin(mesh)

        def step(h, _):
            tok = jnp.argmax(h @ w, -1).astype(jnp.int32)
            return h, pin(tok)

        h, toks = jax.lax.scan(step, x, None, length=3)
        return toks.T, h

    return (
        _prog("decode/buggy", (0,), jax.jit(buggy), (w, x)),
        _prog("decode/fixed", (0,), jax.jit(fixed), (w, x)),
    )


def test_seeded_partial_sum_leak_detected(env):
    buggy, fixed = _buggy_pair(env)
    findings, _ = sc.audit_program(buggy, env)
    assert "partial-sum-leak" in {f.rule for f in findings}
    leak = next(f for f in findings if f.rule == "partial-sum-leak")
    # Findings anchor at the registration line in shardcheck.py itself so
    # `# lint: ignore[...]` comments land next to the program they cover.
    assert (leak.path, leak.line) == (sc.SRC_PATH, buggy.line)
    assert "ys_pin" in leak.message

    findings, _ = sc.audit_program(fixed, env)
    assert findings == []


def test_reintroduced_decode_many_bug_detected(env):
    """_decode_many before the ys_pin fix, verbatim: the grouped paths got
    the pin, this one leaked the same stacked tokens to np.asarray."""
    from llmss_tpu.engine.engine import DecodeEngine

    def old_decode_many(
        cfg, mesh, params, tokens, cache, cur_pos, sample_args, done, eos,
        *, n_steps, t_bucket=None,
    ):
        body = partial(
            DecodeEngine._decode_step_body,
            cfg, mesh, params, sample_args, eos, t_bucket,
        )
        carry, toks = jax.lax.scan(
            body,
            (tokens, cache, cur_pos, done, jnp.zeros_like(done)),
            None,
            length=n_steps,
        )
        tokens, cache, cur_pos, done, poisoned = carry
        return toks.T, cache, cur_pos, done, poisoned

    fn = jax.jit(
        partial(old_decode_many, env.cfg, env.mesh),
        donate_argnums=(2,),
        static_argnames=("n_steps", "t_bucket"),
    )
    args = (
        env.params,
        jnp.zeros((sc.BATCH,), jnp.int32),
        env.engine.new_cache(sc.BATCH),
        jnp.ones((sc.BATCH,), jnp.int32),
        env.sample_args,
        jnp.zeros((sc.BATCH,), bool),
        jnp.full((sc.BATCH,), -1, jnp.int32),
    )
    prog = _prog(
        "decode_many/old", (0, 4), fn, args, {"n_steps": 2, "t_bucket": None}
    )
    findings, _ = sc.audit_program(prog, env)
    assert "partial-sum-leak" in {f.rule for f in findings}


def test_seeded_dropped_donation_detected(env):
    # Donating a (4,4) input to a program whose only outputs are (3,)
    # aliases nothing — the donated buffer is lost for no benefit.
    fn = jax.jit(lambda a, b: b * 2.0, donate_argnums=(0,))
    prog = _prog(
        "decode/donation", (), fn, (jnp.zeros((4, 4)), jnp.zeros((3,)))
    )
    findings, _ = sc.audit_program(prog, env)
    assert [f.rule for f in findings] == ["donation-unmatched"]

    # The matched twin: same shape/dtype out, donation aliases, clean.
    fn_ok = jax.jit(lambda a, b: a * 2.0, donate_argnums=(0,))
    prog_ok = _prog(
        "decode/donation-ok", (), fn_ok, (jnp.zeros((4, 4)), jnp.zeros((3,)))
    )
    findings, _ = sc.audit_program(prog_ok, env)
    assert findings == []


def test_dropped_donation_warning_classification():
    # XLA reports a dropped donation as a compile warning; the audit turns
    # it into a donation-dropped finding. Backend capability notes
    # ("Donation is not implemented for cpu") are not program bugs.
    msgs = [
        "Some donated buffers were not usable: f32[4,4]\nsecond line",
        "Donation is not implemented for cpu.\nSee explanation.",
        "Buffer donated to output 3 was not used.",
        "unrelated warning",
    ]
    out = sc.classify_donation_warnings(msgs)
    assert out == [
        "Some donated buffers were not usable: f32[4,4]",
        "Buffer donated to output 3 was not used.",
    ]


def test_aliased_output_count_from_hlo_header():
    # donation-dropped also fires structurally: fewer aliased buffers in
    # the executable than matchable donations. Parse a realistic header.
    hlo = (
        "HloModule jit_f, input_output_alias={ {0}: (2, {}, may-alias), "
        "{1}: (4, {}, must-alias) }, entry_computation_layout=...\n"
        "ENTRY main { ... }\n"
    )
    assert sc.count_aliased_outputs(hlo) == 2
    assert sc.count_aliased_outputs("HloModule jit_f, entry_layout=x") == 0


def test_host_fetch_not_replicated_detected(env):
    fn = jax.jit(
        lambda x: x * 2.0,
        out_shardings=NamedSharding(env.mesh, P(AXIS_TP)),
    )
    prog = _prog("decode/sharded-out", (0,), fn, (jnp.zeros((8,)),))
    findings, _ = sc.audit_program(prog, env)
    assert [f.rule for f in findings] == ["host-fetch-not-replicated"]


def test_seeded_finding_flips_exit_code(env):
    buggy, _ = _buggy_pair(env)
    code, findings = sc.run_shardcheck(
        None, programs=[buggy], baseline_path=None
    )
    assert code == 1
    assert {f.rule for f in findings} == {"partial-sum-leak"}


# -- golden comms manifest ---------------------------------------------------

def _collective_prog(env):
    """Tiny program with a real collective: tp-sharded matmul pinned
    replicated compiles to an all-reduce of the partial sums."""
    mesh = env.mesh
    w = jax.device_put(
        jnp.zeros((8, 16)), NamedSharding(mesh, P(None, AXIS_TP))
    )

    def f(w, x):
        return jax.lax.with_sharding_constraint(
            x @ w, NamedSharding(mesh, P())
        )

    return _prog("decode/tiny-collective", (0,), jax.jit(f), (w, jnp.zeros((2, 8))))


def _manifest_for(env, name, inv):
    return {
        "version": sc.MANIFEST_VERSION,
        "mesh": env.mesh_dims(),
        "model": {},
        "programs": {name: inv},
    }


def test_manifest_match_and_drift_flip_exit_code(env, tmp_path):
    prog = _collective_prog(env)
    findings, inv = sc.audit_program(prog, env)
    assert findings == []
    # The replication pin over tp-sharded compute must cost a collective.
    assert inv, "expected at least one collective in the tiny program"
    op = sorted(inv)[0]

    golden = tmp_path / "manifest.json"
    golden.write_text(json.dumps(_manifest_for(env, prog.name, inv)))
    code, findings = sc.run_shardcheck(
        str(golden), programs=[prog], baseline_path=None
    )
    assert (code, findings) == (0, [])

    # One extra collective in the golden counts — the audit must fail.
    tampered = {o: dict(v) for o, v in inv.items()}
    tampered[op]["count"] += 1
    golden.write_text(json.dumps(_manifest_for(env, prog.name, tampered)))
    code, findings = sc.run_shardcheck(
        str(golden), programs=[prog], baseline_path=None
    )
    assert code == 1
    assert {f.rule for f in findings} == {"comms-manifest-drift"}
    assert op in findings[0].message

    # A collective class the golden never heard of is also drift.
    extra = {o: dict(v) for o, v in inv.items()}
    extra.pop(op)
    golden.write_text(json.dumps(_manifest_for(env, prog.name, extra)))
    code, findings = sc.run_shardcheck(
        str(golden), programs=[prog], baseline_path=None
    )
    assert code == 1
    assert {f.rule for f in findings} == {"comms-manifest-drift"}


def test_program_missing_from_golden_is_drift(env, tmp_path):
    prog = _collective_prog(env)
    golden = tmp_path / "manifest.json"
    golden.write_text(json.dumps(_manifest_for(env, "someone/else", {})))
    code, findings = sc.run_shardcheck(
        str(golden), programs=[prog], baseline_path=None
    )
    assert code == 1
    assert any("missing from the golden manifest" in f.message for f in findings)
    # Partial audits skip the reverse direction (golden-but-not-audited):
    # `someone/else` not being audited here is not drift.
    assert len(findings) == 1


def test_mesh_mismatch_skips_comms_diff(env, tmp_path):
    prog = _collective_prog(env)
    _, inv = sc.audit_program(prog, env)
    manifest = _manifest_for(env, prog.name, {})  # would be drift...
    manifest["mesh"] = {"dp": 4, "sp": 1, "tp": 2}  # ...but wrong mesh
    golden = tmp_path / "manifest.json"
    golden.write_text(json.dumps(manifest))
    code, findings = sc.run_shardcheck(
        str(golden), programs=[prog], baseline_path=None
    )
    assert (code, findings) == (0, [])


def test_unsupported_manifest_version_is_infra_error(env, tmp_path):
    golden = tmp_path / "manifest.json"
    golden.write_text(json.dumps({"version": 99, "programs": {}}))
    code, _ = sc.run_shardcheck(
        str(golden), programs=[_collective_prog(env)], baseline_path=None
    )
    assert code == 2


def test_update_manifest_refuses_partial_audit(env, tmp_path):
    code, _ = sc.run_shardcheck(
        str(tmp_path / "m.json"),
        update_manifest=True,
        programs=[_collective_prog(env)],
        baseline_path=None,
    )
    assert code == 2


# -- suppression + baseline mechanics ----------------------------------------

def test_registration_line_suppression(env, monkeypatch):
    buggy, _ = _buggy_pair(env)
    monkeypatch.setattr(
        sc, "collect_suppressions",
        lambda _src: {buggy.line: {"partial-sum-leak"}},
    )
    code, findings = sc.run_shardcheck(
        None, programs=[buggy], baseline_path=None
    )
    assert (code, findings) == (0, [])
    # Rule-specific: suppressing a different rule leaves the finding live.
    monkeypatch.setattr(
        sc, "collect_suppressions",
        lambda _src: {buggy.line: {"donation-dropped"}},
    )
    code, _ = sc.run_shardcheck(None, programs=[buggy], baseline_path=None)
    assert code == 1


def test_baseline_accepts_existing_findings(env, tmp_path):
    from llmss_tpu.analysis.findings import Baseline

    buggy, _ = _buggy_pair(env)
    code, findings = sc.run_shardcheck(
        None, programs=[buggy], baseline_path=None
    )
    assert code == 1
    baseline = tmp_path / "shardcheck_baseline.json"
    Baseline().write(str(baseline), findings)
    code, findings = sc.run_shardcheck(
        None, programs=[buggy], baseline_path=str(baseline)
    )
    assert (code, findings) == (0, [])


# -- shared executable-signature vocabulary (devtel <-> shardcheck) ----------

def test_devtel_and_shardcheck_share_signature_vocabulary():
    from llmss_tpu.utils import devtel, signatures

    assert devtel.KERNEL_CLASSES is signatures.METERED_CLASSES
    assert set(signatures.METERED_CLASSES) <= set(signatures.KERNEL_CLASSES)
    with pytest.raises(ValueError):
        signatures.signature("warp_drive", 2)


def test_registry_names_are_signature_strs(env):
    from llmss_tpu.utils.signatures import KERNEL_CLASSES

    progs = sc.registry()
    assert len(progs) == len({p.name for p in progs})
    for p in progs:
        kind = p.name.split("/")[0]
        assert kind in KERNEL_CLASSES, p.name


# -- the gate itself (the CI step runs this same audit) ----------------------

@pytest.mark.slow
def test_full_registry_matches_committed_manifest():
    code, findings = sc.run_shardcheck()
    assert code == 0, "\n".join(f.render() for f in findings)
