"""Prefix/session KV reuse: seed rows from a retained segment, prefill
only the suffix, emit IDENTICAL tokens.

The reference re-prefills every request from scratch (``generate.py:99``);
here a shared system prompt / earlier session turn is prefilled once
(``DecodeEngine.build_prefix``) and later requests reuse the device-resident
KV segment — positions, masks, and sampling counters are absolute, so the
emitted tokens are exactly the from-scratch tokens while the shared
prefill's FLOPs and latency are skipped.
"""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from llmss_tpu.engine import DecodeEngine, GenerationParams
from llmss_tpu.engine.scheduler import ContinuousBatcher
from llmss_tpu.parallel import MeshPlan, make_mesh
from tests.test_bucket import _cfg


@pytest.fixture(scope="module")
def setup(devices):
    mesh = make_mesh(MeshPlan(dp=2, tp=4))
    cfg = _cfg()
    params = __import__(
        "llmss_tpu.models.decoder", fromlist=["init_params"]
    ).init_params(cfg, mesh, jax.random.key(0))
    return cfg, params, mesh


PREFIX = [7, 3, 19, 42, 5, 11, 30, 2, 9, 17, 28, 33, 21, 6, 13, 40, 8, 25]


def test_engine_prefix_identical_tokens_and_skipped_prefill(setup):
    cfg, params, mesh = setup
    eng = DecodeEngine(cfg, params, mesh, max_seq_len=64)
    pfx = eng.build_prefix(PREFIX)
    prompts = [PREFIX + [50, 51], PREFIX + [60], PREFIX + [1, 2, 3, 4]]
    for gen in (
        GenerationParams(max_new_tokens=12, is_greedy=True),
        GenerationParams(
            max_new_tokens=12, is_greedy=False, temperature=0.9, top_k=8,
            seed=4,
        ),
    ):
        scratch_run = eng.generate(prompts, gen, chunk_steps=4)

        calls = []
        orig = eng._prefill

        def spy(params, ids, cache, lens, sa, *rest):
            calls.append(ids.shape)
            return orig(params, ids, cache, lens, sa, *rest)

        eng._prefill = spy
        try:
            reused_run = eng.generate(
                prompts, gen, chunk_steps=4, prefix=pfx
            )
        finally:
            eng._prefill = orig
        assert reused_run == scratch_run
        # The suffix prefill padded to the SUFFIX bucket (max suffix 4 ->
        # bucket 16), not the full-prompt bucket (22 -> 32): the prefix's
        # 18 tokens never went through the model again.
        assert calls == [(3, 16)]


def test_engine_prefix_validation(setup):
    cfg, params, mesh = setup
    eng = DecodeEngine(cfg, params, mesh, max_seq_len=64)
    pfx = eng.build_prefix(PREFIX)
    gen = GenerationParams(max_new_tokens=4, is_greedy=True)
    with pytest.raises(ValueError, match="extend the prefix"):
        eng.generate([[1, 2, 3]], gen, prefix=pfx)  # wrong tokens
    with pytest.raises(ValueError, match="extend the prefix"):
        eng.generate([list(PREFIX)], gen, prefix=pfx)  # no suffix
    with pytest.raises(ValueError, match="prefix length"):
        eng.build_prefix([])
    with pytest.raises(ValueError, match="prefix length"):
        eng.build_prefix([1] * 64)
    # prefix + suffix past the ring must raise like the non-prefix path
    # (a wrapped suffix would overwrite the just-seeded prefix slots).
    long_pfx = eng.build_prefix(list(range(1, 61)))
    with pytest.raises(ValueError, match="exceeds"):
        eng.generate(
            [list(range(1, 61)) + [9] * 10], gen, prefix=long_pfx,
        )


def test_scheduler_prefix_identical_tokens(setup):
    """Turn-2-style requests through the continuous batcher, mixed with
    non-prefix requests in the same queue: prefix rows seed from the
    retained segment (their own admission batch) and still emit exactly
    their solo tokens."""
    cfg, params, mesh = setup
    eng = DecodeEngine(cfg, params, mesh, max_seq_len=64)
    pfx = eng.build_prefix(PREFIX)
    gen = GenerationParams(max_new_tokens=10, is_greedy=True)

    p1 = PREFIX + [50, 51]
    p2 = PREFIX + [60]
    plain = [5, 9, 23]
    solo = eng.generate([p1, p2, plain], gen)

    b = ContinuousBatcher(eng, rows=4, chunk_steps=2)
    got = {}
    b.submit(p1, gen, lambda t: got.__setitem__("p1", t), prefix=pfx)
    b.submit(plain, gen, lambda t: got.__setitem__("plain", t))
    b.submit(p2, gen, lambda t: got.__setitem__("p2", t), prefix=pfx)
    b.run_until_idle()
    assert got["p1"] == solo[0]
    assert got["p2"] == solo[1]
    assert got["plain"] == solo[2]


def test_scheduler_prefix_submit_validation(setup):
    cfg, params, mesh = setup
    eng = DecodeEngine(cfg, params, mesh, max_seq_len=64)
    pfx = eng.build_prefix(PREFIX)
    b = ContinuousBatcher(eng, rows=2)
    with pytest.raises(ValueError, match="extend the prefix"):
        b.submit(
            [1, 2], GenerationParams(max_new_tokens=2), lambda t: None,
            prefix=pfx,
        )


def test_prefix_int8_storage_stable(setup):
    """int8 engines retain the prefix quantized; seeding writes the same
    bits every reuse, and generation stays self-consistent."""
    cfg, params, mesh = setup
    eng = DecodeEngine(cfg, params, mesh, max_seq_len=64, kv_dtype="int8")
    pfx = eng.build_prefix(PREFIX)
    assert pfx.k.dtype == jnp.int8 and pfx.k_scale is not None
    gen = GenerationParams(max_new_tokens=8, is_greedy=True)
    prompts = [PREFIX + [50, 51]]
    a = eng.generate(prompts, gen, prefix=pfx)
    bb = eng.generate(prompts, gen, prefix=pfx)
    assert a == bb


def test_serving_prefix_token_ids_end_to_end(setup):
    """The wire-level prefix hint: requests carrying prefix_token_ids
    through broker -> ContinuousWorker produce exactly the tokens of the
    same request without the hint (it is purely an optimization), and the
    worker retains the segment across requests."""
    from llmss_tpu.serve.broker import InProcBroker
    from llmss_tpu.serve.consumer import ContinuousWorker
    from llmss_tpu.serve.protocol import GenerateRequest

    cfg, params, mesh = setup
    eng = DecodeEngine(cfg, params, mesh, max_seq_len=64)
    broker = InProcBroker()
    worker = ContinuousWorker(
        eng, broker, tokenizer=None, rows=2, poll_timeout_s=0.01,
        chunk_steps=2,
    )
    full = PREFIX + [50, 51]

    def serve(req):
        broker.push_request(req)
        import time as _t
        deadline = _t.time() + 120
        while _t.time() < deadline:
            worker.run_once()
            r = broker.wait_response(req.id, timeout=0.001)
            if r is not None:
                return r
        raise TimeoutError

    plain = serve(GenerateRequest(
        id="np", token_ids=full, max_new_tokens=8, is_greedy=True,
    ))
    with_pfx = serve(GenerateRequest(
        id="wp", token_ids=full, max_new_tokens=8, is_greedy=True,
        prefix_token_ids=list(PREFIX),
    ))
    assert plain.error is None and with_pfx.error is None
    assert with_pfx.token_ids == plain.token_ids
    assert len(worker._prefixes) == 1  # segment retained
    # Second request reuses the retained segment (no rebuild).
    again = serve(GenerateRequest(
        id="wp2", token_ids=PREFIX + [60, 61], max_new_tokens=8,
        is_greedy=True, prefix_token_ids=list(PREFIX),
    ))
    assert again.error is None and len(worker._prefixes) == 1

    # Malformed hint -> per-request error, worker stays up.
    bad = serve(GenerateRequest(
        id="bad", token_ids=[1, 2, 3], max_new_tokens=4, is_greedy=True,
        prefix_token_ids=[9, 9],
    ))
    assert bad.error is not None and "prefix" in bad.error


def test_engine_prefix_near_ring_falls_back_to_scratch(setup):
    """Ring-wrap guard: a request that FITS (prompt + max_new <= ring) but
    whose BUCKET-padded suffix would reach past the ring must not seed the
    prefix — padded prefill columns still compute slots
    (slot = position % max_len), so the wrapped columns would overwrite
    the just-seeded prefix KV. The engine falls back to a from-scratch
    full-prompt prefill: identical tokens, no corruption."""
    cfg, params, mesh = setup
    eng = DecodeEngine(cfg, params, mesh, max_seq_len=64)
    long_prefix = list(range(1, 53))  # 52 tokens; suffix bucket 16 wraps
    pfx = eng.build_prefix(long_prefix)
    prompts = [long_prefix + [60, 61, 62, 63]]  # 56 + 6 new <= 64: legal
    gen = GenerationParams(max_new_tokens=6, is_greedy=True)
    scratch_run = eng.generate(prompts, gen, chunk_steps=2)

    calls = []
    orig = eng._prefill

    def spy(params, ids, cache, lens, sa, *rest):
        calls.append(ids.shape)
        return orig(params, ids, cache, lens, sa, *rest)

    eng._prefill = spy
    try:
        reused_run = eng.generate(prompts, gen, chunk_steps=2, prefix=pfx)
    finally:
        eng._prefill = orig
    assert reused_run == scratch_run
    # Fallback is observable: the prefill saw the FULL prompt padded to
    # its own bucket (56 -> 64), not a 16-wide suffix at start=52.
    assert calls == [(1, 64)]


def test_scheduler_prefix_near_ring_admits_without_prefix(setup):
    """The same ring-wrap guard at scheduler admission: a submit whose
    bucket-padded suffix would wrap past the ring is admitted WITHOUT the
    prefix (from-scratch prefill) and still emits its exact solo tokens,
    alongside a safe prefix request that keeps its seeded admission."""
    cfg, params, mesh = setup
    eng = DecodeEngine(cfg, params, mesh, max_seq_len=64)
    long_prefix = list(range(1, 53))
    pfx_long = eng.build_prefix(long_prefix)
    pfx_safe = eng.build_prefix(PREFIX)
    gen = GenerationParams(max_new_tokens=6, is_greedy=True)

    wrap_p = long_prefix + [60, 61, 62, 63]
    safe_p = PREFIX + [50, 51]
    solo = eng.generate([wrap_p, safe_p], gen)

    b = ContinuousBatcher(eng, rows=2, chunk_steps=2)
    got = {}
    b.submit(wrap_p, gen, lambda t: got.__setitem__("wrap", t),
             prefix=pfx_long)
    b.submit(safe_p, gen, lambda t: got.__setitem__("safe", t),
             prefix=pfx_safe)
    b.run_until_idle()
    assert got["wrap"] == solo[0]
    assert got["safe"] == solo[1]
