"""CLI driver end-to-end on a tiny local checkpoint (token-id mode)."""

import numpy as np


def test_cli_generate(tmp_path, devices, capsys):
    import torch
    import transformers as tr

    torch.manual_seed(3)
    cfg = tr.GPT2Config(
        vocab_size=64, n_positions=64, n_embd=32, n_layer=2, n_head=4
    )
    tr.GPT2LMHeadModel(cfg).eval().save_pretrained(
        tmp_path / "m", safe_serialization=True
    )

    from llmss_tpu.cli.generate import main

    out = main([
        "--pretrained_model_path", str(tmp_path / "m"),
        "--token_ids", "1,2,3", "4,5,6,7",
        "--max_new_tokens", "5",
        "--is_greedy",
        "--dtype", "float32",
        "--tp", "4", "--dp", "2",
    ])
    assert len(out) == 2
    assert all(len(o) == 5 for o in out)
    captured = capsys.readouterr().out
    assert "continuation ids" in captured
    assert "ttft" in captured


def test_cli_generate_speculative(tmp_path, devices, capsys):
    import torch
    import transformers as tr

    torch.manual_seed(3)
    cfg = tr.GPT2Config(
        vocab_size=64, n_positions=64, n_embd=32, n_layer=2, n_head=4
    )
    tr.GPT2LMHeadModel(cfg).eval().save_pretrained(
        tmp_path / "m", safe_serialization=True
    )

    from llmss_tpu.cli.generate import main

    common = [
        "--pretrained_model_path", str(tmp_path / "m"),
        "--token_ids", "1,2,3", "4,5,6,7",
        "--max_new_tokens", "8",
        "--is_greedy",
        "--dtype", "float32",
        "--tp", "4", "--dp", "2",
        "--max_seq_len", "64",
    ]
    plain = main(common)
    spec = main(common + ["--speculative", "3"])
    assert spec == plain  # same kernels on CPU -> token-identical
    captured = capsys.readouterr().out
    assert "speculation:" in captured
