"""SLO-class scheduling, paged-KV preemption, and brownout (ISSUE 14).

Pins the graceful-degradation contract end to end:

- Brokers drain class queues in strict priority order, and the
  preemption requeue path mirrors ``release_requests`` refund semantics:
  a request evicted N times for higher-priority work never inches toward
  the DLQ (both ``InProcBroker`` and ``RedisBroker``-over-``FakeRedis``).
- The scheduler's eviction + chunked-prefill resume is loss-free and
  stream-identical: a preempted greedy request's final tokens equal the
  never-preempted run, COW prefix refcounts balance, and the warmed
  engine keys zero new compiles with preemption active (CompileGuard).
- A worker killed while holding a preempted-but-not-yet-resumed request
  still yields exactly one terminal response with the full stream.
- The brownout ladder degrades batch before standard before interactive
  (interactive is never shed), with dual-threshold + dwell hysteresis,
  and both producer frontends surface it via 429 + Retry-After.
"""

import threading
import time

import pytest

from llmss_tpu.engine import DecodeEngine, GenerationParams
from llmss_tpu.engine.scheduler import ContinuousBatcher
from llmss_tpu.models.common import DecoderConfig
from llmss_tpu.models.decoder import init_params
from llmss_tpu.parallel import MeshPlan, make_mesh
from llmss_tpu.serve.broker import InProcBroker, RedisBroker
from llmss_tpu.serve.chaos import FakeRedis, ScriptedEngine
from llmss_tpu.serve.consumer import ContinuousWorker, Worker
from llmss_tpu.serve.fleet import BrownoutController, interactive_burn
from llmss_tpu.serve.producer import ProducerServer, admission_verdict
from llmss_tpu.sim.invariants import audit_exactly_once, collect_responses
from llmss_tpu.serve.protocol import (
    SLO_CLASS_BATCH,
    SLO_CLASS_INTERACTIVE,
    SLO_CLASS_STANDARD,
    GenerateRequest,
    GenerateResponse,
)
from llmss_tpu.utils import metrics as metrics_mod
from llmss_tpu.utils import trace


def make_broker(kind, **kw):
    if kind == "inproc":
        return InProcBroker(**kw)
    return RedisBroker(client=FakeRedis(), worker_id="w0", **kw)


BROKERS = ("inproc", "fakeredis")


# -- protocol ----------------------------------------------------------------


def test_request_validates_slo_class_and_resume():
    GenerateRequest(token_ids=[1], slo_class=SLO_CLASS_BATCH).validate()
    with pytest.raises(ValueError):
        GenerateRequest(token_ids=[1], slo_class="vip").validate()
    with pytest.raises(ValueError):
        GenerateRequest(
            token_ids=[1], max_new_tokens=2, resume_tokens=[5, 6],
        ).validate()  # resume must leave >= 1 token to generate


# -- broker: class queues + preemption refund --------------------------------


@pytest.mark.parametrize("kind", BROKERS)
def test_class_queues_drain_in_priority_order(kind):
    b = make_broker(kind)
    b.push_request(GenerateRequest(
        id="b1", token_ids=[1], slo_class=SLO_CLASS_BATCH))
    b.push_request(GenerateRequest(
        id="s1", token_ids=[1], slo_class=SLO_CLASS_STANDARD))
    b.push_request(GenerateRequest(
        id="i1", token_ids=[1], slo_class=SLO_CLASS_INTERACTIVE))
    assert b.queue_depths_by_class() == {
        SLO_CLASS_INTERACTIVE: 1, SLO_CLASS_STANDARD: 1, SLO_CLASS_BATCH: 1,
    }
    assert b.queue_depth() == 3
    assert [b.pop_request().id for _ in range(3)] == ["i1", "s1", "b1"]


@pytest.mark.parametrize("kind", BROKERS)
def test_preempt_refunds_attempt_and_never_dlqs(kind):
    b = make_broker(kind, lease_s=30.0, max_delivery_attempts=2)
    b.push_request(GenerateRequest(id="r1", token_ids=[1], max_new_tokens=8))
    for i in range(5):
        req = b.pop_request()
        # The refund means every re-lease is attempt 1 — N preemptions
        # never approach max_delivery_attempts.
        assert req.id == "r1" and req.delivery_attempts == 1, i
        req.resume_tokens = list(range(i + 1))
        req.preemptions += 1
        assert b.preempt_requests([req]) == 1
    assert b.dlq_depth() == 0
    assert b.delivery_stats()["preempted"] == 5
    req = b.pop_request()
    assert req.preemptions == 5 and req.resume_tokens == [0, 1, 2, 3, 4]
    b.push_response(GenerateResponse(id="r1", token_ids=[2]))
    assert b.wait_response("r1", timeout=1).token_ids == [2]
    assert b.wait_response("r1", timeout=0.1) is None  # exactly one


@pytest.mark.parametrize("kind", BROKERS)
def test_preempted_request_requeues_at_class_head(kind):
    b = make_broker(kind)
    b.push_request(GenerateRequest(id="s1", token_ids=[1]))
    b.push_request(GenerateRequest(id="s2", token_ids=[1]))
    req = b.pop_request()
    assert req.id == "s1"
    b.preempt_requests([req])
    # Oldest work in its class: s1 resumes before s2 is started.
    assert b.pop_request().id == "s1"
    assert b.pop_request().id == "s2"


@pytest.mark.parametrize("kind", BROKERS)
def test_preempt_unleased_request_is_noop(kind):
    b = make_broker(kind)
    # Lease already reaped / request settled: the stale preempt loses.
    assert b.preempt_requests(
        [GenerateRequest(id="ghost", token_ids=[1])]
    ) == 0
    assert b.queue_depth() == 0
    assert b.delivery_stats().get("preempted", 0) == 0


@pytest.mark.parametrize("kind", BROKERS)
def test_kill_holding_preempted_request_one_terminal(kind):
    """Worker A preempts a request (refund + requeue) and dies before it
    resumes anywhere; worker B leases it and must produce exactly one
    terminal response with the full unpreempted stream."""
    if kind == "inproc":
        b = InProcBroker(lease_s=0.05)
        wb = b
    else:
        server = FakeRedis()
        b = RedisBroker(client=server, worker_id="prod", lease_s=0.05)
        wb = RedisBroker(client=server, worker_id="w1", lease_s=0.05)
    prompt = [7, 11]
    b.push_request(GenerateRequest(
        id="r1", token_ids=list(prompt), max_new_tokens=4,
        slo_class=SLO_CLASS_INTERACTIVE,
    ))
    full = ScriptedEngine.expected_tokens(prompt, 4)

    # Worker A: leases, makes partial progress, preempts, dies (no ack,
    # no abort — the broker object is simply abandoned).
    req = b.pop_request()
    req.resume_tokens = full[:2]
    req.preemptions += 1
    assert b.preempt_requests([req]) == 1

    # Worker B resumes: replays the emitted tokens, continues the stream.
    w = Worker(
        ScriptedEngine(), wb, batch_size=1, poll_timeout_s=0.01,
        pad_batch=False,
    )
    w.run_once()
    resp = b.wait_response("r1", timeout=2)
    assert resp is not None and resp.error is None
    assert resp.token_ids == full  # zero lost, zero duplicated tokens
    assert b.wait_response("r1", timeout=0.2) is None  # exactly one
    time.sleep(0.06)
    assert b.reap_expired() == 0  # settled: nothing left to redeliver
    assert b.dlq_depth() == 0


# -- scheduler: eviction + chunked-prefill resume ----------------------------


def _cfg(**kw):
    base = dict(
        model_type="llama", vocab_size=64, hidden_size=32, n_layers=2,
        n_heads=4, n_kv_heads=2, head_dim=8, intermediate_size=64,
        max_position_embeddings=64, activation="silu", norm="rmsnorm",
        norm_eps=1e-5, mlp="swiglu", positions="rotary", rope_style="half",
        rotary_dim=8, attn_bias=False, mlp_bias=False,
        tie_word_embeddings=False, dtype="float32",
    )
    base.update(kw)
    return DecoderConfig(**base)


@pytest.fixture(scope="module")
def setup(devices):
    import jax

    cfg = _cfg()
    mesh = make_mesh(MeshPlan(dp=2, tp=4))
    params = init_params(cfg, mesh, jax.random.key(0))
    return cfg, mesh, params


@pytest.fixture(scope="module")
def dense_engine(setup):
    cfg, mesh, params = setup
    return DecodeEngine(cfg, params, mesh, max_seq_len=64)


def _cb_into(got, key):
    def cb(toks, cancelled=False, error=None):
        got[key] = list(toks)
    return cb


def _preempt_cycle(batcher, dense_got, *, p_low, p_hi, gen_low, gen_hi):
    """Run one evict-and-resume cycle: low-priority request mid-decode,
    interactive arrival forces the eviction, low resumes with its emitted
    tokens replayed. Returns the evicted-token count."""
    evicted = {}
    batcher.preempt_cb = (
        lambda rid, toks: evicted.__setitem__(rid, list(toks))
    )
    batcher.submit(
        p_low, gen_low, _cb_into(dense_got, "low"), req_id="low",
        priority=2,
    )
    for _ in range(3):  # low is mid-decode (first token resolved)
        batcher.step()
    batcher.submit(
        p_hi, gen_hi, _cb_into(dense_got, "hi"), req_id="hi", priority=0,
    )
    batcher.step()  # eviction frees the slot that admits "hi"
    assert "low" in evicted, "interactive arrival did not preempt"
    toks = evicted["low"]
    assert 0 < len(toks) < gen_low.max_new_tokens
    # Resume exactly as the consumer does: prompt + emitted tokens, the
    # remaining budget, and replayed= so the stream is not re-emitted.
    batcher.submit(
        p_low + toks,
        GenerationParams(
            max_new_tokens=gen_low.max_new_tokens - len(toks),
            is_greedy=True,
        ),
        _cb_into(dense_got, "low"), req_id="low", priority=2,
        replayed=len(toks),
    )
    batcher.run_until_idle()
    return len(toks)


def test_preempt_resume_stream_identical(dense_engine):
    """The acceptance assertion: a preempted greedy request's final token
    stream equals the unpreempted run exactly."""
    gen_low = GenerationParams(max_new_tokens=12, is_greedy=True)
    gen_hi = GenerationParams(max_new_tokens=4, is_greedy=True)
    p_low, p_hi = [1, 2, 3], [9, 8, 7]
    exp_low = dense_engine.generate([p_low], gen_low)[0]
    exp_hi = dense_engine.generate([p_hi], gen_hi)[0]

    before = dense_engine.metrics.preempted
    b = ContinuousBatcher(dense_engine, rows=1)
    got = {}
    n_evicted = _preempt_cycle(
        b, got, p_low=p_low, p_hi=p_hi, gen_low=gen_low, gen_hi=gen_hi,
    )
    assert dense_engine.metrics.preempted == before + 1
    assert got["hi"] == exp_hi
    assert got["low"] == exp_low, (n_evicted, got["low"], exp_low)


def test_preempt_without_cb_is_disabled(dense_engine):
    """preempt_cb=None (FIFO deployments): an interactive arrival behind
    a busy batcher waits its turn — nothing is evicted."""
    before = dense_engine.metrics.preempted
    b = ContinuousBatcher(dense_engine, rows=1)
    got = {}
    gen = GenerationParams(max_new_tokens=6, is_greedy=True)
    b.submit([1, 2], gen, _cb_into(got, "low"), req_id="low", priority=2)
    for _ in range(3):
        b.step()
    b.submit([3, 4], gen, _cb_into(got, "hi"), req_id="hi", priority=0)
    b.run_until_idle()
    assert dense_engine.metrics.preempted == before
    assert len(got["low"]) == 6 and len(got["hi"]) == 6


def test_preempt_paged_cow_refcounts_balance(setup, dense_engine):
    """Evicting a row that shares a COW prefix releases its owned blocks
    and decrefs the shared ones; the resume re-increfs them. After the
    dust settles only the prefix registry's block remains — refcounts
    balance and the streams are still bit-identical to dense."""
    cfg, mesh, params = setup
    eng = DecodeEngine(
        cfg, params, mesh, max_seq_len=64, kv_layout="paged",
        block_size=16, kv_blocks=4,
    )
    pfx_tokens = list(range(1, 21))  # 1 shared full block + partial tail
    pfx = eng.build_prefix(pfx_tokens)
    gen_low = GenerationParams(max_new_tokens=10, is_greedy=True)
    gen_hi = GenerationParams(max_new_tokens=45, is_greedy=True)
    p_low = pfx_tokens + [30]
    p_hi = [40, 41, 42]  # 3+45=48 tokens -> 3 blocks: exceeds the free 2
    exp_low = dense_engine.generate([p_low], gen_low)[0]
    exp_hi = dense_engine.generate([p_hi], gen_hi)[0]

    b = ContinuousBatcher(eng, rows=2)
    evicted = {}
    b.preempt_cb = lambda rid, toks: evicted.__setitem__(rid, list(toks))
    got = {}
    b.submit(
        p_low, gen_low, _cb_into(got, "low"), req_id="low", prefix=pfx,
        priority=2,
    )
    for _ in range(3):
        b.step()
    assert b.allocator.blocks_in_use == 2  # 1 shared (registry) + 1 owned
    b.submit(
        p_hi, gen_hi, _cb_into(got, "hi"), req_id="hi", priority=0,
    )
    b.step()  # block-pool pressure (not row pressure) forces the evict
    assert "low" in evicted
    toks = evicted["low"]
    b.submit(
        p_low + toks,
        GenerationParams(
            max_new_tokens=gen_low.max_new_tokens - len(toks),
            is_greedy=True,
        ),
        _cb_into(got, "low"), req_id="low", prefix=pfx, priority=2,
        replayed=len(toks),
    )
    b.run_until_idle()
    assert got["hi"] == exp_hi
    assert got["low"] == exp_low
    # Balance: every evict/resume incref-decref pair cancelled; only the
    # prefix registry's shared block is still held.
    assert b.allocator.blocks_in_use == 1
    assert eng.metrics.to_dict()["kv_blocks_in_use"] == 1
    assert eng.metrics.preempted == 1


def test_no_steady_state_recompiles_with_preemption(dense_engine):
    """A warmed batcher running the full evict + chunked-replay-resume
    cycle — with a brownout controller ticking on the side — must never
    key a fresh compile: eviction is host bookkeeping and a resumed row
    admits through the same padded-prefill programs as any admission."""
    from llmss_tpu.analysis import CompileGuard

    gen_low = GenerationParams(max_new_tokens=12, is_greedy=True)
    gen_hi = GenerationParams(max_new_tokens=4, is_greedy=True)
    p_low, p_hi = [1, 2, 3], [9, 8, 7]

    def cycle():
        b = ContinuousBatcher(dense_engine, rows=1)
        got = {}
        _preempt_cycle(
            b, got, p_low=p_low, p_hi=p_hi, gen_low=gen_low, gen_hi=gen_hi,
        )
        return got

    cycle()  # warmup: compiles are expected here
    ctrl = BrownoutController(lambda: 0.0, check_s=0.0)
    guard = CompileGuard.for_engine(dense_engine)
    assert guard._fns, "engine exposes no jitted callables to guard"
    with guard.steady_state():
        got = cycle()
        ctrl.tick()
        assert len(got) == 2


def test_continuous_worker_preempt_roundtrip(dense_engine):
    """End-to-end through the broker: a batch-class request mid-decode is
    preempted by an interactive arrival, refunded to the broker with its
    resume point, re-leased, and finishes with the exact unpreempted
    greedy stream."""
    gen_low = GenerationParams(max_new_tokens=20, is_greedy=True)
    gen_hi = GenerationParams(max_new_tokens=4, is_greedy=True)
    p_low, p_hi = [2, 4, 6], [5, 3, 1]
    exp_low = dense_engine.generate([p_low], gen_low)[0]
    exp_hi = dense_engine.generate([p_hi], gen_hi)[0]

    broker = InProcBroker()
    worker = ContinuousWorker(
        dense_engine, broker, rows=1, poll_timeout_s=0.01,
    )
    stop = threading.Event()
    t = threading.Thread(
        target=worker.run_forever, args=(stop,), daemon=True,
    )
    t.start()
    try:
        low = GenerateRequest(
            id="low", token_ids=list(p_low), max_new_tokens=20,
            is_greedy=True, slo_class=SLO_CLASS_BATCH,
        )
        broker.push_request(low)
        # Wait until low is actually decoding before the interactive
        # request arrives, so the eviction path (not queue order) serves
        # the priority.
        deadline = time.monotonic() + 30
        while time.monotonic() < deadline:
            if broker.delivery_stats()["inflight"] >= 1:
                break
            time.sleep(0.005)
        time.sleep(0.1)  # a few decode chunks of progress
        hi = GenerateRequest(
            id="hi", token_ids=list(p_hi), max_new_tokens=4,
            is_greedy=True, slo_class=SLO_CLASS_INTERACTIVE,
        )
        broker.push_request(hi)
        results = collect_responses(broker, [hi, low], timeout_s=60.0)
    finally:
        stop.set()
        t.join(timeout=10)
    # Shared sim/serve audit against the real engine's solo greedy
    # streams: both answered exactly once, preemption did not perturb
    # a single token.
    exp = {"hi": exp_hi, "low": exp_low}
    assert audit_exactly_once(
        [hi, low], results, expected_tokens=lambda r: exp[r.id],
    ) == 2
    assert broker.delivery_stats()["preempted"] >= 1
    assert broker.dlq_depth() == 0


# -- brownout controller -----------------------------------------------------


def _forced(rung, **kw):
    """A controller pinned at ``rung``: escalations are driven with
    explicit far-future ticks, then the huge check interval time-gates
    every real-time tick so admissions see a constant rung."""
    kw.setdefault("check_s", 1e9)
    ctrl = BrownoutController(lambda: 99.0, **kw)
    for i in range(rung):
        ctrl.tick(now=(i + 1) * 4e9)
    assert ctrl.state()["brownout_state"] == rung
    return ctrl


def test_brownout_ladder_escalates_and_recovers():
    burn = [9.0]
    ctrl = BrownoutController(
        lambda: burn[0], high=2.0, low=1.0, dwell_s=2.0, check_s=0.0,
    )
    names = BrownoutController.LADDER
    # One rung per check while burning hot; clamps at the top.
    assert [ctrl.tick(now=t) for t in (1, 2, 3, 4)] == [1, 2, 3, 3]
    assert ctrl.state()["state"] == names[3]
    # Cool: de-escalation waits out the dwell from the last hot reading
    # (t=4), then walks down one rung per check.
    burn[0] = 0.1
    assert ctrl.tick(now=5) == 3  # only 1s cool < dwell 2s
    assert [ctrl.tick(now=t) for t in (6, 7, 8, 9)] == [2, 1, 0, 0]
    st = ctrl.state()
    assert st["state"] == "normal" and st["transitions_total"] == 6
    assert len(st["recent_transitions"]) == 6


def test_brownout_hysteresis_no_flapping():
    """Burn between low and high: never escalates, and keeps refreshing
    the dwell clock so it never de-escalates either."""
    burn = [9.0]
    ctrl = BrownoutController(
        lambda: burn[0], high=2.0, low=1.0, dwell_s=2.0, check_s=0.0,
    )
    assert ctrl.tick(now=1) == 1
    burn[0] = 1.5  # hot enough to hold, not hot enough to climb
    assert [ctrl.tick(now=t) for t in (2, 3, 4, 5, 6)] == [1] * 5
    burn[0] = 0.1
    assert ctrl.tick(now=7) == 1  # dwell not yet served (last hot t=6)
    assert ctrl.tick(now=8.5) == 0


def test_brownout_admit_order_batch_standard_interactive():
    """The ladder's whole point: batch degrades before standard, and
    interactive is admitted at EVERY rung."""
    def reqs():
        return {
            cls: GenerateRequest(
                token_ids=[1], max_new_tokens=500, slo_class=cls,
            )
            for cls in (
                SLO_CLASS_INTERACTIVE, SLO_CLASS_STANDARD, SLO_CLASS_BATCH,
            )
        }

    r = reqs()
    ctrl = _forced(1, batch_max_new_cap=64, retry_after_s=3)
    assert ctrl.admit(r[SLO_CLASS_INTERACTIVE]) == (True, None)
    assert ctrl.admit(r[SLO_CLASS_STANDARD]) == (True, None)
    assert ctrl.admit(r[SLO_CLASS_BATCH]) == (True, None)
    assert r[SLO_CLASS_BATCH].max_new_tokens == 64  # capped in place
    assert r[SLO_CLASS_STANDARD].max_new_tokens == 500

    r = reqs()
    ctrl = _forced(2, retry_after_s=3)
    assert ctrl.admit(r[SLO_CLASS_BATCH]) == (False, 3)
    assert ctrl.admit(r[SLO_CLASS_STANDARD]) == (True, None)
    assert ctrl.admit(r[SLO_CLASS_INTERACTIVE]) == (True, None)

    r = reqs()
    ctrl = _forced(3, retry_after_s=3)
    assert ctrl.admit(r[SLO_CLASS_BATCH]) == (False, 3)
    assert ctrl.admit(r[SLO_CLASS_STANDARD]) == (False, 3)
    assert ctrl.admit(r[SLO_CLASS_INTERACTIVE]) == (True, None)


def test_interactive_burn_reads_slo_payload():
    payload = {"objectives": [
        {"name": "e2e_p95_5s", "windows": {
            "5m": {"burn_rate": 50.0, "count": 9}}},
        {"name": "ttft_p95_500ms", "windows": {
            "5m": {"burn_rate": 1.0, "count": 9}}},
        {"name": "ttft_p95_500ms_interactive", "windows": {
            "5m": {"burn_rate": 4.0, "count": 3},
            "1h": {"burn_rate": 2.0, "count": 3},
        }},
    ]}
    # Prefers the interactive-class objective; takes the worst window.
    assert interactive_burn(payload) == 4.0
    # Windows with no observations are not alerts.
    assert interactive_burn({"objectives": [
        {"name": "ttft_p95_500ms_interactive", "windows": {
            "5m": {"burn_rate": None, "count": 0}}},
    ]}) == 0.0
    # Falls back to the base TTFT objective when no per-class series.
    assert interactive_burn({"objectives": [
        {"name": "ttft_p95_500ms", "windows": {
            "5m": {"burn_rate": 1.5, "count": 2}}},
    ]}) == 1.5
    assert interactive_burn({}) == 0.0  # empty fleet reads healthy


# -- producer admission ------------------------------------------------------


def test_admission_verdict_class_depth_fraction():
    b = InProcBroker()
    b.push_request(GenerateRequest(id="old", token_ids=[1]))
    # depth 1 vs max 2: batch's 0.5 fraction sheds at depth 1, while
    # standard and interactive still have headroom.
    batch = GenerateRequest(
        token_ids=[1], slo_class=SLO_CLASS_BATCH)
    verdict = admission_verdict(batch, b, 2)
    assert verdict is not None and verdict[0] == 429
    assert verdict[2]["Retry-After"] == "1"
    for cls in (SLO_CLASS_STANDARD, SLO_CLASS_INTERACTIVE):
        req = GenerateRequest(token_ids=[1], slo_class=cls)
        assert admission_verdict(req, b, 2) is None


def _post(port, path, payload):
    import http.client
    import json

    conn = http.client.HTTPConnection("127.0.0.1", port, timeout=10)
    conn.request("POST", path, json.dumps(payload),
                 {"Content-Type": "application/json"})
    r = conn.getresponse()
    body = json.loads(r.read() or b"{}")
    headers = dict(r.getheaders())
    conn.close()
    return r.status, body, headers


def _get(port, path):
    import http.client
    import json

    conn = http.client.HTTPConnection("127.0.0.1", port, timeout=10)
    conn.request("GET", path)
    r = conn.getresponse()
    body = json.loads(r.read() or b"{}")
    conn.close()
    return r.status, body


def _answered(broker):
    """A stub worker that answers the next queued request."""
    def run():
        req = broker.pop_request(timeout=5)
        if req is not None:
            broker.push_response(
                GenerateResponse(id=req.id, token_ids=[1]))
    t = threading.Thread(target=run, daemon=True)
    t.start()
    return t


def test_producer_brownout_sheds_batch_then_standard_never_interactive():
    b = InProcBroker()
    srv = ProducerServer(
        b, host="127.0.0.1", port=0, timeout_s=5.0, brownout=_forced(2),
    )
    srv.start()
    try:
        status, body, headers = _post(srv.port, "/generate", {
            "token_ids": [1], "max_new_tokens": 2,
            "slo_class": SLO_CLASS_BATCH,
        })
        assert status == 429
        assert "brownout" in body["error"]
        assert body["brownout_state"] == "shed-batch"
        # Dwell-derived: the ladder cannot de-escalate sooner than its
        # dwell, so that is the honest earliest-retry hint.
        assert headers.get("Retry-After") == "5"
        assert b.queue_depth() == 0  # shed before queueing

        _answered(b)
        status, _body, _h = _post(srv.port, "/generate", {
            "token_ids": [1], "max_new_tokens": 2,
            "slo_class": SLO_CLASS_STANDARD,
        })
        assert status == 200  # standard survives rung 2

        # Observability: /metrics and /fleet both carry the ladder state
        # and the per-class queue depths (closed enum labels).
        status, m = _get(srv.port, "/metrics")
        assert m["brownout"]["state"] == "shed-batch"
        assert m["brownout"]["brownout_state"] == 2
        assert set(m["queue_depths_by_class"]) == {
            SLO_CLASS_INTERACTIVE, SLO_CLASS_STANDARD, SLO_CLASS_BATCH,
        }
        assert m["delivery"]["preempted"] == 0
        status, f = _get(srv.port, "/fleet")
        assert f["brownout"]["state"] == "shed-batch"
    finally:
        srv.stop()

    srv = ProducerServer(
        b, host="127.0.0.1", port=0, timeout_s=5.0, brownout=_forced(3),
    )
    srv.start()
    try:
        status, body, _h = _post(srv.port, "/generate", {
            "token_ids": [1], "max_new_tokens": 2,
            "slo_class": SLO_CLASS_STANDARD,
        })
        assert status == 429 and body["brownout_state"] == "shed-standard"
        _answered(b)
        status, _body, _h = _post(srv.port, "/generate", {
            "token_ids": [1], "max_new_tokens": 2,
            "slo_class": SLO_CLASS_INTERACTIVE,
        })
        assert status == 200  # interactive admitted at the last rung
    finally:
        srv.stop()


# -- SLO plane: per-class series + preemption cost flow ----------------------


def test_request_cost_carries_class_and_preemptions():
    t0 = 100.0
    events = [
        {"req_id": "r", "name": "enqueue", "t": t0,
         "attrs": {"plen": 2, "max_new": 4,
                   "slo_class": SLO_CLASS_INTERACTIVE}},
        {"req_id": "r", "name": "lease", "t": t0 + 0.01, "attrs": {}},
        {"req_id": "r", "name": "admit", "t": t0 + 0.02, "attrs": {}},
        {"req_id": "r", "name": "preempt", "t": t0 + 0.03,
         "attrs": {"slo_class": SLO_CLASS_INTERACTIVE, "preemptions": 1}},
        {"req_id": "r", "name": "lease", "t": t0 + 0.04, "attrs": {}},
        {"req_id": "r", "name": "admit", "t": t0 + 0.05, "attrs": {}},
        {"req_id": "r", "name": "respond", "t": t0 + 0.06,
         "attrs": {"ok": True, "n_tokens": 4}},
    ]
    cost = trace.request_cost(events, assume_sorted=True)
    assert cost["slo_class"] == SLO_CLASS_INTERACTIVE
    assert cost["preemptions"] == 1
    # TTFT anchors to the FIRST admit — preemption doesn't reset it.
    assert round(cost["ttft_s"], 3) == 0.02


def test_observe_request_cost_feeds_per_class_slo():
    reg = metrics_mod.SeriesRegistry(proc="t-priority")
    metrics_mod.observe_request_cost({
        "ok": True, "total_s": 0.3, "ttft_s": 0.1, "tokens": 4,
        "preemptions": 2, "slo_class": SLO_CLASS_INTERACTIVE,
    }, registry=reg)
    metrics_mod.observe_request_cost({
        "ok": True, "total_s": 2.0, "ttft_s": 1.8, "tokens": 4,
        "preemptions": 0, "slo_class": SLO_CLASS_BATCH,
    }, registry=reg)
    names = reg.names()
    assert "ttft_s_interactive" in names and "ttft_s_batch" in names
    assert "preemptions_total" in names
    assert reg.counter("preemptions_total").total == 2.0

    out = metrics_mod.evaluate_slos([reg.export()])
    rows = {r["name"]: r for r in out["objectives"]}
    inter = rows["ttft_p95_500ms_interactive"]["windows"]["5m"]
    assert inter["count"] == 1 and inter["attainment"] == 1.0
    # The batch request's slow TTFT burns only the batch objective.
    assert rows["ttft_p95_2s_standard"]["windows"]["5m"]["count"] == 0
    assert interactive_burn(out) == 0.0


def test_workload_export_carries_slo_class():
    b = InProcBroker()
    b.push_request(GenerateRequest(
        id="wa", token_ids=[1, 2], max_new_tokens=3,
        slo_class=SLO_CLASS_INTERACTIVE,
    ))
    b.pop_request()
    b.push_response(GenerateResponse(id="wa", token_ids=[5, 6, 7]))
    wl = trace.export_workload([trace.recorder().export()])
    rows = {r["req_id"]: r for r in wl["requests"]}
    assert rows["wa"]["slo_class"] == SLO_CLASS_INTERACTIVE

    # replay restores the class onto the synthesized request
    import importlib
    import os
    import sys
    sys.path.insert(0, os.path.join(os.path.dirname(__file__), ".."))
    tw = importlib.import_module("tools.trace_workload")
    req = tw.synthesize_request(rows["wa"])
    assert req.slo_class == SLO_CLASS_INTERACTIVE
    # legacy "priority" key still restores the class
    req = tw.synthesize_request({
        "req_id": "x", "prompt_len": 2, "max_new_tokens": 2,
        "priority": SLO_CLASS_BATCH,
    })
    assert req.slo_class == SLO_CLASS_BATCH
