"""Paged KV cache: block pool, paged==dense token equivalence, COW prefixes.

The contract everything here pins down: ``kv_layout="paged"`` is a pure
*layout* change. The block pool with per-row tables must produce
**bit-identical tokens** to the dense ring on every path — greedy and
sampled, GQA and MQA, int8 KV, full-capacity generation, continuous
batching with cancellation, and shared-prefix copy-on-write — while
admitting by block-pool capacity instead of row count and never copying
shared prefix blocks per row (asserted through ``kv_blocks_in_use``).
"""

import importlib

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from llmss_tpu.engine import DecodeEngine, GenerationParams
from llmss_tpu.engine.cache import (
    BlockAllocator, gather_block_view, init_paged_cache,
    logical_to_physical, paged_write_stacked, table_sentinel,
)
from llmss_tpu.engine.scheduler import ContinuousBatcher
from llmss_tpu.models.common import DecoderConfig
from llmss_tpu.models.decoder import init_params
from llmss_tpu.parallel import MeshPlan, make_mesh


def _cfg(n_kv_heads=2, **kw):
    base = dict(
        model_type="llama", vocab_size=64, hidden_size=32, n_layers=2,
        n_heads=4, n_kv_heads=n_kv_heads, head_dim=8, intermediate_size=64,
        max_position_embeddings=64, activation="silu", norm="rmsnorm",
        norm_eps=1e-5, mlp="swiglu", positions="rotary", rope_style="half",
        rotary_dim=8, attn_bias=False, mlp_bias=False,
        tie_word_embeddings=False, dtype="float32",
    )
    base.update(kw)
    return DecoderConfig(**base)


@pytest.fixture(scope="module")
def setup(devices):
    cfg = _cfg()
    mesh = make_mesh(MeshPlan(dp=2, tp=4))
    params = init_params(cfg, mesh, jax.random.key(0))
    return cfg, mesh, params


@pytest.fixture(scope="module")
def dense_engine(setup):
    cfg, mesh, params = setup
    return DecodeEngine(cfg, params, mesh, max_seq_len=64)


@pytest.fixture(scope="module")
def paged_engine(setup):
    cfg, mesh, params = setup
    return DecodeEngine(
        cfg, params, mesh, max_seq_len=64, kv_layout="paged", block_size=16,
    )


# -- host allocator ---------------------------------------------------------


def test_allocator_alloc_free_refcount():
    a = BlockAllocator(4)
    assert a.free_blocks == 4 and a.blocks_in_use == 0
    got = a.alloc(3)
    assert len(got) == 3 and a.blocks_in_use == 3
    # Never-partial: a too-big request returns None and takes nothing.
    assert a.alloc(2) is None
    assert a.free_blocks == 1
    # Shared blocks: refcount 2 survives one free.
    a.incref([got[0]])
    assert a.refcount(got[0]) == 2
    assert a.free(got) == 2  # got[0] NOT released yet
    assert a.refcount(got[0]) == 1
    assert a.free([got[0]]) == 1
    assert a.free_blocks == 4 and a.blocks_in_use == 0
    a.record_evictions(2)
    assert a.evictions == 2


def test_allocator_rejects_negative():
    with pytest.raises(ValueError):
        BlockAllocator(2).alloc(-1)


# -- device layout primitives ----------------------------------------------


def test_logical_to_physical_oob_sentinel():
    """Logical slots past the table's reach must map to a POSITIVE OOB
    physical block (scatter mode='drop' drops it): take_along_axis CLAMPS
    its index, so without the explicit where() an OOB slot would silently
    hit the row's last real block."""
    tables = jnp.asarray([[3, 1], [2, 0]], jnp.int32)  # MB=2, bs=4
    slots = jnp.asarray([[0, 5, 8], [7, 9, 100]], jnp.int32)
    blk, off = logical_to_physical(tables, slots, 4)
    blk, off = np.asarray(blk), np.asarray(off)
    big = np.iinfo(np.int32).max
    np.testing.assert_array_equal(blk, [[3, 1, big], [0, big, big]])
    np.testing.assert_array_equal(off[:, :2], [[0, 1], [3, 1]])


def test_gather_view_matches_identity_pool_and_write_roundtrip(devices):
    """With identity tables the gathered logical view IS the dense ring
    (same values, same slot order), and a paged token scatter lands at
    exactly (slot // bs, slot % bs) of the row's table."""
    mesh = make_mesh(MeshPlan(dp=2, tp=4))
    cache = init_paged_cache(
        mesh, n_layers=2, batch=2, max_len=32, n_kv_heads=4, head_dim=8,
        dtype=jnp.float32, block_size=8,
    )
    rng = np.random.default_rng(0)
    pool = jnp.asarray(
        rng.standard_normal(cache.k.shape), jnp.float32
    )
    view = gather_block_view(pool[0], cache.block_tables)
    # identity tables: row b's blocks are [b*MB, (b+1)*MB)
    want = pool[0].reshape(2, 32, 4, 8)
    np.testing.assert_array_equal(np.asarray(view), np.asarray(want))

    tok = jnp.asarray(rng.standard_normal((2, 2, 1, 4, 8)), jnp.float32)
    slots = jnp.asarray([[9], [30]], jnp.int32)
    new_pool = paged_write_stacked(
        pool, tok, cache.block_tables, slots, cache.block_size
    )
    got = gather_block_view(new_pool[0], cache.block_tables)
    np.testing.assert_array_equal(
        np.asarray(got[0, 9]), np.asarray(tok[0, 0, 0])
    )
    np.testing.assert_array_equal(
        np.asarray(got[1, 30]), np.asarray(tok[0, 1, 0])
    )
    # sentinel tables drop the write entirely
    sent = jnp.full_like(cache.block_tables, table_sentinel(8))
    dropped = paged_write_stacked(pool, tok, sent, slots, cache.block_size)
    np.testing.assert_array_equal(np.asarray(dropped), np.asarray(pool))


# -- engine-level equivalence ----------------------------------------------

PROMPTS = [[5, 9, 23, 40], [3, 14, 15, 9, 26, 5]]


def test_engine_greedy_and_fused_match_dense(dense_engine, paged_engine):
    gen = GenerationParams(max_new_tokens=8, is_greedy=True)
    assert dense_engine.generate(PROMPTS, gen) == paged_engine.generate(
        PROMPTS, gen
    )
    assert dense_engine.generate_fused(
        PROMPTS, gen
    ) == paged_engine.generate_fused(PROMPTS, gen)


def test_engine_sampled_matches_dense(dense_engine, paged_engine):
    gen = GenerationParams(
        max_new_tokens=6, is_greedy=False, temperature=1.1, top_k=20,
        top_p=0.95, seed=7,
    )
    assert dense_engine.generate(PROMPTS, gen) == paged_engine.generate(
        PROMPTS, gen
    )


def test_engine_full_capacity_matches_dense(dense_engine, paged_engine):
    """Generate to the very last ring slot (prompt + new == max_seq_len):
    the final token writes into the last block's last offset."""
    gen = GenerationParams(max_new_tokens=60, is_greedy=True)
    p = [[7, 3, 11, 2]]
    assert dense_engine.generate(p, gen) == paged_engine.generate(p, gen)


def test_engine_mqa_matches_dense(devices):
    cfg = _cfg(n_kv_heads=1)
    mesh = make_mesh(MeshPlan(dp=2, tp=4))
    params = init_params(cfg, mesh, jax.random.key(2))
    gen = GenerationParams(max_new_tokens=6, is_greedy=True)
    d = DecodeEngine(cfg, params, mesh, max_seq_len=64)
    p = DecodeEngine(
        cfg, params, mesh, max_seq_len=64, kv_layout="paged", block_size=16,
    )
    assert d.generate(PROMPTS, gen) == p.generate(PROMPTS, gen)


def test_engine_int8_matches_dense_int8(setup):
    """int8 KV: the paged pool stores the same quantized bits + scales, so
    paged-int8 must equal dense-int8 exactly (both quantize identically)."""
    cfg, mesh, params = setup
    gen = GenerationParams(max_new_tokens=8, is_greedy=True)
    d = DecodeEngine(cfg, params, mesh, max_seq_len=64, kv_dtype="int8")
    p = DecodeEngine(
        cfg, params, mesh, max_seq_len=64, kv_dtype="int8",
        kv_layout="paged", block_size=16,
    )
    assert d.generate(PROMPTS, gen) == p.generate(PROMPTS, gen)


def test_engine_flag_validation(setup):
    cfg, mesh, params = setup
    with pytest.raises(ValueError):
        DecodeEngine(cfg, params, mesh, max_seq_len=64, kv_layout="wat")
    with pytest.raises(ValueError):
        # max_seq_len not divisible by block_size
        DecodeEngine(
            cfg, params, mesh, max_seq_len=64, kv_layout="paged",
            block_size=24,
        )


# -- continuous batching on the block pool ----------------------------------


def test_batcher_paged_matches_dense(dense_engine, paged_engine):
    prompts = PROMPTS + [[7, 8], [1, 2, 3]]
    gen = GenerationParams(max_new_tokens=6, is_greedy=True)
    expected = [dense_engine.generate([p], gen)[0] for p in prompts]
    bat = ContinuousBatcher(paged_engine, rows=2)
    results = {}
    for i, p in enumerate(prompts):
        bat.submit(p, gen, lambda t, i=i: results.__setitem__(i, t))
    bat.run_until_idle()
    for i, e in enumerate(expected):
        assert results[i] == e, (i, results[i], e)
    assert bat.allocator.blocks_in_use == 0  # every block returned


def test_batcher_pool_gated_admission(setup):
    """Admission degrades to BLOCK capacity: 4 row slots but a pool that
    fits only 2 requests at a time — all 4 must still complete with their
    solo tokens (the others requeue), and the pool drains to zero."""
    cfg, mesh, params = setup
    eng = DecodeEngine(
        cfg, params, mesh, max_seq_len=64, kv_layout="paged",
        block_size=16, kv_blocks=6,
    )
    dense = DecodeEngine(cfg, params, mesh, max_seq_len=64)
    gen = GenerationParams(max_new_tokens=30, is_greedy=True)  # 3 blocks
    prompts = [[i + 1, i + 2, i + 3] for i in range(4)]
    expected = [dense.generate([p], gen)[0] for p in prompts]
    bat = ContinuousBatcher(eng, rows=4)
    results = {}
    for i, p in enumerate(prompts):
        bat.submit(p, gen, lambda t, i=i: results.__setitem__(i, t))
    bat.run_until_idle()
    for i, e in enumerate(expected):
        assert results[i] == e, (i, results[i], e)
    assert bat.allocator.blocks_in_use == 0
    assert eng.metrics.to_dict()["kv_blocks_in_use"] == 0


def test_batcher_request_bigger_than_pool_errors(setup):
    """A request that can never fit the pool is answered with an error,
    not requeued forever."""
    cfg, mesh, params = setup
    eng = DecodeEngine(
        cfg, params, mesh, max_seq_len=64, kv_layout="paged",
        block_size=16, kv_blocks=2,
    )
    bat = ContinuousBatcher(eng, rows=2)
    out = {}

    def cb(toks, cancelled=False, error=None):
        out["error"] = error

    bat.submit([1, 2, 3], GenerationParams(max_new_tokens=60), cb)
    bat.run_until_idle()
    assert "KV blocks" in out["error"]
    assert bat.allocator.blocks_in_use == 0


def test_cancel_mid_decode_returns_blocks(paged_engine):
    gen = GenerationParams(max_new_tokens=50, is_greedy=True)
    bat = ContinuousBatcher(paged_engine, rows=2)
    done = {}

    def cb(toks, cancelled=False):
        done.update(toks=toks, cancelled=cancelled)

    bat.submit([5, 9, 23], gen, cb, req_id="r1")
    for _ in range(4):
        bat.step()
    assert bat.allocator.blocks_in_use > 0
    bat.cancel("r1")
    bat.run_until_idle()
    assert done["cancelled"] is True
    assert bat.allocator.blocks_in_use == 0  # freed immediately on cancel


def test_shared_prefix_cow_no_per_row_copies(dense_engine, paged_engine):
    """The acceptance assertion: N rows sharing a prefix hold ONE copy of
    its full blocks (refcounted), not N — observed through the
    kv_blocks_in_use gauge at admission — and still emit exactly the
    dense engine's tokens. The partial tail block is copied per row (COW).
    """
    pfx_tokens = list(range(1, 21))  # 20 toks: 1 full block (bs=16) + tail
    pfx = paged_engine.build_prefix(pfx_tokens)
    gen = GenerationParams(max_new_tokens=5, is_greedy=True)
    full = [pfx_tokens + [30 + i] for i in range(3)]
    expected = [dense_engine.generate([p], gen)[0] for p in full]

    bat = ContinuousBatcher(paged_engine, rows=4)
    results = {}
    for i, p in enumerate(full):
        bat.submit(p, gen, lambda t, i=i: results.__setitem__(i, t),
                   prefix=pfx)
    for _ in range(3):  # admit + a few decode chunks; nothing finished yet
        bat.step()
    # Each row: ceil((21 + 5)/16) = 2 blocks total, 1 shared -> 1 owned.
    # Shared full block counted ONCE. Per-row copies would be 3 * 2 = 6.
    assert bat.allocator.blocks_in_use == 1 + 3 * 1
    bat.run_until_idle()
    for i, e in enumerate(expected):
        assert results[i] == e, (i, results[i], e)
    # After finish only the prefix registry's shared block remains.
    assert bat.allocator.blocks_in_use == 1
    assert paged_engine.metrics.to_dict()["kv_blocks_in_use"] == 1


def test_prefix_eviction_under_pressure(setup):
    """An idle registered prefix is evicted (blocks reclaimed, eviction
    counters tick) when a new request can't otherwise fit the pool."""
    cfg, mesh, params = setup
    eng = DecodeEngine(
        cfg, params, mesh, max_seq_len=64, kv_layout="paged",
        block_size=16, kv_blocks=4,
    )
    pfx = eng.build_prefix(list(range(1, 18)))  # 1 full block
    bat = ContinuousBatcher(eng, rows=2)
    r = {}
    bat.submit(
        list(range(1, 18)) + [40], GenerationParams(max_new_tokens=4),
        lambda t: r.__setitem__("a", t), prefix=pfx,
    )
    bat.run_until_idle()
    assert bat.allocator.blocks_in_use == 1  # idle prefix block retained
    # 4-block pool, 1 held by the idle prefix: this needs all 4.
    bat.submit(
        [9] * 40, GenerationParams(max_new_tokens=24),
        lambda t: r.__setitem__("b", t),
    )
    bat.run_until_idle()
    assert "b" in r and len(r["b"]) == 24
    assert eng.metrics.to_dict()["kv_block_evictions"] == 1
    assert bat.allocator.evictions == 1
    assert bat.allocator.blocks_in_use == 0


# -- prefill bucket ladder for prefixes -------------------------------------


def test_build_prefix_keeps_bucket_shape(dense_engine):
    """build_prefix retains the prefill BUCKET's padded segment, so the
    seed scatter compiles once per bucket — not once per distinct prefix
    length (the removed ~28 s one-time cost)."""
    from llmss_tpu.engine.engine import _bucket

    for plen in (5, 7, 20):
        pfx = dense_engine.build_prefix(list(range(1, plen + 1)))
        assert pfx.length == plen
        assert pfx.k.shape[1] == _bucket(plen, dense_engine.max_seq_len)


# -- metrics surfacing ------------------------------------------------------


def test_kv_gauges_flow_to_producer_metrics(paged_engine):
    """The consumer publishes engine.metrics.to_dict() and the producer's
    /metrics serves broker.read_metrics() verbatim — the kv_* gauges must
    survive the round trip."""
    from llmss_tpu.serve.broker import InProcBroker

    d = paged_engine.metrics.to_dict()
    for k in ("kv_blocks_total", "kv_blocks_in_use", "kv_block_evictions"):
        assert k in d
    broker = InProcBroker()
    broker.publish_metrics(d)
    got = broker.read_metrics()
    assert got["kv_blocks_total"] == d["kv_blocks_total"]
    assert got["kv_blocks_in_use"] == d["kv_blocks_in_use"]


# -- Pallas ragged block-table kernel ---------------------------------------


def test_pallas_paged_kernel_matches_xla_oracle(devices):
    """Direct kernel parity (interpret mode): the Pallas grid
    (rows x blocks) flash loop over block tables must match the XLA
    gather-based paged attention on ragged row lengths."""
    from llmss_tpu.ops.attention import (
        paged_decode_attention as xla_paged,
    )
    from llmss_tpu.ops.pallas_paged_decode import (
        paged_decode_attention as pallas_paged, supports,
    )

    B, MB, bs, Hq, Hkv, D, N = 2, 4, 16, 4, 2, 128, 8
    assert supports(bs, Hq, Hkv, D)
    rng = np.random.default_rng(3)
    k_pool = jnp.asarray(
        rng.standard_normal((N, bs, Hkv, D)) * 0.3, jnp.float32
    )
    v_pool = jnp.asarray(
        rng.standard_normal((N, bs, Hkv, D)) * 0.3, jnp.float32
    )
    q = jnp.asarray(rng.standard_normal((B, 1, Hq, D)) * 0.3, jnp.float32)
    kn = jnp.asarray(rng.standard_normal((B, 1, Hkv, D)) * 0.3, jnp.float32)
    vn = jnp.asarray(rng.standard_normal((B, 1, Hkv, D)) * 0.3, jnp.float32)
    tables = jnp.asarray([[4, 2, 7, 1], [0, 5, 3, 6]], jnp.int32)
    # ragged: row 0 has 19 tokens (2 blocks), row 1 has 40 (3 blocks)
    occ = np.full((B, MB * bs), -1, np.int32)
    occ[0, :19] = np.arange(19)
    occ[1, :40] = np.arange(40)
    kv_pos = jnp.asarray(occ)
    q_pos = jnp.asarray([19, 40], jnp.int32)
    slots = q_pos  # append position == logical slot
    nblk = jnp.asarray([2, 3], jnp.int32)

    want = xla_paged(
        q, k_pool, v_pool, kn, vn, q_pos[:, None], kv_pos, tables,
        slots[:, None],
    )
    got = pallas_paged(
        q, k_pool[None], v_pool[None], kn, vn, q_pos, kv_pos, tables,
        nblk, slots, jnp.int32(0), interpret=True,
    )
    np.testing.assert_allclose(
        np.asarray(got, np.float32), np.asarray(want, np.float32),
        rtol=2e-5, atol=2e-5,
    )


def test_paged_forward_kernel_vs_xla_integration(devices):
    """Full fused decode with the paged Pallas kernel forced on
    (IMPL_OVERRIDE='pallas', interpret): same greedy tokens as the paged
    XLA gather path AND the dense engine."""
    attn_mod = importlib.import_module("llmss_tpu.ops.attention")
    cfg = _cfg(
        vocab_size=128, hidden_size=256, n_heads=8, n_kv_heads=4,
        head_dim=128, intermediate_size=128, rotary_dim=128,
    )
    mesh = make_mesh(MeshPlan(dp=2, tp=4))
    params = init_params(cfg, mesh, jax.random.key(3))
    gen = GenerationParams(max_new_tokens=8, is_greedy=True)

    outs = {}
    old = attn_mod.IMPL_OVERRIDE
    for impl in ("xla", "pallas"):
        attn_mod.IMPL_OVERRIDE = impl
        try:
            eng = DecodeEngine(
                cfg, params, mesh, max_seq_len=64, kv_layout="paged",
                block_size=16,
            )
            outs[impl] = eng.generate_fused(PROMPTS, gen)
        finally:
            attn_mod.IMPL_OVERRIDE = old
    dense = DecodeEngine(cfg, params, mesh, max_seq_len=64)
    outs["dense"] = dense.generate_fused(PROMPTS, gen)
    assert outs["xla"] == outs["pallas"] == outs["dense"], outs


def test_batcher_paged_grouped_matches_dense(dense_engine, paged_engine):
    """Grouped dispatch rides the paged layout unchanged: a paged batcher
    at group_chunks>1 must produce every request's solo dense tokens, with
    admissions landing mid-stream and the block pool draining to zero."""
    prompts = PROMPTS + [[7, 8], [1, 2, 3]]
    gen = GenerationParams(max_new_tokens=6, is_greedy=True)
    expected = [dense_engine.generate([p], gen)[0] for p in prompts]
    bat = ContinuousBatcher(
        paged_engine, rows=2, chunk_steps=2, group_chunks=3,
    )
    results = {}
    for i, p in enumerate(prompts[:2]):
        bat.submit(p, gen, lambda t, i=i: results.__setitem__(i, t))
    bat.step()
    bat.step()  # later admissions land while the first rows are mid-group
    for i, p in enumerate(prompts[2:], start=2):
        bat.submit(p, gen, lambda t, i=i: results.__setitem__(i, t))
    bat.run_until_idle()
    for i, e in enumerate(expected):
        assert results[i] == e, (i, results[i], e)
    assert bat.allocator.blocks_in_use == 0  # every block returned
