"""Sequence/context parallelism: ring prefill + split-KV decode parity.

The reference truncates at ``n_positions`` (SURVEY.md §2.11.2, §5
"Long-context: absent"); here the cache's sequence dim shards over ``sp``.
These tests run the real collectives (ppermute / pmax / psum) on the virtual
8-device CPU mesh and require exact agreement with the single-device XLA
attention semantics.
"""

import numpy as np
import pytest

import jax
import jax.numpy as jnp
from jax.sharding import PartitionSpec as P

from llmss_tpu.engine.cache import init_cache
from llmss_tpu.ops.attention import (
    attention,
    dispatch_attention,
    make_causal_mask,
)
from llmss_tpu.ops.ring_attention import lse_merge_attention, ring_attention
from llmss_tpu.parallel import MeshPlan, make_mesh
from llmss_tpu.parallel.mesh import AXIS_DP, AXIS_SP, AXIS_TP
from llmss_tpu.parallel.mesh import shard_map as compat_shard_map


def _rand(rng, *shape):
    return jnp.asarray(rng.normal(size=shape), jnp.float32)


@pytest.fixture(scope="module")
def sp_mesh(devices):
    return make_mesh(MeshPlan(dp=1, sp=4, tp=2))


def test_ring_prefill_parity(sp_mesh):
    rng = np.random.default_rng(0)
    B, S, Hq, Hkv, D = 2, 32, 8, 4, 16
    T = S
    q, k, v = _rand(rng, B, S, Hq, D), _rand(rng, B, T, Hkv, D), _rand(
        rng, B, T, Hkv, D
    )
    # prefill with per-row padding: row 0 has 20 tokens, row 1 has 32
    kv_pos = np.full((B, T), -1, np.int32)
    kv_pos[0, :20] = np.arange(20)
    kv_pos[1, :] = np.arange(T)
    q_pos = np.broadcast_to(np.arange(T), (B, S)).astype(np.int32)
    q_pos, kv_pos = jnp.asarray(q_pos), jnp.asarray(kv_pos)

    ref = attention(q, k, v, make_causal_mask(q_pos, kv_pos, kv_pos >= 0))

    qs = P(AXIS_DP, AXIS_SP, AXIS_TP, None)
    ks = P(AXIS_DP, AXIS_SP, AXIS_TP, None)
    out = jax.jit(
        compat_shard_map(
            lambda q, k, v, qp, kvp: ring_attention(
                q, k, v, qp, kvp, axis_name=AXIS_SP
            ),
            mesh=sp_mesh,
            in_specs=(qs, ks, ks, P(AXIS_DP, AXIS_SP), P(AXIS_DP, AXIS_SP)),
            out_specs=qs,
            check_vma=False,
        )
    )(q, k, v, q_pos, kv_pos)
    np.testing.assert_allclose(out, ref, atol=2e-2)


def test_lse_merge_decode_parity(sp_mesh):
    rng = np.random.default_rng(1)
    B, Hq, Hkv, D, T = 2, 8, 4, 16, 64
    q = _rand(rng, B, 1, Hq, D)
    k, v = _rand(rng, B, T, Hkv, D), _rand(rng, B, T, Hkv, D)
    # ring-buffer state mid-generation: rows at different positions
    kv_pos = np.full((B, T), -1, np.int32)
    kv_pos[0, :37] = np.arange(37)
    kv_pos[1, :52] = np.arange(52)
    q_pos = np.asarray([[36], [51]], np.int32)
    q_pos, kv_pos = jnp.asarray(q_pos), jnp.asarray(kv_pos)

    ref = attention(q, k, v, make_causal_mask(q_pos, kv_pos, kv_pos >= 0))

    qs = P(AXIS_DP, None, AXIS_TP, None)
    ks = P(AXIS_DP, AXIS_SP, AXIS_TP, None)
    out = jax.jit(
        compat_shard_map(
            lambda q, k, v, qp, kvp: lse_merge_attention(
                q, k, v, qp, kvp, axis_name=AXIS_SP
            ),
            mesh=sp_mesh,
            in_specs=(qs, ks, ks, P(AXIS_DP, None), P(AXIS_DP, AXIS_SP)),
            out_specs=qs,
            check_vma=False,
        )
    )(q, k, v, q_pos, kv_pos)
    np.testing.assert_allclose(out, ref, atol=2e-2)


@pytest.mark.parametrize("S", [32, 1])
def test_dispatch_routes_sp(sp_mesh, S):
    """dispatch_attention picks ring (S>1) / lse-merge (S=1) when sp>1."""
    rng = np.random.default_rng(2)
    B, Hq, Hkv, D, T = 2, 8, 4, 16, 64
    q = _rand(rng, B, S, Hq, D)
    k, v = _rand(rng, B, T, Hkv, D), _rand(rng, B, T, Hkv, D)
    kv_pos = jnp.asarray(np.broadcast_to(np.arange(T), (B, T)), jnp.int32)
    q_pos = jnp.asarray(
        np.broadcast_to(np.arange(T - S, T), (B, S)), jnp.int32
    )
    mask = make_causal_mask(q_pos, kv_pos, kv_pos >= 0)
    ref = attention(q, k, v, mask)
    out = jax.jit(
        lambda q, k, v: dispatch_attention(
            q, k, v, mask=mask, q_positions=q_pos, kv_positions=kv_pos,
            mesh=sp_mesh,
        )
    )(q, k, v)
    np.testing.assert_allclose(out, ref, atol=2e-2)


def test_cache_shards_sequence_over_sp(sp_mesh):
    cache = init_cache(
        sp_mesh, n_layers=2, batch=2, max_len=64, n_kv_heads=4, head_dim=16
    )
    assert cache.k.sharding.spec == P(None, AXIS_DP, AXIS_SP, AXIS_TP, None)
    assert cache.positions.sharding.spec == P(AXIS_DP, AXIS_SP)


def test_engine_generate_sp_parity(devices):
    """Greedy generation on a dp×sp×tp mesh matches the tp-only mesh —
    prefill rides ring attention, decode rides the LSE merge."""
    from llmss_tpu.engine import DecodeEngine, GenerationParams
    from llmss_tpu.models.common import DecoderConfig
    from llmss_tpu.models.decoder import init_params

    cfg = DecoderConfig(
        model_type="llama", vocab_size=256, hidden_size=64, n_layers=2,
        n_heads=8, n_kv_heads=4, head_dim=8, intermediate_size=128,
        max_position_embeddings=128, activation="silu", norm="rmsnorm",
        norm_eps=1e-5, mlp="swiglu", positions="rotary", rope_style="half",
        rotary_dim=8, attn_bias=False, mlp_bias=False,
        tie_word_embeddings=False, dtype="float32",
    )
    prompts = [list(range(1, 30)), [7, 8, 9]]
    gen = GenerationParams(max_new_tokens=6, is_greedy=True)

    mesh_tp = make_mesh(MeshPlan(dp=1, sp=1, tp=8))
    params_tp = init_params(cfg, mesh_tp, jax.random.key(0))
    ref = DecodeEngine(cfg, params_tp, mesh_tp, max_seq_len=64).generate(
        prompts, gen
    )

    mesh_sp = make_mesh(MeshPlan(dp=2, sp=2, tp=2))
    params_sp = init_params(cfg, mesh_sp, jax.random.key(0))
    out = DecodeEngine(cfg, params_sp, mesh_sp, max_seq_len=64).generate(
        prompts, gen
    )
    assert out == ref


def test_lse_merge_fresh_kv_decode_parity(sp_mesh):
    """sp>1 deferred-write decode: attention over the stale sharded cache +
    fresh KV merged in-softmax must equal the XLA fresh-KV oracle, including
    pending-slot exclusion on ring wrap."""
    from llmss_tpu.ops.attention import fresh_kv_decode_attention
    from llmss_tpu.ops.ring_attention import lse_merge_fresh_kv_attention

    rng = np.random.default_rng(3)
    B, Hq, Hkv, D, T = 2, 8, 4, 16, 64
    q = _rand(rng, B, 1, Hq, D)
    k, v = _rand(rng, B, T, Hkv, D), _rand(rng, B, T, Hkv, D)
    k_new, v_new = _rand(rng, B, 1, Hkv, D), _rand(rng, B, 1, Hkv, D)
    # Row 0 mid-fill; row 1 wrapped past T (slot 69 % 64 = 5 will be
    # overwritten and must be excluded from the stale read).
    kv_pos = np.full((B, T), -1, np.int32)
    kv_pos[0, :37] = np.arange(37)
    for p in range(69):
        kv_pos[1, p % T] = p
    q_pos = np.asarray([[37], [69]], np.int32)
    slots = np.asarray([[37], [69 % T]], np.int32)
    q_pos, kv_pos, slots = map(jnp.asarray, (q_pos, kv_pos, slots))

    ref = fresh_kv_decode_attention(
        q, k, v, k_new, v_new, q_pos, kv_pos, slots
    )

    qs = P(AXIS_DP, None, AXIS_TP, None)
    ks = P(AXIS_DP, AXIS_SP, AXIS_TP, None)
    ps = P(AXIS_DP, None)
    out = jax.jit(
        compat_shard_map(
            lambda q, k, v, qp, kvp, kn, vn, sl: (
                lse_merge_fresh_kv_attention(
                    q, k, v, qp, kvp, kn, vn, sl, axis_name=AXIS_SP
                )
            ),
            mesh=sp_mesh,
            in_specs=(qs, ks, ks, ps, P(AXIS_DP, AXIS_SP), P(
                AXIS_DP, None, AXIS_TP, None
            ), P(AXIS_DP, None, AXIS_TP, None), ps),
            out_specs=qs,
            check_vma=False,
        )
    )(q, k, v, q_pos, kv_pos, k_new, v_new, slots)
    np.testing.assert_allclose(out, ref, atol=2e-2)


def test_sp_decode_defers_writes(devices):
    """Receipt for the unified deferred-write path: ``_ablate="no_scatter"``
    suppresses the post-scan batched write *only on the deferred path* (the
    in-scan fallback writes the cache inside the layer scan regardless), so
    an unchanged cache proves the sp>1 mesh routes decode through the
    fresh-KV LSE merge + deferred scatter."""
    from llmss_tpu.engine import DecodeEngine
    from llmss_tpu.models.common import DecoderConfig
    from llmss_tpu.models.decoder import forward, init_params

    cfg = DecoderConfig(
        model_type="llama", vocab_size=256, hidden_size=64, n_layers=4,
        n_heads=8, n_kv_heads=4, head_dim=8, intermediate_size=128,
        max_position_embeddings=128, activation="silu", norm="rmsnorm",
        norm_eps=1e-5, mlp="swiglu", positions="rotary", rope_style="half",
        rotary_dim=8, attn_bias=False, mlp_bias=False,
        tie_word_embeddings=False, dtype="float32",
    )
    mesh = make_mesh(MeshPlan(dp=2, sp=2, tp=2))
    params = init_params(cfg, mesh, jax.random.key(0))
    engine = DecodeEngine(cfg, params, mesh, max_seq_len=64)
    cache = engine.new_cache(2)
    tokens = jnp.asarray([[3], [7]], jnp.int32)
    positions = jnp.asarray([[2], [5]], jnp.int32)
    slots = positions % cache.max_len

    _, cache_abl = forward(
        cfg, params, tokens, positions, cache, slots, last_only=True,
        mesh=mesh, _ablate="no_scatter",
    )
    np.testing.assert_array_equal(
        np.asarray(cache_abl.k), np.asarray(cache.k)
    )

    # And without ablation the deferred scatter does land the fresh KV.
    _, cache_real = forward(
        cfg, params, tokens, positions, cache, slots, last_only=True,
        mesh=mesh,
    )
    assert not np.array_equal(np.asarray(cache_real.k), np.asarray(cache.k))
