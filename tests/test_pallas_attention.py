"""Pallas flash-attention kernel parity vs the XLA einsum path.

Runs the kernel in interpret mode on the virtual CPU mesh (the de facto fake
backend, SURVEY.md §4), covering the cache semantics the kernel must honor:
contiguous prefill, padding (-1 positions), ring-buffer wrap (slot order ≠
position order), GQA/MQA head grouping, and the shard_map'd dispatch over
dp×tp.
"""

import numpy as np
import pytest

import jax
import jax.numpy as jnp

import importlib

attn_mod = importlib.import_module("llmss_tpu.ops.attention")
from llmss_tpu.ops.attention import attention, make_causal_mask
from llmss_tpu.ops.pallas_attention import flash_attention, supports


def _rand(rng, *shape):
    return jnp.asarray(rng.normal(size=shape), jnp.float32)


def _xla_ref(q, k, v, q_pos, kv_pos, scale=None):
    mask = make_causal_mask(q_pos, kv_pos, kv_pos >= 0)
    return attention(q, k, v, mask, scale=scale)


@pytest.mark.parametrize("Hq,Hkv", [(8, 8), (8, 2), (8, 1)])
def test_prefill_parity(Hq, Hkv):
    rng = np.random.default_rng(0)
    B, S, T, D = 2, 64, 128, 64
    q, k, v = _rand(rng, B, S, Hq, D), _rand(rng, B, T, Hkv, D), _rand(
        rng, B, T, Hkv, D
    )
    # 100 valid slots; queries are the last 64 tokens; rest of cache empty.
    kv_pos = np.full((B, T), -1, np.int32)
    kv_pos[:, :100] = np.arange(100)
    q_pos = np.broadcast_to(np.arange(36, 100), (B, S)).astype(np.int32)
    q_pos, kv_pos = jnp.asarray(q_pos), jnp.asarray(kv_pos)

    ref = _xla_ref(q, k, v, q_pos, kv_pos)
    out = flash_attention(q, k, v, q_pos, kv_pos, interpret=True)
    np.testing.assert_allclose(out, ref, atol=2e-2)


def test_ring_wrap_and_block_sizes():
    """Slot order ≠ position order (post-wrap sliding window)."""
    rng = np.random.default_rng(1)
    B, S, T, Hq, Hkv, D = 2, 32, 128, 4, 4, 32
    q, k, v = _rand(rng, B, S, Hq, D), _rand(rng, B, T, Hkv, D), _rand(
        rng, B, T, Hkv, D
    )
    base = np.array([[37], [91]])
    kv_pos = jnp.asarray((np.arange(T)[None, :] + base) % 200 + 50, jnp.int32)
    q_pos = jnp.asarray(rng.integers(60, 240, (B, S)), jnp.int32)
    ref = _xla_ref(q, k, v, q_pos, kv_pos)
    for bq, bk in [(32, 128), (16, 32), (8, 16)]:
        out = flash_attention(
            q, k, v, q_pos, kv_pos, block_q=bq, block_k=bk, interpret=True
        )
        np.testing.assert_allclose(out, ref, atol=2e-2)


def test_custom_scale():
    rng = np.random.default_rng(2)
    B, S, T, Hq, Hkv, D = 1, 16, 64, 2, 2, 32
    q, k, v = _rand(rng, B, S, Hq, D), _rand(rng, B, T, Hkv, D), _rand(
        rng, B, T, Hkv, D
    )
    kv_pos = jnp.asarray(np.broadcast_to(np.arange(T), (B, T)), jnp.int32)
    q_pos = jnp.asarray(np.broadcast_to(np.arange(T - S, T), (B, S)),
                        jnp.int32)
    ref = _xla_ref(q, k, v, q_pos, kv_pos, scale=0.5)
    out = flash_attention(q, k, v, q_pos, kv_pos, scale=0.5, interpret=True)
    np.testing.assert_allclose(out, ref, atol=2e-2)


def test_supports_gating():
    assert supports(128, 256, 8, 8)
    assert supports(16, 128, 8, 1)
    assert not supports(1, 128, 8, 8)  # decode stays on XLA
    assert not supports(12, 128, 8, 8)  # unaligned S
    assert not supports(128, 128, 8, 3)  # non-grouping heads


def test_sharded_dispatch_matches_xla(devices):
    """dispatch_attention under IMPL_OVERRIDE='pallas' runs the kernel inside
    shard_map over dp×tp on the CPU mesh and must match the XLA path."""
    from llmss_tpu.parallel import MeshPlan, make_mesh

    mesh = make_mesh(MeshPlan(dp=2, sp=1, tp=4))
    rng = np.random.default_rng(3)
    B, S, T, Hq, Hkv, D = 4, 32, 64, 8, 4, 32
    q, k, v = _rand(rng, B, S, Hq, D), _rand(rng, B, T, Hkv, D), _rand(
        rng, B, T, Hkv, D
    )
    kv_pos = np.full((B, T), -1, np.int32)
    kv_pos[:, :48] = np.arange(48)
    q_pos = np.broadcast_to(np.arange(16, 48), (B, S)).astype(np.int32)
    q_pos, kv_pos = jnp.asarray(q_pos), jnp.asarray(kv_pos)
    mask = make_causal_mask(q_pos, kv_pos, kv_pos >= 0)

    ref = attention(q, k, v, mask)
    old = attn_mod.IMPL_OVERRIDE
    attn_mod.IMPL_OVERRIDE = "pallas"
    try:
        out = jax.jit(
            lambda q, k, v: attn_mod.dispatch_attention(
                q, k, v, mask=mask, q_positions=q_pos, kv_positions=kv_pos,
                mesh=mesh,
            )
        )(q, k, v)
    finally:
        attn_mod.IMPL_OVERRIDE = old
    np.testing.assert_allclose(out, ref, atol=2e-2)


def test_fresh_kv_decode_matches_write_then_attend():
    """Deferred-write decode attention == scatter-then-attend, including
    ring-wrap slot reuse and empty caches."""
    from llmss_tpu.engine.cache import write_layer
    from llmss_tpu.ops.attention import fresh_kv_decode_attention

    rng = np.random.default_rng(11)
    B, T, Hq, Hkv, D = 3, 32, 8, 4, 16
    kc = _rand(rng, B, T, Hkv, D)
    vc = _rand(rng, B, T, Hkv, D)
    q = _rand(rng, B, 1, Hq, D)
    k_new, v_new = _rand(rng, B, 1, Hkv, D), _rand(rng, B, 1, Hkv, D)
    for case, (pos_list, qp_list) in {
        "mid": ([12, 20, 0], [12, 20, 0]),  # row 2: empty cache
        "wrap": ([40, 33, 63], [40, 33, 63]),  # past T: slot reuse
    }.items():
        kv_pos = np.full((B, T), -1, np.int32)
        for b, p in enumerate(pos_list):
            n = min(p, T)
            # slots of the last n tokens before position p
            for j in range(n):
                pj = p - 1 - j
                kv_pos[b, pj % T] = pj
        q_pos = jnp.asarray(np.asarray(qp_list, np.int32)[:, None])
        slots = q_pos % T
        kv_pos = jnp.asarray(kv_pos)

        out = fresh_kv_decode_attention(
            q, kc, vc, k_new, v_new, q_pos, kv_pos, slots
        )

        kc2, vc2 = write_layer(kc, vc, k_new, v_new, slots)
        b_idx = np.arange(B)[:, None]
        kv_pos2 = jnp.asarray(np.asarray(kv_pos).copy())
        kv_pos2 = kv_pos2.at[b_idx, np.asarray(slots)].set(
            np.asarray(q_pos)
        )
        ref = attention(
            q, kc2, vc2, make_causal_mask(q_pos, kv_pos2, kv_pos2 >= 0)
        )
        np.testing.assert_allclose(out, ref, atol=2e-2, err_msg=case)


def test_gqa_replicated_kv_falls_back(devices):
    """Hkv=2 with tp=4 can't shard KV heads; the replicated-KV kernel path is
    only valid for MQA, so dispatch must fall back to XLA and stay correct
    (local head→KV grouping would otherwise be wrong — caught in review)."""
    from llmss_tpu.parallel import MeshPlan, make_mesh

    mesh = make_mesh(MeshPlan(dp=2, sp=1, tp=4))
    rng = np.random.default_rng(7)
    B, S, T, Hq, Hkv, D = 2, 32, 32, 8, 2, 16
    q, k, v = _rand(rng, B, S, Hq, D), _rand(rng, B, T, Hkv, D), _rand(
        rng, B, T, Hkv, D
    )
    pos = jnp.asarray(np.broadcast_to(np.arange(T), (B, T)), jnp.int32)
    mask = make_causal_mask(pos, pos, pos >= 0)
    ref = attention(q, k, v, mask)
    old = attn_mod.IMPL_OVERRIDE
    attn_mod.IMPL_OVERRIDE = "pallas"
    try:
        out = jax.jit(
            lambda q, k, v: attn_mod.dispatch_attention(
                q, k, v, mask=mask, q_positions=pos, kv_positions=pos,
                mesh=mesh,
            )
        )(q, k, v)
    finally:
        attn_mod.IMPL_OVERRIDE = old
    np.testing.assert_allclose(out, ref, atol=1e-5)


def test_engine_generate_with_pallas_attention(devices):
    """End-to-end greedy generation is identical with both attention paths."""
    from llmss_tpu.engine import DecodeEngine, GenerationParams
    from llmss_tpu.models.common import DecoderConfig
    from llmss_tpu.models.decoder import init_params
    from llmss_tpu.parallel import MeshPlan, make_mesh

    mesh = make_mesh(MeshPlan(dp=1, sp=1, tp=8))
    cfg = DecoderConfig(
        model_type="llama", vocab_size=256, hidden_size=64, n_layers=2,
        n_heads=8, n_kv_heads=8, head_dim=8, intermediate_size=128,
        max_position_embeddings=128, activation="silu", norm="rmsnorm",
        norm_eps=1e-5, mlp="swiglu", positions="rotary", rope_style="half",
        rotary_dim=8, attn_bias=False, mlp_bias=False,
        tie_word_embeddings=False, dtype="float32",
    )
    params = init_params(cfg, mesh, jax.random.key(0))
    prompts = [[1, 2, 3, 4, 5] * 5, [7, 8, 9]]
    gen = GenerationParams(max_new_tokens=6, is_greedy=True)

    engine = DecodeEngine(cfg, params, mesh, max_seq_len=64)
    ref = engine.generate(prompts, gen)

    old = attn_mod.IMPL_OVERRIDE
    attn_mod.IMPL_OVERRIDE = "pallas"
    try:
        engine2 = DecodeEngine(cfg, params, mesh, max_seq_len=64)
        out = engine2.generate(prompts, gen)
    finally:
        attn_mod.IMPL_OVERRIDE = old
    assert out == ref
