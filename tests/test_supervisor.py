"""Supervisor: crash containment, restart backoff, liveness heartbeats.

The reference's failure story is "the process dies" (SURVEY.md §5); these
tests pin the supervised loop's contract: crashes rebuild the worker and
service continues, the restart budget turns a crash loop into a hard error,
and health is visible through the broker metrics channel.
"""

import threading

import pytest

from llmss_tpu.serve.broker import InProcBroker
from llmss_tpu.serve.supervisor import Supervisor


class FlakyWorker:
    """Crashes on iterations listed in ``crash_at`` (global call count)."""

    calls = 0

    def __init__(self, crash_at, record):
        self.crash_at = crash_at
        self.record = record
        self.record.append("built")

    def run_once(self):
        FlakyWorker.calls += 1
        if FlakyWorker.calls in self.crash_at:
            raise RuntimeError(f"boom@{FlakyWorker.calls}")
        self.record.append(FlakyWorker.calls)


@pytest.fixture(autouse=True)
def _reset_calls():
    FlakyWorker.calls = 0


def _run_until(sup, stop_after_calls, record):
    stop = threading.Event()

    orig = FlakyWorker.run_once

    def wrapped(self):
        if FlakyWorker.calls >= stop_after_calls:
            stop.set()
            return
        orig(self)

    FlakyWorker.run_once = wrapped
    try:
        sup.run(stop)
    finally:
        FlakyWorker.run_once = orig


def test_restarts_after_crash():
    broker = InProcBroker()
    record = []
    sup = Supervisor(
        lambda: FlakyWorker({3, 7}, record), broker,
        backoff_s=0.01, heartbeat_s=0.0,
    )
    _run_until(sup, 12, record)
    assert sup.restarts == 2
    assert record.count("built") == 3  # initial + one per crash
    assert "boom@3" in sup._last_error or "boom@7" in sup._last_error
    m = broker.read_metrics()
    assert m["supervisor"]["restarts"] == 2
    assert m["supervisor"]["alive"] is True  # heartbeat after recovery


def test_restart_budget_exhausted():
    broker = InProcBroker()
    record = []
    sup = Supervisor(
        lambda: FlakyWorker(set(range(1, 100)), record), broker,
        backoff_s=0.0, max_restarts=3, heartbeat_s=0.0,
    )
    with pytest.raises(RuntimeError, match="restart budget"):
        sup.run()
    assert sup.restarts == 4
    assert broker.read_metrics()["supervisor"]["alive"] is False


def test_abort_inflight_errors_pending_requests(devices):
    """A crashing continuous worker must error out admitted requests so no
    client waits forever (supervisor teardown contract)."""
    import jax

    from llmss_tpu.engine import DecodeEngine
    from llmss_tpu.models.common import DecoderConfig
    from llmss_tpu.models.decoder import init_params
    from llmss_tpu.parallel import MeshPlan, make_mesh
    from llmss_tpu.serve.consumer import ContinuousWorker
    from llmss_tpu.serve.protocol import GenerateRequest

    mesh = make_mesh(MeshPlan(dp=1, sp=1, tp=8))
    cfg = DecoderConfig(
        model_type="llama", vocab_size=128, hidden_size=32, n_layers=1,
        n_heads=4, n_kv_heads=4, head_dim=8, intermediate_size=64,
        max_position_embeddings=64, activation="silu", norm="rmsnorm",
        norm_eps=1e-5, mlp="swiglu", positions="rotary", rope_style="half",
        rotary_dim=8, attn_bias=False, mlp_bias=False,
        tie_word_embeddings=False, dtype="float32",
    )
    params = init_params(cfg, mesh, jax.random.key(0))
    engine = DecodeEngine(cfg, params, mesh, max_seq_len=32)
    broker = InProcBroker()
    worker = ContinuousWorker(engine, broker, tokenizer=None, rows=2)
    broker.push_request(GenerateRequest(
        id="rq-long", token_ids=[1, 2, 3], max_new_tokens=25,
        is_greedy=True,
    ))
    worker.run_once()  # admits the request; far from finished
    n = worker.abort_inflight("boom")
    assert n == 1
    resp = broker.wait_response("rq-long", timeout=5)
    assert resp is not None and "worker restarted: boom" in resp.error


def test_supervisor_status_survives_worker_publish():
    """Worker-side publish_metrics must not erase the supervisor block."""
    broker = InProcBroker()
    sup = Supervisor(lambda: None, broker, heartbeat_s=0.0)
    broker.publish_metrics({"tokens_generated": 5})  # worker-style publish
    m = broker.read_metrics()
    assert m["tokens_generated"] == 5
    assert m["supervisor"]["restarts"] == sup.restarts == 0


def test_factory_failure_is_contained():
    """A worker_factory exception counts as a crash (budget applies), it
    does not kill the supervisor outright."""
    broker = InProcBroker()

    def bad_factory():
        raise OSError("cannot rebuild")

    sup = Supervisor(
        bad_factory, broker, backoff_s=0.0, max_restarts=2, heartbeat_s=0.0
    )
    with pytest.raises(RuntimeError, match="restart budget"):
        sup.run()
    assert sup.restarts == 3
    assert broker.read_metrics()["supervisor"]["alive"] is False


def _paid_backoffs(stable_after_s):
    """Run a {crash@2, crash@6} schedule and return the restart delay the
    supervisor was about to pay at each crash (``backoff_current`` at crash
    time is exactly the upcoming wait)."""
    broker = InProcBroker()
    record = []
    paid = []
    sup = None

    class Recording(FlakyWorker):
        def run_once(self):
            if FlakyWorker.calls + 1 in self.crash_at:
                paid.append(sup.backoff_current)
            super().run_once()

    sup = Supervisor(
        lambda: Recording({2, 6}, record), broker,
        backoff_s=0.01, stable_after_s=stable_after_s, heartbeat_s=0.0,
    )
    _run_until(sup, 6, record)
    # Both scheduled crashes happened (restart count itself is stability-
    # dependent now: a stable run resets it along with the backoff).
    assert record.count("built") == 3
    return paid, broker


def test_backoff_grows_without_stability_reset():
    """Crashes spaced closer than ``stable_after_s`` keep doubling the
    restart delay: the second crash pays 2x the first."""
    paid, broker = _paid_backoffs(stable_after_s=3600.0)
    assert paid == [pytest.approx(0.01), pytest.approx(0.02)]
    # Observable to operators through the health/metrics channel.
    assert "backoff_current_s" in broker.read_metrics()["supervisor"]


def test_backoff_resets_after_stable_run():
    """A worker that stays up past ``stable_after_s`` earns its backoff
    back: the second crash pays ``backoff_s`` again, not the doubled
    carry-over from the first."""
    paid, _ = _paid_backoffs(stable_after_s=0.0)
    assert paid == [pytest.approx(0.01), pytest.approx(0.01)]


def test_clean_stop():
    broker = InProcBroker()
    record = []
    sup = Supervisor(
        lambda: FlakyWorker(set(), record), broker,
        backoff_s=0.01, heartbeat_s=0.0,
    )
    _run_until(sup, 5, record)
    assert sup.restarts == 0
    assert broker.read_metrics()["supervisor"]["alive"] is True
