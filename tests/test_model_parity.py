"""Logit parity vs HuggingFace transformers on tiny random checkpoints.

The SURVEY.md §4 test strategy: sharded TP model (dp=2 × tp=4 virtual CPU
mesh) must reproduce the unsharded HF torch reference implementation's logits
for every supported family, for both full-prefix forward and incremental
KV-cache decode.
"""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from llmss_tpu.engine.cache import init_cache
from llmss_tpu.models import config_from_hf
from llmss_tpu.models.decoder import forward
from llmss_tpu.models.registry import MODEL_REGISTRY
from llmss_tpu.parallel import MeshPlan, make_mesh
from llmss_tpu.weights import CheckpointShards, weight_files

B, S = 2, 10


def _save_hf(tmp_path, model_type):
    import torch
    import transformers as tr

    torch.manual_seed(0)
    if model_type == "gptj":
        cfg = tr.GPTJConfig(
            vocab_size=64, n_positions=32, n_embd=32, n_layer=2, n_head=4,
            rotary_dim=4, n_inner=None,
        )
        model = tr.GPTJForCausalLM(cfg)
    elif model_type == "gpt_bigcode":
        cfg = tr.GPTBigCodeConfig(
            vocab_size=64, n_positions=32, n_embd=32, n_layer=2, n_head=4,
            multi_query=True,
        )
        model = tr.GPTBigCodeForCausalLM(cfg)
    elif model_type == "gpt2":
        cfg = tr.GPT2Config(
            vocab_size=64, n_positions=32, n_embd=32, n_layer=2, n_head=4
        )
        model = tr.GPT2LMHeadModel(cfg)
    elif model_type == "llama":
        cfg = tr.LlamaConfig(
            vocab_size=64, hidden_size=32, num_hidden_layers=2,
            num_attention_heads=4, num_key_value_heads=2,
            intermediate_size=48, max_position_embeddings=32,
            tie_word_embeddings=False,
        )
        model = tr.LlamaForCausalLM(cfg)
    elif model_type == "mistral":
        # sliding_window < S so the window actually clips attention in the
        # parity prompt (HF masks it in-forward; here it rides the mask /
        # kernels — tests/test_window.py covers the impl paths).
        cfg = tr.MistralConfig(
            vocab_size=64, hidden_size=32, num_hidden_layers=2,
            num_attention_heads=4, num_key_value_heads=2,
            intermediate_size=48, max_position_embeddings=32,
            sliding_window=6, tie_word_embeddings=False,
        )
        model = tr.MistralForCausalLM(cfg)
    elif model_type == "qwen2":
        cfg = tr.Qwen2Config(
            vocab_size=64, hidden_size=32, num_hidden_layers=2,
            num_attention_heads=4, num_key_value_heads=2,
            intermediate_size=48, max_position_embeddings=32,
            tie_word_embeddings=False,
        )
        model = tr.Qwen2ForCausalLM(cfg)
    elif model_type == "gpt_neox":
        cfg = tr.GPTNeoXConfig(
            vocab_size=64, hidden_size=32, num_hidden_layers=2,
            num_attention_heads=4, intermediate_size=48,
            max_position_embeddings=32, rotary_pct=0.25,
            use_parallel_residual=True,
        )
        model = tr.GPTNeoXForCausalLM(cfg)
    elif model_type == "phi3":
        cfg = tr.Phi3Config(
            vocab_size=64, hidden_size=32, num_hidden_layers=2,
            num_attention_heads=4, num_key_value_heads=2,
            intermediate_size=48, max_position_embeddings=32,
            tie_word_embeddings=False, pad_token_id=0,
        )
        model = tr.Phi3ForCausalLM(cfg)
    elif model_type == "gemma":
        cfg = tr.GemmaConfig(
            vocab_size=64, hidden_size=32, num_hidden_layers=2,
            num_attention_heads=4, num_key_value_heads=1, head_dim=8,
            intermediate_size=48, max_position_embeddings=32,
        )
        model = tr.GemmaForCausalLM(cfg)
    else:
        raise KeyError(model_type)
    model.eval()
    d = tmp_path / model_type
    model.save_pretrained(d, safe_serialization=True)
    return d, model


def _hf_logits(model, ids):
    import torch

    with torch.no_grad():
        return model(torch.tensor(ids)).logits.float().numpy()


@pytest.mark.parametrize(
    "model_type",
    ["gptj", "gpt_bigcode", "gpt2", "llama", "mistral", "qwen2", "gpt_neox",
     "phi3", "gemma"],
)
def test_full_forward_parity(tmp_path, devices, model_type):
    d, hf_model = _save_hf(tmp_path, model_type)
    rng = np.random.default_rng(0)
    ids = rng.integers(0, 64, size=(B, S))
    ref = _hf_logits(hf_model, ids)

    mesh = make_mesh(MeshPlan(dp=2, tp=4))
    from transformers import AutoConfig

    cfg = config_from_hf(AutoConfig.from_pretrained(d), dtype="float32")
    ckpt = CheckpointShards(weight_files(str(d)), dtype=np.float32)
    params = MODEL_REGISTRY[model_type].load_params(ckpt, cfg, mesh)

    cache = init_cache(
        mesh, n_layers=cfg.n_layers, batch=B, max_len=S,
        n_kv_heads=cfg.n_kv_heads, head_dim=cfg.head_dim,
        dtype=jnp.float32,
    )
    positions = jnp.broadcast_to(jnp.arange(S), (B, S))
    logits, _ = jax.jit(forward, static_argnums=0)(
        cfg, params, jnp.asarray(ids), positions, cache, positions
    )
    np.testing.assert_allclose(
        np.asarray(logits), ref, atol=2e-4, rtol=2e-3
    )


@pytest.mark.parametrize(
    "model_type",
    ["gptj", "gpt_bigcode", "gpt2", "llama", "mistral", "qwen2", "gpt_neox",
     "phi3", "gemma"],
)
def test_incremental_decode_parity(tmp_path, devices, model_type):
    """Prefill then token-by-token decode must equal the full forward."""
    d, hf_model = _save_hf(tmp_path, model_type)
    rng = np.random.default_rng(1)
    ids = rng.integers(0, 64, size=(B, S))
    ref = _hf_logits(hf_model, ids)

    mesh = make_mesh(MeshPlan(dp=2, tp=4))
    from transformers import AutoConfig

    cfg = config_from_hf(AutoConfig.from_pretrained(d), dtype="float32")
    ckpt = CheckpointShards(weight_files(str(d)), dtype=np.float32)
    params = MODEL_REGISTRY[model_type].load_params(ckpt, cfg, mesh)

    cache = init_cache(
        mesh, n_layers=cfg.n_layers, batch=B, max_len=S,
        n_kv_heads=cfg.n_kv_heads, head_dim=cfg.head_dim,
        dtype=jnp.float32,
    )
    prefill_len = 6
    positions = jnp.broadcast_to(jnp.arange(prefill_len), (B, prefill_len))
    logits, cache = jax.jit(forward, static_argnums=0)(
        cfg, params, jnp.asarray(ids[:, :prefill_len]), positions, cache,
        positions,
    )
    np.testing.assert_allclose(
        np.asarray(logits[:, -1]), ref[:, prefill_len - 1],
        atol=2e-4, rtol=2e-3,
    )

    step = jax.jit(forward, static_argnums=(0,), static_argnames=("last_only",))
    for t in range(prefill_len, S):
        pos = jnp.full((B, 1), t, jnp.int32)
        logits, cache = step(
            cfg, params, jnp.asarray(ids[:, t : t + 1]), pos, cache, pos,
            last_only=True,
        )
        np.testing.assert_allclose(
            np.asarray(logits[:, 0]), ref[:, t], atol=2e-4, rtol=2e-3
        )
