"""Logit parity vs HuggingFace transformers on tiny random checkpoints.

The SURVEY.md §4 test strategy: sharded TP model (dp=2 × tp=4 virtual CPU
mesh) must reproduce the unsharded HF torch reference implementation's logits
for every supported family, for both full-prefix forward and incremental
KV-cache decode.
"""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from llmss_tpu.engine.cache import init_cache
from llmss_tpu.models import config_from_hf
from llmss_tpu.models.decoder import forward
from llmss_tpu.models.registry import MODEL_REGISTRY
from llmss_tpu.parallel import MeshPlan, make_mesh
from llmss_tpu.weights import CheckpointShards, weight_files

B, S = 2, 10


def _save_hf(tmp_path, model_type):
    import torch
    import transformers as tr

    torch.manual_seed(0)
    if model_type == "gptj":
        cfg = tr.GPTJConfig(
            vocab_size=64, n_positions=32, n_embd=32, n_layer=2, n_head=4,
            rotary_dim=4, n_inner=None,
        )
        model = tr.GPTJForCausalLM(cfg)
    elif model_type == "gpt_bigcode":
        cfg = tr.GPTBigCodeConfig(
            vocab_size=64, n_positions=32, n_embd=32, n_layer=2, n_head=4,
            multi_query=True,
        )
        model = tr.GPTBigCodeForCausalLM(cfg)
    elif model_type == "gpt2":
        cfg = tr.GPT2Config(
            vocab_size=64, n_positions=32, n_embd=32, n_layer=2, n_head=4
        )
        model = tr.GPT2LMHeadModel(cfg)
    elif model_type == "llama":
        cfg = tr.LlamaConfig(
            vocab_size=64, hidden_size=32, num_hidden_layers=2,
            num_attention_heads=4, num_key_value_heads=2,
            intermediate_size=48, max_position_embeddings=32,
            tie_word_embeddings=False,
        )
        model = tr.LlamaForCausalLM(cfg)
    elif model_type == "mistral":
        # sliding_window < S so the window actually clips attention in the
        # parity prompt (HF masks it in-forward; here it rides the mask /
        # kernels — tests/test_window.py covers the impl paths).
        cfg = tr.MistralConfig(
            vocab_size=64, hidden_size=32, num_hidden_layers=2,
            num_attention_heads=4, num_key_value_heads=2,
            intermediate_size=48, max_position_embeddings=32,
            sliding_window=6, tie_word_embeddings=False,
        )
        model = tr.MistralForCausalLM(cfg)
    elif model_type == "qwen2":
        cfg = tr.Qwen2Config(
            vocab_size=64, hidden_size=32, num_hidden_layers=2,
            num_attention_heads=4, num_key_value_heads=2,
            intermediate_size=48, max_position_embeddings=32,
            tie_word_embeddings=False,
        )
        model = tr.Qwen2ForCausalLM(cfg)
    elif model_type == "gpt_neox":
        cfg = tr.GPTNeoXConfig(
            vocab_size=64, hidden_size=32, num_hidden_layers=2,
            num_attention_heads=4, intermediate_size=48,
            max_position_embeddings=32, rotary_pct=0.25,
            use_parallel_residual=True,
        )
        model = tr.GPTNeoXForCausalLM(cfg)
    elif model_type == "phi3":
        cfg = tr.Phi3Config(
            vocab_size=64, hidden_size=32, num_hidden_layers=2,
            num_attention_heads=4, num_key_value_heads=2,
            intermediate_size=48, max_position_embeddings=32,
            tie_word_embeddings=False, pad_token_id=0,
        )
        model = tr.Phi3ForCausalLM(cfg)
    elif model_type == "gemma":
        cfg = tr.GemmaConfig(
            vocab_size=64, hidden_size=32, num_hidden_layers=2,
            num_attention_heads=4, num_key_value_heads=1, head_dim=8,
            intermediate_size=48, max_position_embeddings=32,
        )
        model = tr.GemmaForCausalLM(cfg)
    else:
        raise KeyError(model_type)
    model.eval()
    d = tmp_path / model_type
    model.save_pretrained(d, safe_serialization=True)
    return d, model


def _hf_logits(model, ids):
    import torch

    with torch.no_grad():
        return model(torch.tensor(ids)).logits.float().numpy()


@pytest.mark.parametrize(
    "model_type",
    ["gptj", "gpt_bigcode", "gpt2", "llama", "mistral", "qwen2", "gpt_neox",
     "phi3", "gemma"],
)
def test_full_forward_parity(tmp_path, devices, model_type):
    d, hf_model = _save_hf(tmp_path, model_type)
    rng = np.random.default_rng(0)
    ids = rng.integers(0, 64, size=(B, S))
    ref = _hf_logits(hf_model, ids)

    mesh = make_mesh(MeshPlan(dp=2, tp=4))
    from transformers import AutoConfig

    cfg = config_from_hf(AutoConfig.from_pretrained(d), dtype="float32")
    ckpt = CheckpointShards(weight_files(str(d)), dtype=np.float32)
    params = MODEL_REGISTRY[model_type].load_params(ckpt, cfg, mesh)

    cache = init_cache(
        mesh, n_layers=cfg.n_layers, batch=B, max_len=S,
        n_kv_heads=cfg.n_kv_heads, head_dim=cfg.head_dim,
        dtype=jnp.float32,
    )
    positions = jnp.broadcast_to(jnp.arange(S), (B, S))
    logits, _ = jax.jit(forward, static_argnums=0)(
        cfg, params, jnp.asarray(ids), positions, cache, positions
    )
    np.testing.assert_allclose(
        np.asarray(logits), ref, atol=2e-4, rtol=2e-3
    )


@pytest.mark.parametrize(
    "model_type",
    ["gptj", "gpt_bigcode", "gpt2", "llama", "mistral", "qwen2", "gpt_neox",
     "phi3", "gemma"],
)
def test_incremental_decode_parity(tmp_path, devices, model_type):
    """Prefill then token-by-token decode must equal the full forward."""
    d, hf_model = _save_hf(tmp_path, model_type)
    rng = np.random.default_rng(1)
    ids = rng.integers(0, 64, size=(B, S))
    ref = _hf_logits(hf_model, ids)

    mesh = make_mesh(MeshPlan(dp=2, tp=4))
    from transformers import AutoConfig

    cfg = config_from_hf(AutoConfig.from_pretrained(d), dtype="float32")
    ckpt = CheckpointShards(weight_files(str(d)), dtype=np.float32)
    params = MODEL_REGISTRY[model_type].load_params(ckpt, cfg, mesh)

    cache = init_cache(
        mesh, n_layers=cfg.n_layers, batch=B, max_len=S,
        n_kv_heads=cfg.n_kv_heads, head_dim=cfg.head_dim,
        dtype=jnp.float32,
    )
    prefill_len = 6
    positions = jnp.broadcast_to(jnp.arange(prefill_len), (B, prefill_len))
    logits, cache = jax.jit(forward, static_argnums=0)(
        cfg, params, jnp.asarray(ids[:, :prefill_len]), positions, cache,
        positions,
    )
    np.testing.assert_allclose(
        np.asarray(logits[:, -1]), ref[:, prefill_len - 1],
        atol=2e-4, rtol=2e-3,
    )

    step = jax.jit(forward, static_argnums=(0,), static_argnames=("last_only",))
    for t in range(prefill_len, S):
        pos = jnp.full((B, 1), t, jnp.int32)
        logits, cache = step(
            cfg, params, jnp.asarray(ids[:, t : t + 1]), pos, cache, pos,
            last_only=True,
        )
        np.testing.assert_allclose(
            np.asarray(logits[:, 0]), ref[:, t], atol=2e-4, rtol=2e-3
        )


def test_phi3_longrope_parity_straddling_original_window(tmp_path, devices):
    """Phi-3 LongRoPE (rope_scaling 'longrope'): logits must match HF for a
    forward that STRADDLES original_max_position_embeddings — HF selects
    the long factors for that whole forward, and the static config-time
    choice (max_position_embeddings > original → long) agrees. Incremental
    decode must continue the same basis across the boundary."""
    import torch
    import transformers as tr

    from llmss_tpu.engine import DecodeEngine, GenerationParams

    half = 4  # head_dim 8 → 4 frequencies
    torch.manual_seed(3)
    hf_cfg = tr.Phi3Config(
        vocab_size=64, hidden_size=32, num_hidden_layers=2,
        num_attention_heads=4, num_key_value_heads=2,
        intermediate_size=48, max_position_embeddings=32,
        original_max_position_embeddings=8,
        rope_scaling={
            "type": "longrope",
            "short_factor": [1.0 + 0.1 * i for i in range(half)],
            "long_factor": [2.0 + 0.5 * i for i in range(half)],
        },
        tie_word_embeddings=False, pad_token_id=0,
    )
    model = tr.Phi3ForCausalLM(hf_cfg).eval()
    d = tmp_path / "phi3-longrope"
    model.save_pretrained(d, safe_serialization=True)

    rng = np.random.default_rng(1)
    ids = rng.integers(0, 64, size=(1, 12))  # 12 > original_max 8

    mesh = make_mesh(MeshPlan(dp=2, tp=4))
    from transformers import AutoConfig

    cfg = config_from_hf(AutoConfig.from_pretrained(d), dtype="float32")
    assert cfg.rope_freq_factors is not None
    assert len(cfg.rope_freq_factors) == half
    assert cfg.rope_freq_factors[0] == 2.0  # long (32 > 8)
    assert cfg.rope_attn_factor > 1.0

    ckpt = CheckpointShards(weight_files(str(d)), dtype=np.float32)
    params = MODEL_REGISTRY["phi3"].load_params(ckpt, cfg, mesh)

    # Full forward straddling the original window: HF picks long factors
    # for every position of this seq_len=12 call — exact agreement.
    ref = _hf_logits(model, ids)
    S12 = ids.shape[1]
    cache = init_cache(
        mesh, n_layers=cfg.n_layers, batch=1, max_len=16,
        n_kv_heads=cfg.n_kv_heads, head_dim=cfg.head_dim,
        dtype=jnp.float32,
    )
    positions = jnp.broadcast_to(jnp.arange(S12), (1, S12))
    logits, _ = jax.jit(forward, static_argnums=0)(
        cfg, params, jnp.asarray(ids), positions, cache, positions % 16
    )
    np.testing.assert_allclose(
        np.asarray(logits), ref, atol=2e-4, rtol=2e-3
    )

    # Incremental decode crossing the boundary: greedy continuation from a
    # 6-token prompt through position 14 must match HF's cache-free
    # re-forward argmax at each step beyond the original window (both use
    # the long basis there; the engine never switches basis mid-stream).
    engine = DecodeEngine(cfg, params, mesh, max_seq_len=16)
    prompt = ids[0, :6].tolist()
    out = engine.generate(
        [prompt], GenerationParams(max_new_tokens=8, is_greedy=True)
    )[0]
    prefix = list(prompt)
    for step, tok in enumerate(out):
        full = np.asarray([prefix])
        if full.shape[1] > 8:  # straddles: HF uses the long basis too
            hf_tok = int(_hf_logits(model, full)[0, -1].argmax())
            assert tok == hf_tok, (step, tok, hf_tok, prefix)
        prefix.append(tok)


def test_phi3_longrope_engine_picks_basis_from_its_context(tmp_path, devices):
    """A short-context engine on a long-context LongRoPE checkpoint must run
    the SHORT factors (what HF uses for every forward such an engine can
    serve), and a long-context engine the long factors."""
    import torch
    import transformers as tr

    from llmss_tpu.engine import DecodeEngine
    from llmss_tpu.models.decoder import init_params

    half = 4
    hf_cfg = tr.Phi3Config(
        vocab_size=64, hidden_size=32, num_hidden_layers=2,
        num_attention_heads=4, num_key_value_heads=2,
        intermediate_size=48, max_position_embeddings=32,
        original_max_position_embeddings=8,
        rope_scaling={
            "type": "longrope",
            "short_factor": [1.0] * half,
            "long_factor": [4.0] * half,
        },
        tie_word_embeddings=False, pad_token_id=0,
    )
    d = tmp_path / "m"
    torch.manual_seed(0)
    tr.Phi3ForCausalLM(hf_cfg).save_pretrained(d, safe_serialization=True)
    from transformers import AutoConfig

    cfg = config_from_hf(AutoConfig.from_pretrained(d), dtype="float32")
    mesh = make_mesh(MeshPlan(dp=2, tp=4))
    params = init_params(cfg, mesh, jax.random.key(0))

    short_engine = DecodeEngine(cfg, params, mesh, max_seq_len=8)
    long_engine = DecodeEngine(cfg, params, mesh, max_seq_len=16)
    assert short_engine.cfg.rope_freq_factors == (1.0,) * half
    assert long_engine.cfg.rope_freq_factors == (4.0,) * half
