"""Parity: Pallas stacked-cache decode kernel vs the XLA oracle.

``ops.pallas_decode.decode_attention`` must be bit-compatible (to fp
tolerance) with ``ops.attention.fresh_kv_decode_attention`` applied to the
sliced layer, across ring wrap, sliding windows, GQA/MQA grouping, and
empty caches. Runs in interpret mode on CPU (tests/conftest.py forces the
CPU platform)."""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from llmss_tpu.ops.attention import fresh_kv_decode_attention
from llmss_tpu.ops.pallas_decode import decode_attention, supports


def _mk(B, T, Hq, Hkv, D, L=3, n_valid=None, seed=0, dtype=jnp.float32):
    rng = np.random.default_rng(seed)

    def arr(*shape):
        return jnp.asarray(rng.standard_normal(shape), dtype)

    q = arr(B, 1, Hq, D)
    k_cache = arr(L, B, T, Hkv, D)
    v_cache = arr(L, B, T, Hkv, D)
    k_new = arr(B, 1, Hkv, D)
    v_new = arr(B, 1, Hkv, D)
    n_valid = T if n_valid is None else n_valid
    # Ring semantics: row b holds positions [0, n_valid + b); slot p % T
    # ends up holding the latest position written there (wrap overwrites).
    kv_pos = np.full((B, T), -1, np.int32)
    q_pos = np.zeros((B, 1), np.int32)
    slots = np.zeros((B, 1), np.int32)
    for b in range(B):
        nv = n_valid + b
        for p in range(nv):
            kv_pos[b, p % T] = p
        q_pos[b, 0] = nv
        slots[b, 0] = nv % T
    return q, k_cache, v_cache, k_new, v_new, (
        jnp.asarray(q_pos), jnp.asarray(kv_pos), jnp.asarray(slots)
    )


@pytest.mark.parametrize(
    "B,T,Hq,Hkv,D,n_valid,window",
    [
        (2, 32, 4, 4, 128, 16, None),  # MHA, half-full cache
        (2, 32, 4, 4, 128, 40, None),  # ring wrap (positions past T)
        (1, 64, 8, 2, 128, 64, None),  # GQA G=4, full
        (2, 32, 4, 1, 128, 20, None),  # MQA
        (2, 32, 4, 4, 128, 30, 8),  # sliding window
        (1, 16, 2, 2, 128, 0, None),  # empty cache -> out == v_new-ish
        (2, 24, 4, 4, 128, 24, None),  # T not a power of two (bk halving)
    ],
)
def test_parity_vs_xla(B, T, Hq, Hkv, D, n_valid, window):
    q, kc, vc, kn, vn, (q_pos, kv_pos, slots) = _mk(
        B, T, Hq, Hkv, D, n_valid=n_valid
    )
    assert supports(T, Hq, Hkv, D)
    layer = 1
    want = fresh_kv_decode_attention(
        q, kc[layer], vc[layer], kn, vn, q_pos, kv_pos, slots,
        window=window,
    )
    got = decode_attention(
        q, kc, vc, kn, vn, q_pos, kv_pos, slots, jnp.int32(layer),
        window=window, interpret=True,
    )
    np.testing.assert_allclose(
        np.asarray(got), np.asarray(want), rtol=2e-5, atol=2e-5
    )


def test_layer_indexing():
    """Each layer index must read its own slice of the stacked cache."""
    q, kc, vc, kn, vn, (q_pos, kv_pos, slots) = _mk(2, 32, 4, 4, 128, L=4)
    outs = []
    for layer in range(4):
        want = fresh_kv_decode_attention(
            q, kc[layer], vc[layer], kn, vn, q_pos, kv_pos, slots
        )
        got = decode_attention(
            q, kc, vc, kn, vn, q_pos, kv_pos, slots, jnp.int32(layer),
            interpret=True,
        )
        np.testing.assert_allclose(
            np.asarray(got), np.asarray(want), rtol=2e-5, atol=2e-5
        )
        outs.append(np.asarray(got))
    # Layers hold different KV, so outputs must differ.
    assert not np.allclose(outs[0], outs[1])


def test_bf16_dtype():
    q, kc, vc, kn, vn, (q_pos, kv_pos, slots) = _mk(
        2, 32, 4, 4, 128, n_valid=16, dtype=jnp.bfloat16
    )
    want = fresh_kv_decode_attention(
        q, kc[0], vc[0], kn, vn, q_pos, kv_pos, slots
    )
    got = decode_attention(
        q, kc, vc, kn, vn, q_pos, kv_pos, slots, jnp.int32(0),
        interpret=True,
    )
    assert got.dtype == jnp.bfloat16
    np.testing.assert_allclose(
        np.asarray(got, np.float32), np.asarray(want, np.float32),
        rtol=3e-2, atol=3e-2,
    )


def test_forward_integration_kernel_vs_xla(devices):
    """Full fused decode through DecodeEngine: the stacked-cache kernel path
    (forced via IMPL_OVERRIDE='pallas', interpret mode) must produce the
    same greedy tokens as the XLA fresh-KV path on the same 8-device mesh."""
    import importlib

    import jax

    from llmss_tpu.engine import DecodeEngine, GenerationParams
    from llmss_tpu.models.common import DecoderConfig
    from llmss_tpu.models.decoder import init_params
    from llmss_tpu.parallel import MeshPlan, make_mesh

    attn_mod = importlib.import_module("llmss_tpu.ops.attention")

    cfg = DecoderConfig(
        model_type="llama", vocab_size=128, hidden_size=256, n_layers=2,
        n_heads=8, n_kv_heads=4, head_dim=128, intermediate_size=128,
        max_position_embeddings=64, activation="silu", norm="rmsnorm",
        norm_eps=1e-5, mlp="swiglu", positions="rotary", rope_style="half",
        rotary_dim=128, attn_bias=False, mlp_bias=False,
        tie_word_embeddings=False, dtype="float32",
    )
    mesh = make_mesh(MeshPlan(dp=2, tp=4))
    params = init_params(cfg, mesh, jax.random.key(3))
    prompts = [[5, 9, 23, 40], [3, 14, 15, 9, 26, 5]]
    gen = GenerationParams(max_new_tokens=8, is_greedy=True)

    outs = {}
    old = attn_mod.IMPL_OVERRIDE
    for impl in ("xla", "pallas"):
        attn_mod.IMPL_OVERRIDE = impl
        try:
            engine = DecodeEngine(cfg, params, mesh, max_seq_len=64)
            outs[impl] = engine.generate_fused(prompts, gen)
        finally:
            attn_mod.IMPL_OVERRIDE = old
    assert outs["xla"] == outs["pallas"], outs
