"""Fleet autoscaler (serve/controller.py): the control loop, closed.

Unit tests drive ``FleetController.tick(now=...)`` on a virtual
timeline against a real broker registry, so every robustness property
is asserted directly:

- **epoch fencing**: a controller that lost leadership plans actions
  but actuates nothing — every call dies at the broker fence;
- **crash + restart reconciliation**: a fresh controller instance
  counts still-cold-starting replicas as observed capacity, so a
  restart never double-spawns;
- **do-no-harm**: floor, last-routable, cooldown, and stale-telemetry
  holds each block the exact actuation they exist to block;
- **hysteresis + dwell**: pressure that appears and vanishes within
  the dwell window never moves the fleet;
- **scale-before-shed**: the escalation contract the brownout ladder
  consults — shedding only when scaling structurally cannot respond.

Integration tests run the real serving stack on BOTH delivery
substrates (InProcBroker and RedisBroker over FakeRedis): the
supervisor's last-routable drain guard, and a controller-retired
replica releasing its leases as refunds (no redelivery, no consumed
attempt, never swept by failover). Sim tests replay a small diurnal
autoscale scenario byte-identically and crash the controller mid-climb.
"""

import copy
import json
import threading
import time

import pytest

from llmss_tpu.serve.broker import InProcBroker, RedisBroker
from llmss_tpu.serve.chaos import FakeRedis, ScriptedEngine
from llmss_tpu.serve.consumer import Worker
from llmss_tpu.serve.controller import FleetController
from llmss_tpu.serve.producer import QueueDrainEstimator, admission_verdict
from llmss_tpu.serve.protocol import STATE_DEAD, GenerateRequest
from llmss_tpu.serve.supervisor import Supervisor
from llmss_tpu.sim import run_scenario
from llmss_tpu.sim.invariants import InvariantChecker

BROKER_KINDS = ("inproc", "fakeredis")


def make_brokers(kind, *, lease_s=5.0, max_attempts=6, n_workers=1):
    """(producer_broker, [worker_broker, ...]) on one substrate."""
    if kind == "inproc":
        b = InProcBroker(lease_s=lease_s, max_delivery_attempts=max_attempts)
        return b, [b] * n_workers
    server = FakeRedis()

    def mk(wid):
        return RedisBroker(
            client=server, worker_id=wid, lease_s=lease_s,
            max_delivery_attempts=max_attempts,
        )

    return mk("producer"), [mk(f"worker{i}") for i in range(n_workers)]


# -- unit-test scaffolding ----------------------------------------------------


class Tel:
    """Mutable telemetry source the tests steer tick by tick."""

    def __init__(self):
        self.now = 0.0
        self.burn = 1.0
        self.depth = 0
        self.util: dict = {}
        self.down = False
        self.ts_lag = 0.0

    def read(self):
        if self.down:
            return None
        return {
            "ts": self.now - self.ts_lag, "burn": self.burn,
            "queue_depth": self.depth, "handoff_depth": 0,
            "util": dict(self.util),
        }


def put_worker(broker, wid, *, role="unified", state="ready",
               hb_age=0.0, alive=True, hb_s=1.0):
    broker.publish_worker_load(wid, {
        "role": role, "state": state, "alive": alive,
        "heartbeat_ts": time.time() - hb_age, "heartbeat_s": hb_s,
    })


def make_ctrl(broker, tel, *, spawned=None, retired=None, **kw):
    """Controller with recording actuators; spawns register as starting."""
    spawned = spawned if spawned is not None else []
    retired = retired if retired is not None else []

    def spawn(role):
        wid = f"new-{len(spawned)}"
        spawned.append((role, wid))
        put_worker(broker, wid, role=role, state="starting")
        return wid

    def retire(wid):
        retired.append(wid)
        put_worker(broker, wid, state="draining")

    kw.setdefault("check_s", 0.5)
    kw.setdefault("cooldown_s", 1.0)
    kw.setdefault("dwell_s", 1.0)
    kw.setdefault("cold_start_s", 1.0)
    kw.setdefault("burn_headroom_s", 10.0)
    kw.setdefault("floor", 1)
    kw.setdefault("ceiling", 4)
    ctrl = FleetController(
        broker, spawn=spawn, retire=retire, read_telemetry=tel.read, **kw,
    )
    return ctrl, spawned, retired


def drive(ctrl, tel, t0, t1, step=0.5):
    """Tick the controller over [t0, t1]; returns the actions taken."""
    actions = []
    t = t0
    while t <= t1 + 1e-9:
        tel.now = t
        a = ctrl.tick(now=t)
        if a is not None:
            actions.append(dict(a, t=t))
        t += step
    return actions


# -- scaling, hysteresis, holds ----------------------------------------------


def test_scale_up_on_sustained_burn():
    broker = InProcBroker()
    put_worker(broker, "w0")
    tel = Tel()
    ctrl, spawned, _ = make_ctrl(broker, tel)
    ctrl.start()

    tel.burn = 2.5  # hot, sustained
    acts = drive(ctrl, tel, 0.0, 1.0)
    assert [(a["kind"], a["role"]) for a in acts] == [("spawn", "unified")]
    assert spawned == [("unified", "new-0")]
    # Dwell was respected: no action before a full dwell_s of pressure.
    assert acts[0]["t"] >= ctrl.dwell_s


def test_scale_down_retires_to_floor_and_stops():
    broker = InProcBroker()
    for wid in ("w0", "w1", "w2"):
        put_worker(broker, wid)
    tel = Tel()
    ctrl, _, retired = make_ctrl(broker, tel)
    ctrl.start()

    tel.burn = 0.1  # cold and idle
    drive(ctrl, tel, 0.0, 12.0)
    # Retired down to the floor (1) and NOT past it: one replica of the
    # role must always remain, however long the quiet lasts.
    assert retired == ["w2", "w1"]  # LIFO: newest first
    assert ctrl.counters["retires"] == 2
    # The actuation-time guard backstops the planner against the
    # registry shrinking between plan and act (e.g. a concurrent kill).
    obs = ctrl.observe()
    assert obs["unified"]["ready"] == 1
    assert ctrl._guard({"kind": "retire", "role": "unified"}, obs) == "floor"
    assert ctrl.counters["blocked_floor"] == 1


def test_flapping_pressure_never_moves_the_fleet():
    broker = InProcBroker()
    put_worker(broker, "w0")
    tel = Tel()
    ctrl, spawned, retired = make_ctrl(broker, tel)
    ctrl.start()

    # Burn alternates hot/neutral every tick: always below dwell.
    t = 0.0
    while t <= 10.0:
        tel.now = t
        tel.burn = 2.5 if int(t * 2) % 2 == 0 else 1.0
        ctrl.tick(now=t)
        t += 0.5
    assert spawned == [] and retired == []
    assert ctrl.counters["ticks"] > 0


def test_stale_telemetry_holds_and_resets_dwell():
    broker = InProcBroker()
    put_worker(broker, "w0")
    tel = Tel()
    ctrl, spawned, _ = make_ctrl(broker, tel)
    ctrl.start()

    tel.burn = 2.5
    drive(ctrl, tel, 0.0, 0.5)      # pressure building, not yet dwelled
    tel.down = True
    drive(ctrl, tel, 1.0, 1.5)      # telemetry plane dies mid-dwell
    assert ctrl.counters["held_stale"] == 2
    assert spawned == []
    tel.down = False
    # Pressure must re-prove itself on fresh data: a spawn at t=2.0
    # would mean the pre-outage dwell credit survived the hold.
    acts = drive(ctrl, tel, 2.0, 3.5)
    assert spawned != []
    assert acts[0]["t"] >= 2.0 + ctrl.dwell_s


def test_stale_ts_field_is_a_hold_too():
    broker = InProcBroker()
    put_worker(broker, "w0")
    tel = Tel()
    ctrl, spawned, _ = make_ctrl(broker, tel, telemetry_max_age_s=2.0)
    ctrl.start()
    tel.burn = 2.5
    tel.ts_lag = 10.0  # snapshots exist but are ancient
    drive(ctrl, tel, 0.0, 3.0)
    assert spawned == []
    assert ctrl.counters["held_stale"] == 7


def test_cooldown_allows_one_actuation_per_window():
    broker = InProcBroker()
    put_worker(broker, "w0")
    tel = Tel()
    ctrl, spawned, _ = make_ctrl(broker, tel, cooldown_s=6.0)
    ctrl.start()

    tel.burn = 3.0  # hot the whole time
    acts = drive(ctrl, tel, 0.0, 11.0)
    # First spawn at dwell (t=1.0); the window [1.0, 7.0) admits no
    # second actuation however hot the signal stays.
    assert len(acts) == 2
    assert acts[1]["t"] - acts[0]["t"] >= 6.0
    assert ctrl.counters["held_cooldown"] > 0
    assert [r for r, _ in spawned] == ["unified", "unified"]


def test_never_drains_last_routable_even_with_zero_floor():
    broker = InProcBroker()
    put_worker(broker, "w0")
    tel = Tel()
    ctrl, _, retired = make_ctrl(broker, tel, floor=0)
    ctrl.start()
    tel.burn = 0.0
    drive(ctrl, tel, 0.0, 6.0)
    assert retired == []
    # And even if a retire were forced through the planner, the guard
    # refuses to take the role to zero regardless of the floor.
    obs = ctrl.observe()
    assert ctrl._guard(
        {"kind": "retire", "role": "unified"}, obs
    ) == "last-routable"
    assert ctrl.counters["blocked_last_routable"] == 1


def test_ceiling_blocks_spawn():
    broker = InProcBroker()
    for wid in ("w0", "w1"):
        put_worker(broker, wid)
    tel = Tel()
    ctrl, spawned, _ = make_ctrl(broker, tel, ceiling=2)
    ctrl.start()
    tel.burn = 3.0
    drive(ctrl, tel, 0.0, 6.0)
    assert spawned == []
    assert ctrl.counters["blocked_ceiling"] > 0


# -- observation: registry staleness ------------------------------------------


def test_observe_skips_dead_and_stale_rows():
    broker = InProcBroker()
    put_worker(broker, "fresh")
    put_worker(broker, "killed", hb_age=60.0)  # snapshot frozen at ready
    put_worker(broker, "tombstone", alive=False)
    put_worker(broker, "starting", state="starting")
    tel = Tel()
    ctrl, _, _ = make_ctrl(broker, tel)
    obs = ctrl.observe()
    assert obs["unified"]["ready"] == 1
    assert obs["unified"]["ready_ids"] == ["fresh"]
    assert obs["unified"]["starting"] == 1
    # A hard-killed replica's last snapshot says "ready" forever; only
    # the heartbeat age tells the truth. Counting it would both block
    # scale-up at a phantom ceiling and hide the need to replace it.
    assert ctrl._live(obs, "unified") == 2


# -- epoch fencing + crash/restart reconciliation -----------------------------


def test_stale_epoch_controller_is_fully_fenced():
    broker = InProcBroker()
    put_worker(broker, "w0")
    tel = Tel()
    old, old_spawned, old_retired = make_ctrl(
        broker, tel, controller_id="old",
    )
    old.start()
    new, new_spawned, _ = make_ctrl(broker, tel, controller_id="new")
    new.start()  # bumps the epoch: "old" is now a zombie
    assert broker.controller_holder() == "new"

    tel.burn = 3.0
    drive(old, tel, 0.0, 5.0)
    # The zombie planned spawns every cooldown — and actuated nothing.
    assert old_spawned == [] and old_retired == []
    assert old.counters["fenced"] > 0

    acts = drive(new, tel, 5.5, 7.0)
    assert new_spawned != [] and acts


def test_crash_restart_never_duplicates_inflight_spawns():
    broker = InProcBroker()
    put_worker(broker, "w0")
    tel = Tel()
    first, spawned, _ = make_ctrl(broker, tel, ceiling=2)
    first.start()
    tel.burn = 3.0
    drive(first, tel, 0.0, 1.0)
    assert len(spawned) == 1  # cold-starting, registered as "starting"

    # Controller crashes; a brand-new instance (no in-memory state)
    # reconciles purely from the registry.
    second, spawned2, _ = make_ctrl(broker, tel, ceiling=2)
    second.start()
    drive(second, tel, 2.0, 8.0)
    # The in-flight spawn counts as observed capacity: at ceiling 2
    # (1 ready + 1 starting) the restart spawns NOTHING.
    assert spawned2 == []
    assert second.counters["blocked_ceiling"] > 0
    obs = second.observe()
    assert obs["unified"]["starting"] == 1


# -- escalation contract (scale-before-shed) ----------------------------------


def test_escalation_suppressed_while_scaling_can_respond():
    broker = InProcBroker()
    put_worker(broker, "w0")
    tel = Tel()
    ctrl, _, _ = make_ctrl(
        broker, tel, cold_start_s=2.0, burn_headroom_s=10.0,
    )
    ctrl.start()
    tel.now = 1.0
    assert ctrl.escalation_allowed(now=1.0) is False
    assert ctrl.counters["escalations_suppressed"] == 1


def test_escalation_allowed_when_cold_start_exceeds_headroom():
    broker = InProcBroker()
    put_worker(broker, "w0")
    tel = Tel()
    ctrl, _, _ = make_ctrl(
        broker, tel, cold_start_s=30.0, burn_headroom_s=10.0,
    )
    ctrl.start()
    tel.now = 1.0
    # Reinforcement cannot arrive inside the burn window no matter when
    # it was ordered: shedding is the only lever that works in time.
    assert ctrl.escalation_allowed(now=1.0) is True
    assert ctrl.counters["escalations_allowed"] == 1


def test_escalation_allowed_at_ceiling_and_when_blind():
    broker = InProcBroker()
    put_worker(broker, "w0")
    put_worker(broker, "w1", state="starting")
    tel = Tel()
    ctrl, _, _ = make_ctrl(
        broker, tel, ceiling=2, cold_start_s=2.0, burn_headroom_s=10.0,
    )
    ctrl.start()
    tel.now = 1.0
    # At ceiling — counting the cold-starting spawn — there is no
    # capacity left to add.
    assert ctrl.escalation_allowed(now=1.0) is True
    # Blind controller must not pin brownout down.
    tel.down = True
    assert ctrl.escalation_allowed(now=2.0) is True
    assert ctrl.counters["escalations_allowed"] == 2


# -- P:D reshaping ------------------------------------------------------------


def test_reshape_spawns_before_retiring_donor():
    broker = InProcBroker()
    for wid in ("p0", "p1"):
        put_worker(broker, wid, role="prefill")
    for wid in ("d0", "d1"):
        put_worker(broker, wid, role="decode")
    tel = Tel()
    ctrl, spawned, retired = make_ctrl(
        broker, tel, roles=("prefill", "decode"),
        floor={"prefill": 1, "decode": 1},
    )
    ctrl.start()

    # Prefill saturated (MFU-bound) while decode idles: the fleet's
    # P:D ratio is wrong for the offered phase mix.
    tel.util = {"prefill": 0.95, "decode": 0.1}
    acts = drive(ctrl, tel, 0.0, 2.0)
    assert [(a["kind"], a["role"]) for a in acts] == [
        ("reshape-spawn", "prefill"),
    ]
    assert ctrl.state()["reshape_debt"] == "decode"
    assert retired == []  # spawn strictly first: capacity never dips

    # The spawned prefill replica comes ready; the donor retirement debt
    # settles on a later tick.
    put_worker(broker, spawned[0][1], role="prefill")
    tel.util = {}
    acts = drive(ctrl, tel, 2.5, 5.0)
    assert [(a["kind"], a["role"]) for a in acts] == [
        ("reshape-retire", "decode"),
    ]
    assert retired == ["d1"]
    assert ctrl.counters["reshape_spawns"] == 1
    assert ctrl.counters["reshape_retires"] == 1


# -- invariant catalog items 7-9 ----------------------------------------------


def test_checker_flags_duplicate_spawn_and_unordered_retire():
    ic = InvariantChecker()
    ic.note_worker("w0")
    ic.on_controller_spawn("w1")
    ic.on_controller_drain("w1")
    ic.on_controller_retired("w1")
    assert ic._violations == []

    ic.on_controller_spawn("w0")  # duplicate of the seed fleet
    ic.on_controller_retired("w2")  # never drained
    ic.on_fleet_retire("unified", remaining=0, floor=1)
    msgs = "\n".join(ic._violations)
    assert "duplicate worker_id" in msgs
    assert "without a preceding drain" in msgs
    assert "below floor" in msgs
    assert len(ic._violations) == 3


# -- satellite: honest Retry-After from the queue drain rate ------------------


def test_retry_after_tracks_queue_drain_rate():
    est = QueueDrainEstimator(window_s=30.0, min_s=1, max_s=30)
    assert est.retry_after_s(50, now=0.0) == 1  # no signal: legacy 1s

    # 20 admissions over 10s while depth stays flat: service rate 2/s.
    for i in range(21):
        est.note_admitted(depth=10, now=float(i) / 2.0)
    assert est.retry_after_s(10, now=10.0) == 5    # 10 / (2/s)
    assert est.retry_after_s(30, now=10.0) == 15   # deeper -> longer
    assert est.retry_after_s(2, now=10.0) == 1     # shallow -> clamp floor

    # Queue grew faster than admissions: nothing is draining — back off
    # to the max rather than inviting a thundering herd in 1s.
    est2 = QueueDrainEstimator(window_s=30.0, max_s=30)
    est2.note_admitted(depth=0, now=0.0)
    est2.note_admitted(depth=50, now=5.0)
    assert est2.retry_after_s(50, now=5.0) == 30


def test_admission_verdict_derives_retry_after_from_estimator():
    broker = InProcBroker()
    for i in range(8):
        broker.push_request(GenerateRequest(
            id=f"q{i}", token_ids=[1], max_new_tokens=1,
        ))
    est = QueueDrainEstimator()
    for i in range(11):
        est.note_admitted(depth=8, now=float(i))  # 1 req/s service rate
    req = GenerateRequest(id="shed-me", token_ids=[1], max_new_tokens=1)

    verdict = admission_verdict(req, broker, max_queue_depth=4, drain=est)
    assert verdict is not None
    status, body, headers = verdict
    assert status == 429 and body["queue_depth"] == 8
    assert headers["Retry-After"] == str(est.retry_after_s(8))
    assert int(headers["Retry-After"]) >= 8  # 8 deep at ~1/s

    # Without an estimator the legacy constant stands.
    _, _, h = admission_verdict(req, broker, max_queue_depth=4)
    assert h["Retry-After"] == "1"


# -- satellite: last-routable drain guard (both substrates) -------------------


def _supervised(engine, wb, worker_id):
    def factory():
        return Worker(
            engine, wb, batch_size=2, poll_timeout_s=0.02, pad_batch=False,
            worker_id=worker_id,
        )

    sup = Supervisor(factory, wb, backoff_s=0.01, heartbeat_s=0.05)
    stop = threading.Event()
    t = threading.Thread(target=sup.run, args=(stop,), daemon=True)
    t.start()
    return sup, t


def _wait_routable(prod, wid, timeout_s=10.0):
    from llmss_tpu.serve.fleet import routable_workers

    deadline = time.time() + timeout_s
    while time.time() < deadline:
        if wid in routable_workers(prod):
            return
        time.sleep(0.02)
    raise AssertionError(f"{wid} never became routable")


@pytest.mark.parametrize("kind", BROKER_KINDS)
def test_drain_guard_blocks_last_routable_until_forced(kind):
    prod, (wb,) = make_brokers(kind)
    sup, t = _supervised(ScriptedEngine(), wb, "guard-zz")
    try:
        _wait_routable(prod, "guard-zz")
        # The only routable replica: draining it takes the fleet to zero.
        assert sup.drain(timeout_s=5.0) is False
        assert not sup.draining
        info = prod.read_workers()["guard-zz"]
        assert "last routable" in info["drain_blocked"]
        # Deliberate teardown stays possible.
        assert sup.drain(timeout_s=5.0, force=True) is True
        t.join(timeout=20.0)
        assert not t.is_alive()
        assert prod.read_workers()["guard-zz"]["state"] == STATE_DEAD
    finally:
        sup.drain(force=True)


@pytest.mark.parametrize("kind", BROKER_KINDS)
def test_drain_guard_allows_with_routable_peer(kind):
    prod, (wb1, wb2) = make_brokers(kind, n_workers=2)
    sup, t = _supervised(ScriptedEngine(), wb1, "guard-zz")
    try:
        _wait_routable(prod, "guard-zz")
        # A second routable replica of the same role makes the drain safe
        # (construction registers it ready with a fresh heartbeat).
        Worker(
            ScriptedEngine(), wb2, batch_size=2, poll_timeout_s=0.02,
            pad_batch=False, worker_id="guard-aa",
        )
        _wait_routable(prod, "guard-aa")
        assert sup.drain(timeout_s=5.0) is True
        t.join(timeout=20.0)
        assert not t.is_alive()
    finally:
        sup.drain(force=True)


# -- satellite: controller retirement releases leases as refunds --------------


@pytest.fixture(scope="module")
def tiny_engine(devices):
    import jax

    from llmss_tpu.engine import DecodeEngine
    from llmss_tpu.models.common import DecoderConfig
    from llmss_tpu.models.decoder import init_params
    from llmss_tpu.parallel import MeshPlan, make_mesh

    mesh = make_mesh(MeshPlan(dp=1, sp=1, tp=8))
    cfg = DecoderConfig(
        model_type="llama", vocab_size=128, hidden_size=32, n_layers=1,
        n_heads=4, n_kv_heads=4, head_dim=8, intermediate_size=64,
        max_position_embeddings=64, activation="silu", norm="rmsnorm",
        norm_eps=1e-5, mlp="swiglu", positions="rotary", rope_style="half",
        rotary_dim=8, attn_bias=False, mlp_bias=False,
        tie_word_embeddings=False, dtype="float32",
    )
    params = init_params(cfg, mesh, jax.random.key(0))
    return DecodeEngine(cfg, params, mesh, max_seq_len=32)


@pytest.mark.parametrize("kind", BROKER_KINDS)
def test_controller_retire_drains_and_refunds_leases(kind, tiny_engine):
    """A replica the controller retires while it holds leased work must
    give that work back as a REFUND: no redelivery counted, no delivery
    attempt consumed (max_attempts=1 would dead-letter any), and the
    failover sweeper never touches it — draining is not dying."""
    from llmss_tpu.serve.consumer import ContinuousWorker
    from llmss_tpu.serve.fleet import Router

    prod, (wb1, wb2) = make_brokers(kind, max_attempts=1, n_workers=2)
    w_old = ContinuousWorker(
        tiny_engine, wb1, rows=2, poll_timeout_s=0.0, chunk_steps=2,
        worker_id="ret-zz",
    )
    w_new = ContinuousWorker(
        tiny_engine, wb2, rows=2, poll_timeout_s=0.0, chunk_steps=2,
        worker_id="ret-aa",
    )
    reqs = [
        GenerateRequest(
            id=f"ret{i}", token_ids=[1 + i, 2], max_new_tokens=3,
            is_greedy=True,
        )
        for i in range(6)
    ]
    for r in reqs:
        prod.push_request(r)
    w_old.run_once()  # leases everything: 2 active rows + 4 pending
    assert prod.queue_depth() == 0

    retire_calls = []

    def retire(wid):
        retire_calls.append(wid)
        w_old.begin_drain()
        released = w_old.release_pending()
        assert released == 4, "leased-not-started work must be refunded"

    # The first run_once paid the XLA compile (tens of wall seconds), so
    # both construction-time heartbeats are stale by now — refresh them,
    # exactly as the serving loop's periodic publisher would have.
    w_old._publish_load()
    w_new._publish_load()

    tel = Tel()
    tel.burn = 0.1  # cold: the controller wants to shrink the fleet
    ctrl = FleetController(
        prod, spawn=lambda role: "never", retire=retire,
        read_telemetry=tel.read, floor=1, ceiling=4, check_s=0.5,
        cooldown_s=1.0, dwell_s=1.0,
    )
    ctrl.start()
    drive(ctrl, tel, 0.0, 2.0)
    # LIFO retire of the sorted registry: ret-zz (the lease holder).
    assert retire_calls == ["ret-zz"]

    # Mid-drain, with leases still held: the failover sweeper must leave
    # the draining worker alone — its heartbeat is fresh and its leases
    # are renewed; only DEAD capacity gets evacuated.
    router = Router(prod, policy="least_loaded")
    assert router.check_failover(force=True) == 0
    assert router.stats()["failover_reroutes"] == 0

    # The drain finishes its two active rows cleanly...
    deadline = time.time() + 120.0
    while not w_old.batcher.idle and time.time() < deadline:
        w_old.run_once()
    assert w_old.drained

    # ...and the refunded four are served by the surviving replica.
    got = {}
    while len(got) < len(reqs) and time.time() < deadline:
        w_new.run_once()
        for r in reqs:
            if r.id not in got:
                resp = prod.wait_response(r.id, timeout=0.001)
                if resp is not None:
                    got[r.id] = resp
    assert set(got) == {r.id for r in reqs}
    for rid, resp in got.items():
        assert resp.error is None, (rid, resp.error)

    stats = prod.delivery_stats()
    assert stats.get("redelivered", 0) == 0
    assert stats.get("inflight", 0) == 0
    # max_delivery_attempts=1: had the refund consumed an attempt, every
    # re-leased request would have dead-lettered instead of serving.
    assert prod.read_dlq(limit=100) == []


# -- sim: closed-loop autoscale scenarios -------------------------------------


def autoscale_spec(broker_kind="inproc", seed=5, **over):
    """Small diurnal surge: 1 replica cannot carry the peak, 4 can."""
    spec = {
        "format": "llmss-scenario/1",
        "name": f"autoscale-{broker_kind}",
        "seed": seed,
        "duration_s": 600.0,
        "broker": {
            "kind": broker_kind, "lease_s": 2.0, "max_delivery_attempts": 8,
        },
        "cost_model": {
            "kind": "table", "decode_step_s": 0.02,
            "prefill_token_s": 0.0002,
        },
        "fleet": {
            "replicas": [{"count": 1, "role": "unified", "rows": 4}],
            "router_policy": "least_loaded",
            "failover_check_s": 1.0,
            "controller": {
                "floor": 1, "ceiling": 4, "cold_start_s": 1.0,
                "check_s": 0.5, "cooldown_s": 2.0, "dwell_s": 1.0,
                "burn_headroom_s": 10.0, "scale_up_burn": 1.2,
                "scale_down_burn": 0.4, "backlog_high": 2.0,
                "backlog_low": 0.4, "ttft_target_s": 0.5,
            },
        },
        "workload": {
            "kind": "synthetic", "requests": 850, "rate_rps": 3.0,
            "arrival": "poisson", "prompt_len": [8, 24],
            "max_new": [16, 48],
            "classes": {"interactive": 0.3, "standard": 0.7},
            "rate_profile": [
                [0, 0.5], [20, 2.5], [60, 3.0], [100, 1.0], [130, 0.4],
            ],
        },
        "metrics": {"per_class": True},
    }
    spec.update(over)
    return spec


def run_twice(spec):
    a = json.dumps(run_scenario(copy.deepcopy(spec)), sort_keys=True)
    b = json.dumps(run_scenario(copy.deepcopy(spec)), sort_keys=True)
    assert a == b, "same-seed autoscale replay diverged"
    return json.loads(a)


def test_sim_autoscale_deterministic_and_scales():
    r = run_twice(autoscale_spec())
    fl = r["fleet"]
    assert r["invariants"]["violations"] == 0
    assert r["requests"]["ok"] == r["requests"]["submitted"]
    # The controller actually worked the trace: grew into the surge,
    # shrank back after it, and never breached its envelope.
    assert fl["spawns"] > 0 and fl["retires"] > 0
    assert 1 <= fl["replicas_end"] <= fl["peak_alive"] <= 4
    assert fl["peak_alive"] > 1
    assert fl["controller"]["counters"]["fenced"] == 0


def test_sim_autoscale_fakeredis():
    """Same control loop through the real RedisBroker code paths
    (epoch INCR fencing included) on the virtual-clock FakeRedis."""
    r = run_twice(autoscale_spec(broker_kind="fakeredis", requests=200))
    assert r["invariants"]["violations"] == 0
    assert r["fleet"]["spawns"] > 0
    assert r["fleet"]["peak_alive"] > 1


def test_sim_controller_crash_zombie_fenced():
    """Crash the controller mid-surge, restart it 2s later, and leave
    the dead instance ticking as a zombie: the fresh epoch reconciles
    from the registry (zero duplicate spawns — checker-certified) while
    every actuation the zombie plans dies at the broker fence."""
    spec = autoscale_spec(seed=9)
    spec["faults"] = [
        {"kind": "controller_crash", "at_s": 25.0,
         "restart_after_s": 2.0, "zombie": True},
    ]
    r = run_twice(spec)
    assert r["invariants"]["violations"] == 0
    assert r["faults"]["controller_crashes"] == 1
    assert r["faults"]["controller_restarts"] == 1
    fl = r["fleet"]
    assert fl["zombie_fenced"] > 0       # the zombie kept planning
    assert fl["controller"]["counters"]["fenced"] == 0  # the live one never
    assert fl["spawns"] > 0


# -- chaos: flapping registration ---------------------------------------------


@pytest.mark.parametrize("kind", BROKER_KINDS)
def test_chaos_flap_registration_storm(kind):
    """tools/chaos_serve.py --fault flap: a worker registering and
    deregistering every few ms must never be routed to mid-gap, never
    draw a controller actuation, and exactly-one-terminal must hold."""
    import os
    import subprocess
    import sys

    repo = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
    proc = subprocess.run(
        [sys.executable, os.path.join(repo, "tools", "chaos_serve.py"),
         "--fault", "flap", "--requests", "24", "--broker", kind],
        capture_output=True, text=True, timeout=300,
        env={**os.environ, "JAX_PLATFORMS": "cpu"},
    )
    assert proc.returncode == 0, proc.stdout + proc.stderr
    report = json.loads(proc.stdout.strip().splitlines()[-1])
    assert report["violation"] is None
    assert report["routed_mid_gap"] == 0
    assert report["controller_actions"] == 0
    assert report["ok"] == report["requests"]
    assert report["flaps"] >= 3
