"""At-least-once delivery: leases, redelivery, DLQ, deadlines, admission.

Pins the broker delivery contract (serve/broker.py docstring) on both
substrates: ``InProcBroker`` directly, and the real ``RedisBroker`` code
against the in-memory ``FakeRedis`` (serve/chaos.py) — same primitives a
real server provides, no server required.
"""

import threading
import time

import pytest

from llmss_tpu.serve.broker import InProcBroker, RedisBroker
from llmss_tpu.serve.chaos import FakeRedis, ScriptedEngine
from llmss_tpu.serve.consumer import Worker
from llmss_tpu.serve.producer import ProducerServer
from llmss_tpu.serve.protocol import GenerateRequest, GenerateResponse


def make_broker(kind, **kw):
    if kind == "inproc":
        return InProcBroker(**kw)
    return RedisBroker(client=FakeRedis(), worker_id="w0", **kw)


BROKERS = ("inproc", "fakeredis")


# -- lease / ack ------------------------------------------------------------


@pytest.mark.parametrize("kind", BROKERS)
def test_ack_prevents_redelivery(kind):
    b = make_broker(kind, lease_s=0.05)
    b.push_request(GenerateRequest(id="r1", token_ids=[1]))
    req = b.pop_request()
    assert req.id == "r1" and req.delivery_attempts == 1
    b.push_response(GenerateResponse(id="r1", token_ids=[2]))  # ack
    time.sleep(0.1)  # lease would have expired had it not been acked
    assert b.reap_expired() == 0
    assert b.pop_request() is None
    assert b.wait_response("r1", timeout=1).token_ids == [2]


@pytest.mark.parametrize("kind", BROKERS)
def test_expired_lease_is_redelivered(kind):
    b = make_broker(kind, lease_s=0.05)
    b.push_request(GenerateRequest(id="r1", token_ids=[1]))
    assert b.pop_request().delivery_attempts == 1
    # Worker dies holding the lease: no ack, no touch.
    time.sleep(0.1)
    again = b.pop_request()  # reaper runs here and requeues
    assert again is not None and again.id == "r1"
    assert again.delivery_attempts == 2
    assert b.delivery_stats()["redelivered"] == 1


@pytest.mark.parametrize("kind", BROKERS)
def test_touch_keeps_lease_alive(kind):
    b = make_broker(kind, lease_s=0.08)
    b.push_request(GenerateRequest(id="r1", token_ids=[1]))
    b.pop_request()
    for _ in range(4):  # a long decode, renewing every chunk
        time.sleep(0.04)
        b.touch_requests(["r1"])
    assert b.reap_expired() == 0
    assert b.pop_request() is None  # never redelivered


@pytest.mark.parametrize("kind", BROKERS)
def test_dead_letter_after_max_attempts(kind):
    b = make_broker(kind, lease_s=0.03, max_delivery_attempts=2)
    b.push_request(GenerateRequest(id="poison", token_ids=[1]))
    assert b.pop_request().delivery_attempts == 1
    time.sleep(0.06)
    assert b.pop_request().delivery_attempts == 2  # redelivery
    time.sleep(0.06)
    # Attempts exhausted: quarantined, not requeued.
    assert b.pop_request() is None
    assert b.dlq_depth() == 1
    dlq = b.read_dlq()
    assert dlq[0]["id"] == "poison" and dlq[0]["delivery_attempts"] == 2
    # The waiter gets a terminal error, not silence.
    resp = b.wait_response("poison", timeout=1)
    assert resp is not None and "dead-lettered after 2" in resp.error
    stats = b.delivery_stats()
    assert stats["dead_lettered"] == 1 and stats["dlq_depth"] == 1


@pytest.mark.parametrize("kind", BROKERS)
def test_deadline_shed_at_redelivery(kind):
    b = make_broker(kind, lease_s=0.03)
    b.push_request(GenerateRequest(
        id="late", token_ids=[1], deadline_ts=time.time() + 0.05,
    ))
    b.pop_request()
    time.sleep(0.1)  # lease AND deadline both expired
    assert b.pop_request() is None  # shed, not redelivered
    resp = b.wait_response("late", timeout=1)
    assert resp is not None and "deadline exceeded" in resp.error
    assert b.delivery_stats()["deadline_expired"] == 1


@pytest.mark.parametrize("kind", BROKERS)
def test_delivery_stats_shape(kind):
    b = make_broker(kind)
    b.push_request(GenerateRequest(id="a", token_ids=[1]))
    b.push_request(GenerateRequest(id="b", token_ids=[1]))
    assert b.queue_depth() == 2
    b.pop_request()
    stats = b.delivery_stats()
    assert stats["queue_depth"] == 1
    assert stats["inflight"] == 1
    assert stats["dlq_depth"] == 0
    assert stats["redelivered"] == 0


def test_cross_worker_redelivery_fakeredis():
    """A live worker recovers a dead worker's leases (the reaper runs on
    every pop, whoever the popper is)."""
    server = FakeRedis()
    dead = RedisBroker(client=server, worker_id="dead", lease_s=0.05)
    live = RedisBroker(client=server, worker_id="live", lease_s=0.05)
    dead.push_request(GenerateRequest(id="r1", token_ids=[1]))
    assert dead.pop_request().id == "r1"  # then the worker is SIGKILLed
    time.sleep(0.1)
    again = live.pop_request()
    assert again is not None and again.id == "r1"
    assert again.delivery_attempts == 2
    # The recovering worker now holds its own lease; its ack settles it.
    live.push_response(GenerateResponse(id="r1", token_ids=[7]))
    assert live.reap_expired() == 0
    assert live.wait_response("r1", timeout=1).token_ids == [7]


# -- satellite fixes --------------------------------------------------------


def test_inproc_response_ttl_reaps_uncollected():
    """Responses nobody waits for age out instead of leaking forever."""
    b = InProcBroker(response_ttl_s=0.01)
    b.push_response(GenerateResponse(id="orphan", token_ids=[1]))
    time.sleep(0.03)
    # Any later push runs the reap pass.
    b.push_response(GenerateResponse(id="fresh", token_ids=[2]))
    assert "orphan" not in b._responses
    assert b.wait_response("orphan", timeout=0.01) is None
    assert b.wait_response("fresh", timeout=1).token_ids == [2]


def test_inproc_dropped_stream_stays_dropped():
    """pop_stream after drop_stream must not resurrect the tombstoned
    queue (the leak the tombstone exists to prevent)."""
    b = InProcBroker()
    b.push_stream("s1", [1, 2])
    assert b.pop_stream("s1") == [1, 2]
    b.drop_stream("s1")
    assert b.pop_stream("s1") is None
    assert "s1" not in b._streams  # not resurrected by the pop
    b.push_stream("s1", [3])  # late worker flush
    assert "s1" not in b._streams
    assert b.pop_stream("s1") is None


# -- worker integration -----------------------------------------------------


def test_worker_sheds_expired_before_prefill():
    """An already-expired request never reaches the engine."""
    b = InProcBroker()
    eng = ScriptedEngine()
    w = Worker(eng, b, batch_size=2, poll_timeout_s=0.01, pad_batch=False)
    b.push_request(GenerateRequest(
        id="stale", token_ids=[5], max_new_tokens=4,
        deadline_ts=time.time() - 1,
    ))
    w.run_once()
    assert eng.generate_calls == 0
    assert eng.metrics.deadline_expired == 1
    resp = b.wait_response("stale", timeout=1)
    assert resp is not None and "deadline exceeded" in resp.error


def test_worker_acks_via_push_response():
    b = InProcBroker(lease_s=0.05)
    eng = ScriptedEngine()
    w = Worker(eng, b, batch_size=2, poll_timeout_s=0.01, pad_batch=False)
    b.push_request(GenerateRequest(id="ok", token_ids=[5], max_new_tokens=4))
    w.run_once()
    resp = b.wait_response("ok", timeout=1)
    assert resp.token_ids == ScriptedEngine.expected_tokens([5], 4)
    time.sleep(0.1)
    assert b.reap_expired() == 0  # settled, nothing to redeliver


# -- producer: admission control + admin surface ----------------------------


def _post(port, path, payload):
    import http.client
    import json

    conn = http.client.HTTPConnection("127.0.0.1", port, timeout=10)
    conn.request("POST", path, json.dumps(payload),
                 {"Content-Type": "application/json"})
    r = conn.getresponse()
    body = json.loads(r.read() or b"{}")
    headers = dict(r.getheaders())
    conn.close()
    return r.status, body, headers


def _get(port, path):
    import http.client
    import json

    conn = http.client.HTTPConnection("127.0.0.1", port, timeout=10)
    conn.request("GET", path)
    r = conn.getresponse()
    body = json.loads(r.read() or b"{}")
    conn.close()
    return r.status, body


def test_producer_sheds_when_queue_full():
    b = InProcBroker()
    # Fill the backlog past the admission limit; no worker drains it.
    b.push_request(GenerateRequest(id="old", token_ids=[1]))
    srv = ProducerServer(b, host="127.0.0.1", port=0, timeout_s=5.0,
                         max_queue_depth=1)
    srv.start()
    try:
        status, body, headers = _post(
            srv.port, "/generate", {"token_ids": [2], "max_new_tokens": 2},
        )
        assert status == 429
        assert body["error"] == "queue full"
        assert headers.get("Retry-After") == "1"
        assert b.queue_depth() == 1  # the shed request was never queued
    finally:
        srv.stop()


def test_producer_stamps_deadline():
    b = InProcBroker()
    srv = ProducerServer(b, host="127.0.0.1", port=0, timeout_s=7.0)
    srv.start()
    got = {}

    def worker():
        req = b.pop_request(timeout=5)
        got["req"] = req
        b.push_response(GenerateResponse(id=req.id, token_ids=[1]))

    t = threading.Thread(target=worker, daemon=True)
    t.start()
    try:
        before = time.time()
        status, body, _ = _post(
            srv.port, "/generate", {"token_ids": [2], "max_new_tokens": 2},
        )
        assert status == 200
        t.join(timeout=5)
        dl = got["req"].deadline_ts
        assert dl is not None
        assert before + 5.0 < dl <= time.time() + 7.0
    finally:
        srv.stop()


def test_producer_dlq_and_delivery_metrics():
    b = InProcBroker(lease_s=0.02, max_delivery_attempts=1)
    srv = ProducerServer(b, host="127.0.0.1", port=0)
    srv.start()
    try:
        b.push_request(GenerateRequest(id="p1", token_ids=[1]))
        b.pop_request()  # leased, worker "dies"
        time.sleep(0.05)
        b.reap_expired()  # attempts exhausted -> DLQ
        status, body = _get(srv.port, "/dlq")
        assert status == 200
        assert body["depth"] == 1
        assert body["requests"][0]["id"] == "p1"
        status, body = _get(srv.port, "/metrics")
        assert status == 200
        assert body["delivery"]["dead_lettered"] == 1
        assert body["delivery"]["dlq_depth"] == 1
    finally:
        srv.stop()
