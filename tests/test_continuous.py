"""Continuous batching: row isolation, staggered admission, serving loop."""

import threading

import numpy as np
import pytest

from llmss_tpu.engine import DecodeEngine, GenerationParams
from llmss_tpu.engine.scheduler import ContinuousBatcher
from llmss_tpu.models import config_from_hf
from llmss_tpu.models.registry import MODEL_REGISTRY
from llmss_tpu.parallel import MeshPlan, make_mesh
from llmss_tpu.weights import CheckpointShards, weight_files


@pytest.fixture(scope="module")
def engine(tmp_path_factory, devices):
    import torch
    import transformers as tr

    torch.manual_seed(21)
    cfg_hf = tr.GPT2Config(
        vocab_size=64, n_positions=64, n_embd=32, n_layer=2, n_head=4
    )
    d = tmp_path_factory.mktemp("cb") / "m"
    tr.GPT2LMHeadModel(cfg_hf).eval().save_pretrained(
        d, safe_serialization=True
    )
    from transformers import AutoConfig

    mesh = make_mesh(MeshPlan(dp=2, tp=4))
    cfg = config_from_hf(AutoConfig.from_pretrained(d), dtype="float32")
    ckpt = CheckpointShards(weight_files(str(d)), dtype=np.float32)
    params = MODEL_REGISTRY["gpt2"].load_params(ckpt, cfg, mesh)
    return DecodeEngine(cfg, params, mesh, max_seq_len=64)


def test_interleaved_matches_isolated(engine):
    """Tokens under continuous batching == tokens when each request runs
    alone (row isolation through the shared cache)."""
    prompts = [[i + 1, i + 2, i + 3, i + 4] for i in range(5)]
    gen = GenerationParams(max_new_tokens=6, is_greedy=True)
    expected = [engine.generate([p], gen)[0] for p in prompts]

    batcher = ContinuousBatcher(engine, rows=2)  # rows < requests: queueing
    results = {}
    for i, p in enumerate(prompts):
        batcher.submit(p, gen, lambda toks, i=i: results.__setitem__(i, toks))
    batcher.run_until_idle()

    for i in range(5):
        assert results[i] == expected[i], (i, results[i], expected[i])


def test_sampled_seed_matches_isolated(engine):
    """A sampled request's tokens depend only on (prompt, seed) — not on
    admission order or batch mix: continuous batching must reproduce the
    isolated engine.generate output for the same seed."""
    prompts = [[i + 1, i + 2, i + 3] for i in range(4)]
    gens = [
        GenerationParams(
            max_new_tokens=5, is_greedy=False, temperature=1.3, seed=100 + i,
        )
        for i in range(4)
    ]
    expected = [
        engine.generate([p], g)[0] for p, g in zip(prompts, gens)
    ]
    batcher = ContinuousBatcher(engine, rows=2)
    results = {}
    for i, (p, g) in enumerate(zip(prompts, gens)):
        batcher.submit(p, g, lambda toks, i=i: results.__setitem__(i, toks))
    batcher.run_until_idle()
    for i in range(4):
        assert results[i] == expected[i], (i, results[i], expected[i])


def test_cancel_frees_row_within_one_step(engine):
    """A cancelled active request stops consuming decode steps within one
    step: its row frees, its callback fires with the partial tokens AND the
    cancelled flag (so the serving layer answers honestly), and the rest of
    the batch is unaffected."""
    results = {}

    def cb(key):
        return lambda t, cancelled=False: results.__setitem__(
            key, (t, cancelled)
        )

    batcher = ContinuousBatcher(engine, rows=2)
    long_gen = GenerationParams(max_new_tokens=40, is_greedy=True)
    batcher.submit([1, 2, 3], long_gen, cb("a"), req_id="a")
    batcher.submit([4, 5], GenerationParams(max_new_tokens=6, is_greedy=True),
                   cb("b"), req_id="b")
    for _ in range(3):
        batcher.step()
    assert "a" not in results
    batcher.cancel("a")
    batcher.step()  # processes the cancellation at the top of the step
    assert "a" in results
    toks_a, cancelled_a = results["a"]
    assert cancelled_a and 0 < len(toks_a) < 40
    assert not any(r.req_id == "a" for r in batcher.active.values())
    assert engine.metrics.cancelled >= 1
    # remaining request runs to completion untouched
    batcher.run_until_idle()
    toks_b, cancelled_b = results["b"]
    assert not cancelled_b and len(toks_b) == 6

    # cancelling a *pending* (never admitted) request answers it as
    # cancelled with no tokens (every submitted request gets one response)
    batcher2 = ContinuousBatcher(engine, rows=1)
    batcher2.submit([1], long_gen, cb("c"), req_id="c")
    batcher2.cancel("c")
    batcher2.step()
    assert batcher2.idle and results["c"] == ([], True)


def test_staggered_admission(engine):
    """Requests submitted mid-flight join the running batch and still match
    their isolated outputs."""
    gen = GenerationParams(max_new_tokens=8, is_greedy=True)
    p0, p1 = [1, 2, 3], [9, 8, 7, 6]
    e0 = engine.generate([p0], gen)[0]
    e1 = engine.generate([p1], gen)[0]

    batcher = ContinuousBatcher(engine, rows=4)
    results = {}
    batcher.submit(p0, gen, lambda t: results.__setitem__(0, t))
    # run a few steps so p0 is mid-decode, then admit p1
    for _ in range(3):
        batcher.step()
    batcher.submit(p1, gen, lambda t: results.__setitem__(1, t))
    batcher.run_until_idle()

    assert results[0] == e0
    assert results[1] == e1


def test_varied_lengths_and_eos(engine):
    gens = [
        GenerationParams(max_new_tokens=2, is_greedy=True),
        GenerationParams(max_new_tokens=9, is_greedy=True),
        GenerationParams(max_new_tokens=5, is_greedy=False, temperature=0.8,
                         top_k=10, top_p=0.9),
    ]
    prompts = [[4, 5], [6, 7, 8], [10, 11, 12, 13]]
    batcher = ContinuousBatcher(engine, rows=3)
    results = {}
    for i, (p, g) in enumerate(zip(prompts, gens)):
        batcher.submit(p, g, lambda t, i=i: results.__setitem__(i, t))
    batcher.run_until_idle()
    assert len(results[0]) == 2
    assert len(results[1]) == 9
    assert len(results[2]) == 5


def test_continuous_worker_roundtrip(engine):
    from llmss_tpu.serve import GenerateRequest, InProcBroker
    from llmss_tpu.serve.consumer import ContinuousWorker

    broker = InProcBroker()
    worker = ContinuousWorker(engine, broker, rows=2, poll_timeout_s=0.01)
    stop = threading.Event()
    t = threading.Thread(target=worker.run_forever, args=(stop,), daemon=True)
    t.start()

    reqs = [
        GenerateRequest(token_ids=[i + 1, i + 2], max_new_tokens=4,
                        is_greedy=True)
        for i in range(4)
    ]
    for r in reqs:
        broker.push_request(r)
    resps = [broker.wait_response(r.id, timeout=120) for r in reqs]
    stop.set()
    for r in resps:
        assert r is not None and r.error is None
        assert len(r.token_ids) == 4


def test_chunked_step_matches_single_step(engine):
    """chunk_steps batches host round-trips only: tokens must be identical
    to the single-step scheduler, including mid-chunk EOS/max_new finishes
    and mid-stream admission."""
    from llmss_tpu.engine.scheduler import ContinuousBatcher

    def run(chunk):
        b = ContinuousBatcher(engine, rows=4, chunk_steps=chunk)
        got = {}
        b.submit([5, 9, 23], GenerationParams(max_new_tokens=7,
                                              is_greedy=True),
                 lambda t: got.__setitem__("a", t), req_id="a")
        b.submit([3, 14], GenerationParams(max_new_tokens=3, is_greedy=True),
                 lambda t: got.__setitem__("b", t), req_id="b")
        b.step()
        # admit mid-stream while the first two are decoding
        b.submit([40, 41, 42, 43], GenerationParams(max_new_tokens=5,
                                                    is_greedy=True),
                 lambda t: got.__setitem__("c", t), req_id="c")
        b.run_until_idle()
        return got

    assert run(1) == run(4)


@pytest.mark.parametrize("n_simultaneous,rows", [(3, 4), (5, 8)])
def test_padded_admission_preserves_active_rows(engine, n_simultaneous, rows):
    """Admitting a non-power-of-two number of requests pads the admission
    batch with sentinel rows; the sentinel must be a positive OOB index —
    a -1 sentinel wraps (JAX normalizes negatives before the OOB check) and
    scatters the dummy row into live row rows-1, zeroing the KV of whatever
    request holds it. _free.pop() allocates the highest row first, so the
    FIRST admitted request is exactly the victim. Regression test: tokens
    must match isolated runs."""
    gen_long = GenerationParams(max_new_tokens=12, is_greedy=True)
    gen_short = GenerationParams(max_new_tokens=6, is_greedy=True)
    first_prompt = [7, 11, 13]
    later_prompts = [[20 + 3 * i, 21 + 3 * i] for i in range(n_simultaneous)]

    expected_first = engine.generate([first_prompt], gen_long)[0]
    expected_later = [engine.generate([p], gen_short)[0]
                      for p in later_prompts]

    batcher = ContinuousBatcher(engine, rows=rows)
    results = {}
    batcher.submit(first_prompt, gen_long,
                   lambda t: results.__setitem__("first", t), req_id="first")
    batcher.step()  # first request occupies the highest row, mid-decode
    assert not batcher.idle
    victim_row = max(batcher.active)  # _free.pop() hands out highest first
    for i, p in enumerate(later_prompts):
        batcher.submit(p, gen_short,
                       lambda t, i=i: results.__setitem__(i, t))
    batcher.step()  # dispatches the padded admission insert
    # Token parity alone can't catch the corruption on this degenerate toy
    # model, so assert on the cache directly: the victim row's KV positions
    # must still describe its real history, not the scratch dummy row's
    # single pad slot.
    victim_pos = np.asarray(batcher.cache.positions)[victim_row]
    n_valid = int((victim_pos >= 0).sum())
    assert n_valid >= len(first_prompt), victim_pos[:8]
    batcher.run_until_idle()

    assert results["first"] == expected_first
    for i in range(n_simultaneous):
        assert results[i] == expected_later[i], (i, results[i])


def test_generate_chunked_matches_single(engine):
    prompts = [[5, 9, 23, 40], [3, 14, 15]]
    gens = [
        GenerationParams(max_new_tokens=9, is_greedy=True),
        GenerationParams(max_new_tokens=4, is_greedy=False,
                         temperature=0.8, top_k=7, seed=11),
    ]
    a = engine.generate(prompts, gens, chunk_steps=1)
    b = engine.generate(prompts, gens, chunk_steps=4)
    c = engine.generate(prompts, gens, chunk_steps=64)
    assert a == b == c


# -- grouped dispatch: bit-identity with the chunked path ---------------------


def _run_jobs(engine, jobs, *, rows, chunk_steps, group_chunks,
              interleave_after=0):
    """Run ``jobs`` [(prompt, gen)] through a fresh batcher; returns
    {req_id: (tokens, error)}. ``interleave_after`` submits that many jobs
    up front and the rest only after two scheduler steps, so admissions
    land while earlier rows are mid-group."""
    b = ContinuousBatcher(
        engine, rows=rows, chunk_steps=chunk_steps,
        group_chunks=group_chunks,
    )
    got = {}

    def cb_for(rid):
        def cb(toks, cancelled=False, error=None):
            got[rid] = (list(toks), error)
        return cb

    head = jobs[:interleave_after] if interleave_after else jobs
    tail = jobs[interleave_after:] if interleave_after else []
    for rid, (p, g) in enumerate(head):
        b.submit(p, g, cb_for(rid), req_id=str(rid))
    if tail:
        b.step()
        b.step()
        for rid, (p, g) in enumerate(tail, start=len(head)):
            b.submit(p, g, cb_for(rid), req_id=str(rid))
    b.run_until_idle()
    assert len(got) == len(jobs)
    return got


def test_grouped_matches_chunked_interleaved(engine):
    """group_chunks batches host syncs only: with admissions landing
    mid-stream, every request's tokens must be identical to the
    group_chunks=1 scheduler (which test_chunked_step_matches_single_step
    already pins to the single-step path)."""
    jobs = [
        ([5, 9, 23], GenerationParams(max_new_tokens=11, is_greedy=True)),
        ([3, 14], GenerationParams(max_new_tokens=3, is_greedy=True)),
        ([40, 41, 42, 43], GenerationParams(
            max_new_tokens=7, is_greedy=False, temperature=0.9, top_k=12,
            seed=5,
        )),
        ([7, 11], GenerationParams(max_new_tokens=9, is_greedy=True)),
        ([2, 4, 8], GenerationParams(max_new_tokens=5, is_greedy=True)),
    ]
    base = _run_jobs(engine, jobs, rows=3, chunk_steps=2, group_chunks=1,
                     interleave_after=2)
    grouped = _run_jobs(engine, jobs, rows=3, chunk_steps=2, group_chunks=3,
                        interleave_after=2)
    assert grouped == base


def test_grouped_eos_mid_group(engine):
    """A row hitting EOS inside a group must emit exactly the pre-EOS
    tokens: the device EOS-fills the rest of the group, and the host must
    never read the fills as output."""
    probe = engine.generate(
        [[1, 2, 3, 4]], GenerationParams(max_new_tokens=8, is_greedy=True)
    )[0]
    eos = probe[2]  # a token the greedy stream provably emits mid-flight
    jobs = [
        ([1, 2, 3, 4], GenerationParams(
            max_new_tokens=12, is_greedy=True, eos_token_id=eos)),
        ([9, 8, 7], GenerationParams(max_new_tokens=12, is_greedy=True)),
    ]
    base = _run_jobs(engine, jobs, rows=2, chunk_steps=2, group_chunks=1)
    grouped = _run_jobs(engine, jobs, rows=2, chunk_steps=2, group_chunks=3)
    assert grouped == base
    # The EOS row stopped early (before its max_new_tokens budget).
    assert len(base[0][0]) < 12 and base[0][1] is None


def test_grouped_poison_mid_group(engine):
    """A row poisoned mid-group errors out with the tokens produced before
    the poison — at the same boundary as the ungrouped path — and its
    batch-mates keep their exact streams."""
    gen = GenerationParams(max_new_tokens=8, is_greedy=True)

    def run(group_chunks):
        b = ContinuousBatcher(
            engine, rows=2, chunk_steps=2, group_chunks=group_chunks,
        )
        orig = engine._decode_group
        got = {}

        def cb_for(rid):
            def cb(toks, cancelled=False, error=None):
                got[rid] = (list(toks), error)
            return cb

        def poisoning(*a, **k):
            # Flip the packed poisoned flag (layout: nc*B*k tokens then
            # nc*B per-chunk flags) for req "bad"'s row in every chunk of
            # the group, from its first live dispatch on.
            packed, last_tok, cache, cur_pos, done = orig(*a, **k)
            bad_row = next(
                (row for row, r in b.active.items()
                 if r.req_id == "bad" and not r.awaiting_first), None,
            )
            if bad_row is not None:
                nc, steps = k["n_chunks"], k["n_steps"]
                base_i = nc * b.rows * steps
                for c in range(nc):
                    packed = packed.at[base_i + c * b.rows + bad_row].set(1)
            return packed, last_tok, cache, cur_pos, done

        engine._decode_group = poisoning
        try:
            b.submit([5, 6, 7], gen, cb_for("good"), req_id="good")
            b.submit([9, 9], gen, cb_for("bad"), req_id="bad")
            b.run_until_idle()
        finally:
            engine._decode_group = orig
        return got

    base = run(1)
    grouped = run(3)
    assert grouped == base
    assert "poisoned" in (base["bad"][1] or "")
    assert base["good"][1] is None
    solo = engine.generate([[5, 6, 7]], gen)[0]
    assert base["good"][0] == solo
