"""Layer library: sharded-vs-unsharded parity, attention, sampling."""

import jax
import jax.numpy as jnp
import numpy as np
import pytest
from jax.sharding import NamedSharding, PartitionSpec as P

from llmss_tpu.ops import attention, dense, embedding, layer_norm, lm_head, rms_norm, sample
from llmss_tpu.ops.layers import LinearParams, NormParams, linear_specs
from llmss_tpu.parallel import AXIS_TP, MeshPlan, make_mesh
from llmss_tpu.parallel.sharding import tree_named


@pytest.fixture(scope="module")
def mesh(devices):
    return make_mesh(MeshPlan(tp=8))


def _place(mesh, params, specs):
    return jax.tree.map(
        lambda x, s: jax.device_put(x, NamedSharding(mesh, s)), params, specs
    )


def test_column_then_row_parity(mesh):
    """Megatron column→row pair equals unsharded two-layer MLP."""
    rng = np.random.default_rng(1)
    x = jnp.asarray(rng.normal(size=(2, 4, 32)), jnp.float32)
    w1 = jnp.asarray(rng.normal(size=(32, 64)), jnp.float32)
    b1 = jnp.asarray(rng.normal(size=(64,)), jnp.float32)
    w2 = jnp.asarray(rng.normal(size=(64, 32)), jnp.float32)
    b2 = jnp.asarray(rng.normal(size=(32,)), jnp.float32)

    ref = jax.nn.gelu(x @ w1 + b1) @ w2 + b2

    col = _place(mesh, LinearParams(w1, b1), linear_specs("column"))
    row = _place(mesh, LinearParams(w2, b2), linear_specs("row"))

    @jax.jit
    def f(x, col, row):
        return dense(jax.nn.gelu(dense(x, col)), row)

    out = f(x, col, row)
    np.testing.assert_allclose(np.asarray(out), np.asarray(ref), atol=1e-4)


def test_vocab_parallel_embedding_and_head(mesh):
    rng = np.random.default_rng(2)
    table = jnp.asarray(rng.normal(size=(40, 16)), jnp.float32)  # 40 % 8 != 0
    ids = jnp.asarray(rng.integers(0, 40, size=(2, 5)), jnp.int32)
    ref_emb = jnp.take(table, ids, axis=0)
    ref_logits = (ref_emb @ table.T).astype(jnp.float32)

    sh_table = jax.device_put(table, NamedSharding(mesh, P(AXIS_TP, None)))
    head = LinearParams(
        jax.device_put(table.T, NamedSharding(mesh, P(None, AXIS_TP))), None
    )

    @jax.jit
    def f(ids, table, head):
        h = embedding(ids, table, one_hot=True)
        return h, lm_head(h, head)

    emb, logits = f(ids, sh_table, head)
    np.testing.assert_allclose(np.asarray(emb), np.asarray(ref_emb), atol=1e-5)
    np.testing.assert_allclose(
        np.asarray(logits), np.asarray(ref_logits), atol=1e-4
    )


def test_norms():
    rng = np.random.default_rng(3)
    x = jnp.asarray(rng.normal(size=(2, 3, 8)), jnp.float32)
    p = NormParams(
        jnp.asarray(rng.normal(size=(8,)), jnp.float32),
        jnp.asarray(rng.normal(size=(8,)), jnp.float32),
    )
    y = layer_norm(x, p, 1e-5)
    ref = p.scale * (x - x.mean(-1, keepdims=True)) / jnp.sqrt(
        x.var(-1, keepdims=True) + 1e-5
    ) + p.bias
    np.testing.assert_allclose(np.asarray(y), np.asarray(ref), atol=1e-5)

    pr = NormParams(p.scale, None)
    yr = rms_norm(x, pr, 1e-6)
    refr = p.scale * x / jnp.sqrt((x * x).mean(-1, keepdims=True) + 1e-6)
    np.testing.assert_allclose(np.asarray(yr), np.asarray(refr), atol=1e-5)


def test_attention_matches_naive_mha():
    rng = np.random.default_rng(4)
    B, S, H, D = 2, 6, 4, 8
    q = jnp.asarray(rng.normal(size=(B, S, H, D)), jnp.float32)
    k = jnp.asarray(rng.normal(size=(B, S, H, D)), jnp.float32)
    v = jnp.asarray(rng.normal(size=(B, S, H, D)), jnp.float32)
    pos = jnp.broadcast_to(jnp.arange(S), (B, S))
    mask = (pos[:, None, :] <= pos[:, :, None])

    out = attention(q, k, v, mask)

    # naive reference
    logits = jnp.einsum("bshd,bthd->bhst", q, k) / np.sqrt(D)
    logits = jnp.where(mask[:, None], logits, -1e30)
    ref = jnp.einsum("bhst,bthd->bshd", jax.nn.softmax(logits), v)
    np.testing.assert_allclose(np.asarray(out), np.asarray(ref), atol=1e-5)


def test_attention_mqa_broadcasts_kv():
    rng = np.random.default_rng(5)
    B, S, H, D = 1, 4, 6, 8
    q = jnp.asarray(rng.normal(size=(B, S, H, D)), jnp.float32)
    k1 = jnp.asarray(rng.normal(size=(B, S, 1, D)), jnp.float32)
    v1 = jnp.asarray(rng.normal(size=(B, S, 1, D)), jnp.float32)
    pos = jnp.broadcast_to(jnp.arange(S), (B, S))
    mask = pos[:, None, :] <= pos[:, :, None]

    out_mqa = attention(q, k1, v1, mask)
    out_rep = attention(
        q, jnp.repeat(k1, H, 2), jnp.repeat(v1, H, 2), mask
    )
    np.testing.assert_allclose(
        np.asarray(out_mqa), np.asarray(out_rep), atol=1e-5
    )


def _sargs(n, seed=0, counter=0):
    return dict(
        seeds=jnp.full(n, seed, jnp.int32),
        counters=jnp.full(n, counter, jnp.int32),
    )


def test_sampling_greedy_and_filters():
    logits = jnp.asarray(
        [[0.0, 1.0, 2.0, 3.0], [3.0, 2.0, 1.0, 0.0]], jnp.float32
    )
    tok = sample(
        logits, **_sargs(2),
        temperature=jnp.ones(2), top_k=jnp.zeros(2, jnp.int32),
        top_p=jnp.ones(2), greedy=jnp.array([True, True]),
    )
    np.testing.assert_array_equal(np.asarray(tok), [3, 0])

    # top_k=1 forces argmax even when sampling.
    tok = sample(
        logits, **_sargs(2, seed=1),
        temperature=jnp.ones(2), top_k=jnp.ones(2, jnp.int32),
        top_p=jnp.ones(2), greedy=jnp.array([False, False]),
    )
    np.testing.assert_array_equal(np.asarray(tok), [3, 0])

    # tiny top_p keeps only the head of the nucleus.
    tok = sample(
        logits, **_sargs(2, seed=2),
        temperature=jnp.ones(2), top_k=jnp.zeros(2, jnp.int32),
        top_p=jnp.full(2, 1e-6), greedy=jnp.array([False, False]),
    )
    np.testing.assert_array_equal(np.asarray(tok), [3, 0])


def test_sampling_distribution_sane():
    # With temperature→0 sampling must concentrate on the argmax.
    logits = jnp.asarray([[1.0, 5.0, 2.0, 0.0]], jnp.float32)
    toks = [
        int(
            sample(
                logits, **_sargs(1, seed=i),
                temperature=jnp.full(1, 0.01),
                top_k=jnp.zeros(1, jnp.int32),
                top_p=jnp.ones(1),
                greedy=jnp.array([False]),
            )[0]
        )
        for i in range(10)
    ]
    assert toks == [1] * 10


def test_sampling_per_row_seed_determinism():
    # Same (seed, counter) → same draw; different seed or counter → the
    # stream moves. Rows are independent: a row's draw doesn't depend on
    # what else is in the batch (the serving `seed` contract).
    rng = np.random.default_rng(0)
    logits = jnp.asarray(rng.normal(size=(4, 64)), jnp.float32)
    kw = dict(
        temperature=jnp.ones(4), top_k=jnp.zeros(4, jnp.int32),
        top_p=jnp.ones(4), greedy=jnp.zeros(4, bool),
    )
    seeds = jnp.asarray([7, 7, 8, 8], jnp.int32)
    counters = jnp.asarray([3, 4, 3, 4], jnp.int32)
    a = sample(logits, seeds=seeds, counters=counters, **kw)
    b = sample(logits, seeds=seeds, counters=counters, **kw)
    np.testing.assert_array_equal(np.asarray(a), np.asarray(b))

    # Row 0 and row 2 share logits-row? No — use identical logits rows to
    # compare across seeds/counters directly.
    same = jnp.broadcast_to(logits[0], (4, 64))
    t = sample(same, seeds=seeds, counters=counters, **kw)
    t = np.asarray(t)
    # batch-mix independence: row 0 alone gives the same token as row 0
    # inside the batch of 4.
    solo = sample(
        same[:1], seeds=seeds[:1], counters=counters[:1],
        temperature=jnp.ones(1), top_k=jnp.zeros(1, jnp.int32),
        top_p=jnp.ones(1), greedy=jnp.zeros(1, bool),
    )
    assert int(solo[0]) == int(t[0])


def test_sampling_topk_bucket_matches_full_sort():
    """The static top-k bucket path and the full-sort fallback must draw
    identical tokens for rows they both serve: a row's draw is batch-mix
    independent, so adding one bucket-busting row (top_k > TOPK_BUCKET)
    flips the whole batch to the full sort without changing any other
    row's token."""
    from llmss_tpu.ops.sampling import TOPK_BUCKET

    rng = np.random.default_rng(3)
    V = 512
    logits = jnp.asarray(rng.normal(size=(4, V)) * 3, jnp.float32)
    kw = dict(
        temperature=jnp.full(4, 0.8),
        top_k=jnp.asarray([40, 0, 5, 40], jnp.int32),
        top_p=jnp.asarray([1.0, 0.9, 0.95, 0.7], jnp.float32),
        greedy=jnp.zeros(4, bool),
    )
    a = np.asarray(sample(logits, **_sargs(4, seed=11), **kw))

    # Same rows + a fifth row whose top_k exceeds the bucket: the batch
    # falls back to the full sort; shared rows must not move. (Peaked
    # logits keep the top_p rows resolvable in-bucket for run A.)
    logits_b = jnp.concatenate([logits, logits[:1]], axis=0)
    kw_b = dict(
        temperature=jnp.full(5, 0.8),
        top_k=jnp.asarray(
            [40, 0, 5, 40, TOPK_BUCKET + 100], jnp.int32
        ),
        top_p=jnp.asarray([1.0, 0.9, 0.95, 0.7, 0.999], jnp.float32),
        greedy=jnp.zeros(5, bool),
    )
    b = np.asarray(sample(
        logits_b, seeds=jnp.full(5, 11, jnp.int32),
        counters=jnp.zeros(5, jnp.int32), **kw_b,
    ))
    np.testing.assert_array_equal(a, b[:4])


def test_sampling_bucket_fallback_on_flat_nucleus():
    """Near-uniform logits with a high top_p cannot resolve the nucleus
    inside the bucket — the runtime guard must take the full sort, and the
    draw stays deterministic and within the nucleus-eligible set."""
    V = 512
    logits = jnp.zeros((2, V), jnp.float32)  # uniform: mass(bucket) = Kb/V
    kw = dict(
        temperature=jnp.ones(2),
        top_k=jnp.zeros(2, jnp.int32),
        top_p=jnp.full(2, 0.99),
        greedy=jnp.zeros(2, bool),
    )
    a = np.asarray(sample(logits, **_sargs(2, seed=5), **kw))
    b = np.asarray(sample(logits, **_sargs(2, seed=5), **kw))
    np.testing.assert_array_equal(a, b)
    # uniform + top_p=0.99 keeps ~507 of 512 tokens; any id is plausible,
    # but it must be a valid token id.
    assert ((a >= 0) & (a < V)).all()


def test_sampling_unfiltered_row_keeps_full_vocab_in_mixed_batch():
    """A warper-free sampled row sharing a batch with a filtered row must
    draw over the FULL vocab (not the top-k bucket): its token equals its
    solo draw exactly."""
    rng = np.random.default_rng(9)
    V = 512
    row = jnp.asarray(rng.normal(size=(1, V)), jnp.float32)
    solo = int(sample(
        row, **_sargs(1, seed=21),
        temperature=jnp.full(1, 3.0),
        top_k=jnp.zeros(1, jnp.int32), top_p=jnp.ones(1),
        greedy=jnp.zeros(1, bool),
    )[0])
    mixed = np.asarray(sample(
        jnp.concatenate([row, row], axis=0),
        seeds=jnp.asarray([21, 22], jnp.int32),
        counters=jnp.zeros(2, jnp.int32),
        temperature=jnp.full(2, 3.0),
        top_k=jnp.asarray([0, 5], jnp.int32),
        top_p=jnp.ones(2),
        greedy=jnp.zeros(2, bool),
    ))
    assert mixed[0] == solo
