"""Ragged mixed prefill+decode dispatch (ISSUE 10).

Three layers of evidence that one ragged program can serve rows at
arbitrary positions — decode rows (``q_len == 1``) and mid-prefill rows
(``q_len`` up to the chunk budget) in the same dispatch:

- **kernel**: ``ops.pallas_ragged`` (interpret mode) vs the XLA gather
  oracle ``ops.attention.ragged_paged_attention`` — GQA/MQA, int8 KV with
  scales, partial tail block, a chunk crossing a block boundary, an empty
  cache; plus bit-for-bit identity with ``pallas_paged_decode`` when every
  row is a decode row at ``CB == 1``;
- **engine**: ``_ragged_group`` on an all-decode plan reproduces
  ``_decode_group`` token-for-token, and a chunked 32-token feed
  reproduces the ``_prefill`` + ``_decode_group`` stream;
- **scheduler**: ``ContinuousBatcher(chunked_prefill=...)`` emits the
  exact token streams of the split prefill/decode path on dense,
  sampled, and shared-prefix traces; prewarm compiles NO per-(P, S)
  prefill executables; steady state holds zero recompiles under
  CompileGuard.
"""

import importlib

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from llmss_tpu.analysis.compile_guard import CompileGuard
from llmss_tpu.engine import DecodeEngine, GenerationParams
from llmss_tpu.engine.scheduler import ContinuousBatcher
from llmss_tpu.models.common import DecoderConfig
from llmss_tpu.models.decoder import init_params
from llmss_tpu.ops import pallas_paged_decode, pallas_ragged
from llmss_tpu.parallel import MeshPlan, make_mesh

attn = importlib.import_module("llmss_tpu.ops.attention")


# --------------------------------------------------------------------------
# Kernel vs oracle (no mesh; interpret mode on CPU)
# --------------------------------------------------------------------------

L, N, BS, HKV, D = 2, 16, 8, 2, 128
HQ = 4
B, MB, CB = 3, 4, 4
RING = MB * BS

# Row 0: partial tail block; row 1: empty cache, whole prompt in-chunk;
# row 2: decode row whose chunk crosses a block boundary (27 + 1 = 28).
CTX = np.array([13, 0, 27])
QLEN = np.array([3, 4, 1])
BT = np.asarray([[1, 5, 9, 13], [2, 6, 10, 14], [3, 7, 11, 15]], np.int32)


def _ragged_inputs(rng, ctx, qlen, Hq=HQ, Hkv=HKV):
    nblk = np.asarray(
        [max(-(-int(c + q) // BS), 1) for c, q in zip(ctx, qlen)], np.int32
    )
    kv_pos = np.full((B, RING), -1, np.int32)
    for b in range(B):
        kv_pos[b, : ctx[b]] = np.arange(ctx[b])
    q = jnp.asarray(rng.normal(size=(B, CB, Hq, D)), jnp.float32)
    kn = jnp.asarray(rng.normal(size=(B, CB, Hkv, D)), jnp.float32)
    vn = jnp.asarray(rng.normal(size=(B, CB, Hkv, D)), jnp.float32)
    return (
        q, kn, vn, jnp.asarray(ctx, jnp.int32),
        jnp.asarray(qlen, jnp.int32), jnp.asarray(kv_pos),
        jnp.asarray(BT), jnp.asarray(nblk),
        jnp.asarray(ctx % RING, jnp.int32),
    )


def _assert_live_rows_close(got, want, qlen):
    for b in range(B):
        np.testing.assert_allclose(
            np.asarray(got)[b, : qlen[b]], np.asarray(want)[b, : qlen[b]],
            rtol=2e-5, atol=2e-5,
        )


def test_kernel_parity_vs_oracle_gqa():
    """Mixed rows (partial tail / empty ctx / boundary-crossing chunk) on
    every layer of the stacked pool match the XLA gather oracle."""
    rng = np.random.default_rng(0)
    k_pool = jnp.asarray(rng.normal(size=(L, N, BS, HKV, D)), jnp.float32)
    v_pool = jnp.asarray(rng.normal(size=(L, N, BS, HKV, D)), jnp.float32)
    q, kn, vn, q_pos, qlen, kv_pos, bt, nblk, slot0 = _ragged_inputs(
        rng, CTX, QLEN
    )
    assert pallas_ragged.supports(BS, HQ, HKV, D)
    for layer in range(L):
        got = pallas_ragged.ragged_paged_attention(
            q, k_pool, v_pool, kn, vn, q_pos, qlen, kv_pos, bt, nblk,
            slot0, jnp.int32(layer), interpret=True,
        )
        want = attn.ragged_paged_attention(
            q, k_pool[layer], v_pool[layer], kn, vn, q_pos, qlen, kv_pos,
            bt, slot0, RING,
        )
        _assert_live_rows_close(got, want, QLEN)


def test_kernel_all_decode_bit_identity_vs_paged_decode():
    """At CB == 1 with every q_len == 1 the ragged kernel IS the grouped
    decode kernel: identical block loop, identical merge order, so the
    outputs must match bit for bit (np.array_equal, not allclose)."""
    rng = np.random.default_rng(0)
    k_pool = jnp.asarray(rng.normal(size=(L, N, BS, HKV, D)), jnp.float32)
    v_pool = jnp.asarray(rng.normal(size=(L, N, BS, HKV, D)), jnp.float32)
    ctx = np.array([13, 5, 27])
    kv_pos = np.full((B, RING), -1, np.int32)
    for b in range(B):
        kv_pos[b, : ctx[b]] = np.arange(ctx[b])
    nblk = jnp.asarray([-(-int(c + 1) // BS) for c in ctx], jnp.int32)
    q1 = jnp.asarray(rng.normal(size=(B, 1, HQ, D)), jnp.float32)
    kn1 = jnp.asarray(rng.normal(size=(B, 1, HKV, D)), jnp.float32)
    vn1 = jnp.asarray(rng.normal(size=(B, 1, HKV, D)), jnp.float32)
    slots = jnp.asarray(ctx % RING, jnp.int32)
    out_r = pallas_ragged.ragged_paged_attention(
        q1, k_pool, v_pool, kn1, vn1, jnp.asarray(ctx, jnp.int32),
        jnp.ones(B, jnp.int32), jnp.asarray(kv_pos), jnp.asarray(BT),
        nblk, slots, jnp.int32(0), interpret=True,
    )
    out_d = pallas_paged_decode.paged_decode_attention(
        q1, k_pool, v_pool, kn1, vn1,
        jnp.asarray(ctx, jnp.int32).reshape(B, 1), jnp.asarray(kv_pos),
        jnp.asarray(BT), nblk, slots.reshape(B, 1), jnp.int32(0),
        interpret=True,
    )
    assert np.array_equal(np.asarray(out_r)[:, 0], np.asarray(out_d)[:, 0])


def test_kernel_int8_scales_parity():
    """Quantized pool with per-(block, slot, head) scales matches the
    oracle's dequantized gather."""
    rng = np.random.default_rng(1)
    k8 = jnp.asarray(rng.integers(-127, 127, size=(L, N, BS, HKV, D)),
                     jnp.int8)
    v8 = jnp.asarray(rng.integers(-127, 127, size=(L, N, BS, HKV, D)),
                     jnp.int8)
    ks = jnp.asarray(rng.uniform(0.01, 0.03, size=(L, N, BS, HKV)),
                     jnp.float32)
    vs = jnp.asarray(rng.uniform(0.01, 0.03, size=(L, N, BS, HKV)),
                     jnp.float32)
    q, kn, vn, q_pos, qlen, kv_pos, bt, nblk, slot0 = _ragged_inputs(
        rng, CTX, QLEN
    )
    got = pallas_ragged.ragged_paged_attention(
        q, k8, v8, kn, vn, q_pos, qlen, kv_pos, bt, nblk, slot0,
        jnp.int32(1), k_scale_pool=ks, v_scale_pool=vs, interpret=True,
    )
    want = attn.ragged_paged_attention(
        q, k8[1], v8[1], kn, vn, q_pos, qlen, kv_pos, bt, slot0, RING,
        k_scale_layer=ks[1], v_scale_layer=vs[1],
    )
    _assert_live_rows_close(got, want, QLEN)


def test_kernel_mqa_parity():
    rng = np.random.default_rng(2)
    Hkv = 1
    k_pool = jnp.asarray(rng.normal(size=(L, N, BS, Hkv, D)), jnp.float32)
    v_pool = jnp.asarray(rng.normal(size=(L, N, BS, Hkv, D)), jnp.float32)
    q, kn, vn, q_pos, qlen, kv_pos, bt, nblk, slot0 = _ragged_inputs(
        rng, CTX, QLEN, Hkv=Hkv
    )
    got = pallas_ragged.ragged_paged_attention(
        q, k_pool, v_pool, kn, vn, q_pos, qlen, kv_pos, bt, nblk, slot0,
        jnp.int32(0), interpret=True,
    )
    want = attn.ragged_paged_attention(
        q, k_pool[0], v_pool[0], kn, vn, q_pos, qlen, kv_pos, bt, slot0,
        RING,
    )
    _assert_live_rows_close(got, want, QLEN)


# --------------------------------------------------------------------------
# Engine and scheduler (8-device dp=2 x tp=4 mesh, XLA ragged path)
# --------------------------------------------------------------------------

@pytest.fixture(scope="module")
def mesh(devices):
    return make_mesh(MeshPlan(dp=2, tp=4))


@pytest.fixture(scope="module")
def cfg():
    # head_dim=8 is outside the kernel envelope, so the engine runs the
    # XLA ragged oracle — the numerics under test are the dispatch
    # structure, not the kernel (covered above in interpret mode).
    return DecoderConfig(
        model_type="llama", vocab_size=128, hidden_size=64, n_layers=2,
        n_heads=8, n_kv_heads=4, head_dim=8, intermediate_size=128,
        max_position_embeddings=256, activation="silu", norm="rmsnorm",
        norm_eps=1e-5, mlp="swiglu", positions="rotary", rope_style="half",
        rotary_dim=8, attn_bias=False, mlp_bias=False,
        tie_word_embeddings=False, dtype="float32",
    )


@pytest.fixture(scope="module")
def params(cfg, mesh):
    return init_params(cfg, mesh, jax.random.key(0))


def _paged_engine(cfg, params, mesh):
    return DecodeEngine(
        cfg, params, mesh, max_seq_len=64, kv_layout="paged", block_size=8,
    )


def test_engine_all_decode_matches_decode_group(cfg, params, mesh):
    """An all-decode plan (q_len == 1, no feeds, every step emitting)
    through _ragged_group reproduces _decode_group's packed tokens and
    counters exactly — the unified dispatch costs nothing on the pure
    decode steady state."""
    eng = _paged_engine(cfg, params, mesh)
    nB = 4
    gen = GenerationParams(max_new_tokens=8, is_greedy=True)
    sa = eng._sample_args([gen] * nB, nB)
    prompts = [[5, 9, 23, 40], [3, 14, 15, 9], [7, 7, 7, 7], [1, 2, 3, 4]]
    ids = jnp.asarray(prompts, jnp.int32)
    lens = jnp.asarray([len(p) for p in prompts], jnp.int32)
    eos = jnp.full(nB, -1, jnp.int32)

    cache = eng.new_paged_cache(nB, num_blocks=64, identity=True)
    tok, _, cache = eng._prefill(eng.params, ids, cache, lens, sa)
    packed, *_rest = eng._decode_group(
        eng.params, tok, cache, lens, sa, jnp.zeros(nB, bool), eos,
        n_chunks=6, n_steps=1, t_bucket=None,
    )
    curA = _rest[2]
    toksA = np.asarray(packed)[: 6 * nB].reshape(6, nB)

    # lens was donated into _decode_group above — rebuild from host data.
    lens2 = jnp.asarray([len(p) for p in prompts], jnp.int32)
    cache2 = eng.new_paged_cache(nB, num_blocks=64, identity=True)
    tok2, _, cache2 = eng._prefill(eng.params, ids, cache2, lens2, sa)
    cur2 = jnp.asarray([len(p) for p in prompts], jnp.int32)
    nc, cb = 6, 4
    packedR, *_restR = eng._ragged_group(
        eng.params, tok2, cache2, cur2, sa, jnp.zeros(nB, bool), eos,
        jnp.zeros((nc, nB, cb), jnp.int32), jnp.ones((nc, nB), jnp.int32),
        jnp.zeros((nc, nB), bool), jnp.ones((nc, nB), bool),
    )
    curR = _restR[2]
    toksR = np.asarray(packedR)[: nc * nB].reshape(nc, nB)
    assert np.array_equal(toksA, toksR)
    assert np.array_equal(np.asarray(curA), np.asarray(curR))


def test_engine_chunked_feed_matches_prefill_stream(cfg, params, mesh):
    """Feeding a 32-token prompt through _ragged_group in CB=4 chunks
    (emit on the final feed step, then plain decode steps) reproduces the
    _prefill + _decode_group token stream."""
    eng = _paged_engine(cfg, params, mesh)
    prompt = list(range(2, 34))
    gen = GenerationParams(max_new_tokens=8, is_greedy=True)
    sa = eng._sample_args([gen], 1)

    cacheS = eng.new_paged_cache(1, num_blocks=64, identity=True)
    tokS, _, cacheS = eng._prefill(
        eng.params, jnp.asarray([prompt], jnp.int32), cacheS,
        jnp.asarray([len(prompt)], jnp.int32), sa,
    )
    first_tok = int(np.asarray(tokS)[0])
    packedS, *_ = eng._decode_group(
        eng.params, tokS, cacheS, jnp.asarray([len(prompt)], jnp.int32),
        sa, jnp.zeros(1, bool), jnp.full(1, -1, jnp.int32),
        n_chunks=5, n_steps=1, t_bucket=None,
    )
    split_stream = [first_tok] + [
        int(x) for x in np.asarray(packedS)[:5].reshape(5)
    ]

    cb, nc = 4, 13  # 8 feed steps + 5 decode steps
    ids_seq = np.zeros((nc, 1, cb), np.int32)
    qlens = np.ones((nc, 1), np.int32)
    feed = np.zeros((nc, 1), bool)
    emit = np.zeros((nc, 1), bool)
    for c in range(8):
        ids_seq[c, 0] = prompt[c * cb : (c + 1) * cb]
        qlens[c, 0] = cb
        feed[c, 0] = True
        emit[c, 0] = c == 7
    emit[8:, 0] = True
    cacheC = eng.new_paged_cache(1, num_blocks=64, identity=True)
    packedC, *_ = eng._ragged_group(
        eng.params, jnp.zeros(1, jnp.int32), cacheC,
        jnp.zeros(1, jnp.int32), sa, jnp.zeros(1, bool),
        jnp.full(1, -1, jnp.int32), jnp.asarray(ids_seq),
        jnp.asarray(qlens), jnp.asarray(feed), jnp.asarray(emit),
    )
    chunk_stream = [int(x) for x in np.asarray(packedC)[7:nc].reshape(6)]
    assert split_stream == chunk_stream


PROMPTS = [
    list(range(2, 34)),       # 32 tokens — chunked across many steps
    [5, 9, 23],
    [7, 7, 7, 7, 7, 7, 7],
    [40, 41, 42, 43, 44],
]
GENS = [
    GenerationParams(max_new_tokens=8, is_greedy=True),
    GenerationParams(max_new_tokens=6, is_greedy=True),
    GenerationParams(max_new_tokens=5, is_greedy=True),
    GenerationParams(max_new_tokens=7, is_greedy=False, seed=3,
                     temperature=0.9, top_k=20),
]


def _run_trace(cfg, params, mesh, chunked):
    b = ContinuousBatcher(
        _paged_engine(cfg, params, mesh), rows=4, chunk_steps=2,
        group_chunks=2, chunked_prefill=4 if chunked else None,
    )
    outs = {}
    for i, (p, g) in enumerate(zip(PROMPTS, GENS)):
        b.submit(p, g, lambda toks, i=i, **kw: outs.__setitem__(i, toks))
    b.run_until_idle()
    return outs


def test_scheduler_chunked_matches_split(cfg, params, mesh):
    """The chunked-admission batcher must emit the exact token streams of
    the split prefill/decode batcher — greedy AND seeded-sampled rows —
    on a dense-prompt trace with a long prompt riding decode steps."""
    split = _run_trace(cfg, params, mesh, chunked=False)
    chunk = _run_trace(cfg, params, mesh, chunked=True)
    assert split == chunk, (split, chunk)


def test_scheduler_shared_prefix_chunked_matches_split(cfg, params, mesh):
    """Shared-prefix rows (full shared block + COW tail) re-feed only the
    unshared span under chunked prefill; token streams stay identical to
    the split path."""
    shared = list(range(3, 3 + 13))  # 1 full block + 5-token COW tail
    suffixes = [[20, 21, 22], [30], [40, 41, 42, 43, 44, 45]]
    gen = GenerationParams(max_new_tokens=6, is_greedy=True)

    def run(chunked):
        eng = _paged_engine(cfg, params, mesh)
        pfx = eng.build_prefix(shared)
        b = ContinuousBatcher(
            eng, rows=4, chunk_steps=2, group_chunks=2,
            chunked_prefill=4 if chunked else None,
        )
        outs = {}
        for i, s in enumerate(suffixes):
            b.submit(shared + s, gen,
                     lambda toks, i=i, **kw: outs.__setitem__(i, toks),
                     prefix=pfx)
        b.run_until_idle()
        return outs

    assert run(False) == run(True)


def test_prewarm_shrink_and_zero_steady_state_recompiles(cfg, params, mesh):
    """Under chunked prefill the (P, S) prefill ladder is gone: prewarm
    compiles ZERO prefill executables, and a mixed workload (long chunked
    prompt + short prompts) triggers no steady-state recompiles."""
    eng = _paged_engine(cfg, params, mesh)
    b = ContinuousBatcher(eng, rows=4, chunk_steps=2, group_chunks=2,
                          chunked_prefill=4)
    b.prewarm()
    assert b._prefill_row._cache_size() == 0
    guard = CompileGuard({
        **vars(eng),
        "sched_prefill_row": b._prefill_row,
        "sched_merge_positions": b._merge_positions,
    })
    with guard.steady_state():
        outs = {}
        for i, (p, g) in enumerate(zip(PROMPTS[:3], GENS[:3])):
            b.submit(p, g, lambda toks, i=i, **kw: outs.__setitem__(i, toks))
        b.run_until_idle()
    assert sorted(outs) == [0, 1, 2]


def test_mixed_batch_metrics(cfg, params, mesh):
    """The ragged dispatch stamps mixed-batch composition into
    EngineMetrics: chunked prompt tokens, decode vs prefill row-steps,
    and chunk-budget utilization."""
    b = ContinuousBatcher(_paged_engine(cfg, params, mesh), rows=4,
                          chunk_steps=2, group_chunks=2, chunked_prefill=4)
    got = {}
    b.submit(PROMPTS[0], GENS[0], lambda toks, **kw: got.__setitem__(0, toks))
    b.run_until_idle()
    mb = b.engine.metrics.to_dict()["mixed_batch"]
    assert mb["steps"] > 0
    assert mb["prefill_tokens_chunked"] == len(PROMPTS[0])
    assert 0 < mb["chunk_budget_utilization"] <= 1
    assert mb["decode_rows"] + mb["prefill_rows"] > 0


def test_chunked_prefill_requires_paged(cfg, params, mesh):
    dense = DecodeEngine(cfg, params, mesh, max_seq_len=64)
    with pytest.raises(ValueError, match="paged"):
        ContinuousBatcher(dense, rows=2, chunked_prefill=4)
    eng = _paged_engine(cfg, params, mesh)
    with pytest.raises(ValueError):
        ContinuousBatcher(eng, rows=2, chunked_prefill=0)


def test_ragged_kernel_forward_integration(devices):
    """Chunked-admission serving with the ragged Pallas kernel forced on
    (IMPL_OVERRIDE='pallas', interpret): same greedy tokens as the XLA
    ragged oracle path on a kernel-envelope config (D=128)."""
    attn_mod = importlib.import_module("llmss_tpu.ops.attention")
    kcfg = DecoderConfig(
        model_type="llama", vocab_size=128, hidden_size=256, n_layers=2,
        n_heads=8, n_kv_heads=4, head_dim=128, intermediate_size=128,
        max_position_embeddings=64, activation="silu", norm="rmsnorm",
        norm_eps=1e-5, mlp="swiglu", positions="rotary", rope_style="half",
        rotary_dim=128, attn_bias=False, mlp_bias=False,
        tie_word_embeddings=False, dtype="float32",
    )
    mesh = make_mesh(MeshPlan(dp=2, tp=4))
    kparams = init_params(kcfg, mesh, jax.random.key(3))
    prompts = [list(range(2, 22)), [3, 14, 15, 9, 26, 5]]
    gen = GenerationParams(max_new_tokens=6, is_greedy=True)

    outs = {}
    old = attn_mod.IMPL_OVERRIDE
    for impl in ("xla", "pallas"):
        attn_mod.IMPL_OVERRIDE = impl
        try:
            eng = DecodeEngine(
                kcfg, kparams, mesh, max_seq_len=64, kv_layout="paged",
                block_size=8,
            )
            b = ContinuousBatcher(eng, rows=2, chunk_steps=2,
                                  group_chunks=2, chunked_prefill=4)
            res = {}
            for i, p in enumerate(prompts):
                b.submit(p, gen,
                         lambda toks, i=i, **kw: res.__setitem__(i, toks))
            b.run_until_idle()
            outs[impl] = res
        finally:
            attn_mod.IMPL_OVERRIDE = old
    assert outs["xla"] == outs["pallas"], outs
