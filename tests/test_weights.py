"""Weights layer: routing, sliced loads, concat loads, transpose loads."""

import numpy as np
import pytest
from jax.sharding import PartitionSpec as P
from safetensors.numpy import save_file

from llmss_tpu.parallel import AXIS_TP, MeshPlan, make_mesh
from llmss_tpu.weights import CheckpointShards, weight_files


@pytest.fixture(scope="module")
def ckpt_dir(tmp_path_factory):
    d = tmp_path_factory.mktemp("ckpt")
    rng = np.random.default_rng(0)
    save_file(
        {
            "wte": rng.normal(size=(32, 16)).astype(np.float32),
            "q": rng.normal(size=(16, 16)).astype(np.float32),
            "k": rng.normal(size=(16, 16)).astype(np.float32),
            "v": rng.normal(size=(16, 16)).astype(np.float32),
            "idx": np.arange(10, dtype=np.int32),
        },
        str(d / "model-00001.safetensors"),
    )
    save_file(
        {"ln.weight": np.ones(16, dtype=np.float32)},
        str(d / "model-00002.safetensors"),
    )
    return d


def test_weight_files_local_dir(ckpt_dir):
    files = weight_files(str(ckpt_dir))
    assert len(files) == 2


def test_routing_duplicate_key_raises(tmp_path):
    save_file({"a": np.zeros(2, np.float32)}, str(tmp_path / "x.safetensors"))
    save_file({"a": np.zeros(2, np.float32)}, str(tmp_path / "y.safetensors"))
    with pytest.raises(RuntimeError, match="multiple files"):
        CheckpointShards(sorted(tmp_path.glob("*.safetensors")))


def test_get_tensor_and_aliases(ckpt_dir):
    ckpt = CheckpointShards(
        weight_files(str(ckpt_dir)),
        aliases={"transformer.wte": ["wte"]},
    )
    np.testing.assert_array_equal(
        ckpt.get_tensor("transformer.wte"), ckpt.get_tensor("wte")
    )
    assert "transformer.wte" in ckpt and "missing" not in ckpt
    assert ckpt.get_shape("wte") == (32, 16)


def test_int_tensors_skip_cast(ckpt_dir):
    ckpt = CheckpointShards(weight_files(str(ckpt_dir)), dtype=np.float16)
    assert ckpt.get_tensor("idx").dtype == np.int32
    assert ckpt.get_tensor("q").dtype == np.float16


def test_sharded_load_matches_full(ckpt_dir, devices):
    mesh = make_mesh(MeshPlan(tp=8))
    ckpt = CheckpointShards(weight_files(str(ckpt_dir)))
    full = ckpt.get_tensor("wte")
    arr = ckpt.get_array("wte", mesh, P(AXIS_TP, None))
    np.testing.assert_array_equal(np.asarray(arr), full)
    # Each shard holds 32/8 rows.
    shard = arr.addressable_shards[0]
    assert shard.data.shape == (4, 16)


def test_transpose_load(ckpt_dir, devices):
    mesh = make_mesh(MeshPlan(tp=8))
    ckpt = CheckpointShards(weight_files(str(ckpt_dir)))
    full = ckpt.get_tensor("q")
    arr = ckpt.get_array("q", mesh, P(None, AXIS_TP), transpose=True)
    np.testing.assert_array_equal(np.asarray(arr), full.T)


def test_concat_load_fused_qkv(ckpt_dir, devices):
    mesh = make_mesh(MeshPlan(tp=8))
    ckpt = CheckpointShards(weight_files(str(ckpt_dir)))
    ref = np.concatenate(
        [ckpt.get_tensor(n) for n in ("q", "k", "v")], axis=0
    )
    arr = ckpt.get_concat_array(("q", "k", "v"), 0, mesh, P(AXIS_TP, None))
    np.testing.assert_array_equal(np.asarray(arr), ref)
    assert arr.shape == (48, 16)
    # Sharded on the concat axis: 6 rows per device, crossing source borders.
    assert arr.addressable_shards[0].data.shape == (6, 16)
