"""Weights layer: routing, sliced loads, concat loads, transpose loads."""

import numpy as np
import pytest
from jax.sharding import PartitionSpec as P
from safetensors.numpy import save_file

from llmss_tpu.parallel import AXIS_TP, MeshPlan, make_mesh
from llmss_tpu.weights import CheckpointShards, weight_files


@pytest.fixture(scope="module")
def ckpt_dir(tmp_path_factory):
    d = tmp_path_factory.mktemp("ckpt")
    rng = np.random.default_rng(0)
    save_file(
        {
            "wte": rng.normal(size=(32, 16)).astype(np.float32),
            "q": rng.normal(size=(16, 16)).astype(np.float32),
            "k": rng.normal(size=(16, 16)).astype(np.float32),
            "v": rng.normal(size=(16, 16)).astype(np.float32),
            "idx": np.arange(10, dtype=np.int32),
        },
        str(d / "model-00001.safetensors"),
    )
    save_file(
        {"ln.weight": np.ones(16, dtype=np.float32)},
        str(d / "model-00002.safetensors"),
    )
    return d


def test_weight_files_local_dir(ckpt_dir):
    files = weight_files(str(ckpt_dir))
    assert len(files) == 2


def test_routing_duplicate_key_raises(tmp_path):
    save_file({"a": np.zeros(2, np.float32)}, str(tmp_path / "x.safetensors"))
    save_file({"a": np.zeros(2, np.float32)}, str(tmp_path / "y.safetensors"))
    with pytest.raises(RuntimeError, match="multiple files"):
        CheckpointShards(sorted(tmp_path.glob("*.safetensors")))


def test_get_tensor_and_aliases(ckpt_dir):
    ckpt = CheckpointShards(
        weight_files(str(ckpt_dir)),
        aliases={"transformer.wte": ["wte"]},
    )
    np.testing.assert_array_equal(
        ckpt.get_tensor("transformer.wte"), ckpt.get_tensor("wte")
    )
    assert "transformer.wte" in ckpt and "missing" not in ckpt
    assert ckpt.get_shape("wte") == (32, 16)


def test_int_tensors_skip_cast(ckpt_dir):
    ckpt = CheckpointShards(weight_files(str(ckpt_dir)), dtype=np.float16)
    assert ckpt.get_tensor("idx").dtype == np.int32
    assert ckpt.get_tensor("q").dtype == np.float16


def test_sharded_load_matches_full(ckpt_dir, devices):
    mesh = make_mesh(MeshPlan(tp=8))
    ckpt = CheckpointShards(weight_files(str(ckpt_dir)))
    full = ckpt.get_tensor("wte")
    arr = ckpt.get_array("wte", mesh, P(AXIS_TP, None))
    np.testing.assert_array_equal(np.asarray(arr), full)
    # Each shard holds 32/8 rows.
    shard = arr.addressable_shards[0]
    assert shard.data.shape == (4, 16)


def test_transpose_load(ckpt_dir, devices):
    mesh = make_mesh(MeshPlan(tp=8))
    ckpt = CheckpointShards(weight_files(str(ckpt_dir)))
    full = ckpt.get_tensor("q")
    arr = ckpt.get_array("q", mesh, P(None, AXIS_TP), transpose=True)
    np.testing.assert_array_equal(np.asarray(arr), full.T)


def test_concat_load_fused_qkv(ckpt_dir, devices):
    mesh = make_mesh(MeshPlan(tp=8))
    ckpt = CheckpointShards(weight_files(str(ckpt_dir)))
    ref = np.concatenate(
        [ckpt.get_tensor(n) for n in ("q", "k", "v")], axis=0
    )
    arr = ckpt.get_concat_array(("q", "k", "v"), 0, mesh, P(AXIS_TP, None))
    np.testing.assert_array_equal(np.asarray(arr), ref)
    assert arr.shape == (48, 16)
    # Sharded on the concat axis: 6 rows per device, crossing source borders.
    assert arr.addressable_shards[0].data.shape == (6, 16)


def test_multifile_checkpoint_end_to_end(tmp_path, devices):
    """A MULTI-file sharded HF checkpoint (the real cold-start layout the
    reference's loader routes, ``utils/weights.py:18-24``) loads through
    ``load_model`` and matches HF logits — round 3 had only ever loaded
    single-file checkpoints end-to-end."""
    import torch
    import transformers as tr

    from llmss_tpu.engine import DecodeEngine, GenerationParams
    from llmss_tpu.models.registry import load_model

    torch.manual_seed(7)
    hf_cfg = tr.LlamaConfig(
        vocab_size=128, hidden_size=64, intermediate_size=192,
        num_hidden_layers=3, num_attention_heads=4, num_key_value_heads=2,
        max_position_embeddings=64, tie_word_embeddings=False,
    )
    model = tr.LlamaForCausalLM(hf_cfg).eval()
    d = tmp_path / "sharded"
    # ~160 KB shards force a genuinely multi-file layout with an index.
    model.save_pretrained(d, safe_serialization=True, max_shard_size="160KB")
    files = list(d.glob("*.safetensors"))
    assert len(files) > 1, files
    assert (d / "model.safetensors.index.json").exists()

    mesh = make_mesh(MeshPlan(tp=8))
    cfg, params = load_model(str(d), mesh, dtype="float32")
    engine = DecodeEngine(cfg, params, mesh, max_seq_len=64)

    ids = [[3, 17, 42, 9, 88, 21]]
    with torch.no_grad():
        ref = model(torch.tensor(ids)).logits[0, -1].float().numpy()
    import jax.numpy as jnp

    cache = engine.new_cache(1)
    sa = engine._sample_args(GenerationParams(is_greedy=True), 1)
    padded, lens = engine._pad_prompts(ids)
    _, logits, _ = engine._prefill(
        engine.params, jnp.asarray(padded), cache, jnp.asarray(lens), sa
    )
    np.testing.assert_allclose(
        np.asarray(logits)[0], ref, atol=2e-3, rtol=2e-3
    )
