"""Prompt-lookup speculative decoding: exact greedy parity, fewer
forwards.

Verification is exact — ``generate_speculative`` must emit token-for-token
what plain greedy ``generate`` emits, on every workload shape: repetitive
prompts (speculation hits), random prompts (speculation misses — degrades
to normal steps, never to wrong tokens), EOS mid-draft, near-ring rows
(falls back to plain steps), and desynchronized row lengths.
"""

import jax
import numpy as np
import pytest

from llmss_tpu.engine import DecodeEngine, GenerationParams
from llmss_tpu.engine.speculative import lookup_draft
from llmss_tpu.parallel import MeshPlan, make_mesh
from tests.test_bucket import _cfg


@pytest.fixture(scope="module")
def engine(devices):
    mesh = make_mesh(MeshPlan(dp=2, tp=4))
    cfg = _cfg()
    from llmss_tpu.models.decoder import init_params

    params = init_params(cfg, mesh, jax.random.key(0))
    return DecodeEngine(cfg, params, mesh, max_seq_len=64)


def test_lookup_draft_basics():
    # trailing 3-gram [4,5,6] occurred before, followed by 7, 8
    assert lookup_draft([1, 4, 5, 6, 7, 8, 2, 4, 5, 6], 2) == [7, 8]
    # no match anywhere -> repeat last token
    assert lookup_draft([1, 2, 3], 3) == [3, 3, 3]
    # shorter-n fallback: 1-gram [2] matched, continuation padded
    assert lookup_draft([9, 2, 7, 2], 3)[0] == 7
    # single-token history
    assert lookup_draft([5], 2) == [5, 5]


@pytest.mark.parametrize("gamma", [1, 3, 4])
def test_exact_greedy_parity(engine, gamma):
    rng = np.random.default_rng(0)
    prompts = [
        # repetitive: speculation should hit
        [7, 3, 9, 7, 3, 9, 7, 3, 9, 7, 3],
        # random: speculation mostly misses
        rng.integers(1, 64, 10).tolist(),
        # short
        [5],
    ]
    gen = GenerationParams(max_new_tokens=20, is_greedy=True)
    plain = engine.generate(prompts, gen)
    spec = engine.generate_speculative(prompts, gen, gamma=gamma)
    assert spec == plain
    stats = engine.metrics.spec_stats
    assert stats is not None and stats["tokens_via_speculation"] > 0


def test_parity_with_eos_and_mixed_lengths(engine):
    """EOS can land mid-draft; rows finish at different steps."""
    rng = np.random.default_rng(1)
    prompts = [rng.integers(1, 64, n).tolist() for n in (4, 9, 2, 13)]
    # Find an eos that actually occurs: run plain first, pick a token
    gen0 = GenerationParams(max_new_tokens=24, is_greedy=True)
    plain0 = engine.generate(prompts, gen0)
    eos = plain0[0][len(plain0[0]) // 2]  # some token row 0 emits
    gen = GenerationParams(
        max_new_tokens=24, is_greedy=True, eos_token_id=int(eos),
    )
    plain = engine.generate(prompts, gen)
    spec = engine.generate_speculative(prompts, gen, gamma=4)
    assert spec == plain


def test_parity_near_ring_capacity(engine):
    """Rows whose generation approaches the ring must finish via the
    plain-step fallback with identical tokens."""
    prompts = [[3, 1, 4, 1, 5] * 8]  # 40 tokens in a 64-slot ring
    gen = GenerationParams(max_new_tokens=23, is_greedy=True)
    plain = engine.generate(prompts, gen)
    spec = engine.generate_speculative(prompts, gen, gamma=4)
    assert spec == plain


def test_sampled_rejected(engine):
    with pytest.raises(ValueError, match="greedy"):
        engine.generate_speculative(
            [[1, 2]],
            GenerationParams(max_new_tokens=4, is_greedy=False,
                             temperature=0.8),
        )


def test_prompt_at_ring_capacity_delegates(engine):
    """A prompt that (nearly) fills the ring can't speculate — the call
    must transparently serve plain greedy instead of crashing."""
    prompts = [[3, 1, 4, 1] * 16]  # 64 tokens == max_seq_len
    gen = GenerationParams(max_new_tokens=4, is_greedy=True)
    plain = engine.generate(prompts, gen)
    spec = engine.generate_speculative(prompts, gen, gamma=4)
    assert spec == plain


def test_stats_reset_between_calls(engine):
    gen = GenerationParams(max_new_tokens=8, is_greedy=True)
    engine.generate_speculative([[7, 3, 9] * 4], gen, gamma=2)
    assert engine.metrics.spec_stats["verify_forwards"] > 0
    # Near-capacity call -> zero speculation; stats must say so, not echo
    # the previous call's numbers.
    engine.generate_speculative([[3, 1, 4, 1] * 16], gen, gamma=4)
    assert engine.metrics.spec_stats["verify_forwards"] == 0


def test_device_draft_matches_host_reference():
    """Fuzz parity of the vectorized device draft against the host-side
    reference rule, INCLUDING the padding path: continuations truncated by
    the live length must pad exactly like the reference's
    ``out.append(out[-1])`` — on periodic prompts the bucket-padded device
    history otherwise drafts from stale pad slots and silently degrades
    acceptance."""
    import jax.numpy as jnp

    from llmss_tpu.engine.speculative import _device_draft

    rng = np.random.default_rng(7)
    H = 32
    fn = jax.jit(_device_draft, static_argnums=(2, 3))
    for trial in range(200):
        L = int(rng.integers(1, H + 1))
        vocab = int(rng.integers(2, 6))  # tiny vocab: frequent n-gram hits
        h = rng.integers(0, vocab, size=L).astype(np.int32)
        gamma = int(rng.integers(1, 6))
        ngram = int(rng.integers(1, 4))
        # Device histories are bucket-padded with garbage past L — the
        # draft must never read it as signal.
        hist = np.full(H, 99, np.int32)
        hist[:L] = h
        want = lookup_draft(h.tolist(), gamma, ngram)
        got = np.asarray(
            fn(jnp.asarray(hist), jnp.int32(L), gamma, ngram)
        ).tolist()
        assert got == want, (trial, h.tolist(), gamma, ngram, got, want)
