"""Test harness: force a virtual 8-device CPU mesh before JAX initializes.

This is the TPU-native analogue of the reference's ``FakeGroup`` /``DEBUG=1``
testing affordance (``utils/dist.py:14-37,62-63``): the same TP program runs on
any dev box, but here the collectives are *real* (XLA CPU collectives over 8
virtual devices) rather than no-ops, so sharded numerics are actually tested.
"""

import os

# XLA flags must be set before the CPU backend initializes.
os.environ["JAX_PLATFORMS"] = "cpu"
flags = os.environ.get("XLA_FLAGS", "")
if "xla_force_host_platform_device_count" not in flags:
    os.environ["XLA_FLAGS"] = (
        flags + " --xla_force_host_platform_device_count=8"
    ).strip()
# Keep CPU compile times sane on small test shapes.
os.environ.setdefault("JAX_ENABLE_X64", "0")

import jax  # noqa: E402

# The environment's sitecustomize imports jax at interpreter startup with
# JAX_PLATFORMS pointing at the real TPU platform, so the env var alone is
# read too early to help — override via config (backends are not yet
# initialized at conftest import time).
jax.config.update("jax_platforms", "cpu")

import pytest  # noqa: E402


@pytest.fixture(scope="session")
def devices():
    devs = jax.devices()
    assert len(devs) == 8, f"expected 8 virtual CPU devices, got {len(devs)}"
    return devs
