"""Metrics: stats math + engine/serving integration."""

import numpy as np

from llmss_tpu.utils.metrics import EngineMetrics, LatencyStat


def test_latency_stat_percentiles():
    s = LatencyStat("x")
    for v in [0.01, 0.02, 0.03, 0.04, 0.1]:
        s.record(v)
    d = s.to_dict()
    assert d["count"] == 5
    assert d["p50_ms"] == 30.0
    assert d["p99_ms"] == 100.0
    assert abs(d["mean_ms"] - 40.0) < 1e-6


def test_engine_metrics_shape():
    m = EngineMetrics()
    m.add_request(2)
    m.add_tokens(10)
    m.ttft.record(0.05)
    d = m.to_dict()
    assert d["requests_served"] == 2
    assert d["tokens_generated"] == 10
    assert d["ttft"]["count"] == 1
    assert d["poisoned_rows"] == 0


def test_poisoned_row_counter():
    m = EngineMetrics()
    m.add_poisoned()
    m.add_poisoned(2)
    assert m.to_dict()["poisoned_rows"] == 3


def test_supervisor_lifecycle_fields_exported():
    """The health channel carries the lifecycle state machine: state,
    watchdog stall count, and the watchdog config ride every publish (the
    producer's /health and /metrics read them from here)."""
    from llmss_tpu.serve.broker import InProcBroker
    from llmss_tpu.serve.protocol import STATE_STARTING, WORKER_STATES
    from llmss_tpu.serve.supervisor import Supervisor

    b = InProcBroker()
    sup = Supervisor(
        lambda: None, b, heartbeat_s=0.0, step_timeout_s=12.5,
    )
    b.publish_metrics({})
    s = b.read_metrics()["supervisor"]
    assert s["state"] == STATE_STARTING
    assert s["state"] in WORKER_STATES
    assert s["watchdog_stalls"] == 0
    assert s["step_timeout_s"] == 12.5
    assert "heartbeat_ts" in s and "heartbeat_s" in s
    sup.watchdog_stalls += 1
    b.publish_metrics({})
    assert b.read_metrics()["supervisor"]["watchdog_stalls"] == 1


def test_engine_records_metrics(tmp_path, devices):
    import torch
    import transformers as tr

    from llmss_tpu.engine import DecodeEngine, GenerationParams
    from llmss_tpu.models import config_from_hf
    from llmss_tpu.models.registry import MODEL_REGISTRY
    from llmss_tpu.parallel import MeshPlan, make_mesh
    from llmss_tpu.weights import CheckpointShards, weight_files

    torch.manual_seed(1)
    cfg_hf = tr.GPT2Config(
        vocab_size=64, n_positions=64, n_embd=32, n_layer=2, n_head=4
    )
    d = tmp_path / "m"
    tr.GPT2LMHeadModel(cfg_hf).eval().save_pretrained(
        d, safe_serialization=True
    )
    from transformers import AutoConfig

    mesh = make_mesh(MeshPlan(dp=2, tp=4))
    cfg = config_from_hf(AutoConfig.from_pretrained(d), dtype="float32")
    ckpt = CheckpointShards(weight_files(str(d)), dtype=np.float32)
    params = MODEL_REGISTRY["gpt2"].load_params(ckpt, cfg, mesh)
    engine = DecodeEngine(cfg, params, mesh, max_seq_len=64)

    engine.generate([[1, 2, 3]], GenerationParams(max_new_tokens=5))
    m = engine.metrics.to_dict()
    assert m["requests_served"] == 1
    assert m["tokens_generated"] == 5
    assert m["ttft"]["count"] == 1
    assert m["decode_step"]["count"] == 4
