"""Metrics: stats math + engine/serving integration."""

import numpy as np

from llmss_tpu.utils.metrics import EngineMetrics, LatencyStat


def test_latency_stat_percentiles():
    s = LatencyStat("x")
    for v in [0.01, 0.02, 0.03, 0.04, 0.1]:
        s.record(v)
    d = s.to_dict()
    assert d["count"] == 5
    assert d["p50_ms"] == 30.0
    assert d["p99_ms"] == 100.0
    assert abs(d["mean_ms"] - 40.0) < 1e-6


def test_engine_metrics_shape():
    m = EngineMetrics()
    m.add_request(2)
    m.add_tokens(10)
    m.ttft.record(0.05)
    d = m.to_dict()
    assert d["requests_served"] == 2
    assert d["tokens_generated"] == 10
    assert d["ttft"]["count"] == 1
    assert d["poisoned_rows"] == 0


def test_poisoned_row_counter():
    m = EngineMetrics()
    m.add_poisoned()
    m.add_poisoned(2)
    assert m.to_dict()["poisoned_rows"] == 3


def test_supervisor_lifecycle_fields_exported():
    """The health channel carries the lifecycle state machine: state,
    watchdog stall count, and the watchdog config ride every publish (the
    producer's /health and /metrics read them from here)."""
    from llmss_tpu.serve.broker import InProcBroker
    from llmss_tpu.serve.protocol import STATE_STARTING, WORKER_STATES
    from llmss_tpu.serve.supervisor import Supervisor

    b = InProcBroker()
    sup = Supervisor(
        lambda: None, b, heartbeat_s=0.0, step_timeout_s=12.5,
    )
    b.publish_metrics({})
    s = b.read_metrics()["supervisor"]
    assert s["state"] == STATE_STARTING
    assert s["state"] in WORKER_STATES
    assert s["watchdog_stalls"] == 0
    assert s["step_timeout_s"] == 12.5
    assert "heartbeat_ts" in s and "heartbeat_s" in s
    sup.watchdog_stalls += 1
    b.publish_metrics({})
    assert b.read_metrics()["supervisor"]["watchdog_stalls"] == 1


def test_engine_records_metrics(tmp_path, devices):
    import torch
    import transformers as tr

    from llmss_tpu.engine import DecodeEngine, GenerationParams
    from llmss_tpu.models import config_from_hf
    from llmss_tpu.models.registry import MODEL_REGISTRY
    from llmss_tpu.parallel import MeshPlan, make_mesh
    from llmss_tpu.weights import CheckpointShards, weight_files

    torch.manual_seed(1)
    cfg_hf = tr.GPT2Config(
        vocab_size=64, n_positions=64, n_embd=32, n_layer=2, n_head=4
    )
    d = tmp_path / "m"
    tr.GPT2LMHeadModel(cfg_hf).eval().save_pretrained(
        d, safe_serialization=True
    )
    from transformers import AutoConfig

    mesh = make_mesh(MeshPlan(dp=2, tp=4))
    cfg = config_from_hf(AutoConfig.from_pretrained(d), dtype="float32")
    ckpt = CheckpointShards(weight_files(str(d)), dtype=np.float32)
    params = MODEL_REGISTRY["gpt2"].load_params(ckpt, cfg, mesh)
    engine = DecodeEngine(cfg, params, mesh, max_seq_len=64)

    engine.generate([[1, 2, 3]], GenerationParams(max_new_tokens=5))
    m = engine.metrics.to_dict()
    assert m["requests_served"] == 1
    assert m["tokens_generated"] == 5
    assert m["ttft"]["count"] == 1
    assert m["decode_step"]["count"] == 4

def test_latency_stat_reservoir_spans_stream():
    """Algorithm-R sampling: once the reservoir is full, retained samples
    must span the whole stream rather than being a cyclic slice of the
    most recent ``max_samples`` values (the old deterministic-stride
    behavior). The rng is seeded from the stat name, so this is exact."""
    s = LatencyStat("resv", max_samples=50)
    for i in range(1000):
        s.record(float(i))
    assert len(s._samples) == 50
    early = sum(1 for v in s._samples if v < 500.0)
    # The stride sampler would keep only the tail (early == 0); a fair
    # reservoir keeps ~half from the first half of the stream.
    assert 10 <= early <= 40
    d = s.to_dict()
    assert set(d) == {"count", "mean_ms", "p50_ms", "p95_ms", "p99_ms"}
    assert d["count"] == 1000


def test_latency_stat_reservoir_deterministic():
    a, b = LatencyStat("same", max_samples=20), LatencyStat("same", max_samples=20)
    for i in range(300):
        a.record(float(i))
        b.record(float(i))
    assert a._samples == b._samples
    assert a.to_dict() == b.to_dict()


def test_render_prometheus():
    from llmss_tpu.utils.metrics import render_prometheus

    payload = {
        "requests_served": 3,
        "ttft": {
            "count": 2, "mean_ms": 5.0, "p50_ms": 4.0,
            "p95_ms": 6.0, "p99_ms": 6.5,
        },
        "delivery": {"redelivered": 1, "handoff_bytes": 64},
        "supervisor": {"state": "ready", "alive": True, "restarts": 0},
        "fleet": {
            "handoff_depth": 0,
            "workers": {
                "w0": {"queue_depth": 2, "free_slots": 4, "state": "ready"},
                "w1": {"queue_depth": 0, "free_slots": 8, "state": "ready"},
            },
        },
    }
    text = render_prometheus(payload)
    lines = text.splitlines()
    assert "llmss_requests_served 3" in lines
    # Latency dicts become a quantile family plus _count/_mean_ms.
    assert "# TYPE llmss_ttft_ms gauge" in lines
    assert 'llmss_ttft_ms{quantile="p50"} 4.0' in lines
    assert 'llmss_ttft_ms{quantile="p99"} 6.5' in lines
    assert "llmss_ttft_count 2" in lines
    assert "llmss_ttft_mean_ms 5.0" in lines
    assert "llmss_delivery_redelivered 1" in lines
    # Fleet workers get a worker label instead of per-worker names.
    assert 'llmss_fleet_worker_queue_depth{worker="w0"} 2' in lines
    assert 'llmss_fleet_worker_free_slots{worker="w1"} 8' in lines
    assert "llmss_fleet_handoff_depth 0" in lines
    # Strings and bools are not Prometheus samples.
    assert "ready" not in text and "alive" not in text
    assert "llmss_supervisor_restarts 0" in lines
    assert text.endswith("\n")
