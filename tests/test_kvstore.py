"""Tiered KV store: blob round-trips, LRU demotion, park/resume identity.

The tiered store (``serve/kvstore.py``) ships on four claims, each
pinned here:

- the at-rest LKVH prefix blob round-trips bit-exactly (bf16 and
  int8+scales, partial tail block included) through T1 and T2, and a
  corrupt blob is quarantined, never adopted;
- T1 is a byte-capped LRU whose overflow spills to T2 in exact LRU
  order — counted, never silent;
- a parked session resumes with zero re-prefill and the stream is
  bit-identical to the never-evicted run (dense greedy AND paged COW),
  and a promotion-installed prefix leaves the COW refcounts balanced;
- both T2 backends (``InProcBlobStore`` and ``RedisBlobStore`` over
  ``FakeRedis``) honor one contract, and tiering adds zero steady-state
  recompiles under ``CompileGuard``.
"""

import time

import numpy as np
import pytest

from llmss_tpu.serve.broker import InProcBroker
from llmss_tpu.serve.chaos import FakeRedis
from llmss_tpu.serve.kvstore import (
    HostKVStore,
    InProcBlobStore,
    RedisBlobStore,
    TieredKVStore,
    decode_prefix,
    encode_prefix,
    prefix_key,
)
from llmss_tpu.serve.protocol import GenerateRequest

# -- T1: cap-enforced LRU demotion order -------------------------------------


def test_host_lru_spills_in_lru_order():
    spilled = []
    h = HostKVStore(cap_bytes=300, spill_cb=lambda k, v: spilled.append(k))
    for key in ("a", "b", "c"):
        h.put(key, key.encode() * 100)
    assert spilled == []
    assert h.get("a") is not None  # touch: "a" becomes MRU
    h.put("d", b"d" * 100)  # over cap -> LRU ("b") spills first
    h.put("e", b"e" * 100)  # then "c" — never the touched "a"
    assert spilled == ["b", "c"]
    assert sorted(h.keys()) == ["a", "d", "e"]
    st = h.stats()
    assert st["bytes"] == 300 and st["entries"] == 3
    assert st["spilled"] == 2 and st["dropped"] == 0


def test_host_lru_oversized_payload_spills_straight_through():
    spilled = []
    h = HostKVStore(cap_bytes=100, spill_cb=lambda k, v: spilled.append(k))
    h.put("big", b"x" * 101)  # larger than the whole cap: never resident
    assert spilled == ["big"] and h.keys() == []
    assert h.get("big") is None


def test_host_lru_drops_are_counted_without_spill_cb():
    h = HostKVStore(cap_bytes=100)
    h.put("a", b"x" * 80)
    h.put("b", b"y" * 80)  # evicts "a" with nowhere to spill
    assert h.keys() == ["b"]
    assert h.stats()["dropped"] == 1 and h.stats()["spilled"] == 0


def test_tiered_get_falls_through_and_rewarm_t1():
    blob = InProcBlobStore()
    store = TieredKVStore(host=HostKVStore(cap_bytes=8), blob=blob)
    store.put_blob("a", b"A" * 8)
    store.put_blob("b", b"B" * 8)  # cap fits one: "a" spills to T2
    assert store.host.keys() == ["b"] and blob.keys() == ["a"]
    assert store.get_blob("a") == b"A" * 8  # T2 hit...
    assert store.host.keys() == ["a"]  # ...re-warms T1 ("b" spilled)
    assert sorted(blob.keys()) == ["a", "b"]


# -- T2: dual-backend blob contract ------------------------------------------


def make_blob(kind):
    if kind == "inproc":
        return InProcBlobStore(), None
    client = FakeRedis()
    return RedisBlobStore(client, namespace="t"), client


@pytest.mark.parametrize("kind", ("inproc", "fakeredis"))
def test_blob_store_contract(kind):
    b, _ = make_blob(kind)
    assert b.get("k") is None  # miss
    b.put("k", b"\x00\x01\xff")
    assert b.get("k") == b"\x00\x01\xff"  # raw bytes round-trip
    b.put("k", b"v2")
    assert b.get("k") == b"v2"  # overwrite, not append
    b.put("sess:1", b"s")
    assert sorted(b.keys()) == ["k", "sess:1"]
    b.delete("k")
    assert b.get("k") is None and b.keys() == ["sess:1"]
    b.delete("k")  # deleting a missing key is a no-op
    st = b.stats()
    assert st["puts"] == 3 and st["hits"] == 2
    assert st["misses"] == 2 and st["entries"] == 1


def test_redis_blob_store_namespaced_off_broker_keys():
    b, client = make_blob("fakeredis")
    b.put("prefix:abc", b"blob")
    # A broker queue key in the same namespace must not leak into the KV
    # segment's listing — and vice versa.
    client.set("t:queue:req", b"1")
    assert b.keys() == ["prefix:abc"]
    raw = client.get("t:kv:prefix:abc")
    assert raw == b"blob"


# -- at-rest prefix blob: bit-exactness --------------------------------------


def _synth_prefix(n, max_seq_len=64, quantized=False, seed=0):
    """A synthetic device Prefix: [L, P, Hkv, D] arrays (scales
    [L, P, Hkv]) bucket-padded the way ``engine.build_prefix`` pads, in
    the exact dtypes the cache uses."""
    import ml_dtypes

    from llmss_tpu.engine.engine import Prefix, _bucket

    pb = _bucket(n, max_seq_len)
    rng = np.random.default_rng(seed)
    shape = (2, pb, 2, 8)
    if quantized:
        k = rng.integers(-128, 128, shape, dtype=np.int8)
        v = rng.integers(-128, 128, shape, dtype=np.int8)
        ks = rng.standard_normal(shape[:-1], dtype=np.float32)
        vs = rng.standard_normal(shape[:-1], dtype=np.float32)
    else:
        k = rng.standard_normal(shape, np.float32).astype(ml_dtypes.bfloat16)
        v = rng.standard_normal(shape, np.float32).astype(ml_dtypes.bfloat16)
        ks = vs = None
    return Prefix(
        tokens=tuple(range(1, n + 1)), k=k, v=v, k_scale=ks, v_scale=vs,
    )


def _assert_prefix_bit_exact(a, b, n):
    """The first ``n`` slots (the live tokens) must match BIT-exactly;
    pad slots carry no positions and are zeroed by the round-trip."""
    for name in ("k", "v", "k_scale", "v_scale"):
        x, y = getattr(a, name), getattr(b, name)
        if x is None:
            assert y is None
            continue
        x, y = np.asarray(x), np.asarray(y)
        assert y.dtype == x.dtype and y.shape == x.shape
        assert y[:, :n].tobytes() == x[:, :n].tobytes()


# 32 = two full blocks; 18 = one full block + a 2-slot tail the encoder
# must zero-pad deterministically.
@pytest.mark.parametrize("quantized", (False, True))
@pytest.mark.parametrize("n", (32, 18))
def test_prefix_blob_roundtrip_bit_exact(quantized, n):
    pfx = _synth_prefix(n, quantized=quantized)
    payload = encode_prefix(pfx, block_size=16)
    rt = decode_prefix(payload, max_seq_len=64)
    assert rt.tokens == pfx.tokens
    _assert_prefix_bit_exact(pfx, rt, n)
    # Same tokens, same arrays -> byte-identical blob (pad slots are
    # zeroed, not whatever the builder's cache row held).
    pfx2 = _synth_prefix(n, quantized=quantized)
    assert encode_prefix(pfx2, block_size=16) == payload


@pytest.mark.parametrize("kind", ("inproc", "fakeredis"))
def test_demote_promote_through_both_tiers_bit_exact(kind):
    blob, _ = make_blob(kind)
    # cap 0: every demotion spills straight through T1 into T2, so the
    # promote below is a genuine fleet-blob fetch.
    store = TieredKVStore(host=HostKVStore(cap_bytes=0), blob=blob)
    pfx = _synth_prefix(18)
    store.demote_prefix(pfx, block_size=16)
    store.flush()
    assert store.host.keys() == []
    assert blob.keys() == [prefix_key(pfx.tokens)]
    got = store.fetch_prefix(list(pfx.tokens), max_seq_len=64)
    assert got is not None and got.tokens == pfx.tokens
    _assert_prefix_bit_exact(pfx, got, 18)
    st = store.stats()
    assert st["prefix_demotes"] == 1 and st["prefix_promotes"] == 1


def test_corrupt_blob_quarantined_not_adopted():
    store = TieredKVStore(host=HostKVStore(cap_bytes=0),
                          blob=InProcBlobStore())
    pfx = _synth_prefix(18)
    store.demote_prefix(pfx, block_size=16)
    store.flush()
    key = prefix_key(pfx.tokens)
    payload = store.blob.get(key)
    store.blob.put(key, payload[:-1] + bytes([payload[-1] ^ 0x01]))
    # CRC mismatch -> the blob is deleted and the caller re-prefills.
    assert store.fetch_prefix(list(pfx.tokens), max_seq_len=64) is None
    assert store.blob.keys() == []
    assert store.stats()["prefix_promotes"] == 0


def test_session_resume_consumes_only_on_proper_prefix():
    store = TieredKVStore(blob=InProcBlobStore())
    pfx = _synth_prefix(16)
    from llmss_tpu.serve.kvstore import blocks_from_prefix

    blocks, n = blocks_from_prefix(pfx, 16)
    store.park_session("s1", list(pfx.tokens), blocks, 16)
    # An edited-history turn (mismatched prompt) leaves the blob parked.
    assert store.resume_session("s1", token_ids=[9] * 20) is None
    assert store.resume_session("s1", token_ids=list(pfx.tokens)) is None
    good = list(pfx.tokens) + [77, 78]
    got = store.resume_session("s1", token_ids=good)
    assert got is not None and got[0] == list(pfx.tokens)
    # Consumed: the resumed row's KV diverges immediately, so a second
    # resume must re-prefill instead of adopting a stale copy.
    assert store.resume_session("s1", token_ids=good) is None
    assert store.stats()["sessions_resumed"] == 1


# -- real engine: stream identity + refcounts --------------------------------


import jax  # noqa: E402

from llmss_tpu.analysis import CompileGuard  # noqa: E402
from llmss_tpu.engine import DecodeEngine, GenerationParams  # noqa: E402
from llmss_tpu.engine.scheduler import ContinuousBatcher  # noqa: E402
from llmss_tpu.models.common import DecoderConfig  # noqa: E402
from llmss_tpu.models.decoder import init_params  # noqa: E402
from llmss_tpu.parallel import MeshPlan, make_mesh  # noqa: E402
from llmss_tpu.serve.consumer import ContinuousWorker  # noqa: E402


def _cfg():
    return DecoderConfig(
        model_type="llama", vocab_size=64, hidden_size=32, n_layers=2,
        n_heads=4, n_kv_heads=2, head_dim=8, intermediate_size=64,
        max_position_embeddings=64, activation="silu", norm="rmsnorm",
        norm_eps=1e-5, mlp="swiglu", positions="rotary", rope_style="half",
        rotary_dim=8, attn_bias=False, mlp_bias=False,
        tie_word_embeddings=False, dtype="float32",
    )


@pytest.fixture(scope="module")
def setup():
    mesh = make_mesh(MeshPlan(dp=2, tp=4))
    cfg = _cfg()
    params = init_params(cfg, mesh, jax.random.key(0))
    return cfg, mesh, params


@pytest.fixture(scope="module")
def paged_engine(setup):
    cfg, mesh, params = setup
    return DecodeEngine(
        cfg, params, mesh, max_seq_len=64, kv_layout="paged", block_size=16,
    )


@pytest.fixture(scope="module")
def dense_engine(setup):
    cfg, mesh, params = setup
    return DecodeEngine(cfg, params, mesh, max_seq_len=64)


@pytest.mark.slow
@pytest.mark.parametrize("quantized", (False, True))
def test_built_prefix_blob_roundtrip_bit_exact(setup, quantized):
    """The real exporter path: build_prefix KV (float32 or int8+scales)
    through the blob and back, bit-exact in every live slot."""
    cfg, mesh, params = setup
    engine = DecodeEngine(
        cfg, params, mesh, max_seq_len=64, kv_layout="paged",
        block_size=16, **({"kv_dtype": "int8"} if quantized else {}),
    )
    toks = list(range(1, 19))  # partial tail block
    pfx = engine.build_prefix(toks)
    payload = encode_prefix(pfx, engine.block_size)
    rt = decode_prefix(payload, max_seq_len=engine.max_seq_len)
    assert rt.tokens == tuple(toks)
    _assert_prefix_bit_exact(pfx, rt, len(toks))


@pytest.mark.slow
def test_promotion_install_preserves_cow_refcounts(paged_engine,
                                                   dense_engine):
    """A promoted (fetched + rebuilt) prefix installs into the COW
    registry exactly like a locally built one: rows share its block,
    their release decrefs only their own references, and eviction after
    the last reference frees the pool to zero — with streams matching
    the dense engine's exact tokens."""
    pfx_tokens = list(range(1, 21))  # 1 full block + tail
    built = paged_engine.build_prefix(pfx_tokens)
    store = TieredKVStore(blob=InProcBlobStore())
    store.demote_prefix(built, paged_engine.block_size)
    store.flush()
    promoted = store.fetch_prefix(
        pfx_tokens, max_seq_len=paged_engine.max_seq_len,
    )
    assert promoted is not None and promoted.tokens == tuple(pfx_tokens)

    gen = GenerationParams(max_new_tokens=5, is_greedy=True)
    full = [pfx_tokens + [30 + i] for i in range(2)]
    expected = [dense_engine.generate([p], gen)[0] for p in full]

    dec = ContinuousBatcher(paged_engine, rows=2)
    results = {}
    for i, p in enumerate(full):
        dec.submit(
            p, gen, lambda t, i=i: results.__setitem__(i, t),
            req_id=str(i), prefix=promoted,
        )
    dec.run_until_idle()
    for i, e in enumerate(expected):
        assert results[i] == e, (i, results[i], e)
    # Rows released their owned blocks; only the registry's shared
    # full block remains...
    assert dec.allocator.blocks_in_use == 1
    # ...and once no row references it, eviction balances to zero —
    # demoting the Prefix back down instead of dropping it.
    dec.demote_cb = lambda pfx: store.demote_prefix(pfx, 16)
    assert dec._paged_evict_idle_prefixes() == 1
    assert dec.allocator.blocks_in_use == 0
    store.flush()
    assert store.stats()["prefix_demotes"] == 2


# Turn 1 totals 20 tokens: (T-1)//16 = 1 full block parked, well under
# the ring-wrap park guard (T-1 + chunk lag <= 64).
_TURN1_PROMPT = [3, 1, 4, 1, 5, 9, 2, 6, 5, 3, 5, 8]


def _run_session(engine, kvstore, extra=(50, 51)):
    """Two turns of one session through a ContinuousWorker; returns the
    (turn1, turn2) token streams."""
    b = InProcBroker()
    w = ContinuousWorker(engine, b, rows=2, worker_id="w0", kvstore=kvstore)

    def ask(req):
        b.push_request(req)
        deadline = time.monotonic() + 60
        while time.monotonic() < deadline:
            w.run_once()
            resp = b.wait_response(req.id, timeout=0.01)
            if resp is not None:
                assert resp.error is None, (req.id, resp.error)
                return resp
        raise AssertionError(f"timeout waiting for {req.id}")

    r1 = ask(GenerateRequest(
        id="t1", token_ids=list(_TURN1_PROMPT), max_new_tokens=8,
        is_greedy=True, session_id="s1",
    ))
    prompt2 = list(_TURN1_PROMPT) + list(r1.token_ids) + list(extra)
    r2 = ask(GenerateRequest(
        id="t2", token_ids=prompt2, max_new_tokens=6,
        is_greedy=True, session_id="s1",
    ))
    return r1.token_ids, r2.token_ids


@pytest.mark.slow
@pytest.mark.parametrize("layout", ("dense", "paged"))
def test_session_park_resume_stream_identity(layout, dense_engine,
                                             paged_engine, request):
    """The headline claim: turn 2 of a parked session seeds from the
    parked KV (16 of its 22 prompt tokens never re-prefill) and the
    stream is bit-identical to the never-parked run."""
    engine = dense_engine if layout == "dense" else paged_engine
    ref1, ref2 = _run_session(engine, None)  # pre-tiering reference
    store = TieredKVStore(blob=InProcBlobStore())
    got1, got2 = _run_session(engine, store)
    assert got1 == ref1
    assert got2 == ref2
    st = store.stats()
    # Both turns parked; turn 2 consumed turn 1's blob and skipped
    # re-prefilling exactly the 16 parked tokens.
    assert st["sessions_parked"] == 2
    assert st["sessions_resumed"] == 1
    assert st["reprefill_tokens_avoided"] == 16


@pytest.mark.slow
def test_session_resume_survives_t1_pressure(paged_engine):
    """The parked blob spills to T2 under T1 pressure (cap 0 forces it);
    resume fetches it back through the blob store — same identity."""
    ref1, ref2 = _run_session(paged_engine, None)
    blob = InProcBlobStore()
    store = TieredKVStore(host=HostKVStore(cap_bytes=0), blob=blob)
    got1, got2 = _run_session(paged_engine, store)
    assert (got1, got2) == (ref1, ref2)
    assert store.stats()["sessions_resumed"] == 1
    assert blob.stats()["puts"] >= 1  # the park really went through T2


@pytest.mark.slow
def test_zero_steady_state_recompiles_with_tiering(paged_engine):
    """Park, resume, demote, and promote reuse the engine's prewarmed
    bucket shapes: after one warm pass, a fresh session and a fresh
    promoted prefix of the same lengths add ZERO compile-cache entries."""
    store = TieredKVStore(blob=InProcBlobStore())
    b = InProcBroker()
    w = ContinuousWorker(
        paged_engine, b, rows=2, worker_id="w0", kvstore=store,
    )

    def ask(req):
        b.push_request(req)
        deadline = time.monotonic() + 60
        while time.monotonic() < deadline:
            w.run_once()
            resp = b.wait_response(req.id, timeout=0.01)
            if resp is not None:
                assert resp.error is None, (req.id, resp.error)
                return resp
        raise AssertionError(f"timeout waiting for {req.id}")

    def one_session(sid, base):
        r1 = ask(GenerateRequest(
            id=f"{sid}-1", token_ids=[base] * 12, max_new_tokens=8,
            is_greedy=True, session_id=sid,
        ))
        ask(GenerateRequest(
            id=f"{sid}-2",
            token_ids=[base] * 12 + list(r1.token_ids) + [base + 1] * 2,
            max_new_tokens=6, is_greedy=True, session_id=sid,
        ))

    def one_promotion(pfx_tokens, rid):
        built = paged_engine.build_prefix(list(pfx_tokens))
        store.demote_prefix(built, paged_engine.block_size)
        store.flush()
        w._prefixes.clear()  # force the local LRU miss -> promote path
        ask(GenerateRequest(
            id=rid, token_ids=list(pfx_tokens) + [9], max_new_tokens=4,
            is_greedy=True, prefix_token_ids=list(pfx_tokens),
        ))

    # Warm: every tiering path once (park, resume, demote, promote).
    one_session("warm", base=2)
    one_promotion(range(1, 21), "warm-p")

    guard = CompileGuard.for_engine(paged_engine)
    # Steady state: same shapes, fresh session + fresh prefix.
    one_session("steady", base=5)
    one_promotion(range(21, 41), "steady-p")
    guard.assert_no_recompiles()
