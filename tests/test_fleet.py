"""Fleet routing: worker registry, routing policies, failover, and
multi-replica chaos.

The fleet layer (``serve/fleet.py`` over the broker registry/routed-queue
substrate) must keep the delivery contract the single-worker stack
already guarantees — every accepted request gets exactly one terminal
response — while adding replica placement and failover. Every
broker-level behavior here is exercised on both ``InProcBroker`` and
``RedisBroker``-over-``FakeRedis`` (the real Redis code paths: JSON
registry keys, routed lists, per-worker lease keys, SCAN-based
failover).
"""

import threading
import time
from types import SimpleNamespace
from typing import NamedTuple

import pytest

from llmss_tpu.serve.broker import InProcBroker, RedisBroker
from llmss_tpu.serve.chaos import FakeRedis, ScriptedEngine
from llmss_tpu.serve.consumer import Worker
from llmss_tpu.serve.fleet import (
    FleetHarness,
    Router,
    fleet_status,
    routable_workers,
)
from llmss_tpu.serve.handoff import HandoffRecord
from llmss_tpu.serve.producer import ProducerServer, evaluate_fleet_health
from llmss_tpu.sim.invariants import audit_exactly_once, collect_responses
from llmss_tpu.serve.protocol import (
    STATE_DEAD,
    STATE_READY,
    GenerateRequest,
    prefix_hash,
)

BROKER_KINDS = ("inproc", "fakeredis")


def make_brokers(kind, **kw):
    """(producer-side broker, make_worker_broker(worker_id)) pair.

    InProc: one shared object (worker identity is per-pop). Redis: one
    client instance per participant over a shared FakeRedis server, the
    real deployment shape.
    """
    if kind == "inproc":
        b = InProcBroker(**kw)
        return b, (lambda wid: b)
    server = FakeRedis()

    def mk(wid):
        return RedisBroker(client=server, worker_id=wid, **kw)

    return mk("producer"), mk


def snap(**over):
    """A fresh ready-worker load snapshot (what consumers publish)."""
    s = {
        "state": STATE_READY,
        "alive": True,
        "rows": 4,
        "inflight_rows": 0,
        "queue_depth": 0,
        "free_slots": 4,
        "free_kv_blocks": None,
        "kv_blocks_total": None,
        "prefix_hashes": [],
        "heartbeat_s": 5.0,
        "heartbeat_ts": time.time(),
    }
    s.update(over)
    return s


def req(i=0, **kw):
    kw.setdefault("deadline_ts", time.time() + 60.0)
    # token_ids must extend prefix_token_ids (protocol.validate contract).
    toks = list(kw.get("prefix_token_ids") or []) + [i + 1]
    r = GenerateRequest(token_ids=toks, max_new_tokens=4, **kw)
    r.validate()
    return r


# -- registry ---------------------------------------------------------------


@pytest.mark.parametrize("kind", BROKER_KINDS)
def test_registry_register_publish_read(kind):
    b, _ = make_brokers(kind)
    b.register_worker({"worker_id": "w0", "model": "gpt2", "kv_blocks": 64})
    b.publish_worker_load("w0", snap(inflight_rows=2))
    workers = b.read_workers()
    assert set(workers) == {"w0"}
    info = workers["w0"]
    # Capabilities and load snapshot merge into one entry.
    assert info["model"] == "gpt2" and info["kv_blocks"] == 64
    assert info["inflight_rows"] == 2 and info["state"] == STATE_READY
    # Internal expiry bookkeeping never leaks to readers.
    assert "_expires_at" not in info
    b.deregister_worker("w0")
    assert b.read_workers() == {}


@pytest.mark.parametrize("kind", BROKER_KINDS)
def test_registry_expiry_and_reregistration(kind):
    b, _ = make_brokers(kind, worker_ttl_s=0.1)
    b.register_worker({"worker_id": "w0", "model": "gpt2"})
    assert "w0" in b.read_workers()
    time.sleep(0.15)
    # Entry ages out when the worker stops publishing entirely.
    assert b.read_workers() == {}
    # A worker may simply re-register (consumer.register is re-callable);
    # a load publish alone also resurrects + refreshes the entry.
    b.register_worker({"worker_id": "w0", "model": "gpt2"})
    assert "w0" in b.read_workers()
    time.sleep(0.06)
    b.publish_worker_load("w0", snap())
    time.sleep(0.06)  # past the original stamp, within the refreshed one
    assert "w0" in b.read_workers()


@pytest.mark.parametrize("kind", BROKER_KINDS)
def test_routed_pop_priority_and_depths(kind):
    b, _ = make_brokers(kind)
    shared = req(0, id="shared")
    routed = req(1, id="routed")
    b.push_request(shared)
    b.push_request_to("w0", routed)
    assert b.routed_depths() == {"w0": 1}
    # Routed backlog counts toward admission control.
    assert b.queue_depth() == 2
    # A worker popping with its id drains its routed queue before the
    # shared one; a plain (anonymous) pop never sees routed work.
    got = b.pop_request(worker_id="w0")
    assert got.id == "routed"
    assert b.lease_holders() == {"w0": 1}
    got2 = b.pop_request(worker_id="w0")
    assert got2.id == "shared"
    assert b.routed_depths() == {}


# -- routing policies -------------------------------------------------------


def fleet_of(b, *wids, **snap_over):
    for wid in wids:
        b.register_worker({"worker_id": wid, "model": "gpt2"})
        b.publish_worker_load(wid, snap(**snap_over.get(wid, {})))


@pytest.mark.parametrize("kind", BROKER_KINDS)
def test_round_robin_rotation(kind):
    b, _ = make_brokers(kind)
    fleet_of(b, "w0", "w1", "w2", w1={}, w0={}, w2={})
    r = Router(b, "round_robin")
    picks = [r.submit(req(i)) for i in range(6)]
    assert picks == ["w0", "w1", "w2", "w0", "w1", "w2"]
    assert b.routed_depths() == {"w0": 2, "w1": 2, "w2": 2}
    stats = r.stats()
    assert stats["routed_total"] == 6 and stats["shared_fallback"] == 0
    assert stats["routed_by_worker"] == {"w0": 2, "w1": 2, "w2": 2}


@pytest.mark.parametrize("kind", BROKER_KINDS)
def test_least_loaded_prefers_idle_then_kv_headroom(kind):
    b, _ = make_brokers(kind)
    fleet_of(
        b, "w0", "w1", "w2",
        w0={"inflight_rows": 3, "free_slots": 1},
        w1={"free_kv_blocks": 8, "kv_blocks_total": 16},
        w2={"free_kv_blocks": 2, "kv_blocks_total": 16},
    )
    r = Router(b, "least_loaded")
    # Both idle workers beat the busy one; KV headroom breaks the tie.
    assert r.submit(req(0)) == "w1"
    # The live routed depth (not just the lagging snapshot) feeds back:
    # w1 now has backlog 1, so the truly idle w2 wins next.
    assert r.submit(req(1)) == "w2"
    # Tie again at backlog 1 each — headroom prefers w1.
    assert r.submit(req(2)) == "w1"
    assert "w0" not in r.stats()["routed_by_worker"]


@pytest.mark.parametrize("kind", BROKER_KINDS)
def test_least_loaded_skips_unroutable_states(kind):
    b, _ = make_brokers(kind)
    fleet_of(
        b, "w0", "w1", "w2",
        w0={"state": STATE_DEAD},
        w1={"state": "draining"},
        w2={"inflight_rows": 4, "free_slots": 0},
    )
    r = Router(b, "least_loaded")
    # Dead and draining replicas take nothing, however loaded the
    # survivor is.
    assert r.submit(req(0)) == "w2"
    assert set(routable_workers(b)) == {"w2"}


@pytest.mark.parametrize("kind", BROKER_KINDS)
def test_prefix_affinity_snapshot_sticky_and_fallback(kind):
    b, _ = make_brokers(kind)
    pfx = [7, 7, 7, 7]
    h = prefix_hash(pfx)
    fleet_of(
        b, "w0", "w1",
        w0={"free_kv_blocks": 64},  # the headroom favorite
        w1={"prefix_hashes": [h]},  # already holds the prefix
    )
    r = Router(b, "prefix_affinity")
    # Resident prefix wins over headroom: the request rides to w1.
    assert r.submit(req(0, prefix_token_ids=pfx)) == "w1"
    # Sticky thereafter, even as w1's backlog grows.
    assert r.submit(req(1, prefix_token_ids=pfx)) == "w1"
    assert r.submit(req(2, prefix_token_ids=pfx)) == "w1"
    # Unknown prefix: least-loaded fallback (w0 — all of w1's backlog),
    # and the chosen worker becomes the sticky owner.
    new_pfx = [9, 9]
    assert r.submit(req(3, prefix_token_ids=new_pfx)) == "w0"
    assert r.submit(req(4, prefix_token_ids=new_pfx)) == "w0"
    # No prefix → plain least-loaded, no affinity accounting.
    stats_before = r.stats()
    r.submit(req(5))
    stats = r.stats()
    assert stats["affinity_hits"] == stats_before["affinity_hits"]
    assert stats["affinity_misses"] == stats_before["affinity_misses"]
    # 4 hits (3 resident/sticky + 1 new-prefix sticky), 1 miss.
    assert stats["affinity_hits"] == 4 and stats["affinity_misses"] == 1
    assert stats["affinity_hit_rate"] == pytest.approx(0.8)


@pytest.mark.parametrize("kind", BROKER_KINDS)
def test_shared_fallback_when_no_replicas(kind):
    b, _ = make_brokers(kind)
    r = Router(b, "least_loaded")
    fallback = req(0)
    assert r.submit(fallback) is None
    assert r.stats()["shared_fallback"] == 1
    assert b.routed_depths() == {}
    # The request landed on the shared queue — any worker that appears
    # later serves it.
    got = b.pop_request(worker_id="late-joiner")
    assert got is not None and got.id == fallback.id


def test_router_rejects_unknown_policy():
    b = InProcBroker()
    with pytest.raises(ValueError, match="unknown policy"):
        Router(b, "fastest")


# -- failover ---------------------------------------------------------------


@pytest.mark.parametrize("kind", BROKER_KINDS)
def test_failover_moves_routed_and_leased_to_survivor(kind):
    b, mk = make_brokers(kind)
    # w0 heartbeats on a 0.05s cadence, so it reads stale 0.15s after its
    # last publish; w1 heartbeats slowly (stays fresh for the whole test).
    fleet_of(b, "w0", "w1", w0={"heartbeat_s": 0.05}, w1={})
    r = Router(b, "round_robin", failover_check_s=0.01)
    wb = mk("w0")
    r1, r2 = req(0), req(1)
    assert r.submit(r1) == "w0"
    assert r.submit(r2) == "w1"
    # Re-route r2's twin onto w0 so it holds routed AND leased work.
    r3 = req(2)
    b.push_request_to("w0", r3)
    leased = wb.pop_request(worker_id="w0")  # r1: now in-flight on w0
    assert leased.id == r1.id and leased.delivery_attempts == 1
    time.sleep(0.2)  # w0's heartbeat is now stale; w1 still fresh
    assert set(routable_workers(b)) == {"w1"}

    moved = r.check_failover(force=True)
    assert moved == 2  # r3 (routed) + r1 (force-expired lease)
    # Everything w0 held is now on the survivor's routed queue.
    assert b.routed_depths() == {"w1": 3}
    assert b.lease_holders() == {}
    got = {b.pop_request(worker_id="w1").id for _ in range(3)}
    assert got == {r1.id, r2.id, r3.id}
    # The never-delivered r3 spent no attempt; the leased r1 spent one.
    assert r.stats()["failover_reroutes"] == 2
    assert b.delivery_stats()["failover_rerouted"] == 2


@pytest.mark.parametrize("kind", BROKER_KINDS)
def test_failover_orphan_routed_queue(kind):
    """A routed queue whose worker has vanished from the registry
    entirely (TTL expiry) is still evacuated."""
    b, _ = make_brokers(kind, worker_ttl_s=0.05)
    b.register_worker({"worker_id": "ghost", "model": "gpt2"})
    fleet_of(b, "live")
    orphan = req(0)
    b.push_request_to("ghost", orphan)
    time.sleep(0.1)  # ghost's registry entry ages out; queue remains
    assert "ghost" not in b.read_workers()
    # "live" was registered with the same short TTL — keep it fresh.
    b.publish_worker_load("live", snap())
    r = Router(b, "least_loaded")
    assert r.check_failover(force=True) == 1
    assert b.routed_depths() == {"live": 1}
    assert b.pop_request(worker_id="live").id == orphan.id


@pytest.mark.parametrize("kind", BROKER_KINDS)
def test_failover_applies_terminal_dispositions(kind):
    """Force-expired leases go through the standard at-least-once
    disposition: attempts exhausted → DLQ + terminal error; deadline
    passed → terminal deadline error. Neither is re-routed."""
    b, mk = make_brokers(kind, max_delivery_attempts=1)
    fleet_of(b, "w0", w0={"heartbeat_s": 0.05})
    wb = mk("w0")
    doomed = req(0)  # its 1st delivery attempt is also its last
    late = req(1, deadline_ts=time.time() + 0.1)
    b.push_request_to("w0", doomed)
    b.push_request_to("w0", late)
    assert wb.pop_request(worker_id="w0") is not None
    assert wb.pop_request(worker_id="w0") is not None
    time.sleep(0.2)  # w0 stale AND late's deadline passed
    r = Router(b, "least_loaded")
    assert r.check_failover(force=True) == 0  # both terminal, none moved
    assert b.dlq_depth() == 1
    dead = b.wait_response(doomed.id, timeout=1.0)
    assert dead is not None and "dead-lettered after 1" in dead.error
    shed = b.wait_response(late.id, timeout=1.0)
    assert shed is not None and "deadline" in shed.error
    assert r.stats()["failover_reroutes"] == 0


@pytest.mark.parametrize("kind", BROKER_KINDS)
def test_failover_leaves_healthy_and_draining_workers_alone(kind):
    b, _ = make_brokers(kind)
    fleet_of(
        b, "w0", "w1",
        w0={},  # healthy
        w1={"state": "draining"},  # finishing its leases on purpose
    )
    b.push_request_to("w0", req(0))
    b.push_request_to("w1", req(1))
    r = Router(b, "least_loaded")
    assert r.check_failover(force=True) == 0
    assert b.routed_depths() == {"w0": 1, "w1": 1}


# -- status surfaces --------------------------------------------------------


@pytest.mark.parametrize("kind", BROKER_KINDS)
def test_fleet_status_and_aggregate_health(kind):
    b, _ = make_brokers(kind)
    fleet_of(
        b, "w0", "w1", "w2",
        w0={},
        w1={"state": STATE_DEAD},
        w2={"heartbeat_ts": time.time() - 600.0},  # long-stale
    )
    b.push_request_to("w0", req(0))
    r = Router(b, "least_loaded")
    st = fleet_status(b, r)
    assert set(st["workers"]) == {"w0", "w1", "w2"}
    assert st["ready"] == 1
    assert st["workers"]["w0"]["routable"] is True
    assert st["workers"]["w0"]["routed_queue_depth"] == 1
    assert st["workers"]["w1"]["routable"] is False
    assert st["workers"]["w1"]["health"] == STATE_DEAD
    assert st["workers"]["w2"]["health"] == "stale-heartbeat"
    assert st["router"]["policy"] == "least_loaded"

    code, body = evaluate_fleet_health(b.read_workers())
    assert code == 200 and body["ready"] == 1
    # The last ready replica going stale flips the fleet to 503.
    b.publish_worker_load(
        "w0", snap(heartbeat_ts=time.time() - 600.0)
    )
    code, body = evaluate_fleet_health(b.read_workers())
    assert code == 503 and body["status"] == "no-ready-workers"


def test_producer_fleet_endpoints():
    import http.client
    import json

    b = InProcBroker()
    fleet_of(b, "w0", "w1", w0={}, w1={"state": STATE_DEAD})
    router = Router(b, "least_loaded")
    srv = ProducerServer(b, host="127.0.0.1", port=0, router=router)
    srv.start()
    try:
        conn = http.client.HTTPConnection("127.0.0.1", srv.port, timeout=10)
        # Aggregate health: one dead replica does not 503 the frontend.
        conn.request("GET", "/health")
        resp = conn.getresponse()
        body = json.loads(resp.read())
        assert resp.status == 200
        assert body["ready"] == 1
        assert body["workers"]["w1"]["routable"] is False
        # GET /fleet: per-worker registry detail + router stats.
        conn.request("GET", "/fleet")
        resp = conn.getresponse()
        body = json.loads(resp.read())
        assert resp.status == 200
        assert body["ready"] == 1 and set(body["workers"]) == {"w0", "w1"}
        assert body["router"]["policy"] == "least_loaded"
        # /metrics grows a fleet block with per-worker labels.
        conn.request("GET", "/metrics")
        resp = conn.getresponse()
        body = json.loads(resp.read())
        assert resp.status == 200
        fl = body["fleet"]
        assert set(fl["workers"]) == {"w0", "w1"}
        assert fl["workers"]["w0"]["state"] == STATE_READY
        assert fl["router"]["routed_total"] == 0
        # The whole fleet going dead flips /health to 503.
        b.publish_worker_load("w0", snap(state=STATE_DEAD))
        conn.request("GET", "/health")
        resp = conn.getresponse()
        body = json.loads(resp.read())
        assert resp.status == 503 and body["status"] == "no-ready-workers"
        conn.close()
    finally:
        srv.stop()


def test_producer_metrics_unchanged_without_fleet():
    """No registry, no router → the /metrics payload has no fleet block
    and /health takes the legacy single-supervisor path (bit-identical
    pre-fleet behavior)."""
    import http.client
    import json

    b = InProcBroker()
    srv = ProducerServer(b, host="127.0.0.1", port=0)
    srv.start()
    try:
        conn = http.client.HTTPConnection("127.0.0.1", srv.port, timeout=10)
        conn.request("GET", "/metrics")
        body = json.loads(conn.getresponse().read())
        assert "fleet" not in body
        conn.request("GET", "/health")
        resp = conn.getresponse()
        body = json.loads(resp.read())
        assert resp.status == 200 and body.get("worker") == "unsupervised"
        conn.close()
    finally:
        srv.stop()


# -- worker integration -----------------------------------------------------


def test_worker_registers_and_serves_routed_requests():
    b = InProcBroker()
    w = Worker(
        ScriptedEngine(), b, batch_size=2, poll_timeout_s=0.01,
        pad_batch=False, worker_id="w0", snapshot_interval_s=0.01,
    )
    info = b.read_workers()["w0"]
    assert info["model"] == "ScriptedEngine"
    assert info["state"] == STATE_READY and "heartbeat_ts" in info
    first_ts = info["heartbeat_ts"]
    r = req(0)
    b.push_request_to("w0", r)
    time.sleep(0.02)
    w.run_once()
    got = b.wait_response(r.id, timeout=5.0)
    assert got is not None and not got.error
    assert got.token_ids == ScriptedEngine.expected_tokens(
        list(r.token_ids), r.max_new_tokens
    )
    # run_once refreshed the heartbeat past the registration stamp.
    assert b.read_workers()["w0"]["heartbeat_ts"] >= first_ts


def test_anonymous_worker_stays_out_of_registry():
    b = InProcBroker()
    w = Worker(
        ScriptedEngine(), b, batch_size=2, poll_timeout_s=0.01,
        pad_batch=False,
    )
    assert b.read_workers() == {}
    r = req(0)
    b.push_request(r)
    w.run_once()
    assert b.wait_response(r.id, timeout=5.0) is not None
    assert b.read_workers() == {}


def test_scheduler_load_snapshot_is_host_only():
    """ContinuousBatcher.load_snapshot: host counters + resident prefix
    hashes, no device arrays touched."""
    from llmss_tpu.engine import GenerationParams
    from llmss_tpu.engine.scheduler import ContinuousBatcher

    class _Eng:
        kv_layout = "dense"
        max_seq_len = 64
        cfg = None
        mesh = None

        def canon_vec(self, x):
            return x

        def new_cache(self, rows):
            return None

        def check_capacity(self, prompt_len, max_new_tokens):
            pass

    b = ContinuousBatcher(_Eng(), rows=4)
    gen = GenerationParams(max_new_tokens=4, is_greedy=True)
    b.submit([1, 2], gen, lambda *_: None)
    b.submit([3, 4], gen, lambda *_: None)
    s = b.load_snapshot()
    assert s["rows"] == 4 and s["pending"] == 2
    assert s["inflight_rows"] == 0 and s["free_slots"] == 4
    assert s["free_kv_blocks"] is None and s["prefix_hashes"] == []

    # Paged bookkeeping surfaces pool headroom + prefix content hashes.
    class _Pfx(NamedTuple):
        tokens: tuple

    b._paged = True
    b.allocator = SimpleNamespace(free_blocks=5, num_blocks=8)
    b._paged_prefixes = {1: (_Pfx((1, 2, 3)), [0, 1])}
    s = b.load_snapshot()
    assert s["free_kv_blocks"] == 5 and s["kv_blocks_total"] == 8
    assert s["prefix_hashes"] == [prefix_hash((1, 2, 3))]


# -- multi-replica chaos ----------------------------------------------------


# Shared with the fleet simulator's invariant catalog (sim/invariants):
# wall-clock chaos and virtual-clock storms audit the same contract.
_collect = collect_responses


@pytest.mark.parametrize("kind", BROKER_KINDS)
def test_fleet_chaos_kill_mid_decode(kind):
    """3 replicas, one hard-killed mid-decode while holding routed and
    leased work; the machine never comes back. Failover + lease
    redelivery must get every request exactly one terminal response with
    an uncorrupted payload — zero lost, zero double-answered."""
    producer, mk = make_brokers(
        kind, lease_s=0.25, max_delivery_attempts=6,
    )
    wids = ["w0", "w1", "w2"]
    switches = {wid: threading.Event() for wid in wids}

    def make_worker(wid):
        return Worker(
            ScriptedEngine(kill_switch=switches[wid], chunk_delay_s=0.002),
            mk(wid), batch_size=2, poll_timeout_s=0.02, pad_batch=False,
            worker_id=wid, snapshot_interval_s=0.04,
        )

    # stale_factor 10 × 0.04s heartbeats: a live replica would have to
    # stall 0.4s to be misjudged (heartbeats refresh every decode chunk),
    # while the killed one reads stale well inside the test budget.
    router = Router(
        producer, "least_loaded", stale_factor=10.0, failover_check_s=0.05,
    )
    reqs = [req(i) for i in range(18)]
    stop_pump = threading.Event()

    def pump():
        while not stop_pump.is_set():
            router.check_failover(force=True)
            time.sleep(0.05)

    harness = FleetHarness(make_worker, wids, respawn=False)
    # w0 dies at its first decode chunk — mid-decode, leases held.
    switches["w0"].set()
    pump_t = threading.Thread(target=pump, daemon=True)
    with harness:
        deadline = time.monotonic() + 10.0
        while len(router.routable_workers()) < 3:
            assert time.monotonic() < deadline, "fleet never became ready"
            time.sleep(0.01)
        for r in reqs[:12]:
            router.submit(r)
        deadline = time.monotonic() + 10.0
        while harness.hosts["w0"].kills < 1:
            assert time.monotonic() < deadline, "kill switch never fired"
            time.sleep(0.01)
        # Strand work on the corpse: routed directly to w0, never leased.
        stranded = reqs[12:15]
        for r in stranded:
            producer.push_request_to("w0", r)
        for r in reqs[15:]:
            router.submit(r)
        pump_t.start()
        try:
            results = _collect(producer, reqs, timeout_s=60.0)
        finally:
            stop_pump.set()
            pump_t.join(timeout=5)

    assert not [h.error for h in harness.hosts.values() if h.error]
    assert harness.hosts["w0"].kills == 1
    assert harness.hosts["w0"].spawns == 1  # the machine stayed dead
    # == len(reqs): exactly-once AND zero terminal errors — a kill with
    # failover may not cost any request its clean payload.
    assert audit_exactly_once(reqs, results) == len(reqs)
    # The stranded routed work was rescued by failover, not luck.
    assert router.stats()["failover_reroutes"] >= len(stranded)
    assert producer.delivery_stats()["failover_rerouted"] >= len(stranded)
    assert "w0" not in router.routable_workers()


# -- disaggregated roles ----------------------------------------------------


def hrec(i=0, **kw):
    r = req(i, **kw)
    return HandoffRecord(
        req=r, first_token=1, n_tokens=len(r.token_ids), payload=b"kv" * 8,
    )


@pytest.mark.parametrize("kind", BROKER_KINDS)
def test_router_excludes_decode_replicas_from_raw_requests(kind):
    b, _ = make_brokers(kind)
    fleet_of(
        b, "w0", "d0",
        w0={"inflight_rows": 3, "free_slots": 1},  # busy unified replica
        d0={"role": "decode"},  # idle decode replica
    )
    r = Router(b, "least_loaded")
    # The idle decode replica NEVER takes a raw request — it only speaks
    # the handoff channel; a request routed there would strand.
    assert r.submit(req(0)) == "w0"
    assert r.submit(req(1)) == "w0"
    assert "d0" not in r.stats()["routed_by_worker"]

    # A decode-only fleet has no raw-request target at all: shared-queue
    # fallback (a prefill/unified replica appearing later serves it).
    b2, _ = make_brokers(kind)
    fleet_of(b2, "d0", d0={"role": "decode"})
    r2 = Router(b2, "least_loaded")
    assert r2.submit(req(2)) is None
    assert r2.stats()["shared_fallback"] == 1
    assert b2.routed_depths() == {}


@pytest.mark.parametrize("kind", BROKER_KINDS)
def test_fleet_status_shows_roles_and_handoff_depths(kind):
    b, mk = make_brokers(kind)
    fleet_of(
        b, "p0", "d0",
        p0={"role": "prefill"},
        d0={"role": "decode"},
    )
    routed, shared = hrec(0), hrec(1)
    b.push_handoff_to("d0", routed)
    b.push_handoff(shared)
    st = fleet_status(b, Router(b, "least_loaded"))
    assert st["workers"]["p0"]["role"] == "prefill"
    assert st["workers"]["d0"]["role"] == "decode"
    assert st["workers"]["d0"]["routed_handoff_depth"] == 1
    assert st["workers"]["d0"]["handoff_leases_held"] == 0
    assert st["handoff_depth"] == 2  # shared + routed

    # Adoption converts routed depth into a held lease (the routed queue
    # drains before the shared one, so d0 gets its targeted record).
    got = mk("d0").pop_handoff(timeout=0.5, worker_id="d0")
    assert got is not None and got.req.id == routed.req.id
    st = fleet_status(b, None)
    assert st["workers"]["d0"]["routed_handoff_depth"] == 0
    assert st["workers"]["d0"]["handoff_leases_held"] == 1
    assert st["handoff_depth"] == 1


@pytest.mark.parametrize("kind", BROKER_KINDS)
def test_failover_reroutes_handoffs_to_surviving_decode(kind):
    b, mk = make_brokers(kind)
    fleet_of(
        b, "p0", "d0", "d1",
        p0={"role": "prefill"},
        d0={"role": "decode", "heartbeat_s": 0.05},
        d1={"role": "decode"},
    )
    # d0 adopted one record (leased) and has one routed-but-unleased.
    b.push_handoff_to("d0", hrec(0, id="adopted"))
    db = mk("d0")
    got = db.pop_handoff(timeout=0.5, worker_id="d0")
    assert got is not None and got.req.id == "adopted"
    b.push_handoff_to("d0", hrec(1, id="routed"))
    time.sleep(0.2)  # d0's heartbeat goes stale; d1 stays fresh
    r = Router(b, "least_loaded", failover_check_s=0.01)
    assert r.check_failover(force=True) == 1  # the intact routed record
    # The routed record (KV payload intact) moved to the surviving
    # decode replica — no re-prefill for it...
    assert b.handoff_depths() == {"d1": 1}
    moved = mk("d1").pop_handoff(timeout=0.5, worker_id="d1")
    assert moved is not None and moved.req.id == "routed"
    # ...while the adopted one re-prefills: its device state died with
    # d0, so the embedded request returns to the shared queue.
    back = b.pop_request(timeout=0.5)
    assert back is not None and back.id == "adopted"
    assert b.delivery_stats()["reprefills"] == 1
    assert r.stats()["handoff_reroutes"] == 1


def test_producer_surfaces_roles_and_handoff_metrics():
    import http.client
    import json

    b = InProcBroker()
    fleet_of(
        b, "p0", "d0",
        p0={"role": "prefill"},
        d0={"role": "decode"},
    )
    b.push_handoff_to("d0", hrec(0))
    router = Router(b, "least_loaded")
    srv = ProducerServer(b, host="127.0.0.1", port=0, router=router)
    srv.start()
    try:
        conn = http.client.HTTPConnection("127.0.0.1", srv.port, timeout=10)
        # GET /fleet: per-worker role + handoff depth detail.
        conn.request("GET", "/fleet")
        resp = conn.getresponse()
        body = json.loads(resp.read())
        assert resp.status == 200
        assert body["workers"]["p0"]["role"] == "prefill"
        assert body["workers"]["d0"]["role"] == "decode"
        assert body["workers"]["d0"]["routed_handoff_depth"] == 1
        assert body["handoff_depth"] == 1
        # /metrics fleet block: role per worker + handoff queue depths.
        conn.request("GET", "/metrics")
        resp = conn.getresponse()
        body = json.loads(resp.read())
        assert resp.status == 200
        fl = body["fleet"]
        assert fl["workers"]["p0"]["role"] == "prefill"
        assert fl["workers"]["d0"]["role"] == "decode"
        assert fl["handoff_depth"] == 1
        assert fl["handoff_depths"] == {"d0": 1}
        # The delivery block carries the channel counters.
        assert body["delivery"]["handoffs"] == 1
        conn.close()
    finally:
        srv.stop()
