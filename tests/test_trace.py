"""End-to-end request tracing (``utils/trace.py``).

The tentpole claims pinned here:

- trace context (``trace_id`` + ``trace_attempt``) survives the wire and
  the LKVH handoff on BOTH brokers, so ``GET /trace/{req_id}`` can
  reconstruct the full producer → prefill → handoff → decode timeline;
- a decode replica hard-killed mid-handoff leaves a complete flight
  recorder timeline: the re-prefill keeps the SAME trace id with a bumped
  attempt index, and the timeline ends in exactly one terminal event;
- the Chrome trace export is valid JSON with per-process monotonically
  consistent timestamps even under (simulated) cross-process clock skew —
  the one-wall-anchor-per-export discipline is what makes that true;
- tracing off records nothing, and tracing on adds zero steady-state
  recompiles (the instrumentation is host-side only).
"""

import json
import threading
import time

import httpx
import pytest

from llmss_tpu.serve.broker import InProcBroker, RedisBroker
from llmss_tpu.serve.chaos import (
    ChaosWorkerHost,
    FakeRedis,
    HardKill,
    ScriptedEngine,
)
from llmss_tpu.serve.handoff import DecodeWorker, PrefillWorker
from llmss_tpu.serve.producer import ProducerServer
from llmss_tpu.serve.protocol import GenerateRequest
from llmss_tpu.utils import trace
from llmss_tpu.utils.trace import FlightRecorder

BROKER_KINDS = ("inproc", "fakeredis")


def make_brokers(kind, **kw):
    """(producer-side broker, make_worker_broker(worker_id)) — the same
    two deployment shapes tests/test_handoff.py exercises."""
    if kind == "inproc":
        b = InProcBroker(**kw)
        return b, (lambda wid: b)
    server = FakeRedis()

    def mk(wid):
        return RedisBroker(client=server, worker_id=wid, **kw)

    return mk("producer"), mk


@pytest.fixture(autouse=True)
def clean_recorder():
    """Each test starts from an empty process recorder with tracing on."""
    trace.set_enabled(True)
    trace.recorder().clear()
    yield
    trace.set_enabled(True)
    trace.recorder().clear()


# -- flight recorder unit behavior ------------------------------------------


def test_recorder_ring_evicts_oldest_request():
    rec = FlightRecorder(max_requests=2, proc="p")
    rec.record("a", "enqueue")
    rec.record("b", "enqueue")
    rec.record("c", "enqueue")  # ring full: "a" (oldest) is evicted
    assert rec.req_ids() == ["b", "c"]
    rec.record("b", "lease")  # touching "b" makes "c" the eviction victim
    rec.record("d", "enqueue")
    assert rec.req_ids() == ["b", "d"]


def test_recorder_sheds_group_spam_before_lifecycle_events():
    rec = FlightRecorder(max_events=4, proc="p")
    rec.record("r", "enqueue")
    for _ in range(3):
        rec.record("r", "group_fetch")
    # At capacity a lifecycle event evicts a sheddable one, never the
    # other way around...
    rec.record("r", "respond")
    names = [e["name"] for e in rec.events_for("r")]
    assert names.count("group_fetch") == 2
    assert names[0] == "enqueue" and names[-1] == "respond"
    # ...and new sheddable events at capacity are simply dropped.
    rec.record("r", "group_dispatch")
    assert len(rec.events_for("r")) == 4
    assert rec.export()["requests"]["r"]["dropped"] == 2


def test_recorder_throttles_renewals():
    rec = FlightRecorder(proc="p")
    rec.record("r", "lease_renew", throttle_s=10.0)
    rec.record("r", "lease_renew", throttle_s=10.0)
    rec.record("r", "lease_renew", throttle_s=10.0)
    assert len(rec.events_for("r")) == 1


def test_span_records_duration_error_and_is_idempotent():
    rec = FlightRecorder(proc="p")
    with rec.start_span("r", "prefill", worker="w0"):
        pass
    ev = rec.events_for("r")[0]
    assert ev["name"] == "prefill" and ev["dur"] >= 0.0
    assert ev["attrs"]["worker"] == "w0"
    with pytest.raises(RuntimeError):
        with rec.start_span("r", "decode"):
            raise RuntimeError("boom")
    assert rec.events_for("r")[1]["attrs"]["error"] == "RuntimeError"
    s = rec.start_span("r", "adopt")
    s.end()
    s.end()  # idempotent: one event, not two
    assert len(rec.events_for("r")) == 3


def test_export_budget_keeps_most_recent():
    rec = FlightRecorder(proc="p")
    for i in range(5):
        rec.record(f"r{i}", "enqueue")
    ex = rec.export(max_events=2)
    assert set(ex["requests"]) == {"r4", "r3"}
    assert "wall_anchor" in ex and "mono_anchor" in ex and ex["proc"] == "p"


# -- trace context on the wire ----------------------------------------------


def test_trace_context_survives_wire_roundtrip():
    req = GenerateRequest(id="w1", token_ids=[1, 2])
    trace.ensure_context(req)
    assert req.trace_id == "w1"
    rt = GenerateRequest.from_json(req.to_json())
    assert rt.trace_id == "w1" and rt.trace_attempt == 0
    # Pre-tracing payloads (no trace fields) still parse: wire-compatible.
    d = json.loads(req.to_json())
    d.pop("trace_id")
    d.pop("trace_attempt")
    old = GenerateRequest.from_json(json.dumps(d))
    assert old.trace_id is None and old.trace_attempt == 0


# -- end-to-end propagation across the handoff ------------------------------


def _run_to_completion(b, workers, reqs, timeout_s=20.0):
    got = {}
    deadline = time.monotonic() + timeout_s
    while len(got) < len(reqs) and time.monotonic() < deadline:
        for w in workers:
            w.run_once()
        for r in reqs:
            if r.id not in got:
                resp = b.wait_response(r.id, timeout=0.01)
                if resp is not None:
                    got[r.id] = resp
    return got


@pytest.mark.parametrize("kind", BROKER_KINDS)
def test_trace_propagates_producer_to_decode(kind):
    b, mk = make_brokers(kind, lease_s=2.0)
    pre = PrefillWorker(ScriptedEngine(), mk("p0"), worker_id="p0")
    dec = DecodeWorker(ScriptedEngine(), mk("d0"), worker_id="d0")
    reqs = [
        GenerateRequest(id=f"t{i}", token_ids=[5 + i, 3], max_new_tokens=4)
        for i in range(2)
    ]
    for r in reqs:
        b.push_request(r)
    got = _run_to_completion(b, [pre, dec], reqs)
    assert len(got) == len(reqs)

    exports = [trace.recorder().export()]
    for r in reqs:
        tl = trace.timeline(exports, r.id)
        assert tl is not None and tl["trace_id"] == r.id
        names = [e["name"] for e in tl["events"]]
        # The full disaggregated path, in one stitched timeline.
        for expected in (
            "enqueue", "lease", "prefill", "handoff_push",
            "handoff_lease", "decode", "respond",
        ):
            assert expected in names, (r.id, expected, names)
        assert names.count("respond") == 1
        assert names[-1] == "respond"
        assert {e["trace_id"] for e in tl["events"]} == {r.id}
        assert tl["phases"].get("queue_wait", 0.0) >= 0.0
        assert tl["dominant_phase"] is not None


# -- the acceptance chaos case ----------------------------------------------


class _KillOnAdopt(ScriptedEngine):
    """Decode-engine stand-in whose first N adoptions are machine death:
    HardKill escapes mid-adopt with the handoff lease still open."""

    def __init__(self, kills: int):
        super().__init__()
        self._kills_left = kills
        self._klock = threading.Lock()

    def adopt_generate(self, *a, **kw):
        with self._klock:
            if self._kills_left > 0:
                self._kills_left -= 1
                raise HardKill("chaos: decode replica died mid-adopt")
        return super().adopt_generate(*a, **kw)


@pytest.mark.parametrize("kind", BROKER_KINDS)
def test_chaos_kill_decode_mid_handoff_timeline(kind):
    """A decode replica hard-dies after leasing a handoff record. The
    lease expires, the broker re-prefills the request — same trace_id,
    bumped attempt index — and the flight recorder shows the complete
    story ending in exactly one terminal event."""
    b, mk = make_brokers(kind, lease_s=0.25, max_delivery_attempts=6)
    eng = _KillOnAdopt(2)  # shared across respawns: exactly 2 deaths
    pre = ChaosWorkerHost(
        lambda: PrefillWorker(
            ScriptedEngine(), mk("p0"), worker_id="p0",
            poll_timeout_s=0.02,
        ),
        respawn_delay_s=0.02,
    )
    dec = ChaosWorkerHost(
        lambda: DecodeWorker(
            eng, mk("d0"), worker_id="d0", poll_timeout_s=0.02,
        ),
        respawn_delay_s=0.02,
    )
    reqs = [
        GenerateRequest(
            id=f"c{i}", token_ids=[i + 2, 9], max_new_tokens=4,
            deadline_ts=time.time() + 30.0,
        )
        for i in range(4)
    ]
    pre.start()
    dec.start()
    try:
        for r in reqs:
            b.push_request(r)
        for r in reqs:
            resp = b.wait_response(r.id, timeout=20.0)
            assert resp is not None, f"lost {r.id}"
            assert resp.error is None, (r.id, resp.error)
            assert resp.token_ids == ScriptedEngine.expected_tokens(
                list(r.token_ids), r.max_new_tokens,
            )
            assert b.wait_response(r.id, timeout=0.05) is None, (
                f"duplicate terminal response for {r.id}"
            )
    finally:
        pre.stop()
        dec.stop()
    assert pre.error is None and dec.error is None
    assert dec.kills == 2

    exports = [trace.recorder().export()]
    n_reprefills = 0
    for r in reqs:
        tl = trace.timeline(exports, r.id)
        assert tl is not None and tl["trace_id"] == r.id
        names = [e["name"] for e in tl["events"]]
        terminals = [n for n in names if n in trace.TERMINAL_EVENTS]
        assert terminals == ["respond"], (r.id, names)
        assert names[-1] == "respond"
        reps = [e for e in tl["events"] if e["name"] == "reprefill"]
        for i, e in enumerate(reps, start=1):
            # Re-prefill stays inside the ORIGINAL request's timeline:
            # same trace id, attempt index bumped per re-prefill.
            assert e["trace_id"] == r.id
            assert e["attrs"]["attempt"] == i
        n_reprefills += len(reps)
    assert n_reprefills == 2
    assert b.delivery_stats()["reprefills"] == 2


# -- cross-process stitching under clock skew --------------------------------


def _skewed_exports():
    """Two process exports whose monotonic epochs are wildly different
    (1000s vs 50s) and whose wall anchors disagree by 200 ms — the
    stitcher must align them purely through the per-export anchors."""
    ex_a = {
        "proc": "pA", "mono_anchor": 1000.0, "wall_anchor": 5000.0,
        "requests": {"r": {"trace_id": "r", "dropped": 0, "events": [
            {"req_id": "r", "name": "enqueue", "t": 999.0},
            {"req_id": "r", "name": "lease", "t": 999.5},
        ]}},
    }
    ex_b = {
        "proc": "pB", "mono_anchor": 50.0, "wall_anchor": 5000.2,
        "requests": {"r": {"trace_id": "r", "dropped": 0, "events": [
            {"req_id": "r", "name": "prefill", "t": 49.9, "dur": 0.4},
            {"req_id": "r", "name": "respond", "t": 49.95},
        ]}},
    }
    return [ex_a, ex_b]


def test_stitch_aligns_across_clock_skew():
    evs = trace.stitch(_skewed_exports())
    assert [e["name"] for e in evs] == [
        "enqueue", "lease", "prefill", "respond",
    ]
    phases = trace.phase_breakdown(evs)
    assert abs(phases["queue_wait"] - 0.5) < 1e-9
    assert abs(phases["prefill"] - 0.4) < 1e-9
    assert trace.dominant_phase(evs) == "queue_wait"
    tl = trace.timeline(_skewed_exports(), "r")
    assert abs(tl["total_s"] - 1.15) < 1e-6
    rows = trace.slowest(_skewed_exports(), n=3)
    assert rows[0]["req_id"] == "r"
    assert rows[0]["dominant_phase"] == "queue_wait"


def test_stitch_dedups_double_delivered_events():
    # The same export arriving twice (local recorder + registry
    # heartbeat) must not duplicate the timeline.
    ex = _skewed_exports()[0]
    assert len(trace.stitch([ex, ex])) == 2


def test_chrome_trace_export_valid():
    exports = _skewed_exports()
    doc = json.loads(trace.chrome_trace_json(exports))  # valid JSON
    assert doc["displayTimeUnit"] == "ms"
    evs = doc["traceEvents"]
    # "C" = devtel counter tracks (KV blocks, MFU/MBU, queue depths).
    assert {e["ph"] for e in evs} <= {"M", "X", "i", "C"}
    procs = {
        e["args"]["name"] for e in evs
        if e["ph"] == "M" and e["name"] == "process_name"
    }
    assert procs == {"pA", "pB"}
    xs = [e for e in evs if e["ph"] == "X"]
    assert len(xs) == 1 and abs(xs[0]["dur"] - 0.4e6) < 1.0
    assert all(e["ts"] >= 0 for e in evs if e["ph"] in ("X", "i"))
    assert all(e["s"] == "t" for e in evs if e["ph"] == "i")

    # Per-process consistency: within one process the wall-aligned order
    # must equal the monotonic order (the anchor is a pure offset).
    by_proc: dict = {}
    for e in trace.stitch(exports):
        by_proc.setdefault(e["proc"], []).append(e)
    for proc_evs in by_proc.values():
        ts = [e["ts_wall"] for e in proc_evs]
        mono = [e["t"] for e in proc_evs]
        assert ts == sorted(ts) and mono == sorted(mono)


# -- tracing off -------------------------------------------------------------


def test_tracing_off_records_nothing():
    trace.set_enabled(False)
    b, mk = make_brokers("inproc", lease_s=2.0)
    pre = PrefillWorker(ScriptedEngine(), mk("p0"), worker_id="p0")
    dec = DecodeWorker(ScriptedEngine(), mk("d0"), worker_id="d0")
    r = GenerateRequest(id="off", token_ids=[3, 4], max_new_tokens=3)
    b.push_request(r)
    got = _run_to_completion(b, [pre, dec], [r], timeout_s=10.0)
    assert got and got["off"].token_ids
    assert trace.recorder().req_ids() == []
    with trace.span("off", "phase"):
        pass
    assert trace.recorder().req_ids() == []
    # Heartbeat snapshots omit the trace blob entirely on the off path.
    assert all("trace" not in info for info in b.read_workers().values())


# -- producer endpoints ------------------------------------------------------


def _seed_recorder():
    trace.record("rq1", "enqueue", trace_id="rq1", queue="shared")
    with trace.span("rq1", "prefill", trace_id="rq1", worker="w0"):
        time.sleep(0.01)
    trace.record("rq1", "respond", ok=True)


def test_producer_trace_and_prometheus_endpoints():
    b = InProcBroker()
    srv = ProducerServer(b, host="127.0.0.1", port=0, timeout_s=5.0)
    srv.start()
    try:
        _seed_recorder()
        base = f"http://127.0.0.1:{srv.port}"
        tl = httpx.get(f"{base}/trace/rq1").json()
        assert tl["req_id"] == "rq1" and tl["trace_id"] == "rq1"
        assert [e["name"] for e in tl["events"]][-1] == "respond"
        assert "prefill" in tl["phases"]

        sl = httpx.get(f"{base}/trace/slowest?n=5").json()["slowest"]
        assert sl and sl[0]["req_id"] == "rq1"

        ch = httpx.get(f"{base}/trace/rq1?format=chrome").json()
        assert any(e.get("ph") == "X" for e in ch["traceEvents"])

        assert httpx.get(f"{base}/trace/nope").status_code == 404

        r = httpx.get(f"{base}/metrics")  # JSON stays the default
        assert r.headers["content-type"].startswith("application/json")
        assert "delivery" in r.json()

        r = httpx.get(f"{base}/metrics?format=prometheus")
        assert r.status_code == 200
        assert r.headers["content-type"].startswith("text/plain")
        assert "# TYPE" in r.text and "llmss_delivery_" in r.text
    finally:
        srv.stop()


def test_profile_endpoint_serializes_captures(tmp_path):
    from llmss_tpu.serve import producer as producer_mod

    b = InProcBroker()
    srv = ProducerServer(b, host="127.0.0.1", port=0)
    srv.start()
    try:
        base = f"http://127.0.0.1:{srv.port}"
        r = httpx.post(f"{base}/profile", json={
            "log_dir": str(tmp_path / "prof"), "duration_s": 0.3,
        })
        assert r.status_code == 202
        body = r.json()
        assert body["profiling"] is True and body["duration_s"] == 0.3
        # One capture per process: an overlapping request is refused.
        r2 = httpx.post(f"{base}/profile", json={"duration_s": 0.1})
        assert r2.status_code == 409
        deadline = time.monotonic() + 10.0
        # The slot (not the lock — that's only held for bookkeeping) is
        # what the capture thread frees on completion.
        while producer_mod._PROFILE_ACTIVE:
            assert time.monotonic() < deadline, "profile never finished"
            time.sleep(0.05)
    finally:
        srv.stop()


# -- tracing on adds zero steady-state recompiles ----------------------------

import jax  # noqa: E402

from llmss_tpu.engine import DecodeEngine, GenerationParams  # noqa: E402
from llmss_tpu.engine.scheduler import ContinuousBatcher  # noqa: E402
from llmss_tpu.models.common import DecoderConfig  # noqa: E402
from llmss_tpu.models.decoder import init_params  # noqa: E402
from llmss_tpu.parallel import MeshPlan, make_mesh  # noqa: E402


def test_tracing_adds_no_steady_state_recompiles(devices):
    """The instrumentation is host-side only: with tracing ON and traced
    req_ids flowing through the scheduler, a warmed batcher must hit the
    jit caches exactly as before — zero new compiles."""
    from llmss_tpu.analysis import CompileGuard

    cfg = DecoderConfig(
        model_type="llama", vocab_size=64, hidden_size=32, n_layers=2,
        n_heads=4, n_kv_heads=2, head_dim=8, intermediate_size=64,
        max_position_embeddings=64, activation="silu", norm="rmsnorm",
        norm_eps=1e-5, mlp="swiglu", positions="rotary", rope_style="half",
        rotary_dim=8, attn_bias=False, mlp_bias=False,
        tie_word_embeddings=False, dtype="float32",
    )
    mesh = make_mesh(MeshPlan(dp=2, tp=4))
    params = init_params(cfg, mesh, jax.random.key(0))
    engine = DecodeEngine(cfg, params, mesh, max_seq_len=64)
    batcher = ContinuousBatcher(
        engine, rows=2, chunk_steps=2, group_chunks=2,
    )
    batcher.prewarm()
    gen = GenerationParams(max_new_tokens=4, is_greedy=True)

    guard = CompileGuard.for_engine(engine)
    assert guard._fns, "engine exposes no jitted callables to guard"
    got = {}
    with guard.steady_state():
        for i, p in enumerate([[5, 9], [3, 14, 15]]):
            batcher.submit(
                p, gen, lambda t, i=i: got.__setitem__(i, t),
                req_id=f"g{i}",
            )
        batcher.run_until_idle()
    assert len(got) == 2
    names = {e["name"] for e in trace.recorder().events_for("g0")}
    assert {"sched_submit", "admit", "finish"} <= names
