"""Decode engine: greedy parity with HF generate, fused==streaming, overflow."""

import jax.numpy as jnp
import numpy as np
import pytest

from llmss_tpu.engine import DecodeEngine, GenerationParams
from llmss_tpu.models import config_from_hf
from llmss_tpu.models.registry import MODEL_REGISTRY
from llmss_tpu.parallel import MeshPlan, make_mesh
from llmss_tpu.weights import CheckpointShards, weight_files


@pytest.fixture(scope="module")
def tiny_gptj(tmp_path_factory):
    import torch
    import transformers as tr

    torch.manual_seed(7)
    cfg = tr.GPTJConfig(
        vocab_size=64, n_positions=64, n_embd=32, n_layer=2, n_head=4,
        rotary_dim=4,
    )
    model = tr.GPTJForCausalLM(cfg).eval()
    d = tmp_path_factory.mktemp("m") / "gptj"
    model.save_pretrained(d, safe_serialization=True)
    return d, model


@pytest.fixture(scope="module")
def engine(tiny_gptj, devices):
    d, _ = tiny_gptj
    from transformers import AutoConfig

    mesh = make_mesh(MeshPlan(dp=2, tp=4))
    cfg = config_from_hf(AutoConfig.from_pretrained(d), dtype="float32")
    ckpt = CheckpointShards(weight_files(str(d)), dtype=np.float32)
    params = MODEL_REGISTRY["gptj"].load_params(ckpt, cfg, mesh)
    return DecodeEngine(cfg, params, mesh, max_seq_len=64)


def test_greedy_matches_hf_generate(tiny_gptj, engine):
    _, hf_model = tiny_gptj
    import torch

    prompts = [[5, 9, 23, 40], [3, 14, 15, 9, 26, 5]]
    gen = GenerationParams(max_new_tokens=8, is_greedy=True)
    ours = engine.generate(prompts, gen)

    for p, o in zip(prompts, ours):
        with torch.no_grad():
            ref = hf_model.generate(
                torch.tensor([p]), max_new_tokens=8, do_sample=False,
            )[0][len(p):].tolist()
        assert o == ref, (o, ref)


def test_fused_matches_streaming(engine):
    prompts = [[5, 9, 23, 40], [3, 14, 15, 9, 26, 5]]
    gen = GenerationParams(max_new_tokens=8, is_greedy=True)
    assert engine.generate(prompts, gen) == engine.generate_fused(
        prompts, gen
    )


def test_sampling_reproducible_and_valid(engine):
    prompts = [[1, 2, 3]]
    gen = GenerationParams(
        max_new_tokens=6, is_greedy=False, temperature=0.8, top_k=10,
        top_p=0.9, seed=42,
    )
    a = engine.generate(prompts, gen)
    b = engine.generate(prompts, gen)
    assert a == b
    assert all(0 <= t < 64 for t in a[0])


def test_per_request_seed_honored(engine):
    """The protocol's `seed` is per request: every row of a batch draws from
    its own seed's stream (not gens[0]'s), so identical prompts with
    different seeds must be able to diverge, and a request's tokens must not
    depend on what shares the batch."""
    prompt = [1, 2, 3]
    mk = lambda seed: GenerationParams(
        max_new_tokens=8, is_greedy=False, temperature=1.5, seed=seed,
    )
    # One batch, same prompt, different per-request seeds.
    outs = engine.generate([prompt] * 4, [mk(0), mk(1), mk(2), mk(0)])
    assert outs[0] == outs[3]  # same seed → same stream
    assert len({tuple(o) for o in outs[:3]}) > 1  # some seed must diverge

    # Batch-mix independence: solo run with seed 1 == row 1 of the batch.
    solo = engine.generate([prompt], mk(1))
    assert solo[0] == outs[1]

    # ...including when another row's warpers route the batch through the
    # sorted-filter path: the warper-free row's realization must not change
    # (the filtered draw happens in token order, ops/sampling.py).
    warped = GenerationParams(
        max_new_tokens=8, is_greedy=False, temperature=0.7, top_k=5,
        top_p=0.8, seed=9,
    )
    mixed = engine.generate([prompt, prompt], [mk(1), warped])
    assert mixed[0] == solo[0]


def test_ring_buffer_overflow(tiny_gptj, devices):
    """Generation past max_seq_len slides the window (≙ SURVEY §2.11.2)
    instead of crashing or growing."""
    d, _ = tiny_gptj
    from transformers import AutoConfig

    mesh = make_mesh(MeshPlan(dp=2, tp=4))
    cfg = config_from_hf(AutoConfig.from_pretrained(d), dtype="float32")
    ckpt = CheckpointShards(weight_files(str(d)), dtype=np.float32)
    params = MODEL_REGISTRY["gptj"].load_params(ckpt, cfg, mesh)
    eng = DecodeEngine(cfg, params, mesh, max_seq_len=16)

    prompts = [[1, 2, 3, 4, 5, 6], [7, 8, 9, 10, 11, 12]]
    gen = GenerationParams(max_new_tokens=20, is_greedy=True)
    out = eng.generate(prompts, gen)
    assert all(len(o) == 20 for o in out)


def test_no_steady_state_recompiles(engine):
    """CompileGuard (llmss_tpu/analysis): once warmed, a repeat of the same
    workload must hit the jit caches — zero new compiles. This is the
    runtime twin of graftlint's static shape rules: canon_vec/canon_cache
    exist precisely so steady-state serving keeps one executable signature
    per phase."""
    from llmss_tpu.analysis import CompileGuard

    prompts = [[5, 9, 23, 40], [3, 14, 15, 9, 26, 5]]
    gen = GenerationParams(max_new_tokens=8, is_greedy=True)
    engine.generate(prompts, gen)  # warmup: compiles are expected here

    guard = CompileGuard.for_engine(engine)
    assert guard._fns, "engine exposes no jitted callables to guard"
    with guard.steady_state():
        engine.generate(prompts, gen)
        engine.generate(prompts, gen)


def test_no_steady_state_recompiles_grouped(engine):
    """CompileGuard over the GROUPED decode path: a warmed batcher running
    group_chunks>1 traffic — including the low-load single-chunk shape and
    mid-stream admissions — must never key a fresh compile. The grouped
    scheduler's whole point is fewer host round-trips; a silent mid-serve
    recompile would hand the savings straight back."""
    from llmss_tpu.analysis import CompileGuard
    from llmss_tpu.engine.scheduler import ContinuousBatcher

    batcher = ContinuousBatcher(
        engine, rows=4, chunk_steps=2, group_chunks=3,
    )
    batcher.prewarm()
    gen = GenerationParams(max_new_tokens=6, is_greedy=True)

    guard = CompileGuard.for_engine(engine)
    assert guard._fns, "engine exposes no jitted callables to guard"
    with guard.steady_state():
        got = {}
        for i, p in enumerate([[5, 9], [3, 14, 15], [7, 8, 9, 10]]):
            batcher.submit(p, gen, lambda t, i=i: got.__setitem__(i, t))
        batcher.step()
        batcher.submit([11, 12], gen, lambda t: got.__setitem__(9, t))
        batcher.run_until_idle()
        assert len(got) == 4
