"""Multi-process runtime: real jax.distributed rendezvous across processes.

Round-3 verdict: the ``initialize_runtime`` multi-process branch
(``parallel/mesh.py``, ≙ reference ``dist.py:65-73`` + the torchrun recipe
in ``poc-server/producer-consumer/README.md:24-37``) had never been
executed. This test launches two OS processes that rendezvous at a real
coordinator, build a TP mesh spanning both, and run prefill + decode steps
whose RowLinear psums and lm-head all-gather are genuine cross-process
collectives (tools/multiprocess_smoke.py is the launch recipe).
"""

import os
import re
import socket
import subprocess
import sys

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
SCRIPT = os.path.join(REPO, "tools", "multiprocess_smoke.py")


def _free_port() -> int:
    with socket.socket() as s:
        s.bind(("localhost", 0))
        return s.getsockname()[1]


def test_two_process_mesh_runs_engine_step():
    port = _free_port()
    env = {
        k: v for k, v in os.environ.items()
        # The workers set their own platform/device-count flags; inherited
        # pytest-session values would double-apply.
        if k not in ("XLA_FLAGS", "JAX_PLATFORMS")
    }
    env["JAX_PLATFORMS"] = "cpu"
    procs = [
        subprocess.Popen(
            [
                sys.executable, SCRIPT,
                "--process-id", str(pid),
                "--num-processes", "2",
                "--coordinator", f"localhost:{port}",
                "--local-devices", "2",
            ],
            stdout=subprocess.PIPE, stderr=subprocess.STDOUT,
            text=True, env=env, cwd=REPO,
        )
        for pid in (0, 1)
    ]
    outs = []
    try:
        for p in procs:
            out, _ = p.communicate(timeout=600)
            outs.append(out)
    finally:
        for p in procs:
            if p.poll() is None:
                p.kill()
                p.communicate()
    for pid, (p, out) in enumerate(zip(procs, outs)):
        assert p.returncode == 0, f"process {pid} failed:\n{out}"
        assert f"mpsmoke ok pid={pid} processes=2 devices=4" in out, out

    # Single-controller semantics: both processes computed the same global
    # program — their greedy tokens must be identical.
    toks = [re.search(r"toks=(\[[^\]]*\])", o).group(1) for o in outs]
    assert toks[0] == toks[1], toks
