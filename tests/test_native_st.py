"""Native safetensors gather (C++ pread pool) vs the safetensors package.

The data plane for weight loads is ``native/st_gather.cc`` (threaded strided
pread through ctypes); these tests pin its reads — full, dim-0/dim-1 shard,
2D rectangle, bf16, batched multi-tensor — against the safetensors package
on the same file, plus the memmap fallback used when no toolchain exists.
"""

import numpy as np
import pytest

import ml_dtypes
from safetensors.numpy import save_file

from llmss_tpu.weights import native_st
from llmss_tpu.weights.native_st import NativeSafetensors


@pytest.fixture(scope="module")
def ckpt(tmp_path_factory):
    rng = np.random.default_rng(0)
    data = {
        "w2d": rng.normal(size=(96, 56)).astype(np.float32),
        "b1d": rng.normal(size=(41,)).astype(np.float32),
        "wbf16": rng.normal(size=(32, 128)).astype(ml_dtypes.bfloat16),
        "t3d": rng.normal(size=(3, 8, 16)).astype(np.float32),
        "i32": rng.integers(0, 100, (24,)).astype(np.int32),
    }
    path = tmp_path_factory.mktemp("st") / "model.safetensors"
    save_file(data, str(path))
    return str(path), data


def test_header_parse(ckpt):
    path, data = ckpt
    st = NativeSafetensors(path)
    assert set(st.keys()) == set(data)
    for k, v in data.items():
        assert st.shape(k) == v.shape
        assert st.dtype(k) == v.dtype


@pytest.mark.parametrize(
    "name,index",
    [
        ("w2d", None),
        ("w2d", (slice(24, 72), slice(None))),  # dim-0 shard
        ("w2d", (slice(None), slice(14, 42))),  # dim-1 shard (strided)
        ("w2d", (slice(5, 91), slice(3, 9))),  # rectangle
        ("b1d", (slice(7, 30),)),
        ("wbf16", (slice(8, 24), slice(32, 96))),
        ("t3d", None),
        ("t3d", (slice(0, 2), slice(1, 5), slice(2, 9))),  # memmap path
        ("i32", None),
    ],
)
def test_reads_match(ckpt, name, index):
    path, data = ckpt
    st = NativeSafetensors(path)
    expect = data[name][index] if index is not None else data[name]
    np.testing.assert_array_equal(st.read(name, index), expect)


def test_read_many_batched(ckpt):
    path, data = ckpt
    st = NativeSafetensors(path)
    reqs = [
        ("w2d", (slice(0, 48), slice(None))),
        ("b1d", None),
        ("t3d", (slice(1, 3), slice(None), slice(4, 12))),  # mixed fallback
        ("wbf16", (slice(None), slice(0, 64))),
    ]
    outs = st.read_many(reqs)
    np.testing.assert_array_equal(outs[0], data["w2d"][:48])
    np.testing.assert_array_equal(outs[1], data["b1d"])
    np.testing.assert_array_equal(outs[2], data["t3d"][1:3, :, 4:12])
    np.testing.assert_array_equal(outs[3], data["wbf16"][:, :64])


def test_memmap_fallback_matches(ckpt, monkeypatch):
    path, data = ckpt
    monkeypatch.setattr(native_st, "_build_lib", lambda: None)
    st = NativeSafetensors(path)
    np.testing.assert_array_equal(
        st.read("w2d", (slice(None), slice(14, 42))), data["w2d"][:, 14:42]
    )
    np.testing.assert_array_equal(st.read("b1d"), data["b1d"])


def test_checkpoint_shards_use_native(ckpt):
    """CheckpointShards reads (incl. transpose + batched stacked loads)
    produce identical bytes through the native path."""
    from llmss_tpu.weights.loader import CheckpointShards

    path, data = ckpt
    ckpt_shards = CheckpointShards([path])
    np.testing.assert_array_equal(
        ckpt_shards.read_slice("w2d", (slice(10, 20), slice(0, 56))),
        data["w2d"][10:20],
    )
    np.testing.assert_array_equal(
        ckpt_shards.read_slice(
            "w2d", (slice(0, 56), slice(10, 20)), transpose=True
        ),
        data["w2d"].T[:, 10:20],
    )
    outs = ckpt_shards.read_slices(
        ["w2d", "w2d"], (slice(0, 8), slice(8, 16))
    )
    for out in outs:
        np.testing.assert_array_equal(out, data["w2d"][:8, 8:16])
