"""Fault injection: every accepted request gets exactly one terminal
response, even when workers are hard-killed mid-batch.

These tests run the real delivery stack (broker leases + Worker loop)
under ``serve.chaos``: ``HardKill`` escapes every containment layer the
way a SIGKILL would — the dying worker answers nothing and aborts
nothing — so only the broker's lease/redelivery machinery can keep the
at-least-once promise. ``ScriptedEngine`` makes every successful payload
exactly predictable, so the audit can also catch corruption.
"""

import time

import pytest

from llmss_tpu.serve.broker import InProcBroker, RedisBroker
from llmss_tpu.serve.chaos import (
    POISON_TOKEN, ChaosBroker, ChaosWorkerHost, FakeRedis, ScriptedEngine,
)
from llmss_tpu.serve.consumer import Worker
from llmss_tpu.serve.producer import ProducerServer
from llmss_tpu.serve.protocol import GenerateRequest
from llmss_tpu.sim.invariants import audit_exactly_once, collect_responses


# Collection and the exactly-once audit are the shared sim/serve helpers:
# the fleet simulator's invariant catalog and these wall-clock chaos tests
# must enforce the same contract, so they literally share the code.
_collect = collect_responses
_audit = audit_exactly_once


def _run_fleet(make_worker_broker, producer_broker, n_requests=24,
               n_workers=2, seed=0):
    """Kill-heavy chaos run: every request must still be answered once."""
    reqs = [
        GenerateRequest(
            token_ids=[i + 1], max_new_tokens=4,
            deadline_ts=time.time() + 60.0,
        )
        for i in range(n_requests)
    ]
    hosts = []
    for i in range(n_workers):
        chaos = ChaosBroker(
            make_worker_broker(i), seed=seed + i,
            kill_after_pop_prob=0.15, drop_response_prob=0.1,
        )

        def factory(chaos=chaos):
            return Worker(
                ScriptedEngine(), chaos, batch_size=2,
                poll_timeout_s=0.02, pad_batch=False,
            )

        hosts.append(ChaosWorkerHost(factory, respawn_delay_s=0.01))

    for r in reqs:
        producer_broker.push_request(r)
    for h in hosts:
        h.start()
    try:
        results = _collect(producer_broker, reqs, timeout_s=60.0)
    finally:
        for h in hosts:
            h.stop()

    assert not [h.error for h in hosts if h.error]
    successes = _audit(reqs, results)
    # The error-path responses are dead-letters from repeated kills —
    # legitimate terminal answers — but chaos at these rates must not
    # wipe out the run.
    assert successes >= n_requests // 2
    assert sum(h.kills for h in hosts) > 0, "chaos schedule never fired"
    return hosts


def test_chaos_inproc_every_request_answered_once():
    b = InProcBroker(lease_s=0.15, max_delivery_attempts=6)
    _run_fleet(lambda i: b, b)


def test_chaos_fakeredis_every_request_answered_once():
    """Same contract through the real RedisBroker code paths (per-worker
    lease keys, SCAN-based reaper, DLQ list) on FakeRedis."""
    server = FakeRedis()

    def mk(i):
        return RedisBroker(
            client=server, worker_id=f"w{i}", lease_s=0.15,
            max_delivery_attempts=6,
        )

    producer = RedisBroker(
        client=server, worker_id="producer", lease_s=0.15,
        max_delivery_attempts=6,
    )
    _run_fleet(mk, producer)


def test_poison_request_lands_in_dlq_fleet_stays_healthy():
    """A request that deterministically crashes whichever worker takes it
    must end up quarantined after max_delivery_attempts kills — with the
    fleet alive, the other requests served, and the poison visible on the
    admin surfaces (/dlq, /metrics) with /health still 200."""
    b = InProcBroker(lease_s=0.1, max_delivery_attempts=3)

    def factory():
        # batch_size=1 so the poison takes down only its own lease, and
        # kill_on_poison simulates the chip reset.
        return Worker(
            ScriptedEngine(kill_on_poison=True), b, batch_size=1,
            poll_timeout_s=0.02, pad_batch=False,
        )

    host = ChaosWorkerHost(factory, respawn_delay_s=0.01)
    poison = GenerateRequest(
        id="poison", token_ids=[POISON_TOKEN], max_new_tokens=4,
        deadline_ts=time.time() + 60.0,
    )
    normals = [
        GenerateRequest(
            id=f"n{i}", token_ids=[i + 1], max_new_tokens=4,
            deadline_ts=time.time() + 60.0,
        )
        for i in range(4)
    ]
    b.push_request(poison)
    for r in normals:
        b.push_request(r)

    srv = ProducerServer(b, host="127.0.0.1", port=0)
    srv.start()
    host.start()
    try:
        results = _collect(b, [poison] + normals, timeout_s=30.0)
    finally:
        host.stop()

    try:
        assert host.error is None
        # Each delivery attempt killed a worker; then quarantine.
        assert host.kills == 3
        assert host.spawns >= host.kills + 1  # fleet kept respawning
        presp = results["poison"]
        assert presp not in (None, "DUPLICATE")
        assert "dead-lettered after 3" in presp.error
        assert b.dlq_depth() == 1
        assert b.read_dlq()[0]["id"] == "poison"
        # Normal traffic survived the poison.
        for r in normals:
            got = results[r.id]
            assert got not in (None, "DUPLICATE") and not got.error
            assert got.token_ids == ScriptedEngine.expected_tokens(
                list(r.token_ids), r.max_new_tokens
            )
        # Admin surfaces agree and the producer still reports healthy.
        import http.client
        import json

        conn = http.client.HTTPConnection("127.0.0.1", srv.port, timeout=10)
        conn.request("GET", "/health")
        assert conn.getresponse().status == 200
        conn.request("GET", "/dlq")
        r = conn.getresponse()
        body = json.loads(r.read())
        assert r.status == 200 and body["depth"] == 1
        conn.close()
    finally:
        srv.stop()


def test_hardkill_escapes_worker_containment():
    """The per-batch ``except Exception`` containment must NOT catch a
    HardKill: a real SIGKILL would never produce error responses."""
    b = InProcBroker(lease_s=5.0)
    w = Worker(
        ScriptedEngine(kill_on_poison=True), b, batch_size=1,
        poll_timeout_s=0.02, pad_batch=False,
    )
    b.push_request(GenerateRequest(
        id="poison", token_ids=[POISON_TOKEN], max_new_tokens=2,
    ))
    from llmss_tpu.serve.chaos import HardKill

    with pytest.raises(HardKill):
        w.run_once()
    # No terminal response was emitted; the lease is still outstanding.
    assert b.wait_response("poison", timeout=0.05) is None
    assert b.delivery_stats()["inflight"] == 1
