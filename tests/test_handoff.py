"""KV handoff: wire format, broker channel, role workers, bit-identity.

The disaggregated prefill/decode subsystem (``serve/handoff.py`` +
``engine/scheduler.py`` prefill-only/adopt + the broker handoff channel)
ships on three claims, each pinned here:

- the wire format round-trips paged blocks bit-exactly (bf16 and
  int8+scales) and refuses corrupt payloads loudly;
- the handoff channel keeps the single-worker delivery contract —
  exactly one terminal response per request — across handoff lease
  expiry, un-adoptable payloads, failover, and a prefill replica
  hard-killed mid-handoff (the acceptance chaos case);
- a 1-prefill + 1-decode fleet emits token streams bit-identical to a
  unified worker on the same requests, on both ``InProcBroker`` and
  ``RedisBroker``-over-``FakeRedis``.
"""

import threading
import time

import numpy as np
import pytest

from llmss_tpu.serve.broker import InProcBroker, RedisBroker
from llmss_tpu.serve.chaos import (
    ChaosWorkerHost,
    FakeRedis,
    HardKill,
    ScriptedEngine,
)
from llmss_tpu.serve.handoff import (
    DecodeWorker,
    HandoffRecord,
    PrefillWorker,
    decode_blocks,
    encode_blocks,
    pick_decode_worker,
)
from llmss_tpu.serve.protocol import (
    STATE_READY,
    GenerateRequest,
    GenerateResponse,
)
from llmss_tpu.sim.invariants import audit_exactly_once, collect_responses

BROKER_KINDS = ("inproc", "fakeredis")


def make_brokers(kind, **kw):
    """(producer-side broker, make_worker_broker(worker_id)) pair — the
    same two deployment shapes tests/test_fleet.py exercises."""
    if kind == "inproc":
        b = InProcBroker(**kw)
        return b, (lambda wid: b)
    server = FakeRedis()

    def mk(wid):
        return RedisBroker(client=server, worker_id=wid, **kw)

    return mk("producer"), mk


# -- wire format ------------------------------------------------------------


def _blocks(nb=3, quantized=False, seed=0):
    """A synthetic export_blocks dict: [L, nb, bs, Hkv, D] segments
    (scales [L, nb, bs, Hkv]) in the exact dtypes the paged pool uses."""
    import ml_dtypes

    rng = np.random.default_rng(seed)
    shape = (2, nb, 16, 2, 8)
    if quantized:
        k = rng.integers(-128, 128, shape, dtype=np.int8)
        v = rng.integers(-128, 128, shape, dtype=np.int8)
        ks = rng.standard_normal(shape[:-1], dtype=np.float32)
        vs = rng.standard_normal(shape[:-1], dtype=np.float32)
    else:
        k = rng.standard_normal(shape, np.float32).astype(ml_dtypes.bfloat16)
        v = rng.standard_normal(shape, np.float32).astype(ml_dtypes.bfloat16)
        ks = vs = None
    return {"k": k, "v": v, "k_scale": ks, "v_scale": vs}


@pytest.mark.parametrize("quantized", (False, True))
def test_wire_roundtrip_bit_exact(quantized):
    blocks = _blocks(quantized=quantized)
    payload = encode_blocks(blocks, req_id="r1", n_tokens=40, block_size=16)
    out = decode_blocks(payload)
    assert out["req_id"] == "r1" and out["n_tokens"] == 40
    assert out["block_size"] == 16 and out["quantized"] is quantized
    for name in ("k", "v", "k_scale", "v_scale"):
        a, b = blocks[name], out[name]
        if a is None:
            assert b is None
            continue
        assert b.dtype == a.dtype and b.shape == a.shape
        assert b.tobytes() == a.tobytes()  # BIT-exact, not just close


def test_wire_roundtrip_partial_tail_block():
    # 18 tokens over block_size 16: 2 blocks, the second only 2 slots
    # live. The slot masking is the exporter's job — the wire just has to
    # carry n_tokens through so the adopter seeds positions correctly.
    blocks = _blocks(nb=2)
    payload = encode_blocks(blocks, req_id="t", n_tokens=18, block_size=16)
    out = decode_blocks(payload)
    assert out["n_tokens"] == 18
    assert out["k"].shape[1] == 2 and out["k"].tobytes() == blocks["k"].tobytes()


def test_wire_rejects_corruption():
    payload = encode_blocks(
        _blocks(), req_id="r", n_tokens=48, block_size=16,
    )
    cases = {
        "bad magic": b"XKVH" + payload[4:],
        "unknown version": payload.replace(
            b'"version": 1', b'"version": 9', 1,
        ),
        "truncated header": payload[:6],
        "truncated buffers": payload[:-3],
        "flipped buffer byte": (
            payload[:-1] + bytes([payload[-1] ^ 0x01])
        ),
        "trailing bytes": payload + b"\x00",
    }
    for name, data in cases.items():
        with pytest.raises(ValueError):
            decode_blocks(data)  # noqa: B017 — each case must reject
    decode_blocks(payload)  # the pristine payload still decodes


# -- decode-replica placement ----------------------------------------------


def test_pick_decode_worker_least_backlog():
    ws = {
        "p0": {"role": "prefill", "state": STATE_READY, "free_slots": 4},
        "d0": {"role": "decode", "state": STATE_READY,
               "inflight_rows": 2, "free_slots": 2},
        "d1": {"role": "decode", "state": STATE_READY,
               "inflight_rows": 0, "free_slots": 4},
        "d2": {"role": "decode", "state": "draining",
               "inflight_rows": 0, "free_slots": 8},
    }
    assert pick_decode_worker(ws) == "d1"
    # Routed handoff depth counts as backlog — d1 stops being best.
    assert pick_decode_worker(ws, {"d1": 5}) == "d0"
    # No ready decode replica -> None (caller uses the shared queue).
    assert pick_decode_worker({"p0": ws["p0"], "d2": ws["d2"]}) is None


# -- broker handoff channel -------------------------------------------------


def _req(i=0, **kw):
    kw.setdefault("deadline_ts", time.time() + 60.0)
    kw.setdefault("max_new_tokens", 4)
    return GenerateRequest(id=f"h{i}", token_ids=[1, 2, i + 3], **kw)


def _rec(req, payload=b"kv-payload"):
    return HandoffRecord(
        req=req, first_token=7, n_tokens=len(req.token_ids), payload=payload,
    )


@pytest.mark.parametrize("kind", BROKER_KINDS)
def test_handoff_settles_request_lease_then_acks_on_response(kind):
    b, mk = make_brokers(kind, lease_s=0.1, max_delivery_attempts=3)
    pb, db = mk("p0"), mk("d0")
    b.push_request(_req(0))
    leased = pb.pop_request(timeout=1.0, worker_id="p0")
    pb.push_handoff(_rec(leased, payload=b"x" * 32))
    # The handoff IS the prefill worker's ack: the request lease is
    # settled, so its expiry never redelivers.
    time.sleep(0.15)
    b.reap_expired()
    assert b.pop_request(timeout=0.01) is None
    st = b.delivery_stats()
    assert st["redelivered"] == 0
    assert st["handoffs"] == 1 and st["handoff_bytes"] == 32
    assert b.handoff_depth() == 1

    got = db.pop_handoff(timeout=1.0, worker_id="d0")
    assert got.req.id == "h0" and got.payload == b"x" * 32
    assert got.first_token == 7 and got.n_tokens == 3
    assert b.handoff_holders() == {"d0": 1}
    # push_response acks the handoff lease — no disposition ever runs.
    db.push_response(GenerateResponse(id="h0", token_ids=[7, 8]))
    assert b.handoff_holders() == {}
    time.sleep(0.15)
    b.reap_expired()
    resp = b.wait_response("h0", timeout=1.0)
    assert resp is not None and resp.token_ids == [7, 8]
    assert b.wait_response("h0", timeout=0.05) is None  # exactly one
    assert b.delivery_stats()["reprefills"] == 0


@pytest.mark.parametrize("kind", BROKER_KINDS)
def test_routed_handoff_targets_one_decode_worker(kind):
    b, mk = make_brokers(kind)
    b.push_handoff_to("d1", _rec(_req(0)))
    assert b.handoff_depths() == {"d1": 1}
    assert b.handoff_depth() == 1
    # Another decode worker never sees a routed record.
    assert mk("d0").pop_handoff(timeout=0.01, worker_id="d0") is None
    got = mk("d1").pop_handoff(timeout=0.5, worker_id="d1")
    assert got is not None and got.req.id == "h0"
    assert b.handoff_depths() == {}


@pytest.mark.parametrize("kind", BROKER_KINDS)
def test_handoff_lease_expiry_reprefills(kind):
    b, mk = make_brokers(kind, lease_s=0.08, max_delivery_attempts=5)
    b.push_handoff(_rec(_req(0)))
    assert mk("d0").pop_handoff(timeout=0.5, worker_id="d0") is not None
    time.sleep(0.15)
    b.reap_expired()
    # The decode replica is presumed dead; its adopted KV died with it —
    # the embedded request goes back to the SHARED queue for a fresh
    # prefill, counted as a re-prefill (not a redelivery).
    back = b.pop_request(timeout=0.5)
    assert back is not None and back.id == "h0"
    st = b.delivery_stats()
    assert st["reprefills"] == 1 and st["redelivered"] == 0
    assert b.handoff_holders() == {}


@pytest.mark.parametrize("kind", BROKER_KINDS)
def test_touch_handoffs_keeps_lease_alive(kind):
    b, mk = make_brokers(kind, lease_s=0.12)
    db = mk("d0")
    b.push_handoff(_rec(_req(0)))
    assert db.pop_handoff(timeout=0.5, worker_id="d0") is not None
    for _ in range(4):  # 4 * 0.06 = 2x the lease, renewed per "chunk"
        time.sleep(0.06)
        db.touch_handoffs(["h0"])
        b.reap_expired()
    assert b.handoff_holders() == {"d0": 1}  # never dispositioned
    db.push_response(GenerateResponse(id="h0", token_ids=[7]))
    assert b.delivery_stats()["reprefills"] == 0


@pytest.mark.parametrize("kind", BROKER_KINDS)
def test_fail_handoff_reprefills_then_dead_letters(kind):
    b, mk = make_brokers(kind, lease_s=5.0, max_delivery_attempts=2)
    pb, db = mk("p0"), mk("d0")
    b.push_request(_req(0))
    for attempt in (1, 2):
        req = pb.pop_request(timeout=1.0, worker_id="p0")
        assert req is not None and req.delivery_attempts == attempt
        pb.push_handoff(_rec(req))
        rec = db.pop_handoff(timeout=1.0, worker_id="d0")
        db.fail_handoff(rec, error="corrupt payload")
    # Attempt 1 re-prefilled; attempt 2 exhausted the budget.
    st = b.delivery_stats()
    assert st["reprefills"] == 1 and st["dead_lettered"] == 1
    assert b.dlq_depth() == 1
    resp = b.wait_response("h0", timeout=1.0)
    assert resp is not None and "dead-lettered" in resp.error
    assert b.wait_response("h0", timeout=0.05) is None  # exactly one
    assert b.pop_request(timeout=0.01) is None


@pytest.mark.parametrize("kind", BROKER_KINDS)
def test_handoff_deadline_sheds_terminally(kind):
    b, mk = make_brokers(kind, lease_s=0.05, max_delivery_attempts=5)
    b.push_handoff(_rec(_req(0, deadline_ts=time.time() + 0.1)))
    assert mk("d0").pop_handoff(timeout=0.5, worker_id="d0") is not None
    time.sleep(0.2)  # lease AND end-to-end deadline both pass
    b.reap_expired()
    resp = b.wait_response("h0", timeout=1.0)
    assert resp is not None and "deadline" in resp.error
    assert b.pop_request(timeout=0.01) is None  # shed, not re-prefilled
    st = b.delivery_stats()
    assert st["deadline_expired"] == 1 and st["reprefills"] == 0


@pytest.mark.parametrize("kind", BROKER_KINDS)
def test_failover_handoffs_splits_routed_from_leased(kind):
    b, mk = make_brokers(kind, lease_s=60.0)
    # One adopted (leased) record: the device state dies with d0.
    b.push_handoff(_rec(_req(0)))
    got = mk("d0").pop_handoff(timeout=0.5, worker_id="d0")
    assert got is not None and got.req.id == "h0"
    # Two routed-but-unleased records: payload intact, re-routable.
    b.push_handoff_to("d0", _rec(_req(1)))
    b.push_handoff_to("d0", _rec(_req(2)))
    assert b.handoff_depths() == {"d0": 2}
    assert b.handoff_holders() == {"d0": 1}

    moved = b.failover_handoffs("d0")
    assert sorted(m.req.id for m in moved) == ["h1", "h2"]
    back = b.pop_request(timeout=0.5)
    assert back is not None and back.id == "h0"  # re-prefill
    assert b.delivery_stats()["reprefills"] == 1
    assert b.handoff_depths() == {} and b.handoff_holders() == {}


# -- role workers over ScriptedEngine ---------------------------------------


@pytest.mark.parametrize("kind", BROKER_KINDS)
def test_role_workers_end_to_end(kind):
    b, mk = make_brokers(kind, lease_s=2.0)
    pre = PrefillWorker(ScriptedEngine(), mk("p0"), worker_id="p0")
    dec = DecodeWorker(ScriptedEngine(), mk("d0"), worker_id="d0")
    reqs = [
        GenerateRequest(
            id=f"r{i}", token_ids=[10 + i, 20 + i], max_new_tokens=5,
        )
        for i in range(4)
    ] + [GenerateRequest(id="s", token_ids=[7], max_new_tokens=1)]
    for r in reqs:
        b.push_request(r)
    got = {}
    deadline = time.monotonic() + 20
    while len(got) < len(reqs) and time.monotonic() < deadline:
        pre.run_once()
        dec.run_once()
        for r in reqs:
            if r.id not in got:
                resp = b.wait_response(r.id, timeout=0.01)
                if resp is not None:
                    got[r.id] = resp
    assert len(got) == len(reqs)
    for r in reqs:
        assert got[r.id].error is None, (r.id, got[r.id].error)
        assert got[r.id].token_ids == ScriptedEngine.expected_tokens(
            list(r.token_ids), r.max_new_tokens,
        )
    ws = b.read_workers()
    assert ws["p0"]["role"] == "prefill" and ws["d0"]["role"] == "decode"
    st = b.delivery_stats()
    # The max_new=1 request answered locally on the prefill replica.
    assert st["handoffs"] == 4 and st["reprefills"] == 0


@pytest.mark.parametrize("kind", BROKER_KINDS)
def test_chaos_kill_prefill_mid_handoff_exactly_one_terminal(kind):
    """The acceptance chaos case: the prefill replica hard-dies AFTER
    exporting but BEFORE push_handoff. The request lease is still open,
    so at-least-once redelivery re-prefills it on the respawned replica —
    zero requests lost, zero double-answered."""
    b, mk = make_brokers(kind, lease_s=0.25, max_delivery_attempts=6)
    kills_left = [2]
    klock = threading.Lock()

    def on_exported(rec):
        with klock:
            if kills_left[0] > 0:
                kills_left[0] -= 1
                raise HardKill(f"killed after exporting {rec.req.id}")

    pre = ChaosWorkerHost(
        lambda: PrefillWorker(
            ScriptedEngine(), mk("p0"), worker_id="p0",
            on_exported=on_exported, poll_timeout_s=0.02,
        ),
        respawn_delay_s=0.02,
    )
    dec = ChaosWorkerHost(
        lambda: DecodeWorker(
            ScriptedEngine(), mk("d0"), worker_id="d0",
            poll_timeout_s=0.02,
        ),
        respawn_delay_s=0.02,
    )
    reqs = [
        GenerateRequest(
            id=f"r{i}", token_ids=[i % 50 + 1, i % 7 + 1],
            max_new_tokens=4, deadline_ts=time.time() + 30.0,
        )
        for i in range(10)
    ]
    pre.start()
    dec.start()
    try:
        for r in reqs:
            b.push_request(r)
        # Shared sim/serve audit: exactly one terminal response per
        # request, clean scripted payloads, zero errors (== len(reqs)).
        results = collect_responses(b, reqs, timeout_s=20.0)
        assert audit_exactly_once(reqs, results) == len(reqs)
    finally:
        pre.stop()
        dec.stop()
    assert pre.error is None and dec.error is None
    assert pre.kills == 2 and pre.spawns >= 3
    # The two killed exports came back via request-lease redelivery.
    assert b.delivery_stats()["redelivered"] >= 2


# -- real-engine bit-identity ----------------------------------------------


import jax  # noqa: E402

from llmss_tpu.engine import DecodeEngine, GenerationParams  # noqa: E402
from llmss_tpu.engine.scheduler import ContinuousBatcher  # noqa: E402
from llmss_tpu.models.common import DecoderConfig  # noqa: E402
from llmss_tpu.models.decoder import init_params  # noqa: E402
from llmss_tpu.parallel import MeshPlan, make_mesh  # noqa: E402
from llmss_tpu.serve.consumer import ContinuousWorker  # noqa: E402


def _cfg():
    return DecoderConfig(
        model_type="llama", vocab_size=64, hidden_size=32, n_layers=2,
        n_heads=4, n_kv_heads=2, head_dim=8, intermediate_size=64,
        max_position_embeddings=64, activation="silu", norm="rmsnorm",
        norm_eps=1e-5, mlp="swiglu", positions="rotary", rope_style="half",
        rotary_dim=8, attn_bias=False, mlp_bias=False,
        tie_word_embeddings=False, dtype="float32",
    )


@pytest.fixture(scope="module")
def setup():
    mesh = make_mesh(MeshPlan(dp=2, tp=4))
    cfg = _cfg()
    params = init_params(cfg, mesh, jax.random.key(0))
    return cfg, mesh, params


@pytest.fixture(scope="module")
def paged_engine(setup):
    cfg, mesh, params = setup
    return DecodeEngine(
        cfg, params, mesh, max_seq_len=64, kv_layout="paged", block_size=16,
    )


@pytest.fixture(scope="module")
def dense_engine(setup):
    cfg, mesh, params = setup
    return DecodeEngine(cfg, params, mesh, max_seq_len=64)


# Greedy, seed-stateful sampled, partial tail block (18 > block_size),
# and a max_new=1 row the prefill replica must answer locally.
_PROMPTS = [[1, 2, 3, 4, 5], list(range(1, 19)), [7, 8, 9]]
_GENS = [
    GenerationParams(max_new_tokens=8, is_greedy=True),
    GenerationParams(max_new_tokens=6, temperature=0.8, top_k=20, seed=3),
    GenerationParams(max_new_tokens=1, is_greedy=True),
]


def _unified_reference(engine):
    uni = ContinuousBatcher(engine, rows=2)
    expected = {}
    for i, (p, g) in enumerate(zip(_PROMPTS, _GENS)):
        uni.submit(p, g, lambda toks, i=i: expected.__setitem__(i, toks))
    uni.run_until_idle()
    return expected


def _export_adopt_roundtrip(engine):
    """prefill-only export -> wire round-trip -> adopt on a second
    batcher; returns {index: tokens} merged with locally answered rows."""
    pre = ContinuousBatcher(engine, rows=2, prefill_only=True)
    exports, results = {}, {}
    pre.export_cb = lambda rid, first, n, blocks: exports.__setitem__(
        rid, (first, n, blocks),
    )
    for i, (p, g) in enumerate(zip(_PROMPTS, _GENS)):
        pre.submit(
            p, g, lambda toks, i=i: results.__setitem__(i, toks),
            req_id=str(i),
        )
    pre.run_until_idle()
    assert pre.allocator.blocks_in_use == 0  # exported rows fully released

    dec = ContinuousBatcher(engine, rows=2)
    for rid, (first, n, blocks) in exports.items():
        payload = encode_blocks(
            blocks, req_id=rid, n_tokens=n, block_size=engine.block_size,
        )
        d = decode_blocks(payload)
        ok = dec.adopt(
            rid, first, n,
            {k: d[k] for k in ("k", "v", "k_scale", "v_scale")},
            _GENS[int(rid)],
            lambda toks, rid=rid: results.__setitem__(int(rid), toks),
        )
        assert ok, rid
    dec.run_until_idle()
    return results


def test_export_adopt_bit_identical(paged_engine):
    expected = _unified_reference(paged_engine)
    results = _export_adopt_roundtrip(paged_engine)
    for i in range(len(_PROMPTS)):
        assert results[i] == expected[i], (i, results[i], expected[i])


def test_export_adopt_bit_identical_int8(setup):
    cfg, mesh, params = setup
    engine = DecodeEngine(
        cfg, params, mesh, max_seq_len=64, kv_layout="paged",
        block_size=16, kv_dtype="int8",
    )
    expected = _unified_reference(engine)
    results = _export_adopt_roundtrip(engine)
    for i in range(len(_PROMPTS)):
        assert results[i] == expected[i], (i, results[i], expected[i])


def test_cow_refcounts_preserved_on_export(paged_engine, dense_engine):
    """Exporting rows whose prompt rides a COW-shared prefix must not
    disturb the prefix registry: export is a pure pool read, row release
    decrefs only the rows' own references, and the adopted rows still
    emit the dense engine's exact tokens."""
    pfx_tokens = list(range(1, 21))  # 1 full block (bs=16) + tail
    pfx = paged_engine.build_prefix(pfx_tokens)
    gen = GenerationParams(max_new_tokens=5, is_greedy=True)
    full = [pfx_tokens + [30 + i] for i in range(2)]
    expected = [dense_engine.generate([p], gen)[0] for p in full]

    pre = ContinuousBatcher(paged_engine, rows=2, prefill_only=True)
    exports = {}
    pre.export_cb = lambda rid, first, n, blocks: exports.__setitem__(
        rid, (first, n, blocks),
    )
    for i, p in enumerate(full):
        pre.submit(p, gen, lambda t: None, req_id=str(i), prefix=pfx)
    pre.run_until_idle()
    # Only the prefix registry's shared block remains resident — the
    # exported rows' owned blocks are freed, the shared one survives.
    assert pre.allocator.blocks_in_use == 1
    assert len(exports) == 2

    dec = ContinuousBatcher(paged_engine, rows=2)
    results = {}
    for rid, (first, n, blocks) in exports.items():
        assert n == len(pfx_tokens) + 1
        payload = encode_blocks(
            blocks, req_id=rid, n_tokens=n, block_size=16,
        )
        d = decode_blocks(payload)
        ok = dec.adopt(
            rid, first, n,
            {k: d[k] for k in ("k", "v", "k_scale", "v_scale")},
            gen, lambda t, rid=rid: results.__setitem__(int(rid), t),
        )
        assert ok, rid
    dec.run_until_idle()
    for i, e in enumerate(expected):
        assert results[i] == e, (i, results[i], e)


@pytest.mark.parametrize("kind", BROKER_KINDS)
def test_two_replica_fleet_bit_identical_to_unified(kind, paged_engine):
    """The acceptance criterion: 1 prefill + 1 decode ContinuousWorker
    replicas produce byte-for-byte the unified worker's responses."""
    reqs = [
        GenerateRequest(
            id="a", token_ids=[1, 2, 3, 4, 5], max_new_tokens=8,
            is_greedy=True,
        ),
        GenerateRequest(
            id="b", token_ids=list(range(1, 19)), max_new_tokens=6,
            temperature=0.8, top_k=20, seed=3, is_greedy=False,
        ),
        GenerateRequest(
            id="c", token_ids=[7, 8, 9], max_new_tokens=1, is_greedy=True,
        ),
    ]

    def collect(broker, workers):
        got = {}
        deadline = time.monotonic() + 60
        while len(got) < len(reqs) and time.monotonic() < deadline:
            for w in workers:
                w.run_once()
            for r in reqs:
                if r.id not in got:
                    resp = broker.wait_response(r.id, timeout=0.01)
                    if resp is not None:
                        got[r.id] = resp
        assert len(got) == len(reqs), sorted(got)
        for r in reqs:
            assert got[r.id].error is None, (r.id, got[r.id].error)
        return {rid: resp.token_ids for rid, resp in got.items()}

    b1, mk1 = make_brokers(kind)
    uni = ContinuousWorker(
        paged_engine, mk1("u0"), rows=2, worker_id="u0",
    )
    for r in reqs:
        b1.push_request(r)
    expected = collect(b1, [uni])

    b2, mk2 = make_brokers(kind)
    pre = ContinuousWorker(
        paged_engine, mk2("p0"), rows=2, worker_id="p0", role="prefill",
    )
    dec = ContinuousWorker(
        paged_engine, mk2("d0"), rows=2, worker_id="d0", role="decode",
    )
    for r in reqs:
        b2.push_request(r)
    got = collect(b2, [pre, dec])
    assert got == expected

    st = b2.delivery_stats()
    # "c" (max_new=1) answers on the prefill replica — 2 handoffs, all
    # settled (nothing in flight, nothing re-prefilled).
    assert st["handoffs"] == 2 and st["reprefills"] == 0
    assert st["handoff_inflight"] == 0 and st["handoff_depth"] == 0
    ws = b2.read_workers()
    assert ws["p0"]["role"] == "prefill" and ws["d0"]["role"] == "decode"
