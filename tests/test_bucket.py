"""Bucketed decode cache reads: decode HBM traffic follows live context.

The decode step reads only ring slots ``[0, t_bucket)`` when the engine can
prove no row has (or will) wrap past the bucket — the throughput lever that
makes a generously provisioned ring free (PROFILE.md). These tests pin the
semantics: bucketed and full-ring decode produce *bitwise identical* logits
(masked slots contribute exact zeros to every reduction), the bucket policy
refuses wrapped rows, and the whole serving envelope stays single-compile.
"""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from llmss_tpu.engine import DecodeEngine, GenerationParams
from llmss_tpu.models.common import DecoderConfig
from llmss_tpu.models.decoder import forward, init_params
from llmss_tpu.parallel import MeshPlan, make_mesh


def _cfg(**kw):
    base = dict(
        model_type="llama", vocab_size=64, hidden_size=32, n_layers=2,
        n_heads=4, n_kv_heads=4, head_dim=8, intermediate_size=64,
        max_position_embeddings=64, activation="silu", norm="rmsnorm",
        norm_eps=1e-5, mlp="swiglu", positions="rotary", rope_style="half",
        rotary_dim=8, attn_bias=False, mlp_bias=False,
        tie_word_embeddings=False, dtype="float32",
    )
    base.update(kw)
    return DecoderConfig(**base)


@pytest.fixture(scope="module")
def setup(devices):
    mesh = make_mesh(MeshPlan(dp=2, tp=4))
    cfg = _cfg()
    params = init_params(cfg, mesh, jax.random.key(0))
    return cfg, params, mesh


def test_ladder_and_policy(setup):
    cfg, params, mesh = setup
    eng = DecodeEngine(cfg, params, mesh, max_seq_len=64)
    assert eng.bucket_ladder() == [32]
    assert eng.decode_bucket(10) == 32
    assert eng.decode_bucket(32) == 32
    assert eng.decode_bucket(33) is None  # no entry covers it -> full ring
    assert eng.decode_bucket(64) is None
    assert eng.decode_bucket(65) is None  # wrapped rows: full-ring semantics


def test_buckets_env_disable(setup, monkeypatch):
    cfg, params, mesh = setup
    monkeypatch.setenv("LLMSS_BUCKETS", "0")
    eng = DecodeEngine(cfg, params, mesh, max_seq_len=64)
    assert eng.bucket_ladder() == []
    assert eng.decode_bucket(4) is None


def test_bucketed_decode_bitwise_logit_parity(setup):
    """A bucketed decode step must equal the full-ring step: the excluded
    slots contribute exp(-inf)=0 terms to every reduction. (Mathematically
    identical; tolerance only for XLA re-tiling reductions per shape —
    observed diffs are ~1e-10 on fp32 logits.)"""
    cfg, params, mesh = setup
    eng = DecodeEngine(cfg, params, mesh, max_seq_len=64)
    rng = np.random.default_rng(1)
    prompts = [rng.integers(1, 64, 9).tolist() for _ in range(4)]
    ids, lens = eng._pad_prompts(prompts)
    sa = eng._sample_args(GenerationParams(), 4)

    def one_step(t_bucket):
        cache = eng.new_cache(4)
        tok, _, cache = eng._prefill(
            eng.params, jnp.asarray(ids), cache, jnp.asarray(lens), sa,
        )
        _, logits, cache = eng._decode(
            eng.params, tok, cache, jnp.asarray(lens), sa, t_bucket=t_bucket,
        )
        return np.asarray(logits), cache

    full, cache_full = one_step(None)
    bucketed, cache_b = one_step(32)
    np.testing.assert_allclose(full, bucketed, rtol=0, atol=1e-6)
    # The write path is untouched: full buffers updated identically.
    np.testing.assert_allclose(
        np.asarray(cache_full.k), np.asarray(cache_b.k), rtol=0, atol=1e-6
    )
    np.testing.assert_array_equal(
        np.asarray(cache_full.positions), np.asarray(cache_b.positions)
    )


def test_bucketed_generate_token_parity(setup, monkeypatch):
    cfg, params, mesh = setup
    eng_b = DecodeEngine(cfg, params, mesh, max_seq_len=64)
    monkeypatch.setenv("LLMSS_BUCKETS", "0")
    eng_f = DecodeEngine(cfg, params, mesh, max_seq_len=64)
    assert eng_b._ladder and not eng_f._ladder
    prompts = [[5, 9, 23, 40], [3, 14, 15, 9, 26, 5], [7], [2, 4]]
    for gen in (
        GenerationParams(max_new_tokens=20, is_greedy=True),
        GenerationParams(
            max_new_tokens=20, is_greedy=False, temperature=0.9, top_k=8,
            top_p=0.9, seed=3,
        ),
    ):
        a = eng_b.generate(prompts, gen, chunk_steps=4)
        b = eng_f.generate(prompts, gen, chunk_steps=4)
        assert a == b
        assert eng_b.generate_fused(prompts, gen) == b


def test_generate_crossing_bucket_boundary_and_wrap(setup):
    """Tokens must be identical as pos crosses the 32-slot bucket boundary
    (bucket -> full-ring switch) and then the ring wrap itself."""
    cfg, params, mesh = setup
    eng = DecodeEngine(cfg, params, mesh, max_seq_len=32)
    prompts = [[5, 9, 23, 40]]
    gen = GenerationParams(max_new_tokens=40, is_greedy=True)  # wraps at 32
    out_chunked = eng.generate(prompts, gen, chunk_steps=4)
    out_single = eng.generate(prompts, gen)
    assert out_chunked == out_single


def test_worker_prewarm_compiles_each_executable_once(setup):
    """Worker-path prewarm covers the full envelope with ONE compile per
    executable signature: generate()/generate_fused() carry canon-resharded
    state, so no steady-state call may key a fresh compile (the round-3
    double-compile workaround is retired)."""
    cfg, params, mesh = setup
    eng = DecodeEngine(cfg, params, mesh, max_seq_len=64)
    n = eng.prewarm(4, chunk_steps=4)
    # prefill buckets (16, 32, 64) + decode x (None, 32) + chunk x (None, 32)
    assert n == 3 + 2 + 2
    sizes = {
        "prefill": eng._prefill._cache_size(),
        "decode": eng._decode._cache_size(),
        "decode_group": eng._decode_group._cache_size(),
    }
    prompts = [[5, 9, 23, 40], [3, 14, 15, 9, 26, 5], [7], [2, 4]]
    gen = GenerationParams(max_new_tokens=30, is_greedy=True)
    eng.generate(prompts, gen, chunk_steps=4)
    eng.generate(prompts, gen)  # single-step path
    # fused with n_steps inside the prewarmed chunk envelope (a fused call
    # with an arbitrary max_new compiles its own n_steps by design)
    eng.generate_fused(prompts, GenerationParams(
        max_new_tokens=5, is_greedy=True,
    ))
    assert eng._prefill._cache_size() == sizes["prefill"]
    assert eng._decode._cache_size() == sizes["decode"]
    assert eng._decode_group._cache_size() == sizes["decode_group"]


def test_submit_rejects_ring_overflow(setup):
    from llmss_tpu.engine.scheduler import ContinuousBatcher

    cfg, params, mesh = setup
    eng = DecodeEngine(cfg, params, mesh, max_seq_len=32)
    b = ContinuousBatcher(eng, rows=2, chunk_steps=2)
    with pytest.raises(ValueError, match="max_seq_len"):
        b.submit([1] * 20, GenerationParams(max_new_tokens=20), lambda t: None)
    # At exactly the ring size it must be accepted.
    got = []
    b.submit(
        [1] * 20, GenerationParams(max_new_tokens=12, is_greedy=True),
        lambda t: got.append(t),
    )
    b.run_until_idle()
    assert len(got) == 1 and len(got[0]) == 12
