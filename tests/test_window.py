"""Sliding-window attention equivalence across every implementation path.

ADVICE r1 (medium): ``DecoderConfig.sliding_window`` must actually constrain
attention in all four implementations — XLA mask fallback, Pallas flash
kernel, sequence-parallel ring/LSE-merge, and the deferred-write fresh-KV
decode path — and at the model level (a windowed model must decode the same
tokens streaming as it does re-prefilling the full prefix each step).
"""

import numpy as np
import pytest

import jax
import jax.numpy as jnp
from jax.sharding import PartitionSpec as P

import importlib

attn_mod = importlib.import_module("llmss_tpu.ops.attention")
from llmss_tpu.ops.attention import (
    attention,
    dispatch_attention,
    fresh_kv_decode_attention,
    make_causal_mask,
)
from llmss_tpu.parallel import MeshPlan, make_mesh

W = 8  # window width under test


def _rand(rng, *shape):
    return jnp.asarray(rng.normal(size=shape), jnp.float32)


def _ref(q, k, v, q_pos, kv_pos):
    return attention(
        q, k, v, make_causal_mask(q_pos, kv_pos, kv_pos >= 0, window=W)
    )


def _case(rng, B, S, T, Hq, Hkv, D):
    q = _rand(rng, B, S, Hq, D)
    k, v = _rand(rng, B, T, Hkv, D), _rand(rng, B, T, Hkv, D)
    kv_pos = jnp.asarray(np.broadcast_to(np.arange(T), (B, T)), np.int32)
    q_pos = jnp.asarray(
        np.broadcast_to(np.arange(T - S, T), (B, S)), np.int32
    )
    return q, k, v, q_pos, kv_pos


def test_window_xla_fallback_applies_window():
    """dispatch_attention folds ``window`` into the mask on the XLA path —
    the caller's mask carries only causality/validity (ADVICE r1 low)."""
    rng = np.random.default_rng(0)
    q, k, v, q_pos, kv_pos = _case(rng, 2, 16, 64, 4, 4, 16)
    plain_mask = make_causal_mask(q_pos, kv_pos, kv_pos >= 0)  # no window
    out = dispatch_attention(
        q, k, v, mask=plain_mask, q_positions=q_pos, kv_positions=kv_pos,
        window=W, mesh=None,
    )
    ref = _ref(q, k, v, q_pos, kv_pos)
    np.testing.assert_allclose(np.asarray(out), np.asarray(ref), atol=1e-5)
    # And the window genuinely bites: T=64 history with W=8 differs from
    # full causal.
    full = attention(q, k, v, plain_mask)
    assert not np.allclose(np.asarray(out), np.asarray(full), atol=1e-3)


def test_window_pallas_parity():
    from llmss_tpu.ops.pallas_attention import flash_attention

    rng = np.random.default_rng(1)
    q, k, v, q_pos, kv_pos = _case(rng, 2, 32, 128, 8, 2, 32)
    ref = _ref(q, k, v, q_pos, kv_pos)
    out = flash_attention(q, k, v, q_pos, kv_pos, window=W, interpret=True)
    np.testing.assert_allclose(np.asarray(out), np.asarray(ref), atol=2e-2)


def test_window_ring_and_lse_merge_parity(devices):
    mesh = make_mesh(MeshPlan(dp=1, sp=4, tp=2))
    rng = np.random.default_rng(2)

    # Prefill-shaped (S == T, divisible by sp) → ring path.
    q, k, v, q_pos, kv_pos = _case(rng, 2, 32, 32, 8, 4, 16)
    out = dispatch_attention(
        q, k, v, mask=None, q_positions=q_pos, kv_positions=kv_pos,
        window=W, mesh=mesh,
    )
    ref = _ref(q, k, v, q_pos, kv_pos)
    np.testing.assert_allclose(np.asarray(out), np.asarray(ref), atol=1e-5)

    # Decode-shaped (S == 1) → split-KV LSE-merge path.
    q1, k1, v1, _, kv_pos1 = _case(rng, 2, 1, 32, 8, 4, 16)
    q_pos1 = jnp.full((2, 1), 31, jnp.int32)
    out1 = dispatch_attention(
        q1, k1, v1, mask=None, q_positions=q_pos1, kv_positions=kv_pos1,
        window=W, mesh=mesh,
    )
    ref1 = _ref(q1, k1, v1, q_pos1, kv_pos1)
    np.testing.assert_allclose(np.asarray(out1), np.asarray(ref1), atol=1e-5)


def test_window_fresh_kv_decode_parity():
    """Deferred-write decode: stale cache + fresh token under a window must
    equal attention over the written cache with the same window."""
    rng = np.random.default_rng(3)
    B, T, Hq, Hkv, D = 2, 32, 4, 2, 16
    cur = 20  # decoding position `cur`; slots 0..cur-1 hold the history
    q = _rand(rng, B, 1, Hq, D)
    k_c, v_c = _rand(rng, B, T, Hkv, D), _rand(rng, B, T, Hkv, D)
    k_n, v_n = _rand(rng, B, 1, Hkv, D), _rand(rng, B, 1, Hkv, D)
    kv_pos_old = np.full((B, T), -1, np.int32)
    kv_pos_old[:, :cur] = np.arange(cur)
    kv_pos_old = jnp.asarray(kv_pos_old)
    q_pos = jnp.full((B, 1), cur, jnp.int32)
    slots = jnp.full((B, 1), cur, jnp.int32)

    out = fresh_kv_decode_attention(
        q, k_c, v_c, k_n, v_n, q_pos, kv_pos_old, slots, window=W,
    )

    # Reference: write the fresh KV, then windowed attention over the cache.
    b = jnp.arange(B)[:, None]
    k_full = k_c.at[b, slots].set(k_n)
    v_full = v_c.at[b, slots].set(v_n)
    kv_pos_new = kv_pos_old.at[b, slots].set(q_pos)
    ref = _ref(q, k_full, v_full, q_pos, kv_pos_new)
    np.testing.assert_allclose(np.asarray(out), np.asarray(ref), atol=1e-5)


def test_window_model_streaming_matches_reprefill(devices):
    """Model level: with cfg.sliding_window set, streaming decode (fresh-KV
    deferred-write path) must emit the same greedy tokens as re-prefilling
    the growing prefix every step (mask path)."""
    from llmss_tpu.engine import DecodeEngine, GenerationParams
    from llmss_tpu.models.common import DecoderConfig
    from llmss_tpu.models.decoder import init_params

    mesh = make_mesh(MeshPlan(dp=1, sp=1, tp=8))
    cfg = DecoderConfig(
        model_type="llama", vocab_size=128, hidden_size=64, n_layers=2,
        n_heads=8, n_kv_heads=4, head_dim=8, intermediate_size=192,
        max_position_embeddings=64, activation="silu", norm="rmsnorm",
        norm_eps=1e-5, mlp="swiglu", positions="rotary", rope_style="half",
        rotary_dim=8, attn_bias=False, mlp_bias=False,
        tie_word_embeddings=False, dtype="float32", sliding_window=4,
    )
    params = init_params(cfg, mesh, jax.random.key(0))
    engine = DecodeEngine(cfg, params, mesh, max_seq_len=32)

    prompt = [3, 17, 99, 54, 23, 8]
    n_new = 10
    gen = GenerationParams(max_new_tokens=n_new, is_greedy=True)
    streamed = engine.generate([prompt], gen)[0]

    # Re-prefill the full prefix each step; greedy argmax must agree.
    prefix = list(prompt)
    for t in streamed:
        cache = engine.new_cache(1)
        ids, lens = engine._pad_prompts([prefix])
        sa = engine._sample_args(gen, 1)
        tok, _, _ = engine._prefill(
            engine.params, jnp.asarray(ids), cache, jnp.asarray(lens), sa,
        )
        assert int(np.asarray(tok)[0]) == t, (prefix, streamed)
        prefix.append(t)

    # The window genuinely bites: with the window removed, prefill logits
    # over the same (longer-than-window) prefix must change numerically.
    # (Greedy argmax can coincide on a random-init model; logits can't.)
    cfg_full = DecoderConfig(**{
        **{f: getattr(cfg, f) for f in cfg.__dataclass_fields__},
        "sliding_window": None,
    })
    engine_full = DecodeEngine(cfg_full, params, mesh, max_seq_len=32)
    ids, lens = engine._pad_prompts([prefix])
    sa = engine._sample_args(gen, 1)
    _, logits_w, _ = engine._prefill(
        engine.params, jnp.asarray(ids), engine.new_cache(1),
        jnp.asarray(lens), sa,
    )
    _, logits_f, _ = engine_full._prefill(
        engine_full.params, jnp.asarray(ids), engine_full.new_cache(1),
        jnp.asarray(lens), sa,
    )
    assert not np.allclose(
        np.asarray(logits_w), np.asarray(logits_f), atol=1e-4
    )
