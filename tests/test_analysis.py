"""graftlint: per-rule violation fixtures, suppression/baseline mechanics,
lock-discipline detection, and the repo-lints-clean gate."""

import json
import subprocess
import sys
from pathlib import Path

import pytest

from llmss_tpu.analysis.cli import RULES, run

REPO_ROOT = Path(__file__).resolve().parents[1]

# One self-contained violating snippet per rule (the fixture set the CI
# gate's exit-nonzero acceptance criterion runs against).
VIOLATIONS = {
    "jit-host-sync": """
import jax
import numpy as np

def _step_impl(params, x):
    return float(x)

step = jax.jit(_step_impl)
""",
    "jit-if-on-tracer": """
import jax

def _step_impl(params, x):
    if x > 0:
        return x
    return -x

step = jax.jit(_step_impl)
""",
    "host-sync-in-loop": """
import jax
import numpy as np

step = jax.jit(lambda x: x)

def drive(xs):
    out = []
    for x in xs:
        t = step(x)
        out.append(np.asarray(t))
    return out
""",
    "jit-in-loop": """
import jax

def build(fns):
    for f in fns:
        g = jax.jit(f)
    return g
""",
    "jit-dynamic-static-args": """
import jax

AXES = (0, 1)

def build(f):
    return jax.jit(f, static_argnums=AXES)
""",
    "jit-missing-donate": """
import jax

def _decode_impl(params, tok, cache):
    return tok, cache

decode = jax.jit(_decode_impl)
""",
    "wall-clock-timer": """
import time

def timed(fn):
    t0 = time.time()
    fn()
    return time.time() - t0
""",
    "span-not-ended": """
from llmss_tpu.utils import trace

def handle(req):
    span = trace.recorder().start_span(req.id, "prefill")
    run_prefill(req)

def fire_and_forget(req):
    trace.recorder().start_span(req.id, "decode")
    run_decode(req)
""",
    "unbounded-metric-label": """
from llmss_tpu.utils import metrics

def handle(req_id):
    metrics.series().counter(f"requests_{req_id}").add()
""",
    "fetch-inside-jit-scan": """
import jax
import numpy as np

def _step(carry, x):
    y = carry + x
    np.asarray(y)
    return y, y

def roll(init, xs):
    return jax.lax.scan(_step, init, xs)
""",
    "unguarded-write": """
import threading

class Box:
    def __init__(self):
        self.items = []  # guarded_by: self._lock
        self._lock = threading.Lock()

    def put(self, x):
        self.items.append(x)
""",
    "lock-order-cycle": """
import threading

class Box:
    def __init__(self):
        self._lock_a = threading.Lock()
        self._lock_b = threading.Lock()

    def ab(self):
        with self._lock_a:
            with self._lock_b:
                pass

    def ba(self):
        with self._lock_b:
            with self._lock_a:
                pass
""",
}


def lint(tmp_path, source, name="snippet.py", **kwargs):
    f = tmp_path / name
    f.write_text(source)
    return run([str(f)], **kwargs)


@pytest.mark.parametrize("rule", sorted(VIOLATIONS))
def test_each_violation_fixture_fails(tmp_path, rule):
    code, findings = lint(tmp_path, VIOLATIONS[rule])
    assert code == 1
    assert rule in {f.rule for f in findings}, [f.render() for f in findings]


def test_fixture_catalog_covers_every_rule():
    assert set(VIOLATIONS) == set(RULES)


def test_docs_catalog_covers_every_rule():
    from llmss_tpu.analysis.shardcheck_rules import SHARD_RULES

    doc = (REPO_ROOT / "docs" / "static-analysis.md").read_text()
    for rule in [*RULES, *SHARD_RULES]:
        assert f"`{rule}`" in doc, f"{rule} missing from docs/static-analysis.md"


def test_clean_file_exits_zero(tmp_path):
    code, findings = lint(tmp_path, "import time\nt0 = time.monotonic()\n")
    assert (code, findings) == (0, [])


# -- rule precision (the sites the repo relies on staying legal) ------------

def test_deadline_ts_statements_are_exempt(tmp_path):
    code, findings = lint(tmp_path, """
import time

def stamp(req, timeout):
    req.deadline_ts = time.time() + timeout

def expired(req):
    return req.deadline_ts is not None and time.time() > req.deadline_ts
""")
    assert (code, findings) == (0, [])


def test_wall_anchor_statements_are_exempt(tmp_path):
    # The trace export's one-wall-read-per-process anchor is the other
    # legal wall-clock site (cross-process stitching needs it); the same
    # statement discipline as deadline_ts applies.
    code, findings = lint(tmp_path, """
import time

def export(reqs):
    return {"wall_anchor": time.time(), "mono_anchor": time.monotonic()}
""")
    assert (code, findings) == (0, [])
    # The exemption is per-statement, not per-file.
    code, findings = lint(tmp_path, """
import time

def export(reqs):
    wall_anchor = time.time()
    t0 = time.time()
    return wall_anchor, t0
""")
    assert code == 1
    assert [f.rule for f in findings] == ["wall-clock-timer"]
    assert findings[0].line == 6


def test_span_with_statement_and_finally_end_are_legal(tmp_path):
    # The two blessed shapes: context manager, and try/finally .end().
    code, findings = lint(tmp_path, """
from llmss_tpu.utils import trace

def ctx(req):
    with trace.recorder().start_span(req.id, "prefill"):
        run(req)

def explicit(req):
    span = trace.recorder().start_span(req.id, "decode")
    try:
        run(req)
    finally:
        span.end(ok=True)

def factory(req):
    # Returning the span hands lifetime to the caller — not a leak.
    return trace.recorder().start_span(req.id, "adopt")
""")
    assert (code, findings) == (0, [])


def test_span_ended_only_on_happy_path_flagged(tmp_path):
    code, findings = lint(tmp_path, """
from llmss_tpu.utils import trace

def leaky(req):
    span = trace.recorder().start_span(req.id, "decode")
    run(req)  # raises -> span never ends
    span.end()
""")
    # .end() after a statement that can raise is not a guaranteed
    # position... but a straight-line body IS guaranteed to reach it, so
    # this form passes; only branch-dependent ends are flagged.
    assert (code, findings) == (0, [])
    code, findings = lint(tmp_path, """
from llmss_tpu.utils import trace

def branchy(req, ok):
    span = trace.recorder().start_span(req.id, "decode")
    if ok:
        span.end()
""")
    assert code == 1
    assert {f.rule for f in findings} == {"span-not-ended"}


def test_time_import_alias_tracked(tmp_path):
    code, findings = lint(tmp_path, """
import time as _time

def timer():
    return _time.time()
""")
    assert code == 1
    assert findings[0].rule == "wall-clock-timer"


def test_shape_unpack_and_is_none_not_flagged(tmp_path):
    # `x.shape` is static inside jit; `is None` tests are how optional
    # params are threaded — neither may be flagged.
    code, findings = lint(tmp_path, """
import functools
import jax

@functools.partial(jax.jit, static_argnames=("scale",))
def attend(q, k, scale=None):
    B, S, H, D = q.shape
    assert S == 1
    if scale is None:
        scale = D ** -0.5
    if S > 4:
        q = q * scale
    return q
""")
    assert (code, findings) == (0, [])


def test_isinstance_pytree_branch_not_flagged(tmp_path):
    # isinstance() on a jit argument branches on PYTREE STRUCTURE, which
    # is resolved at trace time — the forward()'s dense/paged dispatch
    # relies on this staying legal.
    code, findings = lint(tmp_path, """
import jax

class PagedKVCache(tuple):
    pass

@jax.jit
def forward(tok, cache):
    if isinstance(cache, PagedKVCache):
        tok = tok + 1
    return tok, cache
""")
    assert (code, findings) == (0, [])


def test_partial_bound_args_are_not_tracers(tmp_path):
    # partial-bound leading args (cfg, mesh) are trace-time constants:
    # branching on them is legal and must not be flagged.
    code, findings = lint(tmp_path, """
from functools import partial
import jax

def _prefill_impl(cfg, mesh, cache, tok):
    if cfg.rotary:
        tok = tok + 1
    return cache, tok

def build(cfg, mesh):
    return jax.jit(partial(_prefill_impl, cfg, mesh), donate_argnums=(0,))
""")
    assert (code, findings) == (0, [])


def test_donated_cache_jit_not_flagged(tmp_path):
    code, findings = lint(tmp_path, """
import jax

def _decode_impl(params, tok, cache):
    return tok, cache

decode = jax.jit(_decode_impl, donate_argnums=(2,))
""")
    assert (code, findings) == (0, [])


def test_metric_label_taint_through_str_and_concat(tmp_path):
    # str() wraps and +-concat are the usual laundering paths; the walk
    # must see through both, and `.labels(...)` / `labels=` count too.
    code, findings = lint(tmp_path, """
from llmss_tpu.utils import metrics

def a(trace_id):
    metrics.series().histogram("lat_" + str(trace_id)).observe(1.0)

def b(request_id):
    metrics.series().counter("reqs").labels(request_id).add()

def c(req):
    make_gauge("queue_depth", labels={"req": req.req_id})
""")
    assert code == 1
    hits = [f for f in findings if f.rule == "unbounded-metric-label"]
    assert len(hits) == 3
    assert {f.line for f in hits} == {5, 8, 11}


def test_metric_label_bounded_names_and_trace_record_not_flagged(tmp_path):
    # Bounded vocabularies are the point of the rule staying quiet; the
    # per-request id's rightful home — trace.record(req_id, ...) — must
    # never be flagged (traces are per-request by design).
    code, findings = lint(tmp_path, """
from llmss_tpu.utils import metrics, trace

def observe(req_id, phase, dur_s):
    trace.record(req_id, "respond", ok=True)
    metrics.series().counter("requests_total").add()
    metrics.series().histogram(f"{phase}_s").observe(dur_s)
    metrics.series().counter("reqs").labels(phase).add()
""")
    assert (code, findings) == (0, [])


def test_fetch_in_scan_device_get_and_fori_body(tmp_path):
    # device_get is the fetch jit-host-sync never modelled; the fori_loop
    # body index (arg 2) and the `from jax import lax` alias must both
    # resolve.
    code, findings = lint(tmp_path, """
import jax
from jax import lax

def _body(i, val):
    jax.device_get(val)
    return val + i

def run(n, v0):
    return lax.fori_loop(0, n, _body, v0)
""")
    assert code == 1
    hits = [f for f in findings if f.rule == "fetch-inside-jit-scan"]
    assert len(hits) == 1 and hits[0].line == 6
    assert "fori_loop" in hits[0].message


def test_fetch_in_while_loop_cond_and_lambda_body(tmp_path):
    # while_loop traces BOTH callables; lambdas never appear in the jit
    # registry, so the call-site resolution is the only way in.
    code, findings = lint(tmp_path, """
import jax

def _cond(state):
    return state.item() > 0

def run(s0):
    return jax.lax.while_loop(_cond, lambda s: float(s) + s, s0)
""")
    assert code == 1
    hits = [f for f in findings if f.rule == "fetch-inside-jit-scan"]
    assert {f.line for f in hits} == {5, 8}


def test_fetch_in_scan_partial_bound_args_are_static(tmp_path):
    # partial-bound leading params are trace-time constants (same contract
    # as _seed_params for jit): fetching THEM is legal, fetching the scan
    # carry is not.
    code, findings = lint(tmp_path, """
from functools import partial
import jax
import numpy as np

def _step(cfg, table, carry, x):
    np.asarray(table)
    return carry + x, np.asarray(carry)

def roll(cfg, table, init, xs):
    return jax.lax.scan(partial(_step, cfg, table), init, xs)
""")
    assert code == 1
    hits = [f for f in findings if f.rule == "fetch-inside-jit-scan"]
    assert len(hits) == 1 and hits[0].line == 8


def test_clean_scan_body_and_host_fetch_after_loop_not_flagged(tmp_path):
    # Static attribute reads inside the body and the blessed shape — fetch
    # the stacked ys ONCE after the loop returns — must stay quiet.
    code, findings = lint(tmp_path, """
import jax
import numpy as np

def _step(carry, x):
    b = x.shape[0]
    return carry + x, carry

def roll(init, xs):
    carry, ys = jax.lax.scan(_step, init, xs)
    return np.asarray(ys)
""")
    assert (code, findings) == (0, [])


# -- suppression + baseline mechanics ---------------------------------------

def test_suppression_same_line_and_line_above(tmp_path):
    code, findings = lint(tmp_path, """
import time

t0 = time.time()  # lint: ignore[wall-clock-timer]
# lint: ignore[wall-clock-timer] cross-process stamp
t1 = time.time()
""")
    assert (code, findings) == (0, [])


def test_suppression_is_rule_specific(tmp_path):
    code, findings = lint(tmp_path, """
import time

t0 = time.time()  # lint: ignore[host-sync-in-loop]
""")
    assert code == 1
    assert findings[0].rule == "wall-clock-timer"


def test_baseline_accepts_existing_and_catches_new(tmp_path):
    src = VIOLATIONS["wall-clock-timer"]
    baseline = tmp_path / "baseline.json"

    code, _ = lint(tmp_path, src, baseline_path=str(baseline),
                   write_baseline=True)
    assert code == 0
    assert json.loads(baseline.read_text())["version"] == 1

    # same findings: baselined, exit 0
    code, findings = lint(tmp_path, src, baseline_path=str(baseline))
    assert (code, findings) == (0, [])

    # a NEW finding on another line is not covered by the baseline
    code, findings = lint(
        tmp_path, src + "\nt_extra = time.time()\n",
        baseline_path=str(baseline),
    )
    assert code == 1
    assert len(findings) == 1


# -- lock discipline (seeded-violation acceptance criteria) ------------------

def test_seeded_unguarded_write_detected(tmp_path):
    code, findings = lint(tmp_path, """
import threading

class Sched:
    def __init__(self):
        self.pending = []  # guarded_by: self._lock
        self._free = []  # guarded_by: self._lock
        self._lock = threading.Lock()

    def ok(self, x):
        with self._lock:
            self.pending.append(x)

    def bad(self, row):
        self._free.append(row)

    def also_bad(self):
        self.pending = []
""")
    assert code == 1
    hits = [f for f in findings if f.rule == "unguarded-write"]
    assert {f.line for f in hits} == {15, 18}
    assert all("self._lock" in f.message for f in hits)


def test_seeded_lock_order_cycle_detected(tmp_path):
    code, findings = lint(tmp_path, VIOLATIONS["lock-order-cycle"])
    assert code == 1
    cycles = [f for f in findings if f.rule == "lock-order-cycle"]
    assert len(cycles) == 1
    assert "Box._lock_a" in cycles[0].message
    assert "Box._lock_b" in cycles[0].message


def test_call_mediated_lock_cycle_detected(tmp_path):
    # outer holds A and calls a sibling that takes B; rev nests B->A
    # lexically — the cycle only exists through the call edge.
    code, findings = lint(tmp_path, """
import threading

class Box:
    def outer(self):
        with self._lock_a:
            self.inner()

    def inner(self):
        with self._lock_b:
            pass

    def rev(self):
        with self._lock_b:
            with self._lock_a:
                pass
""")
    assert code == 1
    assert "lock-order-cycle" in {f.rule for f in findings}


def test_consistent_lock_order_has_no_cycle(tmp_path):
    code, findings = lint(tmp_path, """
import threading

class Box:
    def a_then_b(self):
        with self._lock_a:
            with self._lock_b:
                pass

    def also_a_then_b(self):
        with self._lock_a:
            self.just_b()

    def just_b(self):
        with self._lock_b:
            pass
""")
    assert (code, findings) == (0, [])


# -- the gate itself ---------------------------------------------------------

def test_repo_lints_clean_against_committed_baseline():
    code, findings = run(
        [str(REPO_ROOT / "llmss_tpu")],
        baseline_path=str(REPO_ROOT / "tools" / "lint_baseline.json"),
    )
    assert code == 0, "\n".join(f.render() for f in findings)


def test_module_entrypoint_exit_codes(tmp_path):
    bad = tmp_path / "bad.py"
    bad.write_text(VIOLATIONS["wall-clock-timer"])
    proc = subprocess.run(
        [sys.executable, "-m", "llmss_tpu.analysis", str(bad),
         "--no-baseline"],
        capture_output=True, text=True, cwd=REPO_ROOT,
    )
    assert proc.returncode == 1
    assert "wall-clock-timer" in proc.stdout

    good = tmp_path / "good.py"
    good.write_text("x = 1\n")
    proc = subprocess.run(
        [sys.executable, "-m", "llmss_tpu.analysis", str(good)],
        capture_output=True, text=True, cwd=REPO_ROOT,
    )
    assert proc.returncode == 0


# -- CompileGuard (runtime twin) ---------------------------------------------

def test_compile_guard_passes_steady_state_and_catches_recompile():
    import jax
    import jax.numpy as jnp

    from llmss_tpu.analysis import CompileGuard

    fn = jax.jit(lambda x: x * 2)

    class Host:
        pass

    host = Host()
    host._step = fn
    guard = CompileGuard.for_engine(host)
    assert "_step" in guard._fns

    fn(jnp.zeros(4))  # warmup compile
    guard.snapshot()
    fn(jnp.zeros(4))  # steady state: same signature
    guard.assert_no_recompiles()

    fn(jnp.zeros(8))  # new shape -> recompile
    with pytest.raises(AssertionError, match="_step"):
        guard.assert_no_recompiles()


def test_compile_guard_context_manager():
    import jax
    import jax.numpy as jnp

    from llmss_tpu.analysis import CompileGuard

    fn = jax.jit(lambda x: x + 1)
    fn(jnp.zeros(2))
    guard = CompileGuard({"step": fn})
    with guard.steady_state():
        fn(jnp.zeros(2))
    with pytest.raises(AssertionError):
        with guard.steady_state():
            fn(jnp.zeros(3))


def test_compile_guard_degrades_to_noop_without_cache_size():
    from llmss_tpu.analysis import CompileGuard

    guard = CompileGuard({"plain": lambda x: x})
    assert guard._fns == {}
    guard.snapshot()
    guard.assert_no_recompiles()  # nothing tracked, nothing raised
