"""Mesh construction and plan resolution (parity with utils/dist.py roles)."""

import jax
import jax.numpy as jnp
import pytest
from jax.sharding import NamedSharding, PartitionSpec as P

from llmss_tpu.parallel import AXIS_DP, AXIS_TP, MeshPlan, make_mesh
from llmss_tpu.parallel.mesh import shard_map as compat_shard_map


def test_default_plan_is_all_tp(devices):
    # Reference default: world group == TP group (dist.py:77).
    mesh = make_mesh()
    assert mesh.shape[AXIS_TP] == 8
    assert mesh.shape[AXIS_DP] == 1


def test_plan_resolution():
    assert MeshPlan(dp=2, tp=None).resolve(8) == (2, 1, 4)
    assert MeshPlan(dp=2, sp=2, tp=2).resolve(8) == (2, 2, 2)
    with pytest.raises(ValueError):
        MeshPlan(dp=3).resolve(8)
    with pytest.raises(ValueError):
        MeshPlan(dp=2, tp=2).resolve(8)


def test_psum_over_tp_axis(devices):
    # A real collective over the virtual mesh — the FakeGroup upgrade.
    mesh = make_mesh(MeshPlan(tp=8))
    x = jnp.arange(8.0)

    def f(x):
        return jax.lax.psum(x, AXIS_TP)

    y = compat_shard_map(
        f, mesh=mesh, in_specs=P(AXIS_TP), out_specs=P()
    )(x)
    assert y.shape == (1,)
    assert float(y[0]) == 28.0


def test_sharded_matmul_gspmd(devices):
    # Column-parallel matmul via NamedSharding: XLA partitions without error.
    mesh = make_mesh(MeshPlan(tp=8))
    w = jax.device_put(
        jnp.ones((16, 32)), NamedSharding(mesh, P(None, AXIS_TP))
    )
    x = jnp.ones((4, 16))
    y = jax.device_get(jax.jit(lambda x, w: x @ w)(x, w))
    assert y.shape == (4, 32)
    assert float(y[0, 0]) == 16.0
