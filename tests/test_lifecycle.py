"""Worker lifecycle: graceful drain, decode watchdog, per-row poison
containment (ISSUE 2).

The three chaos scenarios here are the acceptance contract for the
``starting → ready → draining → dead`` state machine in
``serve/supervisor.py``, each run against BOTH delivery substrates
(InProcBroker and the real RedisBroker code paths over FakeRedis):

- **drain**: a drain issued mid-load lets every in-flight request finish
  and ack — zero errors, zero redeliveries — and the worker ends ``dead``;
- **hang**: a decode step that wedges is detected by the watchdog within
  ``step_timeout_s``, the worker restarts, and every accepted request
  still gets exactly one terminal response with the exact payload;
- **nan**: a row whose logits go non-finite errors out alone, while
  co-batched rows keep their exact solo tokens.
"""

import threading
import time

import pytest

from llmss_tpu.serve.broker import InProcBroker, RedisBroker
from llmss_tpu.serve.chaos import NAN_TOKEN, FakeRedis, ScriptedEngine
from llmss_tpu.serve.consumer import Worker
from llmss_tpu.serve.producer import ProducerServer
from llmss_tpu.serve.protocol import (
    STATE_DEAD,
    STATE_READY,
    GenerateRequest,
)
from llmss_tpu.serve.supervisor import Supervisor

BROKER_KINDS = ("inproc", "fakeredis")


def make_brokers(kind, *, lease_s=5.0, max_attempts=6):
    """(producer_broker, worker_broker) on one substrate."""
    if kind == "inproc":
        b = InProcBroker(lease_s=lease_s, max_delivery_attempts=max_attempts)
        return b, b
    server = FakeRedis()

    def mk(wid):
        return RedisBroker(
            client=server, worker_id=wid, lease_s=lease_s,
            max_delivery_attempts=max_attempts,
        )

    return mk("producer"), mk("worker")


def collect(broker, reqs, timeout_s, give_up=None):
    """One waiter per request (the producer pattern). Returns
    {id: response|'DUPLICATE'}; unanswered ids are absent."""
    results = {}
    lock = threading.Lock()
    deadline = time.time() + timeout_s

    def wait_one(req):
        while time.time() < deadline:
            if give_up is not None and give_up.is_set():
                return
            resp = broker.wait_response(req.id, timeout=0.2)
            if resp is None:
                continue
            with lock:
                results[req.id] = resp
            dup = broker.wait_response(req.id, timeout=0.2)
            if dup is not None:
                with lock:
                    results[req.id] = "DUPLICATE"
            return

    threads = [
        threading.Thread(target=wait_one, args=(r,), daemon=True)
        for r in reqs
    ]
    for t in threads:
        t.start()
    return results, threads


def push_requests(broker, n, *, max_new_tokens=4, first_token=1):
    reqs = [
        GenerateRequest(
            token_ids=[first_token + i], max_new_tokens=max_new_tokens,
            deadline_ts=time.time() + 60.0,
        )
        for i in range(n)
    ]
    for r in reqs:
        broker.push_request(r)
    return reqs


# -- acceptance (a): drain under load ---------------------------------------


@pytest.mark.parametrize("kind", BROKER_KINDS)
def test_drain_under_load_completes_inflight_cleanly(kind):
    prod, wb = make_brokers(kind)
    engine = ScriptedEngine(chunk_delay_s=0.03)

    def factory():
        return Worker(
            engine, wb, batch_size=2, poll_timeout_s=0.02, pad_batch=False,
            chunk_steps=4,
        )

    sup = Supervisor(factory, wb, backoff_s=0.01, heartbeat_s=0.05)
    reqs = push_requests(prod, 16, max_new_tokens=16)
    stop = threading.Event()
    t = threading.Thread(target=sup.run, args=(stop,), daemon=True)
    t.start()

    give_up = threading.Event()
    results, waiters = collect(prod, reqs, timeout_s=30.0, give_up=give_up)
    deadline = time.time() + 20.0
    while len(results) < 2 and time.time() < deadline:
        time.sleep(0.005)
    assert len(results) >= 2, "no load was served before the drain"
    sup.drain(timeout_s=10.0)
    t.join(timeout=20.0)
    assert not t.is_alive(), "drain did not complete"
    time.sleep(0.3)  # let terminal responses already pushed land
    give_up.set()
    for w in waiters:
        w.join(timeout=5.0)

    # Everything answered was answered exactly once, cleanly, with the
    # exact scripted payload — the drain produced no errors.
    answered = 0
    for r in reqs:
        got = results.get(r.id)
        if got is None:
            continue  # still queued at drain time: expected, not an error
        assert got != "DUPLICATE", f"{r.id} answered twice"
        assert not got.error, f"{r.id} errored during drain: {got.error}"
        assert got.token_ids == ScriptedEngine.expected_tokens(
            list(r.token_ids), r.max_new_tokens
        )
        answered += 1
    assert answered >= 2
    stats = prod.delivery_stats()
    assert stats.get("redelivered", 0) == 0
    assert stats.get("inflight", 0) == 0  # nothing left holding a lease
    # Unanswered requests are still queued for another worker, not lost.
    assert prod.queue_depth() == len(reqs) - answered
    # Terminal lifecycle state is published through the health channel.
    assert sup.state == STATE_DEAD
    m = prod.read_metrics()["supervisor"]
    assert m["state"] == STATE_DEAD and m["alive"] is False


# -- acceptance (b): hang → watchdog → restart ------------------------------


@pytest.mark.parametrize("kind", BROKER_KINDS)
def test_hang_detected_and_every_request_answered_once(kind):
    prod, wb = make_brokers(kind, lease_s=0.4, max_attempts=10)
    # ONE engine across restarts: generate call #2 wedges (30 s — only the
    # watchdog can end it), every other call is instant.
    engine = ScriptedEngine(hang_at=2, hang_s=30.0)

    def factory():
        return Worker(
            engine, wb, batch_size=2, poll_timeout_s=0.02, pad_batch=False,
        )

    sup = Supervisor(
        factory, wb, backoff_s=0.01, heartbeat_s=0.05, step_timeout_s=0.3,
    )
    reqs = push_requests(prod, 8)
    stop = threading.Event()
    t = threading.Thread(target=sup.run, args=(stop,), daemon=True)
    t.start()
    t_start = time.time()

    results, waiters = collect(prod, reqs, timeout_s=30.0)
    for w in waiters:
        w.join(timeout=35.0)
    detect_latency = None
    if sup.watchdog_stalls:
        detect_latency = time.time() - t_start
    stop.set()
    t.join(timeout=10.0)

    assert sup.watchdog_stalls == 1, "watchdog never detected the hang"
    assert sup.restarts >= 1, "worker was not restarted after the stall"
    assert "watchdog" in (sup._last_error or "") or sup.restarts >= 1
    # Detection must be watchdog-speed (step_timeout_s), not hang_s-speed:
    # the full run — serve, detect, restart, redeliver, finish — fits in a
    # small multiple of step_timeout_s, nowhere near the 30 s hang.
    assert detect_latency is not None and detect_latency < 10.0
    # Exactly one terminal response per accepted request, exact payloads:
    # the hung batch's leases expired and were redelivered to the rebuilt
    # worker.
    for r in reqs:
        got = results.get(r.id)
        assert got is not None, f"{r.id} never answered after the hang"
        assert got != "DUPLICATE", f"{r.id} answered twice"
        assert not got.error, f"{r.id}: {got.error}"
        assert got.token_ids == ScriptedEngine.expected_tokens(
            list(r.token_ids), r.max_new_tokens
        )
    stats = prod.delivery_stats()
    assert stats.get("redelivered", 0) >= 1, "hung leases never redelivered"


# -- acceptance (c): NaN row poisoned, batch-mates exact --------------------


@pytest.mark.parametrize("kind", BROKER_KINDS)
def test_nan_row_errors_alone_batchmates_keep_solo_tokens(kind):
    prod, wb = make_brokers(kind)
    engine = ScriptedEngine(nan_at=1)
    worker = Worker(
        engine, wb, batch_size=2, poll_timeout_s=0.05, pad_batch=False,
    )
    bad = GenerateRequest(id="bad", token_ids=[NAN_TOKEN], max_new_tokens=4)
    good = GenerateRequest(id="good", token_ids=[7], max_new_tokens=4)
    prod.push_request(bad)
    prod.push_request(good)
    worker.run_once()  # one co-batched generate call

    bresp = prod.wait_response("bad", timeout=5)
    gresp = prod.wait_response("good", timeout=5)
    assert bresp is not None and bresp.error
    assert "poisoned" in bresp.error
    assert gresp is not None and not gresp.error
    assert gresp.token_ids == ScriptedEngine.expected_tokens([7], 4)
    assert engine.metrics.to_dict()["poisoned_rows"] == 1


# -- satellite 3: hung run_once flips producer /health ----------------------


def test_hung_run_once_flips_health_503_within_3x_heartbeat():
    """The heartbeat is progress-stamped, so a run_once wedged inside the
    engine goes stale at the producer within 3× heartbeat_s even though
    the supervisor thread (the one that publishes) is blocked — no
    watchdog needed for visibility."""
    b = InProcBroker()
    engine = ScriptedEngine(hang_at=1, hang_s=2.0)

    def factory():
        return Worker(
            engine, b, batch_size=1, poll_timeout_s=0.01, pad_batch=False,
        )

    sup = Supervisor(factory, b, backoff_s=0.01, heartbeat_s=0.1)
    srv = ProducerServer(b, host="127.0.0.1", port=0)
    stop = threading.Event()
    t = threading.Thread(target=sup.run, args=(stop,), daemon=True)
    t.start()
    try:
        deadline = time.time() + 5.0
        while time.time() < deadline:
            code, body = srv.health()
            if code == 200 and body.get("state") == STATE_READY:
                break
            time.sleep(0.01)
        assert code == 200, f"worker never became healthy: {body}"

        # This request's generate call wedges for 2 s with no progress.
        b.push_request(GenerateRequest(id="h", token_ids=[1],
                                       max_new_tokens=2))
        t0 = time.time()
        code = 200
        while time.time() - t0 < 3.0:
            code, body = srv.health()
            if code == 503:
                break
            time.sleep(0.01)
        flipped_after = time.time() - t0
        assert code == 503, "health never flipped on the hung step"
        assert body["status"] == "stale-heartbeat"
        # 3 × heartbeat_s = 0.3 s staleness threshold; the flip lands
        # shortly after it, long before the 2 s hang resolves.
        assert flipped_after < 1.5
    finally:
        stop.set()
        t.join(timeout=10.0)


# -- satellite 1: sliding-window restart budget -----------------------------


def test_restart_budget_is_sliding_window():
    """``max_restarts`` bounds crash *density* (crashes since the last
    stable run), not the lifetime total: with stability between crashes the
    budget never exhausts, while the same schedule without stability resets
    raises."""

    def run_schedule(stable_after_s):
        calls = {"n": 0}
        stop = threading.Event()

        class W:
            def run_once(self):
                calls["n"] += 1
                if calls["n"] >= 9:
                    stop.set()
                    return
                if calls["n"] % 2 == 0:
                    raise RuntimeError(f"crash@{calls['n']}")

        sup = Supervisor(
            W, InProcBroker(), backoff_s=0.0, max_restarts=2,
            stable_after_s=stable_after_s, heartbeat_s=0.0,
        )
        sup.run(stop)
        return sup

    # Crash every other call, but each intervening success counts as a
    # stable run (stable_after_s=0): 4 lifetime crashes never exceed the
    # budget of 2.
    sup = run_schedule(stable_after_s=0.0)
    assert sup.restarts <= 1

    # The same schedule with no stability credit exhausts the budget on
    # the third crash.
    with pytest.raises(RuntimeError, match="restart budget"):
        run_schedule(stable_after_s=3600.0)


# -- real-engine lifecycle paths (continuous batching) ----------------------


@pytest.fixture(scope="module")
def small_engine(devices):
    import jax

    from llmss_tpu.engine import DecodeEngine
    from llmss_tpu.models.common import DecoderConfig
    from llmss_tpu.models.decoder import init_params
    from llmss_tpu.parallel import MeshPlan, make_mesh

    mesh = make_mesh(MeshPlan(dp=1, sp=1, tp=8))
    cfg = DecoderConfig(
        model_type="llama", vocab_size=128, hidden_size=32, n_layers=1,
        n_heads=4, n_kv_heads=4, head_dim=8, intermediate_size=64,
        max_position_embeddings=64, activation="silu", norm="rmsnorm",
        norm_eps=1e-5, mlp="swiglu", positions="rotary", rope_style="half",
        rotary_dim=8, attn_bias=False, mlp_bias=False,
        tie_word_embeddings=False, dtype="float32",
    )
    params = init_params(cfg, mesh, jax.random.key(0))
    return DecodeEngine(cfg, params, mesh, max_seq_len=64)


def test_continuous_worker_drains_active_rows(small_engine):
    """Clean drain with real decode in flight: the active row finishes and
    acks; the worker reports drained only once the batcher is idle."""
    from llmss_tpu.serve.consumer import ContinuousWorker

    b = InProcBroker()
    w = ContinuousWorker(small_engine, b, tokenizer=None, rows=2)
    b.push_request(GenerateRequest(
        id="rq", token_ids=[1, 2, 3], max_new_tokens=20, is_greedy=True,
    ))
    w.run_once()  # admits; far from finished
    w.begin_drain()
    assert not w.drained  # active row still decoding
    for _ in range(200):
        if w.drained:
            break
        w.run_once()
    assert w.drained
    resp = b.wait_response("rq", timeout=5)
    assert resp is not None and not resp.error
    assert len(resp.token_ids) == 20


def test_release_pending_requeues_unstarted_requests(small_engine):
    """Drain-deadline fallback: requests the device never touched go back
    to the broker queue with their delivery attempt refunded; active rows
    are aborted with an error (every client gets exactly one answer)."""
    from llmss_tpu.serve.consumer import ContinuousWorker

    b = InProcBroker()
    w = ContinuousWorker(small_engine, b, tokenizer=None, rows=1)
    b.push_request(GenerateRequest(
        id="active", token_ids=[1, 2], max_new_tokens=20, is_greedy=True,
    ))
    b.push_request(GenerateRequest(
        id="queued", token_ids=[3, 4], max_new_tokens=20, is_greedy=True,
    ))
    w.run_once()  # leases both; admits "active" (rows=1), "queued" pends
    assert w.release_pending() == 1
    assert b.queue_depth() == 1
    n = w.abort_inflight("worker draining: drain deadline exceeded")
    assert n == 1
    aresp = b.wait_response("active", timeout=5)
    assert aresp is not None and "drain deadline exceeded" in aresp.error
    # The released request is deliverable again, with its delivery attempt
    # refunded — the drain bounce doesn't count toward dead-lettering.
    req2 = b.pop_request(timeout=1.0)
    assert req2 is not None and req2.id == "queued"
    assert req2.delivery_attempts == 1


def test_scheduler_poisons_row_without_touching_batchmates(small_engine):
    """Per-row containment on the continuous path: a poisoned flag for one
    row errors only that row; the co-batched row's tokens are exactly its
    solo tokens."""
    from llmss_tpu.engine import GenerationParams
    from llmss_tpu.engine.scheduler import ContinuousBatcher

    gp = GenerationParams(max_new_tokens=8, is_greedy=True)
    solo = small_engine.generate([[5, 6, 7]], gp)[0]

    batcher = ContinuousBatcher(small_engine, rows=2, chunk_steps=2)
    orig = small_engine._decode_group

    def poisoning(*a, **k):
        # Tamper with the grouped program's PACKED output: flip the
        # per-chunk poisoned flag (layout: n_chunks*B*k tokens, then
        # n_chunks*B flags) for the bad row in every chunk.
        packed, last_tok, cache, cur_pos, done = orig(*a, **k)
        bad_row = next(
            (row for row, r in batcher.active.items()
             if r.req_id == "bad" and not r.awaiting_first),
            None,
        )
        if bad_row is not None:
            nc, steps = k["n_chunks"], k["n_steps"]
            B = batcher.rows
            base = nc * B * steps
            for c in range(nc):
                packed = packed.at[base + c * B + bad_row].set(1)
        return packed, last_tok, cache, cur_pos, done

    small_engine._decode_group = poisoning
    try:
        done = {}

        def cb_for(name):
            def cb(toks, cancelled=False, error=None):
                done[name] = (list(toks), error)
            return cb

        batcher.submit([5, 6, 7], GenerationParams(
            max_new_tokens=8, is_greedy=True), cb_for("good"),
            req_id="good")
        batcher.submit([9, 9], GenerationParams(
            max_new_tokens=8, is_greedy=True), cb_for("bad"), req_id="bad")
        for _ in range(100):
            if len(done) == 2:
                break
            batcher.step()
    finally:
        small_engine._decode_group = orig

    assert "poisoned" in (done["bad"][1] or "")
    good_toks, good_err = done["good"]
    assert good_err is None
    assert good_toks == solo, "poison leaked into a batch-mate's tokens"
    assert small_engine.metrics.to_dict()["poisoned_rows"] >= 1


def test_engine_generate_reports_poisoned_rows(small_engine):
    """Batch path plumbing: a poisoned flag from the fused decode surfaces
    through ``on_poisoned`` and never reads as a clean success."""
    from llmss_tpu.engine import GenerationParams

    gp = GenerationParams(max_new_tokens=6, is_greedy=True)
    solo = small_engine.generate([[11, 12]], gp)[0]

    orig = small_engine._decode_group

    def poisoning(*a, **k):
        # Flip row 0's poisoned flag in the grouped program's packed
        # output (n_chunks*B*k tokens, then n_chunks*B flags; B = the
        # tokens carry's row count).
        packed, last_tok, cache, cur_pos, done = orig(*a, **k)
        nc, steps = k["n_chunks"], k["n_steps"]
        B = a[1].shape[0]
        base = nc * B * steps
        for c in range(nc):
            packed = packed.at[base + c * B + 0].set(1)
        return packed, last_tok, cache, cur_pos, done

    flagged = set()
    small_engine._decode_group = poisoning
    try:
        outs = small_engine.generate(
            [[3, 4], [11, 12]],
            [GenerationParams(max_new_tokens=6, is_greedy=True),
             GenerationParams(max_new_tokens=6, is_greedy=True)],
            on_poisoned=flagged.add,
            chunk_steps=2,  # the chunked (serving) path carries the flag
        )
    finally:
        small_engine._decode_group = orig
    assert flagged == {0}
    assert outs[1] == solo, "poison leaked into a batch-mate's tokens"


def test_nonfinite_rows_unit():
    import jax.numpy as jnp
    import numpy as np

    from llmss_tpu.ops.sampling import nonfinite_rows

    logits = jnp.asarray([
        [0.1, 0.2, 0.3],
        [0.1, jnp.nan, 0.3],
        [jnp.inf, 0.2, 0.3],
        [-jnp.inf, 0.2, 0.3],
    ])
    np.testing.assert_array_equal(
        np.asarray(nonfinite_rows(logits)), [False, True, True, True]
    )
