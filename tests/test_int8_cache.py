"""int8 KV cache: quantization numerics + engine parity with bf16 cache.

kv_dtype="int8" stores K/V quantized with per-(token, head) scales —
half the cache HBM footprint (double the rows/context per chip). These
tests pin the numerics contract: exact dequant→quant round trips, logits
within quantization-noise tolerance of the full-precision cache, and the
whole engine stack (prefill → ring-buffer decode → continuous batching)
running unchanged on the quantized cache."""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from llmss_tpu.engine import DecodeEngine, GenerationParams
from llmss_tpu.engine.cache import dequantize_kv, quantize_kv
from llmss_tpu.models.common import DecoderConfig
from llmss_tpu.models.decoder import init_params
from llmss_tpu.parallel import MeshPlan, make_mesh


def test_quantize_roundtrip_error_bound():
    rng = np.random.default_rng(0)
    x = jnp.asarray(rng.standard_normal((3, 5, 4, 32)), jnp.float32)
    q, s = quantize_kv(x)
    assert q.dtype == jnp.int8 and s.shape == x.shape[:-1]
    err = np.abs(np.asarray(dequantize_kv(q, s, jnp.float32)) - np.asarray(x))
    # Symmetric quantization error is bounded by half a step per element.
    assert (err <= np.asarray(s)[..., None] / 2 + 1e-7).all()

    # Dequant -> quant reproduces the stored int8 exactly (the per-head max
    # always maps to +-127), so the prefill path's re-quantize of untouched
    # slots is lossless.
    q2, s2 = quantize_kv(dequantize_kv(q, s, jnp.float32))
    np.testing.assert_array_equal(np.asarray(q), np.asarray(q2))
    np.testing.assert_allclose(np.asarray(s), np.asarray(s2), rtol=1e-6)

    # All-zero rows (empty cache slots) stay exactly zero.
    q0, s0 = quantize_kv(jnp.zeros((2, 4, 8)))
    assert (np.asarray(q0) == 0).all()
    assert (np.asarray(dequantize_kv(q0, s0, jnp.float32)) == 0).all()


@pytest.fixture(scope="module")
def cfg_and_params(devices):
    cfg = DecoderConfig(
        model_type="llama", vocab_size=256, hidden_size=64, n_layers=2,
        n_heads=8, n_kv_heads=4, head_dim=8, intermediate_size=128,
        max_position_embeddings=128, activation="silu", norm="rmsnorm",
        norm_eps=1e-5, mlp="swiglu", positions="rotary", rope_style="half",
        rotary_dim=8, attn_bias=False, mlp_bias=False,
        tie_word_embeddings=False, dtype="float32",
    )
    mesh = make_mesh(MeshPlan(dp=2, tp=4))
    params = init_params(cfg, mesh, jax.random.key(5))
    return cfg, mesh, params


def test_logits_close_to_fp_cache(cfg_and_params):
    """Decoding on the int8 cache must track the full-precision cache to
    quantization-noise tolerance (the model compute itself is untouched —
    only stored K/V round through int8)."""
    cfg, mesh, params = cfg_and_params
    prompts = [[5, 9, 23, 40, 17, 2], [3, 14, 15]]
    gen = GenerationParams(max_new_tokens=6, is_greedy=True)

    logits = {}
    for kv in (None, "int8"):
        engine = DecodeEngine(
            cfg, params, mesh, max_seq_len=64, kv_dtype=kv,
        )
        ids, lens = engine._pad_prompts(prompts)
        sa = engine._sample_args(gen, 2)
        cache = engine.new_cache(2)
        tok, lg, cache = engine._prefill(
            engine.params, jnp.asarray(ids), cache, jnp.asarray(lens), sa,
        )
        # a few decode steps so quantized reads feed later logits
        cur = jnp.asarray(lens)
        for _ in range(4):
            tok, lg, cache = engine._decode(
                engine.params, tok, cache, cur, sa
            )
            cur = cur + 1
        logits[kv] = np.asarray(lg, np.float32)

    scale = np.abs(logits[None]).max()
    err = np.abs(logits["int8"] - logits[None]).max()
    assert err < 0.05 * scale, (err, scale)


def test_full_stack_on_int8_cache(cfg_and_params):
    """generate / generate_fused / continuous batching all run on the
    quantized cache and agree with each other token-for-token."""
    from llmss_tpu.engine.scheduler import ContinuousBatcher

    cfg, mesh, params = cfg_and_params
    engine = DecodeEngine(
        cfg, params, mesh, max_seq_len=64, kv_dtype="int8",
    )
    prompts = [[5, 9, 23, 40], [3, 14, 15, 9, 26, 5]]
    gen = GenerationParams(max_new_tokens=8, is_greedy=True)

    streamed = engine.generate(prompts, gen)
    fused = engine.generate_fused(prompts, gen)
    chunked = engine.generate(prompts, gen, chunk_steps=4)
    assert streamed == fused == chunked
    assert all(len(o) == 8 for o in streamed)

    results = {}
    batcher = ContinuousBatcher(engine, rows=2, chunk_steps=2)
    for i, p in enumerate(prompts):
        batcher.submit(
            p, gen, lambda t, c=False, i=i: results.__setitem__(i, t)
        )
    batcher.run_until_idle()
    assert results[0] == streamed[0] and results[1] == streamed[1]


def test_int8_on_sp_mesh_matches_tp_only(devices):
    """int8 composes with sequence parallelism: greedy generation on a
    dp×sp×tp mesh must produce the same tokens as the tp-only mesh with
    the same int8 cache (the sp decode path pre-dequantizes each layer
    before the shard_map'd LSE merge; prefill rides ring attention over
    the dequantized slices)."""
    cfg = DecoderConfig(
        model_type="llama", vocab_size=256, hidden_size=64, n_layers=2,
        n_heads=8, n_kv_heads=4, head_dim=8, intermediate_size=128,
        max_position_embeddings=128, activation="silu", norm="rmsnorm",
        norm_eps=1e-5, mlp="swiglu", positions="rotary", rope_style="half",
        rotary_dim=8, attn_bias=False, mlp_bias=False,
        tie_word_embeddings=False, dtype="float32",
    )
    prompts = [list(range(1, 30)), [7, 8, 9]]
    gen = GenerationParams(max_new_tokens=6, is_greedy=True)

    mesh_tp = make_mesh(MeshPlan(dp=1, sp=1, tp=8))
    params_tp = init_params(cfg, mesh_tp, jax.random.key(0))
    ref = DecodeEngine(
        cfg, params_tp, mesh_tp, max_seq_len=64, kv_dtype="int8"
    ).generate(prompts, gen)

    mesh_sp = make_mesh(MeshPlan(dp=2, sp=2, tp=2))
    params_sp = init_params(cfg, mesh_sp, jax.random.key(0))
    out = DecodeEngine(
        cfg, params_sp, mesh_sp, max_seq_len=64, kv_dtype="int8"
    ).generate(prompts, gen)
    assert out == ref


def test_int8_serving_end_to_end(cfg_and_params):
    """The quantized cache composes with the serving stack: continuous
    worker + prewarm + chunked decode + streaming; the served tokens match
    a solo engine.generate of the same request."""
    import time

    from llmss_tpu.serve import GenerateRequest, InProcBroker
    from llmss_tpu.serve.consumer import ContinuousWorker

    cfg, mesh, params = cfg_and_params
    engine = DecodeEngine(cfg, params, mesh, max_seq_len=64,
                          kv_dtype="int8")
    broker = InProcBroker()
    worker = ContinuousWorker(engine, broker, rows=2, poll_timeout_s=0.01,
                              chunk_steps=2)
    worker.prewarm()  # the batcher envelope must compile on the int8 cache

    broker.push_request(GenerateRequest(
        id="a", token_ids=[5, 9, 23], max_new_tokens=6, is_greedy=True,
    ))
    broker.push_request(GenerateRequest(
        id="b", token_ids=[3, 14], max_new_tokens=6, is_greedy=True,
        stream=True,
    ))
    got, streamed = {}, []
    deadline = time.time() + 120
    while len(got) < 2 and time.time() < deadline:
        worker.run_once()
        while True:
            inc = broker.pop_stream("b")
            if inc is None:
                break
            streamed.extend(inc)
        for rid in ("a", "b"):
            if rid not in got:
                r = broker.wait_response(rid, timeout=0.001)
                if r is not None:
                    got[rid] = r
    assert set(got) == {"a", "b"}
    assert got["a"].error is None and len(got["a"].token_ids) == 6
    assert streamed == got["b"].token_ids
    # Same request solo through the engine matches the served tokens.
    solo = engine.generate([[5, 9, 23]], GenerationParams(
        max_new_tokens=6, is_greedy=True,
    ))
    assert solo[0] == got["a"].token_ids
