"""Deterministic fleet simulator (llmss_tpu/sim): virtual-clock storms
over the real serving stack, byte-identical replays, and the fleet-wide
invariant catalog.

Every test here runs the REAL broker / router / brownout / preemption
code under the sim's virtual clock — the sim never mocks them — so a
green run certifies the serving stack, not a model of it. Scenarios are
dicts (the JSON file format, inline); ``run_scenario`` raises
``InvariantViolation`` if any request is lost, double-answered, refunded
wrong, or dead-lettered without being poison.
"""

import copy
import json

import pytest

from llmss_tpu.serve.broker import InProcBroker, RedisBroker
from llmss_tpu.serve.chaos import ChaosBroker, FakeRedis
from llmss_tpu.serve.protocol import GenerateRequest
from llmss_tpu.sim import DeviceCostModel, FleetSim, run_scenario

FMT = "llmss-scenario/1"


def smoke_spec(**over):
    spec = {
        "format": FMT,
        "name": "smoke",
        "seed": 7,
        "duration_s": 120.0,
        "broker": {"kind": "inproc"},
        "fleet": {"replicas": [{"count": 2, "role": "unified"}]},
        "workload": {
            "kind": "synthetic", "requests": 200, "rate_rps": 40.0,
            "prompt_len": [8, 64], "max_new": [4, 24],
        },
    }
    spec.update(over)
    return spec


def gauntlet_spec(broker_kind, seed, requests=600):
    """Mixed unified+disagg fleet, all five fault kinds, poison."""
    return {
        "format": FMT,
        "name": f"gauntlet-{broker_kind}",
        "seed": seed,
        "duration_s": 120.0,
        "broker": {
            "kind": broker_kind, "lease_s": 2.0, "max_delivery_attempts": 8,
        },
        "fleet": {
            "replicas": [
                {"count": 4, "role": "unified"},
                {"count": 2, "role": "prefill"},
                {"count": 2, "role": "decode"},
            ],
            "router_policy": "least_loaded",
            "failover_check_s": 1.0,
        },
        "workload": {
            "kind": "synthetic", "requests": requests, "rate_rps": 120.0,
            "prompt_len": [8, 96], "max_new": [4, 32],
            "classes": {"interactive": 0.3, "standard": 0.5, "batch": 0.2},
            "deadline_s": {"interactive": 30.0},
            "poison_every": 200,
        },
        "faults": [
            {"kind": "kill_wave", "at_s": 4.0, "count": 2,
             "respawn_after_s": 1.0, "repeat_every_s": 5.0},
            {"kind": "partition", "at_s": 6.0, "duration_s": 2.5,
             "targets": 1},
            {"kind": "latency_spike", "at_s": 9.0, "duration_s": 3.0,
             "extra_s": 0.08, "targets": "*"},
            {"kind": "heartbeat_stall", "at_s": 11.0, "duration_s": 4.0,
             "count": 1},
            {"kind": "handoff_storm", "at_s": 7.5, "count": 1,
             "respawn_after_s": 0.8, "repeat_every_s": 7.0},
        ],
    }


def run_twice(spec):
    """Same seed twice; the whole report must be byte-identical."""
    a = json.dumps(run_scenario(copy.deepcopy(spec)), sort_keys=True)
    b = json.dumps(run_scenario(copy.deepcopy(spec)), sort_keys=True)
    assert a == b, "same-seed scenario replay diverged"
    return json.loads(a)


# -- determinism + smoke -----------------------------------------------------


def test_smoke_deterministic_and_clean():
    r = run_twice(smoke_spec())
    assert r["requests"]["submitted"] == 200
    assert r["requests"]["ok"] == 200
    assert r["invariants"]["violations"] == 0
    assert r["invariants"]["pending_at_drain"] == 0
    assert r["throughput"]["tokens_out"] > 0
    assert r["latency_ms"]["ttft_p95"] > 0


def test_different_seed_different_run():
    a = run_scenario(smoke_spec(seed=7))
    b = run_scenario(smoke_spec(seed=8))
    assert a["latency_ms"] != b["latency_ms"]


def test_bad_format_rejected():
    with pytest.raises(ValueError, match="format"):
        FleetSim({"format": "llmss-scenario/999"})


# -- fault gauntlets over both brokers ---------------------------------------


def test_gauntlet_inproc():
    r = run_twice(gauntlet_spec("inproc", seed=11))
    reqs = r["requests"]
    # Every non-poison request answered OK despite kills, partitions,
    # stalls, and handoff storms; only poison dead-letters.
    assert reqs["answered"] == reqs["submitted"]
    assert reqs["dead_lettered"] == 600 // 200
    assert reqs["ok"] == reqs["submitted"] - reqs["dead_lettered"]
    assert r["faults"]["kills"] > 0
    assert r["faults"]["partitions"] > 0
    assert r["delivery"]["redelivered"] > 0
    assert r["delivery"]["handoffs"] > 0


def test_gauntlet_fakeredis():
    """Same storm through the real RedisBroker code paths (per-worker
    lease keys, SCAN reaper, DLQ list) on the virtual-clock FakeRedis."""
    r = run_twice(gauntlet_spec("fakeredis", seed=3, requests=400))
    reqs = r["requests"]
    assert reqs["answered"] == reqs["submitted"]
    assert reqs["dead_lettered"] == 400 // 200
    assert r["faults"]["kills"] > 0


# -- targeted fault semantics ------------------------------------------------


def test_preemption_refund_keeps_exactly_once():
    """Batch rows evicted for interactive arrivals come back through the
    preemption-refund path (no delivery attempt consumed) and every
    request still completes cleanly."""
    spec = smoke_spec(
        name="preempt",
        fleet={"replicas": [{
            "count": 1, "role": "unified", "rows": 2, "preempt": True,
        }]},
        workload={
            "kind": "synthetic", "requests": 120, "rate_rps": 60.0,
            "prompt_len": [4, 16], "max_new": [8, 24],
            "classes": {"interactive": 0.5, "batch": 0.5},
        },
    )
    r = run_twice(spec)
    assert r["faults"]["preemptions"] > 0
    assert r["delivery"]["preempted"] > 0
    assert r["requests"]["ok"] == r["requests"]["submitted"]


def test_handoff_storm_reprefills():
    """Killing prefill replicas mid-handoff forces re-prefill via lease
    redelivery; nothing is lost and nothing lands in the DLQ."""
    spec = smoke_spec(
        name="handoff-storm",
        fleet={"replicas": [
            {"count": 2, "role": "prefill"},
            {"count": 2, "role": "decode"},
        ]},
        faults=[{"kind": "handoff_storm", "at_s": 1.0, "count": 1,
                 "respawn_after_s": 0.5, "repeat_every_s": 2.0}],
    )
    r = run_twice(spec)
    assert r["delivery"]["handoffs"] > 0
    assert r["faults"]["kills"] > 0
    assert r["requests"]["ok"] == r["requests"]["submitted"]
    assert r["delivery"]["dead_lettered"] == 0


# -- workload replay ---------------------------------------------------------


def test_workload_file_replay(tmp_path):
    """Native replay of an llmss-workload/1 capture: arrivals, lengths,
    classes, and session ids replay verbatim."""
    doc = {
        "format": "llmss-workload/1",
        "requests": [
            {
                "req_id": f"cap{i}", "arrival_s": i * 0.05,
                "prompt_len": 8 + i, "max_new_tokens": 6,
                "slo_class": "interactive" if i % 2 else "standard",
                "session_id": f"sess-{i % 3}" if i % 2 else None,
            }
            for i in range(40)
        ],
    }
    path = tmp_path / "capture.json"
    path.write_text(json.dumps(doc))
    spec = smoke_spec(
        name="replay",
        workload={"kind": "workload-file", "path": str(path)},
    )
    r = run_twice(spec)
    assert r["requests"]["submitted"] == 40
    assert r["requests"]["ok"] == 40


def test_trace_workload_inline_rows():
    spec = smoke_spec(
        name="trace",
        workload={"kind": "trace", "rows": [
            {"arrival_s": 0.0, "token_ids": [5, 6, 7], "max_new": 4,
             "slo_class": "interactive", "id": "t-a"},
            {"arrival_s": 0.2, "prompt_len": 12, "max_new": 8,
             "session_id": "s0"},
        ]},
    )
    r = run_twice(spec)
    assert r["requests"]["submitted"] == 2
    assert r["requests"]["ok"] == 2


# -- cost model --------------------------------------------------------------


def test_cost_model_devtel_seeding():
    """Devtel seeding prices sim time from the same roofline as the
    MFU/MBU accounting; on CPU the peaks resolve deterministically."""
    m = DeviceCostModel.from_config({"kind": "devtel"})
    assert m.seeded_from.startswith("devtel")
    assert m.decode_step_s > 0 and m.prefill_token_s > 0
    # Seeding is deterministic, so devtel-seeded scenarios replay too.
    m2 = DeviceCostModel.from_config({"kind": "devtel"})
    assert m.describe() == m2.describe()


def test_cost_model_table_overrides():
    m = DeviceCostModel.from_config(
        {"kind": "table", "decode_step_s": 0.02, "prefill_token_s": 1e-4}
    )
    assert m.decode_step_s == 0.02
    assert m.step_s(4, feeding_tokens=10) == pytest.approx(0.02 + 10e-4)
    assert m.kv_blocks(17, 16) == 3  # ceil(33 / 16)


# -- broker fault plumbing (satellites: retry + partition/latency) -----------


def test_redis_broker_retries_transient_connection_errors():
    """Two injected connection failures on the pop path are absorbed by
    the capped-backoff retry loop and surface in delivery_stats."""
    server = FakeRedis()
    fail = {"left": 2}

    def hook(op):
        if op == "rpop" and fail["left"] > 0:
            fail["left"] -= 1
            raise ConnectionError("injected blip")

    server.fault_hook = hook
    b = RedisBroker(client=server, worker_id="w0", retry_base_s=0.001)
    b.push_request(GenerateRequest(token_ids=[1], max_new_tokens=2))
    req = b.pop_request(timeout=0.0)
    assert req is not None
    assert b.delivery_stats()["broker_retries"] == 2


def test_redis_broker_retry_budget_exhausts():
    server = FakeRedis()
    server.fault_hook = lambda op: (_ for _ in ()).throw(
        ConnectionError("down hard")
    )
    b = RedisBroker(
        client=server, worker_id="w0", retry_attempts=2, retry_base_s=0.001,
    )
    with pytest.raises(ConnectionError):
        b.pop_request(timeout=0.0)


def test_chaos_broker_partition_window_and_latency():
    inner = InProcBroker()
    cb = ChaosBroker(inner, seed=1, op_latency_s=0.0)
    cb.partition_for(0.15)
    with pytest.raises(ConnectionError):
        cb.pop_request(timeout=0.0)
    assert cb.faults["partition_errors"] == 1
    cb._partition_until = 0.0  # close the window
    inner.push_request(GenerateRequest(token_ids=[1], max_new_tokens=2))
    cb.op_latency_s = 0.001
    assert cb.pop_request(timeout=0.0) is not None
    assert cb.faults["latency_injections"] >= 1
