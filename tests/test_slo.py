"""SLO plane (``utils/metrics.py`` windowed layer + cost attribution).

The tentpole claims pinned here:

- windowed rings rotate on monotonic epochs (slot reuse resets the
  window, never the cumulative total) and merge across process exports
  against each export's OWN mono anchor — no cross-host clock compare;
- burn rates come out of windowed fleet-aggregated series and match the
  hand-computed ``(1 - attainment) / (1 - target)`` on a pinned export;
- a chaos-killed decode replica's requests are cost-attributed exactly
  once, on BOTH broker shapes — the settling ``push_response`` is the
  single ingestion point;
- the trace-to-workload export replays through a stub arrival-process
  consumer and re-serves through a fresh broker pair;
- the producer surfaces it all (``/slo``, ``/fleet/timeseries``,
  ``/trace/slowest?phase=``, ``/trace/export_workload``, Prometheus
  ``_bucket`` families) and ``LLMSS_TRACE=0`` records nothing;
- plane ingestion is host-side only: zero steady-state recompiles.
"""

import json
import sys
import threading
import time
from pathlib import Path

import httpx
import pytest

from llmss_tpu.analysis import cli as lint_cli
from llmss_tpu.serve.broker import InProcBroker, RedisBroker
from llmss_tpu.serve.chaos import (
    ChaosWorkerHost,
    FakeRedis,
    HardKill,
    ScriptedEngine,
)
from llmss_tpu.serve.handoff import DecodeWorker, PrefillWorker
from llmss_tpu.serve.producer import ProducerServer
from llmss_tpu.serve.protocol import GenerateRequest
from llmss_tpu.utils import metrics, trace
from llmss_tpu.utils.metrics import (
    DEFAULT_BOUNDS_S,
    SeriesRegistry,
    WindowedCounter,
    WindowedHistogram,
)

REPO_ROOT = Path(__file__).resolve().parents[1]

BROKER_KINDS = ("inproc", "fakeredis")


def make_brokers(kind, **kw):
    if kind == "inproc":
        b = InProcBroker(**kw)
        return b, (lambda wid: b)
    server = FakeRedis()

    def mk(wid):
        return RedisBroker(client=server, worker_id=wid, **kw)

    return mk("producer"), mk


@pytest.fixture(autouse=True)
def clean_plane():
    """Each test starts from an empty recorder AND series registry."""
    trace.set_enabled(True)
    trace.recorder().clear()
    metrics.series().clear()
    yield
    trace.set_enabled(True)
    trace.recorder().clear()
    metrics.series().clear()


def _run_to_completion(b, workers, reqs, timeout_s=20.0):
    got = {}
    deadline = time.monotonic() + timeout_s
    while len(got) < len(reqs) and time.monotonic() < deadline:
        for w in workers:
            w.run_once()
        for r in reqs:
            if r.id not in got:
                resp = b.wait_response(r.id, timeout=0.01)
                if resp is not None:
                    got[r.id] = resp
    return got


# -- windowed ring mechanics --------------------------------------------------


def test_counter_ring_rotation_resets_window_not_total():
    c = WindowedCounter("c", n_buckets=4, bucket_s=1.0)
    c.add(1.0, t=0.5)   # epoch 0 -> slot 0
    c.add(2.0, t=1.5)   # epoch 1 -> slot 1
    assert c.window_sum(4.0, now=2.0) == 3.0
    # Epoch 4 wraps onto slot 0: the stale epoch-0 value is lazily reset
    # out of the window, but the cumulative total keeps it.
    c.add(5.0, t=4.5)
    assert c.total == 8.0
    assert c.window_sum(10.0, now=5.0) == 7.0
    # A narrow trailing window sees only the newest slot.
    assert c.window_sum(1.0, now=4.9) == 5.0
    ex = c.export()
    assert ex["kind"] == "counter" and ex["total"] == 8.0
    assert ex["slots"] == [[1, 2.0], [4, 5.0]]


def test_histogram_ring_windows_and_cumulative_totals():
    h = WindowedHistogram("h", bounds=(0.1, 1.0), n_buckets=4, bucket_s=1.0)
    h.observe(0.05, t=0.5)   # le 0.1
    h.observe(0.5, t=1.5)    # le 1.0
    h.observe(5.0, t=1.6)    # +inf tail
    w = h.window_counts(2.0, now=2.0)
    assert w["count"] == 3 and w["counts"] == [1, 1, 1]
    assert abs(w["sum"] - 5.55) < 1e-9
    # Only the epoch-1 slot is live in a 1 s trailing window at t=2.5.
    w1 = h.window_counts(1.0, now=2.5)
    assert w1["count"] == 2 and w1["counts"] == [0, 1, 1]
    # Ring wrap (epoch 4 -> slot 0) resets the slot, not the cumulatives.
    h.observe(0.05, t=4.5)
    assert h.total_count == 4 and h.total_counts == [2, 1, 1]
    assert h.window_counts(1.0, now=5.0)["counts"] == [1, 0, 0]
    ex = h.export()
    assert ex["total"]["count"] == 4
    assert [s[0] for s in ex["slots"]] == [1, 4]


def test_bound_edges_use_le_semantics():
    h = WindowedHistogram("h", bounds=(0.1, 1.0), n_buckets=4, bucket_s=1.0)
    h.observe(0.1, t=0.5)   # exactly on a bound -> that bucket (le)
    h.observe(1.0, t=0.5)
    assert h.total_counts == [1, 1, 0]


def test_merged_window_respects_each_exports_own_anchor():
    """Two processes with wildly different monotonic epochs (uptime 1000 s
    vs 50 s): each export's slots are judged live against its OWN anchor,
    so the merge needs no cross-host clock agreement."""
    ex_a = {
        "proc": "pA", "mono_anchor": 1000.0, "wall_anchor": 5000.0,
        "series": {"c": {
            "kind": "counter", "bucket_s": 10.0, "total": 9.0,
            # epoch 99 ends at 1000 (live @5m); epoch 60 ends at 610
            # (dead @5m, live @1h).
            "slots": [[60, 4.0], [99, 3.0]],
        }},
    }
    ex_b = {
        "proc": "pB", "mono_anchor": 50.0, "wall_anchor": 5000.2,
        "series": {"c": {
            "kind": "counter", "bucket_s": 10.0, "total": 2.0,
            "slots": [[4, 2.0]],  # ends at 50 == pB's anchor: live
        }},
    }
    assert metrics.merged_window([ex_a, ex_b], "c", 300.0)["value"] == 5.0
    assert metrics.merged_window([ex_a, ex_b], "c", 3600.0)["value"] == 9.0
    assert metrics.merged_window([ex_a, ex_b], "nope", 300.0) is None
    # The same process arriving via several heartbeats counts once.
    assert len(metrics.dedup_series_exports([ex_a, ex_a, ex_b])) == 2


def test_registry_export_is_anchored_and_cached():
    reg = SeriesRegistry(proc="t")
    reg.counter("c").add(1.0)
    ex = reg.export(cache_s=60.0)
    assert "mono_anchor" in ex and "wall_anchor" in ex and ex["proc"] == "t"
    # Within cache_s the SAME blob comes back — the heartbeat path never
    # re-snapshots per worker tick.
    assert reg.export(cache_s=60.0) is ex
    assert reg.export(cache_s=0.0) is not ex


def test_metrics_module_is_wall_clock_clean():
    """The windowed layer must live on monotonic time: graftlint's
    wall-clock-timer rule stays silent on it (wall_anchor is the one
    exempted wall read per export)."""
    _code, findings = lint_cli.run(
        [str(REPO_ROOT / "llmss_tpu" / "utils" / "metrics.py"),
         str(REPO_ROOT / "llmss_tpu" / "utils" / "trace.py")],
        baseline_path=None,
    )
    assert not [f for f in findings if f.rule == "wall-clock-timer"]


# -- burn-rate math vs hand-computed ------------------------------------------


def _pinned_slo_exports():
    """One synthetic export, anchored at mono 1000.0 with all slots live:
    10 ttft observations (5 at <=0.5 s, 5 at <=1.0 s), 10 requests, 1
    error. Hand-computed vs target 0.95 / 0.999:

    - ttft attainment 0.5 -> burn (1-0.5)/0.05 = 10.0, p95 = 1.0 s
    - error attainment 0.9 -> burn 0.1/0.001 = 100.0
    """
    counts = [0] * (len(DEFAULT_BOUNDS_S) + 1)
    counts[DEFAULT_BOUNDS_S.index(0.5)] = 5
    counts[DEFAULT_BOUNDS_S.index(1.0)] = 5
    return [{
        "proc": "pA", "mono_anchor": 1000.0, "wall_anchor": 5000.0,
        "series": {
            "ttft_s": {
                "kind": "histogram", "bucket_s": 10.0,
                "bounds": list(DEFAULT_BOUNDS_S),
                "total": {"count": 10, "sum": 6.0, "counts": counts},
                "slots": [[99, 10, 6.0, counts]],
            },
            "requests_total": {
                "kind": "counter", "bucket_s": 10.0, "total": 10.0,
                "slots": [[99, 10.0]],
            },
            "requests_error": {
                "kind": "counter", "bucket_s": 10.0, "total": 1.0,
                "slots": [[99, 1.0]],
            },
        },
    }]


def test_burn_rates_match_hand_computed():
    out = metrics.evaluate_slos(_pinned_slo_exports())
    assert out["windows"] == {"5m": 300.0, "1h": 3600.0}
    rows = {r["name"]: r for r in out["objectives"]}
    assert set(rows) == {"ttft_p95_500ms", "e2e_p95_5s",
                         "terminal_error_rate",
                         "ttft_p95_500ms_interactive",
                         "ttft_p95_2s_standard",
                         "ttft_p95_15s_batch"}

    ttft = rows["ttft_p95_500ms"]
    for w in ("5m", "1h"):
        cell = ttft["windows"][w]
        assert cell["count"] == 10
        assert cell["attainment"] == 0.5
        assert cell["burn_rate"] == 10.0
        assert cell["p95_ms"] == 1000.0
    assert ttft["met"] is False

    err = rows["terminal_error_rate"]
    cell = err["windows"]["5m"]
    assert cell["count"] == 10 and cell["bad"] == 1
    assert cell["attainment"] == 0.9
    assert cell["burn_rate"] == 100.0
    assert err["met"] is False

    # No e2e_s series in the exports: the objective reports empty windows
    # rather than inventing attainment from nothing.
    e2e = rows["e2e_p95_5s"]
    assert e2e["windows"]["5m"]["attainment"] is None
    assert e2e["met"] is None


def test_clean_window_burns_nothing():
    exports = _pinned_slo_exports()
    exports[0]["series"]["requests_error"]["slots"] = []
    rows = {
        r["name"]: r for r in metrics.evaluate_slos(exports)["objectives"]
    }
    cell = rows["terminal_error_rate"]["windows"]["5m"]
    assert cell["attainment"] == 1.0 and cell["burn_rate"] == 0.0
    assert rows["terminal_error_rate"]["met"] is True


def test_observe_request_cost_feeds_every_sink():
    reg = SeriesRegistry(proc="t")
    cost = {
        "req_id": "r", "ok": True, "error": None, "total_s": 0.8,
        "ttft_s": 0.2, "queue_wait_s": 0.05, "prefill_s": 0.1,
        "decode_s": 0.4, "handoff_s": 0.01, "handoff_bytes": 4096,
        "tokens": 32, "kv_block_s": 1.5, "attempts": 1, "reprefills": 0,
    }
    metrics.observe_request_cost(cost, registry=reg)
    metrics.observe_request_cost({**cost, "ok": False, "error": "boom",
                                  "reprefills": 2}, registry=reg)
    assert reg.counter("requests_total").total == 2.0
    assert reg.counter("requests_error").total == 1.0
    assert reg.counter("tokens_out").total == 64.0
    assert reg.counter("handoff_bytes").total == 8192.0
    assert reg.counter("kv_block_seconds").total == 3.0
    assert reg.counter("reprefills").total == 2.0
    assert reg.histogram("e2e_s").total_count == 2
    assert reg.histogram("ttft_s").total_count == 2
    assert abs(reg.histogram("decode_s").total_sum - 0.8) < 1e-9
    # A cost record missing optional phases (no handoff) skips those
    # sinks instead of polluting them with zeros.
    metrics.observe_request_cost(
        {"req_id": "r2", "ok": True, "total_s": 0.1, "ttft_s": None,
         "handoff_s": None, "tokens": None}, registry=reg,
    )
    assert reg.counter("requests_total").total == 3.0
    assert reg.histogram("ttft_s").total_count == 2
    assert reg.histogram("handoff_s").total_count == 2


# -- exactly-once cost attribution under chaos --------------------------------


class _KillOnAdopt(ScriptedEngine):
    """First N adoptions die mid-adopt with the handoff lease open."""

    def __init__(self, kills: int):
        super().__init__()
        self._kills_left = kills
        self._klock = threading.Lock()

    def adopt_generate(self, *a, **kw):
        with self._klock:
            if self._kills_left > 0:
                self._kills_left -= 1
                raise HardKill("chaos: decode replica died mid-adopt")
        return super().adopt_generate(*a, **kw)


@pytest.mark.parametrize("kind", BROKER_KINDS)
def test_chaos_kill_attributes_cost_exactly_once(kind):
    """Two decode replicas die mid-handoff; every request still settles
    and produces exactly ONE cost record — requests_total equals the
    request count, with the killed attempts' reprefills folded into the
    surviving record rather than spawning extra ones."""
    b, mk = make_brokers(kind, lease_s=0.25, max_delivery_attempts=6)
    eng = _KillOnAdopt(2)
    pre = ChaosWorkerHost(
        lambda: PrefillWorker(
            ScriptedEngine(), mk("p0"), worker_id="p0", poll_timeout_s=0.02,
        ),
        respawn_delay_s=0.02,
    )
    dec = ChaosWorkerHost(
        lambda: DecodeWorker(
            eng, mk("d0"), worker_id="d0", poll_timeout_s=0.02,
        ),
        respawn_delay_s=0.02,
    )
    reqs = [
        GenerateRequest(
            id=f"c{i}", token_ids=[i + 2, 9], max_new_tokens=4,
            deadline_ts=time.time() + 30.0,
        )
        for i in range(4)
    ]
    pre.start()
    dec.start()
    try:
        for r in reqs:
            b.push_request(r)
        for r in reqs:
            resp = b.wait_response(r.id, timeout=20.0)
            assert resp is not None, f"lost {r.id}"
            assert resp.error is None, (r.id, resp.error)
    finally:
        pre.stop()
        dec.stop()
    assert pre.error is None and dec.error is None
    assert dec.kills == 2

    # Exactly-once: one ingestion per request, none for dead attempts.
    reg = metrics.series()
    assert reg.counter("requests_total").total == len(reqs)
    assert reg.counter("requests_error").total == 0.0
    assert reg.histogram("e2e_s").total_count == len(reqs)
    assert reg.counter("reprefills").total == 2.0

    costs = trace.derive_costs([trace.recorder().export()])
    by_id = {c["req_id"]: c for c in costs}
    assert set(by_id) == {r.id for r in reqs}  # one record per request
    assert len(costs) == len(reqs)
    assert all(c["ok"] for c in costs)
    assert sum(c["reprefills"] for c in costs) == 2
    for c in costs:
        assert c["total_s"] >= 0.0 and c["tokens"]
        if c["reprefills"]:
            # The killed request's record carries its full delivery story.
            assert c["attempts"] >= 2
    # The windowed view agrees with the trace-derived one.
    assert reg.counter("tokens_out").total == sum(c["tokens"] for c in costs)


def test_error_response_attributed_as_error():
    b, mk = make_brokers("inproc", lease_s=2.0)
    from llmss_tpu.serve.protocol import GenerateResponse

    trace.record("bad", "enqueue", trace_id="bad")
    b.push_response(GenerateResponse(id="bad", token_ids=[], error="boom"))
    reg = metrics.series()
    assert reg.counter("requests_total").total == 1.0
    assert reg.counter("requests_error").total == 1.0


# -- trace-to-workload export and replay --------------------------------------


def _tools():
    sys.path.insert(0, str(REPO_ROOT / "tools"))
    try:
        import trace_workload
    finally:
        sys.path.pop(0)
    return trace_workload


def _serve(reqs, kind="inproc", **kw):
    b, mk = make_brokers(kind, lease_s=5.0, **kw)
    pre = PrefillWorker(ScriptedEngine(), mk("p0"), worker_id="p0")
    dec = DecodeWorker(ScriptedEngine(), mk("d0"), worker_id="d0")
    for r in reqs:
        b.push_request(r)
    got = _run_to_completion(b, [pre, dec], reqs)
    assert len(got) == len(reqs)
    return b


@pytest.mark.parametrize("kind", BROKER_KINDS)
def test_workload_export_roundtrip_through_stub_consumer(tmp_path, kind):
    shared = [7] * 8
    reqs = [
        GenerateRequest(id="w0", token_ids=[1, 2, 3], max_new_tokens=4,
                        prefix_token_ids=shared),
        GenerateRequest(id="w1", token_ids=[4, 5], max_new_tokens=3,
                        prefix_token_ids=shared),
        GenerateRequest(id="w2", token_ids=[6, 7, 8, 9], max_new_tokens=2),
    ]
    _serve(reqs, kind)

    wl = trace.export_workload([trace.recorder().export()])
    assert wl["format"] == trace.WORKLOAD_FORMAT
    assert wl["n_requests"] == 3
    rows = {r["req_id"]: r for r in wl["requests"]}
    assert rows["w0"]["prompt_len"] == 3 and rows["w0"]["max_new_tokens"] == 4
    assert rows["w2"]["prompt_len"] == 4 and rows["w2"]["prefix_hash"] is None
    # Prefix identity (not contents) is captured: the two sharers agree.
    assert rows["w0"]["prefix_hash"] == rows["w1"]["prefix_hash"] is not None
    arrivals = [r["arrival_s"] for r in wl["requests"]]
    assert arrivals[0] == 0.0 and arrivals == sorted(arrivals)
    assert wl["span_s"] == arrivals[-1]

    # Replay through a stub arrival-process consumer.
    tw = _tools()
    got: list = []
    assert tw.replay(wl, got.append) == 3
    assert [r.id for r in got] == [r["req_id"] for r in wl["requests"]]
    for r in got:
        assert len(r.token_ids) == rows[r.id]["prompt_len"]
        assert r.max_new_tokens == rows[r.id]["max_new_tokens"]
    by_id = {r.id: r for r in got}
    # Shared capture-time prefix -> identical synthesized replay prefix,
    # so the prefix cache sees the production hit structure.
    assert by_id["w0"].prefix_token_ids == by_id["w1"].prefix_token_ids
    assert by_id["w0"].prefix_token_ids and by_id["w2"].prefix_token_ids is None

    summary = tw.summarize(wl)
    assert summary["n_requests"] == 3 and summary["distinct_prefixes"] == 1

    # File round-trip + format guard.
    p = tmp_path / "wl.json"
    p.write_text(json.dumps(wl))
    assert tw.load_workload(str(p)) == wl
    bad = tmp_path / "bad.json"
    bad.write_text(json.dumps({"format": "nope"}))
    with pytest.raises(ValueError):
        tw.load_workload(str(bad))
    with pytest.raises(ValueError):
        tw.replay({"format": "nope"}, got.append)


def test_replayed_workload_reserves_end_to_end():
    reqs = [
        GenerateRequest(id=f"rr{i}", token_ids=[i + 1, 2, 3],
                        max_new_tokens=3)
        for i in range(3)
    ]
    _serve(reqs)
    wl = trace.export_workload([trace.recorder().export()])
    tw = _tools()

    # The captured arrival process drives a FRESH broker pair.
    trace.recorder().clear()
    metrics.series().clear()
    b, mk = make_brokers("inproc", lease_s=5.0)
    pre = PrefillWorker(ScriptedEngine(), mk("p0"), worker_id="p0")
    dec = DecodeWorker(ScriptedEngine(), mk("d0"), worker_id="d0")
    replayed: list = []

    def submit(req):
        replayed.append(req)
        b.push_request(req)

    assert tw.replay(wl, submit) == 3
    got = _run_to_completion(b, [pre, dec], replayed)
    assert len(got) == 3
    for req in replayed:
        assert got[req.id].token_ids == ScriptedEngine.expected_tokens(
            list(req.token_ids), req.max_new_tokens,
        )
    # The replay itself was cost-attributed like any other traffic.
    assert metrics.series().counter("requests_total").total == 3.0


def test_replay_paces_real_time_arrivals():
    tw = _tools()
    wl = {
        "format": trace.WORKLOAD_FORMAT, "n_requests": 2, "span_s": 0.2,
        "requests": [
            {"req_id": "a", "arrival_s": 0.0, "prompt_len": 2,
             "max_new_tokens": 1, "prefix_hash": None, "priority": None},
            {"req_id": "b", "arrival_s": 0.2, "prompt_len": 2,
             "max_new_tokens": 1, "prefix_hash": None, "priority": None},
        ],
    }
    t0 = time.monotonic()
    tw.replay(wl, lambda r: None, speed=2.0)  # 2x: ~0.1 s gap
    elapsed = time.monotonic() - t0
    assert 0.05 <= elapsed < 2.0


# -- producer endpoints -------------------------------------------------------


def test_producer_slo_plane_endpoints():
    reqs = [
        GenerateRequest(id=f"e{i}", token_ids=[i + 1, 4], max_new_tokens=3)
        for i in range(3)
    ]
    b = _serve(reqs)
    srv = ProducerServer(b, host="127.0.0.1", port=0, timeout_s=5.0)
    srv.start()
    try:
        base = f"http://127.0.0.1:{srv.port}"

        slo = httpx.get(f"{base}/slo").json()
        assert slo["windows"] == {"5m": 300.0, "1h": 3600.0}
        rows = {r["name"]: r for r in slo["objectives"]}
        err = rows["terminal_error_rate"]["windows"]["5m"]
        # Computed from the windowed series the serve pass just fed.
        assert err["count"] == 3 and err["bad"] == 0
        assert err["attainment"] == 1.0 and err["burn_rate"] == 0.0
        assert rows["e2e_p95_5s"]["windows"]["5m"]["count"] == 3

        ts = httpx.get(f"{base}/fleet/timeseries").json()["series"]
        assert "requests_total" in ts and "e2e_s" in ts
        row = ts["requests_total"]
        pts = row["sources"]["producer"]["points"]
        assert pts and sum(p["v"] for p in pts) == 3.0
        assert all("t" in p for p in pts)
        assert ts["e2e_s"]["bounds"] == list(DEFAULT_BOUNDS_S)

        sl = httpx.get(f"{base}/trace/slowest?n=5&phase=decode").json()
        for r in sl["slowest"]:
            assert r["rank_phase"] == "decode" and r["phase_s"] > 0.0
        assert {r["req_id"] for r in sl["slowest"]} == {r.id for r in reqs}
        assert httpx.get(
            f"{base}/trace/slowest?phase=never_entered",
        ).json()["slowest"] == []

        wl = httpx.get(f"{base}/trace/export_workload").json()
        assert wl["format"] == trace.WORKLOAD_FORMAT
        assert wl["n_requests"] == 3

        prom = httpx.get(f"{base}/metrics?format=prometheus")
        assert prom.status_code == 200
        assert 'llmss_e2e_s_bucket{le="' in prom.text
        assert 'llmss_e2e_s_bucket{le="+Inf"} 3' in prom.text
        assert "llmss_e2e_s_count 3" in prom.text
        assert "# TYPE llmss_requests_total counter" in prom.text
        # JSON stays the default and free of the windowed families.
        r = httpx.get(f"{base}/metrics")
        assert r.headers["content-type"].startswith("application/json")
        assert "e2e_s" not in r.json()
    finally:
        srv.stop()


# -- tracing off records nothing ----------------------------------------------


def test_plane_disabled_records_nothing():
    trace.set_enabled(False)
    reqs = [GenerateRequest(id="off", token_ids=[3, 4], max_new_tokens=3)]
    b, mk = make_brokers("inproc", lease_s=2.0)
    pre = PrefillWorker(ScriptedEngine(), mk("p0"), worker_id="p0")
    dec = DecodeWorker(ScriptedEngine(), mk("d0"), worker_id="d0")
    for r in reqs:
        b.push_request(r)
    got = _run_to_completion(b, [pre, dec], reqs, timeout_s=10.0)
    assert got and got["off"].token_ids
    # No recorder entries, no series, no cost records.
    assert trace.recorder().req_ids() == []
    assert metrics.series().names() == []
    assert trace.derive_costs([trace.recorder().export()]) == []
    srv = ProducerServer(b, host="127.0.0.1", port=0, timeout_s=5.0)
    srv.start()
    try:
        base = f"http://127.0.0.1:{srv.port}"
        rows = httpx.get(f"{base}/slo").json()["objectives"]
        assert all(
            c["attainment"] is None
            for r in rows for c in r["windows"].values()
        )
        assert httpx.get(f"{base}/trace/export_workload").json()[
            "n_requests"] == 0
    finally:
        srv.stop()


# -- plane ingestion adds zero steady-state recompiles ------------------------

import jax  # noqa: E402

from llmss_tpu.engine import DecodeEngine, GenerationParams  # noqa: E402
from llmss_tpu.engine.scheduler import ContinuousBatcher  # noqa: E402
from llmss_tpu.models.common import DecoderConfig  # noqa: E402
from llmss_tpu.models.decoder import init_params  # noqa: E402
from llmss_tpu.parallel import MeshPlan, make_mesh  # noqa: E402


def test_slo_plane_adds_no_steady_state_recompiles(devices):
    """Cost derivation, series ingestion, export, and SLO evaluation are
    host-side only: running the whole plane against a warmed engine hits
    the jit caches exactly as before — zero new compiles."""
    from llmss_tpu.analysis import CompileGuard

    cfg = DecoderConfig(
        model_type="llama", vocab_size=64, hidden_size=32, n_layers=2,
        n_heads=4, n_kv_heads=2, head_dim=8, intermediate_size=64,
        max_position_embeddings=64, activation="silu", norm="rmsnorm",
        norm_eps=1e-5, mlp="swiglu", positions="rotary", rope_style="half",
        rotary_dim=8, attn_bias=False, mlp_bias=False,
        tie_word_embeddings=False, dtype="float32",
    )
    mesh = make_mesh(MeshPlan(dp=2, tp=4))
    params = init_params(cfg, mesh, jax.random.key(0))
    engine = DecodeEngine(cfg, params, mesh, max_seq_len=64)
    batcher = ContinuousBatcher(
        engine, rows=2, chunk_steps=2, group_chunks=2,
    )
    batcher.prewarm()
    gen = GenerationParams(max_new_tokens=4, is_greedy=True)

    guard = CompileGuard.for_engine(engine)
    assert guard._fns, "engine exposes no jitted callables to guard"
    got = {}
    with guard.steady_state():
        for i, p in enumerate([[5, 9], [3, 14, 15]]):
            batcher.submit(
                p, gen, lambda t, i=i: got.__setitem__(i, t),
                req_id=f"s{i}",
            )
        batcher.run_until_idle()
        # The full plane, inside the guard: derive + ingest + evaluate.
        for i in range(2):
            trace.record(f"s{i}", "respond", ok=True)
            cost = trace.local_cost(f"s{i}")
            assert cost is not None
            metrics.observe_request_cost(cost)
        payload = metrics.evaluate_slos([metrics.series().export()])
    assert len(got) == 2
    assert metrics.series().counter("requests_total").total == 2.0
    assert payload["objectives"]
