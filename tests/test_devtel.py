"""Device telemetry plane (utils/devtel.py): roofline cost accounting,
compile forensics, counter tracks, and the observability wiring that
rides along with it (/profile slot stealing, Prometheus label escaping).

CPU-backed like every tier-1 suite: MFU/MBU magnitudes are meaningless
off-TPU (tiny model vs v5e peaks), but the CONTRACTS under test —
(0, 1] bounds, cache-vs-fallback provenance, steady-state recompile
flagging, Chrome counter-event schema — are platform-independent.
"""

import time

import pytest

from llmss_tpu.serve.broker import InProcBroker
from llmss_tpu.serve.producer import ProducerServer
from llmss_tpu.utils import devtel, trace
from llmss_tpu.utils import metrics as metrics_mod

import jax  # noqa: E402
import jax.numpy as jnp  # noqa: E402

from llmss_tpu.engine import DecodeEngine, GenerationParams  # noqa: E402
from llmss_tpu.engine.scheduler import ContinuousBatcher  # noqa: E402
from llmss_tpu.models.common import DecoderConfig  # noqa: E402
from llmss_tpu.models.decoder import init_params  # noqa: E402
from llmss_tpu.parallel import MeshPlan, make_mesh  # noqa: E402


@pytest.fixture(autouse=True)
def clean_devtel():
    """Every test starts with tracing+devtel on and empty accumulators."""
    trace.set_enabled(True)
    trace.recorder().clear()
    devtel.set_enabled(True)
    devtel.reset()
    yield
    trace.set_enabled(True)
    trace.recorder().clear()
    devtel.set_enabled(True)
    devtel.reset()


def _tiny_batcher():
    cfg = DecoderConfig(
        model_type="llama", vocab_size=64, hidden_size=32, n_layers=2,
        n_heads=4, n_kv_heads=2, head_dim=8, intermediate_size=64,
        max_position_embeddings=64, activation="silu", norm="rmsnorm",
        norm_eps=1e-5, mlp="swiglu", positions="rotary", rope_style="half",
        rotary_dim=8, attn_bias=False, mlp_bias=False,
        tie_word_embeddings=False, dtype="float32",
    )
    mesh = make_mesh(MeshPlan(dp=2, tp=4))
    params = init_params(cfg, mesh, jax.random.key(0))
    engine = DecodeEngine(cfg, params, mesh, max_seq_len=64)
    batcher = ContinuousBatcher(engine, rows=2, chunk_steps=2, group_chunks=2)
    return engine, batcher


@pytest.fixture(scope="module")
def warm(devices):
    """One prewarmed tiny engine+batcher for the whole module (prewarm is
    the expensive part; tests re-enable/reset devtel around it)."""
    trace.set_enabled(True)
    devtel.set_enabled(True)
    devtel.reset()
    engine, batcher = _tiny_batcher()
    batcher.prewarm()
    return engine, batcher


def _serve(batcher, n=2, max_new=4, prefix="dv"):
    gen = GenerationParams(max_new_tokens=max_new, is_greedy=True)
    got = {}
    for i in range(n):
        batcher.submit(
            [5 + i, 9, 3], gen, lambda t, i=i: got.__setitem__(i, t),
            req_id=f"{prefix}{i}",
        )
    batcher.run_until_idle()
    assert len(got) == n
    return got


# -- cost table ---------------------------------------------------------------


class _FakeLowered:
    """A ``jax.stages.Lowered``-shaped object with a countable
    cost_analysis, so provenance and cache behavior are observable."""

    def __init__(self):
        self.calls = 0

    def cost_analysis(self):
        self.calls += 1
        return {"flops": 1.0e9, "bytes accessed": 2.0e8}


def test_cost_table_cache_hit_never_relowers():
    table = devtel.CostTable()
    lowered = _FakeLowered()
    c1 = table.derive(("decode", 8, 64), lambda: lowered)
    assert c1.source == "cost_analysis"
    assert (c1.flops, c1.hbm_bytes) == (1.0e9, 2.0e8)
    assert lowered.calls == 1
    # Hit: the (trace-cost) thunk must not run again.
    c2 = table.derive(("decode", 8, 64), lambda: lowered)
    assert c2 is c1 and lowered.calls == 1


def test_cost_table_analytical_fallback():
    table = devtel.CostTable()

    class _Empty:
        def cost_analysis(self):
            return {}  # backend returned nothing usable

    c = table.derive(("decode", 4, 32), lambda: _Empty(), fallback=(3.0, 7.0))
    assert c.source == "analytical" and (c.flops, c.hbm_bytes) == (3.0, 7.0)
    assert table.derive(("nope",)) is None  # every source absent


def test_real_lowering_prices_via_cost_analysis(devices):
    # The real jax integration: lower() (trace-only, nothing executed)
    # feeds cost_analysis() and the table records backend provenance.
    @jax.jit
    def g(x):
        return x @ x

    c = devtel.costs().derive(
        ("unit", "g"), lambda: g.lower(jnp.ones((16, 16))),
    )
    assert c is not None and c.source == "cost_analysis"
    assert c.flops > 0


# -- MFU/MBU fold -------------------------------------------------------------


def test_mfu_mbu_in_unit_interval_on_real_dispatch(warm):
    # The cost table was reset after prewarm (fixture scoping), so the
    # dispatch-site lookup prices these groups via the analytical model
    # — the fallback path, exercised on a REAL grouped dispatch.
    engine, batcher = warm
    _serve(batcher, n=3, max_new=8, prefix="mfu")
    util = devtel.last_util()
    assert "decode_group" in util, f"no decode_group fold: {util}"
    g = util["decode_group"]
    # Roofline-achieved fractions: strictly positive (real work folded),
    # clamped at 1.0 by contract. CPU magnitudes are ~1e-9 — the bound,
    # not the magnitude, is the contract.
    assert 0.0 < g["mfu"] <= 1.0
    assert 0.0 < g["mbu"] <= 1.0
    assert g["source"] in ("cost_analysis", "analytical")
    # The windowed histograms got the same fold.
    reg = metrics_mod.series()
    assert "mfu_decode_group" in reg.names()
    assert "mbu_decode_group" in reg.names()


def test_fold_accumulator_drains_to_histograms():
    cost = devtel.KernelCost(1.0e9, 2.0e8, "analytical")
    for _ in range(5):
        devtel.fold("decode_group", 0.004, cost)
    util = devtel.last_util()  # reader forces the drain
    assert util["decode_group"]["dur_s"] == pytest.approx(0.004)
    assert util["decode_group"]["mfu"] > 0.0


# -- counter tracks -----------------------------------------------------------


def test_counter_tracks_pass_chrome_schema(warm):
    engine, batcher = warm
    _serve(batcher, n=3, max_new=8, prefix="ctr")
    # One more serve with the sampler throttle defeated: by now MFU/MBU
    # folds exist, so the sample deterministically carries those tracks
    # alongside rows/queue depth.
    batcher._devtel_last_t = float("-inf")
    _serve(batcher, n=1, prefix="ctr2")
    # The scheduler's group-boundary sampler recorded counter samples;
    # they ride the same Chrome export as the spans.
    doc = trace.to_chrome_trace(
        [trace.recorder().export()], counters=[devtel.export()],
    )
    evs = doc["traceEvents"]
    assert {e["ph"] for e in evs} <= {"M", "X", "i", "C"}
    cs = [e for e in evs if e["ph"] == "C"]
    tracks = {e["name"] for e in cs}
    assert len(tracks) >= 3, f"want >=3 counter tracks, got {tracks}"
    assert {"rows", "queue_depth"} <= tracks
    for e in cs:
        assert e["ts"] >= 0
        assert e["cat"] == "counter"
        assert isinstance(e["args"], dict) and e["args"]
        for v in e["args"].values():
            assert isinstance(v, (int, float))


def test_largest_run_fragmentation_signal():
    assert devtel.largest_run([]) == 0
    assert devtel.largest_run([4]) == 1
    assert devtel.largest_run([1, 2, 3, 7, 8]) == 3
    assert devtel.largest_run([0, 2, 4]) == 1


# -- compile forensics --------------------------------------------------------


def test_steady_recompile_attributed_and_flagged_on_slo():
    obs = devtel.observer()

    @jax.jit
    def f(x):
        return x * 2 + 1

    obs.watch("f", f)
    f(jnp.ones(4))  # warmup compile
    obs.mark_steady()
    f(jnp.ones(8))  # steady-state recompile: a new shape signature
    obs._last_sample = float("-inf")  # defeat the sweep throttle
    grew = obs.maybe_sample("req-attr")
    assert grew == 1
    ev = [e for e in obs.events() if e.get("req_id") == "req-attr"]
    assert ev and ev[0]["steady_state"] and ev[0]["source"] == "cache_size"
    # The attributed compile span rides the triggering request's timeline.
    names = {e["name"] for e in trace.recorder().events_for("req-attr")}
    assert "compile" in names

    # The REAL /slo payload path flags it (local export via the broker
    # collection the producer uses).
    ps = ProducerServer(broker=InProcBroker())
    flag = ps.slo().get("compile")
    assert flag and flag["flagged"] and flag["steady_state_recompiles"] >= 1
    comp = ps.compiles()
    assert comp["n_compiles"] >= 1
    assert any(e.get("req_id") == "req-attr" for e in comp["compiles"])


def test_trace_off_devtel_silent_zero_recompiles(warm):
    """LLMSS_TRACE=0 gates the whole plane: a warmed batcher serving with
    tracing off must record NOTHING in devtel and, under CompileGuard,
    hit the jit caches exactly as before — zero new compiles."""
    from llmss_tpu.analysis import CompileGuard

    engine, batcher = warm
    trace.set_enabled(False)
    assert not devtel.enabled()
    guard = CompileGuard.for_engine(engine)
    with guard.steady_state():
        _serve(batcher, prefix="off")
    ex = devtel.export()
    assert ex["counters"] == []
    assert ex["compiles"]["events"] == []
    assert ex["compiles"]["steady_recompiles"] == 0
    assert ex["util"] == {}


# -- Prometheus rendering -----------------------------------------------------


def test_prometheus_label_value_escaping():
    hostile = 'w"1\\evil\nid'
    text = metrics_mod.render_prometheus(
        {"fleet": {"workers": {hostile: {"tokens_generated": 3}}}},
    )
    line = next(
        ln for ln in text.splitlines() if ln.startswith("llmss_fleet_worker")
    )
    # Escaped per the text-format spec; the raw newline must not survive
    # into the sample line (it would truncate the scrape).
    assert '\\"1' in line and "\\\\evil" in line and "\\nid" in line
    assert line.endswith(" 3")


def test_prometheus_util_gauges_closed_label_set():
    text = metrics_mod.render_prometheus(
        {"uptime_s": 1.0},
        util={"mfu": {"decode_group": 0.5}, "mbu": {"decode_group": 0.25}},
    )
    assert 'llmss_mfu{kernel="decode_group"} 0.5' in text
    assert 'llmss_mbu{kernel="decode_group"} 0.25' in text


# -- /profile slot lifecycle --------------------------------------------------


def test_profile_slot_steals_wedged_holder_and_auto_releases(tmp_path):
    from llmss_tpu.serve import producer as producer_mod

    with producer_mod._PROFILE_LOCK:
        saved = (
            producer_mod._PROFILE_ACTIVE, producer_mod._PROFILE_GEN,
            producer_mod._PROFILE_DEADLINE,
        )
    try:
        # A live holder within its deadline still refuses overlap.
        with producer_mod._PROFILE_LOCK:
            producer_mod._PROFILE_GEN += 1
            producer_mod._PROFILE_ACTIVE = producer_mod._PROFILE_GEN
            producer_mod._PROFILE_DEADLINE = time.monotonic() + 30.0
        code, body = producer_mod.start_profile(
            log_dir=str(tmp_path / "a"), duration_s=0.2,
        )
        assert code == 409 and body["retry_after_s"] > 0

        # A wedged holder (deadline blown: its capture thread hung or
        # died) no longer wedges profiling until restart — the slot is
        # stolen, not refused.
        with producer_mod._PROFILE_LOCK:
            producer_mod._PROFILE_DEADLINE = time.monotonic() - 1.0
        code, body = producer_mod.start_profile(
            log_dir=str(tmp_path / "b"), duration_s=0.2,
        )
        assert code == 202 and body.get("stole_wedged_slot") is True

        # The thief's capture auto-stops and frees the slot.
        deadline = time.monotonic() + 10.0
        while True:
            with producer_mod._PROFILE_LOCK:
                if producer_mod._PROFILE_ACTIVE == 0:
                    break
            assert time.monotonic() < deadline, "profile never released"
            time.sleep(0.05)
    finally:
        with producer_mod._PROFILE_LOCK:
            (
                producer_mod._PROFILE_ACTIVE, producer_mod._PROFILE_GEN,
                producer_mod._PROFILE_DEADLINE,
            ) = saved
