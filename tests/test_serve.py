"""Serving stack: HTTP round-trip, id correlation under concurrency,
error containment."""

import json
import threading
import time

import numpy as np
import pytest

import httpx

from llmss_tpu.engine import DecodeEngine, GenerationParams
from llmss_tpu.models import config_from_hf
from llmss_tpu.models.registry import MODEL_REGISTRY
from llmss_tpu.parallel import MeshPlan, make_mesh
from llmss_tpu.serve import GenerateRequest, InProcBroker
from llmss_tpu.serve.consumer import ContinuousWorker, Worker
from llmss_tpu.serve.producer import ProducerServer
from llmss_tpu.weights import CheckpointShards, weight_files


@pytest.fixture(scope="module")
def serving(tmp_path_factory, devices):
    import torch
    import transformers as tr

    torch.manual_seed(11)
    cfg_hf = tr.GPT2Config(
        vocab_size=64, n_positions=64, n_embd=32, n_layer=2, n_head=4
    )
    d = tmp_path_factory.mktemp("serve") / "m"
    tr.GPT2LMHeadModel(cfg_hf).eval().save_pretrained(
        d, safe_serialization=True
    )

    mesh = make_mesh(MeshPlan(dp=2, tp=4))
    from transformers import AutoConfig

    cfg = config_from_hf(AutoConfig.from_pretrained(d), dtype="float32")
    ckpt = CheckpointShards(weight_files(str(d)), dtype=np.float32)
    params = MODEL_REGISTRY["gpt2"].load_params(ckpt, cfg, mesh)
    engine = DecodeEngine(cfg, params, mesh, max_seq_len=64)

    broker = InProcBroker()
    worker = Worker(engine, broker, batch_size=4, poll_timeout_s=0.05)
    stop = threading.Event()
    t = threading.Thread(target=worker.run_forever, args=(stop,), daemon=True)
    t.start()

    server = ProducerServer(broker, host="127.0.0.1", port=0, timeout_s=120)
    server.start()

    yield server, engine
    stop.set()
    server.stop()


def _post(port, payload, timeout=120.0):
    return httpx.post(
        f"http://127.0.0.1:{port}/generate", json=payload, timeout=timeout
    )


def test_roundtrip(serving):
    server, _ = serving
    r = _post(server.port, {
        "token_ids": [1, 2, 3], "max_new_tokens": 4, "is_greedy": True,
    })
    assert r.status_code == 200, r.text
    body = r.json()
    assert len(body["token_ids"]) == 4
    assert body["id"]


def test_correlation_under_concurrency(serving):
    """Concurrent requests each get their own answer (the reference's
    producer can mix these up — SURVEY.md §2.10)."""
    server, engine = serving
    prompts = [[i + 1, i + 2, i + 3] for i in range(6)]
    expected = engine.generate(
        prompts, [GenerationParams(max_new_tokens=4, is_greedy=True)] * 6
    )

    results = {}

    def call(i):
        r = _post(server.port, {
            "token_ids": prompts[i], "max_new_tokens": 4, "is_greedy": True,
        })
        results[i] = r.json()["token_ids"]

    threads = [threading.Thread(target=call, args=(i,)) for i in range(6)]
    for t in threads:
        t.start()
    for t in threads:
        t.join()

    for i in range(6):
        assert results[i] == expected[i], (i, results[i], expected[i])


def test_bad_request_and_health(serving):
    server, _ = serving
    r = _post(server.port, {"max_new_tokens": 4})
    assert r.status_code == 400
    r = _post(server.port, {
        "token_ids": [1], "is_greedy": False, "temperature": -1.0,
    })
    assert r.status_code == 400
    r = httpx.get(f"http://127.0.0.1:{server.port}/health", timeout=10)
    assert r.status_code == 200


def test_mixed_params_batch(serving):
    server, _ = serving
    greedy = _post(server.port, {
        "token_ids": [5, 6], "max_new_tokens": 3, "is_greedy": True,
    }).json()
    sampled = _post(server.port, {
        "token_ids": [5, 6], "max_new_tokens": 6, "is_greedy": False,
        "temperature": 0.7, "top_k": 5, "top_p": 0.9, "seed": 1,
    }).json()
    assert len(greedy["token_ids"]) == 3
    assert len(sampled["token_ids"]) == 6


def test_cancelled_pending_request_is_skipped(serving):
    """A request cancelled while still queued (e.g. producer timeout) must
    not reach the engine: the worker answers it with a 'cancelled' error."""
    _, engine = serving
    broker = InProcBroker()
    worker = Worker(engine, broker, batch_size=4, poll_timeout_s=0.01)
    broker.push_request(GenerateRequest(
        id="dead", token_ids=[1, 2], max_new_tokens=30, is_greedy=True,
    ))
    broker.cancel_request("dead")
    before = engine.metrics.cancelled
    worker.run_once()
    resp = broker.wait_response("dead", timeout=10)
    assert resp.error == "cancelled"
    assert engine.metrics.cancelled == before + 1


def test_cancel_http_route(serving):
    server, _ = serving
    r = httpx.post(
        f"http://127.0.0.1:{server.port}/cancel", json={"id": "xyz"},
        timeout=10,
    )
    assert r.status_code == 200 and r.json()["cancelled"] == "xyz"


def test_no_recompile_across_batch_sizes(serving):
    """Steady-state serving must reuse one executable per seq bucket no
    matter how many requests each queue drain yields: the worker pads the
    batch dim to its envelope (a fresh compile per live batch size would be
    a multi-second stall under bursty load)."""
    _, engine = serving
    broker = InProcBroker()
    worker = Worker(engine, broker, batch_size=4, poll_timeout_s=0.01)

    def push(n, start):
        ids = []
        for i in range(n):
            rid = f"r{start + i}"
            broker.push_request(GenerateRequest(
                id=rid, token_ids=[1 + i, 2, 3], max_new_tokens=3,
                is_greedy=True,
            ))
            ids.append(rid)
        return ids

    ids = push(4, 0)  # full batch: compiles (or reuses) the envelope shape
    worker.run_once()
    for rid in ids:
        assert broker.wait_response(rid, timeout=30).error is None
    base_prefill = engine._prefill._cache_size()
    base_decode = engine._decode._cache_size()

    for n, start in ((1, 10), (3, 20), (2, 30)):
        ids = push(n, start)
        worker.run_once()
        for rid in ids:
            assert broker.wait_response(rid, timeout=30).error is None

    assert engine._prefill._cache_size() == base_prefill
    assert engine._decode._cache_size() == base_decode


def test_prewarm_covers_all_shapes(serving):
    """After prewarm, no request shape inside the envelope may trigger a
    new compile: varied prompt-length buckets and admission batch sizes all
    hit prewarmed executables (first long-prompt request must not eat a
    multi-second XLA compile mid-serve)."""
    _, engine = serving
    broker = InProcBroker()
    worker = ContinuousWorker(
        engine, broker, rows=4, poll_timeout_s=0.01, chunk_steps=2
    )
    worker.prewarm()
    b = worker.batcher
    sizes = {
        "prefill_row": b._prefill_row._cache_size(),
        "insert": b._insert._cache_size(),
        "decode": engine._decode._cache_size(),
        "decode_group": engine._decode_group._cache_size(),
    }

    # Prompt lengths spanning every bucket (engine max_seq_len caps them),
    # admitted in drains of 1, 3, and 4 requests.
    rid = 0
    for n in (1, 3, 4):
        ids = []
        for _ in range(n):
            rid += 1
            L = [3, 20, 40, 7][rid % 4] % engine.max_seq_len or 3
            broker.push_request(GenerateRequest(
                id=f"p{rid}", token_ids=list(range(1, L + 1)),
                max_new_tokens=3, is_greedy=True,
            ))
            ids.append(f"p{rid}")
        deadline = time.time() + 60
        while ids and time.time() < deadline:
            worker.run_once()
            ids = [i for i in ids
                   if broker.wait_response(i, timeout=0.001) is None]
        assert not ids

    # The expensive executables (prefill buckets, fused decode) must be
    # airtight. _insert — a sub-second scatter compile — may pick up a
    # couple of late variants: the cache's PartitionSpec representation
    # alternates normalized forms as it cycles through differently-pinned
    # jit outputs, and insert sits downstream of all of them.
    assert b._prefill_row._cache_size() == sizes["prefill_row"]
    assert engine._decode._cache_size() == sizes["decode"]
    assert engine._decode_group._cache_size() == sizes["decode_group"]
    assert b._insert._cache_size() <= sizes["insert"] + 2


def test_cancel_race_orderings(serving):
    """The cancellation flag is TTL'd broker state, so both orderings land:
    (a) cancel after the request is queued, (b) cancel *before* the worker
    ever sees the request (the Redis no-cross-queue-ordering race). Both
    must answer error='cancelled', and a mid-decode cancel must not be
    disguised as a success response."""
    _, engine = serving
    broker = InProcBroker()
    worker = ContinuousWorker(
        engine, broker, rows=2, poll_timeout_s=0.01, chunk_steps=2
    )

    # (b) cancel races ahead of its request.
    broker.cancel_request("early")
    worker.run_once()  # drains nothing; flag must persist
    broker.push_request(GenerateRequest(
        id="early", token_ids=[1, 2, 3], max_new_tokens=30, is_greedy=True,
    ))
    deadline = time.time() + 60
    resp = None
    while resp is None and time.time() < deadline:
        worker.run_once()
        resp = broker.wait_response("early", timeout=0.001)
    assert resp is not None and resp.error == "cancelled"

    # (a) cancel mid-decode: honest error + partial tokens, not success.
    broker.push_request(GenerateRequest(
        id="mid", token_ids=[4, 5], max_new_tokens=40, is_greedy=True,
    ))
    for _ in range(4):
        worker.run_once()
    broker.cancel_request("mid")
    deadline = time.time() + 60
    resp = None
    while resp is None and time.time() < deadline:
        worker.run_once()
        resp = broker.wait_response("mid", timeout=0.001)
    assert resp is not None and resp.error == "cancelled"
    assert resp.token_ids is not None and 0 < len(resp.token_ids) < 40


def test_health_flips_on_stale_heartbeat(serving):
    """A hung supervised worker must not look healthy: /health serves 503
    once the published heartbeat goes stale (VERDICT: the reference at
    least dies visibly; a green light over a dead worker 504s clients)."""
    server, _ = serving
    broker = server.broker

    # Fresh heartbeat: healthy, with age surfaced.
    broker.publish_metrics({})
    broker.metrics_extra = lambda: {"supervisor": {
        "alive": True, "heartbeat_ts": time.time(), "heartbeat_s": 1.0,
        "restarts": 0, "last_error": None,
    }}
    broker.publish_metrics({})
    r = httpx.get(f"http://127.0.0.1:{server.port}/health", timeout=10)
    assert r.status_code == 200 and r.json()["status"] == "ok"

    # Stale heartbeat: 503.
    broker.metrics_extra = lambda: {"supervisor": {
        "alive": True, "heartbeat_ts": time.time() - 60.0,
        "heartbeat_s": 1.0, "restarts": 0, "last_error": None,
    }}
    broker.publish_metrics({})
    r = httpx.get(f"http://127.0.0.1:{server.port}/health", timeout=10)
    assert r.status_code == 503
    assert r.json()["status"] == "stale-heartbeat"

    # Dead worker: 503 regardless of age.
    broker.metrics_extra = lambda: {"supervisor": {
        "alive": False, "heartbeat_ts": time.time(), "heartbeat_s": 1.0,
        "restarts": 3, "last_error": "boom",
    }}
    broker.publish_metrics({})
    r = httpx.get(f"http://127.0.0.1:{server.port}/health", timeout=10)
    assert r.status_code == 503 and r.json()["status"] == "unhealthy"

    # Supervisor block vanishing after having been seen (metrics TTL
    # expiry over a hung worker) must NOT read as recovery.
    broker.metrics_extra = None
    broker.publish_metrics({})
    r = httpx.get(f"http://127.0.0.1:{server.port}/health", timeout=10)
    assert r.status_code == 503
    assert r.json()["status"] == "no-heartbeat-data"


def test_two_workers_share_one_broker(serving):
    """Multi-consumer topology (what RedisBroker exists for): two workers
    draining one queue must serve disjoint requests correctly, and a
    cancellation must reach the worker that owns the request — the TTL'd
    flag is readable by all workers, not competitively consumed by
    whichever polls first."""
    _, engine = serving
    broker = InProcBroker()
    w1 = ContinuousWorker(engine, broker, rows=2, poll_timeout_s=0.01,
                          chunk_steps=2)
    w2 = ContinuousWorker(engine, broker, rows=2, poll_timeout_s=0.01,
                          chunk_steps=2)

    ids = []
    for i in range(6):
        rid = f"mw{i}"
        broker.push_request(GenerateRequest(
            id=rid, token_ids=[1 + i, 2, 3], max_new_tokens=4,
            is_greedy=True,
        ))
        ids.append(rid)
    # A long request that will be cancelled mid-flight; either worker may
    # own it.
    broker.push_request(GenerateRequest(
        id="mw-long", token_ids=[9, 9], max_new_tokens=60, is_greedy=True,
    ))

    # Interleave the two workers; cancel the long request once it is
    # somewhere in the system.
    for step in range(6):
        w1.run_once()
        w2.run_once()
    broker.cancel_request("mw-long")

    deadline = time.time() + 120
    got = {}
    while len(got) < 7 and time.time() < deadline:
        w1.run_once()
        w2.run_once()
        for rid in ids + ["mw-long"]:
            if rid not in got:
                r = broker.wait_response(rid, timeout=0.001)
                if r is not None:
                    got[rid] = r
    assert set(got) == set(ids) | {"mw-long"}, sorted(got)
    for rid in ids:
        assert got[rid].error is None and len(got[rid].token_ids) == 4
    assert got["mw-long"].error == "cancelled"
    assert len(got["mw-long"].token_ids or []) < 60


def test_streaming_sse_roundtrip(serving):
    """stream: true delivers token increments as SSE events while the
    request decodes (continuous worker), then a done event with the full
    response; tokens concatenate to exactly the non-streamed result."""
    _, engine = serving
    broker = InProcBroker()
    worker = ContinuousWorker(engine, broker, rows=2, poll_timeout_s=0.01,
                              chunk_steps=2)
    stop = threading.Event()
    t = threading.Thread(target=worker.run_forever, args=(stop,),
                         daemon=True)
    t.start()
    server = ProducerServer(broker, host="127.0.0.1", port=0, timeout_s=60)
    server.start()
    try:
        ref = _post(server.port, {
            "token_ids": [5, 6, 7], "max_new_tokens": 12, "is_greedy": True,
        }).json()["token_ids"]

        events, done = [], None
        with httpx.stream(
            "POST", f"http://127.0.0.1:{server.port}/generate",
            json={"token_ids": [5, 6, 7], "max_new_tokens": 12,
                  "is_greedy": True, "stream": True},
            timeout=60,
        ) as r:
            assert r.status_code == 200
            assert "text/event-stream" in r.headers["content-type"]
            cur_event = None
            for line in r.iter_lines():
                if line.startswith("event:"):
                    cur_event = line.split(":", 1)[1].strip()
                elif line.startswith("data:"):
                    payload = json.loads(line.split(":", 1)[1])
                    if cur_event == "done":
                        done = payload
                    elif cur_event is None:
                        events.append(payload["token_ids"])
                    cur_event = None

        assert done is not None and done["error"] is None
        streamed = [t for inc in events for t in inc]
        assert len(events) >= 2  # actually incremental, not one blob
        assert streamed == ref == done["token_ids"]
    finally:
        stop.set()
        server.stop()


def test_streaming_from_batch_worker_is_incremental(serving):
    """The STATIC (batch-at-a-time) Worker streams too: stream:true must
    deliver >1 increment per request (round 3 degraded to one blob at
    completion), with engine-owned completion semantics — increments
    concatenate to exactly the final response tokens."""
    _, engine = serving
    broker = InProcBroker()
    worker = Worker(
        engine, broker, batch_size=2, poll_timeout_s=0.01, chunk_steps=2
    )
    broker.push_request(GenerateRequest(
        id="s1", token_ids=[5, 6, 7], max_new_tokens=10, is_greedy=True,
        stream=True,
    ))
    broker.push_request(GenerateRequest(
        id="p1", token_ids=[5, 6, 7], max_new_tokens=10, is_greedy=True,
    ))
    worker.run_once()

    done = broker.wait_response("s1", timeout=5)
    plain = broker.wait_response("p1", timeout=5)
    assert done is not None and done.error is None

    events = []
    while True:
        inc = broker.pop_stream("s1", timeout=0.05)
        if inc is None:
            break
        events.append(inc)
    assert len(events) >= 2, events  # actually incremental, not one blob
    streamed = [t for inc in events for t in inc]
    assert streamed == done.token_ids == plain.token_ids
