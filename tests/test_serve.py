"""Serving stack: HTTP round-trip, id correlation under concurrency,
error containment."""

import json
import threading

import numpy as np
import pytest

import httpx

from llmss_tpu.engine import DecodeEngine, GenerationParams
from llmss_tpu.models import config_from_hf
from llmss_tpu.models.registry import MODEL_REGISTRY
from llmss_tpu.parallel import MeshPlan, make_mesh
from llmss_tpu.serve import GenerateRequest, InProcBroker
from llmss_tpu.serve.consumer import Worker
from llmss_tpu.serve.producer import ProducerServer
from llmss_tpu.weights import CheckpointShards, weight_files


@pytest.fixture(scope="module")
def serving(tmp_path_factory, devices):
    import torch
    import transformers as tr

    torch.manual_seed(11)
    cfg_hf = tr.GPT2Config(
        vocab_size=64, n_positions=64, n_embd=32, n_layer=2, n_head=4
    )
    d = tmp_path_factory.mktemp("serve") / "m"
    tr.GPT2LMHeadModel(cfg_hf).eval().save_pretrained(
        d, safe_serialization=True
    )

    mesh = make_mesh(MeshPlan(dp=2, tp=4))
    from transformers import AutoConfig

    cfg = config_from_hf(AutoConfig.from_pretrained(d), dtype="float32")
    ckpt = CheckpointShards(weight_files(str(d)), dtype=np.float32)
    params = MODEL_REGISTRY["gpt2"].load_params(ckpt, cfg, mesh)
    engine = DecodeEngine(cfg, params, mesh, max_seq_len=64)

    broker = InProcBroker()
    worker = Worker(engine, broker, batch_size=4, poll_timeout_s=0.05)
    stop = threading.Event()
    t = threading.Thread(target=worker.run_forever, args=(stop,), daemon=True)
    t.start()

    server = ProducerServer(broker, host="127.0.0.1", port=0, timeout_s=120)
    server.start()

    yield server, engine
    stop.set()
    server.stop()


def _post(port, payload, timeout=120.0):
    return httpx.post(
        f"http://127.0.0.1:{port}/generate", json=payload, timeout=timeout
    )


def test_roundtrip(serving):
    server, _ = serving
    r = _post(server.port, {
        "token_ids": [1, 2, 3], "max_new_tokens": 4, "is_greedy": True,
    })
    assert r.status_code == 200, r.text
    body = r.json()
    assert len(body["token_ids"]) == 4
    assert body["id"]


def test_correlation_under_concurrency(serving):
    """Concurrent requests each get their own answer (the reference's
    producer can mix these up — SURVEY.md §2.10)."""
    server, engine = serving
    prompts = [[i + 1, i + 2, i + 3] for i in range(6)]
    expected = engine.generate(
        prompts, [GenerationParams(max_new_tokens=4, is_greedy=True)] * 6
    )

    results = {}

    def call(i):
        r = _post(server.port, {
            "token_ids": prompts[i], "max_new_tokens": 4, "is_greedy": True,
        })
        results[i] = r.json()["token_ids"]

    threads = [threading.Thread(target=call, args=(i,)) for i in range(6)]
    for t in threads:
        t.start()
    for t in threads:
        t.join()

    for i in range(6):
        assert results[i] == expected[i], (i, results[i], expected[i])


def test_bad_request_and_health(serving):
    server, _ = serving
    r = _post(server.port, {"max_new_tokens": 4})
    assert r.status_code == 400
    r = _post(server.port, {
        "token_ids": [1], "is_greedy": False, "temperature": -1.0,
    })
    assert r.status_code == 400
    r = httpx.get(f"http://127.0.0.1:{server.port}/health", timeout=10)
    assert r.status_code == 200


def test_mixed_params_batch(serving):
    server, _ = serving
    greedy = _post(server.port, {
        "token_ids": [5, 6], "max_new_tokens": 3, "is_greedy": True,
    }).json()
    sampled = _post(server.port, {
        "token_ids": [5, 6], "max_new_tokens": 6, "is_greedy": False,
        "temperature": 0.7, "top_k": 5, "top_p": 0.9, "seed": 1,
    }).json()
    assert len(greedy["token_ids"]) == 3
    assert len(sampled["token_ids"]) == 6


def test_cancelled_pending_request_is_skipped(serving):
    """A request cancelled while still queued (e.g. producer timeout) must
    not reach the engine: the worker answers it with a 'cancelled' error."""
    _, engine = serving
    broker = InProcBroker()
    worker = Worker(engine, broker, batch_size=4, poll_timeout_s=0.01)
    broker.push_request(GenerateRequest(
        id="dead", token_ids=[1, 2], max_new_tokens=30, is_greedy=True,
    ))
    broker.cancel_request("dead")
    before = engine.metrics.cancelled
    worker.run_once()
    resp = broker.wait_response("dead", timeout=10)
    assert resp.error == "cancelled"
    assert engine.metrics.cancelled == before + 1


def test_cancel_http_route(serving):
    server, _ = serving
    r = httpx.post(
        f"http://127.0.0.1:{server.port}/cancel", json={"id": "xyz"},
        timeout=10,
    )
    assert r.status_code == 200 and r.json()["cancelled"] == "xyz"


def test_no_recompile_across_batch_sizes(serving):
    """Steady-state serving must reuse one executable per seq bucket no
    matter how many requests each queue drain yields: the worker pads the
    batch dim to its envelope (a fresh compile per live batch size would be
    a multi-second stall under bursty load)."""
    _, engine = serving
    broker = InProcBroker()
    worker = Worker(engine, broker, batch_size=4, poll_timeout_s=0.01)

    def push(n, start):
        ids = []
        for i in range(n):
            rid = f"r{start + i}"
            broker.push_request(GenerateRequest(
                id=rid, token_ids=[1 + i, 2, 3], max_new_tokens=3,
                is_greedy=True,
            ))
            ids.append(rid)
        return ids

    ids = push(4, 0)  # full batch: compiles (or reuses) the envelope shape
    worker.run_once()
    for rid in ids:
        assert broker.wait_response(rid, timeout=30).error is None
    base_prefill = engine._prefill._cache_size()
    base_decode = engine._decode._cache_size()

    for n, start in ((1, 10), (3, 20), (2, 30)):
        ids = push(n, start)
        worker.run_once()
        for rid in ids:
            assert broker.wait_response(rid, timeout=30).error is None

    assert engine._prefill._cache_size() == base_prefill
    assert engine._decode._cache_size() == base_decode
