#!/usr/bin/env python
"""Thin wrapper so `./tools/lint.py llmss_tpu` works from the repo root."""

import sys
from pathlib import Path

sys.path.insert(0, str(Path(__file__).resolve().parent.parent))

from llmss_tpu.analysis.cli import main  # noqa: E402

if __name__ == "__main__":
    sys.exit(main())
