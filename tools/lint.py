#!/usr/bin/env python
"""Run the full static-analysis gate from the repo root.

With plain path arguments this runs BOTH passes CI gates on — the AST
lint (graftlint) over the given paths, then the IR-level SPMD audit
(shardcheck, which traces + compiles the production programs and diffs
the collective inventory against tools/comms_manifest.json) — and exits
with the worst code.

    ./tools/lint.py llmss_tpu             # both passes
    ./tools/lint.py --ast llmss_tpu       # AST pass only
    ./tools/lint.py --shardcheck ...      # IR pass only (pass-through)

Any invocation carrying an explicit mode flag (--shardcheck,
--list-rules, --write-baseline) is passed straight through to
``python -m llmss_tpu.analysis`` unchanged.
"""

import sys
from pathlib import Path

ROOT = Path(__file__).resolve().parent.parent
sys.path.insert(0, str(ROOT))

from llmss_tpu.analysis.cli import main  # noqa: E402


def run(argv: list[str]) -> int:
    if any(
        f in argv for f in ("--shardcheck", "--list-rules", "--write-baseline")
    ):
        return main(argv)
    if "--ast" in argv:
        return main([a for a in argv if a != "--ast"])
    ast_code = main(argv)
    shard_code = main([
        "--shardcheck",
        "--manifest", str(ROOT / "tools" / "comms_manifest.json"),
        "--baseline", str(ROOT / "tools" / "shardcheck_baseline.json"),
    ])
    return max(ast_code, shard_code)


if __name__ == "__main__":
    sys.exit(run(sys.argv[1:]))
