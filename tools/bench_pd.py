"""PD bench: disaggregated prefill/decode vs unified continuous batching.

The workload is the one disaggregation exists for (handoff.py): a mixed
trace of long-prompt/short-decode requests interleaved with short
interactive ones. A UNIFIED replica runs prefill and decode on the same
chip, so every long prefill it admits stalls the fused decode steps of
its co-batched rows — the stall shows up as decode step-time variance
and TTFT tail. A DISAGGREGATED fleet (1 prefill + 1 decode replica at
the same chip count) absorbs prefills on the prefill chip and ships the
paged blocks through the broker handoff channel; the decode chip's only
non-step work is adopting a payload (an HBM-bandwidth block import, ~3
orders of magnitude cheaper than a long prefill).

Both arms run on the deterministic fleet simulator (``llmss_tpu.sim``):
the chip is a :class:`DeviceCostModel` charging
``PREFILL_TOKEN_COST_S`` per prompt token, ``DECODE_STEP_COST_S`` per
fused step, and payload bytes over ``HBM_GBPS`` for an adopt — but the
TRANSFER PLANE IS REAL: records ride the broker's
push_handoff/pop_handoff/push_response with full-size payloads
(``KV_BYTES_PER_TOKEN`` defaults to the 1b2 dims in bf16), leases
touched per cycle, so handoff bytes per request and the delivery
counters come from the broker, not the model — and the sim's invariant
catalog (exactly-one-terminal, KV balance, …) is asserted at drain.
Virtual clock: the run is byte-reproducible and takes milliseconds of
wall time regardless of the simulated seconds.

Runs on CPU in one process (no JAX, no device). Writes PD_BENCH.json;
prints one JSON line. Asserts the structural claims the subsystem ships
on: zero lost/errored requests in both modes, every multi-token request
handed off exactly once, and strictly lower decode step-time variance
for the disaggregated fleet.
"""

from __future__ import annotations

import json
import os
import statistics
import sys

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

from llmss_tpu.sim import FleetSim  # noqa: E402

N_CHIPS = 2  # both fleets: 2 unified vs 1 prefill + 1 decode
ROWS = int(os.environ.get("PD_ROWS", 8))  # decode rows per chip
N_LONG = int(os.environ.get("PD_LONG", 8))
N_SHORT = int(os.environ.get("PD_SHORT", 24))
LONG_PROMPT = int(os.environ.get("PD_LONG_PROMPT", 256))
SHORT_PROMPT = int(os.environ.get("PD_SHORT_PROMPT", 32))
LONG_NEW = int(os.environ.get("PD_LONG_NEW", 16))
SHORT_NEW = int(os.environ.get("PD_SHORT_NEW", 32))
ARRIVAL_GAP_S = float(os.environ.get("PD_ARRIVAL_GAP_S", 0.005))

PREFILL_TOKEN_COST_S = float(os.environ.get("PD_PREFILL_TOKEN_COST_S", 50e-6))
DECODE_STEP_COST_S = float(os.environ.get("PD_DECODE_STEP_COST_S", 1.5e-3))
ADOPT_CONST_S = float(os.environ.get("PD_ADOPT_CONST_S", 1e-3))
HBM_GBPS = float(os.environ.get("BENCH_HBM_GBPS", 819.0))  # v5e
# 1b2 dims bf16: k+v x 20 layers x 16 kv heads x 128 head_dim x 2 bytes.
KV_BYTES_PER_TOKEN = int(
    os.environ.get("PD_KV_BYTES_PER_TOKEN", 2 * 20 * 16 * 128 * 2)
)


def make_trace_rows() -> list[dict]:
    """Mixed trace, interleaved so long prefills keep landing while
    short interactive rows are mid-decode."""
    longs = [
        {"token_ids": [1000 + i] * LONG_PROMPT, "max_new": LONG_NEW}
        for i in range(N_LONG)
    ]
    shorts = [
        {"token_ids": [2000 + i] * SHORT_PROMPT, "max_new": SHORT_NEW}
        for i in range(N_SHORT)
    ]
    out: list[dict] = []
    ratio = max(1, N_SHORT // max(N_LONG, 1))
    while longs or shorts:
        if longs:
            out.append(longs.pop(0))
        for _ in range(ratio):
            if shorts:
                out.append(shorts.pop(0))
    for i, row in enumerate(out):
        row["id"] = f"pd{i:04d}"
        row["arrival_s"] = i * ARRIVAL_GAP_S
    return out


def make_spec(mode: str) -> dict:
    # prefill_chunk covers the whole prompt: the unified arm prefills
    # INLINE in one fused step, stalling co-batched decode — the
    # head-of-line cost disaggregation removes. chunk_tokens=1 so every
    # decode step is one gap sample.
    inline = max(LONG_PROMPT, SHORT_PROMPT)
    common = {
        "rows": ROWS, "chunk_tokens": 1, "prefill_chunk": inline,
        "admit_burst": 1,
    }
    if mode == "unified":
        replicas = [{"count": N_CHIPS, "role": "unified", **common}]
    else:
        replicas = [
            {"count": 1, "role": "prefill", **common,
             "sized_handoff_payload": True},
            {"count": 1, "role": "decode", **common},
        ]
    return {
        "format": "llmss-scenario/1",
        "name": f"bench-pd-{mode}",
        "seed": 0,
        "broker": {"kind": "inproc", "lease_s": 5.0},
        "cost_model": {
            "kind": "table",
            "prefill_token_s": PREFILL_TOKEN_COST_S,
            "decode_step_s": DECODE_STEP_COST_S,
            "adopt_const_s": ADOPT_CONST_S,
            "kv_bytes_per_token": KV_BYTES_PER_TOKEN,
            "wire_gbps": HBM_GBPS,
        },
        "fleet": {"replicas": replicas, "router_policy": "shared"},
        "workload": {"kind": "trace", "rows": make_trace_rows()},
        "metrics": {"step_gaps": True},
    }


def run_mode(mode: str) -> dict:
    sim = FleetSim(make_spec(mode))
    report = sim.run()
    r = report["requests"]
    tp = report["throughput"]
    # Virtual span from submit of the first request to the last
    # completion (recover it from the rounded rate rather than the
    # drain-padded clock).
    elapsed = (
        tp["tokens_out"] / tp["tokens_per_s"] if tp["tokens_per_s"] else 0.0
    )
    delivery = report["delivery"]
    gaps_ms = [g * 1e3 for g in sim.step_gaps]
    return {
        "mode": mode,
        "requests": r["submitted"],
        "lost": r["submitted"] - r["answered"],
        "errored": r["answered"] - r["ok"],
        "tokens": tp["tokens_out"],
        "tok_s_chip": round(tp["tokens_out"] / elapsed / N_CHIPS, 1)
        if elapsed else 0.0,
        "ttft_p50_ms": round(report["latency_ms"]["ttft_p50"], 3),
        "ttft_p95_ms": round(report["latency_ms"]["ttft_p95"], 3),
        "decode_step_ms_mean": round(statistics.fmean(gaps_ms), 3),
        "decode_step_ms_stdev": round(statistics.stdev(gaps_ms), 3),
        "decode_step_ms_p95": round(
            statistics.quantiles(gaps_ms, n=20)[18], 3
        ),
        "handoffs": delivery.get("handoffs", 0),
        "handoff_bytes": delivery.get("handoff_bytes", 0),
        "handoff_bytes_per_request": (
            round(delivery["handoff_bytes"] / delivery["handoffs"])
            if delivery.get("handoffs") else 0
        ),
        "reprefills": delivery.get("reprefills", 0),
        "elapsed_s": round(elapsed, 3),
    }


def main():
    unified = run_mode("unified")
    disagg = run_mode("disagg")
    from bench import bench_provenance

    result = {
        "config": {
            "chips": N_CHIPS,
            "rows_per_chip": ROWS,
            "trace": {
                "long": {"n": N_LONG, "prompt": LONG_PROMPT,
                         "max_new": LONG_NEW},
                "short": {"n": N_SHORT, "prompt": SHORT_PROMPT,
                          "max_new": SHORT_NEW},
                "arrival_gap_s": ARRIVAL_GAP_S,
            },
            "prefill_token_cost_s": PREFILL_TOKEN_COST_S,
            "decode_step_cost_s": DECODE_STEP_COST_S,
            "adopt_const_s": ADOPT_CONST_S,
            "kv_bytes_per_token": KV_BYTES_PER_TOKEN,
            "hbm_gbps": HBM_GBPS,
        },
        "unified": unified,
        "disagg": disagg,
        "provenance": bench_provenance(),
    }
    # The claims the subsystem ships on: nothing lost or errored, every
    # multi-token request handed off exactly once, and the decode chip's
    # step cadence freed of prefill stalls.
    for mode in (unified, disagg):
        assert mode["lost"] == 0 and mode["errored"] == 0, result
    assert disagg["handoffs"] == N_LONG + N_SHORT, result
    assert unified["handoffs"] == 0, result
    assert (
        disagg["decode_step_ms_stdev"] < unified["decode_step_ms_stdev"]
    ), result
    path = os.path.join(
        os.path.dirname(os.path.dirname(os.path.abspath(__file__))),
        "PD_BENCH.json",
    )
    with open(path, "w") as f:
        json.dump(result, f, indent=1)
        f.write("\n")
    print(json.dumps({
        "metric": "pd_disagg_decode_tok_s_chip",
        "value": disagg["tok_s_chip"],
        "unit": (
            f"tok/s/chip sim ({N_CHIPS} chips, 1P+1D vs {N_CHIPS} unified"
            f"={unified['tok_s_chip']}; decode step stdev "
            f"{disagg['decode_step_ms_stdev']} vs "
            f"{unified['decode_step_ms_stdev']} ms, ttft_p95 "
            f"{disagg['ttft_p95_ms']} vs {unified['ttft_p95_ms']} ms, "
            f"{disagg['handoff_bytes_per_request'] / 1e6:.1f} MB/handoff)"
        ),
        "vs_baseline": round(
            disagg["tok_s_chip"] / max(unified["tok_s_chip"], 1e-9), 3
        ),
    }))


if __name__ == "__main__":
    main()
