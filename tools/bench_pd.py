"""PD bench: disaggregated prefill/decode vs unified continuous batching.

The workload is the one disaggregation exists for (handoff.py): a mixed
trace of long-prompt/short-decode requests interleaved with short
interactive ones. A UNIFIED replica runs prefill and decode on the same
chip, so every long prefill it admits stalls the fused decode steps of
its co-batched rows — the stall shows up as decode step-time variance
and TTFT tail. A DISAGGREGATED fleet (1 prefill + 1 decode replica at
the same chip count) absorbs prefills on the prefill chip and ships the
paged blocks through the broker handoff channel; the decode chip's only
non-step work is adopting a payload (an HBM-bandwidth block import, ~3
orders of magnitude cheaper than a long prefill).

The chip is simulated — a cost model charges ``PREFILL_TOKEN_COST_S``
per prompt token, ``DECODE_STEP_COST_S`` per fused step, and payload
bytes over ``HBM_GBPS`` for an adopt — but the TRANSFER PLANE IS REAL:
records ride ``InProcBroker`` push_handoff/pop_handoff/push_response
with full-size payloads (``KV_BYTES_PER_TOKEN`` defaults to the 1b2
dims in bf16), leases touched per decode step, so handoff bytes per
request and the delivery counters come from the broker, not the model.

Runs on CPU in one process (no JAX, no device). Writes PD_BENCH.json;
prints one JSON line. Asserts the structural claims the subsystem ships
on: zero lost/errored requests in both modes, every multi-token request
handed off exactly once, and strictly lower decode step-time variance
for the disaggregated fleet.
"""

from __future__ import annotations

import json
import os
import statistics
import sys
import threading
import time

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

from llmss_tpu.serve.broker import InProcBroker  # noqa: E402
from llmss_tpu.serve.handoff import HandoffRecord  # noqa: E402
from llmss_tpu.serve.protocol import (  # noqa: E402
    GenerateRequest,
    GenerateResponse,
)

N_CHIPS = 2  # both fleets: 2 unified vs 1 prefill + 1 decode
ROWS = int(os.environ.get("PD_ROWS", 8))  # decode rows per chip
N_LONG = int(os.environ.get("PD_LONG", 8))
N_SHORT = int(os.environ.get("PD_SHORT", 24))
LONG_PROMPT = int(os.environ.get("PD_LONG_PROMPT", 256))
SHORT_PROMPT = int(os.environ.get("PD_SHORT_PROMPT", 32))
LONG_NEW = int(os.environ.get("PD_LONG_NEW", 16))
SHORT_NEW = int(os.environ.get("PD_SHORT_NEW", 32))
ARRIVAL_GAP_S = float(os.environ.get("PD_ARRIVAL_GAP_S", 0.005))

PREFILL_TOKEN_COST_S = float(os.environ.get("PD_PREFILL_TOKEN_COST_S", 50e-6))
DECODE_STEP_COST_S = float(os.environ.get("PD_DECODE_STEP_COST_S", 1.5e-3))
ADOPT_CONST_S = float(os.environ.get("PD_ADOPT_CONST_S", 1e-3))
HBM_GBPS = float(os.environ.get("BENCH_HBM_GBPS", 819.0))  # v5e
# 1b2 dims bf16: k+v x 20 layers x 16 kv heads x 128 head_dim x 2 bytes.
KV_BYTES_PER_TOKEN = int(
    os.environ.get("PD_KV_BYTES_PER_TOKEN", 2 * 20 * 16 * 128 * 2)
)


class _Recorder:
    """Shared per-mode measurement state (one per run_mode call)."""

    def __init__(self):
        self.lock = threading.Lock()
        self.submit_ts: dict[str, float] = {}
        self.ttfts: list[float] = []  # guarded_by: self.lock
        self.gaps: list[float] = []  # inter-token s  guarded_by: self.lock
        self.tokens = 0  # guarded_by: self.lock

    def first_token(self, rid: str) -> None:
        with self.lock:
            self.ttfts.append(time.monotonic() - self.submit_ts[rid])
            self.tokens += 1

    def step(self, rows: list[dict], now: float) -> None:
        """One fused decode step landed: every active row gained a token;
        the gap since ITS last token (prefill/adopt stalls included — that
        is the variance being measured) goes into the pool."""
        with self.lock:
            for row in rows:
                self.gaps.append(now - row["last_t"])
                row["last_t"] = now
                self.tokens += 1


class _SimWorker:
    """Thread shell: subclasses implement one scheduler iteration."""

    def __init__(self, wid: str, broker, rec: _Recorder):
        self.wid = wid
        self.broker = broker
        self.rec = rec
        self._stop = threading.Event()
        self._thread = threading.Thread(target=self._loop, daemon=True)
        broker.register_worker({"worker_id": self.wid, "role": self.role})

    def _loop(self):
        while not self._stop.is_set():
            self.iterate()

    def start(self):
        self._thread.start()

    def stop(self):
        self._stop.set()
        self._thread.join(timeout=10)


class UnifiedSim(_SimWorker):
    """Continuous batching on one chip: admit, prefill INLINE (stalling
    the fused decode loop — the head-of-line cost disaggregation
    removes), then step all active rows."""

    role = "unified"

    def __init__(self, *a):
        super().__init__(*a)
        self.active: list[dict] = []

    def iterate(self):
        req = None
        if len(self.active) < ROWS:
            req = self.broker.pop_request(
                timeout=0.0 if self.active else 0.005, worker_id=self.wid,
            )
        if req is not None:
            time.sleep(PREFILL_TOKEN_COST_S * len(req.token_ids or []))
            self.rec.first_token(req.id)
            if req.max_new_tokens <= 1:
                self.broker.push_response(GenerateResponse(
                    id=req.id, token_ids=[0][: req.max_new_tokens],
                ))
                return
            self.active.append({
                "id": req.id, "left": req.max_new_tokens - 1,
                "last_t": time.monotonic(),
            })
        if not self.active:
            return
        time.sleep(DECODE_STEP_COST_S)
        now = time.monotonic()
        self.rec.step(self.active, now)
        done = [r for r in self.active if r["left"] <= 1]
        self.active = [r for r in self.active if r["left"] > 1]
        for r in self.active:
            r["left"] -= 1
        for r in done:
            self.broker.push_response(GenerateResponse(
                id=r["id"], token_ids=[0],  # sim: count, not content
            ))


class PrefillSim(_SimWorker):
    """Prefill-only chip: pop, charge the prefill, ship the full-size
    payload through the REAL broker handoff channel."""

    role = "prefill"

    def iterate(self):
        req = self.broker.pop_request(timeout=0.005, worker_id=self.wid)
        if req is None:
            return
        n = len(req.token_ids or [])
        time.sleep(PREFILL_TOKEN_COST_S * n)
        self.rec.first_token(req.id)
        if req.max_new_tokens <= 1:
            self.broker.push_response(GenerateResponse(
                id=req.id, token_ids=[0][: req.max_new_tokens],
            ))
            return
        self.broker.push_handoff(HandoffRecord(
            req=req, first_token=0, n_tokens=n,
            payload=bytes(n * KV_BYTES_PER_TOKEN),
        ))


class DecodeSim(_SimWorker):
    """Decode-only chip: adopt handoffs (HBM import cost, leases renewed
    per fused step) and run the same batched step loop as UnifiedSim —
    minus the inline prefills."""

    role = "decode"

    def __init__(self, *a):
        super().__init__(*a)
        self.active: list[dict] = []

    def iterate(self):
        rec = None
        if len(self.active) < ROWS:
            rec = self.broker.pop_handoff(
                timeout=0.0 if self.active else 0.005, worker_id=self.wid,
            )
        if rec is not None:
            time.sleep(
                ADOPT_CONST_S + len(rec.payload) / (HBM_GBPS * 1e9)
            )
            self.active.append({
                "id": rec.req.id, "left": rec.req.max_new_tokens - 1,
                "last_t": time.monotonic(),
            })
        if not self.active:
            return
        time.sleep(DECODE_STEP_COST_S)
        now = time.monotonic()
        self.rec.step(self.active, now)
        self.broker.touch_handoffs([r["id"] for r in self.active])
        done = [r for r in self.active if r["left"] <= 1]
        self.active = [r for r in self.active if r["left"] > 1]
        for r in self.active:
            r["left"] -= 1
        for r in done:  # push_response acks the handoff lease
            self.broker.push_response(GenerateResponse(
                id=r["id"], token_ids=[0],
            ))


def make_trace() -> list[GenerateRequest]:
    """Mixed trace, interleaved so long prefills keep landing while
    short interactive rows are mid-decode."""
    longs = [
        GenerateRequest(
            token_ids=[1000 + i] * LONG_PROMPT, max_new_tokens=LONG_NEW,
        )
        for i in range(N_LONG)
    ]
    shorts = [
        GenerateRequest(
            token_ids=[2000 + i] * SHORT_PROMPT, max_new_tokens=SHORT_NEW,
        )
        for i in range(N_SHORT)
    ]
    out: list[GenerateRequest] = []
    ratio = max(1, N_SHORT // max(N_LONG, 1))
    while longs or shorts:
        if longs:
            out.append(longs.pop(0))
        for _ in range(ratio):
            if shorts:
                out.append(shorts.pop(0))
    return out


def run_mode(mode: str) -> dict:
    broker = InProcBroker()
    rec = _Recorder()
    if mode == "unified":
        workers = [
            UnifiedSim(f"u{i}", broker, rec) for i in range(N_CHIPS)
        ]
    else:
        workers = [
            PrefillSim("prefill0", broker, rec),
            DecodeSim("decode0", broker, rec),
        ]
    reqs = make_trace()
    for w in workers:
        w.start()
    t0 = time.monotonic()
    for r in reqs:
        rec.submit_ts[r.id] = time.monotonic()
        broker.push_request(r)
        time.sleep(ARRIVAL_GAP_S)
    lost = errored = 0
    for r in reqs:
        resp = broker.wait_response(r.id, timeout=60.0)
        if resp is None:
            lost += 1
        elif resp.error:
            errored += 1
    elapsed = time.monotonic() - t0
    for w in workers:
        w.stop()
    stats = broker.delivery_stats()
    gaps_ms = [g * 1e3 for g in rec.gaps]
    out = {
        "mode": mode,
        "requests": len(reqs),
        "lost": lost,
        "errored": errored,
        "tokens": rec.tokens,
        "tok_s_chip": round(rec.tokens / elapsed / N_CHIPS, 1),
        "ttft_p50_ms": round(statistics.median(rec.ttfts) * 1e3, 3),
        "ttft_p95_ms": round(
            statistics.quantiles(rec.ttfts, n=20)[18] * 1e3, 3
        ),
        "decode_step_ms_mean": round(statistics.fmean(gaps_ms), 3),
        "decode_step_ms_stdev": round(statistics.stdev(gaps_ms), 3),
        "decode_step_ms_p95": round(
            statistics.quantiles(gaps_ms, n=20)[18], 3
        ),
        "handoffs": stats.get("handoffs", 0),
        "handoff_bytes": stats.get("handoff_bytes", 0),
        "handoff_bytes_per_request": (
            round(stats["handoff_bytes"] / stats["handoffs"])
            if stats.get("handoffs") else 0
        ),
        "reprefills": stats.get("reprefills", 0),
        "elapsed_s": round(elapsed, 3),
    }
    return out


def main():
    unified = run_mode("unified")
    disagg = run_mode("disagg")
    from bench import bench_provenance

    result = {
        "config": {
            "chips": N_CHIPS,
            "rows_per_chip": ROWS,
            "trace": {
                "long": {"n": N_LONG, "prompt": LONG_PROMPT,
                         "max_new": LONG_NEW},
                "short": {"n": N_SHORT, "prompt": SHORT_PROMPT,
                          "max_new": SHORT_NEW},
                "arrival_gap_s": ARRIVAL_GAP_S,
            },
            "prefill_token_cost_s": PREFILL_TOKEN_COST_S,
            "decode_step_cost_s": DECODE_STEP_COST_S,
            "adopt_const_s": ADOPT_CONST_S,
            "kv_bytes_per_token": KV_BYTES_PER_TOKEN,
            "hbm_gbps": HBM_GBPS,
        },
        "unified": unified,
        "disagg": disagg,
        "provenance": bench_provenance(),
    }
    # The claims the subsystem ships on: nothing lost or errored, every
    # multi-token request handed off exactly once, and the decode chip's
    # step cadence freed of prefill stalls.
    for mode in (unified, disagg):
        assert mode["lost"] == 0 and mode["errored"] == 0, result
    assert disagg["handoffs"] == N_LONG + N_SHORT, result
    assert unified["handoffs"] == 0, result
    assert (
        disagg["decode_step_ms_stdev"] < unified["decode_step_ms_stdev"]
    ), result
    path = os.path.join(
        os.path.dirname(os.path.dirname(os.path.abspath(__file__))),
        "PD_BENCH.json",
    )
    with open(path, "w") as f:
        json.dump(result, f, indent=1)
        f.write("\n")
    print(json.dumps({
        "metric": "pd_disagg_decode_tok_s_chip",
        "value": disagg["tok_s_chip"],
        "unit": (
            f"tok/s/chip sim ({N_CHIPS} chips, 1P+1D vs {N_CHIPS} unified"
            f"={unified['tok_s_chip']}; decode step stdev "
            f"{disagg['decode_step_ms_stdev']} vs "
            f"{unified['decode_step_ms_stdev']} ms, ttft_p95 "
            f"{disagg['ttft_p95_ms']} vs {unified['ttft_p95_ms']} ms, "
            f"{disagg['handoff_bytes_per_request'] / 1e6:.1f} MB/handoff)"
        ),
        "vs_baseline": round(
            disagg["tok_s_chip"] / max(unified["tok_s_chip"], 1e-9), 3
        ),
    }))


if __name__ == "__main__":
    main()
