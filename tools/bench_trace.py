"""Tracing-overhead bench: the serve host path with the recorder on vs off.

The flight recorder (utils/trace.py) is host-side bookkeeping on the
request path — producer admission, broker lease/handoff churn, worker
spans, scheduler events. Its acceptance bar is that end-to-end serve
throughput with tracing ENABLED stays within 2% of DISABLED. This bench
pins that number on the worst case for instrumentation: ScriptedEngine
workers (no model math, no device), so every recorded event is pure
overhead against an already-cheap host loop. A real fleet amortizes the
same events over device steps, so the real overhead is strictly lower
than what this prints.

Workload: N requests ride producer push → broker queue → PrefillWorker →
LKVH handoff → DecodeWorker → response on an InProcBroker, single-thread
run_once stepping (deterministic; no scheduler-jitter noise). Each mode
runs REPEATS times; best-of is compared (best-of isolates the code path
from machine noise, which is the honest comparison for a <2% question).

Two numbers come out:

- ``host_overhead_us_per_request`` — the raw instrumentation microcost,
  measured with zero simulated chip time (every microsecond is tracing).
- ``overhead_pct`` — the acceptance number: end-to-end throughput delta
  with ``DECODE_STEP_COST_S`` charged per decode chunk (the bench_pd.py
  cost-model convention; the default 2 ms/chunk is conservative — real
  fused-step times are larger, which shrinks the relative overhead).

Runs on CPU in one process (no JAX, no device). Writes TRACE_BENCH.json;
prints one JSON line. Asserts zero lost requests in both modes and that
the traced mode leaves a complete timeline for a sampled request.
"""

from __future__ import annotations

import json
import os
import sys
import time

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

from llmss_tpu.serve.broker import InProcBroker  # noqa: E402
from llmss_tpu.serve.chaos import ScriptedEngine  # noqa: E402
from llmss_tpu.serve.handoff import DecodeWorker, PrefillWorker  # noqa: E402
from llmss_tpu.serve.protocol import GenerateRequest  # noqa: E402
from llmss_tpu.utils import trace  # noqa: E402

N_REQUESTS = int(os.environ.get("TRACE_BENCH_REQUESTS", 400))
MAX_NEW = int(os.environ.get("TRACE_BENCH_MAX_NEW", 32))
PROMPT_LEN = int(os.environ.get("TRACE_BENCH_PROMPT", 16))
REPEATS = int(os.environ.get("TRACE_BENCH_REPEATS", 3))
DECODE_STEP_COST_S = float(os.environ.get("TRACE_STEP_COST_S", 0.002))


def run_once(enabled: bool, chunk_delay_s: float = 0.0) -> float:
    """One full serve pass; returns wall seconds for N_REQUESTS."""
    trace.set_enabled(enabled)
    trace.recorder().clear()
    b = InProcBroker(lease_s=30.0)
    pre = PrefillWorker(
        ScriptedEngine(chunk_delay_s=chunk_delay_s), b, worker_id="p0",
    )
    dec = DecodeWorker(
        ScriptedEngine(chunk_delay_s=chunk_delay_s), b, worker_id="d0",
    )
    reqs = [
        GenerateRequest(
            id=f"b{i}",
            token_ids=[(i + j) % 50257 for j in range(PROMPT_LEN)],
            max_new_tokens=MAX_NEW,
        )
        for i in range(N_REQUESTS)
    ]
    t0 = time.monotonic()
    for r in reqs:
        b.push_request(r)
    done = 0
    while done < N_REQUESTS:
        pre.run_once()
        dec.run_once()
        while b.wait_response(reqs[done].id, timeout=0.0) is not None:
            done += 1
            if done == N_REQUESTS:
                break
    elapsed = time.monotonic() - t0

    if enabled:
        tl = trace.timeline([trace.recorder().export()], reqs[-1].id)
        assert tl is not None and tl["events"][-1]["name"] == "respond"
    else:
        assert trace.recorder().req_ids() == []
    return elapsed


def main() -> int:
    # Pass 1 — zero chip time: the instrumentation microcost itself.
    host = {"on": float("inf"), "off": float("inf")}
    for _ in range(REPEATS):
        for mode in ("off", "on"):
            host[mode] = min(host[mode], run_once(mode == "on"))
    host_us_per_req = (host["on"] - host["off"]) / N_REQUESTS * 1e6

    # Pass 2 — the acceptance workload: decode chunks cost chip time.
    best = {"on": float("inf"), "off": float("inf")}
    for _ in range(REPEATS):
        for mode in ("off", "on"):
            best[mode] = min(
                best[mode], run_once(mode == "on", DECODE_STEP_COST_S),
            )
    trace.set_enabled(True)  # restore the default

    tokens = N_REQUESTS * MAX_NEW
    tput_on = tokens / best["on"]
    tput_off = tokens / best["off"]
    overhead_pct = (best["on"] - best["off"]) / best["off"] * 100.0
    out = {
        "bench": "trace_overhead",
        "requests": N_REQUESTS,
        "max_new_tokens": MAX_NEW,
        "repeats": REPEATS,
        "decode_step_cost_s": DECODE_STEP_COST_S,
        "host_overhead_us_per_request": round(host_us_per_req, 1),
        "wall_s_tracing_off": round(best["off"], 4),
        "wall_s_tracing_on": round(best["on"], 4),
        "tok_per_s_tracing_off": round(tput_off, 1),
        "tok_per_s_tracing_on": round(tput_on, 1),
        "overhead_pct": round(overhead_pct, 2),
        "within_2pct": overhead_pct < 2.0,
    }
    with open("TRACE_BENCH.json", "w") as f:
        json.dump(out, f, indent=2)
    print(json.dumps(out))
    return 0 if out["within_2pct"] else 1


if __name__ == "__main__":
    sys.exit(main())
