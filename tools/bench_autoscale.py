"""Autoscale bench: closed-loop fleet controller vs static peak fleet.

Runs the ``scenarios/diurnal.json`` scenario — a diurnal heavy-tailed
arrival trace — through the deterministic fleet simulator in five arms:

- **controlled**: floor-sized fleet + the reconciling FleetController
  (``serve/controller.py``) spawning/retiring replicas from burn,
  backlog, and utilization telemetry.
- **static**: the same trace on a fixed fleet sized at the controlled
  arm's PEAK replica count — what you must provision without a
  controller.
- **killwave_fast**: a 6-replica kill wave at the evening peak with a
  2s cold start (well inside the 10s burn headroom). The controller
  must replace the dead capacity while the brownout ladder never moves:
  every escalation ask is suppressed (scale-before-shed).
- **killwave_slow**: the same wave with a 30s cold start (past the burn
  headroom). Scaling structurally cannot respond in time, so the
  controller must ALLOW the ladder to engage — shedding is the correct
  lever, and the bench asserts it actually fired.
- **crash**: the controller is crashed mid-climb and restarted 3s later
  as a brand-new instance reconciling from the registry, while the dead
  instance keeps ticking as a zombie. Zero duplicate spawns (checker-
  certified) and every zombie actuation dies at the epoch fence. A
  telemetry stall overlay asserts the staleness hold.

The headline check: the controlled fleet spends FEWER replica-seconds
(chip-hours) than the static peak fleet at equal-or-better per-class
TTFT SLO attainment. Receipt: ``AUTOSCALE_BENCH.json``.

    python tools/bench_autoscale.py
    python tools/bench_autoscale.py --check-determinism --out -
"""

from __future__ import annotations

import argparse
import copy
import json
import os
import sys

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

from llmss_tpu.sim import run_scenario  # noqa: E402

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
DEFAULT_SCENARIO = os.path.join(REPO, "scenarios", "diurnal.json")

# Attainment slack for "equal-or-better": the controlled arm rides
# closer to the edge by design; more than this is a real SLO regression.
ATTAINMENT_EPS = 0.02

KILL_WAVE = {
    "kind": "kill_wave", "at_s": 270.0, "count": 6,
    "respawn_after_s": None, "stagger_s": 0.5,
}


def _arm_specs(base: dict) -> dict[str, dict]:
    """The five arm specs, all derived from the one scenario file."""
    arms: dict[str, dict] = {}

    arms["controlled"] = copy.deepcopy(base)

    fast = copy.deepcopy(base)
    fast["fleet"]["controller"]["cold_start_s"] = 2.0
    fast["fleet"]["controller"]["ceiling"] = 16
    fast["fleet"]["brownout"]["high"] = 2.0
    fast["faults"] = [copy.deepcopy(KILL_WAVE)]
    arms["killwave_fast"] = fast

    slow = copy.deepcopy(base)
    slow["fleet"]["controller"]["cold_start_s"] = 30.0
    slow["fleet"]["controller"]["ceiling"] = 16
    slow["fleet"]["brownout"]["high"] = 2.0
    slow["faults"] = [copy.deepcopy(KILL_WAVE)]
    arms["killwave_slow"] = slow

    crash = copy.deepcopy(base)
    crash["faults"] = [
        {"kind": "controller_crash", "at_s": 95.0,
         "restart_after_s": 3.0, "zombie": True},
        {"kind": "telemetry_stall", "at_s": 120.0, "duration_s": 8.0},
    ]
    arms["crash"] = crash

    return arms


def _static_spec(base: dict, peak: int) -> dict:
    st = copy.deepcopy(base)
    del st["fleet"]["controller"]
    st["fleet"].pop("brownout", None)
    st["fleet"]["replicas"] = [
        {**base["fleet"]["replicas"][0], "count": peak},
    ]
    return st


def _attainment(report: dict) -> dict[str, float]:
    return {
        cls: v["ttft_attainment"]
        for cls, v in (report.get("classes") or {}).items()
        if v.get("ttft_attainment") is not None
    }


def _summarize(name: str, rep: dict) -> dict:
    fl = rep.get("fleet") or {}
    cc = (fl.get("controller") or {}).get("counters") or {}
    bo = fl.get("brownout") or {}
    return {
        "arm": name,
        "virtual_s": rep["virtual_s"],
        "replica_seconds": fl.get("replica_seconds"),
        "peak_alive": fl.get("peak_alive"),
        "spawns": fl.get("spawns"),
        "retires": fl.get("retires"),
        "zombie_fenced": fl.get("zombie_fenced"),
        "controller_counters": cc or None,
        "brownout_transitions": bo.get("transitions_total"),
        "brownout_suppressed": bo.get("suppressed_escalations"),
        "kills": rep["faults"].get("kills", 0),
        "controller_crashes": rep["faults"].get("controller_crashes", 0),
        "controller_restarts": rep["faults"].get("controller_restarts", 0),
        "shed": sum(
            v["shed"] for v in (rep.get("classes") or {}).values()
        ),
        "attainment": _attainment(rep),
        "violations": rep["invariants"]["violations"],
    }


def run_all(scenario_path: str, n_requests: int | None,
            seed: int | None) -> dict:
    from llmss_tpu.sim.scenario import load_scenario

    base = load_scenario(scenario_path)
    arms = _arm_specs(base)
    reports = {
        name: run_scenario(
            copy.deepcopy(spec), n_requests=n_requests, seed=seed,
        )
        for name, spec in arms.items()
    }
    peak = reports["controlled"]["fleet"]["peak_alive"]
    static_spec = _static_spec(base, peak)
    reports["static"] = run_scenario(
        copy.deepcopy(static_spec), n_requests=n_requests, seed=seed,
    )

    ctl, sta = reports["controlled"], reports["static"]
    fast, slow = reports["killwave_fast"], reports["killwave_slow"]
    crash = reports["crash"]

    ctl_chips = ctl["fleet"]["replica_seconds"]
    # A static fleet pays for every replica over the whole span.
    sta_chips = round(peak * sta["virtual_s"], 6)
    ctl_att, sta_att = _attainment(ctl), _attainment(sta)

    fast_bo = fast["fleet"]["brownout"]
    slow_bo = slow["fleet"]["brownout"]
    checks = {
        # Headline: fewer chip-seconds at equal-or-better attainment.
        "controlled_fewer_chips": ctl_chips < sta_chips,
        "equal_or_better_slo": all(
            ctl_att.get(cls, 0.0) >= sta_att[cls] - ATTAINMENT_EPS
            for cls in sta_att
        ),
        # Kill wave, cold start inside the burn headroom: the controller
        # replaces dead capacity and the ladder never moves — every
        # escalation ask suppressed, nothing shed.
        "killwave_fast_controller_replaces": (
            fast["faults"].get("kills", 0) == KILL_WAVE["count"]
            and fast["fleet"]["spawns"] >= KILL_WAVE["count"]
        ),
        "killwave_fast_brownout_never_moves": (
            fast_bo["transitions_total"] == 0
            and fast_bo["suppressed_escalations"] > 0
        ),
        # Kill wave, cold start past the burn headroom: scaling cannot
        # respond in time, so the ladder MUST engage.
        "killwave_slow_brownout_engages": (
            slow_bo["transitions_total"] > 0
            and slow["fleet"]["controller"]["counters"][
                "escalations_allowed"] > 0
        ),
        # Crash + zombie: a fresh epoch reconciles with zero duplicate
        # spawns (any dup is an invariant violation) and every actuation
        # the zombie plans dies at the epoch fence.
        "crash_restart_reconciles": (
            crash["faults"].get("controller_crashes", 0) == 1
            and crash["faults"].get("controller_restarts", 0) == 1
        ),
        "crash_zombie_fenced": (
            crash["fleet"]["zombie_fenced"] > 0
            and crash["fleet"]["controller"]["counters"]["fenced"] == 0
        ),
        "crash_stale_telemetry_holds": (
            crash["faults"].get("telemetry_stalls", 0) == 1
            and crash["fleet"]["controller"]["counters"]["held_stale"] > 0
        ),
        "zero_invariant_violations": all(
            r["invariants"]["violations"] == 0 for r in reports.values()
        ),
    }

    return {
        "bench": "fleet_autoscale",
        "scenario_file": os.path.relpath(scenario_path, REPO),
        "chips": {
            "controlled_replica_seconds": ctl_chips,
            "static_replica_seconds": sta_chips,
            "savings_frac": round(1.0 - ctl_chips / sta_chips, 6),
            "static_fleet_size": peak,
        },
        "attainment": {"controlled": ctl_att, "static": sta_att},
        "arms": {n: _summarize(n, r) for n, r in reports.items()},
        "checks": checks,
    }


def main(argv: list[str] | None = None) -> int:
    ap = argparse.ArgumentParser(description=__doc__.split("\n")[0])
    ap.add_argument("--scenario", default=DEFAULT_SCENARIO)
    ap.add_argument(
        "--requests", type=int, default=None,
        help="override the scenario's request count (NOTE: the kill-wave "
             "overlays fire at fixed virtual times — shrinking the trace "
             "below them voids those checks)",
    )
    ap.add_argument("--seed", type=int, default=None)
    ap.add_argument(
        "--out", default=os.path.join(REPO, "AUTOSCALE_BENCH.json"),
        help="receipt path (default AUTOSCALE_BENCH.json at repo root); "
             "'-' skips the write",
    )
    ap.add_argument(
        "--check-determinism", action="store_true",
        help="run every arm twice and fail unless the serialized results "
             "are byte-identical",
    )
    args = ap.parse_args(argv)

    result = run_all(args.scenario, args.requests, args.seed)
    if args.check_determinism:
        again = run_all(args.scenario, args.requests, args.seed)
        a = json.dumps(result, sort_keys=True)
        b = json.dumps(again, sort_keys=True)
        if a != b:
            print("DETERMINISM FAIL: same-seed re-run differs",
                  file=sys.stderr)
            return 1
        print("determinism: byte-identical same-seed re-run",
              file=sys.stderr)

    from bench import bench_provenance

    checks = result["checks"]
    passed = sum(bool(v) for v in checks.values())
    ok = passed == len(checks)
    receipt = {
        **result,
        # Flat count for bench_trend's AUTOSCALE_BENCH family: the
        # regression gate compares this across revisions.
        "checks_passed": passed,
        "provenance": bench_provenance(),
    }
    if args.out != "-":
        with open(args.out, "w") as f:
            json.dump(receipt, f, indent=1, sort_keys=True)
            f.write("\n")

    ch = result["chips"]
    print(json.dumps({
        "metric": "autoscale_checks_passed",
        "value": passed,
        "unit": (
            f"of {len(checks)} checks (controlled "
            f"{ch['controlled_replica_seconds']} vs static "
            f"{ch['static_replica_seconds']} replica-s, "
            f"{round(ch['savings_frac'] * 100, 1)}% saved at fleet size "
            f"{ch['static_fleet_size']}; failed: "
            f"{sorted(k for k, v in checks.items() if not v) or 'none'})"
        ),
        "ok": ok,
    }))
    return 0 if ok else 1


if __name__ == "__main__":
    sys.exit(main())
