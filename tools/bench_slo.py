"""SLO-plane overhead bench: cost attribution + windowed series on the hot path.

The SLO plane (PR 11) adds two things to every request on top of the
flight recorder: a terminal ``RequestCost`` derivation at broker
``push_response`` (one timeline scan + ~10 windowed-series updates) and
the per-heartbeat cached series export. Its acceptance bar: at most
~25 µs of host time per request over tracing alone, and under 1%
end-to-end throughput delta on the cost-model workload.

Three modes isolate the increments:

- ``off``   — recorder disabled: nothing records (the LLMSS_TRACE=0 path).
- ``trace`` — recorder on, but the cost-ingestion hook stubbed out: the
  PR-10 tracing baseline.
- ``slo``   — everything on: cost records derived and folded into the
  windowed registry at each respond.

Workload mirrors tools/bench_trace.py: N requests over InProcBroker →
PrefillWorker → LKVH → DecodeWorker with ScriptedEngine (no device,
worst case for instrumentation). The microcost is timed directly on the
respond-path hook over real recorded timelines (deterministic); the
throughput delta comes from median-of-paired adjacent trace/slo runs
with DECODE_STEP_COST_S charged per decode chunk, which cancels machine
drift a best-of comparison cannot. Writes SLO_BENCH.json with the
standard bench_provenance stamp; prints one JSON line.
"""

from __future__ import annotations

import json
import os
import sys
import time

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

from bench import bench_provenance  # noqa: E402
from llmss_tpu.serve import broker as broker_mod  # noqa: E402
from llmss_tpu.serve.broker import InProcBroker  # noqa: E402
from llmss_tpu.serve.chaos import ScriptedEngine  # noqa: E402
from llmss_tpu.serve.handoff import DecodeWorker, PrefillWorker  # noqa: E402
from llmss_tpu.serve.protocol import GenerateRequest  # noqa: E402
from llmss_tpu.utils import metrics as metrics_mod  # noqa: E402
from llmss_tpu.utils import trace  # noqa: E402

N_REQUESTS = int(os.environ.get("SLO_BENCH_REQUESTS", 400))
MAX_NEW = int(os.environ.get("SLO_BENCH_MAX_NEW", 32))
PROMPT_LEN = int(os.environ.get("SLO_BENCH_PROMPT", 16))
REPEATS = int(os.environ.get("SLO_BENCH_REPEATS", 5))
DECODE_STEP_COST_S = float(os.environ.get("SLO_STEP_COST_S", 0.002))

US_PER_REQ_BUDGET = 25.0
THROUGHPUT_PCT_BUDGET = 1.0


def run_once(mode: str, chunk_delay_s: float = 0.0) -> float:
    """One full serve pass in ``mode``; returns wall seconds."""
    trace.set_enabled(mode != "off")
    trace.recorder().clear()
    metrics_mod.series().clear()
    stubbed = None
    if mode == "trace":
        stubbed = broker_mod._observe_cost
        broker_mod._observe_cost = lambda resp: None
    try:
        b = InProcBroker(lease_s=30.0)
        pre = PrefillWorker(
            ScriptedEngine(chunk_delay_s=chunk_delay_s), b, worker_id="p0",
        )
        dec = DecodeWorker(
            ScriptedEngine(chunk_delay_s=chunk_delay_s), b, worker_id="d0",
        )
        reqs = [
            GenerateRequest(
                id=f"s{i}",
                token_ids=[(i + j) % 50257 for j in range(PROMPT_LEN)],
                max_new_tokens=MAX_NEW,
            )
            for i in range(N_REQUESTS)
        ]
        t0 = time.monotonic()
        for r in reqs:
            b.push_request(r)
        done = 0
        while done < N_REQUESTS:
            pre.run_once()
            dec.run_once()
            while b.wait_response(reqs[done].id, timeout=0.0) is not None:
                done += 1
                if done == N_REQUESTS:
                    break
        elapsed = time.monotonic() - t0
    finally:
        if stubbed is not None:
            broker_mod._observe_cost = stubbed

    if mode == "slo":
        # every request produced exactly one terminal cost record
        total = metrics_mod.series().counter("requests_total").total
        assert total == N_REQUESTS, (total, N_REQUESTS)
    return elapsed


def main() -> int:
    for m in ("off", "trace", "slo"):  # warmup off the clock
        run_once(m)

    def paired(chunk_delay_s: float, pairs: int):
        """Median slo-minus-trace delta over adjacent (trace, slo) pairs.

        Machine drift here dwarfs the ~10ms signal over a multi-minute
        sweep, so diff-of-best-runs is hopeless; adjacent pairs see the
        same drift and difference it away. Within-pair order alternates
        to cancel ordering bias; median rejects the loud outlier pairs.
        """
        deltas, t_tr, t_slo = [], float("inf"), float("inf")
        for p in range(pairs):
            order = ("trace", "slo") if p % 2 == 0 else ("slo", "trace")
            got = {m: run_once(m, chunk_delay_s) for m in order}
            deltas.append(got["slo"] - got["trace"])
            t_tr = min(t_tr, got["trace"])
            t_slo = min(t_slo, got["slo"])
        deltas.sort()
        return deltas[len(deltas) // 2], t_tr, t_slo

    # Pass 1 — the plane's host microcost: time the exact respond-path
    # hook (local_cost + observe_request_cost) over the REAL timelines the
    # warmup's slo run left in the recorder. Deterministic where a
    # wall-clock A/B of whole ~100ms serve loops is noise-bound around a
    # ~10ms signal. (Re-ingesting inflates the registry's cumulative
    # counters; nothing below reads them.)
    run_once("slo")
    ids = trace.recorder().req_ids()
    hook_best = float("inf")
    for _ in range(10 * REPEATS):
        t0 = time.monotonic()
        for rid in ids:
            c = trace.local_cost(rid)
            if c is not None:
                metrics_mod.observe_request_cost(c)
        hook_best = min(hook_best, (time.monotonic() - t0) / len(ids))
    slo_us_per_req = hook_best * 1e6

    # Pass 2 — acceptance workload: decode chunks cost chip time.
    d_e2e, best_trace, best_slo = paired(DECODE_STEP_COST_S, 2 * REPEATS)
    overhead_pct = d_e2e / best_trace * 100.0
    best = {
        "off": min(run_once("off", DECODE_STEP_COST_S)
                   for _ in range(REPEATS)),
        "trace": best_trace,
        "slo": best_slo,
    }

    # On-demand cost: one /slo evaluation over the registry the slo pass
    # left behind (informational — this is endpoint-time, not hot-path).
    exports = [metrics_mod.series().export()]
    t0 = time.monotonic()
    slo_payload = metrics_mod.evaluate_slos(exports)
    eval_ms = (time.monotonic() - t0) * 1e3
    assert slo_payload["objectives"], "SLO evaluation returned no objectives"
    trace.set_enabled(True)  # restore the default

    tokens = N_REQUESTS * MAX_NEW
    out = {
        "bench": "slo_plane_overhead",
        "provenance": bench_provenance(),
        "requests": N_REQUESTS,
        "max_new_tokens": MAX_NEW,
        "repeats": REPEATS,
        "decode_step_cost_s": DECODE_STEP_COST_S,
        "slo_overhead_us_per_request": round(slo_us_per_req, 1),
        "wall_s_off": round(best["off"], 4),
        "wall_s_trace": round(best["trace"], 4),
        "wall_s_slo": round(best["slo"], 4),
        "tok_per_s_trace": round(tokens / best["trace"], 1),
        "tok_per_s_slo": round(tokens / best["slo"], 1),
        "overhead_pct_vs_trace": round(overhead_pct, 2),
        "slo_eval_ms": round(eval_ms, 2),
        "us_budget": US_PER_REQ_BUDGET,
        "pct_budget": THROUGHPUT_PCT_BUDGET,
        "within_budget": (
            slo_us_per_req <= US_PER_REQ_BUDGET
            and overhead_pct < THROUGHPUT_PCT_BUDGET
        ),
    }
    with open("SLO_BENCH.json", "w") as f:
        json.dump(out, f, indent=2)
    print(json.dumps(out))
    return 0 if out["within_budget"] else 1


if __name__ == "__main__":
    sys.exit(main())
