"""End-to-end HTTP serving smoke on the synthesized 1.2B checkpoint.

The round-4 verdict's remaining real-checkpoint gap: the 1.2B multi-file
checkpoint (tools/bench_load.py's) had been loaded and CLI-driven but
never served through the HTTP stack. This drives, on the real chip:

    HTTP client → ProducerServer (real sockets, localhost)
      → broker → ContinuousWorker (continuous batcher) → engine
      → streamed SSE + JSON responses back over HTTP

with the checkpoint loaded through the full loader path (index.json +
5 sharded safetensors via the native read plane). The bench host has no
Redis (no server binary, no client lib), so the broker is the in-process
implementation; the Redis transport is exercised by
tests/test_serve.py's broker-compatibility suite instead.

Appends results to SMOKE_REAL_CKPT.md and prints a JSON summary.
Run: ``python tools/smoke_serve_1b2.py`` (checkpoint is synthesized on
first use at /tmp/llmss-1b2-ckpt — see tools/bench_load.py).
"""

from __future__ import annotations

import json
import os
import sys
import threading
import time
import urllib.request

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

from tools.bench_load import ensure_checkpoint  # noqa: E402

N_REQUESTS = int(os.environ.get("SMOKE_REQS", 24))
DECODE = int(os.environ.get("SMOKE_DECODE", 64))
PROMPT_LEN = int(os.environ.get("SMOKE_PROMPT", 64))


def main():
    import jax
    import numpy as np

    from llmss_tpu.engine import DecodeEngine
    from llmss_tpu.models.registry import load_model
    from llmss_tpu.parallel import MeshPlan, make_mesh
    from llmss_tpu.serve.broker import InProcBroker
    from llmss_tpu.serve.consumer import ContinuousWorker
    from llmss_tpu.serve.producer import ProducerServer

    ckpt = ensure_checkpoint()
    mesh = make_mesh(MeshPlan(tp=len(jax.devices())))
    t0 = time.time()
    cfg, params = load_model(str(ckpt), mesh)
    load_s = time.time() - t0
    engine = DecodeEngine(
        cfg, params, mesh, max_seq_len=PROMPT_LEN + DECODE,
    )
    broker = InProcBroker()
    worker = ContinuousWorker(
        engine, broker, tokenizer=None, rows=8, chunk_steps=16,
    )
    t0 = time.time()
    n_exec = worker.prewarm(seq_buckets=[PROMPT_LEN])
    prewarm_s = time.time() - t0

    server = ProducerServer(broker, host="127.0.0.1", port=0)
    server.start()
    stop = threading.Event()
    wt = threading.Thread(target=worker.run_forever, args=(stop,),
                          daemon=True)
    wt.start()
    base = f"http://127.0.0.1:{server.port}"

    rng = np.random.default_rng(0)
    lat: dict[str, float] = {}
    streamed_events = {"n": 0}
    errors = []
    lock = threading.Lock()

    def one_request(i: int):
        body = {
            "id": f"smoke-{i}",
            "token_ids": rng.integers(
                0, cfg.vocab_size, PROMPT_LEN
            ).tolist(),
            "max_new_tokens": DECODE,
            "is_greedy": True,
            "stream": i % 4 == 0,  # every 4th request over SSE
        }
        req = urllib.request.Request(
            base + "/generate", data=json.dumps(body).encode(),
            headers={"Content-Type": "application/json"},
        )
        t0 = time.time()
        try:
            with urllib.request.urlopen(req, timeout=300) as r:
                if body["stream"]:
                    n_tok, first_t = 0, None
                    for line in r:
                        if line.startswith(b"data: "):
                            if first_t is None:
                                first_t = time.time() - t0
                            payload = json.loads(line[6:])
                            n_tok += len(payload.get("token_ids", []))
                            with lock:
                                streamed_events["n"] += 1
                    ok = n_tok >= DECODE
                else:
                    resp = json.loads(r.read())
                    first_t = time.time() - t0
                    ok = len(resp.get("token_ids", [])) == DECODE
            if not ok:
                raise RuntimeError(f"short response for smoke-{i}")
            with lock:
                lat[f"smoke-{i}"] = first_t
        except Exception as e:  # noqa: BLE001 — smoke must report, not die
            with lock:
                errors.append(f"smoke-{i}: {e!r}")

    t_start = time.time()
    threads = [
        threading.Thread(target=one_request, args=(i,), daemon=True)
        for i in range(N_REQUESTS)
    ]
    for i, t in enumerate(threads):
        t.start()
        time.sleep(0.05 if i % 4 else 0.0)
    for t in threads:
        t.join(timeout=300)
    wall = time.time() - t_start
    stop.set()
    server.stop()

    m = engine.metrics.to_dict()
    summary = {
        "checkpoint": str(ckpt),
        "params_load_s": round(load_s, 1),
        "prewarm_execs": n_exec,
        "prewarm_s": round(prewarm_s, 1),
        "requests": N_REQUESTS,
        "served_ok": len(lat),
        "errors": errors,
        "sse_events": streamed_events["n"],
        "wall_s": round(wall, 1),
        "tokens_generated": m["tokens_generated"],
        "serve_tok_s": round(m["tokens_generated"] / wall, 1),
        "ttft_p50_ms": m["ttft"]["p50_ms"],
        "ttft_p95_ms": m["ttft"]["p95_ms"],
    }
    print(json.dumps(summary))
    assert not errors and len(lat) == N_REQUESTS, summary

    md = f"""

## HTTP serving smoke on the 1.2B checkpoint (round 5)

Produced by `tools/smoke_serve_1b2.py` on the real chip: the synthesized
1.2B sharded checkpoint (5 safetensors files + index.json,
`tools/bench_load.py`) loaded through the native read plane
({summary['params_load_s']} s cold-ish), served through the REAL HTTP
stack — `ProducerServer` on localhost sockets → broker →
`ContinuousWorker` (continuous batching, rows=8, chunk=16) — to
{N_REQUESTS} concurrent HTTP clients ({PROMPT_LEN}-token prompts,
{DECODE} greedy tokens each, every 4th over SSE streaming).

- served: **{summary['served_ok']}/{N_REQUESTS}** (0 errors),
  {summary['sse_events']} SSE increment events delivered
- throughput: **{summary['serve_tok_s']} tok/s** over {summary['wall_s']} s
  wall (includes ramp-up/drain of a smoke-sized run)
- client-side TTFT p50: **{summary['ttft_p50_ms']} ms**
  (p95 {summary['ttft_p95_ms']} ms)
- prewarm: {summary['prewarm_execs']} executables in
  {summary['prewarm_s']} s (no mid-serve compiles)

No Redis on the bench host (no server binary or client lib): the broker
is the in-process implementation; the Redis transport is covered by
`tests/test_serve.py`'s broker-compatibility suite.
"""
    with open(os.path.join(os.path.dirname(os.path.dirname(
            os.path.abspath(__file__))), "SMOKE_REAL_CKPT.md"), "a") as f:
        f.write(md)


if __name__ == "__main__":
    main()
