"""Fleet storm: a million simulated requests through repeated failure.

Runs the ``scenarios/storm.json`` scenario on the deterministic fleet
simulator (``llmss_tpu.sim``): a 16-replica mixed unified +
prefill/decode fleet absorbing ~1M requests at ~1500 rps while seeded
correlated kill waves, broker partitions, fleet-wide latency spikes,
heartbeat stalls, and handoff-mid-kill storms fire every few tens of
virtual seconds — with the full invariant catalog (exactly-one terminal
response, zero lost / zero double-answered, preemption refunds consume
no delivery attempts, KV accounts balance at drain, DLQ holds only
genuine poison) asserted continuously and at drain.

The run is byte-reproducible: same scenario + same seed produces a
byte-identical ``STORM_BENCH.json`` (``--check-determinism`` proves it
by running twice and comparing serialized reports). ``--requests``
scales the storm down for CI without touching the scenario file.

    python tools/sim_storm.py                         # the full 1M storm
    python tools/sim_storm.py --requests 20000 --check-determinism
"""

from __future__ import annotations

import argparse
import json
import os
import sys

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

from llmss_tpu.sim import run_scenario  # noqa: E402

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
DEFAULT_SCENARIO = os.path.join(REPO, "scenarios", "storm.json")


def main(argv: list[str] | None = None) -> int:
    ap = argparse.ArgumentParser(description=__doc__.split("\n")[0])
    ap.add_argument("--scenario", default=DEFAULT_SCENARIO)
    ap.add_argument(
        "--requests", type=int, default=None,
        help="override the scenario's request count (CI scale-down)",
    )
    ap.add_argument("--seed", type=int, default=None)
    ap.add_argument(
        "--out", default=os.path.join(REPO, "STORM_BENCH.json"),
        help="receipt path (default STORM_BENCH.json at repo root); "
             "'-' skips the write",
    )
    ap.add_argument(
        "--check-determinism", action="store_true",
        help="run the scenario twice and fail unless the serialized "
             "reports are byte-identical",
    )
    args = ap.parse_args(argv)

    report = run_scenario(
        args.scenario, n_requests=args.requests, seed=args.seed,
    )
    if args.check_determinism:
        again = run_scenario(
            args.scenario, n_requests=args.requests, seed=args.seed,
        )
        a = json.dumps(report, sort_keys=True)
        b = json.dumps(again, sort_keys=True)
        if a != b:
            print("DETERMINISM FAIL: same-seed re-run differs",
                  file=sys.stderr)
            return 1
        print("determinism: byte-identical same-seed re-run", file=sys.stderr)

    from bench import bench_provenance

    receipt = {
        "bench": "fleet_storm",
        "scenario_file": os.path.relpath(args.scenario, REPO),
        "report": report,
        "provenance": bench_provenance(),
    }
    if args.out != "-":
        with open(args.out, "w") as f:
            json.dump(receipt, f, indent=1, sort_keys=True)
            f.write("\n")

    r = report["requests"]
    print(json.dumps({
        "metric": "storm_requests_per_s",
        "value": report["throughput"]["requests_per_s"],
        "unit": (
            f"req/s virtual ({r['submitted']} submitted, {r['ok']} ok, "
            f"{r['deadline_shed']} deadline-shed, {r['shed']} brownout-shed, "
            f"{r['dead_lettered']} dead-lettered over "
            f"{report['virtual_s']}s; {report['faults'].get('kills', 0)} "
            f"kills, {report['faults'].get('poison_crashes', 0)} poison "
            f"crashes; invariants: {report['invariants']['violations']} "
            "violations)"
        ),
        "ok": report["invariants"]["violations"] == 0,
    }))
    return 0


if __name__ == "__main__":
    sys.exit(main())
