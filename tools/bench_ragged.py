"""Ragged bench: unified ragged dispatch (chunked prefill) vs split path.

The workload is the one the ragged program exists for (ISSUE 10): long
prompts landing while short interactive rows are mid-decode. The SPLIT
path admits a prompt through the (P, S) prefill bucket ladder — the
prompt pads to the next power-of-two bucket and the whole padded prefill
runs INLINE, stalling every co-batched row's fused decode step; a prompt
past the prewarmed ladder additionally pays the bucket's XLA compile,
the multi-second TTFT cliff. The UNIFIED-RAGGED path admits the same
prompt as extra query rows of the decode dispatch: up to ``CHUNK_BUDGET``
prompt tokens per row per step, so prefill compute is metered across
steps and no bucket (or its compile) exists at all.

Both arms run on the deterministic fleet simulator (``llmss_tpu.sim``):
one unified replica whose ``prefill_mode`` selects the path (``split`` =
bucket ladder + mid-serve compile, ``chunked`` = ragged metering with
``prefill_chunk = CHUNK_BUDGET``), priced by a :class:`DeviceCostModel`
charging ``PREFILL_TOKEN_COST_S`` per prompt token, ``DECODE_STEP_COST_S``
per fused step, and ``BUCKET_COMPILE_S`` once per bucket beyond the
prewarmed ladder — so the comparison is deterministic and free of host
noise; the scheduler arithmetic (admission, chunk metering, head-of-line
stalls) is the thing being measured, and requests ride the REAL broker
with the invariant catalog asserted at drain. Runs on CPU in one process
(no JAX, no device). Writes RAGGED_BENCH.json; prints one JSON line.
Asserts the claims the subsystem ships on: decode step-time stdev no
worse on the all-decode trace (the ragged program is not allowed to tax
the steady state) and materially lower TTFT p95 plus lower decode stdev
on the mixed long-prompt trace.
"""

from __future__ import annotations

import json
import os
import statistics
import sys

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

from llmss_tpu.sim import FleetSim  # noqa: E402

ROWS = int(os.environ.get("RAGGED_ROWS", 8))
CHUNK_BUDGET = int(os.environ.get("RAGGED_CB", 16))
N_LONG = int(os.environ.get("RAGGED_LONG", 8))
N_SHORT = int(os.environ.get("RAGGED_SHORT", 24))
LONG_PROMPT = int(os.environ.get("RAGGED_LONG_PROMPT", 384))
SHORT_PROMPT = int(os.environ.get("RAGGED_SHORT_PROMPT", 24))
LONG_NEW = int(os.environ.get("RAGGED_LONG_NEW", 16))
SHORT_NEW = int(os.environ.get("RAGGED_SHORT_NEW", 32))
ARRIVAL_GAP_S = float(os.environ.get("RAGGED_ARRIVAL_GAP_S", 0.004))

PREFILL_TOKEN_COST_S = float(
    os.environ.get("RAGGED_PREFILL_TOKEN_COST_S", 50e-6)
)
DECODE_STEP_COST_S = float(os.environ.get("RAGGED_DECODE_STEP_COST_S", 1.5e-3))
# First use of a prompt bucket past the prewarmed ladder compiles a fresh
# (P, S) executable mid-serve — the stall chunked prefill retires.
BUCKET_COMPILE_S = float(os.environ.get("RAGGED_BUCKET_COMPILE_S", 2.5))
PREWARM_MAX_BUCKET = int(os.environ.get("RAGGED_PREWARM_MAX", 128))


def make_trace(long_prompt: int, n_long: int) -> list[dict]:
    """Mixed trace, interleaved so long prefills keep landing while short
    interactive rows are mid-decode. ``n_long == 0`` gives the all-decode
    control trace (every prompt fits one chunk / the smallest bucket)."""
    longs = [
        {"plen": long_prompt, "new": LONG_NEW} for _ in range(n_long)
    ]
    shorts = [
        {"plen": SHORT_PROMPT, "new": SHORT_NEW} for _ in range(N_SHORT)
    ]
    out: list[dict] = []
    ratio = max(1, N_SHORT // max(n_long, 1))
    while longs or shorts:
        if longs:
            out.append(longs.pop(0))
        for _ in range(ratio):
            if shorts:
                out.append(shorts.pop(0))
    return [
        {
            "id": f"rg{i:04d}",
            "arrival_s": i * ARRIVAL_GAP_S,
            "token_ids": [3000 + i] * r["plen"],
            "max_new": r["new"],
        }
        for i, r in enumerate(out)
    ]


def make_spec(mode: str, rows: list[dict]) -> dict:
    return {
        "format": "llmss-scenario/1",
        "name": f"bench-ragged-{mode}",
        "seed": 0,
        "broker": {"kind": "inproc", "lease_s": 10.0},
        "cost_model": {
            "kind": "table",
            "prefill_token_s": PREFILL_TOKEN_COST_S,
            "decode_step_s": DECODE_STEP_COST_S,
            "bucket_compile_s": BUCKET_COMPILE_S,
            "prewarm_max_bucket": PREWARM_MAX_BUCKET,
        },
        "fleet": {
            "replicas": [{
                "count": 1, "role": "unified", "rows": ROWS,
                "chunk_tokens": 1, "admit_burst": ROWS,
                "prefill_mode": "split" if mode == "split" else "chunked",
                "prefill_chunk": CHUNK_BUDGET,
            }],
            "router_policy": "shared",
        },
        "workload": {"kind": "trace", "rows": rows},
        "metrics": {"step_gaps": True},
    }


def run_mode(mode: str, trace: list[dict]) -> dict:
    sim = FleetSim(make_spec(mode, trace))
    report = sim.run()
    tp = report["throughput"]
    elapsed = (
        tp["tokens_out"] / tp["tokens_per_s"] if tp["tokens_per_s"] else 0.0
    )
    ttfts = report["latency_ms"]
    gaps_ms = [g * 1e3 for g in sim.step_gaps]
    return {
        "mode": mode,
        "requests": len(trace),
        "tokens": tp["tokens_out"],
        "elapsed_s": round(elapsed, 3),
        "tok_s_chip": round(tp["tokens_out"] / elapsed, 1)
        if elapsed else 0.0,
        "ttft_p50_ms": round(ttfts["ttft_p50"], 3),
        "ttft_p95_ms": round(ttfts["ttft_p95"], 3),
        "decode_step_ms_mean": round(statistics.fmean(gaps_ms), 3),
        "decode_step_ms_stdev": round(statistics.stdev(gaps_ms), 3),
        "decode_step_ms_p95": round(
            statistics.quantiles(gaps_ms, n=20)[18], 3
        ),
        "buckets_compiled_mid_serve": sim.counters["buckets_compiled"],
    }


def main():
    mixed = make_trace(LONG_PROMPT, N_LONG)
    # All-decode control: every prompt fits one chunk AND the smallest
    # prewarmed bucket, so both paths insert identical prefill work and
    # the ragged program must not tax the pure-decode cadence.
    alldec = make_trace(CHUNK_BUDGET, 0)

    result = {
        "config": {
            "rows": ROWS,
            "chunk_budget": CHUNK_BUDGET,
            "trace": {
                "long": {"n": N_LONG, "prompt": LONG_PROMPT,
                         "max_new": LONG_NEW},
                "short": {"n": N_SHORT, "prompt": SHORT_PROMPT,
                          "max_new": SHORT_NEW},
                "arrival_gap_s": ARRIVAL_GAP_S,
            },
            "prefill_token_cost_s": PREFILL_TOKEN_COST_S,
            "decode_step_cost_s": DECODE_STEP_COST_S,
            "bucket_compile_s": BUCKET_COMPILE_S,
            "prewarm_max_bucket": PREWARM_MAX_BUCKET,
        },
        "mixed": {
            "split": run_mode("split", mixed),
            "ragged": run_mode("ragged", mixed),
        },
        "all_decode": {
            "split": run_mode("split", alldec),
            "ragged": run_mode("ragged", alldec),
        },
    }
    from bench import bench_provenance

    result["provenance"] = bench_provenance()

    ms, mr = result["mixed"]["split"], result["mixed"]["ragged"]
    as_, ar = result["all_decode"]["split"], result["all_decode"]["ragged"]
    # The claims the subsystem ships on: metering beats monopolizing on
    # the mixed trace, and costs nothing when there is nothing to meter.
    assert mr["ttft_p95_ms"] < 0.5 * ms["ttft_p95_ms"], result
    assert mr["decode_step_ms_stdev"] < ms["decode_step_ms_stdev"], result
    assert (
        ar["decode_step_ms_stdev"] <= as_["decode_step_ms_stdev"] + 0.05
    ), result
    assert mr["buckets_compiled_mid_serve"] == 0, result

    path = os.path.join(
        os.path.dirname(os.path.dirname(os.path.abspath(__file__))),
        "RAGGED_BENCH.json",
    )
    with open(path, "w") as f:
        json.dump(result, f, indent=1)
        f.write("\n")
    print(json.dumps({
        "metric": "ragged_mixed_ttft_p95_ms",
        "value": mr["ttft_p95_ms"],
        "unit": (
            f"ms sim (ragged CB={CHUNK_BUDGET} vs split bucket ladder "
            f"{ms['ttft_p95_ms']}ms; decode step stdev "
            f"{mr['decode_step_ms_stdev']} vs "
            f"{ms['decode_step_ms_stdev']} ms mixed, "
            f"{ar['decode_step_ms_stdev']} vs "
            f"{as_['decode_step_ms_stdev']} ms all-decode; "
            f"{mr['tok_s_chip']} vs {ms['tok_s_chip']} tok/s/chip; "
            f"split compiled {ms['buckets_compiled_mid_serve']} bucket(s) "
            "mid-serve, ragged 0)"
        ),
        "vs_baseline": round(
            mr["ttft_p95_ms"] / max(ms["ttft_p95_ms"], 1e-9), 3
        ),
    }))


if __name__ == "__main__":
    main()
