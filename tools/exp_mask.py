"""Experiment: decode-mask variants for fresh_kv_decode_attention.

PROFILE.md diagnoses a ~0.6 ms/step cost for the *dynamic* decode score
mask (the hoisted additive [B, T] penalty) over a compile-time-foldable
one. This measures candidate replacements on the real chip, all inside
the actual fused decode scan (engine._decode_many via forward):

- penalty   : shipped path — hoisted additive [B, T] f32 penalty
- nomask    : no masking at all (incorrect; the fusion floor)
- iota      : inline ``iota_t < q_pos`` comparison on the scores
              (no [B, T] HBM operand; valid only for no-wrap decode)
- postexp   : multiplicative [B, T] 0/1 mask applied to probs AFTER exp
              (exact: m is softmax-shift-invariant; masked slots' scores
              are finite since the cache is zero-init / holds stale reals)
- iota_postexp: iota comparison, applied post-exp as a multiply

Usage: python tools/exp_mask.py [variants...]
"""

from __future__ import annotations

import json
import os
import sys
import time

import jax
import jax.numpy as jnp
import numpy as np

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

from bench import _MODEL_RUN, DECODE, PROMPT, flagship_cfg, slope_time  # noqa: E402

BATCH = int(os.environ.get("BENCH_BATCH", 0)) or _MODEL_RUN["1b2"]["batch"]

_NEG_INF = float(jnp.finfo(jnp.float32).min)


def make_attn_variant(variant: str):
    """Returns (decode_mask_penalty_fn, fresh_kv_decode_attention_fn)."""

    def penalty_fn(q_pos, kv_pos_old, slots, window=None):
        if variant in ("iota", "iota_postexp", "nomask", "postexp"):
            return None  # variants compute masking inline (or not at all)
        T = kv_pos_old.shape[1]
        slot_idx = jnp.arange(T, dtype=jnp.int32)
        mask = (
            (kv_pos_old <= q_pos)
            & (kv_pos_old >= 0)
            & (slot_idx[None, :] != slots)
        )
        if window is not None:
            mask &= kv_pos_old > q_pos - window
        return jnp.where(mask, 0.0, _NEG_INF).astype(jnp.float32)

    def attn(q, k_cache, v_cache, k_new, v_new, q_pos, kv_pos_old, slots, *,
             scale=None, window=None, penalty=None, k_scale=None,
             v_scale=None):
        B, S, Hq, D = q.shape
        T, Hkv = k_cache.shape[1], k_cache.shape[2]
        G = Hq // Hkv
        if scale is None:
            scale = 1.0 / (D ** 0.5)
        qf = q.astype(jnp.float32).reshape(B, S, Hkv, G, D) * scale
        s_c = jnp.einsum("bskgd,btkd->bkgst", qf, k_cache.astype(jnp.float32))
        iota = jnp.arange(T, dtype=jnp.int32)
        if variant == "penalty":
            if penalty is None:
                penalty = penalty_fn(q_pos, kv_pos_old, slots, window)
            s_c = s_c + penalty[:, None, None, None, :]
        elif variant == "iota":
            # no-wrap specialization: slot t visible iff t < q_pos
            vis = iota[None, :] < q_pos  # [B, T] (q_pos [B,1])
            s_c = jnp.where(vis[:, None, None, None, :], s_c, _NEG_INF)
        s_s = jnp.einsum(
            "bskgd,bskd->bkgs", qf, k_new.astype(jnp.float32)
        )[..., None]
        m = jnp.maximum(jnp.max(s_c, axis=-1, keepdims=True), s_s)
        p_c = jnp.exp(s_c - m)
        p_s = jnp.exp(s_s - m)
        if variant == "postexp":
            vis = (
                (kv_pos_old <= q_pos) & (kv_pos_old >= 0)
                & (iota[None, :] != slots)
            )
            p_c = p_c * vis[:, None, None, None, :].astype(jnp.float32)
        elif variant == "iota_postexp":
            vis = iota[None, :] < q_pos
            p_c = p_c * vis[:, None, None, None, :].astype(jnp.float32)
        denom = jnp.sum(p_c, axis=-1, keepdims=True) + p_s
        if G == 1 and S == 1:
            p_t = p_c[:, :, 0, 0, :]
            vterm = jnp.sum(
                p_t.transpose(0, 2, 1)[..., None]
                * v_cache.astype(jnp.float32),
                axis=1,
            )
            out_c = vterm[:, :, None, None, :]
        else:
            out_c = jnp.einsum(
                "bkgst,btkd->bkgsd", p_c, v_cache.astype(jnp.float32)
            )
        out = (
            out_c
            + p_s * v_new.astype(jnp.float32).transpose(0, 2, 1, 3)[:, :, None]
        ) / denom
        return out.transpose(0, 3, 1, 2, 4).reshape(B, S, Hq, D).astype(q.dtype)

    return penalty_fn, attn


def measure(variant: str) -> float:
    import llmss_tpu.models.decoder as dec
    from llmss_tpu.engine import DecodeEngine, GenerationParams
    from llmss_tpu.models.decoder import init_params
    from llmss_tpu.parallel import MeshPlan, make_mesh

    pen_fn, attn_fn = make_attn_variant(variant)
    dec.decode_mask_penalty = pen_fn
    dec.fresh_kv_decode_attention = attn_fn

    mesh = make_mesh(MeshPlan(tp=len(jax.devices())))
    cfg = flagship_cfg()
    params = init_params(cfg, mesh, jax.random.key(0))
    engine = DecodeEngine(cfg, params, mesh, max_seq_len=PROMPT + DECODE)
    gen = GenerationParams(max_new_tokens=DECODE, is_greedy=True)
    rng = np.random.default_rng(0)
    prompts = [
        rng.integers(0, cfg.vocab_size, PROMPT).tolist() for _ in range(BATCH)
    ]
    ids, lens = engine._pad_prompts(prompts)
    sa = engine._sample_args(gen, BATCH)
    eos = jnp.int32(-1)

    def prepare(n):
        cache = engine.new_cache(BATCH)
        tok, _, cache = engine._prefill(
            engine.params, jnp.asarray(ids), cache, jnp.asarray(lens), sa,
        )
        cur = jnp.asarray(lens)
        done = jnp.zeros(BATCH, bool)
        state = {"cache": cache}

        def run():
            out = engine._decode_many(
                engine.params, tok, state["cache"], cur, sa, done, eos,
                n_steps=n,
            )
            toks, state["cache"] = out[0], out[1]
            _ = float(jnp.sum(toks))

        return run

    return slope_time(prepare)[0]


def main():
    variants = sys.argv[1:] or [
        "penalty", "nomask", "iota", "postexp", "iota_postexp"
    ]
    out = {}
    for v in variants:
        ms = measure(v)
        out[v] = round(ms, 3)
        print(f"{v}: {ms:.3f} ms/step", flush=True)
    print(json.dumps(out))


if __name__ == "__main__":
    main()
