"""End-to-end smoke on a *trained* checkpoint: tokenizer → loader → engine → text.

The reference's de facto validation is decoding a real small model
(``/root/reference/poc-server/producer-consumer/README.md:3`` —
``heegyu/kogpt-j-350m``). The bench host has no network access and no HF
cache, so a hub checkpoint is unobtainable; this script builds the closest
offline equivalent and drives the **full** CLI path against it:

1. trains a ByteLevel-BPE tokenizer on a small corpus (real merges, real
   special tokens — saved in HF ``tokenizer.json`` format and loaded back
   through ``AutoTokenizer``, exactly like a hub tokenizer);
2. trains a tiny HF GPT-2 (torch, CPU) until it memorizes the corpus —
   so, unlike random-init weights, greedy decoding has one *correct*
   output the whole stack must reproduce;
3. saves it with ``save_pretrained`` (safetensors) and decodes **text
   prompts** through ``llmss_tpu.cli.generate`` — tokenizer load, hub
   file resolution, sharded weight load, engine prefill/decode, detokenize;
4. asserts the decoded continuations equal both the memorized corpus text
   and HF ``model.generate`` on the same checkpoint, then writes the
   captured transcript to ``SMOKE_REAL_CKPT.md``.

Run: ``python tools/smoke_real_ckpt.py`` (uses the default backend — the
real TPU on the bench host, CPU elsewhere).
"""

from __future__ import annotations

import json
import os
import subprocess
import sys
import tempfile
import time

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
sys.path.insert(0, REPO)

CORPUS = [
    "the quick brown fox jumps over the lazy dog",
    "pack my box with five dozen liquor jugs",
    "how vexingly quick daft zebras jump",
    "sphinx of black quartz judge my vow",
    "the five boxing wizards jump quickly",
]
PROMPT_WORDS = 4  # words of each sentence used as the generation prompt


def build_tokenizer(workdir: str):
    from tokenizers import ByteLevelBPETokenizer

    tok = ByteLevelBPETokenizer()
    tok.train_from_iterator(
        CORPUS * 50, vocab_size=384, min_frequency=1,
        special_tokens=["<|endoftext|>"],
    )
    from transformers import PreTrainedTokenizerFast

    fast = PreTrainedTokenizerFast(
        tokenizer_object=tok._tokenizer,
        eos_token="<|endoftext|>",
        bos_token="<|endoftext|>",
        unk_token="<|endoftext|>",
    )
    fast.save_pretrained(workdir)
    return fast


def train_model(workdir: str, tokenizer):
    import torch
    from transformers import GPT2Config, GPT2LMHeadModel

    torch.manual_seed(0)
    cfg = GPT2Config(
        vocab_size=len(tokenizer), n_positions=64, n_embd=128, n_layer=2,
        n_head=4, bos_token_id=tokenizer.eos_token_id,
        eos_token_id=tokenizer.eos_token_id,
    )
    model = GPT2LMHeadModel(cfg)
    opt = torch.optim.AdamW(model.parameters(), lr=3e-3)
    # EOS-terminated sequences: the model must learn to *stop* after each
    # memorized sentence, so greedy decoding has a finite correct output.
    enc = [
        torch.tensor(
            tokenizer(s)["input_ids"] + [tokenizer.eos_token_id]
        )
        for s in CORPUS
    ]
    model.train()
    for step in range(800):
        loss_total = 0.0
        opt.zero_grad()
        for ids in enc:
            out = model(ids[None], labels=ids[None])
            out.loss.backward()
            loss_total += float(out.loss)
        opt.step()
        if loss_total / len(enc) < 0.02:
            break
    model.eval()
    model.save_pretrained(workdir, safe_serialization=True)
    return model, loss_total / len(enc), step


def main():
    workdir = os.environ.get(
        "SMOKE_DIR", os.path.join(tempfile.gettempdir(), "llmss-smoke-gpt2")
    )
    os.makedirs(workdir, exist_ok=True)
    t0 = time.time()
    tokenizer = build_tokenizer(workdir)
    model, final_loss, steps = train_model(workdir, tokenizer)
    train_s = time.time() - t0

    prompts = [" ".join(s.split()[:PROMPT_WORDS]) for s in CORPUS]
    expected = [" ".join(s.split()[PROMPT_WORDS:]) for s in CORPUS]

    # HF reference continuations on the same checkpoint.
    import torch

    hf_out = []
    for p in prompts:
        ids = torch.tensor([tokenizer(p)["input_ids"]])
        gen = model.generate(
            ids, max_new_tokens=16, do_sample=False,
            eos_token_id=tokenizer.eos_token_id,
            pad_token_id=tokenizer.eos_token_id,
        )[0][ids.shape[1]:]
        gen = [t for t in gen.tolist() if t != tokenizer.eos_token_id]
        hf_out.append(tokenizer.decode(gen))

    # Full CLI path, as a subprocess — the exact user entry point.
    cmd = [
        sys.executable, "-m", "llmss_tpu.cli.generate",
        "--pretrained_model_path", workdir,
        "--prompts", *prompts,
        "--max_new_tokens", "16", "--is_greedy",
    ]
    proc = subprocess.run(
        cmd, capture_output=True, text=True, cwd=REPO, timeout=900,
    )
    print(proc.stdout)
    if proc.returncode != 0:
        print(proc.stderr[-4000:], file=sys.stderr)
        raise SystemExit(f"CLI failed: {proc.returncode}")

    import ast

    ours = []
    for line in proc.stdout.splitlines():
        if "continuation:" in line:
            ours.append(
                ast.literal_eval(line.split("continuation:", 1)[1].strip())
            )
    if len(ours) != len(prompts):
        raise SystemExit(
            f"CLI printed {len(ours)} continuations for {len(prompts)} "
            f"prompts — output format drift?\n{proc.stdout[-2000:]}"
        )

    results = []
    ok_all = True
    for p, want_text, hf, got in zip(prompts, expected, hf_out, ours):
        got_clean = got.strip()
        # The CLI continuation must reproduce the memorized sentence tail
        # and agree with HF generate on the same checkpoint (both stop at
        # the learned EOS).
        ok = got_clean == want_text.strip() and got_clean == hf.strip()
        ok_all &= ok
        results.append(
            {"prompt": p, "memorized": want_text, "hf": hf, "cli": got,
             "ok": ok}
        )
        print(f"[{'OK' if ok else 'MISMATCH'}] {p!r} -> {got!r} "
              f"(hf={hf!r})")

    md = [
        "# Real-checkpoint smoke (tokenizer → loader → engine → text)",
        "",
        "Produced by `tools/smoke_real_ckpt.py`. The bench host has no",
        "network and no HF cache, so the checkpoint is a tiny GPT-2",
        f"(vocab {len(tokenizer)}, 2 layers) **trained on-host** to",
        f"memorize a 5-sentence corpus (final loss {final_loss:.4f} after",
        f"{steps + 1} epochs, {train_s:.0f}s), saved with HF",
        "`save_pretrained` + a ByteLevel-BPE `tokenizer.json`, and decoded",
        "through the full `llmss_tpu.cli.generate` path — AutoTokenizer,",
        "hub file resolution, sharded safetensors load, prefill/decode,",
        "detokenize. Greedy continuations must equal both the memorized",
        "text and HF `model.generate` on the same checkpoint.",
        "",
        "| prompt | CLI continuation | matches memorized + HF |",
        "|---|---|---|",
    ]
    for r in results:
        md.append(
            f"| `{r['prompt']}` | `{r['cli'].strip()}` | "
            f"{'yes' if r['ok'] else '**NO**'} |"
        )
    md.append("")
    md.append("Raw CLI output:")
    md.append("```")
    md.append(proc.stdout.strip())
    md.append("```")
    with open(os.path.join(REPO, "SMOKE_REAL_CKPT.md"), "w") as f:
        f.write("\n".join(md) + "\n")

    print(json.dumps({
        "ok": ok_all, "n_prompts": len(prompts),
        "final_loss": round(final_loss, 4), "train_s": round(train_s, 1),
    }))
    if not ok_all:
        raise SystemExit("smoke FAILED")


if __name__ == "__main__":
    main()
