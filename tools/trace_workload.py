"""Trace-to-workload: capture a live fleet's arrival process and replay it.

A stitched trace corpus already contains everything a load generator
needs — when each request arrived, how long its prompt was, how many
tokens it asked for, and which requests shared a cached prefix. The
producer's ``GET /trace/export_workload`` distils that into a compact
``llmss-workload/1`` JSON (see ``trace.export_workload``): arrival
offsets from the first request, prompt/max_new lengths, prefix hashes,
and each arrival's ``slo_class`` so a replay reproduces the priority
mix the SLO-tiered scheduler saw.

This tool does two jobs:

* **CLI** — fetch the workload from a running producer (or read an
  already-saved file) and write it out, so a production traffic shape
  can be carried to a bench box as one small file::

      python tools/trace_workload.py http://prod:8000/trace/export_workload \
          --out workload.json
      python tools/trace_workload.py workload.json --summary

* **Library** — ``replay(workload, submit, speed=...)`` re-enacts the
  arrival process against any submit callable (``Broker.push_request``,
  a producer HTTP client, or a test stub). Token contents are
  synthesized deterministically: the trace records *lengths and prefix
  identity*, not token values (prompts never leave the fleet), so two
  requests that shared a prefix hash at capture time share a
  deterministically derived prefix at replay time — the prefix-affinity
  router and scheduler prefix cache see the same shape the production
  traffic had.

``speed=0`` (the default) submits as fast as possible, preserving only
the *order*; ``speed=1.0`` reproduces real-time inter-arrival gaps;
``speed=2.0`` replays at double speed.
"""

from __future__ import annotations

import argparse
import json
import os
import sys
import time

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

from llmss_tpu.serve.protocol import (  # noqa: E402
    SLO_CLASSES,
    GenerateRequest,
)
from llmss_tpu.utils import trace  # noqa: E402

#: Synthesized shared-prefix length. The workload records prefix
#: *identity* (a hash), not its length; any fixed length reproduces the
#: cache-hit structure, which is what replay is after.
PREFIX_LEN = 16
VOCAB = 50257


def load_workload(source: str) -> dict:
    """Read a workload JSON from a file path or a producer URL."""
    if source.startswith(("http://", "https://")):
        from urllib.request import urlopen

        with urlopen(source, timeout=30) as r:
            payload = json.load(r)
    else:
        with open(source) as f:
            payload = json.load(f)
    fmt = payload.get("format")
    if fmt != trace.WORKLOAD_FORMAT:
        raise ValueError(
            f"{source}: format {fmt!r} is not {trace.WORKLOAD_FORMAT!r}"
        )
    return payload


def _prefix_tokens(ph: str) -> list[int]:
    """Deterministic token block for one captured prefix hash.

    Seeded from the hash digits so distinct production prefixes stay
    distinct at replay and every replayer derives the same tokens.
    """
    try:
        seed = int(str(ph)[:8], 16)
    except ValueError:
        seed = sum(ord(c) for c in str(ph))
    return [(seed + j * 31) % VOCAB for j in range(PREFIX_LEN)]


def synthesize_request(
    row: dict, index: int = 0, prefixes: dict | None = None,
    sessions: dict | None = None,
) -> GenerateRequest:
    """One replayable request from one workload row.

    ``sessions`` (session_id -> that session's last synthesized prompt)
    makes replayed chat traffic *structurally* multi-turn: turn N's
    prompt EXTENDS turn N-1's, the way real conversation history does —
    which is what exercises session parking and prefix tiering at
    replay. Captures record only lengths, so the extension is padded
    deterministically to the captured prompt_len.
    """
    plen = int(row.get("prompt_len") or 16)
    sess = row.get("session_id")
    base: list[int] = []
    if sess and sessions is not None:
        base = list(sessions.get(str(sess)) or [])
    fresh = max(plen - len(base), 1)
    req = GenerateRequest(
        id=str(row.get("req_id") or f"wl-{index}"),
        token_ids=base + [(index * 7 + j) % VOCAB for j in range(fresh)],
        max_new_tokens=int(row.get("max_new_tokens") or 20),
    )
    # Older captures carried a "priority" placeholder instead; either key
    # restores the scheduling class, defaulting to standard.
    cls = row.get("slo_class") or row.get("priority")
    if cls in SLO_CLASSES:
        req.slo_class = cls
    # session_id is optional in the capture (older workload files predate
    # it); present, it restores per-session arrival structure — and the
    # turn ordinal, when the capture recorded one.
    if sess:
        req.session_id = str(sess)
        if row.get("turn") is not None:
            req.turn = int(row["turn"])
        if sessions is not None:
            sessions[str(sess)] = list(req.token_ids)
    ph = row.get("prefix_hash")
    if ph is not None:
        if prefixes is None:
            prefixes = {}
        if ph not in prefixes:
            prefixes[ph] = _prefix_tokens(ph)
        req.prefix_token_ids = prefixes[ph]
    return req


def replay(workload: dict, submit, speed: float = 0.0) -> int:
    """Re-enact the arrival process; returns the number submitted.

    ``submit`` receives one ``GenerateRequest`` per captured row, in
    arrival order. ``speed`` scales real time: 0 = no pacing (order
    only), 1.0 = captured inter-arrival gaps, 2.0 = twice as fast.
    """
    if workload.get("format") != trace.WORKLOAD_FORMAT:
        raise ValueError(f"not a {trace.WORKLOAD_FORMAT} payload")
    # Secondary sort on the turn ordinal: simultaneous arrivals within a
    # session must still replay in turn order (turn N's prompt extends
    # turn N-1's).
    rows = sorted(
        workload.get("requests", []),
        key=lambda r: (r["arrival_s"], r.get("turn") or 0),
    )
    prefixes: dict = {}
    sessions: dict = {}
    t0 = time.monotonic()
    n = 0
    for i, row in enumerate(rows):
        if speed > 0:
            lag = row["arrival_s"] / speed - (time.monotonic() - t0)
            if lag > 0:
                time.sleep(lag)
        submit(synthesize_request(row, i, prefixes, sessions))
        n += 1
    return n


def summarize(workload: dict) -> dict:
    rows = workload.get("requests", [])
    plens = [r.get("prompt_len") or 0 for r in rows]
    news = [r.get("max_new_tokens") or 0 for r in rows]
    span = workload.get("span_s") or 0.0
    return {
        "n_requests": len(rows),
        "span_s": round(span, 3),
        "arrival_rate_per_s": round(len(rows) / span, 2) if span else None,
        "prompt_len_mean": round(sum(plens) / len(plens), 1) if plens else 0,
        "max_new_mean": round(sum(news) / len(news), 1) if news else 0,
        "distinct_prefixes": len(
            {r["prefix_hash"] for r in rows if r.get("prefix_hash")}
        ),
        **_session_shape(rows),
    }


def _session_shape(rows: list[dict]) -> dict:
    """Multi-turn summary block — empty for captures without sessions."""
    turns: dict[str, int] = {}
    thinks: list[float] = []
    for r in rows:
        sid = r.get("session_id")
        if not sid:
            continue
        turns[sid] = turns.get(sid, 0) + 1
        if r.get("think_s") is not None:
            thinks.append(float(r["think_s"]))
    if not turns:
        return {}
    return {
        "sessions": len(turns),
        "turns_per_session_mean": round(
            sum(turns.values()) / len(turns), 2
        ),
        "turns_per_session_max": max(turns.values()),
        "think_s_mean": (
            round(sum(thinks) / len(thinks), 3) if thinks else None
        ),
    }


def main(argv: list[str] | None = None) -> int:
    parser = argparse.ArgumentParser(
        description="fetch / inspect a replayable trace workload",
    )
    parser.add_argument(
        "source",
        help="producer /trace/export_workload URL, or a saved workload file",
    )
    parser.add_argument(
        "--out", default=None,
        help="write the workload JSON here (default: stdout)",
    )
    parser.add_argument(
        "--summary", action="store_true",
        help="print a one-line shape summary instead of the payload",
    )
    args = parser.parse_args(argv)

    wl = load_workload(args.source)
    if args.out:
        with open(args.out, "w") as f:
            json.dump(wl, f, indent=2)
        print(f"wrote {wl['n_requests']} request(s) to {args.out}")
    if args.summary or not args.out:
        print(json.dumps(summarize(wl) if args.summary else wl))
    return 0


if __name__ == "__main__":
    sys.exit(main())
