"""int8 KV where it matters: long context — capacity AND throughput.

The int8 cache halves KV HBM bytes, which is a *capacity* feature: twice
the rows×context fits one chip. This bench pins that claim with numbers
on real hardware (1b2 flagship dims, ring 2048, 1024-token prompts):

1. throughput: decode step time bf16 vs int8 at a batch both fit;
2. capacity: a batch whose bf16 cache CANNOT be allocated next to the
   params (driven to OOM and caught) but whose int8 cache serves fine —
   the "2x rows/context" receipt;
3. the sp>1 dequant bound: on sequence-parallel meshes the int8 layer is
   pre-dequantized before the shard_map'd attention (models/decoder.py),
   an analytic extra-traffic bound reported per step.

Writes INT8_BENCH.json; prints one JSON line.
"""

from __future__ import annotations

import json
import os
import sys
import time

import jax
import jax.numpy as jnp
import numpy as np

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

from bench import chunk_schedule, flagship_cfg, slope_time  # noqa: E402

RING = int(os.environ.get("INT8_RING", 2048))
PROMPT = int(os.environ.get("INT8_PROMPT", 1024))
BATCH_BOTH = int(os.environ.get("INT8_BATCH", 24))
BATCH_BIG = int(os.environ.get("INT8_BATCH_BIG", 48))
N_SLOPE = (16, 112)
CHUNK = 16


def step_ms_for(engine, cfg, batch) -> float:
    from llmss_tpu.engine import GenerationParams

    gen = GenerationParams(max_new_tokens=N_SLOPE[1], is_greedy=True)
    rng = np.random.default_rng(0)
    prompts = [
        rng.integers(0, cfg.vocab_size, PROMPT).tolist()
        for _ in range(batch)
    ]
    ids, lens = engine._pad_prompts(prompts)
    sa = engine._sample_args(gen, batch)
    eos = engine.canon_vec(jnp.full(batch, -1, jnp.int32))
    done = jnp.zeros(batch, bool)

    def prepare(n):
        cache = engine.new_cache(batch)
        tok0, _, cache = engine._prefill(
            engine.params, jnp.asarray(ids), cache, jnp.asarray(lens), sa,
        )
        tok0 = engine.canon_vec(tok0)
        cache = engine.canon_cache(cache)
        cur0 = engine.canon_vec(jnp.asarray(lens))
        sched = chunk_schedule(engine, int(lens.max()), n, CHUNK)
        state = {"cache": cache}

        def run():
            cache, tok, cur = state["cache"], tok0, cur0
            total = jnp.zeros((), jnp.int32)
            for k, tb in sched:
                toks, cache, cur, _, _ = engine._decode_many(
                    engine.params, tok, cache, cur, sa, done, eos,
                    n_steps=k, t_bucket=tb,
                )
                cache = engine.canon_cache(cache)
                cur = engine.canon_vec(cur)
                tok = engine.canon_vec(toks[:, -1])
                total = total + jnp.sum(toks)
            state["cache"] = cache
            _ = int(total)

        return run

    return slope_time(prepare, N_SLOPE)[0]


def main():
    from llmss_tpu.engine import DecodeEngine
    from llmss_tpu.models.decoder import init_params
    from llmss_tpu.parallel import MeshPlan, make_mesh

    mesh = make_mesh(MeshPlan(tp=len(jax.devices())))
    cfg = flagship_cfg("1b2")
    params = init_params(cfg, mesh, jax.random.key(0))
    kv_gb = lambda b, dtype_bytes: (  # noqa: E731
        2 * cfg.n_layers * b * RING * cfg.n_kv_heads * cfg.head_dim
        * dtype_bytes / 1e9
    )

    out = {
        "config": {
            "model": "1b2", "ring": RING, "prompt": PROMPT,
            "batch_both": BATCH_BOTH, "batch_big": BATCH_BIG,
            "bf16_cache_gb_at_batch_big": round(kv_gb(BATCH_BIG, 2), 2),
            "int8_cache_gb_at_batch_big": round(
                kv_gb(BATCH_BIG, 1) + kv_gb(BATCH_BIG, 2) / 256, 2
            ),
        },
    }

    # 1. throughput at a batch both dtypes fit
    for kv in (None, "int8"):
        eng = DecodeEngine(
            cfg, params, mesh, max_seq_len=RING, kv_dtype=kv,
        )
        ms = step_ms_for(eng, cfg, BATCH_BOTH)
        out[f"step_ms_{kv or 'bf16'}_b{BATCH_BOTH}"] = round(ms, 3)
        out[f"tok_s_chip_{kv or 'bf16'}_b{BATCH_BOTH}"] = round(
            BATCH_BOTH / ms * 1e3, 1
        )

    # 2. capacity: bf16 at BATCH_BIG should not fit beside the params;
    # int8 must serve it.
    try:
        eng = DecodeEngine(cfg, params, mesh, max_seq_len=RING)
        ms = step_ms_for(eng, cfg, BATCH_BIG)
        out["bf16_big_batch"] = {
            "fit": True, "step_ms": round(ms, 3),
            "note": "bf16 unexpectedly fit - capacity margin larger "
                    "than modeled",
        }
    except Exception as e:  # noqa: BLE001 — OOM is the expected outcome
        out["bf16_big_batch"] = {
            "fit": False,
            "error": type(e).__name__ + ": " + str(e)[:200],
        }
    eng = DecodeEngine(cfg, params, mesh, max_seq_len=RING, kv_dtype="int8")
    ms = step_ms_for(eng, cfg, BATCH_BIG)
    out["int8_big_batch"] = {
        "fit": True, "step_ms": round(ms, 3),
        "tok_s_chip": round(BATCH_BIG / ms * 1e3, 1),
    }

    # 3. analytic sp>1 dequant bound (models/decoder.py pre-dequantizes
    # each layer's int8 shard to bf16 before the shard_map'd attention):
    # per step, per shard: 2 (k+v) x L x B x (T/sp) x Hkv x D x 2 bytes
    # written + the int8 read it replaces — an upper bound of one extra
    # bf16 cache-copy per step.
    out["sp_dequant_bound_gb_per_step_per_shard"] = {
        "formula": "2*L*B*(T/sp)*Hkv*D*2 bytes written (+int8 read)",
        "example_sp2_b8": round(
            2 * cfg.n_layers * 8 * (RING // 2) * cfg.n_kv_heads
            * cfg.head_dim * 2 / 1e9, 3
        ),
    }

    speedup = out[f"step_ms_bf16_b{BATCH_BOTH}"] / out[
        f"step_ms_int8_b{BATCH_BOTH}"
    ]
    result = {
        "metric": "int8_kv_long_context",
        "value": out["int8_big_batch"]["tok_s_chip"],
        "unit": (
            f"tok/s/chip (1b2, ring={RING}, prompt={PROMPT}, int8 KV at "
            f"batch={BATCH_BIG} — bf16 "
            + ("OOMs" if not out["bf16_big_batch"]["fit"] else "fits(!)")
            + f" there; at batch={BATCH_BOTH} both fit: int8 "
            f"{speedup:.2f}x bf16 step time)"
        ),
        "vs_baseline": round(speedup, 3),
    }
    out["headline"] = result
    print(json.dumps(result))
    from bench import bench_provenance

    out["provenance"] = bench_provenance()
    with open(os.path.join(os.path.dirname(os.path.dirname(
            os.path.abspath(__file__))), "INT8_BENCH.json"), "w") as f:
        json.dump(out, f, indent=1)


if __name__ == "__main__":
    main()
