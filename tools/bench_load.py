"""Cold-start load bench: multi-file sharded checkpoint → sharded params.

Round-3 verdict gaps closed here:

- **No multi-file sharded checkpoint had ever been loaded end-to-end** —
  the only e2e checkpoint run was a single-file 346-vocab toy. This bench
  synthesizes a 1.2B-parameter bf16 Llama-architecture HF checkpoint
  sharded into ~500 MB safetensors files (the real cold-start path the
  reference's loader routes, ``utils/weights.py:18-24`` +
  ``hub.py:77-118``) and loads it through ``load_model`` on the real chip.
- **No evidence the native weight data plane was actually faster.** Times
  three read paths over the same files:

  1. ``native``  — ``llmss_tpu/native/st_gather.cc`` threaded GIL-free
     pread through ``CheckpointShards`` (the default).
  2. ``memmap``  — the repo's single-threaded np.memmap fallback (native
     lib disabled).
  3. ``safetensors-binding`` — the reference's read path
     (``utils/weights.py:77-88``): the safetensors Python binding,
     one GIL-bound ``get_tensor`` per tensor, bytes→numpy only (no jax
     transfer), as a raw-IO floor for the reference's data plane.

The page cache is dropped before each timed run when permitted
(``/proc/sys/vm/drop_caches``); otherwise numbers are warm-cache and the
JSON says so. Writes ``LOAD_BENCH.json`` at the repo root.

Run: ``python tools/bench_load.py`` (env ``LOAD_BENCH_DIR`` overrides the
checkpoint location, ``LOAD_BENCH_SMALL=1`` shrinks the model for smoke).
"""

from __future__ import annotations

import json
import os
import subprocess
import sys
import time
from pathlib import Path

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

CKPT_DIR = Path(os.environ.get("LOAD_BENCH_DIR", "/tmp/llmss-1b2-ckpt"))
SMALL = bool(os.environ.get("LOAD_BENCH_SMALL"))


def ensure_checkpoint() -> Path:
    if (CKPT_DIR / "config.json").exists():
        return CKPT_DIR
    print(f"# synthesizing checkpoint at {CKPT_DIR} ...", flush=True)
    import torch
    from transformers import LlamaConfig, LlamaForCausalLM

    dims = (
        dict(hidden_size=256, intermediate_size=688, num_hidden_layers=2,
             num_attention_heads=4, num_key_value_heads=4)
        if SMALL else
        dict(hidden_size=2048, intermediate_size=5504, num_hidden_layers=20,
             num_attention_heads=16, num_key_value_heads=16)
    )
    cfg = LlamaConfig(
        vocab_size=32000, max_position_embeddings=4096,
        tie_word_embeddings=False, **dims,
    )
    torch.manual_seed(0)
    with torch.device("meta"):
        model = LlamaForCausalLM(cfg)
    model = model.to_empty(device="cpu").to(torch.bfloat16)
    for p in model.parameters():
        p.data.normal_(0.0, 0.02)
    model.save_pretrained(
        CKPT_DIR, safe_serialization=True,
        max_shard_size="10MB" if SMALL else "500MB",
    )
    return CKPT_DIR


def drop_caches() -> bool:
    try:
        subprocess.run(["sync"], check=True, timeout=120)
        Path("/proc/sys/vm/drop_caches").write_text("3\n")
        return True
    except (OSError, subprocess.SubprocessError):
        return False


def time_load_model(native: bool) -> float:
    """Full cold start in a fresh process: file resolution → sliced reads →
    sharded device arrays on the chip (and compile of nothing — load only).
    A subprocess per run isolates the native-lib toggle and jax state."""
    code = (
        "import os, sys, time\n"
        "sys.path.insert(0, %r)\n"
        "from llmss_tpu.weights import native_st\n"
        "native_st._LIB_FAILED = %r  # True => memmap fallback\n"
        "native_st._build_lib()  # compile-and-cache outside the timing\n"
        "import jax\n"
        "from llmss_tpu.models.registry import load_model\n"
        "from llmss_tpu.parallel import MeshPlan, make_mesh\n"
        "mesh = make_mesh(MeshPlan(tp=len(jax.devices())))\n"
        "t0 = time.perf_counter()\n"
        "cfg, params = load_model(%r, mesh)\n"
        "jax.block_until_ready(params)\n"
        "print('LOAD_SECONDS', time.perf_counter() - t0)\n"
    ) % (str(Path(__file__).resolve().parent.parent), not native,
         str(CKPT_DIR))
    r = subprocess.run(
        [sys.executable, "-c", code], capture_output=True, text=True,
        timeout=1800,
    )
    if r.returncode != 0:
        raise RuntimeError(
            f"load subprocess failed (rc={r.returncode}):\n{r.stderr[-4000:]}"
        )
    out = r.stdout
    for line in out.splitlines():
        if line.startswith("LOAD_SECONDS"):
            return float(line.split()[1])
    raise RuntimeError(f"no LOAD_SECONDS in output:\n{out}")


def time_native_read_only() -> float:
    """Same scope as the binding baseline — bytes → numpy, no jax — but
    through the native data plane (one batched read_many per file)."""
    from llmss_tpu.weights.native_st import NativeSafetensors, _build_lib

    _build_lib()
    files = sorted(CKPT_DIR.glob("*.safetensors"))
    t0 = time.perf_counter()
    total = 0
    for fn in files:
        f = NativeSafetensors(fn)
        outs = f.read_many([(name, None) for name in f.keys()])
        total += sum(o.nbytes for o in outs)
    dt = time.perf_counter() - t0
    print(f"#   native read {total / 1e9:.2f} GB")
    return dt


def time_safetensors_binding() -> float:
    """The reference's data plane: safetensors Python binding, one
    GIL-bound get_tensor per tensor (utils/weights.py:77-88), to numpy."""
    from safetensors import safe_open

    files = sorted(CKPT_DIR.glob("*.safetensors"))
    t0 = time.perf_counter()
    total = 0
    for fn in files:
        with safe_open(str(fn), framework="numpy") as f:
            for name in f.keys():
                t = f.get_tensor(name)
                total += t.nbytes
    dt = time.perf_counter() - t0
    print(f"#   safetensors-binding read {total / 1e9:.2f} GB")
    return dt


def main() -> None:
    ensure_checkpoint()
    files = sorted(CKPT_DIR.glob("*.safetensors"))
    total_bytes = sum(f.stat().st_size for f in files)
    print(f"# checkpoint: {len(files)} files, {total_bytes / 1e9:.2f} GB")
    assert len(files) > 1, "bench requires a MULTI-file checkpoint"

    cold = drop_caches()
    results = {}
    for name, fn in [
        ("native", lambda: time_load_model(native=True)),
        ("memmap", lambda: time_load_model(native=False)),
        ("native_read_only", time_native_read_only),
        ("safetensors_binding_read_only", time_safetensors_binding),
    ]:
        if cold:
            drop_caches()
        dt = fn()
        results[name] = round(dt, 2)
        print(f"# {name}: {dt:.2f}s "
              f"({total_bytes / dt / 1e9:.2f} GB/s)", flush=True)

    out = {
        "metric": "cold_start_load_seconds",
        "value": results["native"],
        "unit": (
            f"s (1.2B bf16 llama, {len(files)}-file sharded safetensors, "
            f"{total_bytes / 1e9:.2f} GB -> sharded device arrays; "
            f"page cache {'dropped' if cold else 'WARM'}; NOTE on the "
            f"axon bench host the host->device transfer rides a network "
            f"tunnel that dominates end-to-end load — the *_read_only "
            f"modes isolate the data plane)"
        ),
        "modes": results,
        "files": len(files),
        "bytes": total_bytes,
        "cold_page_cache": cold,
        "gbps": {
            k: round(total_bytes / v / 1e9, 2) for k, v in results.items()
        },
    }
    print(json.dumps({k: out[k] for k in ("metric", "value", "unit")}))
    from bench import bench_provenance

    out["provenance"] = bench_provenance()
    repo = Path(__file__).resolve().parent.parent
    with open(repo / "LOAD_BENCH.json", "w") as f:
        json.dump(out, f, indent=1)


if __name__ == "__main__":
    main()
