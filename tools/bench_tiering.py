"""KV tiering bench: fleet-tiered prefix/session store vs per-worker LRU.

Runs ``scenarios/sessions.json`` — a multi-tenant multi-turn trace whose
shared-prefix working set (24 prefixes) exceeds any one replica's local
prefix LRU (4 slots) — through the deterministic fleet simulator in two
arms:

- **tiered**: the fleet-shared tier store (``fleet.kv_tiering``) is on.
  Prefixes evicted from a replica's local LRU demote to T1 host RAM
  (spilling to the T2 blob store under cap pressure) and promote back on
  the next miss anywhere in the fleet; finished session turns park their
  KV and the next turn resumes it without re-prefill.
- **baseline**: the same trace, same seed, with ``kv_tiering.enabled``
  flipped off — each worker has only its local prefix LRU, and every
  session turn re-prefills its full history. This is the pre-tiering
  code path, byte-identical to it.

Headline checks: the tiered arm must beat the baseline on fleet prefix
hit rate AND per-turn TTFT p95, and must avoid a nonzero number of
re-prefill tokens (the baseline, with no tier store, avoids none).
Receipt: ``TIER_BENCH.json``.

    python tools/bench_tiering.py
    python tools/bench_tiering.py --check-determinism --out -
"""

from __future__ import annotations

import argparse
import copy
import json
import os
import sys

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

from llmss_tpu.sim import run_scenario  # noqa: E402

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
DEFAULT_SCENARIO = os.path.join(REPO, "scenarios", "sessions.json")


def _local_hit_rate(report: dict) -> float | None:
    """Prefix hit rate for an arm with no tier store: local LRU hits
    only, read from the flat sim counters."""
    f = report.get("faults") or {}
    hits = f.get("prefix_hits", 0)
    misses = f.get("prefix_misses", 0)
    total = hits + misses
    return round(hits / total, 6) if total else None


def run_all(scenario_path: str, n_requests: int | None,
            seed: int | None) -> dict:
    from llmss_tpu.sim.scenario import load_scenario

    base = load_scenario(scenario_path)
    if "kv_tiering" not in (base.get("fleet") or {}):
        raise SystemExit(
            f"{scenario_path}: scenario has no fleet.kv_tiering block — "
            "nothing to compare"
        )

    tiered_spec = copy.deepcopy(base)
    baseline_spec = copy.deepcopy(base)
    baseline_spec["fleet"]["kv_tiering"] = {"enabled": False}

    tiered = run_scenario(tiered_spec, n_requests=n_requests, seed=seed)
    baseline = run_scenario(baseline_spec, n_requests=n_requests, seed=seed)

    kt = tiered["kv_tiers"]
    tiered_hit = kt["fleet_prefix_hit_rate"]
    base_hit = _local_hit_rate(baseline)
    tiered_ttft = tiered["latency_ms"]["ttft_p95"]
    base_ttft = baseline["latency_ms"]["ttft_p95"]
    avoided = kt["reprefill_tokens_avoided"]

    checks = {
        # Headline: fleet-wide prefix reuse beats per-worker LRU reuse.
        "tiered_higher_prefix_hit_rate": (
            tiered_hit is not None and base_hit is not None
            and tiered_hit > base_hit
        ),
        # Promotions + session resume are cheaper than re-prefilling, so
        # the tail TTFT must come down.
        "tiered_lower_ttft_p95": tiered_ttft < base_ttft,
        # Parked sessions and tier hits must have skipped real prefill
        # work; the baseline (no tier store) avoids none by construction.
        "reprefill_tokens_avoided": avoided > 0,
        "sessions_resumed": kt["sessions_resumed"] > 0,
        # The baseline arm must be the pre-tiering code path: no tier
        # telemetry at all.
        "baseline_untiered": "kv_tiers" not in baseline,
        "zero_invariant_violations": (
            tiered["invariants"]["violations"] == 0
            and baseline["invariants"]["violations"] == 0
        ),
    }

    return {
        "bench": "kv_tiering",
        "scenario_file": os.path.relpath(scenario_path, REPO),
        "tiered": {
            "fleet_prefix_hit_rate": tiered_hit,
            "ttft_p95_ms": tiered_ttft,
            "reprefill_tokens_avoided": avoided,
            "sessions_parked": kt["sessions_parked"],
            "sessions_resumed": kt["sessions_resumed"],
            "tier_demotes": kt["tier_demotes"],
            "t1_spills": kt.get("t1_spills", 0),
            "prefix_hits_local": kt["prefix_hits_local"],
            "prefix_hits_tier": kt["prefix_hits_tier"],
            "prefix_misses": kt["prefix_misses"],
            "virtual_s": tiered["virtual_s"],
        },
        "baseline": {
            "prefix_hit_rate": base_hit,
            "ttft_p95_ms": base_ttft,
            "virtual_s": baseline["virtual_s"],
        },
        "checks": checks,
    }


def main(argv: list[str] | None = None) -> int:
    ap = argparse.ArgumentParser(description=__doc__.split("\n")[0])
    ap.add_argument("--scenario", default=DEFAULT_SCENARIO)
    ap.add_argument(
        "--requests", type=int, default=None,
        help="override the scenario's request count",
    )
    ap.add_argument("--seed", type=int, default=None)
    ap.add_argument(
        "--out", default=os.path.join(REPO, "TIER_BENCH.json"),
        help="receipt path (default TIER_BENCH.json at repo root); "
             "'-' skips the write",
    )
    ap.add_argument(
        "--check-determinism", action="store_true",
        help="run both arms twice and fail unless the serialized results "
             "are byte-identical",
    )
    args = ap.parse_args(argv)

    result = run_all(args.scenario, args.requests, args.seed)
    if args.check_determinism:
        again = run_all(args.scenario, args.requests, args.seed)
        a = json.dumps(result, sort_keys=True)
        b = json.dumps(again, sort_keys=True)
        if a != b:
            print("DETERMINISM FAIL: same-seed re-run differs",
                  file=sys.stderr)
            return 1
        print("determinism: byte-identical same-seed re-run",
              file=sys.stderr)

    from bench import bench_provenance

    checks = result["checks"]
    passed = sum(bool(v) for v in checks.values())
    ok = passed == len(checks)
    receipt = {
        **result,
        # Flat count for bench_trend's TIER_BENCH family: the regression
        # gate compares this across revisions.
        "checks_passed": passed,
        "provenance": bench_provenance(),
    }
    if args.out != "-":
        with open(args.out, "w") as f:
            json.dump(receipt, f, indent=1, sort_keys=True)
            f.write("\n")

    t, b = result["tiered"], result["baseline"]
    print(json.dumps({
        "metric": "tiering_checks_passed",
        "value": passed,
        "unit": (
            f"of {len(checks)} checks (hit rate {t['fleet_prefix_hit_rate']}"
            f" vs {b['prefix_hit_rate']} baseline; ttft_p95 "
            f"{t['ttft_p95_ms']}ms vs {b['ttft_p95_ms']}ms; "
            f"{t['reprefill_tokens_avoided']} re-prefill tokens avoided; "
            f"failed: "
            f"{sorted(k for k, v in checks.items() if not v) or 'none'})"
        ),
        "ok": ok,
    }))
    return 0 if ok else 1


if __name__ == "__main__":
    sys.exit(main())
