"""Chaos runner: hammer the delivery substrate with worker kills and lost
responses, then audit that every accepted request got exactly one terminal
response.

This is the executable form of the at-least-once contract in
``serve/broker.py``: producers push N requests with deadlines, a fleet of
``ChaosWorkerHost``-hosted workers serves them through ``ChaosBroker``
proxies that hard-kill workers mid-lease and drop terminal responses, and
the audit at the end fails the process (exit 1) if any accepted request was
lost, answered twice, or answered with the wrong payload.

No server, no device: the engine is ``ScriptedEngine`` (deterministic
tokens, so payloads are checkable) and ``--broker fakeredis`` runs the real
``RedisBroker`` code against the in-memory ``FakeRedis``.

Examples::

    python tools/chaos_serve.py --requests 50 --workers 3 \
        --kill-prob 0.2 --drop-response-prob 0.1
    python tools/chaos_serve.py --broker fakeredis --poison 2 \
        --max-attempts 3

Prints a one-line JSON delivery report.
"""

from __future__ import annotations

import argparse
import json
import os
import sys
import threading
import time

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

from llmss_tpu.serve.broker import InProcBroker, RedisBroker  # noqa: E402
from llmss_tpu.serve.chaos import (  # noqa: E402
    POISON_TOKEN, ChaosBroker, ChaosWorkerHost, FakeRedis, ScriptedEngine,
)
from llmss_tpu.serve.consumer import Worker  # noqa: E402
from llmss_tpu.serve.protocol import GenerateRequest  # noqa: E402


def build_brokers(args):
    """(producer_broker, [worker_broker...]) sharing one substrate."""
    if args.broker == "inproc":
        b = InProcBroker(
            lease_s=args.lease_s, max_delivery_attempts=args.max_attempts
        )
        return b, [b] * args.workers
    server = FakeRedis()

    def mk(worker_id):
        return RedisBroker(
            client=server, worker_id=worker_id, lease_s=args.lease_s,
            max_delivery_attempts=args.max_attempts,
        )

    return mk("producer"), [mk(f"worker{i}") for i in range(args.workers)]


def main(argv=None):
    p = argparse.ArgumentParser(
        "chaos_serve", description=__doc__.split("\n")[0]
    )
    p.add_argument("--requests", type=int, default=40)
    p.add_argument("--workers", type=int, default=2)
    p.add_argument("--broker", choices=("inproc", "fakeredis"),
                   default="inproc")
    p.add_argument("--seed", type=int, default=0)
    p.add_argument("--kill-prob", type=float, default=0.15,
                   help="P(hard-kill worker right after it leases a request)")
    p.add_argument("--drop-response-prob", type=float, default=0.1,
                   help="P(a terminal response is silently lost)")
    p.add_argument("--lease-s", type=float, default=0.5)
    p.add_argument("--max-attempts", type=int, default=6)
    p.add_argument("--poison", type=int, default=0,
                   help="requests whose prompt reliably crashes a worker "
                        "(expected to land in the DLQ)")
    p.add_argument("--deadline-s", type=float, default=60.0,
                   help="end-to-end deadline stamped on every request")
    p.add_argument("--batch-size", type=int, default=1)
    args = p.parse_args(argv)

    prod_broker, worker_brokers = build_brokers(args)

    hosts = []
    for i, wb in enumerate(worker_brokers):
        chaos = ChaosBroker(
            wb, seed=args.seed + i,
            kill_after_pop_prob=args.kill_prob,
            drop_response_prob=args.drop_response_prob,
        )

        def factory(chaos=chaos):
            return Worker(
                ScriptedEngine(kill_on_poison=True), chaos,
                batch_size=args.batch_size, poll_timeout_s=0.05,
                pad_batch=False,
            )

        hosts.append(ChaosWorkerHost(factory, respawn_delay_s=0.02))

    # -- submit --------------------------------------------------------------
    reqs = []
    for i in range(args.requests):
        prompt = [POISON_TOKEN] if i < args.poison else [i % 1000 + 1]
        reqs.append(GenerateRequest(
            token_ids=prompt, max_new_tokens=4,
            deadline_ts=time.time() + args.deadline_s,
        ))
    for r in reqs:
        prod_broker.push_request(r)

    for h in hosts:
        h.start()

    # -- collect: one waiter thread per request ------------------------------
    results: dict[str, object] = {}
    lock = threading.Lock()

    def wait_one(req):
        resp = prod_broker.wait_response(req.id, timeout=args.deadline_s)
        with lock:
            results[req.id] = resp
        # A second terminal response for the same id is a contract
        # violation; probe briefly.
        dup = prod_broker.wait_response(req.id, timeout=0.2)
        if dup is not None:
            with lock:
                results[req.id] = "DUPLICATE"

    waiters = [
        threading.Thread(target=wait_one, args=(r,), daemon=True)
        for r in reqs
    ]
    for t in waiters:
        t.start()
    for t in waiters:
        t.join(timeout=args.deadline_s + 5)
    for h in hosts:
        h.stop()

    # -- audit ---------------------------------------------------------------
    lost, dup, wrong, ok, errored = [], [], [], 0, 0
    for r in reqs:
        got = results.get(r.id)
        if got is None:
            lost.append(r.id)
        elif got == "DUPLICATE":
            dup.append(r.id)
        elif got.error:
            errored += 1
        elif got.token_ids != ScriptedEngine.expected_tokens(
            list(r.token_ids), r.max_new_tokens
        ):
            wrong.append(r.id)
        else:
            ok += 1

    report = {
        "requests": args.requests,
        "ok": ok,
        "errored": errored,
        "lost": len(lost),
        "duplicates": len(dup),
        "wrong_payload": len(wrong),
        "kills": sum(h.kills for h in hosts),
        "spawns": sum(h.spawns for h in hosts),
        "dlq_depth": prod_broker.dlq_depth(),
        "delivery": prod_broker.delivery_stats(),
        "host_errors": [h.error for h in hosts if h.error],
    }
    print(json.dumps(report))
    violations = lost or dup or wrong or report["host_errors"]
    if args.poison and prod_broker.dlq_depth() < args.poison:
        violations = True
    return 1 if violations else 0


if __name__ == "__main__":
    raise SystemExit(main())
