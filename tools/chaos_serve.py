"""Chaos runner: hammer the delivery substrate with worker kills and lost
responses, then audit that every accepted request got exactly one terminal
response.

This is the executable form of the at-least-once contract in
``serve/broker.py``: producers push N requests with deadlines, a fleet of
``ChaosWorkerHost``-hosted workers serves them through ``ChaosBroker``
proxies that hard-kill workers mid-lease and drop terminal responses, and
the audit at the end fails the process (exit 1) if any accepted request was
lost, answered twice, or answered with the wrong payload.

No server, no device: the engine is ``ScriptedEngine`` (deterministic
tokens, so payloads are checkable) and ``--broker fakeredis`` runs the real
``RedisBroker`` code against the in-memory ``FakeRedis``.

Examples::

    python tools/chaos_serve.py --requests 50 --workers 3 \
        --kill-prob 0.2 --drop-response-prob 0.1
    python tools/chaos_serve.py --broker fakeredis --poison 2 \
        --max-attempts 3
    python tools/chaos_serve.py --fault drain   # lifecycle scenarios:
    python tools/chaos_serve.py --fault hang    #   supervised worker +
    python tools/chaos_serve.py --fault nan     #   scripted failure
    python tools/chaos_serve.py --scenario scenarios/storm.json \
        --requests 60                           # sim-scenario parity

Prints a one-line JSON delivery report.
"""

from __future__ import annotations

import argparse
import json
import os
import random
import sys
import threading
import time

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

from llmss_tpu.serve.broker import InProcBroker, RedisBroker  # noqa: E402
from llmss_tpu.serve.chaos import (  # noqa: E402
    NAN_TOKEN, POISON_TOKEN, ChaosBroker, ChaosWorkerHost, FakeRedis,
    HardKill, ScriptedEngine,
)
from llmss_tpu.serve.consumer import Worker  # noqa: E402
from llmss_tpu.serve.handoff import DecodeWorker, PrefillWorker  # noqa: E402
from llmss_tpu.serve.protocol import (  # noqa: E402
    SLO_CLASSES,
    GenerateRequest,
    GenerateResponse,
)
from llmss_tpu.serve.supervisor import Supervisor  # noqa: E402
from llmss_tpu.sim.invariants import (  # noqa: E402
    audit_exactly_once,
    collect_responses,
)


def build_brokers(args):
    """(producer_broker, [worker_broker...]) sharing one substrate."""
    if args.broker == "inproc":
        b = InProcBroker(
            lease_s=args.lease_s, max_delivery_attempts=args.max_attempts
        )
        return b, [b] * args.workers
    server = FakeRedis()

    def mk(worker_id):
        return RedisBroker(
            client=server, worker_id=worker_id, lease_s=args.lease_s,
            max_delivery_attempts=args.max_attempts,
        )

    return mk("producer"), [mk(f"worker{i}") for i in range(args.workers)]


def run_fault(args):
    """Deterministic single-worker lifecycle scenarios (``--fault``).

    Unlike the random kill/drop fleet, these drive ONE supervised worker
    through a scripted failure and audit the lifecycle contract:

    - ``drain``:  drain mid-load; every response so far is clean, nothing
      was redelivered, and the supervisor lands in state ``dead``.
    - ``hang``:   the engine wedges on one generate call; the watchdog must
      detect it, restart the worker, and every request still gets exactly
      one terminal response with the exact scripted payload.
    - ``nan``:    rows carrying ``NAN_TOKEN`` go non-finite; only those rows
      error while co-batched requests keep their exact solo tokens.
    """
    args.workers = 1
    prod_broker, (wb,) = build_brokers(args)

    engine_kwargs = {}
    if args.fault == "hang":
        engine_kwargs = {"hang_at": 3, "hang_s": args.deadline_s}
    elif args.fault == "nan":
        engine_kwargs = {"nan_at": 1}
    # One engine shared across supervised restarts so a scripted hang
    # fires exactly once (the rebuilt worker must make progress).
    engine = ScriptedEngine(**engine_kwargs)

    def factory():
        return Worker(
            engine, wb, batch_size=args.batch_size, poll_timeout_s=0.02,
            pad_batch=False,
        )

    sup = Supervisor(
        factory, wb, backoff_s=0.01, heartbeat_s=0.05,
        step_timeout_s=0.5 if args.fault == "hang" else None,
        drain_timeout_s=10.0,
    )

    n_poison = args.poison
    if args.fault == "nan" and n_poison == 0:
        n_poison = max(1, args.requests // 4)
    reqs = []
    for i in range(args.requests):
        prompt = [NAN_TOKEN, i + 1] if i < n_poison else [i % 1000 + 1]
        reqs.append(GenerateRequest(
            token_ids=prompt, max_new_tokens=4,
            deadline_ts=time.time() + args.deadline_s,
        ))
    for r in reqs:
        prod_broker.push_request(r)

    stop = threading.Event()
    sup_thread = threading.Thread(
        target=sup.run, args=(stop,), daemon=True
    )
    sup_thread.start()

    results: dict[str, object] = {}
    lock = threading.Lock()
    give_up = threading.Event()
    hard_deadline = time.time() + args.deadline_s

    def wait_one(req):
        while not give_up.is_set() and time.time() < hard_deadline:
            resp = prod_broker.wait_response(req.id, timeout=0.2)
            if resp is None:
                continue
            with lock:
                results[req.id] = resp
            dup = prod_broker.wait_response(req.id, timeout=0.2)
            if dup is not None:
                with lock:
                    results[req.id] = "DUPLICATE"
            return

    waiters = [
        threading.Thread(target=wait_one, args=(r,), daemon=True)
        for r in reqs
    ]
    for t in waiters:
        t.start()

    if args.fault == "drain":
        # Let some of the load complete, then drain mid-stream.
        threshold = max(1, args.requests // 3)
        while time.time() < hard_deadline:
            with lock:
                if len(results) >= threshold:
                    break
            time.sleep(0.01)
        sup.drain(timeout_s=10.0)
        sup_thread.join(timeout=args.deadline_s)
        time.sleep(0.3)  # let in-flight terminal responses land
        give_up.set()
    else:
        while time.time() < hard_deadline:
            with lock:
                if len(results) == args.requests:
                    break
            time.sleep(0.02)
        stop.set()
        sup_thread.join(timeout=10.0)
        give_up.set()
    for t in waiters:
        t.join(timeout=5.0)

    # -- audit ---------------------------------------------------------------
    lost, dup, wrong, bad_error, ok, errored = [], [], [], [], 0, 0
    for i, r in enumerate(reqs):
        got = results.get(r.id)
        poisoned = args.fault == "nan" and i < n_poison
        if got is None:
            lost.append(r.id)
        elif got == "DUPLICATE":
            dup.append(r.id)
        elif got.error:
            errored += 1
            if not poisoned or "poisoned" not in got.error:
                bad_error.append(r.id)
        elif poisoned:
            bad_error.append(r.id)  # poisoned row must not look clean
        elif got.token_ids != ScriptedEngine.expected_tokens(
            list(r.token_ids), r.max_new_tokens
        ):
            wrong.append(r.id)
        else:
            ok += 1

    stats = prod_broker.delivery_stats()
    report = {
        "fault": args.fault,
        "requests": args.requests,
        "ok": ok,
        "errored": errored,
        "unanswered": len(lost),
        "duplicates": len(dup),
        "wrong_payload": len(wrong),
        "bad_error": len(bad_error),
        "restarts": sup.restarts,
        "watchdog_stalls": sup.watchdog_stalls,
        "state": sup.state,
        "delivery": stats,
    }
    print(json.dumps(report))

    violations = bool(dup or wrong or bad_error)
    if args.fault == "drain":
        # Everything answered before/through the drain must be clean and
        # delivered once; requests still queued at drain are expected to go
        # unanswered here, not errored.
        violations |= errored > 0 or stats.get("redelivered", 0) > 0
        violations |= sup.state != "dead"
    elif args.fault == "hang":
        violations |= bool(lost) or sup.watchdog_stalls < 1
    elif args.fault == "nan":
        violations |= bool(lost) or errored != n_poison
    return 1 if violations else 0


def run_kill_mid_handoff(args):
    """Disaggregated prefill/decode chaos (``--fault kill-mid-handoff``).

    One prefill replica + one decode replica over the broker's KV handoff
    channel. The prefill replica is hard-killed AFTER exporting a
    request's KV but BEFORE pushing the handoff record — the narrowest
    loss window in the disaggregated path. Because ``push_handoff`` is
    what settles the request lease, a death in that window leaves the
    lease un-acked: the visibility timeout must redeliver the request to
    the respawned prefill replica (a re-prefill), and the audit fails the
    process if any request was lost, double-answered, or answered with
    the wrong payload.
    """
    args.workers = 2
    prod_broker, (pb, db) = build_brokers(args)

    kills_left = [args.kills]
    klock = threading.Lock()

    def on_exported(rec):
        with klock:
            if kills_left[0] > 0:
                kills_left[0] -= 1
                raise HardKill(
                    f"chaos: killed after exporting {rec.req.id}, "
                    "before push_handoff"
                )

    pre_host = ChaosWorkerHost(
        lambda: PrefillWorker(
            ScriptedEngine(), pb, worker_id="prefill0",
            on_exported=on_exported, poll_timeout_s=0.02,
        ),
        respawn_delay_s=0.02,
    )
    dec_host = ChaosWorkerHost(
        lambda: DecodeWorker(
            ScriptedEngine(), db, worker_id="decode0", poll_timeout_s=0.02,
        ),
        respawn_delay_s=0.02,
    )

    reqs = [
        GenerateRequest(
            token_ids=[i % 1000 + 1, i % 7 + 1], max_new_tokens=4,
            deadline_ts=time.time() + args.deadline_s,
        )
        for i in range(args.requests)
    ]
    for r in reqs:
        prod_broker.push_request(r)
    pre_host.start()
    dec_host.start()

    results: dict[str, object] = {}
    lock = threading.Lock()

    def wait_one(req):
        resp = prod_broker.wait_response(req.id, timeout=args.deadline_s)
        with lock:
            results[req.id] = resp
        dup = prod_broker.wait_response(req.id, timeout=0.2)
        if dup is not None:
            with lock:
                results[req.id] = "DUPLICATE"

    waiters = [
        threading.Thread(target=wait_one, args=(r,), daemon=True)
        for r in reqs
    ]
    for t in waiters:
        t.start()
    for t in waiters:
        t.join(timeout=args.deadline_s + 5)
    pre_host.stop()
    dec_host.stop()

    lost, dup, wrong, ok, errored = [], [], [], 0, 0
    for r in reqs:
        got = results.get(r.id)
        if got is None:
            lost.append(r.id)
        elif got == "DUPLICATE":
            dup.append(r.id)
        elif got.error:
            errored += 1
        elif got.token_ids != ScriptedEngine.expected_tokens(
            list(r.token_ids), r.max_new_tokens
        ):
            wrong.append(r.id)
        else:
            ok += 1

    stats = prod_broker.delivery_stats()
    report = {
        "fault": "kill-mid-handoff",
        "requests": args.requests,
        "ok": ok,
        "errored": errored,
        "lost": len(lost),
        "duplicates": len(dup),
        "wrong_payload": len(wrong),
        "prefill_kills": pre_host.kills,
        "handoffs": stats.get("handoffs"),
        "reprefills": stats.get("reprefills"),
        "delivery": stats,
        "host_errors": [
            h.error for h in (pre_host, dec_host) if h.error
        ],
    }
    print(json.dumps(report))
    violations = bool(
        lost or dup or wrong or errored or report["host_errors"]
    )
    violations |= pre_host.kills < args.kills  # the fault must have fired
    return 1 if violations else 0


class _PromoteWorker:
    """Chaos worker for ``--fault kill-mid-promotion``: serves scripted
    requests through a REAL :class:`TieredKVStore`. A request carrying
    ``prefix_token_ids`` first tries to promote the prefix out of the
    tier store (the affinity-miss path) — which is where the store's
    ``fault_hook`` can hard-kill the process, mid-T2-fetch. A
    REDELIVERED request (``delivery_attempts > 1``) skips the promotion
    path entirely and full-prefills: a request that already took a
    worker down mid-promotion must not re-enter the same hazard window,
    the same discipline delivery_attempts applies to poison prompts."""

    def __init__(self, broker, store, counters, lock, max_seq_len=128):
        self.broker = broker
        self.store = store
        self.counters = counters
        self.lock = lock
        self.max_seq_len = max_seq_len

    def run_once(self):
        req = self.broker.pop_request(timeout=0.02)
        if req is None:
            return
        via = "full_prefill"
        if req.prefix_token_ids and req.delivery_attempts <= 1:
            pfx = self.store.fetch_prefix(  # fault_hook may HardKill here
                req.prefix_token_ids, max_seq_len=self.max_seq_len,
            )
            if pfx is not None:
                via = "promotion"
        with self.lock:
            self.counters[via] += 1
            if req.delivery_attempts > 1:
                self.counters["retry_full_prefill"] += 1
        self.broker.push_response(GenerateResponse(
            id=req.id,
            token_ids=ScriptedEngine.expected_tokens(
                list(req.token_ids), req.max_new_tokens,
            ),
        ))


def run_kill_mid_promotion(args):
    """Tiered-KV promotion chaos (``--fault kill-mid-promotion``).

    A prefix is parked in the fleet blob tier (T2) of a real
    ``serve/kvstore.py`` store; two workers share that T2 backend (each
    with its own empty T1, like two real hosts). The chaos worker's
    ``fault_hook`` hard-kills it INSIDE ``fetch_prefix`` — after the T2
    fetch began, before the rebuilt prefix ever reached a device — for
    the first ``--kills`` promotions. Contracts audited:

    - exactly-one-terminal with exact payloads for every request (the
      killed worker's lease rots; the visibility timeout redelivers);
    - the parked blob survives its dead readers BIT-EXACT in T2 — a
      promotion is a read, never a move;
    - every redelivered request serves by full prefill (the worker
      refuses to re-enter the promotion window for it), and promotions
      succeed again once the kill budget is spent.
    """
    import numpy as np

    from llmss_tpu.serve.kvstore import (
        HostKVStore, InProcBlobStore, RedisBlobStore, TieredKVStore,
        prefix_from_blocks,
    )

    args.workers = 2
    prod_broker, (wb1, wb2) = build_brokers(args)
    if args.broker == "fakeredis":
        # Same substrate as the broker, same namespace discipline as
        # consumer.main: the ``:kv:`` segment keeps blobs clear of
        # queue/lease keys.
        blob = RedisBlobStore(prod_broker._r, namespace="chaos")
    else:
        blob = InProcBlobStore()

    # Park one shared prefix straight into T2 (cap 0: every put spills).
    bs, n, L, Hkv, D = 16, 20, 2, 2, 4
    pfx_tokens = [(i * 13) % 997 + 1 for i in range(n)]
    blocks = {
        "k": np.arange(L * 2 * bs * Hkv * D, dtype=np.float32).reshape(
            L, 2, bs, Hkv, D,
        ),
        "v": -np.arange(L * 2 * bs * Hkv * D, dtype=np.float32).reshape(
            L, 2, bs, Hkv, D,
        ),
        "k_scale": None,
        "v_scale": None,
    }
    parked = prefix_from_blocks(pfx_tokens, blocks, max_seq_len=128)
    seeder = TieredKVStore(host=HostKVStore(cap_bytes=0), blob=blob)
    seeder.demote_prefix(parked, bs)
    seeder.flush()

    kills_left = [args.kills]
    klock = threading.Lock()

    def hook(stage, key):
        if stage != "t2_get":
            return
        with klock:
            if kills_left[0] > 0:
                kills_left[0] -= 1
                raise HardKill(f"chaos: killed mid-promotion of {key}")

    chaos_store = TieredKVStore(host=HostKVStore(cap_bytes=0), blob=blob)
    chaos_store.fault_hook = hook
    sane_store = TieredKVStore(host=HostKVStore(cap_bytes=0), blob=blob)

    counters: dict[str, int] = {
        "promotion": 0, "full_prefill": 0, "retry_full_prefill": 0,
    }
    clock = threading.Lock()
    hosts = [
        ChaosWorkerHost(
            lambda: _PromoteWorker(wb1, chaos_store, counters, clock),
            respawn_delay_s=0.02,
        ),
        ChaosWorkerHost(
            lambda: _PromoteWorker(wb2, sane_store, counters, clock),
            respawn_delay_s=0.02,
        ),
    ]

    reqs = [
        GenerateRequest(
            token_ids=list(pfx_tokens) + [i % 1000 + 1, i % 7 + 1],
            prefix_token_ids=list(pfx_tokens),
            max_new_tokens=4,
            deadline_ts=time.time() + args.deadline_s,
        )
        for i in range(args.requests)
    ]
    for r in reqs:
        prod_broker.push_request(r)
    # The chaos worker must spend its kill budget on first-attempt
    # promotions before the healthy worker races it to an empty queue
    # (run_burst's discipline).
    hosts[0].start()
    spend_deadline = time.time() + args.deadline_s / 2
    while time.time() < spend_deadline:
        with klock:
            if kills_left[0] <= 0:
                break
        time.sleep(0.01)
    hosts[1].start()

    results = collect_responses(prod_broker, reqs, timeout_s=args.deadline_s)
    for h in hosts:
        h.stop()

    violation = None
    successes = 0
    try:
        successes = audit_exactly_once(reqs, results, broker=prod_broker)
    except AssertionError as e:
        violation = str(e)

    # The blob must still be promotable, bit-exact, after its readers
    # died mid-fetch.
    check = TieredKVStore(host=HostKVStore(cap_bytes=0), blob=blob)
    survivor = check.fetch_prefix(pfx_tokens, max_seq_len=128)
    blob_intact = survivor is not None and all(
        np.array_equal(
            np.asarray(getattr(survivor, f))[:, :n],
            np.asarray(getattr(parked, f))[:, :n],
        )
        for f in ("k", "v")
    )

    kills = hosts[0].kills
    stats = prod_broker.delivery_stats()
    report = {
        "fault": "kill-mid-promotion",
        "requests": args.requests,
        "ok": successes,
        "kills": kills,
        "promotions": counters["promotion"],
        "full_prefills": counters["full_prefill"],
        "retry_full_prefills": counters["retry_full_prefill"],
        "blob_intact_in_t2": blob_intact,
        "delivery": stats,
        "host_errors": [h.error for h in hosts if h.error],
        "violation": violation,
    }
    print(json.dumps(report))
    violations = bool(violation or report["host_errors"])
    violations |= kills < args.kills          # the fault must have fired
    violations |= not blob_intact             # promotion is a read, not a move
    # Every kill orphaned exactly one request; each must have come back
    # through redelivery and served by full prefill.
    violations |= counters["retry_full_prefill"] < args.kills
    violations |= counters["promotion"] < 1   # the path works post-budget
    return 1 if violations else 0


class _PreemptThenDie:
    """Chaos worker for ``--fault burst``: leases a request, records its
    partial progress as ``resume_tokens``, hands it back through the
    preemption refund path, then hard-kills — the
    preempted-but-not-yet-resumed window. Alternate kills die while
    still *holding* the lease (no preempt), so the reaper redelivery
    window is exercised in the same run. Once its kill budget is spent
    it idles and the healthy worker drains the queue."""

    def __init__(self, broker, kills_left, klock, partial=2):
        self.broker = broker
        self.kills_left = kills_left
        self.klock = klock
        self.partial = partial

    def run_once(self):
        with self.klock:
            if self.kills_left[0] <= 0:
                time.sleep(0.05)
                return
            req = self.broker.pop_request(timeout=0.02)
            if req is None:
                return
            n = self.kills_left[0]
            self.kills_left[0] = n - 1
        if n % 2:
            full = ScriptedEngine.expected_tokens(
                list(req.token_ids), req.max_new_tokens
            )
            take = min(
                len(req.resume_tokens or ()) + self.partial,
                req.max_new_tokens - 1,
            )
            req.resume_tokens = full[:take] or None
            req.preemptions += 1
            self.broker.preempt_requests([req])
            raise HardKill(f"chaos: died after preempting {req.id}")
        raise HardKill(f"chaos: died holding lease on {req.id}")


def run_burst(args):
    """Mixed-class burst chaos (``--fault burst``).

    The whole request set — interactive, standard, and batch interleaved
    — lands on the queue at once. One chaos replica preempts requests
    mid-flight (stamping partial ``resume_tokens``) and dies in the
    preempted-but-not-yet-resumed window, or dies holding an unpreempted
    lease; one healthy replica serves everything, resuming preempted
    work from its replayed tokens. The audit fails the process unless
    every request got exactly one terminal response whose token stream
    equals the never-preempted scripted stream.
    """
    args.workers = 2
    prod_broker, (doom_b, work_b) = build_brokers(args)

    kills_left = [args.kills]
    klock = threading.Lock()
    doom_host = ChaosWorkerHost(
        lambda: _PreemptThenDie(doom_b, kills_left, klock),
        respawn_delay_s=0.02,
    )
    work_host = ChaosWorkerHost(
        lambda: Worker(
            ScriptedEngine(), work_b, batch_size=args.batch_size,
            poll_timeout_s=0.02, pad_batch=False,
        ),
        respawn_delay_s=0.02,
    )

    reqs = [
        GenerateRequest(
            token_ids=[i % 1000 + 1, i % 7 + 1], max_new_tokens=4,
            slo_class=SLO_CLASSES[i % len(SLO_CLASSES)],
            deadline_ts=time.time() + args.deadline_s,
        )
        for i in range(args.requests)
    ]
    for r in reqs:
        prod_broker.push_request(r)
    doom_host.start()
    # The chaos replica must get its kills in before the healthy replica
    # starts draining, or a fast worker races it to an empty queue.
    spend_deadline = time.time() + args.deadline_s / 2
    while time.time() < spend_deadline:
        with klock:
            if kills_left[0] <= 0:
                break
        time.sleep(0.01)
    work_host.start()

    results: dict[str, object] = {}
    lock = threading.Lock()

    def wait_one(req):
        resp = prod_broker.wait_response(req.id, timeout=args.deadline_s)
        with lock:
            results[req.id] = resp
        dup = prod_broker.wait_response(req.id, timeout=0.2)
        if dup is not None:
            with lock:
                results[req.id] = "DUPLICATE"

    waiters = [
        threading.Thread(target=wait_one, args=(r,), daemon=True)
        for r in reqs
    ]
    for t in waiters:
        t.start()
    for t in waiters:
        t.join(timeout=args.deadline_s + 5)
    doom_host.stop()
    work_host.stop()

    lost, dup, wrong, ok, errored = [], [], [], 0, 0
    for r in reqs:
        got = results.get(r.id)
        if got is None:
            lost.append(r.id)
        elif got == "DUPLICATE":
            dup.append(r.id)
        elif got.error:
            errored += 1
        elif got.token_ids != ScriptedEngine.expected_tokens(
            list(r.token_ids), r.max_new_tokens
        ):
            wrong.append(r.id)
        else:
            ok += 1

    stats = prod_broker.delivery_stats()
    report = {
        "fault": "burst",
        "requests": args.requests,
        "ok": ok,
        "errored": errored,
        "lost": len(lost),
        "duplicates": len(dup),
        "wrong_payload": len(wrong),
        "chaos_kills": doom_host.kills,
        "preempted": stats.get("preempted"),
        "dlq_depth": prod_broker.dlq_depth(),
        "delivery": stats,
        "host_errors": [
            h.error for h in (doom_host, work_host) if h.error
        ],
    }
    print(json.dumps(report))
    violations = bool(
        lost or dup or wrong or errored or report["host_errors"]
    )
    violations |= doom_host.kills < args.kills  # every kill must fire
    # Preempt-then-die kills must all have traveled the refund path, and
    # a refunded preemption must never land in the DLQ.
    violations |= (stats.get("preempted") or 0) < -(-args.kills // 2)
    violations |= prod_broker.dlq_depth() > 0
    return 1 if violations else 0


def run_flap(args):
    """Registry flap chaos (``--fault flap``).

    A phantom worker rapidly registers and deregisters (period ~10ms)
    while one stable replica serves a steady stream through the Router.
    Three contracts under audit:

    - requests the Router placed on the flapper during an up-window are
      evacuated by the failover sweep (orphan routed queue) and still
      answered exactly once with the exact scripted payload;
    - once the flapper is durably gone, the Router never again places
      work on it — no routing into the gap;
    - a reconciling FleetController watching the same registry holds
      still: registry flapping alone, with neutral telemetry, must not
      produce a single spawn or retire (dwell + telemetry-driven
      planning absorb membership noise).
    """
    from llmss_tpu.serve.controller import FleetController
    from llmss_tpu.serve.fleet import Router

    args.workers = 1
    prod_broker, (wb,) = build_brokers(args)

    host = ChaosWorkerHost(
        lambda: Worker(
            ScriptedEngine(), wb, batch_size=args.batch_size,
            poll_timeout_s=0.02, pad_batch=False,
        ),
        respawn_delay_s=0.02,
    )
    host.start()

    router = Router(
        prod_broker, policy="least_loaded", failover_check_s=0.05,
    )

    flap_id = "flap-w"
    stop_flap = threading.Event()
    flap_lock = threading.Lock()
    flap_state = {"registered": False, "since": time.monotonic(), "ups": 0}

    def flapper():
        while not stop_flap.is_set():
            prod_broker.register_worker({
                "worker_id": flap_id, "model": "scripted",
                "role": "unified", "heartbeat_ts": time.time(),
                "heartbeat_s": 0.5, "free_slots": 8,
            })
            with flap_lock:
                flap_state["registered"] = True
                flap_state["since"] = time.monotonic()
                flap_state["ups"] += 1
            time.sleep(0.005)
            prod_broker.deregister_worker(flap_id)
            with flap_lock:
                flap_state["registered"] = False
                flap_state["since"] = time.monotonic()
            time.sleep(0.005)

    flap_thread = threading.Thread(target=flapper, daemon=True)
    flap_thread.start()

    # A reconciling controller over the same (flapping) registry. Its
    # telemetry is pinned neutral — any actuation it takes can only have
    # come from membership noise, which is exactly the non-contract.
    actions: list = []
    ctrl = FleetController(
        prod_broker,
        spawn=lambda role: (
            actions.append(("spawn", role)), f"flap-spawn-{len(actions)}",
        )[1],
        retire=lambda wid: actions.append(("retire", wid)),
        read_telemetry=lambda: {
            "ts": time.monotonic(), "burn": 1.0,
            "queue_depth": 0, "handoff_depth": 0, "util": {},
        },
        roles=("unified",), floor=1, ceiling=4,
        check_s=0.02, cooldown_s=0.1, dwell_s=0.5,
    )
    ctrl.start()
    stop_ctrl = threading.Event()

    def ctrl_loop():
        while not stop_ctrl.is_set():
            ctrl.tick()
            time.sleep(0.01)

    ctrl_thread = threading.Thread(target=ctrl_loop, daemon=True)
    ctrl_thread.start()

    mid_gap: list[str] = []

    def routed_mid_gap() -> bool:
        # Only a route placed while the flapper has been CONTINUOUSLY
        # deregistered for longer than any registry-read race window
        # counts — flap period is ~10ms, so 250ms of gap is unambiguous.
        with flap_lock:
            return (
                not flap_state["registered"]
                and time.monotonic() - flap_state["since"] > 0.25
            )

    reqs = [
        GenerateRequest(
            token_ids=[i % 1000 + 1], max_new_tokens=4,
            slo_class=SLO_CLASSES[i % len(SLO_CLASSES)],
            deadline_ts=time.time() + args.deadline_s,
        )
        for i in range(args.requests)
    ]
    routed_to_flapper = 0
    for r in reqs:
        wid = router.submit(r)
        if wid == flap_id:
            routed_to_flapper += 1
            if routed_mid_gap():
                mid_gap.append(r.id)
        time.sleep(0.003)

    # Durably kill the flapper, then probe: nothing may route there now.
    stop_flap.set()
    flap_thread.join(timeout=2.0)
    prod_broker.deregister_worker(flap_id)
    with flap_lock:
        flap_state["registered"] = False
        flap_state["since"] = time.monotonic() - 1.0
    probes = [
        GenerateRequest(
            token_ids=[500 + i], max_new_tokens=4,
            deadline_ts=time.time() + args.deadline_s,
        )
        for i in range(10)
    ]
    for r in probes:
        wid = router.submit(r)
        if wid == flap_id:
            mid_gap.append(r.id)

    # Evacuate anything still parked on the flapper's orphan queue.
    deadline = time.time() + args.deadline_s
    while time.time() < deadline:
        router.check_failover(force=True)
        if not prod_broker.routed_depths().get(flap_id):
            break
        time.sleep(0.05)

    everything = reqs + probes
    results = collect_responses(
        prod_broker, everything, timeout_s=args.deadline_s,
    )
    stop_ctrl.set()
    ctrl_thread.join(timeout=2.0)
    host.stop()

    violation = None
    successes = 0
    try:
        successes = audit_exactly_once(
            everything, results, broker=prod_broker,
        )
    except AssertionError as e:
        violation = str(e)

    report = {
        "fault": "flap",
        "requests": len(everything),
        "ok": successes,
        "flaps": flap_state["ups"],
        "routed_to_flapper": routed_to_flapper,
        "routed_mid_gap": len(mid_gap),
        "failover_reroutes": router.stats()["failover_reroutes"],
        "controller_actions": len(actions),
        "controller_counters": ctrl.counters,
        "dlq_depth": prod_broker.dlq_depth(),
        "delivery": prod_broker.delivery_stats(),
        "host_error": host.error,
        "violation": violation,
    }
    print(json.dumps(report))
    violations = bool(violation or host.error or mid_gap or actions)
    violations |= flap_state["ups"] < 3  # the storm must actually flap
    return 1 if violations else 0


def run_scenario(args):
    """Replay a fleet-simulator scenario's fault plane against a REAL
    in-process fleet (``--scenario file.json``).

    The simulator (``llmss_tpu/sim/``) runs these scenario files on a
    virtual clock; this mode is the parity check — same fault kinds,
    actual threads and wall time, audited with the same shared helpers
    (``collect_responses`` / ``audit_exactly_once``). The scenario's
    virtual schedule maps onto wall time via ``--time-scale`` and is
    truncated at ``--scenario-wall-s``; the fleet's role mix is kept but
    scaled down to ``--workers`` machines; the request count comes from
    ``--requests`` (the scenario's own count is a sim-scale number).

    Fault mapping (virtual -> wall):

    - ``kill_wave`` / ``handoff_storm``: a one-shot ``HardKill`` window
      (``kill_after_pop_prob=1.0`` until the scaled respawn delay
      elapses) on request-popping workers; ``handoff_storm`` prefers
      prefill replicas so the export-then-die window is exercised.
    - ``partition``: ``ChaosBroker.partition_for`` — ops raise builtin
      ``ConnectionError``; hosts reconnect, held leases rot to
      redelivery.
    - ``latency_spike``: ``op_latency_s = extra_s`` for the scaled
      window.
    - ``heartbeat_stall``: an op-latency window longer than the lease,
      so the reaper races the stalled worker's late answers.
    """
    with open(args.scenario) as f:
        spec = json.load(f)
    scale = args.time_scale
    rng = random.Random(int(spec.get("seed", 0)) ^ args.seed)
    br_spec = spec.get("broker") or {}
    args.max_attempts = int(
        br_spec.get("max_delivery_attempts", args.max_attempts)
    )

    # -- fleet: keep the scenario's role mix, scaled to --workers ------------
    groups = (spec.get("fleet") or {}).get("replicas") or [
        {"count": args.workers, "role": "unified"}
    ]
    total = sum(int(g.get("count", 1)) for g in groups)
    roles: list[str] = []
    for g in groups:
        n = max(1, round(int(g.get("count", 1)) * args.workers / total))
        roles.extend([g.get("role", "unified")] * n)
    args.workers = len(roles)
    prod_broker, worker_brokers = build_brokers(args)

    proxies: list[ChaosBroker] = []
    hosts: list[ChaosWorkerHost] = []
    popper_idx: list[int] = []   # workers that pop_request (killable pool)
    prefill_idx: list[int] = []
    for i, (role, wb) in enumerate(zip(roles, worker_brokers)):
        chaos = ChaosBroker(wb, seed=int(spec.get("seed", 0)) + i)
        proxies.append(chaos)
        delay = args.chunk_delay_s
        if role == "prefill":
            popper_idx.append(i)
            prefill_idx.append(i)

            def factory(c=chaos, i=i, delay=delay):
                return PrefillWorker(
                    ScriptedEngine(chunk_delay_s=delay), c,
                    worker_id=f"prefill{i}", poll_timeout_s=0.02,
                )
        elif role == "decode":

            def factory(c=chaos, i=i, delay=delay):
                return DecodeWorker(
                    ScriptedEngine(chunk_delay_s=delay), c,
                    worker_id=f"decode{i}", poll_timeout_s=0.02,
                )
        else:
            popper_idx.append(i)

            def factory(c=chaos, delay=delay):
                return Worker(
                    ScriptedEngine(kill_on_poison=True, chunk_delay_s=delay),
                    c, batch_size=args.batch_size, poll_timeout_s=0.02,
                    pad_batch=False,
                )

        hosts.append(ChaosWorkerHost(factory, respawn_delay_s=0.05))

    # -- fault schedule: expand repeats, scale, truncate ---------------------
    duration = float(spec.get("duration_s", 60.0))
    instances: list[tuple[float, dict]] = []
    for f in spec.get("faults", ()):
        t = float(f.get("at_s", 0.0))
        rep = float(f.get("repeat_every_s", 0.0) or 0.0)
        while t < duration:
            wall = t * scale
            if wall < args.scenario_wall_s:
                instances.append((wall, f))
            if rep <= 0:
                break
            t += rep
    instances.sort(key=lambda p: p[0])

    timers: list[threading.Timer] = []

    def at(wall_t, fn, *fn_args):
        tm = threading.Timer(wall_t, fn, fn_args)
        tm.daemon = True
        timers.append(tm)

    def pick(n, pool):
        if not pool:
            return []
        if n == "*" or int(n) >= len(pool):
            return list(pool)
        start = rng.randrange(len(pool))
        return [pool[(start + j) % len(pool)] for j in range(int(n))]

    def kill_window(idxs, hold_s):
        for i in idxs:
            proxies[i].kill_after_pop_prob = 1.0

        def relax():
            for i in idxs:
                proxies[i].kill_after_pop_prob = 0.0
        tm = threading.Timer(max(0.1, hold_s), relax)
        tm.daemon = True
        tm.start()

    for wall_t, f in instances:
        kind = f.get("kind")
        if kind in ("kill_wave", "handoff_storm"):
            pool = prefill_idx if (
                kind == "handoff_storm" and prefill_idx
            ) else popper_idx
            idxs = pick(f.get("count", 1), pool)
            hold = float(f.get("respawn_after_s", 1.0)) * scale
            stagger = float(f.get("stagger_s", 0.0)) * scale
            for k, i in enumerate(idxs):
                at(wall_t + k * stagger, kill_window, [i], hold)
        elif kind == "partition":
            dur = float(f.get("duration_s", 1.0)) * scale
            for i in pick(f.get("targets", 1), popper_idx):
                at(wall_t, proxies[i].partition_for, dur)
        elif kind in ("latency_spike", "heartbeat_stall"):
            if kind == "latency_spike":
                extra = float(f.get("extra_s", 0.05))
                idxs = pick(f.get("targets", "*"), popper_idx)
            else:  # stall past the lease so redelivery must race the worker
                extra = args.lease_s * 1.2
                idxs = pick(f.get("count", 1), popper_idx)
            dur = float(f.get("duration_s", 1.0)) * scale

            def spike(idxs=idxs, extra=extra, dur=dur):
                for i in idxs:
                    proxies[i].op_latency_s = extra

                def calm():
                    for i in idxs:
                        proxies[i].op_latency_s = 0.0
                tm = threading.Timer(dur, calm)
                tm.daemon = True
                tm.start()
            at(wall_t, spike)

    # -- paced traffic so faults land on live work ---------------------------
    n_poison = args.poison
    reqs = []
    for i in range(args.requests):
        prompt = [POISON_TOKEN] if i < n_poison else [i % 1000 + 1, i % 7 + 1]
        reqs.append(GenerateRequest(
            token_ids=prompt, max_new_tokens=4,
            slo_class=SLO_CLASSES[i % len(SLO_CLASSES)],
            deadline_ts=time.time() + args.deadline_s,
        ))
    span = max((instances[-1][0] + 0.5) if instances else 0.0, 1.0)

    def feed():
        gap = span / max(1, len(reqs))
        for r in reqs:
            prod_broker.push_request(r)
            time.sleep(gap)

    feeder = threading.Thread(target=feed, daemon=True)
    for h in hosts:
        h.start()
    for tm in timers:
        tm.start()
    feeder.start()

    results = collect_responses(prod_broker, reqs, timeout_s=args.deadline_s)

    for tm in timers:
        tm.cancel()
    for h in hosts:
        h.stop()

    violation = None
    successes = 0
    try:
        successes = audit_exactly_once(
            reqs, results, broker=prod_broker,
            poison_ids=[reqs[i].id for i in range(n_poison)],
        )
    except AssertionError as e:
        violation = str(e)

    def fsum(key):
        return sum(p.faults[key] for p in proxies)

    report = {
        "scenario": spec.get("name"),
        "requests": args.requests,
        "workers": {r: roles.count(r) for r in dict.fromkeys(roles)},
        "ok": successes,
        "fault_instances": len(instances),
        "kills": sum(h.kills for h in hosts),
        "spawns": sum(h.spawns for h in hosts),
        "reconnects": sum(h.reconnects for h in hosts),
        "partition_errors": fsum("partition_errors"),
        "latency_injections": fsum("latency_injections"),
        "dlq_depth": prod_broker.dlq_depth(),
        "delivery": prod_broker.delivery_stats(),
        "host_errors": [h.error for h in hosts if h.error],
        "violation": violation,
    }
    print(json.dumps(report))
    return 1 if (violation or report["host_errors"]) else 0


def main(argv=None):
    p = argparse.ArgumentParser(
        "chaos_serve", description=__doc__.split("\n")[0]
    )
    p.add_argument("--requests", type=int, default=40)
    p.add_argument("--workers", type=int, default=2)
    p.add_argument("--broker", choices=("inproc", "fakeredis"),
                   default="inproc")
    p.add_argument("--seed", type=int, default=0)
    p.add_argument("--kill-prob", type=float, default=0.15,
                   help="P(hard-kill worker right after it leases a request)")
    p.add_argument("--drop-response-prob", type=float, default=0.1,
                   help="P(a terminal response is silently lost)")
    p.add_argument("--lease-s", type=float, default=0.5)
    p.add_argument("--max-attempts", type=int, default=6)
    p.add_argument("--poison", type=int, default=0,
                   help="requests whose prompt reliably crashes a worker "
                        "(expected to land in the DLQ)")
    p.add_argument("--deadline-s", type=float, default=60.0,
                   help="end-to-end deadline stamped on every request")
    p.add_argument("--batch-size", type=int, default=1)
    p.add_argument("--fault",
                   choices=("drain", "hang", "nan", "kill-mid-handoff",
                            "kill-mid-promotion", "burst", "flap"),
                   default=None,
                   help="run a deterministic scripted-failure scenario "
                        "instead of the random kill/drop fleet")
    p.add_argument("--kills", type=int, default=3,
                   help="kill-mid-handoff: how many exports get the "
                        "prefill replica killed before push_handoff; "
                        "kill-mid-promotion: how many tier-store "
                        "promotions die mid-T2-fetch")
    p.add_argument("--scenario", default=None,
                   help="replay a sim scenario file's fault plane against "
                        "a real in-proc fleet (parity with llmss_tpu/sim)")
    p.add_argument("--time-scale", type=float, default=0.05,
                   help="scenario: virtual seconds -> wall seconds factor")
    p.add_argument("--scenario-wall-s", type=float, default=4.0,
                   help="scenario: truncate the scaled fault schedule here")
    p.add_argument("--chunk-delay-s", type=float, default=0.005,
                   help="scenario: per-chunk engine delay so traffic "
                        "overlaps the fault window")
    args = p.parse_args(argv)

    if args.scenario is not None:
        return run_scenario(args)
    if args.fault == "kill-mid-handoff":
        return run_kill_mid_handoff(args)
    if args.fault == "kill-mid-promotion":
        return run_kill_mid_promotion(args)
    if args.fault == "burst":
        return run_burst(args)
    if args.fault == "flap":
        return run_flap(args)
    if args.fault is not None:
        return run_fault(args)

    prod_broker, worker_brokers = build_brokers(args)

    hosts = []
    for i, wb in enumerate(worker_brokers):
        chaos = ChaosBroker(
            wb, seed=args.seed + i,
            kill_after_pop_prob=args.kill_prob,
            drop_response_prob=args.drop_response_prob,
        )

        def factory(chaos=chaos):
            return Worker(
                ScriptedEngine(kill_on_poison=True), chaos,
                batch_size=args.batch_size, poll_timeout_s=0.05,
                pad_batch=False,
            )

        hosts.append(ChaosWorkerHost(factory, respawn_delay_s=0.02))

    # -- submit --------------------------------------------------------------
    reqs = []
    for i in range(args.requests):
        prompt = [POISON_TOKEN] if i < args.poison else [i % 1000 + 1]
        reqs.append(GenerateRequest(
            token_ids=prompt, max_new_tokens=4,
            deadline_ts=time.time() + args.deadline_s,
        ))
    for r in reqs:
        prod_broker.push_request(r)

    for h in hosts:
        h.start()

    # -- collect: one waiter thread per request ------------------------------
    results: dict[str, object] = {}
    lock = threading.Lock()

    def wait_one(req):
        resp = prod_broker.wait_response(req.id, timeout=args.deadline_s)
        with lock:
            results[req.id] = resp
        # A second terminal response for the same id is a contract
        # violation; probe briefly.
        dup = prod_broker.wait_response(req.id, timeout=0.2)
        if dup is not None:
            with lock:
                results[req.id] = "DUPLICATE"

    waiters = [
        threading.Thread(target=wait_one, args=(r,), daemon=True)
        for r in reqs
    ]
    for t in waiters:
        t.start()
    for t in waiters:
        t.join(timeout=args.deadline_s + 5)
    for h in hosts:
        h.stop()

    # -- audit ---------------------------------------------------------------
    lost, dup, wrong, ok, errored = [], [], [], 0, 0
    for r in reqs:
        got = results.get(r.id)
        if got is None:
            lost.append(r.id)
        elif got == "DUPLICATE":
            dup.append(r.id)
        elif got.error:
            errored += 1
        elif got.token_ids != ScriptedEngine.expected_tokens(
            list(r.token_ids), r.max_new_tokens
        ):
            wrong.append(r.id)
        else:
            ok += 1

    report = {
        "requests": args.requests,
        "ok": ok,
        "errored": errored,
        "lost": len(lost),
        "duplicates": len(dup),
        "wrong_payload": len(wrong),
        "kills": sum(h.kills for h in hosts),
        "spawns": sum(h.spawns for h in hosts),
        "dlq_depth": prod_broker.dlq_depth(),
        "delivery": prod_broker.delivery_stats(),
        "host_errors": [h.error for h in hosts if h.error],
    }
    print(json.dumps(report))
    violations = lost or dup or wrong or report["host_errors"]
    if args.poison and prod_broker.dlq_depth() < args.poison:
        violations = True
    return 1 if violations else 0


if __name__ == "__main__":
    raise SystemExit(main())
