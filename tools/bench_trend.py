#!/usr/bin/env python
"""Aggregate every ``*_BENCH.json`` receipt into one trajectory file.

The repo accumulates bench receipts PR over PR — ``BENCH_r01..r05``,
``TRACE_BENCH``, ``SLO_BENCH``, … — but nothing collates them, so the
"bench trajectory" exists only as loose files. This tool builds
``TREND.json``: per-family, per-metric series ordered by revision, each
sample carrying its value/unit/vs_baseline and the receipt's
``bench_provenance`` block when present.

Receipt shapes handled (the three that exist in the tree):

- **runner receipts** (``BENCH_r*``, ``MULTICHIP_r*``): ``{"n", "cmd",
  "rc", "tail"}`` with JSON metric lines (``{"metric", "value", ...}``)
  embedded in the captured ``tail`` text. The FIRST metric line per
  receipt is the headline sample — later lines are config variants
  (int8 KV, a bigger model) whose values are not comparable release to
  release (r05 appends a 7b config; diffing it against r04's 1b2
  headline would read as a 94% "regression").
- **flat receipts** (``SERVE_BENCH``, ``PREFIX_BENCH``, …): a top-level
  ``{"metric", "value", ...}`` dict — one sample.
- **structured receipts** (``PD_BENCH``, ``RAGGED_BENCH``, …): nested
  dicts — every numeric leaf up to depth 3 becomes a dotted-path metric.

``--check FAMILY:metric`` gates CI: exit 1 when the newest receipt's
headline for that metric regressed more than ``--threshold`` (default
10%, lower-is-worse — every headline in the tree is a rate) against the
previous receipt in the family. Families with fewer than two receipts
pass vacuously (a trend needs two points).

Usage:
    python tools/bench_trend.py                    # write TREND.json
    python tools/bench_trend.py --check BENCH:decode_tokens_per_sec_per_chip
"""

from __future__ import annotations

import argparse
import glob
import json
import os
import re
import sys

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))

# BENCH_r01 / MULTICHIP_r03 → family BENCH / MULTICHIP, revision 1 / 3.
_REV_RE = re.compile(r"^(?P<family>.+?)_r(?P<rev>\d+)$")
# A JSON metric line inside a captured tail.
_TAIL_LINE_RE = re.compile(r"^\{.*\}$", re.M)

_MAX_LEAF_DEPTH = 3


def _iter_numeric_leaves(obj, path=()):
    if isinstance(obj, bool):
        return
    if isinstance(obj, (int, float)):
        yield ".".join(path), float(obj)
    elif isinstance(obj, dict) and len(path) < _MAX_LEAF_DEPTH:
        for k, v in obj.items():
            yield from _iter_numeric_leaves(v, (*path, str(k)))


def parse_receipt(path: str) -> dict:
    """One receipt file → {family, rev, metrics: [...], provenance?}."""
    stem = os.path.splitext(os.path.basename(path))[0]
    m = _REV_RE.match(stem)
    family, rev = (m.group("family"), int(m.group("rev"))) if m else (stem, 0)
    with open(path) as f:
        d = json.load(f)
    out = {"family": family, "rev": rev, "file": os.path.basename(path)}
    if isinstance(d.get("provenance"), dict):
        out["provenance"] = d["provenance"]

    metrics: list[dict] = []
    if isinstance(d.get("tail"), str):
        # Runner receipt: metric lines embedded in the captured output.
        seen_headline: set[str] = set()
        for raw in _TAIL_LINE_RE.findall(d["tail"]):
            try:
                line = json.loads(raw)
            except json.JSONDecodeError:
                continue
            name = line.get("metric")
            if not name or not isinstance(line.get("value"), (int, float)):
                continue
            metrics.append({
                "metric": name, "value": float(line["value"]),
                "unit": line.get("unit"),
                "vs_baseline": line.get("vs_baseline"),
                # First occurrence per receipt is the comparable headline;
                # the rest are config variants.
                "headline": name not in seen_headline,
            })
            seen_headline.add(name)
        if "rc" in d:
            metrics.append({
                "metric": "rc", "value": float(d["rc"]), "headline": True,
            })
    elif "metric" in d and isinstance(d.get("value"), (int, float)):
        metrics.append({
            "metric": d["metric"], "value": float(d["value"]),
            "unit": d.get("unit"), "vs_baseline": d.get("vs_baseline"),
            "headline": True,
        })
    else:
        for name, v in _iter_numeric_leaves(
            {k: val for k, val in d.items() if k != "provenance"}
        ):
            metrics.append({"metric": name, "value": v, "headline": True})
    out["metrics"] = metrics
    return out


def build_trend(root: str = REPO) -> dict:
    """All receipts → {families: {family: {series: {metric: [samples]}}}}.

    Within a family, samples are ordered by revision number (``_rNN``);
    each sample is the receipt's HEADLINE value for that metric.
    """
    receipts = []
    for pat in ("*_BENCH.json", "BENCH_*.json", "MULTICHIP_*.json"):
        receipts.extend(glob.glob(os.path.join(root, pat)))
    families: dict[str, dict] = {}
    for path in sorted(set(receipts)):
        try:
            r = parse_receipt(path)
        except (json.JSONDecodeError, OSError) as e:
            print(f"bench_trend: skipping {path}: {e}", file=sys.stderr)
            continue
        fam = families.setdefault(
            r["family"], {"receipts": [], "series": {}},
        )
        fam["receipts"].append(r["file"])
        for m in r["metrics"]:
            if not m.get("headline"):
                continue
            fam["series"].setdefault(m["metric"], []).append({
                "rev": r["rev"], "file": r["file"], "value": m["value"],
                **({"unit": m["unit"]} if m.get("unit") else {}),
                **(
                    {"vs_baseline": m["vs_baseline"]}
                    if m.get("vs_baseline") is not None else {}
                ),
                **(
                    {"provenance": r["provenance"]}
                    if "provenance" in r else {}
                ),
            })
    for fam in families.values():
        fam["receipts"].sort()
        for pts in fam["series"].values():
            pts.sort(key=lambda p: (p["rev"], p["file"]))
    return {
        "format": "llmss-bench-trend-v1",
        "n_families": len(families),
        "families": families,
    }


def check_regression(
    trend: dict, family: str, metric: str, threshold: float = 0.10,
) -> tuple[bool, str]:
    """(ok, message): the newest headline vs the previous one. A drop
    greater than ``threshold`` fails — every headline metric in the tree
    is higher-is-better (a rate or a count of passing checks)."""
    fam = trend["families"].get(family)
    if fam is None:
        return False, f"unknown family {family!r} (have: " + ", ".join(
            sorted(trend["families"])) + ")"
    pts = fam["series"].get(metric)
    if pts is None:
        return False, f"family {family!r} has no metric {metric!r}"
    if len(pts) < 2:
        return True, (
            f"{family}:{metric}: only {len(pts)} receipt(s) — a trend "
            "needs two points; passing vacuously"
        )
    prev, cur = pts[-2], pts[-1]
    if prev["value"] <= 0:
        return True, f"{family}:{metric}: previous value non-positive; skip"
    delta = (cur["value"] - prev["value"]) / prev["value"]
    msg = (
        f"{family}:{metric}: {prev['value']} ({prev['file']}) -> "
        f"{cur['value']} ({cur['file']}) = {delta:+.1%} "
        f"(threshold -{threshold:.0%})"
    )
    return delta >= -threshold, msg


def main(argv=None) -> int:
    ap = argparse.ArgumentParser(description=__doc__.split("\n")[0])
    ap.add_argument(
        "--check", metavar="FAMILY:METRIC",
        help="fail (exit 1) on >threshold regression of the named "
             "headline metric between the two newest receipts",
    )
    ap.add_argument(
        "--threshold", type=float, default=0.10,
        help="max tolerated fractional drop (default 0.10)",
    )
    ap.add_argument(
        "--out", default=os.path.join(REPO, "TREND.json"),
        help="trajectory file to write (default TREND.json at repo root)",
    )
    ap.add_argument(
        "--no-write", action="store_true",
        help="check only; don't rewrite the trend file",
    )
    args = ap.parse_args(argv)

    trend = build_trend()
    if not args.no_write:
        with open(args.out, "w") as f:
            json.dump(trend, f, indent=1, sort_keys=True)
            f.write("\n")
        print(
            f"wrote {args.out}: {trend['n_families']} families, "
            + ", ".join(
                f"{name} ({len(fam['series'])} series)"
                for name, fam in sorted(trend["families"].items())
            )
        )
    if args.check:
        if ":" not in args.check:
            print("--check wants FAMILY:METRIC", file=sys.stderr)
            return 2
        family, metric = args.check.split(":", 1)
        ok, msg = check_regression(trend, family, metric, args.threshold)
        print(("OK  " if ok else "FAIL ") + msg)
        return 0 if ok else 1
    return 0


if __name__ == "__main__":
    sys.exit(main())
