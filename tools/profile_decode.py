"""On-chip decode-step profiler: op-level breakdown + ablation timings.

Produces the receipts behind PROFILE.md: where each microsecond of the
decode step goes, measured two independent ways —

1. **xprof op table**: a ``jax.profiler`` trace of the steady-state fused
   decode scan, parsed into per-op self-time via the tensorboard-plugin-
   profile converter (no TensorBoard UI needed).
2. **Ablation timings**: variants of the decode step with one component
   removed (lm-head, sampling, cache scatter, attention) compiled and timed
   separately; the delta attributes wall time to the removed component.

Run on the bench host: ``python tools/profile_decode.py``.
Writes ``PROFILE.md`` (top-op table + ablations) and prints a JSON summary.
"""

from __future__ import annotations

import glob
import json
import os
import sys
import time
from functools import partial

import jax
import jax.numpy as jnp
import numpy as np

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

from bench import BATCH, DECODE, HBM_GBPS, PROMPT, flagship_cfg  # noqa: E402

TRACE_DIR = os.environ.get("PROFILE_TRACE_DIR", "/tmp/llmss_profile")


def _build():
    from llmss_tpu.engine import DecodeEngine, GenerationParams
    from llmss_tpu.models.decoder import init_params
    from llmss_tpu.parallel import MeshPlan, make_mesh

    n_dev = len(jax.devices())
    mesh = make_mesh(MeshPlan(tp=n_dev))
    cfg = flagship_cfg()
    params = init_params(cfg, mesh, jax.random.key(0))
    engine = DecodeEngine(cfg, params, mesh, max_seq_len=PROMPT + DECODE)
    return cfg, params, mesh, engine


def _prompts(cfg):
    rng = np.random.default_rng(0)
    return [
        rng.integers(0, cfg.vocab_size, PROMPT).tolist() for _ in range(BATCH)
    ]


def _timed(fn, *args, n=5, **kw):
    out = fn(*args, **kw)
    jax.block_until_ready(out)
    t0 = time.perf_counter()
    for _ in range(n):
        out = fn(*args, **kw)
    jax.block_until_ready(out)
    return (time.perf_counter() - t0) / n


# -- ablation variants --------------------------------------------------------


def _step_variant(cfg, mesh, variant: str):
    """A fused N-step decode scan with one component removed."""
    from llmss_tpu.models.decoder import forward
    from llmss_tpu.ops.sampling import sample

    def body(params, sample_args, carry, _):
        tokens, cache, cur_pos = carry
        positions = cur_pos[:, None]
        slots = positions % cache.max_len
        logits, cache = forward(
            cfg, params, tokens[:, None], positions, cache, slots,
            last_only=True, mesh=mesh,
            _ablate=variant if variant not in ("full", "no_sample") else None,
        )
        if variant in ("no_sample", "no_head"):
            # Trivial data-dependent token keeps the logits live (no DCE)
            # without paying argmax-over-V; no_head additionally skips the
            # vocab projection itself. head cost = t(no_sample) - t(no_head).
            tok = logits[:, 0, 0].astype(jnp.int32) % cfg.vocab_size
        else:
            tok = sample(logits[:, 0], counters=cur_pos + 1, **sample_args)
        return (tok, cache, cur_pos + 1), tok

    def many(params, tokens, cache, cur_pos, sample_args, n_steps):
        carry, toks = jax.lax.scan(
            partial(body, params, sample_args), (tokens, cache, cur_pos),
            None, length=n_steps,
        )
        return toks, carry[1]

    return jax.jit(many, donate_argnums=(2,), static_argnames=("n_steps",))


def run_ablations(cfg, mesh, engine, prompts):
    """Time decode-scan variants; each removal's delta vs full = its cost."""
    from llmss_tpu.engine import GenerationParams

    gen = GenerationParams(max_new_tokens=8, is_greedy=True)
    sa = engine._sample_args(gen, BATCH)
    ids, lens = engine._pad_prompts(prompts)

    N = 64
    results = {}
    for variant in ("full", "no_sample", "no_head", "no_scatter", "no_attn"):
        stepper = _step_variant(cfg, mesh, variant)
        cache = engine.new_cache(BATCH)
        tok, _, cache = engine._prefill(
            engine.params, jnp.asarray(ids), cache, jnp.asarray(lens), sa,
        )
        cur = jnp.asarray(lens)
        # warm
        toks, cache = stepper(engine.params, tok, cache, cur, sa, N)
        jax.block_until_ready(toks)
        t0 = time.perf_counter()
        toks, cache = stepper(engine.params, tok, cache, cur, sa, N)
        jax.block_until_ready(toks)
        dt = (time.perf_counter() - t0) / N
        results[variant] = dt * 1e3  # ms/step
        del cache
    return results


# -- xprof trace --------------------------------------------------------------


def capture_trace(engine, prompts):
    from llmss_tpu.engine import GenerationParams

    gen = GenerationParams(max_new_tokens=DECODE, is_greedy=True)
    engine.generate_fused(prompts, gen)  # warm/compile
    os.makedirs(TRACE_DIR, exist_ok=True)
    jax.profiler.start_trace(TRACE_DIR)
    engine.generate_fused(prompts, gen)
    jax.profiler.stop_trace()


def parse_trace() -> list[dict] | None:
    """Extract per-op self-time from the xplane via the xprof converter."""
    paths = sorted(
        glob.glob(os.path.join(TRACE_DIR, "**", "*.xplane.pb"),
                  recursive=True),
        key=os.path.getmtime,
    )
    if not paths:
        return None
    xspace = [paths[-1]]
    for tool in ("framework_op_stats", "tensorflow_stats", "op_profile"):
        try:
            from tensorboard_plugin_profile.convert import raw_to_tool_data
            data, _ = raw_to_tool_data.xspace_to_tool_data(
                xspace, tool, {}
            )
            return _digest_tool(tool, data)
        except Exception as e:  # noqa: BLE001 — try the next tool
            print(f"[profile] {tool} failed: {e!r}", file=sys.stderr)
    return None


def _digest_tool(tool: str, data) -> list[dict] | None:
    if isinstance(data, bytes):
        data = data.decode("utf-8", "replace")
    if tool in ("framework_op_stats", "tensorflow_stats"):
        # gviz JSON table; columns include op name + self time.
        try:
            tbl = json.loads(data)
        except json.JSONDecodeError:
            return None
        cols = [c.get("label", c.get("id", "")) for c in tbl.get("cols", [])]
        rows = []
        for r in tbl.get("rows", []):
            vals = [c.get("v") for c in r.get("c", [])]
            rows.append(dict(zip(cols, vals)))
        return rows
    return None


def main():
    cfg, params, mesh, engine = _build()
    prompts = _prompts(cfg)

    ablations = run_ablations(cfg, mesh, engine, prompts)
    capture_trace(engine, prompts)
    ops = parse_trace()

    full = ablations.get("full")
    print(json.dumps({
        "ablations_ms_per_step": {k: round(v, 3) for k, v in ablations.items()},
        "deltas_ms": {
            k: round(full - v, 3)
            for k, v in ablations.items() if k != "full" and full
        },
        "n_trace_ops": len(ops) if ops else 0,
    }))
    if ops:
        with open("/tmp/llmss_ops.json", "w") as f:
            json.dump(ops, f, indent=1)
        print("op table -> /tmp/llmss_ops.json")


if __name__ == "__main__":
    main()
