"""On-chip decode-step profiler: op-level breakdown + ablation timings.

Produces the receipts behind PROFILE.md: where each microsecond of the
decode step goes, measured two independent ways —

1. **xprof op table**: a ``jax.profiler`` trace of the steady-state fused
   decode scan, parsed into per-op self-time via the xprof converter
   (no TensorBoard UI needed).
2. **Ablation timings**: variants of the decode step with one component
   removed (lm-head, sampling, cache scatter, attention) compiled and timed
   separately; the delta attributes wall time to the removed component.

Timing methodology — the axon TPU tunnel adds ~90 ms of constant per-call
overhead (dispatch + host fetch round-trip), and ``block_until_ready`` can
return at dispatch-time on the first call after compile. Every timing here
therefore (a) forces completion with a host fetch of a scalar reduction and
(b) uses the **slope method**: run the fused scan at two step counts and
take (t(N2) - t(N1)) / (N2 - N1), which cancels all constant overhead and
yields the true marginal cost per decode step.

Run on the bench host: ``python tools/profile_decode.py``.
Writes ``PROFILE.md`` (top-op table + ablations) and prints a JSON summary.
"""

from __future__ import annotations

import glob
import json
import os
import sys
import time
from functools import partial

import jax
import jax.numpy as jnp
import numpy as np

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

from bench import (  # noqa: E402
    _MODEL_RUN, DECODE, HBM_GBPS, PROMPT, flagship_cfg, slope_time,
)

MODEL = os.environ.get("PROFILE_MODEL", "1b2")
BATCH = int(os.environ.get("BENCH_BATCH", 0)) or _MODEL_RUN[MODEL]["batch"]

TRACE_DIR = os.environ.get("PROFILE_TRACE_DIR", "/tmp/llmss_profile")


def host_overhead_breakdown(metrics) -> dict:
    """Per-group host-overhead receipts from an ``EngineMetrics``: how
    much host time each grouped-decode dispatch costs (enqueue + canon
    rewraps), what the ONE packed device→host fetch per group blocks for,
    and what the host-side bookkeeping (token accounting, stream flushes)
    adds — plus the sync/dispatch counters that say how often the host
    touches the device at all. Shared by bench_serve.py and
    tools/bench_spec.py so both bench JSONs carry the same breakdown."""
    ho = metrics.to_dict()["host_overhead"]
    return {
        "host_syncs": ho["host_syncs"],
        "groups_dispatched": ho["groups_dispatched"],
        "dispatch_ms": {k: ho["dispatch"][k]
                        for k in ("mean_ms", "p50_ms", "p95_ms")},
        "fetch_ms": {k: ho["fetch"][k]
                     for k in ("mean_ms", "p50_ms", "p95_ms")},
        "callback_ms": {k: ho["callback"][k]
                        for k in ("mean_ms", "p50_ms", "p95_ms")},
    }


def _build():
    from llmss_tpu.engine import DecodeEngine, GenerationParams
    from llmss_tpu.models.decoder import init_params
    from llmss_tpu.parallel import MeshPlan, make_mesh

    n_dev = len(jax.devices())
    mesh = make_mesh(MeshPlan(tp=n_dev))
    cfg = flagship_cfg(MODEL)
    params = init_params(cfg, mesh, jax.random.key(0))
    engine = DecodeEngine(cfg, params, mesh, max_seq_len=PROMPT + DECODE)
    return cfg, params, mesh, engine


def _prompts(cfg):
    rng = np.random.default_rng(0)
    return [
        rng.integers(0, cfg.vocab_size, PROMPT).tolist() for _ in range(BATCH)
    ]


# -- ablation variants --------------------------------------------------------


def _step_variant(cfg, mesh, variant: str):
    """A fused N-step decode scan with one component removed."""
    from llmss_tpu.models.decoder import forward
    from llmss_tpu.ops.sampling import sample

    def body(params, sample_args, carry, _):
        tokens, cache, cur_pos = carry
        positions = cur_pos[:, None]
        slots = positions % cache.max_len
        logits, cache = forward(
            cfg, params, tokens[:, None], positions, cache, slots,
            last_only=True, mesh=mesh,
            _ablate=variant if variant not in ("full", "no_sample") else None,
        )
        if variant in ("no_sample", "no_head"):
            # A full-logits reduction keeps every vocab column live (a
            # single-element read would let XLA fold the slice into the head
            # matmul, silently ablating it) without paying argmax-over-V;
            # no_head additionally skips the vocab projection itself.
            # head-only cost = t(no_sample) - t(no_head).
            tok = jnp.sum(logits[:, 0], axis=-1).astype(
                jnp.int32
            ) % cfg.vocab_size
        else:
            tok = sample(logits[:, 0], counters=cur_pos + 1, **sample_args)
        return (tok, cache, cur_pos + 1), tok

    def many(params, tokens, cache, cur_pos, sample_args, n_steps):
        carry, toks = jax.lax.scan(
            partial(body, params, sample_args), (tokens, cache, cur_pos),
            None, length=n_steps,
        )
        return toks, carry[1]

    return jax.jit(many, donate_argnums=(2,), static_argnames=("n_steps",))


def run_ablations(cfg, mesh, engine, prompts):
    """Time decode-scan variants; each removal's delta vs full = its cost."""
    from llmss_tpu.engine import GenerationParams

    gen = GenerationParams(max_new_tokens=8, is_greedy=True)
    sa = engine._sample_args(gen, BATCH)
    ids, lens = engine._pad_prompts(prompts)

    results = {}
    for variant in ("full", "no_sample", "no_head", "no_scatter", "no_attn"):
        stepper = _step_variant(cfg, mesh, variant)

        def prepare(n):
            cache = engine.new_cache(BATCH)
            tok, _, cache = engine._prefill(
                engine.params, jnp.asarray(ids), cache, jnp.asarray(lens),
                sa,
            )
            cur = jnp.asarray(lens)
            state = {"cache": cache}

            def run():
                toks, state["cache"] = stepper(
                    engine.params, tok, state["cache"], cur, sa, n
                )
                _ = float(jnp.sum(toks))  # forced completion

            return run

        slope_ms, const_ms = slope_time(prepare)
        results[variant] = {"ms_per_step": slope_ms, "const_ms": const_ms}
    return results


# -- xprof trace --------------------------------------------------------------


def capture_trace(engine, prompts):
    from llmss_tpu.engine import GenerationParams

    gen = GenerationParams(max_new_tokens=DECODE, is_greedy=True)
    engine.generate_fused(prompts, gen)  # warm/compile
    os.makedirs(TRACE_DIR, exist_ok=True)
    jax.profiler.start_trace(TRACE_DIR)
    engine.generate_fused(prompts, gen)
    jax.profiler.stop_trace()


def parse_trace() -> list[dict] | None:
    """Extract per-op self-time from the xplane via the xprof converter."""
    paths = sorted(
        glob.glob(os.path.join(TRACE_DIR, "**", "*.xplane.pb"),
                  recursive=True),
        key=os.path.getmtime,
    )
    if not paths:
        return None
    xspace = [paths[-1]]
    data = None
    for modname in ("xprof.convert", "tensorboard_plugin_profile.convert"):
        try:
            import importlib

            raw_to_tool_data = importlib.import_module(
                f"{modname}.raw_to_tool_data"
            )
            data, _ = raw_to_tool_data.xspace_to_tool_data(
                xspace, "framework_op_stats", {}
            )
            break
        except Exception as e:  # noqa: BLE001 — try the next converter
            print(f"[profile] {modname} failed: {e!r}", file=sys.stderr)
    if data is None:
        return None
    if isinstance(data, bytes):
        data = data.decode("utf-8", "replace")
    try:
        tbl = json.loads(data)
    except json.JSONDecodeError:
        return None
    if isinstance(tbl, list):
        tbl = tbl[0]
    cols = [c.get("label", c.get("id", "")) for c in tbl.get("cols", [])]
    rows = []
    for r in tbl.get("rows", []):
        vals = [c.get("v") for c in r.get("c", [])]
        rows.append(dict(zip(cols, vals)))
    return rows


def _fmt_op_table(ops: list[dict], n_top: int = 15) -> tuple[str, float]:
    dev = [r for r in ops if r.get("Host/device") == "Device"]
    dev.sort(key=lambda r: -float(r.get("Total self-time (us)", 0) or 0))
    total_ms = sum(
        float(r.get("Total self-time (us)", 0) or 0) for r in dev
    ) / 1e3
    lines = [
        "| self-time (ms) | occurrences | GB/s | bound by | op |",
        "|---|---|---|---|---|",
    ]
    for r in dev[:n_top]:
        t = float(r.get("Total self-time (us)", 0) or 0) / 1e3
        occ = int(float(r.get("#Occurrences", 0) or 0))
        bw = float(r.get("Measured Memory BW (GBytes/Sec)", 0) or 0)
        name = str(r.get("Operation Name", ""))
        # Strip the jit wrapper chain for readability.
        name = name.replace("jit(<unknown>)/", "").replace(
            "while/body/closed_call/", ""
        )
        lines.append(
            f"| {t:.2f} | {occ} | {bw:.0f} | "
            f"{r.get('Bound by', '')} | `{name[:90]}` |"
        )
    return "\n".join(lines), total_ms


def write_profile_md(cfg, param_bytes, ablations, ops, full_ms):
    deltas = {
        k: ablations["full"]["ms_per_step"] - v["ms_per_step"]
        for k, v in ablations.items() if k != "full"
    }
    head_only_ms = (
        ablations["no_sample"]["ms_per_step"]
        - ablations["no_head"]["ms_per_step"]
    )
    abl_lines = [
        "| variant | ms/step (marginal) | delta vs full (= component cost) |",
        "|---|---|---|",
        f"| full | {ablations['full']['ms_per_step']:.3f} | — |",
    ]
    for k, v in ablations.items():
        if k == "full":
            continue
        abl_lines.append(
            f"| {k} | {v['ms_per_step']:.3f} | {deltas[k]:+.3f} |"
        )

    # Stream floor from the actual run configuration (env-overridable).
    max_seq = PROMPT + DECODE
    kv_buffer_gb = 2 * cfg.n_layers * BATCH * max_seq * (
        cfg.n_kv_heads * cfg.head_dim * 2
    ) / 1e9
    param_gb = param_bytes / 1e9
    param_floor_ms = param_gb / HBM_GBPS * 1e3
    kv_floor_ms = kv_buffer_gb / HBM_GBPS * 1e3
    floor_ms = param_floor_ms + kv_floor_ms

    op_section = "(xprof trace parse unavailable on this host)"
    if ops:
        tbl, total_ms = _fmt_op_table(ops)
        op_section = (
            f"Total device self-time in trace: {total_ms:.1f} ms "
            f"(one `generate_fused` call: prefill + {DECODE}-step fused "
            f"decode + host fetches).\n\n{tbl}"
        )

    md = f"""# Decode-step profile (v5e single chip)

Flagship model: 1.2B llama-class bf16, batch={BATCH}, prompt={PROMPT},
cache={PROMPT + DECODE}. Generated by `tools/profile_decode.py` on real
hardware; see its docstring for the timing methodology (slope method —
marginal cost per step, constant dispatch/fetch overhead cancelled).

## Steady-state decode step: {full_ms:.2f} ms  (batch {BATCH} → \
{BATCH / full_ms * 1e3:.0f} tok/s/chip)

Stream floor at {HBM_GBPS:.0f} GB/s: params {param_gb:.2f} GB →
{param_floor_ms:.2f} ms; full KV buffer read {kv_buffer_gb:.2f} GB →
{kv_floor_ms:.2f} ms; total ≈ {floor_ms:.2f} ms/step. Measured
{full_ms:.2f} ms = {floor_ms / full_ms * 100:.0f}% of the floor.

## Ablations (slope method, each variant removes one component)

{chr(10).join(abl_lines)}

`no_attn` removes the cache-read einsums and softmax; `no_scatter` removes
the post-scan KV cache write; `no_head` removes the vocab projection *and*
sampling (its delta is head+sampling combined — head-only cost is
t(no_sample) − t(no_head) = {head_only_ms:.3f} ms); `no_sample` replaces
argmax/top-k/top-p with a full-logits-reduction token derivation.

## Top device ops (xprof, one traced `generate_fused` call)

{op_section}

## Reading

- The per-layer weight `dot_general`s stream at ~680 GB/s (83% of peak):
  the scan's weight slices are prefetched into alternate memory by XLA
  (the `S(1)` copies in the HLO) and are near the practical ceiling.
- The attention-over-cache cost (`no_attn` delta) is essentially the
  HBM stream cost of the KV bytes read: the per-layer cache
  `dynamic-slice` copies land in alternate memory (`S(1)` in the HLO —
  on-chip), so the only HBM traffic is the read itself. Round 4 measured
  alternative layouts exhaustively on-chip (head-major, K-transposed —
  both net slower, see git history); round 5 re-measured the mask-variant
  space (`tools/exp_mask.py`: additive penalty / inline iota / post-exp
  multiplicative / no mask at all are within noise of each other — the
  r4 "dynamic mask costs 0.6 ms" diagnosis no longer reproduces) and
  concluded the full-ring step simply runs at the chip's practical
  transfer efficiency (~690 GB/s ≈ 84% of nominal, the same rate the
  weight stream achieves).
- The remaining lever was therefore to read FEWER bytes: the engine's
  **bucketed cache reads** (round 5) slice each layer's KV fetch to the
  ring prefix covering live context via a hand-emitted
  `lax.dynamic_slice` — the serving path's decode cost follows occupancy,
  not ring size (`bench.py` measures that path; the ablations here run
  the full-ring step, the worst case). Emitting the small slice directly
  matters: XLA does not fold a static T-slice into the scan's per-layer
  slice (pre-scan slicing materializes a fresh operand, +1.3 ms/step;
  in-body slicing adds an HBM round-trip, +0.3 ms/step).
- The post-scan deferred KV scatter now fuses to ~0 marginal cost (the
  `no_scatter` delta); round 3 measured it at 0.08 ms.
- IDLE in the trace is host-side gaps of `generate_fused` (tunnel fetch
  latency ~90 ms/call on this host), not device inefficiency — the slope
  method cancels it, `bench.py` measures the same way.
"""
    with open(os.path.join(os.path.dirname(os.path.dirname(
            os.path.abspath(__file__))), "PROFILE.md"), "w") as f:
        f.write(md)


def main():
    cfg, params, mesh, engine = _build()
    prompts = _prompts(cfg)
    param_bytes = sum(
        np.prod(x.shape) * x.dtype.itemsize
        for x in jax.tree.leaves(params)
    )

    ablations = run_ablations(cfg, mesh, engine, prompts)
    capture_trace(engine, prompts)
    ops = parse_trace()

    full = ablations["full"]["ms_per_step"]
    write_profile_md(cfg, param_bytes, ablations, ops, full)
    print(json.dumps({
        "ablations_ms_per_step": {
            k: round(v["ms_per_step"], 3) for k, v in ablations.items()
        },
        "deltas_ms": {
            k: round(full - v["ms_per_step"], 3)
            for k, v in ablations.items() if k != "full"
        },
        "tok_per_sec_at_full": round(BATCH / full * 1e3, 1),
        "n_trace_ops": len(ops) if ops else 0,
        # Accumulated over the ablation runs above — what the host paid
        # per grouped dispatch while the device did the work.
        "host_overhead": host_overhead_breakdown(engine.metrics),
    }))
    if ops:
        with open("/tmp/llmss_ops.json", "w") as f:
            json.dump(ops, f, indent=1)
        print("op table -> /tmp/llmss_ops.json")


if __name__ == "__main__":
    main()
