"""Prefix-reuse TTFT benchmark: long shared prefix, short per-request
suffix — the multi-turn / shared-system-prompt serving shape.

Measures time-to-first-token for a batch whose prompts share a long
prefix, (a) prefilled from scratch and (b) reusing a retained Prefix
segment (``DecodeEngine.build_prefix``), and checks the emitted tokens
are identical. Writes ``PREFIX_BENCH.json`` and prints one JSON line.

Run on the bench host: ``python tools/bench_prefix.py``.
"""

from __future__ import annotations

import json
import os
import sys
import time

import jax
import jax.numpy as jnp
import numpy as np

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

from bench import flagship_cfg  # noqa: E402

BATCH = int(os.environ.get("PREFIX_BATCH", 8))
PREFIX_LEN = int(os.environ.get("PREFIX_LEN", 1024))
SUFFIX_LEN = int(os.environ.get("PREFIX_SUFFIX", 24))
DECODE = int(os.environ.get("PREFIX_DECODE", 32))
REPS = 3


def timed_ttft(engine, prompts, gen, prefix=None) -> float:
    """Best-of-REPS prefill->first-token latency via engine.generate's
    own TTFT metric (prefill dispatch + first sampled token on host)."""
    best = float("inf")
    for _ in range(REPS):
        engine.generate(prompts, gen, prefix=prefix)
        best = min(best, engine.metrics.ttft.last_s * 1e3)
    return best


def main():
    from llmss_tpu.engine import DecodeEngine, GenerationParams
    from llmss_tpu.models.decoder import init_params
    from llmss_tpu.parallel import MeshPlan, make_mesh

    n_dev = len(jax.devices())
    mesh = make_mesh(MeshPlan(tp=n_dev))
    cfg = flagship_cfg("1b2")
    params = init_params(cfg, mesh, jax.random.key(0))
    max_seq = 2048
    engine = DecodeEngine(cfg, params, mesh, max_seq_len=max_seq)
    gen = GenerationParams(max_new_tokens=DECODE, is_greedy=True)

    rng = np.random.default_rng(0)
    shared = rng.integers(0, cfg.vocab_size, PREFIX_LEN).tolist()
    prompts = [
        shared + rng.integers(0, cfg.vocab_size, SUFFIX_LEN).tolist()
        for _ in range(BATCH)
    ]

    # Token parity first (also warms both compile paths).
    scratch_out = engine.generate(prompts, gen)
    t0 = time.time()
    pfx = engine.build_prefix(shared)
    build_s = time.time() - t0
    reused_out = engine.generate(prompts, gen, prefix=pfx)
    assert reused_out == scratch_out, "prefix reuse changed tokens!"

    ttft_scratch = timed_ttft(engine, prompts, gen)
    ttft_reused = timed_ttft(engine, prompts, gen, prefix=pfx)

    result = {
        "metric": "prefix_reuse_ttft_ms",
        "value": round(ttft_reused, 1),
        "unit": (
            f"ms TTFT (1b2 bf16, batch={BATCH}, shared prefix "
            f"{PREFIX_LEN} tok + suffix {SUFFIX_LEN} tok; from-scratch "
            f"ttft={ttft_scratch:.0f}ms -> reused={ttft_reused:.0f}ms, "
            f"{ttft_scratch / max(ttft_reused, 1e-9):.1f}x faster; "
            f"one-time build_prefix={build_s:.2f}s; tokens identical)"
        ),
        "vs_baseline": round(ttft_scratch / max(ttft_reused, 1e-9), 2),
    }
    print(json.dumps(result))
    from bench import bench_provenance

    with open(os.path.join(os.path.dirname(os.path.dirname(
            os.path.abspath(__file__))), "PREFIX_BENCH.json"), "w") as f:
        json.dump({**result, "provenance": bench_provenance()}, f, indent=1)


if __name__ == "__main__":
    main()
