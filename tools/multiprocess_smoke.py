"""Multi-process runtime smoke: one engine step over a cross-process mesh.

The reference's default launch is real multi-process rendezvous
(``torchrun --nproc_per_node 4``, ``poc-server/producer-consumer/
README.md:24-37``; ``utils/dist.py:65-77`` ``init_process_group``). The
TPU-native equivalent is multi-controller JAX: every host runs this same
program, ``jax.distributed.initialize`` rendezvouses them at the
coordinator, and the device mesh spans all processes — collectives are
compiled by XLA across ICI/DCN, with no communication library to manage.

This script IS that launch recipe, sized for CI: each process contributes
``--local-devices`` virtual CPU devices, the mesh is TP over the global
device count (the reference's world-group-as-TP-group, ``dist.py:77``),
and one prefill + one decode step run SPMD across the processes. On a real
multi-host TPU pod the same code runs with no arguments (JAX reads the
cloud TPU metadata) and the mesh spans the pod's chips.

Run two processes locally:

    python tools/multiprocess_smoke.py --process-id 0 --num-processes 2 \
        --coordinator localhost:9911 &
    python tools/multiprocess_smoke.py --process-id 1 --num-processes 2 \
        --coordinator localhost:9911

Each prints ``mpsmoke ok pid=N processes=2 devices=4 toks=[...]``; the
token lists must be identical (tests/test_multiprocess.py asserts this).
"""

from __future__ import annotations

import argparse
import os
import sys

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))


def main() -> None:
    ap = argparse.ArgumentParser()
    ap.add_argument("--process-id", type=int, required=True)
    ap.add_argument("--num-processes", type=int, required=True)
    ap.add_argument("--coordinator", required=True)
    ap.add_argument("--local-devices", type=int, default=2)
    args = ap.parse_args()

    # Environment must be set before the JAX backend initializes. The env
    # var alone can be read too early when a sitecustomize imports jax at
    # interpreter startup (as on the bench host, which pins a TPU
    # platform) — override via config as well, which wins as long as the
    # backend itself has not initialized yet (same trick as
    # tests/conftest.py).
    os.environ["JAX_PLATFORMS"] = "cpu"
    flags = os.environ.get("XLA_FLAGS", "")
    os.environ["XLA_FLAGS"] = (
        f"{flags} --xla_force_host_platform_device_count="
        f"{args.local_devices}"
    ).strip()
    import jax

    jax.config.update("jax_platforms", "cpu")

    from llmss_tpu.parallel.mesh import initialize_runtime

    # The branch under test: real jax.distributed.initialize rendezvous
    # (≙ dist.py:65-73). Must run before any device query.
    initialize_runtime(
        coordinator_address=args.coordinator,
        num_processes=args.num_processes,
        process_id=args.process_id,
    )

    import jax.numpy as jnp
    import numpy as np

    assert jax.process_count() == args.num_processes, jax.process_count()
    n_global = len(jax.devices())
    n_local = len(jax.local_devices())
    assert n_local == args.local_devices, n_local
    assert n_global == args.local_devices * args.num_processes, n_global

    from llmss_tpu.engine import DecodeEngine, GenerationParams
    from llmss_tpu.models.common import DecoderConfig
    from llmss_tpu.models.decoder import init_params
    from llmss_tpu.parallel import MeshPlan, make_mesh

    cfg = DecoderConfig(
        model_type="llama", vocab_size=64, hidden_size=32, n_layers=2,
        n_heads=4, n_kv_heads=4, head_dim=8, intermediate_size=64,
        max_position_embeddings=64, activation="silu", norm="rmsnorm",
        norm_eps=1e-5, mlp="swiglu", positions="rotary", rope_style="half",
        rotary_dim=8, attn_bias=False, mlp_bias=False,
        tie_word_embeddings=False, dtype="float32",
    )
    # TP over the whole cross-process world — the mesh's tp axis spans both
    # processes, so every RowLinear psum and the lm-head all-gather compiled
    # from the sharding constraints is a REAL cross-process collective.
    mesh = make_mesh(MeshPlan(tp=n_global))
    params = init_params(cfg, mesh, jax.random.key(0))
    engine = DecodeEngine(cfg, params, mesh, max_seq_len=32)

    ids = jnp.asarray(np.asarray([[1, 2, 3, 4, 5, 6, 7, 8] + [0] * 8]))
    lens = jnp.asarray(np.asarray([8], np.int32))
    sa = engine._sample_args(GenerationParams(is_greedy=True), 1)
    cache = engine.new_cache(1)
    tok, _, cache = engine._prefill(engine.params, ids, cache, lens, sa)
    toks = [int(np.asarray(engine.canon_vec(tok))[0])]
    cur = jnp.asarray(np.asarray([8], np.int32))
    for _ in range(3):
        tok, _, cache = engine._decode(engine.params, tok, cache, cur, sa)
        toks.append(int(np.asarray(engine.canon_vec(tok))[0]))
        cur = cur + 1

    print(
        f"mpsmoke ok pid={args.process_id} "
        f"processes={jax.process_count()} devices={n_global} toks={toks}",
        flush=True,
    )


if __name__ == "__main__":
    sys.exit(main())
