"""Priority bench: FIFO vs tiered+preemption vs tiered+brownout.

Replays two heavy-tailed traces (the ROADMAP mixed-tenant scenario: a
steady drip of long batch jobs, moderate standard traffic, interactive
arrivals in tight bursts) through three scheduling arms of the
deterministic fleet simulator (``llmss_tpu.sim``) — one continuous-
batching replica over the REAL broker, scheduler preemption policy, and
``BrownoutController`` — and reports per-class TTFT p95/p99, SLO
attainment, and a chips-equivalent figure. The **burst** trace is
recoverable overload — the FIFO-vs-tiered p95 headline, where the
brownout ladder sheds background work so bursts land on free rows
instead of paying the one-eviction-per-cycle train. The **overload**
trace is sustained demand beyond capacity, where priorities alone
cannot save interactive and the degradation-ordering claims (batch
before standard before interactive, interactive never shed) are
asserted on real shed counts.

The arms:

- ``fifo``     — one class-blind queue, no preemption, admit-all: every
  request submits as one SLO class (the broker's class queues collapse
  to FIFO) and a side-table classifier keeps per-class accounting
  honest. The static-fleet baseline: interactive bursts queue behind
  batch rows.
- ``tiered``   — class-priority queues + paged-KV preemption: an
  interactive arrival blocked on row capacity evicts the lowest-class
  running row (the scheduler's REAL ``select_preemption_victim``:
  victim strictly outranked, fewest emitted tokens; refund to the head
  of its class queue; resume replays the emitted prefix).
- ``brownout`` — tiered plus the real ``BrownoutController`` driven by
  the interactive SLO burn rate over the sim's sliding TTFT window,
  walking the cap-batch -> shed-batch -> shed-standard ladder.

The simulator advances in decode-step cycles (every resident row emits
one token per fused step); prompt prefill is metered through the ragged
chunk path before the first token, and a resumed row re-charges prefill
over prompt+emitted — the same cost shape the scheduler's
chunked-replay resume pays. Virtual time makes the bench exactly
reproducible: no sleeps, no wall-clock — and the sim's invariant
catalog (exactly-one-terminal, preemption refunds never consume
delivery attempts, KV balance) is asserted at drain of every arm.

``chips_equivalent`` is the static-fleet cost of buying the same
interactive TTFT p95 without priorities: the smallest N data-parallel
replicas at which the arm meets the interactive target. FIFO needs
several chips; the tiered arms hit the target on one — that delta is
the PR's capacity claim.

Also times the scheduler's real ``_maybe_preempt`` no-op paths (idle,
and pending-but-not-blocked) on a live ContinuousBatcher — the per-step
host tax every deployment with ``preempt_cb`` set pays — against the
25 µs budget. Writes PRIORITY_BENCH.json with ``bench_provenance``;
exits nonzero if any acceptance assertion fails.
"""

from __future__ import annotations

import json
import math
import os
import random
import sys
import time

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

from bench import bench_provenance  # noqa: E402
from llmss_tpu.serve.protocol import (  # noqa: E402
    SLO_CLASS_BATCH,
    SLO_CLASS_INTERACTIVE,
    SLO_CLASS_STANDARD,
)
from llmss_tpu.sim import FleetSim  # noqa: E402

SEED = 1405
ROWS = 12
STEP_S = 0.02  # one fused decode step: every resident row advances one token
#: Tokens per fused chunk — the scheduling quantum: admission, eviction
#: (one per cycle, the ContinuousBatcher bound), and row-freeing happen
#: once per CHUNK_TOKENS steps, so the quantum sets the eviction-train
#: latency an interactive burst pays when rows are pinned by batch.
CHUNK_TOKENS = 2
PREFILL_TOKEN_S = 0.0004
PREFILL_CHUNK = 64  # ragged metering: prompt tokens per row per step
TRACE_S = 120.0

#: per-class TTFT targets (ms) at p95 — mirrors DEFAULT_SLO_OBJECTIVES.
TTFT_TARGET_MS = {
    SLO_CLASS_INTERACTIVE: 500.0,
    SLO_CLASS_STANDARD: 2000.0,
    SLO_CLASS_BATCH: 15000.0,
}
SLO_TARGET = 0.95
US_PER_CALL_BUDGET = 25.0
MAX_CHIPS = 12

CLASSES = (SLO_CLASS_INTERACTIVE, SLO_CLASS_STANDARD, SLO_CLASS_BATCH)


def build_trace(overload: bool = False) -> list[dict]:
    """A heavy-tailed bursty arrival trace, identical across arms.

    Batch max_new is Pareto(a=1.1) — a long tail of multi-hundred-token
    jobs that pin rows for seconds. Interactive arrives as tight bursts
    on top of a steady drip; during a burst the offered row demand far
    exceeds ROWS, which is the moment the arms diverge.

    The default shape is bursty-but-recoverable: overload comes in
    spikes the fleet can absorb between bursts (the FIFO-vs-tiered p95
    headline). ``overload=True`` triples the background classes and
    doubles the burst cadence — sustained demand beyond capacity where
    priorities alone cannot save interactive and the brownout ladder
    must shed (the degradation-ordering scenario).
    """
    rng = random.Random(SEED)
    reqs = []
    batch_rate = 7.5 if overload else 2.5
    std_rate = 12.0 if overload else 4.0
    burst_n, burst_gap = (24, 4) if overload else (16, 8)

    t = 0.0
    while t < TRACE_S:  # batch drip: long, heavy-tailed
        t += rng.expovariate(batch_rate)
        reqs.append({
            "cls": SLO_CLASS_BATCH, "arrival": t, "plen": 256,
            "max_new": min(512, int(24 * rng.paretovariate(1.1))),
        })
    t = 0.0
    while t < TRACE_S:  # standard background
        t += rng.expovariate(std_rate)
        reqs.append({
            "cls": SLO_CLASS_STANDARD, "arrival": t, "plen": 64,
            "max_new": 8 + int(rng.expovariate(1 / 24)),
        })
    t = 0.0
    while t < TRACE_S:  # interactive: drip + tight bursts
        t += rng.expovariate(1.2)
        reqs.append({
            "cls": SLO_CLASS_INTERACTIVE, "arrival": t, "plen": 24,
            "max_new": 4 + int(rng.expovariate(1 / 6)),
        })
    for burst0 in range(4, int(TRACE_S), burst_gap):
        for _ in range(burst_n):
            reqs.append({
                "cls": SLO_CLASS_INTERACTIVE,
                "arrival": burst0 + rng.random() * 0.4,
                "plen": 24, "max_new": 4 + int(rng.expovariate(1 / 6)),
            })
    reqs.sort(key=lambda r: r["arrival"])
    for i, r in enumerate(reqs):
        r["id"] = f"pr{i:05d}"
    return reqs


def make_spec(arm: str, trace: list[dict], chips: int) -> dict:
    rows = [
        {
            "id": r["id"],
            "arrival_s": r["arrival"],
            "prompt_len": r["plen"],
            "max_new": r["max_new"],
            # The FIFO arm is class-blind: everything rides one queue.
            "slo_class": (
                SLO_CLASS_STANDARD if arm == "fifo" else r["cls"]
            ),
        }
        for r in trace
    ]
    spec = {
        "format": "llmss-scenario/1",
        "name": f"bench-priority-{arm}-{chips}",
        "seed": SEED,
        # Long-prompt admission cycles can run past a short visibility
        # timeout; the bench measures scheduling, not lease churn.
        "broker": {"kind": "inproc", "lease_s": 30.0},
        "cost_model": {
            "kind": "table",
            "prefill_token_s": PREFILL_TOKEN_S,
            "decode_step_s": STEP_S,
        },
        "fleet": {
            "replicas": [{
                "count": chips, "role": "unified", "rows": ROWS,
                "chunk_tokens": CHUNK_TOKENS, "prefill_chunk": PREFILL_CHUNK,
                "admit_burst": ROWS, "preempt": arm != "fifo",
            }],
            "router_policy": "shared",
        },
        "workload": {"kind": "trace", "rows": rows},
        "metrics": {"per_class": True},
    }
    if arm == "brownout":
        spec["fleet"]["brownout"] = {
            "ttft_target_s": TTFT_TARGET_MS[SLO_CLASS_INTERACTIVE] / 1e3,
            "burn": "attainment", "slo_target": SLO_TARGET,
            # ``low=0`` latches rungs for the trace duration (the
            # controller de-escalates on ``burn < low``, strict): every
            # de-escalation re-admits the batch backlog into rows, and
            # the next burst pays a one-eviction-per-cycle train to
            # clear it — on a 2-minute trace with bursts every 8 s the
            # flap costs more interactive attainment than any batch
            # throughput it buys back.
            "high": 1.0, "low": 0.0, "dwell_s": 6.0, "check_s": 0.5,
        }
    return spec


def _pct(vals, q) -> float | None:
    if not vals:
        return None
    s = sorted(vals)
    i = min(len(s) - 1, math.ceil(q * len(s)) - 1)
    return round(s[i] * 1e3, 1)


def simulate(arm: str, trace: list[dict], chips: int = 1) -> dict:
    """Run one arm over the trace on a ``chips``-replica data-parallel
    fleet; returns per-class latency/attainment stats."""
    sim = FleetSim(make_spec(arm, trace, chips))
    true_cls = {r["id"]: r["cls"] for r in trace}
    # Per-class accounting keeps the TRUE class even in the class-blind
    # FIFO arm (everything submits as one class there).
    sim.classify = lambda req: true_cls[req.id]
    sim.run()

    out = {
        "classes": {},
        "preemptions": sim.counters["preemptions"],
        "chip_busy_s": round(sum(r.busy_s for r in sim.replicas), 1),
    }
    for c in CLASSES:
        tgt = TTFT_TARGET_MS[c]
        vals = sim._cls_ttft[c]  # per-class TTFT samples (true class)
        offered = sim._cls_offered[c]
        within = sum(1 for v in vals if v * 1e3 <= tgt)
        out["classes"][c] = {
            "offered": offered,
            "completed": sim._cls_done[c],
            "shed": sim._cls_shed[c],
            "ttft_p50_ms": _pct(vals, 0.50),
            "ttft_p95_ms": _pct(vals, 0.95),
            "ttft_p99_ms": _pct(vals, 0.99),
            "ttft_target_ms": tgt,
            # attainment over OFFERED traffic: a shed request is a
            # degraded request — brownout can't launder its sheds out of
            # the denominator.
            "slo_attainment": round(within / offered, 4)
            if offered else None,
        }
    if sim.ctrl is not None:
        out["brownout"] = sim.ctrl.state()
    return out


def chips_equivalent(arm: str, trace: list[dict]) -> int | None:
    """Smallest static N-chip fleet at which ``arm`` meets the
    interactive TTFT p95 target; None if > MAX_CHIPS."""
    tgt = TTFT_TARGET_MS[SLO_CLASS_INTERACTIVE]
    for n in range(1, MAX_CHIPS + 1):
        r = simulate(arm, trace, chips=n)
        p95 = r["classes"][SLO_CLASS_INTERACTIVE]["ttft_p95_ms"]
        if p95 is not None and p95 <= tgt:
            return n
    return None


def preempt_hook_microbench() -> dict:
    """Host cost of the scheduler's real ``_maybe_preempt`` no-op paths
    on a live ContinuousBatcher: idle (no pending), and the steady-state
    pending-but-unblocked check. These run once per step in every
    deployment that sets ``preempt_cb``."""
    import jax

    from llmss_tpu.engine import DecodeEngine
    from llmss_tpu.engine.scheduler import ContinuousBatcher
    from llmss_tpu.models.common import DecoderConfig
    from llmss_tpu.models.decoder import init_params
    from llmss_tpu.parallel import MeshPlan, make_mesh

    cfg = DecoderConfig(
        model_type="llama", vocab_size=64, hidden_size=32, n_layers=2,
        n_heads=4, n_kv_heads=2, head_dim=8, intermediate_size=64,
        max_position_embeddings=64, activation="silu", norm="rmsnorm",
        norm_eps=1e-5, mlp="swiglu", positions="rotary", rope_style="half",
        rotary_dim=8, attn_bias=False, mlp_bias=False,
        tie_word_embeddings=False, dtype="float32",
    )
    mesh = make_mesh(MeshPlan(dp=1, tp=len(jax.devices())))
    params = init_params(cfg, mesh, jax.random.key(0))
    engine = DecodeEngine(cfg, params, mesh, max_seq_len=64)
    bat = ContinuousBatcher(engine, rows=4)
    bat.preempt_cb = lambda rid, toks: None

    n = 20000
    best_idle = float("inf")
    for _ in range(5):
        t0 = time.perf_counter()
        for _ in range(n):
            bat._maybe_preempt()
        best_idle = min(best_idle, (time.perf_counter() - t0) / n)

    # steady-state: a pending head exists but free rows remain, so the
    # hook reads the head's priority and returns without scanning rows
    # (only index 7 — priority — is touched on this path).
    fake = (None, None, None, None, None, None, None, 1, 0)
    with bat._lock:
        bat.pending.append(fake)
    best_pending = float("inf")
    for _ in range(5):
        t0 = time.perf_counter()
        for _ in range(n):
            bat._maybe_preempt()
        best_pending = min(best_pending, (time.perf_counter() - t0) / n)
    with bat._lock:
        bat.pending.clear()
    return {
        "idle_us": round(best_idle * 1e6, 3),
        "pending_unblocked_us": round(best_pending * 1e6, 3),
        "budget_us": US_PER_CALL_BUDGET,
    }


def main() -> int:
    # Scenario 1 — bursty-but-recoverable: the p95 headline. Tiered
    # scheduling absorbs what FIFO cannot; brownout additionally keeps
    # rows free BEFORE each burst lands (shed batch/standard instead of
    # paying the eviction train), which is what buys the p95 target on
    # one chip.
    burst_trace = build_trace()
    burst = {}
    for arm in ("fifo", "tiered", "brownout"):
        burst[arm] = simulate(arm, burst_trace)
        burst[arm]["chips_equivalent"] = chips_equivalent(arm, burst_trace)
    # Scenario 2 — sustained overload: demand exceeds capacity for the
    # whole trace, priorities alone cannot protect interactive, and the
    # ladder must walk. Degradation ordering is asserted HERE, on real
    # shed counts, never on a trace where nothing degrades.
    over_trace = build_trace(overload=True)
    over = {arm: simulate(arm, over_trace)
            for arm in ("fifo", "tiered", "brownout")}
    micro = preempt_hook_microbench()

    fifo_i = burst["fifo"]["classes"][SLO_CLASS_INTERACTIVE]
    bo_i = burst["brownout"]["classes"][SLO_CLASS_INTERACTIVE]
    obo = over["brownout"]["classes"]

    def degradation(c):
        # 1 - attainment over OFFERED traffic (sheds count against the
        # class): the "how much did this class hurt" score the ladder
        # ordering is judged by.
        return 1.0 - (obo[c]["slo_attainment"] or 0.0)

    def att(arms, arm):
        a = arms[arm]["classes"][SLO_CLASS_INTERACTIVE]["slo_attainment"]
        return a or 0.0

    checks = {
        # the headline: brownout meets the interactive target that FIFO
        # blows through on the same single chip
        "brownout_interactive_p95_meets_target":
            bo_i["ttft_p95_ms"] <= TTFT_TARGET_MS[SLO_CLASS_INTERACTIVE],
        "fifo_interactive_p95_violates":
            fifo_i["ttft_p95_ms"] > TTFT_TARGET_MS[SLO_CLASS_INTERACTIVE],
        "preemption_engaged": burst["tiered"]["preemptions"] > 0,
        # overload: the ladder actually walked — batch was shed and the
        # controller recorded transitions (not a vacuous pass)
        "brownout_engaged":
            obo[SLO_CLASS_BATCH]["shed"] > 0
            and over["brownout"]["brownout"]["transitions_total"] > 0,
        # degradation is ordered: batch before standard before
        # interactive, and interactive is never shed in ANY scenario
        "degradation_order_batch_standard_interactive":
            degradation(SLO_CLASS_BATCH)
            >= degradation(SLO_CLASS_STANDARD)
            >= degradation(SLO_CLASS_INTERACTIVE),
        "standard_sheds_only_after_batch":
            obo[SLO_CLASS_STANDARD]["shed"] == 0
            or obo[SLO_CLASS_BATCH]["shed"] > 0,
        "interactive_never_shed": all(
            arms[a]["classes"][SLO_CLASS_INTERACTIVE]["shed"] == 0
            for arms in (burst, over) for a in arms
        ),
        # under overload, shedding buys interactive more attainment than
        # either priorities alone or FIFO
        "brownout_protects_interactive_under_overload":
            att(over, "brownout") >= att(over, "tiered")
            and att(over, "brownout") > att(over, "fifo"),
        "preempt_hook_within_budget":
            max(micro["idle_us"], micro["pending_unblocked_us"])
            <= US_PER_CALL_BUDGET,
    }

    out = {
        "bench": "priority_scheduling",
        "provenance": bench_provenance(),
        "config": {
            "seed": SEED, "rows": ROWS, "step_s": STEP_S,
            "chunk_tokens": CHUNK_TOKENS,
            "prefill_chunk": PREFILL_CHUNK,
            "prefill_token_s": PREFILL_TOKEN_S, "trace_s": TRACE_S,
            "n_requests_burst": len(burst_trace),
            "n_requests_overload": len(over_trace),
            "ttft_targets_ms": TTFT_TARGET_MS, "slo_target": SLO_TARGET,
        },
        "scenarios": {"burst": burst, "overload": over},
        "preempt_hook": micro,
        "checks": checks,
        "checks_passed": sum(1 for v in checks.values() if v),
        "ok": all(checks.values()),
    }
    path = os.path.join(
        os.path.dirname(os.path.dirname(os.path.abspath(__file__))),
        "PRIORITY_BENCH.json",
    )
    with open(path, "w") as f:
        json.dump(out, f, indent=1)
        f.write("\n")
    print(json.dumps({
        "metric": "interactive_ttft_p95_ms",
        "value": bo_i["ttft_p95_ms"],
        "unit": (
            f"ms on 1 chip under brownout (fifo={fifo_i['ttft_p95_ms']} ms; "
            f"chips-equivalent fifo={burst['fifo']['chips_equivalent']} vs "
            f"brownout={burst['brownout']['chips_equivalent']}; "
            f"{burst['tiered']['preemptions']} preemptions in burst arm; "
            f"overload sheds batch={obo[SLO_CLASS_BATCH]['shed']} "
            f"standard={obo[SLO_CLASS_STANDARD]['shed']} interactive=0; "
            f"preempt hook {micro['pending_unblocked_us']} us)"
        ),
        "ok": out["ok"],
        "failed_checks": [k for k, v in checks.items() if not v],
    }))
    return 0 if out["ok"] else 1


if __name__ == "__main__":
    sys.exit(main())
