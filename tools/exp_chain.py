"""Experiment: where does the chained-chunk decode overhead come from?

Times each dispatch of a chained _decode_many sequence (no fetch until the
end) under three variants:
- canon   : canon_cache/canon_vec between chunks (serving path)
- nocanon : raw jit outputs fed straight back in
- single  : one big fused scan (old bench methodology)
and with/without bucketed reads.
"""
import os
import sys
import time

import jax
import jax.numpy as jnp
import numpy as np

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))
from bench import PROMPT, flagship_cfg  # noqa: E402

from llmss_tpu.engine import DecodeEngine, GenerationParams  # noqa: E402
from llmss_tpu.models.decoder import init_params  # noqa: E402
from llmss_tpu.parallel import MeshPlan, make_mesh  # noqa: E402

BATCH = 16
MAX_SEQ = 448
CHUNK = 32
N_CHUNKS = 10

mesh = make_mesh(MeshPlan(tp=len(jax.devices())))
cfg = flagship_cfg("1b2")
params = init_params(cfg, mesh, jax.random.key(0))
engine = DecodeEngine(cfg, params, mesh, max_seq_len=MAX_SEQ)
gen = GenerationParams(max_new_tokens=8, is_greedy=True)
rng = np.random.default_rng(0)
prompts = [rng.integers(0, cfg.vocab_size, PROMPT).tolist() for _ in range(BATCH)]
ids, lens = engine._pad_prompts(prompts)
sa = engine._sample_args(gen, BATCH)
eos = engine.canon_vec(jnp.full(BATCH, -1, jnp.int32))
done = jnp.zeros(BATCH, bool)


def run(variant, use_bucket, timing=False):
    cache = engine.new_cache(BATCH)
    tok, _, cache = engine._prefill(
        engine.params, jnp.asarray(ids), cache, jnp.asarray(lens), sa,
    )
    cur = jnp.asarray(lens)
    if variant == "canon":
        tok, cache, cur = (
            engine.canon_vec(tok), engine.canon_cache(cache),
            engine.canon_vec(cur),
        )
    t0 = time.perf_counter()
    stamps = []
    if variant == "single":
        toks, cache, cur, _, _ = engine._decode_many(
            engine.params, tok, cache, cur, sa, done, eos,
            n_steps=CHUNK * N_CHUNKS,
            t_bucket=None,
        )
        total = jnp.sum(toks)
    else:
        pos = int(lens.max())
        total = jnp.zeros((), jnp.int32)
        for _ in range(N_CHUNKS):
            tb = engine.decode_bucket(pos + CHUNK) if use_bucket else None
            toks, cache, cur, _, _ = engine._decode_many(
                engine.params, tok, cache, cur, sa, done, eos,
                n_steps=CHUNK, t_bucket=tb,
            )
            if variant == "canon":
                cache = engine.canon_cache(cache)
                cur = engine.canon_vec(cur)
                tok = engine.canon_vec(toks[:, -1])
            else:
                tok = toks[:, -1]
            total = total + jnp.sum(toks)
            pos += CHUNK
            stamps.append(time.perf_counter() - t0)
    _ = int(total)
    wall = time.perf_counter() - t0
    if timing:
        per_step = wall / (CHUNK * N_CHUNKS) * 1e3
        print(f"{variant:8s} bucket={use_bucket!s:5s} wall={wall*1e3:7.1f}ms "
              f"per_step={per_step:.3f}ms dispatch_stamps_ms="
              + ",".join(f"{s*1e3:.0f}" for s in stamps), flush=True)


for variant, ub in [
    ("single", False),
    ("nocanon", False), ("nocanon", True),
    ("canon", False), ("canon", True),
]:
    run(variant, ub)          # compile + warm
    run(variant, ub, True)
    run(variant, ub, True)
