"""On-chip speculative-decoding bench: wall-clock tokens/s with and
without prompt-lookup speculation, exact-token check included.

Two workloads at the flagship 1b2 scale:
- natural: greedy decode from random prompts (random-init models settle
  into repetitive cycles, like real text settles into patterns — lookup
  hits organically);
- adversarial: acceptance forced to ~0 by drafting against fresh
  randomness is not constructible host-side, so the floor is measured by
  gamma=1 (smallest verify overhead) on the same prompts.

Prints one JSON line; writes SPEC_BENCH.json.
"""

from __future__ import annotations

import json
import os
import sys
import time

import jax
import numpy as np

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))
sys.path.insert(0, os.path.dirname(os.path.abspath(__file__)))

from bench import flagship_cfg  # noqa: E402
from profile_decode import host_overhead_breakdown  # noqa: E402

MODEL = os.environ.get("SPEC_MODEL", "1b2")
BATCH = int(os.environ.get("SPEC_BATCH", 16))
PROMPT = int(os.environ.get("SPEC_PROMPT", 128))
DECODE = int(os.environ.get("SPEC_DECODE", 256))
GAMMA = int(os.environ.get("SPEC_GAMMA", 4))


def main():
    from llmss_tpu.engine import DecodeEngine, GenerationParams
    from llmss_tpu.models.decoder import init_params
    from llmss_tpu.parallel import MeshPlan, make_mesh

    mesh = make_mesh(MeshPlan(tp=len(jax.devices())))
    cfg = flagship_cfg(MODEL)
    params = init_params(cfg, mesh, jax.random.key(0))
    engine = DecodeEngine(
        cfg, params, mesh, max_seq_len=PROMPT + DECODE + GAMMA + 1,
    )
    gen = GenerationParams(max_new_tokens=DECODE, is_greedy=True)
    rng = np.random.default_rng(0)
    prompts = [
        rng.integers(0, cfg.vocab_size, PROMPT).tolist()
        for _ in range(BATCH)
    ]

    def timed(fn, reps=2):
        fn()  # warm/compile
        best = float("inf")
        out = None
        for _ in range(reps):
            t0 = time.perf_counter()
            out = fn()
            best = min(best, time.perf_counter() - t0)
        return best, out

    def exec_overhead_ms(n=16):
        """Fixed host cost per program EXECUTION on this host (the axon
        tunnel charges ~15 ms each; co-located hosts ~0.1 ms). Measured
        by chaining executions of a trivial donated-buffer program."""
        import jax.numpy as jnp

        f = jax.jit(lambda x: x + 1, donate_argnums=(0,))
        x = jnp.zeros((8,), jnp.int32)
        x = f(x)
        _ = np.asarray(x)
        t0 = time.perf_counter()
        for _ in range(n):
            x = f(x)
        _ = np.asarray(x)
        return (time.perf_counter() - t0) / n * 1e3

    t_plain, out_plain = timed(
        lambda: engine.generate(prompts, gen, chunk_steps=32)
    )
    t_spec, out_spec = timed(
        lambda: engine.generate_speculative(prompts, gen, gamma=GAMMA)
    )
    # Determinism is the hard check: speculation must be repeatable.
    out_spec2 = engine.generate_speculative(prompts, gen, gamma=GAMMA)
    assert out_spec2 == out_spec, "speculative decode not deterministic!"
    # vs the plain path, outputs agree until an fp32 argmax tie resolves
    # differently between the S=1 and S=gamma+1 attention kernels (each
    # run is a valid greedy decode of its own numerics path; on CPU,
    # where both take the same XLA path, tests assert exact equality).
    div = []
    for a, b in zip(out_plain, out_spec):
        n = min(len(a), len(b))
        i = next((k for k in range(n) if a[k] != b[k]), n)
        div.append(i)
    stats = engine.metrics.spec_stats

    # Per-execution host overhead separates framework cost from host-link
    # cost: speculation runs ~8x more (small) executions than chunked
    # decode, so a high-overhead host (this tunnel: ~15 ms/exec) taxes it
    # ~8x harder. The overhead-adjusted ratio is what a co-located
    # deployment sees; xprof cross-check: 5.4 ms device per verify.
    ovh_ms = exec_overhead_ms()
    n_tok = sum(len(o) for o in out_spec)
    fwd = stats["verify_forwards"]
    plain_execs = -(-DECODE // 32)  # chunk_steps=32 in the plain run
    adj_plain = t_plain - plain_execs * ovh_ms / 1e3
    adj_spec = t_spec - fwd * ovh_ms / 1e3
    adj = adj_plain / adj_spec if adj_spec > 0 else float("inf")
    result = {
        "metric": "speculative_decode_speedup",
        "value": round(t_plain / t_spec, 3),
        "unit": (
            f"x wall-clock vs chunked greedy on THIS host "
            f"({MODEL} bf16 on {jax.default_backend()}, "
            f"batch={BATCH}, {DECODE} new tokens, gamma={GAMMA}: "
            f"{n_tok / t_spec:.0f} vs {n_tok / t_plain:.0f} tok/s, "
            f"{stats['mean_tokens_per_forward_per_row']} tok/row/verify; "
            f"host exec-overhead {ovh_ms:.1f} ms x {fwd} verifies — "
            f"overhead-adjusted (co-located host) speedup {adj:.2f}x; "
            f"agree-with-plain-path min/median "
            f"{min(div)}/{int(np.median(div))} of {DECODE} tokens)"
        ),
        "vs_baseline": round(t_plain / t_spec, 3),
        "exec_overhead_ms": round(ovh_ms, 2),
        "overhead_adjusted_speedup": round(adj, 3),
    }
    print(json.dumps(result))
    with open(os.path.join(os.path.dirname(os.path.dirname(
            os.path.abspath(__file__))), "SPEC_BENCH.json"), "w") as f:
        from bench import bench_provenance

        json.dump({**result, "spec_stats": stats,
                   "plain_s": round(t_plain, 2),
                   "spec_s": round(t_spec, 2),
                   # Accumulated over the plain + speculative runs above:
                   # the grouped dispatch pays ONE packed fetch per group,
                   # so spec verify loops dominate host_syncs here.
                   "host_overhead": host_overhead_breakdown(
                       engine.metrics),
                   "provenance": bench_provenance()}, f, indent=1)


if __name__ == "__main__":
    main()
