"""Router bench: shared queue vs prefix-affinity routing, 3 in-proc workers.

The workload is the one the ``prefix_affinity`` policy exists for: many
tenants, each with its own shared system prompt, interleaved so that
consecutive requests almost never share a prefix. Each simulated worker
holds a small prefix LRU (``LRU_SLOTS`` per worker — fewer than the
tenant count, more than tenants/worker), and a prefill that misses the
LRU costs ``MISS_COST_S`` vs ``HIT_COST_S`` on a hit — the same shape as
a real paged-KV COW prefix hit vs a full prefill.

With the shared queue every worker eventually sees every tenant and the
LRUs thrash; with prefix-affinity each tenant's requests ride to one
owning replica, so the fleet-wide working set fits. The bench measures
the worker-observed prefix hit rate, p50/p95 TTFT, and aggregate
tokens/s for both modes and asserts the direction of the result.

Runs on CPU in one process (``InProcBroker``; no JAX, no device).
Writes ROUTER_BENCH.json; prints one JSON line.
"""

from __future__ import annotations

import collections
import json
import os
import statistics
import sys
import threading
import time

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

from llmss_tpu.serve.broker import InProcBroker  # noqa: E402
from llmss_tpu.serve.fleet import Router  # noqa: E402
from llmss_tpu.serve.protocol import (  # noqa: E402
    GenerateRequest,
    GenerateResponse,
    prefix_hash,
)

N_WORKERS = int(os.environ.get("ROUTER_WORKERS", 3))
N_TENANTS = int(os.environ.get("ROUTER_TENANTS", 8))
N_REQUESTS = int(os.environ.get("ROUTER_REQUESTS", 120))
LRU_SLOTS = int(os.environ.get("ROUTER_LRU_SLOTS", 4))
MISS_COST_S = float(os.environ.get("ROUTER_MISS_COST_S", 0.015))
HIT_COST_S = float(os.environ.get("ROUTER_HIT_COST_S", 0.0015))
TOKEN_COST_S = float(os.environ.get("ROUTER_TOKEN_COST_S", 0.0002))
MAX_NEW = 16
PREFIX_LEN = 32


class SimWorker:
    """One replica: pops requests, charges prefill cost by prefix-LRU
    hit/miss, publishes fleet snapshots with its resident hashes."""

    def __init__(self, wid, broker, submit_ts, ttfts, hits, misses, lock):
        self.wid = wid
        self.broker = broker
        self.submit_ts = submit_ts
        self.ttfts = ttfts
        self.hits = hits
        self.misses = misses
        self.lock = lock
        self.lru = collections.OrderedDict()
        self.tokens_done = 0
        self._stop = threading.Event()
        self._thread = threading.Thread(target=self._loop, daemon=True)

    def _snapshot(self):
        return {
            "state": "ready",
            "alive": True,
            "rows": 1,
            "inflight_rows": 0,
            "queue_depth": 0,
            "free_slots": 1,
            "free_kv_blocks": LRU_SLOTS - len(self.lru),
            "kv_blocks_total": LRU_SLOTS,
            "prefix_hashes": list(self.lru),
            "heartbeat_s": 0.5,
            "heartbeat_ts": time.time(),
        }

    def _loop(self):
        self.broker.register_worker({"worker_id": self.wid, "model": "sim"})
        self.broker.publish_worker_load(self.wid, self._snapshot())
        while not self._stop.is_set():
            req = self.broker.pop_request(timeout=0.05, worker_id=self.wid)
            if req is None:
                continue
            h = prefix_hash(req.prefix_token_ids)
            if h in self.lru:
                self.lru.move_to_end(h)
                cost, bucket = HIT_COST_S, self.hits
            else:
                self.lru[h] = True
                while len(self.lru) > LRU_SLOTS:
                    self.lru.popitem(last=False)
                cost, bucket = MISS_COST_S, self.misses
            time.sleep(cost)  # prefill: full on miss, COW-attach on hit
            with self.lock:
                bucket.append(req.id)
                self.ttfts.append(time.monotonic() - self.submit_ts[req.id])
            time.sleep(TOKEN_COST_S * req.max_new_tokens)
            self.tokens_done += req.max_new_tokens
            self.broker.push_response(
                GenerateResponse(id=req.id, token_ids=[0] * req.max_new_tokens)
            )
            self.broker.publish_worker_load(self.wid, self._snapshot())

    def start(self):
        self._thread.start()

    def stop(self):
        self._stop.set()
        self._thread.join(timeout=10)


def make_trace():
    """Interleaved multi-tenant trace: request i belongs to tenant
    i % N_TENANTS, so back-to-back requests never share a prefix."""
    prefixes = [
        [1000 + t] * PREFIX_LEN for t in range(N_TENANTS)
    ]
    return [
        GenerateRequest(
            token_ids=prefixes[i % N_TENANTS] + [i + 1],
            prefix_token_ids=prefixes[i % N_TENANTS],
            max_new_tokens=MAX_NEW,
        )
        for i in range(N_REQUESTS)
    ]


def run_mode(mode: str) -> dict:
    broker = InProcBroker()
    submit_ts: dict[str, float] = {}
    ttfts: list[float] = []
    hits: list[str] = []
    misses: list[str] = []
    lock = threading.Lock()
    workers = [
        SimWorker(f"w{i}", broker, submit_ts, ttfts, hits, misses, lock)
        for i in range(N_WORKERS)
    ]
    router = Router(broker, "prefix_affinity") if mode == "affinity" else None
    reqs = make_trace()
    for w in workers:
        w.start()
    deadline = time.monotonic() + 10.0
    while len(broker.read_workers()) < N_WORKERS:
        if time.monotonic() > deadline:
            raise RuntimeError("workers never registered")
        time.sleep(0.01)
    t0 = time.monotonic()
    for r in reqs:
        submit_ts[r.id] = time.monotonic()
        if router is not None:
            router.submit(r)
        else:
            broker.push_request(r)
    for r in reqs:
        resp = broker.wait_response(r.id, timeout=60.0)
        assert resp is not None and not resp.error, r.id
    elapsed = time.monotonic() - t0
    for w in workers:
        w.stop()
    n = len(hits) + len(misses)
    out = {
        "mode": mode,
        "requests": n,
        "prefix_hit_rate": round(len(hits) / n, 4),
        "ttft_p50_ms": round(statistics.median(ttfts) * 1e3, 3),
        "ttft_p95_ms": round(
            statistics.quantiles(ttfts, n=20)[18] * 1e3, 3
        ),
        "tokens_per_s": round(sum(w.tokens_done for w in workers) / elapsed, 1),
        "elapsed_s": round(elapsed, 3),
    }
    if router is not None:
        out["router"] = router.stats()
    return out


def main():
    shared = run_mode("shared")
    affinity = run_mode("affinity")
    result = {
        "config": {
            "workers": N_WORKERS,
            "tenants": N_TENANTS,
            "requests": N_REQUESTS,
            "lru_slots_per_worker": LRU_SLOTS,
            "miss_cost_s": MISS_COST_S,
            "hit_cost_s": HIT_COST_S,
            "token_cost_s": TOKEN_COST_S,
            "max_new_tokens": MAX_NEW,
        },
        "shared": shared,
        "affinity": affinity,
    }
    from bench import bench_provenance

    result["provenance"] = bench_provenance()
    # The claims the policy ships on: strictly better prefix locality, no
    # TTFT regression.
    assert affinity["prefix_hit_rate"] > shared["prefix_hit_rate"], result
    assert affinity["ttft_p50_ms"] <= shared["ttft_p50_ms"], result
    path = os.path.join(
        os.path.dirname(os.path.dirname(os.path.abspath(__file__))),
        "ROUTER_BENCH.json",
    )
    with open(path, "w") as f:
        json.dump(result, f, indent=2)
        f.write("\n")
    print(json.dumps(result))


if __name__ == "__main__":
    main()
