"""Router bench: shared queue vs prefix-affinity routing, 3 sim workers.

The workload is the one the ``prefix_affinity`` policy exists for: many
tenants, each with its own shared system prompt, interleaved so that
consecutive requests almost never share a prefix. Each simulated worker
holds a small prefix LRU (``LRU_SLOTS`` per worker — fewer than the
tenant count, more than tenants/worker), and a prefill that misses the
LRU pays the full prompt (``MISS_COST_S``) while a hit COW-attaches the
resident prefix and pays only the suffix — the same shape as a real
paged-KV COW prefix hit vs a full prefill.

With the shared queue every worker eventually sees every tenant and the
LRUs thrash; with prefix-affinity each tenant's requests ride to one
owning replica, so the fleet-wide working set fits. Both arms run on
the deterministic fleet simulator (``llmss_tpu.sim``): the REAL
``Router`` routes (or the ``shared`` null policy pushes to the shared
queue), replicas publish their resident prefix hashes in fleet
snapshots, and the invariant catalog is asserted at drain. The bench
measures the worker-observed prefix hit rate, p50/p95 TTFT, and
aggregate tokens/s for both modes and asserts the direction of the
result.

Runs on CPU in one process (no JAX, no device). Writes
ROUTER_BENCH.json; prints one JSON line.
"""

from __future__ import annotations

import json
import os
import sys

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

from llmss_tpu.sim import FleetSim  # noqa: E402

N_WORKERS = int(os.environ.get("ROUTER_WORKERS", 3))
N_TENANTS = int(os.environ.get("ROUTER_TENANTS", 8))
N_REQUESTS = int(os.environ.get("ROUTER_REQUESTS", 120))
LRU_SLOTS = int(os.environ.get("ROUTER_LRU_SLOTS", 4))
MISS_COST_S = float(os.environ.get("ROUTER_MISS_COST_S", 0.015))
HIT_COST_S = float(os.environ.get("ROUTER_HIT_COST_S", 0.0015))
TOKEN_COST_S = float(os.environ.get("ROUTER_TOKEN_COST_S", 0.0002))
MAX_NEW = 16
PREFIX_LEN = 32


def make_trace_rows() -> list[dict]:
    """Interleaved multi-tenant trace: request i belongs to tenant
    i % N_TENANTS, so back-to-back requests never share a prefix."""
    prefixes = [
        [1000 + t] * PREFIX_LEN for t in range(N_TENANTS)
    ]
    return [
        {
            "id": f"rt{i:04d}",
            "arrival_s": 0.0,  # burst submit, like the original bench
            "token_ids": prefixes[i % N_TENANTS] + [i + 1],
            "prefix_token_ids": prefixes[i % N_TENANTS],
            "max_new": MAX_NEW,
        }
        for i in range(N_REQUESTS)
    ]


def make_spec(mode: str) -> dict:
    return {
        "format": "llmss-scenario/1",
        "name": f"bench-router-{mode}",
        "seed": 0,
        "broker": {"kind": "inproc", "lease_s": 10.0},
        "cost_model": {
            "kind": "table",
            # Full prompt (prefix + 1 suffix token) on a miss prices at
            # MISS_COST_S; a COW hit prefills only the suffix token.
            "prefill_token_s": MISS_COST_S / (PREFIX_LEN + 1),
            "decode_step_s": TOKEN_COST_S,
        },
        "fleet": {
            "replicas": [{
                "count": N_WORKERS, "role": "unified", "rows": 1,
                "chunk_tokens": MAX_NEW, "prefill_chunk": PREFIX_LEN + 1,
                "admit_burst": 1, "prefix_lru_slots": LRU_SLOTS,
            }],
            "router_policy": (
                "prefix_affinity" if mode == "affinity" else "shared"
            ),
        },
        "workload": {"kind": "trace", "rows": make_trace_rows()},
    }


def run_mode(mode: str) -> dict:
    sim = FleetSim(make_spec(mode))
    report = sim.run()
    tp = report["throughput"]
    elapsed = (
        tp["tokens_out"] / tp["tokens_per_s"] if tp["tokens_per_s"] else 0.0
    )
    hits = sim.counters["prefix_hits"]
    n = hits + sim.counters["prefix_misses"]
    out = {
        "mode": mode,
        "requests": n,
        "prefix_hit_rate": round(hits / n, 4),
        "ttft_p50_ms": round(report["latency_ms"]["ttft_p50"], 3),
        "ttft_p95_ms": round(report["latency_ms"]["ttft_p95"], 3),
        "tokens_per_s": round(tp["tokens_out"] / elapsed, 1)
        if elapsed else 0.0,
        "elapsed_s": round(elapsed, 3),
    }
    if sim.router is not None:
        out["router"] = sim.router.stats()
    return out


def main():
    shared = run_mode("shared")
    affinity = run_mode("affinity")
    result = {
        "config": {
            "workers": N_WORKERS,
            "tenants": N_TENANTS,
            "requests": N_REQUESTS,
            "lru_slots_per_worker": LRU_SLOTS,
            "miss_cost_s": MISS_COST_S,
            "hit_cost_s": HIT_COST_S,
            "token_cost_s": TOKEN_COST_S,
            "max_new_tokens": MAX_NEW,
        },
        "shared": shared,
        "affinity": affinity,
    }
    from bench import bench_provenance

    result["provenance"] = bench_provenance()
    # The claims the policy ships on: strictly better prefix locality, no
    # TTFT regression.
    assert affinity["prefix_hit_rate"] > shared["prefix_hit_rate"], result
    assert affinity["ttft_p50_ms"] <= shared["ttft_p50_ms"], result
    path = os.path.join(
        os.path.dirname(os.path.dirname(os.path.abspath(__file__))),
        "ROUTER_BENCH.json",
    )
    with open(path, "w") as f:
        json.dump(result, f, indent=2)
        f.write("\n")
    print(json.dumps(result))


if __name__ == "__main__":
    main()
