"""Devtel-overhead bench: the device telemetry plane on vs off.

The devtel plane (utils/devtel.py) rides the grouped-decode hot path:
one cost-table lookup at dispatch, one MFU/MBU fold at fetch, one
throttle check per group for the compile sampler and the counter tracks.
Its acceptance bar (ISSUE 15): fully enabled it adds **≤ 2 µs host
overhead per group dispatch** and **< 1% end-to-end throughput** vs
disabled, and a mixed-trace run's Perfetto export carries MFU/MBU
samples, ≥ 3 counter tracks, and ≥ 1 attributed compile span, with a
forced mid-serve recompile flagged on ``/slo``.

Three passes pin those numbers:

1. **Per-group microcost** — the exact per-group devtel work (cost-table
   hit + fold + both throttle checks) timed directly over many
   iterations. Wall-clock A/B on a real serve loop cannot resolve 2 µs
   under CPU scheduler noise; timing the added code path itself can
   (the bench_trace.py best-of discipline, applied at finer grain).
2. **End-to-end throughput** — a real tiny-engine ``ContinuousBatcher``
   serve pass, devtel on vs off, best-of-REPEATS; the acceptance delta.
3. **Artifact checks** — from the enabled run: the Perfetto export's
   counter tracks and compile spans, plus a forced mid-serve recompile
   surfaced through the REAL ``/slo`` and ``/compiles`` payload code
   (a ``ProducerServer`` over an ``InProcBroker``; no sockets).

CPU-only (JAX_PLATFORMS=cpu, the tests/conftest.py 8-device mesh);
MFU/MBU values are roofline-SHAPED but not meaningful in absolute terms
off-TPU (docs/observability.md). Writes DEVTEL_BENCH.json; prints one
JSON line per metric, headline last.
"""

from __future__ import annotations

import json
import os
import sys
import time

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

os.environ.setdefault("JAX_PLATFORMS", "cpu")
os.environ.setdefault("XLA_FLAGS", "--xla_force_host_platform_device_count=8")

REPEATS = int(os.environ.get("DEVTEL_BENCH_REPEATS", 3))
N_REQUESTS = int(os.environ.get("DEVTEL_BENCH_REQUESTS", 12))
MAX_NEW = int(os.environ.get("DEVTEL_BENCH_MAX_NEW", 16))
MICRO_ITERS = int(os.environ.get("DEVTEL_BENCH_MICRO_ITERS", 20000))


def make_batcher():
    import jax

    from llmss_tpu.engine import DecodeEngine
    from llmss_tpu.engine.scheduler import ContinuousBatcher
    from llmss_tpu.models.common import DecoderConfig
    from llmss_tpu.models.decoder import init_params
    from llmss_tpu.parallel import MeshPlan, make_mesh

    cfg = DecoderConfig(
        model_type="llama", vocab_size=64, hidden_size=32, n_layers=2,
        n_heads=4, n_kv_heads=2, head_dim=8, intermediate_size=64,
        max_position_embeddings=64, activation="silu", norm="rmsnorm",
        norm_eps=1e-5, mlp="swiglu", positions="rotary", rope_style="half",
        rotary_dim=8, attn_bias=False, mlp_bias=False,
        tie_word_embeddings=False, dtype="float32",
    )
    mesh = make_mesh(MeshPlan(dp=2, tp=4))
    params = init_params(cfg, mesh, jax.random.key(0))
    engine = DecodeEngine(cfg, params, mesh, max_seq_len=64)
    batcher = ContinuousBatcher(engine, rows=4, chunk_steps=2, group_chunks=2)
    batcher.prewarm()
    return engine, batcher


def serve_pass(engine, batcher, devtel_on: bool) -> tuple[float, int]:
    """One serve pass of N_REQUESTS; returns (wall_s, groups_dispatched)."""
    from llmss_tpu.engine import GenerationParams
    from llmss_tpu.utils import devtel

    devtel.set_enabled(devtel_on)
    def groups() -> int:
        return engine.metrics.to_dict()["host_overhead"]["groups_dispatched"]

    gen = GenerationParams(max_new_tokens=MAX_NEW, is_greedy=True)
    got = {}
    g0 = groups()
    t0 = time.monotonic()
    for i in range(N_REQUESTS):
        batcher.submit(
            [(3 * i + j) % 63 + 1 for j in range(4)], gen,
            lambda t, i=i: got.__setitem__(i, t), req_id=f"dvb-{i}",
        )
    batcher.run_until_idle()
    wall = time.monotonic() - t0
    assert len(got) == N_REQUESTS, f"lost requests: {len(got)}"
    return wall, groups() - g0


def micro_cost(engine) -> float:
    """µs per group of the devtel hot path: the dispatch-side cost-table
    hit, the fetch-side fold, and both per-group throttle checks — the
    complete set of instructions a group pays when devtel is on."""
    from llmss_tpu.utils import devtel

    devtel.set_enabled(True)
    obs = devtel.observer()
    cost = engine.devtel_cost(
        "decode_group", (4, 2, 2, 32), batch=4, steps=4, kv_len=32,
    )
    assert cost is not None
    # Warm the fold sinks so the loop times the steady path, not the
    # first-call series registration.
    devtel.fold("decode_group", 0.004, cost)
    best = float("inf")
    for _ in range(REPEATS):
        t0 = time.perf_counter()
        for _ in range(MICRO_ITERS):
            c = engine.devtel_cost(
                "decode_group", (4, 2, 2, 32), batch=4, steps=4, kv_len=32,
            )
            devtel.fold("decode_group", 0.004, c)
            obs.maybe_sample("req")  # throttled: monotonic read + compare
        best = min(best, (time.perf_counter() - t0) / MICRO_ITERS * 1e6)
    return best


def main() -> int:
    from llmss_tpu.serve.broker import InProcBroker
    from llmss_tpu.serve.producer import ProducerServer
    from llmss_tpu.utils import devtel, trace

    from bench import bench_provenance  # repo root, on sys.path above

    trace.set_enabled(True)
    devtel.reset()
    engine, batcher = make_batcher()

    # Pass 1 — the per-group microcost.
    host_us_per_group = micro_cost(engine)

    # Pass 2 — end-to-end throughput A/B (best-of-REPEATS each mode).
    best = {"on": float("inf"), "off": float("inf")}
    groups = 0
    for _ in range(REPEATS):
        for mode in ("off", "on"):
            wall, g = serve_pass(engine, batcher, mode == "on")
            best[mode] = min(best[mode], wall)
            if mode == "on":
                groups += g
    tokens = N_REQUESTS * MAX_NEW
    tput_off = tokens / best["off"]
    tput_on = tokens / best["on"]
    overhead_pct = (best["on"] - best["off"]) / best["off"] * 100.0

    # Pass 3 — artifact checks from the enabled state accumulated above.
    devtel.set_enabled(True)
    # Force a mid-serve recompile: the decode executable at a batch the
    # prewarm envelope never covered, observed by the group-boundary
    # cache sweep and attributed to an in-flight request id.
    import jax.numpy as jnp

    from llmss_tpu.engine import GenerationParams

    devtel.observer()._last_sample = float("-inf")
    b = 2  # batcher prewarmed batch=4; 2 is a fresh executable signature
    engine._decode(
        engine.params, engine.canon_vec(jnp.zeros(b, jnp.int32)),
        engine.canon_cache(engine.new_cache(b)),
        engine.canon_vec(jnp.ones(b, jnp.int32)),
        engine._sample_args(GenerationParams(), b), t_bucket=None,
    )
    devtel.observer().maybe_sample("dvb-forced")

    ps = ProducerServer(broker=InProcBroker())
    slo = ps.slo()
    compiles = ps.compiles()
    chrome = trace.to_chrome_trace(
        [trace.recorder().export()],
        counters=[devtel.export()],
    )
    counter_tracks = sorted({
        e["name"] for e in chrome["traceEvents"] if e["ph"] == "C"
    })
    compile_spans = [
        e for e in chrome["traceEvents"]
        if e["ph"] in ("X", "i") and e["name"] == "compile"
    ]
    attributed = [
        e for e in compiles["compiles"] if e.get("req_id") == "dvb-forced"
    ]
    util = devtel.last_util()
    mfu_ok = all(
        0.0 < g["mfu"] <= 1.0 or g["mbu"] > 0.0 for g in util.values()
    ) and bool(util)

    checks = {
        "host_overhead_le_2us": host_us_per_group <= 2.0,
        # One-sided: the contract is "on is not >1% slower"; a negative
        # delta (on measured faster) is CPU wall-clock noise, not a fail.
        "throughput_delta_lt_1pct": overhead_pct < 1.0,
        "perfetto_mfu_mbu_samples": "mfu" in counter_tracks
                                    and "mbu" in counter_tracks,
        "perfetto_counter_tracks_ge_3": len(counter_tracks) >= 3,
        "perfetto_compile_span": len(compile_spans) >= 1,
        "attributed_compile": len(attributed) >= 1,
        "slo_flags_recompile": bool(
            slo.get("compile", {}).get("flagged"),
        ),
        "util_samples_in_unit_interval": mfu_ok,
    }
    out = {
        "bench": "devtel_overhead",
        "provenance": bench_provenance(),
        "requests": N_REQUESTS,
        "max_new_tokens": MAX_NEW,
        "repeats": REPEATS,
        "micro_iters": MICRO_ITERS,
        "group_dispatches_on": groups,
        "host_overhead_us_per_group": round(host_us_per_group, 3),
        "wall_s_devtel_off": round(best["off"], 4),
        "wall_s_devtel_on": round(best["on"], 4),
        "tok_per_s_devtel_off": round(tput_off, 1),
        "tok_per_s_devtel_on": round(tput_on, 1),
        "overhead_pct": round(overhead_pct, 2),
        "counter_tracks": counter_tracks,
        "n_compile_events": compiles["n_compiles"],
        "steady_state_recompiles": compiles["steady_recompiles"],
        "util": {
            k: {"mfu": g["mfu"], "mbu": g["mbu"], "source": g["source"]}
            for k, g in util.items()
        },
        "checks": checks,
        "ok": all(checks.values()),
    }
    with open("DEVTEL_BENCH.json", "w") as f:
        json.dump(out, f, indent=2)
        f.write("\n")
    for key in ("overhead_pct",):
        print(json.dumps({
            "metric": "devtel_" + key, "value": out[key], "unit": "%",
        }))
    print(json.dumps({
        "metric": "devtel_host_overhead_us_per_group",
        "value": out["host_overhead_us_per_group"],
        "unit": "us/group (budget 2.0)",
        "vs_baseline": out["ok"],
    }))
    return 0 if out["ok"] else 1


if __name__ == "__main__":
    sys.exit(main())
