"""Benchmark: decode throughput (tokens/sec/chip) on the flagship model.

Run on real TPU hardware by the driver. Prints ONE JSON line:
``{"metric": ..., "value": N, "unit": ..., "vs_baseline": N}``.

The reference publishes no numbers (BASELINE.md), so ``vs_baseline`` is
reported against the **HBM-bandwidth roofline** for batched decode on this
chip: a decode step must stream all parameter bytes plus the live KV-cache
bytes from HBM, so

    roofline_tokens_per_sec = batch * BW / (param_bytes + batch * kv_bytes)

``vs_baseline`` = measured / roofline — i.e. the fraction of the chip's
theoretical decode ceiling this framework reaches (1.0 is perfect).

Model: Llama-architecture ~1.2B (the BASELINE.md config-ladder scale that
fits one v5e chip with headroom), random-init bf16, batch 8, 128-token
prefill, fused 128-token decode.
"""

from __future__ import annotations

import json
import os
import time

import jax
import jax.numpy as jnp
import numpy as np

# Batch 16 is the sweet spot on v5e for this model: ~2100 tok/s/chip with
# p50 TTFT still under the BASELINE.md 200 ms target (batch 32 crosses it).
BATCH = int(os.environ.get("BENCH_BATCH", 16))
PROMPT = int(os.environ.get("BENCH_PROMPT", 128))
DECODE = int(os.environ.get("BENCH_DECODE", 128))
HBM_GBPS = float(os.environ.get("BENCH_HBM_GBPS", 819.0))  # v5e


def flagship_cfg():
    from llmss_tpu.models.common import DecoderConfig

    return DecoderConfig(
        model_type="llama",
        vocab_size=32000,
        hidden_size=2048,
        n_layers=20,
        n_heads=16,
        n_kv_heads=16,
        head_dim=128,
        intermediate_size=5504,
        max_position_embeddings=2048,
        activation="silu",
        norm="rmsnorm",
        norm_eps=1e-5,
        mlp="swiglu",
        positions="rotary",
        rope_style="half",
        rotary_dim=128,
        attn_bias=False,
        mlp_bias=False,
        tie_word_embeddings=False,
        dtype="bfloat16",
    )


def main():
    from llmss_tpu.engine import DecodeEngine, GenerationParams
    from llmss_tpu.models.decoder import init_params
    from llmss_tpu.parallel import MeshPlan, make_mesh

    n_dev = len(jax.devices())
    mesh = make_mesh(MeshPlan(tp=n_dev))
    cfg = flagship_cfg()
    params = init_params(cfg, mesh, jax.random.key(0))
    n_params = sum(
        np.prod(x.shape) for x in jax.tree.leaves(params)
    )
    param_bytes = float(n_params) * 2  # bf16

    max_seq = PROMPT + DECODE
    engine = DecodeEngine(cfg, params, mesh, max_seq_len=max_seq)
    gen_warm = GenerationParams(max_new_tokens=8, is_greedy=True)
    gen = GenerationParams(max_new_tokens=DECODE, is_greedy=True)

    rng = np.random.default_rng(0)
    prompts = [
        rng.integers(0, cfg.vocab_size, PROMPT).tolist() for _ in range(BATCH)
    ]

    # Warmup (compile prefill + decode_many for both step counts).
    engine.generate_fused(prompts, gen_warm)
    engine.generate_fused(prompts, gen)

    # TTFT: prefill + first sampled token, compiled.
    cache = engine.new_cache(BATCH)
    ids, lens = engine._pad_prompts(prompts)
    sa = engine._sample_args(gen, BATCH)
    t0 = time.perf_counter()
    tok, _, cache = engine._prefill(
        engine.params, jnp.asarray(ids), cache, jnp.asarray(lens), sa,
    )
    tok.block_until_ready()
    ttft_ms = (time.perf_counter() - t0) * 1e3
    del cache

    # Decode throughput: fused generation, steady state.
    t0 = time.perf_counter()
    out = engine.generate_fused(prompts, gen)
    dt = time.perf_counter() - t0
    n_tokens = sum(len(o) for o in out)
    tok_per_sec_per_chip = n_tokens / dt / n_dev

    kv_bytes_per_token = (
        2 * cfg.n_layers * cfg.n_kv_heads * cfg.head_dim * 2 * max_seq / 2
    )  # avg half-full cache, k+v, bf16
    roofline = BATCH * HBM_GBPS * 1e9 / (
        param_bytes + BATCH * kv_bytes_per_token
    )
    result = {
        "metric": "decode_tokens_per_sec_per_chip",
        "value": round(tok_per_sec_per_chip, 1),
        "unit": f"tok/s/chip (1.2B bf16, batch={BATCH}, ttft_ms={ttft_ms:.0f})",
        "vs_baseline": round(tok_per_sec_per_chip / roofline, 3),
    }
    print(json.dumps(result))


if __name__ == "__main__":
    main()
