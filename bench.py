"""Benchmark: decode throughput (tokens/sec/chip) on the flagship model.

Run on real TPU hardware by the driver. Prints ONE JSON line per benched
config — the HEADLINE LAST: **Llama-2-7B dims, the BASELINE.md
north-star scale** (the ~1.2B lines print first: the series tracked since
round 1, kept for cross-round comparability, plus its int8-KV variant):
``{"metric": ..., "value": N, "unit": ..., "vs_baseline": N}``.

The reference publishes no numbers (BASELINE.md), so ``vs_baseline`` is
reported against the **HBM-bandwidth roofline** for batched decode on this
chip: a decode step must stream all parameter bytes plus the live KV-cache
bytes from HBM, so

    roofline_tokens_per_sec = batch * BW / (param_bytes + batch * kv_bytes)

``vs_baseline`` = measured / roofline — i.e. the fraction of the chip's
theoretical decode ceiling this framework reaches (1.0 is perfect), with
``kv_bytes`` accounted at an average half-full ring in bf16.

Methodology: steady-state decode cost is the **marginal** time per fused
decode step, measured by the slope method — run the decode at two step
counts and take (t(N2) - t(N1)) / (N2 - N1). This cancels constant per-call
overhead (on the axon bench host the tunnel adds ~90 ms of dispatch + fetch
latency per call, which is host-link artifact, not framework cost) and
matches what a long-running serving process sustains. The decode runs in
CHUNK-step fused scans chained back-to-back (dispatches are async — no host
sync between chunks), exactly like the serving path, so the engine's
**bucketed cache reads** are measured: each chunk reads only the ring
prefix covering the rows' live context (engine.decode_bucket), not the
whole provisioned ring. The ring (``MAX_SEQ``) is sized so the slope window
never wraps — positions stay inside the advertised context. Prefill
latency is its own number (TTFT, reported in ``unit``), not smeared into
decode throughput. As an independent cross-check, ``unit`` also reports
the achieved HBM rate implied by the measured step time over the bytes the
step actually streams (params + the mean bucketed KV prefix).

Models: Llama-architecture ~1.2B (the series tracked across rounds, plus
its int8-KV variant) and Llama-2-7B dims — the BASELINE.md north-star
scale and the headline, printed last — all random-init bf16 weights.
``BENCH_MODEL=1b2|7b`` restricts to one.
"""

from __future__ import annotations

import json
import os
import time

import jax
import jax.numpy as jnp
import numpy as np

# Batch 16 is the 1b2 headline point (vs_baseline peaks there: params
# dominate the roofline denominator); 7B runs batch 4 (params + cache fill
# the chip). BENCH_KV_DTYPE=int8 halves cache memory (2x rows/context).
PROMPT = int(os.environ.get("BENCH_PROMPT", 128))
DECODE = int(os.environ.get("BENCH_DECODE", 128))
HBM_GBPS = float(os.environ.get("BENCH_HBM_GBPS", 819.0))  # v5e
KV_DTYPE = os.environ.get("BENCH_KV_DTYPE") or None  # "int8" halves KV bytes
CHUNK = int(os.environ.get("BENCH_CHUNK", 32))  # serving-path fused chunk

MODEL = os.environ.get("BENCH_MODEL")  # "1b2" | "7b" | None = both

_MODEL_DIMS = {
    # ~1.2B: the headline config — fits one v5e with generous cache room.
    "1b2": dict(hidden_size=2048, n_layers=20, n_heads=16,
                intermediate_size=5504),
    # Llama-2-7B dims (BASELINE.md north-star scale): 13.5 GB bf16 params
    # on a 16 GB v5e — single-chip analogue of the TP=8 config.
    "7b": dict(hidden_size=4096, n_layers=32, n_heads=32,
               intermediate_size=11008),
    # CPU-runnable smoke scale for the paged A/B's functional half
    # (token identity + block accounting are host-independent).
    "tiny": dict(hidden_size=256, n_layers=2, n_heads=2,
                 intermediate_size=512),
}

# Per-model operating point: batch and slope-method step counts (the 7B
# window is shorter because its params already fill 13.5 of 16 GB). The
# ring is derived as PROMPT + n_slope[1] so the slope window never wraps,
# whatever BENCH_PROMPT is set to.
_MODEL_RUN = {
    "1b2": dict(batch=16, n_slope=(64, 320)),
    "7b": dict(batch=4, n_slope=(32, 224)),
    "tiny": dict(batch=4, n_slope=(8, 24)),
}

BATCH = int(os.environ.get("BENCH_BATCH", 0))  # 0 = per-model default


def bench_provenance() -> dict:
    """Host/accelerator provenance stamped into every bench JSON.

    Every ``*_BENCH.json`` / ``BENCH_*.json`` writer in the repo includes
    this block so a reader can tell a CPU-backend functional run from a
    real-TPU run without parsing the ``unit`` string. Lazy ``jax`` import:
    pure-CPU benches (tools/bench_router.py) reach here without having
    initialized a backend, and the stamp itself is what forces it.
    """
    import platform as _plat

    out = {"python": _plat.python_version(), "machine": _plat.machine()}
    try:
        import jax as _jax

        dev = _jax.devices()[0]
        out.update(
            backend=_jax.default_backend(),
            platform=dev.platform,
            device_kind=dev.device_kind,
            device_count=_jax.device_count(),
        )
    except Exception:  # no JAX / no backend: still stamp the host
        out.update(
            backend=None, platform=_plat.system().lower(), device_count=0
        )
    return out


def flagship_cfg(model: str = "1b2"):
    from llmss_tpu.models.common import DecoderConfig

    dims = _MODEL_DIMS[model]
    return DecoderConfig(
        model_type="llama",
        vocab_size=32000,
        n_kv_heads=dims["n_heads"],
        head_dim=128,
        max_position_embeddings=4096,
        activation="silu",
        norm="rmsnorm",
        norm_eps=1e-5,
        mlp="swiglu",
        positions="rotary",
        rope_style="half",
        rotary_dim=128,
        attn_bias=False,
        mlp_bias=False,
        tie_word_embeddings=False,
        dtype="bfloat16",
        **dims,
    )


def roofline_tokens_per_sec(
    cfg, param_bytes: float, batch: int, max_seq: int,
    hbm_gbps: float = HBM_GBPS,
) -> float:
    """HBM-bandwidth decode ceiling: params + avg-half-full bf16 KV per
    step. The single definition of ``vs_baseline`` shared by bench.py and
    bench_serve.py so the two lines stay directly comparable."""
    kv_bytes_per_token = (
        2 * cfg.n_layers * cfg.n_kv_heads * cfg.head_dim * 2 * max_seq / 2
    )  # avg half-full cache, k+v, bf16
    return batch * hbm_gbps * 1e9 / (
        param_bytes + batch * kv_bytes_per_token
    )


def slope_time(
    prepare, n_slope=(64, 320), reps: int = 3
) -> tuple[float, float]:
    """Marginal ms per decode step + constant ms, via the slope method.

    ``prepare(n)`` must return a zero-arg callable that runs one n-step
    decode **to completion** — force it with a host fetch of a scalar
    reduction; ``block_until_ready`` can return at dispatch time over the
    axon tunnel. The single methodology shared by bench.py and
    tools/profile_decode.py.
    """
    times = {}
    for n in n_slope:
        run = prepare(n)
        run()  # compile + warm
        best = float("inf")
        for _i in range(reps):
            t0 = time.perf_counter()
            run()
            best = min(best, time.perf_counter() - t0)
        times[n] = best
        # Drop the closure (and the cache it carries) BEFORE the next
        # prepare(): at big-ring configs two live caches OOM the chip.
        del run
    n1, n2 = n_slope
    slope_ms = (times[n2] - times[n1]) / (n2 - n1) * 1e3
    const_ms = times[n1] * 1e3 - slope_ms * n1
    return slope_ms, const_ms


def chunk_schedule(engine, start_pos: int, n_steps: int, chunk: int):
    """The (n_steps_in_chunk, t_bucket) sequence a chained-chunk decode of
    ``n_steps`` runs, starting with every row at ``start_pos``. Shared by
    the runner and the achieved-bandwidth accounting."""
    out = []
    pos = start_pos
    left = n_steps
    while left > 0:
        k = min(chunk, left)
        out.append((k, engine.decode_bucket(pos + k)))
        pos += k
        left -= k
    return out


def _decode_slope_ms(engine, ids, lens, sa, eos, batch, n_slope):
    """Serving-path decode: chained CHUNK-step fused scans with bucketed
    cache reads, dispatched back-to-back (async), one forcing fetch at the
    end. Marginal cost via the slope method."""
    done = jnp.zeros(batch, bool)

    def prepare(n):
        cache = engine.new_cache(batch)
        tok0, _, cache = engine._prefill(
            engine.params, jnp.asarray(ids), cache, jnp.asarray(lens), sa,
        )
        tok0 = engine.canon_vec(tok0)
        cache = engine.canon_cache(cache)
        cur0 = engine.canon_vec(jnp.asarray(lens))
        sched = chunk_schedule(engine, int(lens.max()), n, CHUNK)
        state = {"cache": cache}

        def run():
            cache = state["cache"]
            tok, cur = tok0, cur0
            total = jnp.zeros((), jnp.int32)
            for k, tb in sched:
                toks, cache, cur, _, _ = engine._decode_many(
                    engine.params, tok, cache, cur, sa, done, eos,
                    n_steps=k, t_bucket=tb,
                )
                cache = engine.canon_cache(cache)
                cur = engine.canon_vec(cur)
                tok = engine.canon_vec(toks[:, -1])
                total = total + jnp.sum(toks)
            state["cache"] = cache
            _ = int(total)  # forced completion

        return run

    return slope_time(prepare, n_slope)


def run_model(model: str, kv_dtype: str | None = KV_DTYPE) -> dict:
    from llmss_tpu.engine import DecodeEngine, GenerationParams
    from llmss_tpu.models.decoder import init_params
    from llmss_tpu.parallel import MeshPlan, make_mesh

    run_cfg = _MODEL_RUN[model]
    batch = BATCH or run_cfg["batch"]
    n_slope = run_cfg["n_slope"]
    max_seq = int(os.environ.get("BENCH_MAX_SEQ", 0)) or (
        PROMPT + n_slope[1]
    )

    n_dev = len(jax.devices())
    mesh = make_mesh(MeshPlan(tp=n_dev))
    cfg = flagship_cfg(model)
    params = init_params(cfg, mesh, jax.random.key(0))
    n_params = sum(
        np.prod(x.shape) for x in jax.tree.leaves(params)
    )
    param_bytes = float(n_params) * 2  # bf16

    engine = DecodeEngine(
        cfg, params, mesh, max_seq_len=max_seq, kv_dtype=kv_dtype,
    )
    gen = GenerationParams(max_new_tokens=DECODE, is_greedy=True)

    rng = np.random.default_rng(0)
    prompts = [
        rng.integers(0, cfg.vocab_size, PROMPT).tolist() for _ in range(batch)
    ]
    ids, lens = engine._pad_prompts(prompts)
    sa = engine._sample_args(gen, batch)
    eos = engine.canon_vec(jnp.full(batch, -1, jnp.int32))

    # Warmup: compile prefill once.
    cache = engine.new_cache(batch)
    tok, _, cache = engine._prefill(
        engine.params, jnp.asarray(ids), cache, jnp.asarray(lens), sa,
    )
    _ = np.asarray(tok)
    del cache

    # TTFT: prefill + first sampled token on host, compiled path.
    ttft_ms = float("inf")
    for _i in range(3):
        cache = engine.new_cache(batch)
        t0 = time.perf_counter()
        tok, _, cache = engine._prefill(
            engine.params, jnp.asarray(ids), cache, jnp.asarray(lens), sa,
        )
        _ = np.asarray(tok)  # the token must actually reach the host
        ttft_ms = min(ttft_ms, (time.perf_counter() - t0) * 1e3)
        del cache

    # Decode throughput: marginal chained-chunk cost, steady state.
    step_ms, _ = _decode_slope_ms(engine, ids, lens, sa, eos, batch, n_slope)
    tok_per_sec_per_chip = batch / (step_ms * 1e-3) / n_dev

    # Sampled decode (BASELINE config #3): same slope with every row
    # running temperature + top-k + top-p through the static top-k bucket
    # path (ops/sampling.py) — must stay within a few % of greedy.
    sampled_ms = None
    if kv_dtype is None:
        gen_s = GenerationParams(
            max_new_tokens=DECODE, is_greedy=False, temperature=0.8,
            top_k=40, top_p=0.95, seed=1,
        )
        sa_s = engine._sample_args(gen_s, batch)
        sampled_ms, _ = _decode_slope_ms(
            engine, ids, lens, sa_s, eos, batch, n_slope
        )

    roofline = roofline_tokens_per_sec(cfg, param_bytes, batch, max_seq)
    # Independent cross-check: achieved HBM rate over the bytes a step in
    # the slope window actually streams — params + the mean bucketed KV
    # prefix (the full ring where no bucket applied).
    kv_token_bytes = 2 * cfg.n_layers * batch * (
        cfg.n_kv_heads * cfg.head_dim
    ) * (1 if kv_dtype == "int8" else 2)
    n1, n2 = n_slope
    per_step = []
    for k, tb in chunk_schedule(engine, int(lens.max()), n2, CHUNK):
        per_step += [tb if tb is not None else max_seq] * k
    mean_kv_bytes = kv_token_bytes * float(np.mean(per_step[n1:n2]))
    achieved_gbps = (param_bytes + mean_kv_bytes) / (step_ms * 1e-3) / 1e9
    return {
        "metric": "decode_tokens_per_sec_per_chip",
        "value": round(tok_per_sec_per_chip, 1),
        "unit": (
            f"tok/s/chip ({model} bf16, batch={batch}, "
            + (f"kv={kv_dtype}, " if kv_dtype else "")
            + f"ring={max_seq}, ttft_ms={ttft_ms:.0f}, "
            f"step_ms={step_ms:.2f}, "
            + (
                f"sampled_step_ms={sampled_ms:.2f}, "
                if sampled_ms is not None else ""
            )
            + f"achieved_hbm_gbps={achieved_gbps:.0f})"
        ),
        "vs_baseline": round(tok_per_sec_per_chip / roofline, 3),
    }


def run_paged_ab(model: str) -> dict:
    """Paged-vs-dense KV A/B (``python bench.py paged`` or BENCH_PAGED=1).

    Two halves, written to ``BENCH_PAGED.json``:

    1. **Per-layout decode cost** at identical batch/ring: marginal step
       time via the slope method (the paged engine runs identity tables —
       the dense-equivalent pool, so the delta IS the layout's indirection
       cost), tok/s/chip, and the achieved HBM rate over the bytes each
       step streams. The two runs must emit bit-identical tokens.
    2. **Capacity accounting** in a serving-shaped scenario (each request
       uses half its ring provision): the dense batcher provisions
       rows x max_seq and caps concurrency at its row count; the paged
       batcher gets the SAME KV byte budget as a block pool and must
       sustain 2x the concurrent rows with the same tokens — with KV HBM
       bytes per served token measured for both (provisioned bytes over
       tokens actually materialized).
    """
    from llmss_tpu.engine import DecodeEngine, GenerationParams
    from llmss_tpu.engine.scheduler import ContinuousBatcher
    from llmss_tpu.models.decoder import init_params
    from llmss_tpu.parallel import MeshPlan, make_mesh

    run_cfg = _MODEL_RUN[model]
    batch = BATCH or run_cfg["batch"]
    n_slope = run_cfg["n_slope"]
    bsz = int(os.environ.get("BENCH_BLOCK_SIZE", 16))
    max_seq = int(os.environ.get("BENCH_MAX_SEQ", 0)) or (
        PROMPT + n_slope[1]
    )
    max_seq = -(-max_seq // bsz) * bsz  # block-aligned ring

    n_dev = len(jax.devices())
    mesh = make_mesh(MeshPlan(tp=n_dev))
    cfg = flagship_cfg(model)
    params = init_params(cfg, mesh, jax.random.key(0))
    param_bytes = float(sum(
        np.prod(x.shape) for x in jax.tree.leaves(params)
    )) * 2
    kv_el_bytes = 1 if KV_DTYPE == "int8" else 2
    # KV bytes one row holds per token across all layers (k+v).
    row_tok_bytes = 2 * cfg.n_layers * cfg.n_kv_heads * cfg.head_dim * (
        kv_el_bytes
    )

    gen = GenerationParams(max_new_tokens=DECODE, is_greedy=True)
    rng = np.random.default_rng(0)
    prompts = [
        rng.integers(0, cfg.vocab_size, PROMPT).tolist() for _ in range(batch)
    ]

    result: dict = {"config": dict(
        model=model, batch=batch, ring=max_seq, block_size=bsz,
        prompt=PROMPT, decode=DECODE, kv_dtype=KV_DTYPE or "bf16",
        n_devices=n_dev, backend=jax.default_backend(),
    )}
    toks_ab = {}
    for layout in ("dense", "paged"):
        extra = (
            dict(kv_layout="paged", block_size=bsz)
            if layout == "paged" else {}
        )
        engine = DecodeEngine(
            cfg, params, mesh, max_seq_len=max_seq, kv_dtype=KV_DTYPE,
            **extra,
        )
        ids, lens = engine._pad_prompts(prompts)
        sa = engine._sample_args(gen, batch)
        eos = engine.canon_vec(jnp.full(batch, -1, jnp.int32))
        toks_ab[layout] = engine.generate(prompts, gen)
        step_ms, _ = _decode_slope_ms(
            engine, ids, lens, sa, eos, batch, n_slope
        )
        n1, n2 = n_slope
        per_step = []
        for k, tb in chunk_schedule(engine, int(lens.max()), n2, CHUNK):
            per_step += [tb if tb is not None else max_seq] * k
        mean_kv = batch * row_tok_bytes * float(np.mean(per_step[n1:n2]))
        result[layout] = {
            "step_ms": round(step_ms, 3),
            "tok_s_chip": round(batch / (step_ms * 1e-3) / n_dev, 1),
            "achieved_hbm_gbps": round(
                (param_bytes + mean_kv) / (step_ms * 1e-3) / 1e9, 2
            ),
        }
        del engine
    result["tokens_identical_engine"] = toks_ab["dense"] == toks_ab["paged"]
    result["provenance"] = bench_provenance()

    # -- capacity half: same KV byte budget, 2x the concurrent rows ------
    rows_d = batch
    mb = max_seq // bsz
    budget_blocks = rows_d * mb  # == the dense batcher's rows_d * max_seq
    g = min(DECODE, max_seq // 4)
    ps = max_seq // 2 - g  # prompt + new == half the ring provision
    short = [
        rng.integers(0, cfg.vocab_size, ps).tolist()
        for _ in range(2 * rows_d)
    ]
    gen_s = GenerationParams(max_new_tokens=g, is_greedy=True)

    def serve(engine, rows):
        bat = ContinuousBatcher(engine, rows=rows)
        results = {}
        for i, p in enumerate(short):
            bat.submit(
                p, gen_s, lambda t, i=i: results.__setitem__(i, t)
            )
        peak_rows = peak_blocks = 0
        while not bat.idle:
            bat.step()
            peak_rows = max(peak_rows, len(bat.active))
            if engine.kv_layout == "paged":
                peak_blocks = max(
                    peak_blocks, bat.allocator.blocks_in_use
                )
        return results, peak_rows, peak_blocks

    dense_eng = DecodeEngine(
        cfg, params, mesh, max_seq_len=max_seq, kv_dtype=KV_DTYPE,
    )
    paged_eng = DecodeEngine(
        cfg, params, mesh, max_seq_len=max_seq, kv_dtype=KV_DTYPE,
        kv_layout="paged", block_size=bsz, kv_blocks=budget_blocks,
    )
    out_d, rows_peak_d, _ = serve(dense_eng, rows_d)
    out_p, rows_peak_p, blocks_peak = serve(paged_eng, 2 * rows_d)
    served = 2 * rows_d * (ps + g)  # tokens materialized by the scenario
    result["serving"] = {
        "requests": 2 * rows_d,
        "tokens_per_request": ps + g,
        "kv_budget_bytes": budget_blocks * bsz * row_tok_bytes,
        "concurrent_rows_dense": rows_peak_d,
        "concurrent_rows_paged": rows_peak_p,
        "concurrency_ratio": round(rows_peak_p / rows_peak_d, 2),
        # dense serves the 2R requests in two R-row waves, each wave
        # provisioning rows_d full rings; paged provisions only the
        # blocks it actually mapped.
        "kv_hbm_bytes_per_served_token_dense": round(
            2 * rows_d * max_seq * row_tok_bytes / served, 1
        ),
        "kv_hbm_bytes_per_served_token_paged": round(
            blocks_peak * bsz * row_tok_bytes / served, 1
        ),
        "tokens_identical_serving": all(
            out_d[i] == out_p[i] for i in range(2 * rows_d)
        ),
    }
    with open(
        os.path.join(os.path.dirname(__file__), "BENCH_PAGED.json"), "w"
    ) as f:
        json.dump(result, f, indent=1)
    identical = (
        result["tokens_identical_engine"]
        and result["serving"]["tokens_identical_serving"]
    )
    return {
        "metric": "paged_vs_dense_decode",
        "value": result["paged"]["tok_s_chip"],
        "unit": (
            f"tok/s/chip paged ({model}, batch={batch}, ring={max_seq}, "
            f"bs={bsz}; dense={result['dense']['tok_s_chip']}, "
            f"rows {rows_peak_d}->{rows_peak_p} at equal KV budget, "
            f"identical_tokens={identical})"
        ),
        "vs_baseline": round(
            result["paged"]["tok_s_chip"]
            / max(result["dense"]["tok_s_chip"], 1e-9), 3
        ),
    }


def main():
    # Default sweep: the 1b2 series (bf16 — comparable across rounds —
    # and int8 KV: half the cache bytes, scales folded into the attention
    # contractions), then the HEADLINE LAST: Llama-2-7B dims, the
    # BASELINE.md north-star scale. BENCH_MODEL (optionally with
    # BENCH_KV_DTYPE) restricts to that single line; BENCH_KV_DTYPE alone
    # restricts to a single 1b2 line in that dtype.
    import sys

    if "paged" in sys.argv[1:] or os.environ.get("BENCH_PAGED"):
        print(
            json.dumps(run_paged_ab(MODEL or "1b2")), flush=True
        )
        return
    if MODEL:
        runs = [(MODEL, KV_DTYPE)]
    elif KV_DTYPE:
        runs = [("1b2", KV_DTYPE)]
    else:
        runs = [("1b2", None), ("1b2", "int8"), ("7b", None)]
    for model, kv in runs:
        result = run_model(model, kv)
        print(json.dumps(result), flush=True)
        # Free this model's params/executables before the next config —
        # 7B params alone are 13.5 GB of the 16 GB chip.
        jax.clear_caches()
        import gc

        gc.collect()


if __name__ == "__main__":
    main()
