"""Benchmark: decode throughput (tokens/sec/chip) on the flagship model.

Run on real TPU hardware by the driver. Prints ONE JSON line:
``{"metric": ..., "value": N, "unit": ..., "vs_baseline": N}``.

The reference publishes no numbers (BASELINE.md), so ``vs_baseline`` is
reported against the **HBM-bandwidth roofline** for batched decode on this
chip: a decode step must stream all parameter bytes plus the live KV-cache
bytes from HBM, so

    roofline_tokens_per_sec = batch * BW / (param_bytes + batch * kv_bytes)

``vs_baseline`` = measured / roofline — i.e. the fraction of the chip's
theoretical decode ceiling this framework reaches (1.0 is perfect).

Methodology: steady-state decode cost is the **marginal** time per fused
decode step, measured by the slope method — run the fused scan at two step
counts and take (t(N2) - t(N1)) / (N2 - N1). This cancels constant per-call
overhead (on the axon bench host the tunnel adds ~90 ms of dispatch + fetch
latency per call, which is host-link artifact, not framework cost) and
matches what a long-running serving process sustains. Prefill latency is
its own number (TTFT, reported in ``unit``), not smeared into decode
throughput. As an independent cross-check on the roofline accounting, the
achieved HBM rate implied by the measured step time over the bytes the step
must stream (params + full KV buffer) is also reported in ``unit``.

Model: Llama-architecture ~1.2B by default (fits one v5e with generous
cache room; the headline series tracked across rounds), random-init bf16,
batch 16, 128-token prefill, fused decode. ``BENCH_MODEL=7b`` switches to
Llama-2-7B dims — the BASELINE.md north-star scale — which reaches a
*higher* roofline fraction (params dominate the denominator): 0.851 at
batch 4, 203 tok/s/chip, TTFT 129 ms (measured r3).
"""

from __future__ import annotations

import json
import os
import time

import jax
import jax.numpy as jnp
import numpy as np

# Batch 16 is the headline point (vs_baseline peaks there: params dominate
# the roofline denominator). Batch 32 still holds TTFT under the BASELINE.md
# 200 ms target with higher absolute throughput (5785 tok/s/chip, ttft
# 163 ms measured r3) — BENCH_BATCH=32 reproduces it. BENCH_KV_DTYPE=int8
# halves cache memory (2x rows/context) at a dequant-overhead cost.
BATCH = int(os.environ.get("BENCH_BATCH", 16))
PROMPT = int(os.environ.get("BENCH_PROMPT", 128))
DECODE = int(os.environ.get("BENCH_DECODE", 128))
HBM_GBPS = float(os.environ.get("BENCH_HBM_GBPS", 819.0))  # v5e
KV_DTYPE = os.environ.get("BENCH_KV_DTYPE") or None  # "int8" halves KV bytes


MODEL = os.environ.get("BENCH_MODEL", "1b2")  # "1b2" | "7b"

_MODEL_DIMS = {
    # ~1.2B: the headline config — fits one v5e with generous cache room.
    "1b2": dict(hidden_size=2048, n_layers=20, n_heads=16,
                intermediate_size=5504),
    # Llama-2-7B dims (BASELINE.md north-star scale): 13.5 GB bf16 params
    # on a 16 GB v5e — single-chip analogue of the TP=8 config (run with
    # BENCH_BATCH=4; larger batches don't fit beside the params).
    "7b": dict(hidden_size=4096, n_layers=32, n_heads=32,
               intermediate_size=11008),
}


def flagship_cfg():
    from llmss_tpu.models.common import DecoderConfig

    dims = _MODEL_DIMS[MODEL]
    return DecoderConfig(
        model_type="llama",
        vocab_size=32000,
        n_kv_heads=dims["n_heads"],
        head_dim=128,
        max_position_embeddings=4096,
        activation="silu",
        norm="rmsnorm",
        norm_eps=1e-5,
        mlp="swiglu",
        positions="rotary",
        rope_style="half",
        rotary_dim=128,
        attn_bias=False,
        mlp_bias=False,
        tie_word_embeddings=False,
        dtype="bfloat16",
        **dims,
    )


N_SLOPE = (64, 320)  # fused-scan step counts for the slope method


def roofline_tokens_per_sec(
    cfg, param_bytes: float, batch: int, max_seq: int,
    hbm_gbps: float = HBM_GBPS,
) -> float:
    """HBM-bandwidth decode ceiling: params + avg-half-full bf16 KV per
    step. The single definition of ``vs_baseline`` shared by bench.py and
    bench_serve.py so the two lines stay directly comparable."""
    kv_bytes_per_token = (
        2 * cfg.n_layers * cfg.n_kv_heads * cfg.head_dim * 2 * max_seq / 2
    )  # avg half-full cache, k+v, bf16
    return batch * hbm_gbps * 1e9 / (
        param_bytes + batch * kv_bytes_per_token
    )


def slope_time(prepare, n_slope=N_SLOPE, reps: int = 3) -> tuple[float, float]:
    """Marginal ms per decode step + constant ms, via the slope method.

    ``prepare(n)`` must return a zero-arg callable that runs one fused
    n-step scan **to completion** — force it with a host fetch of a scalar
    reduction; ``block_until_ready`` can return at dispatch time over the
    axon tunnel. The single methodology shared by bench.py and
    tools/profile_decode.py.
    """
    times = {}
    for n in n_slope:
        run = prepare(n)
        run()  # compile + warm
        best = float("inf")
        for _i in range(reps):
            t0 = time.perf_counter()
            run()
            best = min(best, time.perf_counter() - t0)
        times[n] = best
    n1, n2 = n_slope
    slope_ms = (times[n2] - times[n1]) / (n2 - n1) * 1e3
    const_ms = times[n1] * 1e3 - slope_ms * n1
    return slope_ms, const_ms


def _decode_slope_ms(engine, ids, lens, sa, eos) -> float:
    def prepare(n):
        cache = engine.new_cache(BATCH)
        tok, _, cache = engine._prefill(
            engine.params, jnp.asarray(ids), cache, jnp.asarray(lens), sa,
        )
        cur = jnp.asarray(lens)
        done = jnp.zeros(BATCH, bool)
        state = {"cache": cache}

        def run():
            out = engine._decode_many(
                engine.params, tok, state["cache"], cur, sa, done, eos,
                n_steps=n,
            )
            toks, state["cache"] = out[0], out[1]
            _ = float(jnp.sum(toks))  # forced completion

        return run

    return slope_time(prepare)[0]


def main():
    from llmss_tpu.engine import DecodeEngine, GenerationParams
    from llmss_tpu.models.decoder import init_params
    from llmss_tpu.parallel import MeshPlan, make_mesh

    n_dev = len(jax.devices())
    mesh = make_mesh(MeshPlan(tp=n_dev))
    cfg = flagship_cfg()
    params = init_params(cfg, mesh, jax.random.key(0))
    n_params = sum(
        np.prod(x.shape) for x in jax.tree.leaves(params)
    )
    param_bytes = float(n_params) * 2  # bf16

    max_seq = PROMPT + DECODE
    engine = DecodeEngine(
        cfg, params, mesh, max_seq_len=max_seq, kv_dtype=KV_DTYPE,
    )
    gen = GenerationParams(max_new_tokens=DECODE, is_greedy=True)

    rng = np.random.default_rng(0)
    prompts = [
        rng.integers(0, cfg.vocab_size, PROMPT).tolist() for _ in range(BATCH)
    ]
    ids, lens = engine._pad_prompts(prompts)
    sa = engine._sample_args(gen, BATCH)
    eos = jnp.int32(-1)

    # Warmup: compile prefill once.
    cache = engine.new_cache(BATCH)
    tok, _, cache = engine._prefill(
        engine.params, jnp.asarray(ids), cache, jnp.asarray(lens), sa,
    )
    _ = np.asarray(tok)
    del cache

    # TTFT: prefill + first sampled token on host, compiled path.
    ttft_ms = float("inf")
    for _i in range(3):
        cache = engine.new_cache(BATCH)
        t0 = time.perf_counter()
        tok, _, cache = engine._prefill(
            engine.params, jnp.asarray(ids), cache, jnp.asarray(lens), sa,
        )
        _ = np.asarray(tok)  # the token must actually reach the host
        ttft_ms = min(ttft_ms, (time.perf_counter() - t0) * 1e3)
        del cache

    # Decode throughput: marginal fused-step cost, steady state.
    step_ms = _decode_slope_ms(engine, ids, lens, sa, eos)
    tok_per_sec_per_chip = BATCH / (step_ms * 1e-3) / n_dev

    roofline = roofline_tokens_per_sec(cfg, param_bytes, BATCH, max_seq)
    # Independent cross-check: the step must stream at least params + the
    # full KV buffer (einsums read all T slots of the ring buffer); the
    # achieved HBM rate over those bytes bounds the accounting from below.
    kv_buffer_bytes = 2 * cfg.n_layers * BATCH * max_seq * (
        cfg.n_kv_heads * cfg.head_dim * 2
    )
    achieved_gbps = (param_bytes + kv_buffer_bytes) / (step_ms * 1e-3) / 1e9
    result = {
        "metric": "decode_tokens_per_sec_per_chip",
        "value": round(tok_per_sec_per_chip, 1),
        "unit": (
            f"tok/s/chip ({MODEL} bf16, batch={BATCH}, "
            + (f"kv={KV_DTYPE}, " if KV_DTYPE else "")
            + f"ttft_ms={ttft_ms:.0f}, "
            f"step_ms={step_ms:.2f}, achieved_hbm_gbps={achieved_gbps:.0f})"
        ),
        "vs_baseline": round(tok_per_sec_per_chip / roofline, 3),
    }
    print(json.dumps(result))


if __name__ == "__main__":
    main()
