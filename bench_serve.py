"""Serving-path benchmark: ContinuousWorker under a swept Poisson load.

BASELINE.md configs #4/#5 analogue at single-chip scale — the serving stack
(broker → continuous batcher → engine) measured under load, not just the
bare engine loop that ``bench.py`` times. The bench SWEEPS the offered
Poisson rate over one warmed worker and reports two operating points:

- **capacity**: sustained tok/s/chip at the first saturated rate (where
  the worker stops keeping up with the offered load — the knee); this is
  the headline ``value`` and is NOT load-limited;
- **ttft_sla**: the highest swept rate whose ttft_p50 stays under the
  BASELINE.md 200 ms target, with its rate/TTFT/throughput.

Prints ONE JSON line; the full sweep table goes to ``SERVE_BENCH.json``.
``vs_baseline`` uses the same HBM-roofline definition as ``bench.py`` at
the worker's row count, so the two lines are directly comparable: the gap
between them is the price of serving (scheduling, admission prefills,
token delivery) on top of raw decode.

Load model: Poisson arrivals (seeded) of 128-token random prompts, 128
greedy new tokens each, ``SERVE_SECONDS`` per swept rate. Env overrides:
``SERVE_RATES`` (comma list, req/s), ``SERVE_ROWS``, ``SERVE_CHUNK``,
``SERVE_SECONDS``.
"""

from __future__ import annotations

import json
import os
import threading
import time
import uuid

import sys

import jax
import numpy as np

from bench import DECODE, PROMPT, flagship_cfg, roofline_tokens_per_sec

# tools/ is not a package; the breakdown helper lives next to the other
# profiling receipts in tools/profile_decode.py.
sys.path.insert(
    0, os.path.join(os.path.dirname(os.path.abspath(__file__)), "tools")
)
from profile_decode import host_overhead_breakdown  # noqa: E402

MODEL = os.environ.get("SERVE_MODEL", "1b2")
RATES = [
    float(r) for r in os.environ.get(
        "SERVE_RATES", "28,36,44,52,60"
    ).split(",")
]
SECONDS = float(os.environ.get("SERVE_SECONDS", 20.0))
ROWS = int(os.environ.get("SERVE_ROWS", 64))
CHUNK = int(os.environ.get("SERVE_CHUNK", 16))
CHUNK_LOW = int(os.environ.get("SERVE_CHUNK_LOW", 8))
GROUP = int(os.environ.get("SERVE_GROUP", 4))
SLA_MS = float(os.environ.get("SERVE_SLA_MS", 200.0))


def run_window(worker, broker, make_req, rate: float, seconds: float,
               n_dev: int) -> dict:
    """One measurement window at a fixed Poisson rate on the (already
    warm) worker. Returns the operating-point stats."""
    from llmss_tpu.utils.metrics import EngineMetrics

    engine = worker.engine
    engine.metrics = EngineMetrics()
    lat: dict[str, float] = {}
    lat_lock = threading.Lock()
    submitted: list[str] = []
    stop_client = threading.Event()

    def waiter(req_id: str, t_submit: float):
        resp = broker.wait_response(req_id, timeout=seconds * 3 + 120)
        if resp is not None and resp.error is None:
            with lat_lock:
                lat[req_id] = time.time() - t_submit

    def client():
        arr_rng = np.random.default_rng(int(rate * 1000) % 2**31)
        t_end = time.time() + seconds
        while time.time() < t_end and not stop_client.is_set():
            time.sleep(arr_rng.exponential(1.0 / rate))
            req = make_req()
            t0 = time.time()
            broker.push_request(req)
            submitted.append(req.id)
            threading.Thread(
                target=waiter, args=(req.id, t0), daemon=True
            ).start()

    ct = threading.Thread(target=client, daemon=True)
    t_start = time.time()
    ct.start()
    while ct.is_alive() or not worker.batcher.idle:
        worker.run_once()
        if time.time() - t_start > seconds * 3 + 180:
            stop_client.set()
            break
    t_wall = time.time() - t_start

    m = engine.metrics.to_dict()
    lat_sorted = sorted(lat.values())

    def pct(q):
        return (
            round(lat_sorted[min(int(q / 100 * len(lat_sorted)),
                                 len(lat_sorted) - 1)], 2)
            if lat_sorted else None
        )

    toks = m["tokens_generated"]
    offered_tps = rate * DECODE
    serve_tps = toks / t_wall / n_dev
    ttft_p50 = m["ttft"]["p50_ms"] or 0.0
    # Saturated = the worker did not keep up with the offered token rate
    # (drained slower than offered) or queueing blew the latency up.
    saturated = bool(
        serve_tps * n_dev < 0.9 * offered_tps or ttft_p50 > 1500.0
    )
    return {
        "rate_req_s": rate,
        "tok_s_chip": round(serve_tps, 1),
        "offered_tok_s": round(offered_tps, 1),
        "served": len(lat),
        "submitted": len(submitted),
        "ttft_p50_ms": ttft_p50,
        "ttft_p95_ms": m["ttft"]["p95_ms"],
        "e2e_p50_s": pct(50),
        "e2e_p95_s": pct(95),
        "decode_step_p50_ms": m["decode_step"]["p50_ms"],
        "saturated": saturated,
        "wall_s": round(t_wall, 1),
        # Per-group host-overhead receipts: with grouped dispatch the
        # host pays dispatch+fetch+callback once per GROUP, not per
        # chunk — host_syncs/groups_dispatched here is exactly 1.0.
        "host_overhead": host_overhead_breakdown(engine.metrics),
        # Mixed-batch composition (all zeros unless the worker ran with
        # chunked prefill): decode vs prompt row-steps per ragged group
        # and how full the chunk budget ran.
        "mixed_batch": m["mixed_batch"],
    }


def main():
    from llmss_tpu.engine import DecodeEngine
    from llmss_tpu.models.decoder import init_params
    from llmss_tpu.parallel import MeshPlan, make_mesh
    from llmss_tpu.serve.broker import InProcBroker
    from llmss_tpu.serve.consumer import ContinuousWorker
    from llmss_tpu.serve.protocol import GenerateRequest

    n_dev = len(jax.devices())
    mesh = make_mesh(MeshPlan(tp=n_dev))
    cfg = flagship_cfg(MODEL)
    params = init_params(cfg, mesh, jax.random.key(0))
    n_params = sum(np.prod(x.shape) for x in jax.tree.leaves(params))
    param_bytes = float(n_params) * 2
    max_seq = PROMPT + DECODE
    engine = DecodeEngine(cfg, params, mesh, max_seq_len=max_seq)
    broker = InProcBroker()
    worker = ContinuousWorker(
        engine, broker, tokenizer=None, rows=ROWS, chunk_steps=CHUNK,
        chunk_steps_low=CHUNK_LOW, group_chunks=GROUP,
    )

    rng = np.random.default_rng(0)

    def make_req():
        return GenerateRequest(
            id=uuid.uuid4().hex,
            token_ids=rng.integers(0, cfg.vocab_size, PROMPT).tolist(),
            max_new_tokens=DECODE,
            is_greedy=True,
        )

    # Host<->device round-trip latency: every scheduler iteration pays
    # one token fetch, so ~2x this RTT (+ prefill) is the hard TTFT floor
    # of the pipelined loop on THIS host. On the axon bench host the
    # tunnel adds ~90 ms; a co-located TPU VM host is <1 ms.
    import jax.numpy as jnp
    x = jnp.zeros((), jnp.int32) + 1
    _ = int(x)
    rtts = []
    for _i in range(5):
        t0 = time.time()
        _ = int(jnp.zeros((), jnp.int32) + 1)
        rtts.append(time.time() - t0)
    host_rtt_ms = round(min(rtts) * 1e3, 1)
    print(f"# host_rtt_ms={host_rtt_ms}", flush=True)

    # -- warmup: compile the full serving envelope for this load shape ----
    t0 = time.time()
    n_exec = worker.prewarm(seq_buckets=[PROMPT])
    print(f"# prewarmed {n_exec} executables in {time.time() - t0:.0f}s",
          flush=True)
    warm_ids = []
    for _ in range(ROWS):
        r = make_req()
        warm_ids.append(r.id)
        broker.push_request(r)
    deadline = time.time() + 300
    while warm_ids and time.time() < deadline:
        worker.run_once()
        warm_ids = [
            i for i in warm_ids
            if broker.wait_response(i, timeout=0.001) is None
        ]
    assert not warm_ids, "warmup did not complete"

    # -- sweep -------------------------------------------------------------
    sweep = []
    for rate in RATES:
        w = run_window(worker, broker, make_req, rate, SECONDS, n_dev)
        sweep.append(w)
        print(f"# rate={rate} -> {json.dumps(w)}", flush=True)
        if w["saturated"]:
            break

    sat = next((w for w in sweep if w["saturated"]), None)
    capacity = sat or sweep[-1]
    sla = [w for w in sweep if (w["ttft_p50_ms"] or 1e9) < SLA_MS]
    best_sla = max(sla, key=lambda w: w["rate_req_s"]) if sla else None

    roofline = roofline_tokens_per_sec(cfg, param_bytes, ROWS, max_seq)
    backend = jax.default_backend()
    result = {
        "metric": "serve_tokens_per_sec_per_chip",
        "value": capacity["tok_s_chip"],
        "load_limited": not capacity["saturated"],
        "unit": (
            f"tok/s/chip ({MODEL} bf16 on {backend}, continuous batching "
            f"rows={ROWS} "
            f"chunk={CHUNK}/{CHUNK_LOW} group={GROUP}, capacity at poisson "
            f"{capacity['rate_req_s']} req/s x {SECONDS:.0f}s: "
            f"{capacity['served']}/{capacity['submitted']} served, "
            f"ttft_p50={capacity['ttft_p50_ms']}ms "
            f"p95={capacity['ttft_p95_ms']}ms, "
            f"e2e_p50={capacity['e2e_p50_s']}s; "
            + (
                f"sla<{SLA_MS:.0f}ms holds to "
                f"{best_sla['rate_req_s']} req/s "
                f"(ttft_p50={best_sla['ttft_p50_ms']}ms, "
                f"{best_sla['tok_s_chip']} tok/s/chip)"
                if best_sla else
                f"no swept rate met ttft_p50<{SLA_MS:.0f}ms: host rtt "
                f"{host_rtt_ms}ms puts the pipelined-loop TTFT floor at "
                f"~{round(2 * host_rtt_ms + 50)}ms on this host"
            )
            + ")"
        ),
        "host_rtt_ms": host_rtt_ms,
        "host_overhead": capacity["host_overhead"],
        "vs_baseline": round(capacity["tok_s_chip"] / roofline, 3),
    }
    print(json.dumps(result))
    from bench import bench_provenance

    with open("SERVE_BENCH.json", "w") as f:
        json.dump(
            {**result, "sla_ms": SLA_MS, "best_sla": best_sla,
             "sweep": sweep, "provenance": bench_provenance()},
            f, indent=1,
        )


if __name__ == "__main__":
    main()
