"""Serving-path benchmark: ContinuousWorker under Poisson arrivals.

BASELINE.md configs #4/#5 analogue at single-chip scale — the serving stack
(broker → continuous batcher → engine) measured under load, not just the
bare engine loop that ``bench.py`` times. Prints ONE JSON line:

    {"metric": "serve_tokens_per_sec_per_chip", "value": N,
     "unit": "... p50/p95 TTFT + e2e latency ...", "vs_baseline": N}

``vs_baseline`` uses the same HBM-roofline definition as ``bench.py`` at
the worker's row count, so the two lines are directly comparable: the gap
between them is the price of serving (scheduling, admission prefills,
token delivery) on top of raw decode. NOTE the reading depends on load:
below saturation the worker serves every request, so the metric equals the
*offered* rate (RATE × DECODE tokens/s), not capacity — ``load_limited``
in the JSON flags this. Measure capacity with a saturating rate
(``SERVE_RATE=40`` measured 0.448 on v5e at rows=32, r4; the scheduler
pipelines decode chunks against the host fetch, so the per-chunk
device→host round-trip is off the critical path — engine/scheduler.py).

Load model: Poisson arrivals (seeded) of 128-token random prompts, 128
greedy new tokens each, at ``SERVE_RATE`` req/s for ``SERVE_SECONDS``;
TTFT comes from the engine's prefill stats, end-to-end latency from the
client side. Writes the full result to ``SERVE_BENCH.json``.
"""

from __future__ import annotations

import json
import os
import threading
import time
import uuid

import jax
import numpy as np

from bench import DECODE, PROMPT, flagship_cfg, roofline_tokens_per_sec

RATE = float(os.environ.get("SERVE_RATE", 24.0))  # requests/sec
SECONDS = float(os.environ.get("SERVE_SECONDS", 30.0))
ROWS = int(os.environ.get("SERVE_ROWS", 32))


def main():
    from llmss_tpu.engine import DecodeEngine
    from llmss_tpu.models.decoder import init_params
    from llmss_tpu.parallel import MeshPlan, make_mesh
    from llmss_tpu.serve.broker import InProcBroker
    from llmss_tpu.serve.consumer import ContinuousWorker
    from llmss_tpu.serve.protocol import GenerateRequest

    n_dev = len(jax.devices())
    mesh = make_mesh(MeshPlan(tp=n_dev))
    cfg = flagship_cfg()
    params = init_params(cfg, mesh, jax.random.key(0))
    n_params = sum(np.prod(x.shape) for x in jax.tree.leaves(params))
    param_bytes = float(n_params) * 2
    max_seq = PROMPT + DECODE
    engine = DecodeEngine(cfg, params, mesh, max_seq_len=max_seq)
    broker = InProcBroker()
    worker = ContinuousWorker(
        engine, broker, tokenizer=None, rows=ROWS,
        chunk_steps=int(os.environ.get("SERVE_CHUNK", 32)),
    )

    rng = np.random.default_rng(0)

    def make_req():
        return GenerateRequest(
            id=uuid.uuid4().hex,
            token_ids=rng.integers(0, cfg.vocab_size, PROMPT).tolist(),
            max_new_tokens=DECODE,
            is_greedy=True,
        )

    # -- warmup: compile the full serving envelope for this load shape ----
    t0 = time.time()
    n_exec = worker.prewarm(seq_buckets=[PROMPT])
    print(f"# prewarmed {n_exec} executables in {time.time() - t0:.0f}s")
    warm_ids = []
    for _ in range(ROWS):
        r = make_req()
        warm_ids.append(r.id)
        broker.push_request(r)
    deadline = time.time() + 300
    while warm_ids and time.time() < deadline:
        worker.run_once()
        warm_ids = [
            i for i in warm_ids
            if broker.wait_response(i, timeout=0.001) is None
        ]
    assert not warm_ids, "warmup did not complete"

    # -- load phase --------------------------------------------------------
    lat: dict[str, float] = {}
    lat_lock = threading.Lock()
    submitted = []
    stop_client = threading.Event()

    def waiter(req_id: str, t_submit: float):
        resp = broker.wait_response(req_id, timeout=SECONDS * 3 + 120)
        if resp is not None and resp.error is None:
            with lat_lock:
                lat[req_id] = time.time() - t_submit

    def client():
        arr_rng = np.random.default_rng(7)
        t_end = time.time() + SECONDS
        while time.time() < t_end and not stop_client.is_set():
            time.sleep(arr_rng.exponential(1.0 / RATE))
            req = make_req()
            t0 = time.time()
            broker.push_request(req)
            submitted.append(req.id)
            threading.Thread(
                target=waiter, args=(req.id, t0), daemon=True
            ).start()

    # Reset metrics so the report covers only the measured window.
    from llmss_tpu.utils.metrics import EngineMetrics

    engine.metrics = EngineMetrics()

    ct = threading.Thread(target=client, daemon=True)
    t_start = time.time()
    ct.start()
    # Worker loop on the main thread until the client stops and the batch
    # drains.
    while ct.is_alive() or not worker.batcher.idle:
        worker.run_once()
        if time.time() - t_start > SECONDS * 3 + 240:
            stop_client.set()
            break
    t_wall = time.time() - t_start

    m = engine.metrics.to_dict()
    done = len(lat)
    lat_sorted = sorted(lat.values())

    def pct(q):
        return (
            round(lat_sorted[min(int(q / 100 * len(lat_sorted)),
                                 len(lat_sorted) - 1)], 2)
            if lat_sorted else None
        )

    toks = m["tokens_generated"]
    serve_tps = toks / t_wall / n_dev

    roofline = roofline_tokens_per_sec(cfg, param_bytes, ROWS, max_seq)

    # Below saturation the worker keeps up (no queue buildup — small
    # TTFT) and the metric equals offered load, not capacity.
    result = {
        "metric": "serve_tokens_per_sec_per_chip",
        "value": round(serve_tps, 1),
        "load_limited": bool(
            done == len(submitted)
            and (m["ttft"]["p50_ms"] or 0) < 1000.0
        ),
        "unit": (
            f"tok/s/chip (1.2B-class bf16, continuous batching rows={ROWS}, "
            f"poisson {RATE} req/s x {SECONDS:.0f}s, {done}/"
            f"{len(submitted)} served, ttft_p50={m['ttft']['p50_ms']}ms "
            f"p95={m['ttft']['p95_ms']}ms, e2e_p50={pct(50)}s "
            f"p95={pct(95)}s, decode_step_p50="
            f"{m['decode_step']['p50_ms']}ms)"
        ),
        "vs_baseline": round(serve_tps / roofline, 3),
    }
    print(json.dumps(result))
    with open("SERVE_BENCH.json", "w") as f:
        json.dump({**result, "raw_metrics": m, "wall_s": round(t_wall, 1)},
                  f, indent=1)


if __name__ == "__main__":
    main()
